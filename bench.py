"""Benchmark driver — prints ONE JSON line with the headline metric.

Measures sustained Llama training throughput (tokens/sec/chip) under the engine's
fused train step on real TPU hardware, and derives MFU against the chip's peak
bf16 TFLOPS. ``vs_baseline`` compares our MFU to the reference's headline Ulysses
efficiency (>54% of peak on A100, BASELINE.md row 1) — ratio > 1.0 beats it.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REFERENCE_MFU = 0.54  # BASELINE.md: Ulysses sustained >54% of peak


def main():
    from bench_util import guard_device_discovery
    # per-preset metric names: a wedged 8b run must NOT replay the banked
    # 697m headline as its own (cross-measurement substitution)
    _preset = os.environ.get("DSTPU_BENCH_MODEL", "697m")
    metric_name = "llama_train_tokens_per_sec_per_chip" if _preset == "697m" \
        else f"llama_{_preset}_train_tokens_per_sec_per_chip"
    disarm = guard_device_discovery("bench", stale_metric=metric_name)
    import jax
    import jax.numpy as jnp
    import numpy as np
    jax.devices()
    disarm()

    import deepspeed_tpu
    from deepspeed_tpu.accelerator import get_accelerator
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM, random_tokens

    n_devices = len(jax.devices())
    seq_len = 2048

    # --- model-size ladder (BASELINE north star is 8B; VERDICT r4 task 2) ----
    # Each preset picks the memory tier a v5e chip (16GB HBM) needs at that
    # size: 697m fits whole; 1b/3b keep fp32 masters+moments on host
    # (ZeRO-Offload, host fused Adam); 8b streams the WEIGHTS themselves
    # (ZeRO-Infinity param offload) since 16.1GB bf16 alone exceeds HBM.
    #          hidden inter  layers heads kv  mb gas  offload
    presets = {
        "697m": (2048,  5632, 12,   16,   8,  2,  4,  "none"),
        "1b":   (2048,  5632, 24,   16,   8,  1,  4,  "optimizer"),
        "3b":   (3072,  8192, 28,   24,   8,  1,  4,  "optimizer"),
        "8b":   (4096, 14336, 32,   32,   8,  1,  2,  "param"),
    }
    preset = os.environ.get("DSTPU_BENCH_MODEL", "697m")
    if preset not in presets:
        raise SystemExit(f"DSTPU_BENCH_MODEL must be one of {sorted(presets)}")
    hidden, inter, layers, heads, kv, mb_default, gas_default, tier = presets[preset]
    # micro_batch=4/gas=2 reaches ~0.68 MFU on 697m but sits within ~260MB of
    # the HBM ceiling (flaky OOM depending on allocator state); the preset
    # defaults are the safe configs
    micro_batch = int(os.environ.get("DSTPU_BENCH_MICRO_BATCH", mb_default))
    gas = int(os.environ.get("DSTPU_BENCH_GAS", gas_default))
    batch = micro_batch * gas * n_devices

    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=hidden, intermediate_size=inter,
        num_layers=layers, num_heads=heads, num_kv_heads=kv,
        max_seq_len=seq_len,
        dtype=jnp.bfloat16,
        attention_backend=os.environ.get("DSTPU_BENCH_ATTN", "flash"),
        # chunked head+CE fusion: the fp32 [B*S,V] logits (1GB at mb=4) never
        # materialize, freeing ~3GB of HLO temps (enables micro_batch 4).
        # OFF by default: its TPU compile was in flight when the axon tunnel
        # wedged (2026-07-30) and is unproven on hardware — flip the default
        # only after DSTPU_BENCH_LOSS_CHUNK=2048 measures clean on a chip
        # DSTPU_BENCH_LOSS_UNROLL=1 replaces the scan(checkpoint) chunk loop
        # with an unrolled one (compile-time mitigation to try FIRST on
        # chip); it implies a 2048 chunk size when LOSS_CHUNK is unset so the
        # knob can't silently measure the dense path
        loss_chunk_size=int(os.environ.get("DSTPU_BENCH_LOSS_CHUNK", 0)) or (
            2048 if os.environ.get("DSTPU_BENCH_LOSS_UNROLL") == "1" else None),
        loss_chunk_unroll=os.environ.get("DSTPU_BENCH_LOSS_UNROLL", "0") == "1",
        remat=os.environ.get("DSTPU_BENCH_REMAT", "1") == "1",
        remat_policy=os.environ.get("DSTPU_BENCH_REMAT_POLICY",
                                    "dots_with_no_batch_dims_saveable"))
    zero = {"stage": 0 if n_devices == 1 else 3}
    if tier == "optimizer":
        zero["offload_optimizer"] = {"device": "cpu", "ratio": 0.0}
    elif tier == "param":
        zero["offload_optimizer"] = {"device": "cpu", "ratio": 0.0}
        zero["offload_param"] = {
            "device": "cpu",
            "layers_per_group": int(os.environ.get("DSTPU_BENCH_LPG", 4))}
    config = {
        "train_batch_size": batch,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 3e-4}},
        "bf16": {"enabled": True},
        "data_types": {"grad_accum_dtype": "bf16"},
        "zero_optimization": zero,
        "steps_per_print": 1000000,
    }
    model = LlamaForCausalLM(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=config,
        example_batch=random_tokens(2, seq_len, vocab_size=cfg.vocab_size))

    def make_batch(i):
        return random_tokens(micro_batch * n_devices, seq_len,
                             vocab_size=cfg.vocab_size, seed=i, gas=gas)

    # Sync barrier: fetch a device scalar to host. (On tunneled platforms
    # block_until_ready can return before execution finishes; a D2H transfer
    # cannot.)
    loss = engine.train_batch(batch=make_batch(0), stacked=True)  # compile
    float(jax.device_get(loss))

    steps = 10
    t0 = time.time()
    for i in range(1, steps + 1):
        loss = engine.train_batch(batch=make_batch(i), stacked=True)
    float(jax.device_get(loss))
    dt = time.time() - t0

    tokens_per_sec = steps * batch * seq_len / dt
    tokens_per_sec_chip = tokens_per_sec / n_devices
    n_params = sum(int(np.prod(np.shape(p)))
                   for p in jax.tree.leaves(engine.get_params()))
    flops_per_token = 6 * n_params  # fwd+bwd dense FLOPs (attention excluded → lower bound)
    achieved_tflops = tokens_per_sec_chip * flops_per_token / 1e12
    peak = get_accelerator().peak_tflops("bf16") or 197.0
    mfu = achieved_tflops / peak

    record = {
        "metric": metric_name,
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(mfu / REFERENCE_MFU, 3),
        "extra": {
            "model": preset,
            "memory_tier": tier,
            "n_devices": n_devices,
            "params_millions": round(n_params / 1e6, 1),
            "seq_len": seq_len,
            "model_tflops_per_chip": round(achieved_tflops, 1),
            "mfu": round(mfu, 3),
            "peak_tflops": peak,
        },
    }
    print(json.dumps(record))
    if not any(k.startswith("DSTPU_BENCH_") for k in os.environ):
        # only the all-defaults config banks the canonical stale-fallback
        # headline — an A/B knob run must never become the replayed record
        from bench_util import bank_headline
        bank_headline(record)


if __name__ == "__main__":
    main()
