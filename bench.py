"""Benchmark driver — prints ONE JSON line with the headline metric.

Measures sustained Llama training throughput (tokens/sec/chip) under the engine's
fused train step on real TPU hardware, and derives MFU against the chip's peak
bf16 TFLOPS. ``vs_baseline`` compares our MFU to the reference's headline Ulysses
efficiency (>54% of peak on A100, BASELINE.md row 1) — ratio > 1.0 beats it.

Alongside tokens/sec the record now carries ``steps_per_sec`` and the host
``dispatch_gap_ms`` (mean host time per step spent *launching* work — the
number the async step pipeline drives toward zero). ``--sync-every 1,8``
[+ ``--prefetch``] additionally sweeps the async pipeline's drain cadence and
reports per-arm steps/sec + dispatch gap under ``extra.async_sweep`` — run with
``DSTPU_BENCH_MODEL=micro`` for the seed-pinned CPU micro-bench. Any sweep flag
disables headline banking (A/B runs must never become the replayed record).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REFERENCE_MFU = 0.54  # BASELINE.md: Ulysses sustained >54% of peak


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="deepspeed_tpu training bench")
    p.add_argument("--sync-every", default="1",
                   help="comma-separated async-pipeline drain cadences to "
                        "sweep (1 = per-step readback; e.g. '1,8')")
    p.add_argument("--prefetch", action="store_true",
                   help="enable double-buffered batch prefetch in the sweep")
    p.add_argument("--sweep-steps", type=int, default=20,
                   help="timed steps per sweep arm")
    return p.parse_args(argv)


def main():
    args = parse_args()
    sweep_values = [int(x) for x in str(args.sync_every).split(",")
                    if x.strip()]
    if args.prefetch and not any(se > 1 for se in sweep_values):
        print("# --prefetch has no effect without a pipelined arm: prefetch "
              "engages only on --sync-every values > 1 (sync_every=1 is the "
              "synchronous baseline) — add e.g. --sync-every 1,8",
              file=sys.stderr)
    sweep_requested = sweep_values != [1] or args.prefetch
    from bench_util import bounded_device_discovery
    # per-preset metric names: a wedged 8b run must NOT replay the banked
    # 697m headline as its own (cross-measurement substitution)
    _preset = os.environ.get("DSTPU_BENCH_MODEL", "697m")
    metric_name = "llama_train_tokens_per_sec_per_chip" if _preset == "697m" \
        else f"llama_{_preset}_train_tokens_per_sec_per_chip"
    # bounded-init path: deadline + backoff retries + classified rc/diagnosis
    # (tunnel wedge vs no devices vs auth) — BENCH runs never hang silently
    bounded_device_discovery("bench", stale_metric=metric_name)
    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.accelerator import get_accelerator
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM, random_tokens

    n_devices = len(jax.devices())
    seq_len = 2048

    # --- model-size ladder (BASELINE north star is 8B; VERDICT r4 task 2) ----
    # Each preset picks the memory tier a v5e chip (16GB HBM) needs at that
    # size: 697m fits whole; 1b/3b keep fp32 masters+moments on host
    # (ZeRO-Offload, host fused Adam); 8b streams the WEIGHTS themselves
    # (ZeRO-Infinity param offload) since 16.1GB bf16 alone exceeds HBM.
    #          hidden inter  layers heads kv  mb gas  offload
    presets = {
        "697m": (2048,  5632, 12,   16,   8,  2,  4,  "none"),
        "1b":   (2048,  5632, 24,   16,   8,  1,  4,  "optimizer"),
        "3b":   (3072,  8192, 28,   24,   8,  1,  4,  "optimizer"),
        "8b":   (4096, 14336, 32,   32,   8,  1,  2,  "param"),
        # CPU-runnable micro model for async-pipeline A/B sweeps (the
        # seed-pinned micro-bench behind docs/performance.md numbers): small
        # enough that one step is tens of ms on a CPU host, so the host-side
        # work the pipeline hides (collate + staging + readback) is a
        # measurable fraction of the step
        "micro": (64,   172,  2,    4,    2,  8,  1,  "none"),
    }
    preset = os.environ.get("DSTPU_BENCH_MODEL", "697m")
    if preset not in presets:
        raise SystemExit(f"DSTPU_BENCH_MODEL must be one of {sorted(presets)}")
    hidden, inter, layers, heads, kv, mb_default, gas_default, tier = presets[preset]
    vocab = 32000
    if preset == "micro":
        seq_len = 64
        vocab = 2048
    # micro_batch=4/gas=2 reaches ~0.68 MFU on 697m but sits within ~260MB of
    # the HBM ceiling (flaky OOM depending on allocator state); the preset
    # defaults are the safe configs
    micro_batch = int(os.environ.get("DSTPU_BENCH_MICRO_BATCH", mb_default))
    gas = int(os.environ.get("DSTPU_BENCH_GAS", gas_default))
    batch = micro_batch * gas * n_devices

    cfg = LlamaConfig(
        vocab_size=vocab, hidden_size=hidden, intermediate_size=inter,
        num_layers=layers, num_heads=heads, num_kv_heads=kv,
        max_seq_len=seq_len,
        dtype=jnp.bfloat16 if preset != "micro" else jnp.float32,
        attention_backend=os.environ.get(
            "DSTPU_BENCH_ATTN", "flash" if preset != "micro" else "xla"),
        # chunked head+CE fusion: the fp32 [B*S,V] logits (1GB at mb=4) never
        # materialize, freeing ~3GB of HLO temps (enables micro_batch 4).
        # OFF by default: its TPU compile was in flight when the axon tunnel
        # wedged (2026-07-30) and is unproven on hardware — flip the default
        # only after DSTPU_BENCH_LOSS_CHUNK=2048 measures clean on a chip
        # DSTPU_BENCH_LOSS_UNROLL=1 replaces the scan(checkpoint) chunk loop
        # with an unrolled one (compile-time mitigation to try FIRST on
        # chip); it implies a 2048 chunk size when LOSS_CHUNK is unset so the
        # knob can't silently measure the dense path
        loss_chunk_size=int(os.environ.get("DSTPU_BENCH_LOSS_CHUNK", 0)) or (
            2048 if os.environ.get("DSTPU_BENCH_LOSS_UNROLL") == "1" else None),
        loss_chunk_unroll=os.environ.get("DSTPU_BENCH_LOSS_UNROLL", "0") == "1",
        remat=os.environ.get(
            "DSTPU_BENCH_REMAT", "1" if preset != "micro" else "0") == "1",
        remat_policy=os.environ.get("DSTPU_BENCH_REMAT_POLICY",
                                    "dots_with_no_batch_dims_saveable"))
    zero = {"stage": 0 if n_devices == 1 else 3}
    if tier == "optimizer":
        zero["offload_optimizer"] = {"device": "cpu", "ratio": 0.0}
    elif tier == "param":
        zero["offload_optimizer"] = {"device": "cpu", "ratio": 0.0}
        zero["offload_param"] = {
            "device": "cpu",
            "layers_per_group": int(os.environ.get("DSTPU_BENCH_LPG", 4))}
    config = {
        "train_batch_size": batch,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 3e-4}},
        # micro runs fp32: CPU bf16 is emulated (slow), and the micro-bench
        # wants a hardware-honest step time so the host share is realistic
        "bf16": {"enabled": preset != "micro"},
        "data_types": {"grad_accum_dtype":
                       "bf16" if preset != "micro" else "fp32"},
        "zero_optimization": zero,
        "steps_per_print": 1000000,
    }
    model = LlamaForCausalLM(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=config,
        example_batch=random_tokens(2, seq_len, vocab_size=cfg.vocab_size))

    def make_batch(i):
        return random_tokens(micro_batch * n_devices, seq_len,
                             vocab_size=cfg.vocab_size, seed=i, gas=gas)

    from deepspeed_tpu.utils.timer import (TRAIN_BATCH_DISPATCH_TIMER,
                                           TRAIN_BATCH_TIMER)

    # Sync barrier: fetch a device scalar to host. (On tunneled platforms
    # block_until_ready can return before execution finishes; a D2H transfer
    # cannot.)
    loss = engine.train_batch(batch=make_batch(0), stacked=True)  # compile
    float(jax.device_get(loss))

    from deepspeed_tpu.telemetry.compiles import compiles_total

    steps = 10
    engine.timers(TRAIN_BATCH_TIMER).reset()   # drop the compile-step record
    compile_mark = compiles_total()            # warmup done: ledger marked
    t0 = time.time()
    for i in range(1, steps + 1):
        loss = engine.train_batch(batch=make_batch(i), stacked=True)
    float(jax.device_get(loss))
    dt = time.time() - t0
    # the compile-event ledger proof: the warm step compiled the exact
    # shapes, so the timed window must be compile-free — a nonzero count
    # means the headline timed XLA compilation, not training. An explicit
    # check (not assert: python -O must not strip the proof)
    compiles_during_measurement = compiles_total() - compile_mark
    if compiles_during_measurement != 0:
        raise SystemExit(
            f"bench: {compiles_during_measurement} XLA compile(s) inside "
            "the timed window — warm the exact shapes first (see "
            "xla/compile instants in the trace)")
    steps_per_sec = steps / dt
    # host time per step spent *launching* — only meaningful on the fused
    # path (async dispatch leaves completion on-device, so its timer records
    # pure dispatch); offload tiers block on the host optimizer between
    # start/stop, which would mislabel the full step time as dispatch
    dispatch_gap_ms = engine.timers(TRAIN_BATCH_TIMER).mean() * 1000.0 \
        if tier == "none" else None

    # --- async-pipeline sweep (--sync-every 1,8 [--prefetch]) ---------------
    # Same engine, reconfigured per arm at an iterator boundary; each arm
    # feeds train_batch(data_iter=...) so prefetch staging can engage. The
    # iterator runs a real host data pipeline per microbatch — greedy
    # pair-merge tokenization of a synthetic byte corpus (the BPE-shaped
    # python work every LM loader pays) + collate — so the sweep measures
    # the host share the pipeline exists to hide, not a zero-cost replay.
    async_sweep = {}
    if sweep_requested and tier != "none":
        print(f"# async sweep skipped: preset '{preset}' runs a "
              "host-synchronous offload step (nothing to defer)",
              file=sys.stderr)
        sweep_requested = False
    if sweep_requested:
        sweep_steps = max(1, args.sweep_steps)
        corpus = np.random.default_rng(1234).integers(
            0, 256, size=(1 << 16,), dtype=np.uint8)
        merges = {(i, i + 1): 256 + i for i in range(0, 200, 2)}
        bytes_per_sample = seq_len * 8

        def tokenize(buf):
            ids, out, i = list(buf), [], 0
            while i < len(ids):
                if i + 1 < len(ids) and (ids[i], ids[i + 1]) in merges:
                    out.append(merges[(ids[i], ids[i + 1])])
                    i += 2
                else:
                    out.append(ids[i])
                    i += 1
            return np.asarray(out[:seq_len], np.int32) % cfg.vocab_size

        for se in sweep_values:
            # sync_every=1 is the synchronous baseline (per-step readback,
            # inline batch staging — the pre-pipeline loop); --prefetch
            # engages only on the pipelined arms it belongs to
            arm_prefetch = args.prefetch and se > 1
            engine.configure_async_pipeline(
                enabled=True, sync_every=se, prefetch=arm_prefetch)

            def micro_iter(arm=se):
                rng = np.random.default_rng(100_000 + arm)
                while True:
                    starts = rng.integers(
                        0, len(corpus) - bytes_per_sample,
                        size=micro_batch * n_devices)
                    yield {"input_ids": np.stack(
                        [tokenize(bytes(corpus[s:s + bytes_per_sample]))
                         for s in starts])}

            it = micro_iter()
            engine.train_batch(data_iter=it)      # warm the arm
            engine.flush_metrics()                # completion barrier
            engine.timers(TRAIN_BATCH_TIMER).reset()
            engine.timers(TRAIN_BATCH_DISPATCH_TIMER).reset()
            arm_mark = compiles_total()           # arm warmed: ledger marked
            a0 = time.time()
            for _ in range(sweep_steps):
                engine.train_batch(data_iter=it)
            engine.flush_metrics()                # completion barrier
            adt = time.time() - a0
            arm_compiles = compiles_total() - arm_mark
            if arm_compiles != 0:
                raise SystemExit(
                    f"bench: sync_every={se}: {arm_compiles} XLA "
                    "compile(s) inside the timed sweep arm — the arm "
                    "warm step missed a shape")
            async_sweep[f"sync_every={se}"] = {
                "steps_per_sec": round(sweep_steps / adt, 3),
                "dispatch_gap_ms": round(
                    engine.timers(TRAIN_BATCH_DISPATCH_TIMER).mean() * 1000.0, 3),
                "step_ms_reconciled": round(
                    engine.timers(TRAIN_BATCH_TIMER).mean() * 1000.0, 3),
                "prefetch": arm_prefetch,
                "compiles_during_measurement": arm_compiles,
            }
        engine.configure_async_pipeline(enabled=False, prefetch=False)

    tokens_per_sec = steps * batch * seq_len / dt
    tokens_per_sec_chip = tokens_per_sec / n_devices
    n_params = sum(int(np.prod(np.shape(p)))
                   for p in jax.tree.leaves(engine.get_params()))
    flops_per_token = 6 * n_params  # fwd+bwd dense FLOPs (attention excluded → lower bound)
    achieved_tflops = tokens_per_sec_chip * flops_per_token / 1e12
    peak = get_accelerator().peak_tflops("bf16") or 197.0
    mfu = achieved_tflops / peak

    record = {
        "metric": metric_name,
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(mfu / REFERENCE_MFU, 3),
        "extra": {
            "model": preset,
            "memory_tier": tier,
            "n_devices": n_devices,
            "params_millions": round(n_params / 1e6, 1),
            "seq_len": seq_len,
            "model_tflops_per_chip": round(achieved_tflops, 1),
            "mfu": round(mfu, 3),
            "peak_tflops": peak,
            "steps_per_sec": round(steps_per_sec, 3),
            # the compile-ledger proof: 0 == the timed window never paid
            # an XLA compile (asserted above; reported for the record)
            "compiles_during_measurement": compiles_during_measurement,
        },
    }
    if dispatch_gap_ms is not None:
        record["extra"]["dispatch_gap_ms"] = round(dispatch_gap_ms, 3)
    if async_sweep:
        record["extra"]["async_sweep"] = async_sweep
    print(json.dumps(record))
    if not any(k.startswith("DSTPU_BENCH_") for k in os.environ) \
            and not sweep_requested:
        # only the all-defaults config banks the canonical stale-fallback
        # headline — an A/B knob run must never become the replayed record
        from bench_util import bank_headline
        bank_headline(record)


if __name__ == "__main__":
    main()
