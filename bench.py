"""Benchmark driver — prints ONE JSON line with the headline metric.

Run on real TPU hardware by the round driver. Measures sustained training
throughput of the flagship model under the engine's fused train step and reports
model FLOPS utilization-derived tokens/sec/chip vs the BASELINE.json north-star.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.simple import SimpleModel, random_batch

    n_devices = len(jax.devices())
    hidden = 2048
    layers = 8
    batch = 64 * n_devices
    input_dim = 1024

    config = {
        "train_batch_size": batch,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 0},
        "steps_per_print": 1000000,
    }
    model = SimpleModel(hidden_dim=hidden, num_layers=layers)
    example = random_batch(4, input_dim=input_dim)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config,
                                               example_batch=example)

    def make_batch(i):
        return random_batch(batch, input_dim=input_dim, seed=i)

    # warmup / compile
    engine.train_batch(batch=make_batch(0))
    jax.block_until_ready(engine.state.params)

    steps = 20
    t0 = time.time()
    for i in range(1, steps + 1):
        engine.train_batch(batch=make_batch(i))
    jax.block_until_ready(engine.state.params)
    dt = time.time() - t0

    samples_per_sec = steps * batch / dt
    # ~6ND FLOPs per sample (fwd+bwd), N = param count
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(engine.state.params))
    flops_per_sample = 6 * n_params
    tflops_per_chip = samples_per_sec * flops_per_sample / n_devices / 1e12

    print(json.dumps({
        "metric": "train_throughput_mlp",
        "value": round(samples_per_sec, 2),
        "unit": "samples/sec",
        "vs_baseline": 0.0,
        "extra": {
            "n_devices": n_devices,
            "model_tflops_per_chip": round(tflops_per_chip, 2),
            "params_millions": round(n_params / 1e6, 1),
        },
    }))


if __name__ == "__main__":
    main()
