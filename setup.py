"""Packaging with optional AOT native build.

Reference analog: ``setup.py:322`` (``ext_modules`` AOT path for the
op-builder ops). The native components (cpu_adam, aio) JIT-compile on first
use via ``ops/op_builder.py``; ``DSTPU_BUILD_OPS=1 pip install .``
pre-compiles them at install time with the SAME flags as the JIT path and a
source-hash sidecar the loader validates (stale or foreign artifacts fall
back to JIT). Note: ``-march=native`` makes AOT artifacts host-specific —
build wheels on the deployment ISA or leave AOT off.
"""

import hashlib
import os
import shutil
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py

_CSRC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "deepspeed_tpu", "ops", "csrc")
# mirrors ops/op_builder.py DEFAULT_FLAGS (kept literal: setup.py must not
# import the package it is building)
_FLAGS = ["-O3", "-march=native", "-fopenmp", "-fPIC", "-shared", "-std=c++17"]
# per-source extra flags, mirroring each op's registration in op_builder.py
# (aio registers extra_flags=['-pthread'] for pre-2.34 glibc dlopen safety)
_EXTRA_FLAGS = {"aio.cpp": ["-pthread"]}


def _sidecar_hash(path, flags):
    """Sources + compile flags; must stay in sync with the validator in
    ops/op_builder.py (OpBuilder.load) — a flag change (e.g. adding
    -pthread) must invalidate previously installed artifacts."""
    return hashlib.sha256(open(path, "rb").read() + b"\0" +
                          " ".join(flags).encode()).hexdigest()[:16]


class BuildWithOps(build_py):
    def run(self):
        super().run()
        if os.environ.get("DSTPU_BUILD_OPS") != "1":
            return
        out_dir = os.path.join(self.build_lib, "deepspeed_tpu", "ops", "csrc")
        os.makedirs(out_dir, exist_ok=True)
        for src in ("cpu_adam.cpp", "aio.cpp"):
            path = os.path.join(_CSRC, src)
            if not os.path.exists(path):
                continue
            name = src[:-4]
            out = os.path.join(out_dir, name + ".so")
            flags = _FLAGS + _EXTRA_FLAGS.get(src, [])
            cmd = ["g++"] + flags + [path, "-o", out]
            print("AOT:", " ".join(cmd))
            subprocess.run(cmd, check=True)
            with open(out + ".src", "w") as f:   # loader validates this
                f.write(_sidecar_hash(path, flags))
            # editable installs build into an ephemeral dir; also land the
            # artifact next to the sources so the loader can find it
            shutil.copy2(out, os.path.join(_CSRC, name + ".so"))
            shutil.copy2(out + ".src", os.path.join(_CSRC, name + ".so.src"))


setup(cmdclass={"build_py": BuildWithOps})
