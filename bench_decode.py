"""Serving decode-throughput microbench — prints ONE JSON line.

Drives the FastGen-equivalent continuous-batching engine (InferenceEngineV2)
end-to-end: a batch of concurrent sequences prefills, then decodes in lockstep;
steady-state decode tokens/sec is the headline. ``vs_baseline`` is the speedup
of the Pallas paged-attention kernel over the gather-based fallback at a
2048-token context, measured attention-only (the reference's FastGen headline —
2.3x vLLM — is against an external system we can't run here; the engine-level
tokens/sec on a tunneled dev chip is dominated by the host round trip, so the
kernel's contribution is reported at the op level where it is visible).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def run(attn_impl: str, batch: int, prompt_len: int, decode_steps: int):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2, V2EngineConfig
    from deepspeed_tpu.inference.v2.scheduler import SchedulerConfig
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM, random_tokens

    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5632,
        num_layers=12, num_heads=16, num_kv_heads=8, max_seq_len=4096,
        dtype=jnp.bfloat16)
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        random_tokens(1, 8, vocab_size=cfg.vocab_size))["params"]
    params = jax.device_put(jax.tree.map(
        lambda x: x.astype(jnp.bfloat16) if jnp.issubdtype(x.dtype, jnp.floating)
        else x, params))

    engine = InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=64, kv_num_blocks=1024,
        scheduler=SchedulerConfig(max_tokens_per_step=2048,
                                  prefill_buckets=(256,)),
        attn_impl=attn_impl))
    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(0, cfg.vocab_size, prompt_len))
               for _ in range(batch)]
    engine.put(list(range(batch)), prompts)

    for _ in range(3):                       # warm the decode bucket
        engine.step()
    t0 = time.time()
    for _ in range(decode_steps):
        engine.step()
    dt = time.time() - t0
    for uid in range(batch):
        engine.flush(uid)
    return batch * decode_steps / dt


def attention_microbench(ctx: int = 2048, bs: int = 64):
    """Attention-only kernel vs gather at serving shapes; returns (ms_k, ms_g)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.ops.pallas.paged_attention import (
        paged_attention, paged_attention_reference)
    rng = np.random.default_rng(0)
    hkv, d, b, h = 8, 128, 16, 32
    mb = ctx // bs
    nblk = b * mb + 8
    kp = jnp.asarray(rng.normal(size=(hkv, nblk, bs, d)), jnp.bfloat16)
    vp = jnp.asarray(rng.normal(size=(hkv, nblk, bs, d)), jnp.bfloat16)
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.bfloat16)
    tables = jnp.asarray(
        rng.permutation(nblk - 1)[: b * mb].reshape(b, mb), jnp.int32)
    start = jnp.full((b,), ctx - 1, jnp.int32)

    def timeit(f, n=30):
        r = f()
        float(jax.device_get(jnp.sum(r.astype(jnp.float32))))
        t0 = time.time()
        for _ in range(n):
            r = f()
        float(jax.device_get(jnp.sum(r.astype(jnp.float32))))
        return (time.time() - t0) / n * 1e3

    fk = jax.jit(lambda: paged_attention(q, kp, vp, tables, start))
    fr = jax.jit(lambda: paged_attention_reference(q, kp, vp, tables, start))
    return timeit(fk), timeit(fr)


def main():
    batch = int(os.environ.get("DSTPU_DECODE_BATCH", 16))
    prompt_len = int(os.environ.get("DSTPU_DECODE_PROMPT", 256))
    steps = int(os.environ.get("DSTPU_DECODE_STEPS", 64))
    from bench_util import guard_device_discovery
    disarm = guard_device_discovery("bench_decode")
    import jax
    jax.devices()
    disarm()
    on_tpu = jax.default_backend() == "tpu"
    impl = "kernel" if on_tpu else "gather"
    tps = run(impl, batch, prompt_len, steps)
    if on_tpu:
        ms_k, ms_g = attention_microbench()
        speedup = ms_g / max(ms_k, 1e-9)
    else:
        ms_k = ms_g = 0.0
        speedup = 1.0

    print(json.dumps({
        "metric": "llama_decode_tokens_per_sec",
        "value": round(tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(speedup, 3),
        "extra": {"batch": batch, "prompt_len": prompt_len,
                  "decode_steps": steps, "attn_impl": impl,
                  "paged_attn_kernel_ms": round(ms_k, 2),
                  "paged_attn_gather_ms": round(ms_g, 2),
                  "attn_ctx": 2048},
    }))


if __name__ == "__main__":
    main()
