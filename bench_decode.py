"""Serving decode-throughput microbench — prints ONE JSON line.

Drives the FastGen-equivalent continuous-batching engine (InferenceEngineV2)
end-to-end: a batch of concurrent sequences prefills, then decodes in lockstep;
steady-state decode tokens/sec is the headline. ``vs_baseline`` is the speedup
of the Pallas paged-attention kernel over the gather-based fallback at a
2048-token context, measured attention-only (the reference's FastGen headline —
2.3x vLLM — is against an external system we can't run here; the engine-level
tokens/sec on a tunneled dev chip is dominated by the host round trip, so the
kernel's contribution is reported at the op level where it is visible).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


_PARAM_CACHE = {}


def _make_engine(attn_impl: str, kv_dtype: str = "model"):
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2, V2EngineConfig
    from deepspeed_tpu.inference.v2.scheduler import SchedulerConfig
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM, random_tokens

    tiny = os.environ.get("DSTPU_DECODE_TINY") == "1"
    if tiny:                                          # CPU smoke config
        cfg = LlamaConfig(vocab_size=512, hidden_size=64, intermediate_size=128,
                          num_layers=2, num_heads=4, num_kv_heads=2,
                          max_seq_len=1024, dtype=jnp.float32)
    else:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_layers=12, num_heads=16, num_kv_heads=8, max_seq_len=4096,
            dtype=jnp.bfloat16)
    if tiny not in _PARAM_CACHE:   # one init + upload across all table rows
        model = LlamaForCausalLM(cfg)
        params = model.init(
            jax.random.PRNGKey(0),
            random_tokens(1, 8, vocab_size=cfg.vocab_size))["params"]
        _PARAM_CACHE[tiny] = jax.device_put(jax.tree.map(
            lambda x: x.astype(cfg.dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, params))
    params = _PARAM_CACHE[tiny]

    engine = InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=64, kv_num_blocks=1024,
        scheduler=SchedulerConfig(max_tokens_per_step=2048,
                                  prefill_buckets=(256,)),
        attn_impl=attn_impl, kv_cache_dtype=kv_dtype))
    return engine, cfg


def run(attn_impl: str, batch: int, prompt_len: int, decode_steps: int,
        kv_dtype: str = "model"):
    import numpy as np

    engine, cfg = _make_engine(attn_impl, kv_dtype)
    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(0, cfg.vocab_size, prompt_len))
               for _ in range(batch)]
    engine.put(list(range(batch)), prompts)

    for _ in range(3):                       # warm the decode bucket
        engine.step()
    t0 = time.time()
    for _ in range(decode_steps):
        engine.step()
    dt = time.time() - t0
    for uid in range(batch):
        engine.flush(uid)
    return batch * decode_steps / dt


def serving_table(attn_impl: str, prompt_len: int, decode_steps: int):
    """The FastGen-comparison table (reference:
    blogs/deepspeed-fastgen/README.md:28,163,168 — tokens/s + TTFT p50/p95
    across load points): 3 batch mixes x {model-dtype, fp8-scaled} KV pages.
    Enabled by DSTPU_DECODE_TABLE=1 (adds several engine compiles of chip
    time); rows land in the JSON line's extra.serving_table."""
    rows = []
    for kv_dtype in ("model", "fp8"):
        for batch in (4, 16, 32):
            tps = run(attn_impl, batch, prompt_len, decode_steps,
                      kv_dtype=kv_dtype)
            arrivals = max(batch // 2, 1)
            # window must admit every arrival (steps 4..4*arrivals) plus a
            # steady tail so the heaviest row measures its labeled load
            mixed = mixed_load(attn_impl, initial=max(batch // 2, 1),
                               arrivals=arrivals, arrive_every=4,
                               prompt_len=prompt_len,
                               max_steps=4 * arrivals + 32,
                               kv_dtype=kv_dtype)
            rows.append({"kv": kv_dtype, "batch": batch,
                         "decode_tokens_per_sec": round(tps, 1),
                         "mixed_tokens_per_sec":
                             mixed["mixed_tokens_per_sec"],
                         "ttft_p50_ms": mixed["ttft_p50_ms"],
                         "ttft_p95_ms": mixed["ttft_p95_ms"]})
    return rows


def mixed_load(attn_impl: str, initial: int, arrivals: int,
               arrive_every: int, prompt_len: int, max_steps: int,
               kv_dtype: str = "model"):
    """Continuous-batching under MIXED prefill/decode load (the FastGen
    serving scenario the attention-only number can't show): ``initial``
    sequences arrive together, then one more every ``arrive_every`` steps —
    each arrival's prompt chunks through the SplitFuse scheduler while the
    resident sequences keep decoding. Reports overall emitted tokens/s and
    TTFT (put -> first sampled token) p50/p95.
    Reference analog: the FastGen latency/throughput benchmark
    (mii/benchmarks), reference blogs' SplitFuse headline."""
    import numpy as np

    engine, cfg = _make_engine(attn_impl, kv_dtype)
    rng = np.random.default_rng(0)
    total = initial + arrivals

    def prompt():
        return list(rng.integers(0, cfg.vocab_size, prompt_len))

    put_time = {}
    first_tok = {}
    t0 = time.time()
    engine.put(list(range(initial)), [prompt() for _ in range(initial)])
    for u in range(initial):
        put_time[u] = t0
    emitted = 0
    next_uid = initial
    now = t0
    for step_i in range(max_steps):
        if next_uid < total and step_i and step_i % arrive_every == 0:
            put_time[next_uid] = time.time()
            engine.put([next_uid], [prompt()])
            next_uid += 1
        out = engine.step()
        now = time.time()
        for uid in out:
            first_tok.setdefault(uid, now)
        emitted += len(out)
        # max_steps IS the measurement window: throughput is sustained mixed
        # load over the whole window, TTFTs accrue as arrivals get served
    for u in list(put_time):
        engine.flush(u)
    tps = emitted / max(now - t0, 1e-9)
    ttfts = sorted(first_tok[u] - put_time[u] for u in first_tok)
    pct = lambda p: ttfts[min(len(ttfts) - 1, int(p * len(ttfts)))] \
        if ttfts else float("nan")  # noqa: E731
    return {"mixed_tokens_per_sec": round(tps, 1),
            "ttft_p50_ms": round(pct(0.50) * 1e3, 1),
            "ttft_p95_ms": round(pct(0.95) * 1e3, 1),
            "sequences": total, "served_first_token": len(ttfts),
            "arrive_every_steps": arrive_every}


def attention_microbench(ctx: int = 2048, bs: int = 64):
    """Attention-only kernel vs gather at serving shapes; returns (ms_k, ms_g)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.ops.pallas.paged_attention import (
        paged_attention, paged_attention_reference)
    rng = np.random.default_rng(0)
    hkv, d, b, h = 8, 128, 16, 32
    mb = ctx // bs
    nblk = b * mb + 8
    kp = jnp.asarray(rng.normal(size=(hkv, nblk, bs, d)), jnp.bfloat16)
    vp = jnp.asarray(rng.normal(size=(hkv, nblk, bs, d)), jnp.bfloat16)
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.bfloat16)
    tables = jnp.asarray(
        rng.permutation(nblk - 1)[: b * mb].reshape(b, mb), jnp.int32)
    start = jnp.full((b,), ctx - 1, jnp.int32)

    def timeit(f, n=30):
        r = f()
        float(jax.device_get(jnp.sum(r.astype(jnp.float32))))
        t0 = time.time()
        for _ in range(n):
            r = f()
        float(jax.device_get(jnp.sum(r.astype(jnp.float32))))
        return (time.time() - t0) / n * 1e3

    fk = jax.jit(lambda: paged_attention(q, kp, vp, tables, start))
    fr = jax.jit(lambda: paged_attention_reference(q, kp, vp, tables, start))
    return timeit(fk), timeit(fr)


def speculative_gate(decode_tokens: int = 64, n_prompts: int = 4,
                     train_steps: int = 300, spec_k: int = 8):
    """Speculative-decoding quality gate on REAL text (round-4 verdict #8:
    prompt-lookup proposals are data-dependent, so oracle tests prove
    exactness but not value). Trains a byte-level LM on the repo's own
    docs/README (the only real corpus available with zero egress), then
    generates continuations of held-out corpus prompts with speculative on
    vs off and reports tokens/step, acceptance rate, and the wall-clock
    speedup at EQUAL (greedy-identical) output."""
    import glob as _glob

    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                      V2EngineConfig)
    from deepspeed_tpu.inference.v2.scheduler import SchedulerConfig
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    here = os.path.dirname(os.path.abspath(__file__))
    text = "\n".join(
        open(p, errors="ignore").read()
        for p in [os.path.join(here, "README.md")] +
        sorted(_glob.glob(os.path.join(here, "docs", "*.md"))))
    corpus = np.frombuffer(text.encode(), np.uint8).astype(np.int32)

    seq, bs = 128, 16
    cfg = LlamaConfig(vocab_size=256, hidden_size=128, intermediate_size=256,
                      num_layers=2, num_heads=4, num_kv_heads=2,
                      max_seq_len=2048, dtype=jnp.float32,
                      attention_backend="xla", remat=False)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=LlamaForCausalLM(cfg),
        config={"train_batch_size": bs * len(jax.devices()),
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "steps_per_print": 10 ** 9},
        example_batch={"input_ids": np.zeros((2, seq), np.int32)})
    rng = np.random.default_rng(0)
    held_out = len(corpus) - 4096              # tail reserved for prompts
    losses = []
    for _ in range(train_steps):
        starts = rng.integers(0, held_out - seq, bs * len(jax.devices()))
        ids = np.stack([corpus[s:s + seq] for s in starts])
        losses.append(float(jax.device_get(
            engine.train_batch(batch={"input_ids": ids}))))
    params = jax.device_get(engine.state.params)

    def mk(k):
        return InferenceEngineV2(params, cfg, V2EngineConfig(
            kv_block_size=32, kv_num_blocks=256,
            scheduler=SchedulerConfig(max_tokens_per_step=512,
                                      prefill_buckets=(64, 128)),
            speculative_k=k))
    prompts = [list(corpus[held_out + i * 512: held_out + i * 512 + 128])
               for i in range(n_prompts)]

    def gen(k):
        eng = mk(k)
        outs, t = [], 0.0
        for p in prompts:
            t0 = time.time()
            outs.append(eng.generate(p, max_new_tokens=decode_tokens))
            t += time.time() - t0
        return outs, t, eng
    # one untimed warm-up per engine kind BEFORE any timed run: the k=0 and
    # k=spec_k engines compile different programs (decode-only vs verify
    # chunks), so warming only one side banks the other's compile time into
    # its timed pass and skews speedup_at_equal_output
    _ = gen(0)
    _ = gen(spec_k)
    plain_out, plain_t, _ = gen(0)
    spec_out, spec_t, eng = gen(spec_k)
    st = eng.speculative_stats()
    equal = plain_out == spec_out
    return {
        "corpus": "repo README+docs bytes",
        "corpus_bytes": int(len(corpus)),
        "train_steps": train_steps,
        "train_loss_first_last": [round(losses[0], 3), round(losses[-1], 3)],
        "speculative_k": spec_k,
        "tokens_per_step": st["tokens_per_step"],
        "acceptance_rate": round(st["accepted"] / max(st["proposed"], 1), 3),
        "proposed": st["proposed"], "accepted": st["accepted"],
        "output_equal_to_plain_greedy": bool(equal),
        "plain_tokens_per_sec": round(
            n_prompts * decode_tokens / max(plain_t, 1e-9), 1),
        "spec_tokens_per_sec": round(
            n_prompts * decode_tokens / max(spec_t, 1e-9), 1),
        "speedup_at_equal_output": round(plain_t / max(spec_t, 1e-9), 3),
    }


def main():
    batch = int(os.environ.get("DSTPU_DECODE_BATCH", 16))
    prompt_len = int(os.environ.get("DSTPU_DECODE_PROMPT", 256))
    steps = int(os.environ.get("DSTPU_DECODE_STEPS", 64))
    if os.environ.get("DSTPU_FORCE_CPU"):
        # CPU smoke (jax is pre-imported on axon hosts; env vars are too
        # late, config updates still work pre-backend-init)
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 1)
    from bench_util import bounded_device_discovery
    # wedged tunnel: replay the banked decode headline (never a train one —
    # wrong-metric records are rejected by the fallback); bounded-init path
    # adds backoff retries + classified rc (wedge vs no devices vs auth)
    bounded_device_discovery(
        "bench_decode", stale_metric="llama_decode_tokens_per_sec")
    import jax
    on_tpu = jax.default_backend() == "tpu"
    impl = "kernel" if on_tpu else "gather"
    tps = run(impl, batch, prompt_len, steps)
    mixed = mixed_load(impl, initial=max(batch // 2, 1),
                       arrivals=max(batch // 2, 1), arrive_every=4,
                       prompt_len=prompt_len,
                       max_steps=int(os.environ.get(
                           "DSTPU_DECODE_MIXED_STEPS", 96)))
    if on_tpu:
        ms_k, ms_g = attention_microbench()
        speedup = ms_g / max(ms_k, 1e-9)
    else:
        ms_k = ms_g = 0.0
        speedup = 1.0
    extra = {"batch": batch, "prompt_len": prompt_len,
             "decode_steps": steps, "attn_impl": impl,
             "paged_attn_kernel_ms": round(ms_k, 2),
             "paged_attn_gather_ms": round(ms_g, 2),
             "attn_ctx": 2048, **mixed}
    if os.environ.get("DSTPU_DECODE_TABLE") == "1":
        extra["serving_table"] = serving_table(impl, prompt_len, steps)
    if os.environ.get("DSTPU_DECODE_SPEC") == "1":
        extra["speculative"] = speculative_gate()

    record = {
        "metric": "llama_decode_tokens_per_sec",
        "value": round(tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(speedup, 3),
        "extra": extra,
    }
    print(json.dumps(record))
    if on_tpu and not any(k.startswith("DSTPU_DECODE_") for k in os.environ):
        from bench_util import bank_headline
        bank_headline(record, "latest_decode.json")


if __name__ == "__main__":
    main()
