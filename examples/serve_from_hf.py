"""Serve an HF checkpoint end to end: config + state dict -> paged engine.

DeepSpeedExamples analog (MII / FastGen quickstart: point the engine at an
HF checkpoint and generate). Here ``from_hf_checkpoint`` (the
engine_factory analog) maps any of the 14 supported model types into the
training-model param tree, which the FastGen-style ``InferenceEngineV2``
serves directly — no conversion step between training and serving layouts.

Run (CPU demo with a random torch-transformers checkpoint):
  DSTPU_FORCE_CPU=1 python examples/serve_from_hf.py
With a real checkpoint: load config.json + the state dict yourself and
pass them in — the mapping is the same.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("DSTPU_FORCE_CPU"):
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)


def main():
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np
    import torch
    from transformers import LlamaConfig as HFLlamaConfig
    from transformers import LlamaForCausalLM as HFLlama

    from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                      V2EngineConfig)
    from deepspeed_tpu.inference.v2.sampling import SamplingConfig
    from deepspeed_tpu.models.hf import from_hf_checkpoint

    # stand-in for a downloaded checkpoint: a tiny random HF llama
    hf_cfg = HFLlamaConfig(vocab_size=256, hidden_size=64,
                           intermediate_size=128, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2,
                           max_position_embeddings=128)
    torch.manual_seed(0)
    hf_model = HFLlama(hf_cfg).eval()

    model, cfg, params = from_hf_checkpoint(hf_cfg.to_dict(),
                                            hf_model.state_dict())
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    print(f"ingested model_type={hf_cfg.model_type}: "
          f"{sum(np.asarray(x).size for x in jax.tree.leaves(params)):,} "
          "params")

    engine = InferenceEngineV2(
        jax.tree.map(jnp.asarray, params), cfg,
        V2EngineConfig(kv_block_size=16, kv_num_blocks=64,
                       sampling=SamplingConfig(temperature=0.0)))
    prompt = [int(t) for t in np.random.default_rng(0).integers(0, 256, 12)]
    out = engine.generate(prompt, max_new_tokens=8)
    print("prompt:", prompt)
    print("generated:", out)

    # cross-check one step against the HF model's own greedy argmax
    with torch.no_grad():
        ref = int(hf_model(torch.tensor([prompt])).logits[0, -1].argmax())
    assert out[0] == ref, (out[0], ref)
    print("first generated token matches torch-transformers argmax:", ref)


if __name__ == "__main__":
    main()
