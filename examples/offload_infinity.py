"""ZeRO-Offload / Infinity: optimizer state on the host tier.

DeepSpeedExamples analog (zero-offload configs): optimizer moments live in
host RAM (or NVMe via "device": "nvme" + nvme_path), stepped by the C++ CPU
optimizer; the device holds compute-dtype shadows. Twin-Flow `ratio` keeps a
slice of the update on-device.

Quick sanity run (tiny model):
    python examples/offload_infinity.py --steps 10

The >HBM demo (reference: blogs/deepspeed-offloadpp/README.md:10 — train a
model whose params + optimizer state exceed device HBM on one chip):
    python examples/offload_infinity.py --model 1b --steps 3 --measure
trains a ~1.3B-param llama whose total training state (bf16 params + fp32
grads + fp32 master/m/v ≈ 18 bytes/param ≈ 22 GiB) exceeds a v5e chip's
16 GB HBM — only the bf16 shadow + grads + activations live on device.
--measure prints one JSON line with step time and the effective
host<->device swap bandwidth (fp32 grads D2H + bf16 shadow H2D =
6 bytes/param/step).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# DSTPU_FORCE_CPU=1: run on virtual CPU devices (jax is pre-imported on some
# hosts, so env vars are too late — config updates still work pre-backend-init)
if os.environ.get("DSTPU_FORCE_CPU"):
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--device", default="cpu", choices=["cpu", "nvme"])
    p.add_argument("--nvme_path", default="/tmp/dstpu_nvme")
    p.add_argument("--model", default="tiny",
                   choices=["tiny", "1b", "3b", "8b"],
                   help="'1b': ~1.3B params — total training state exceeds "
                        "one v5e chip's 16 GB HBM (the ZeRO-Infinity case). "
                        "'8b': ~8B params — bf16 WEIGHTS alone exceed HBM "
                        "(requires --offload_param)")
    p.add_argument("--seq", type=int, default=0,
                   help="override sequence length (default: 32 tiny/1024 1b)")
    p.add_argument("--micro_batch", type=int, default=0)
    p.add_argument("--offload_param", action="store_true",
                   help="ZeRO-Infinity PARAMETER offload: weights live on "
                        "host and stream through HBM layer-group by "
                        "layer-group (runtime/param_offload.py)")
    p.add_argument("--layers_per_group", type=int, default=2)
    p.add_argument("--measure", action="store_true",
                   help="print one JSON line: step time + swap bandwidth")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import (
        TINY_LLAMA, LlamaConfig, LlamaForCausalLM, random_tokens)

    sizes = {
        # hidden, intermediate, layers, heads, kv_heads
        "1b": (2048, 5632, 24, 16, 8),
        "3b": (3072, 8192, 28, 24, 8),
        "8b": (4096, 14336, 32, 32, 8),   # llama-3-8B geometry, 32k vocab
    }
    if args.model in sizes:
        h, inter, layers, heads, kv = sizes[args.model]
        seq = args.seq or 1024
        mb = args.micro_batch or 1
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=h, intermediate_size=inter,
            num_layers=layers, num_heads=heads, num_kv_heads=kv,
            max_seq_len=seq,
            dtype=jnp.bfloat16, attention_backend="flash", remat=True,
            remat_policy="dots_with_no_batch_dims_saveable")
        gas = 2
    else:
        cfg, seq, mb, gas = TINY_LLAMA, args.seq or 32, 8, 1

    offload = {"device": args.device, "ratio": 0.8 if args.model == "tiny"
               else 0.0}  # 1b: fully host-resident moments (>HBM is the point)
    if args.device == "nvme":
        os.makedirs(args.nvme_path, exist_ok=True)
        offload["nvme_path"] = args.nvme_path
    zero = {"stage": 2, "offload_optimizer": offload}
    if args.offload_param:
        zero["offload_param"] = {"device": args.device,
                                 "layers_per_group": args.layers_per_group}
        if args.device == "nvme":
            zero["offload_param"]["nvme_path"] = args.nvme_path
        zero["stage"] = 0
    config = {
        "train_batch_size": mb * gas,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": zero,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=LlamaForCausalLM(cfg), config=config,
        example_batch=random_tokens(1, seq, vocab_size=cfg.vocab_size))
    assert engine._offload is not None
    n_params = sum(int(np.prod(np.shape(x)))
                   for x in jax.tree.leaves(engine.get_params()))
    state_gib = n_params * (2 + 4 + 12) / 2**30  # bf16 + grads + fp32 m/v/mst
    print(f"{n_params / 1e9:.2f}B params; total training state "
          f"{state_gib:.1f} GiB (device keeps ~{n_params * 6 / 2**30:.1f})")

    if args.measure and args.steps < 2:
        p.error("--measure needs --steps >= 2 (step 1 is compile+warmup)")
    # stacked contract: [gas, micro_batch, ...] — micro size is mb, not mb*gas
    fixed = random_tokens(mb, seq, vocab_size=cfg.vocab_size, seed=0,
                          gas=gas if gas > 1 else None)
    losses = [float(engine.train_batch(batch=fixed))]   # compile + step 1
    t0 = time.perf_counter()
    for _ in range(args.steps - 1):
        losses.append(float(engine.train_batch(batch=fixed)))
    dt = (time.perf_counter() - t0) / max(args.steps - 1, 1)
    print(f"offload={args.device}: loss {losses[0]:.4f} -> {losses[-1]:.4f}")
    assert losses[-1] < losses[0] and all(np.isfinite(losses))
    if args.measure:
        phases = {}
        if args.offload_param:
            # measured H2D param stream + fp32 grads D2H (once per microbatch)
            po = engine._param_offload
            swap_bytes = po.bytes_streamed + 4 * n_params * gas
            metric = "zero_infinity_param_offload_step_time"
            phases = po.phase_seconds
        else:
            swap_bytes = 6 * n_params        # fp32 grads D2H + bf16 H2D
            metric = "zero_infinity_step_time"
        print(json.dumps({
            "metric": metric, "value": round(dt, 3),
            "unit": "s/step", "model_params_b": round(n_params / 1e9, 3),
            "state_gib": round(state_gib, 1), "offload_device": args.device,
            "offload_param": bool(args.offload_param),
            "swap_gib_per_step": round(swap_bytes / 2**30, 2),
            "effective_swap_gibps": round(swap_bytes / 2**30 / dt, 2),
            "seq_len": seq, "tokens_per_sec": round(mb * gas * seq / dt, 1),
            **({"phase_seconds": phases} if phases else {}),
        }))


if __name__ == "__main__":
    main()
