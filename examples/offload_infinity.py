"""ZeRO-Offload / Infinity: optimizer state on the host tier.

DeepSpeedExamples analog (zero-offload configs): optimizer moments live in
host RAM (or NVMe via "device": "nvme" + nvme_path), stepped by the C++ CPU
optimizer; the device holds compute-dtype shadows. Twin-Flow `ratio` keeps a
slice of the update on-device.

`python examples/offload_infinity.py --steps 10`
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# DSTPU_FORCE_CPU=1: run on virtual CPU devices (jax is pre-imported on some
# hosts, so env vars are too late — config updates still work pre-backend-init)
if os.environ.get("DSTPU_FORCE_CPU"):
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--device", default="cpu", choices=["cpu", "nvme"])
    p.add_argument("--nvme_path", default="/tmp/dstpu_nvme")
    args = p.parse_args()

    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import (
        TINY_LLAMA, LlamaForCausalLM, random_tokens)

    offload = {"device": args.device, "ratio": 0.8}
    if args.device == "nvme":
        os.makedirs(args.nvme_path, exist_ok=True)
        offload["nvme_path"] = args.nvme_path
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2, "offload_optimizer": offload},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=LlamaForCausalLM(TINY_LLAMA), config=config,
        example_batch=random_tokens(2, 32, vocab_size=TINY_LLAMA.vocab_size))
    assert engine._offload is not None
    fixed = random_tokens(8, 32, vocab_size=TINY_LLAMA.vocab_size, seed=0)
    losses = [float(engine.train_batch(batch=fixed)) for _ in range(args.steps)]
    print(f"offload={args.device}: loss {losses[0]:.4f} -> {losses[-1]:.4f}")
    assert losses[-1] < losses[0] and all(np.isfinite(losses))


if __name__ == "__main__":
    main()
