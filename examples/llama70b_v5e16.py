"""BASELINE.json north star: Llama-70B training on a v5e-16 slice.

Reference analog: ZeRO-Infinity's 'train 100B+ on limited resources' story
(blogs/deepspeed-offloadpp + runtime/swap_tensor/): the weights and state
don't fit the accelerators, so tiers stream.

The memory math on v5e-16 (16 chips x 16 GB HBM = 256 GB; 70B params):

  bf16 weights            138 GB   -> fsdp=16 shard: 8.6 GB/chip
  bf16 grad-accum shard     0.6 GB    (sharded like params, zero>=2)
  fp32 masters + Adam m/v 828 GB   -> HOST/NVMe tier (offload_optimizer;
                                      nvme swaps masters too: swap_masters)
  activations (remat)     ~2-3 GB/chip at seq 4096, mb 1
  allgather working set   ~2 layers' full params ~3.5 GB

  --mode fsdp   : ZeRO-3 over fsdp=16 + host/nvme optimizer states.
                  ~15 GB/chip — fits, the preferred config.
  --mode stream : ZeRO-Infinity PARAMETER offload (offload_param) — weights
                  live on host and stream through HBM layer-group by
                  layer-group. Peak HBM = 2 groups (2x ~3.5 GB) +
                  activations, regardless of model size; for when the fsdp
                  shard itself doesn't fit (bigger models / fewer chips).

``--dryrun`` runs the SAME config mechanics at toy geometry on 16 virtual
CPU devices (mesh, zero stage, offload tiers, streaming) — what the driver's
multichip gate validates; the full-size run needs the real slice.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def geometry(dryrun: bool):
    if dryrun:
        return dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    num_layers=8, num_heads=4, num_kv_heads=2, seq=64,
                    layers_per_group=2)
    return dict(vocab_size=32000, hidden_size=8192, intermediate_size=28672,
                num_layers=80, num_heads=64, num_kv_heads=8, seq=4096,
                layers_per_group=4)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--mode", default="fsdp", choices=["fsdp", "stream"])
    p.add_argument("--dryrun", action="store_true",
                   help="toy geometry on 16 virtual CPU devices")
    p.add_argument("--steps", type=int, default=2)
    p.add_argument("--nvme_path", default=None,
                   help="optimizer-state tier on NVMe (full ZeRO-Infinity: "
                        "moments AND fp32 masters in files)")
    args = p.parse_args()

    if args.dryrun:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   " --xla_force_host_platform_device_count=16").strip()
    import jax
    if args.dryrun:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 16)
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.comm.mesh import create_mesh
    from deepspeed_tpu.config.config import MeshConfig
    from deepspeed_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                            llama_tensor_rules, random_tokens)

    n = len(jax.devices())
    if n < 16 and not args.dryrun:
        p.error(f"needs a 16-chip slice (have {n}); use --dryrun")
    g = geometry(args.dryrun)
    seq = g.pop("seq")
    lpg = g.pop("layers_per_group")
    cfg = LlamaConfig(max_seq_len=seq, dtype=jnp.bfloat16,
                      attention_backend="flash" if not args.dryrun else "xla",
                      remat=True,
                      remat_policy="dots_with_no_batch_dims_saveable", **g)

    opt_tier = {"device": "nvme", "nvme_path": args.nvme_path} \
        if args.nvme_path else {"device": "cpu"}
    if args.mode == "fsdp":
        mesh = create_mesh(MeshConfig(fsdp=16))
        zero = {"stage": 3, "offload_optimizer": {**opt_tier, "ratio": 0.0}}
        batch = 16
    else:
        mesh = create_mesh(MeshConfig(data=16))
        zero = {"stage": 0,
                "offload_optimizer": {**opt_tier, "ratio": 0.0},
                "offload_param": {"device": "cpu",
                                  "layers_per_group": lpg}}
        batch = 16
    config = {
        "train_batch_size": batch,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1.5e-4}},
        "bf16": {"enabled": True},
        "data_types": {"grad_accum_dtype": "bf16"},
        "zero_optimization": zero,
        "gradient_clipping": 1.0,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=LlamaForCausalLM(cfg), config=config, mesh=mesh,
        tensor_rules=llama_tensor_rules,
        example_batch=random_tokens(2, seq, vocab_size=cfg.vocab_size))
    n_params = sum(int(np.prod(np.shape(x)))
                   for x in jax.tree.leaves(engine.get_params()))
    print(f"{n_params/1e9:.2f}B params, mode={args.mode}, mesh="
          f"{dict(mesh.shape)}, bf16 weights {n_params*2/2**30:.1f} GiB "
          f"({n_params*2/2**30/16:.2f}/chip under fsdp=16), fp32 state "
          f"{n_params*12/2**30:.0f} GiB on the "
          f"{'nvme' if args.nvme_path else 'host'} tier")
    losses = []
    for i in range(args.steps):
        b = random_tokens(batch, seq, vocab_size=cfg.vocab_size, seed=i)
        losses.append(float(jax.device_get(engine.train_batch(batch=b))))
    print(f"losses: {[round(l, 4) for l in losses]}")
    assert all(np.isfinite(losses)), losses
    print("ok")


if __name__ == "__main__":
    main()
