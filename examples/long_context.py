"""Long-context training: sequence parallelism over the ``sequence`` axis.

DeepSpeed-Ulysses analog (blogs/deepspeed-ulysses): activations shard as
[B, S/sp, ...] so the per-device activation footprint drops by the sequence
degree. Two backends, same config knob (``attention_backend``):

- ``ulysses``: head-scatter all-to-all, local full-sequence attention on a
  head slice (the reference's only long-context mechanism).
- ``ring``: blockwise ring attention over ``ppermute`` — the
  context-parallel strategy the reference lacks; O(S/sp) resident KV.

`DSTPU_FORCE_CPU=1 python examples/long_context.py --backend ring --seq 2048`
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# DSTPU_FORCE_CPU=1: run on virtual CPU devices (jax is pre-imported on some
# hosts, so env vars are too late — config updates still work pre-backend-init)
if os.environ.get("DSTPU_FORCE_CPU"):
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--backend", default="ring", choices=["ring", "ulysses"])
    p.add_argument("--seq", type=int, default=2048)
    p.add_argument("--sp", type=int, default=4, help="sequence-parallel degree")
    p.add_argument("--steps", type=int, default=5)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import (
        TINY_LLAMA, LlamaConfig, LlamaForCausalLM, random_tokens)

    import dataclasses

    n_dev = len(jax.devices())
    if n_dev % args.sp:
        raise SystemExit(f"{n_dev} devices not divisible by sp={args.sp}")
    if args.seq % args.sp:
        raise SystemExit(f"seq {args.seq} not divisible by sp={args.sp}")
    dp = n_dev // args.sp
    cfg = dataclasses.replace(TINY_LLAMA, max_seq_len=args.seq,
                              attention_backend=args.backend,
                              dtype=jnp.float32)
    config = {
        "train_batch_size": 2 * dp,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "mesh": {"data": dp, "sequence": args.sp},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=LlamaForCausalLM(cfg), config=config,
        example_batch=random_tokens(2, args.seq,
                                    vocab_size=cfg.vocab_size))
    batch = random_tokens(2 * dp, args.seq, vocab_size=cfg.vocab_size, seed=0)
    losses = [float(engine.train_batch(batch=batch))
              for _ in range(args.steps)]
    print(f"{args.backend} sp={args.sp} seq={args.seq}: "
          f"loss {losses[0]:.4f} -> {losses[-1]:.4f}")
    assert losses[-1] < losses[0] and all(np.isfinite(losses))


if __name__ == "__main__":
    main()
