"""Pretrain a Llama-family model end to end.

DeepSpeedExamples analog (megatron/llama pretraining): config-driven engine,
ZeRO-3 + bf16 + remat + chunked-CE loss, checkpoint/resume, monitoring.
Runs anywhere: `python examples/pretrain_llama.py --steps 20` uses a tiny
model on whatever devices exist (8 virtual CPU devices under the test env;
the real thing on a TPU slice). Scale by swapping the config for LLAMA3_8B
and adding a "mesh" block.
"""

import argparse
import dataclasses
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# DSTPU_FORCE_CPU=1: run on virtual CPU devices (jax is pre-imported on some
# hosts, so env vars are too late — config updates still work pre-backend-init)
if os.environ.get("DSTPU_FORCE_CPU"):
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--seq_len", type=int, default=128)
    p.add_argument("--ckpt_dir", default=None)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--packed", action="store_true",
                   help="pack variable-length synthetic documents into the "
                        "batch (segment_ids masked in-kernel, per-document "
                        "positions, target-gated loss)")
    args = p.parse_args()

    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import (
        TINY_LLAMA, LlamaForCausalLM, random_tokens)

    n_dev = len(jax.devices())
    cfg = dataclasses.replace(TINY_LLAMA, max_seq_len=args.seq_len,
                              remat=True, loss_chunk_size=args.seq_len)
    config = {
        "train_batch_size": 2 * n_dev * 2,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 3e-3,
                                                  "weight_decay": 0.1}},
        "scheduler": {"type": "WarmupDecayLR",
                      "params": {"warmup_num_steps": 5,
                                 "total_num_steps": args.steps}},
        "gradient_clipping": 1.0,
        "zero_optimization": {"stage": 3},
        "steps_per_print": 10,
        "csv_monitor": {"enabled": bool(args.ckpt_dir),
                        "output_path": args.ckpt_dir or ""},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=LlamaForCausalLM(cfg), config=config,
        example_batch=random_tokens(2, args.seq_len,
                                    vocab_size=cfg.vocab_size))
    if args.resume and args.ckpt_dir:
        engine.load_checkpoint(args.ckpt_dir)

    packed_batches = None
    if args.packed:
        import numpy as np
        from deepspeed_tpu.data_pipeline import (pack_sequences,
                                                 packing_efficiency)
        rng = np.random.default_rng(0)
        docs = [rng.integers(1, cfg.vocab_size,
                             size=rng.integers(args.seq_len // 6,
                                               args.seq_len)).astype(np.int32)
                for _ in range(24 * n_dev)]
        packed_batches = pack_sequences(docs, batch_size=2 * n_dev,
                                        seq_len=args.seq_len)
        print(f"packed {len(docs)} docs into {len(packed_batches)} batches "
              f"({packing_efficiency(packed_batches):.0%} slot utilization)")

    for step in range(args.steps):
        if packed_batches is not None:
            import numpy as np
            micro = [packed_batches[(2 * step + g) % len(packed_batches)]
                     for g in range(2)]
            batch = {k: np.stack([m[k] for m in micro]) for k in micro[0]}
        else:
            batch = random_tokens(2 * n_dev, args.seq_len,
                                  vocab_size=cfg.vocab_size, seed=step % 4,
                                  gas=2)
        loss = engine.train_batch(batch=batch, stacked=True)
        if step % 5 == 0 or step == args.steps - 1:
            lr = engine.get_lr()
            lr = lr[0] if isinstance(lr, (list, tuple)) else lr
            print(f"step {step}: loss {float(loss):.4f} lr {lr:.2e}")
    if args.ckpt_dir:
        engine.save_checkpoint(args.ckpt_dir)
        print(f"checkpoint saved to {args.ckpt_dir}")
    return float(loss)


if __name__ == "__main__":
    main()
