"""Continuous-batching serving (FastGen analog).

DeepSpeedExamples/MII analog: build an InferenceEngineV2 over any registered
architecture, admit a ragged wave of requests through put/can_schedule,
step the engine, and flush completions — with device-side sampling.

`python examples/serve_fastgen.py --arch bloom` (llama | falcon | opt |
mixtral | bloom | gpt_neox | gpt2).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# DSTPU_FORCE_CPU=1: run on virtual CPU devices (jax is pre-imported on some
# hosts, so env vars are too late — config updates still work pre-backend-init)
if os.environ.get("DSTPU_FORCE_CPU"):
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)

ARCHS = {
    "llama": ("deepspeed_tpu.models.llama", "TINY_LLAMA", "LlamaForCausalLM"),
    "falcon": ("deepspeed_tpu.models.falcon", "TINY_FALCON", "FalconForCausalLM"),
    "opt": ("deepspeed_tpu.models.opt", "TINY_OPT", "OPTForCausalLM"),
    "mixtral": ("deepspeed_tpu.models.mixtral", "TINY_MIXTRAL", "MixtralForCausalLM"),
    "bloom": ("deepspeed_tpu.models.bloom", "TINY_BLOOM", "BloomForCausalLM"),
    "gpt_neox": ("deepspeed_tpu.models.gpt_neox", "TINY_NEOX", "GPTNeoXForCausalLM"),
    "gpt2": ("deepspeed_tpu.models.gpt2", "TINY_GPT2", "GPT2ForCausalLM"),
}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--arch", default="llama", choices=sorted(ARCHS))
    p.add_argument("--requests", type=int, default=6)
    p.add_argument("--max_new_tokens", type=int, default=8)
    p.add_argument("--temperature", type=float, default=0.8)
    p.add_argument("--speculative_k", type=int, default=0,
                   help="also demo draft-free speculative decoding (greedy)")
    args = p.parse_args()

    import importlib

    import jax
    import numpy as np

    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, V2EngineConfig)
    from deepspeed_tpu.inference.v2.sampling import SamplingConfig

    mod_name, cfg_name, cls_name = ARCHS[args.arch]
    mod = importlib.import_module(mod_name)
    cfg, model = getattr(mod, cfg_name), getattr(mod, cls_name)(getattr(mod, cfg_name))
    rng = np.random.default_rng(0)
    init_batch = {"input_ids": rng.integers(
        0, cfg.vocab_size, size=(1, 8)).astype(np.int32)}
    params = model.init(jax.random.PRNGKey(0), init_batch)["params"]

    engine = InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=256,
        sampling=SamplingConfig(temperature=args.temperature, top_k=40,
                                seed=0)))

    prompts = {uid: list(rng.integers(0, cfg.vocab_size,
                                      size=rng.integers(4, 12)))
               for uid in range(args.requests)}
    pending = dict(prompts)
    in_flight = set()
    done = {}
    while pending or in_flight:
        # grow the admitted wave while the BATCH still fits (put() re-checks
        # the combined batch, so admission must be checked combined too)
        admit = []
        for u in list(pending):
            if engine.can_schedule(admit + [u],
                                   [len(pending[c]) for c in admit] +
                                   [len(pending[u])]):
                admit.append(u)
        if admit:
            engine.put(admit, [pending.pop(u) for u in admit])
            in_flight.update(admit)
        engine.step()
        for uid in list(in_flight):
            if len(engine.state.get(uid).generated) >= args.max_new_tokens:
                # put()/step() may overshoot by a token; honor the budget
                done[uid] = engine.flush(uid)[:args.max_new_tokens]
                in_flight.discard(uid)
    for uid in sorted(done):
        print(f"request {uid}: prompt {len(prompts[uid])} tokens -> "
              f"{done[uid]}")
    assert len(done) == args.requests
    print(f"{args.arch}: served {len(done)} requests")

    if args.speculative_k > 0:
        # serial speculative generation on the same weights (greedy-exact,
        # 1..k+1 tokens per verify step; prompt-lookup hits on repetitive
        # prompts)
        spec = InferenceEngineV2(params, cfg, V2EngineConfig(
            kv_block_size=16, kv_num_blocks=256,
            speculative_k=args.speculative_k))
        base = list(rng.integers(0, cfg.vocab_size, size=5))
        out = spec.generate(base * 4, max_new_tokens=args.max_new_tokens * 2)
        st = spec.speculative_stats()
        if st["steps"]:
            print(f"speculative k={args.speculative_k}: {len(out)} tokens, "
                  f"{st['tokens_per_step']:.2f} tokens/step on verify steps "
                  f"(accepted {st['accepted']}/{st['proposed']})")
        else:
            # a randomly-initialized model never re-emits its context's
            # n-grams, so lookup proposals don't fire — generation stays
            # exact via the 1-token fallback; real LMs repeat constantly
            print(f"speculative k={args.speculative_k}: {len(out)} tokens, "
                  "no lookup hits on this random tiny model (exact greedy "
                  "fallback; proposals engage on repetitive text)")


if __name__ == "__main__":
    main()
