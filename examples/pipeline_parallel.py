"""Pipeline-parallel llama training via initialize(model=PipeModule).

DeepSpeedExamples analog (``training/pipeline_parallelism``): build a
PipelineModule, hand it to ``deepspeed.initialize``, train with
``engine.train_batch()`` pulling microbatches. Here the llama adapter
splits a scan-layers param tree into (stacked blocks, tied embed/head),
the 1F1B lockstep executor runs the whole schedule in one jit, and the
trained weights consolidate back into the dense model tree for serving or
a different parallelism topology.

Run: ``DSTPU_FORCE_CPU=1 python examples/pipeline_parallel.py --steps 10``
(pipe=2 x data=4 on the 8 virtual devices; on a real slice raise --stages).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("DSTPU_FORCE_CPU"):
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--stages", type=int, default=2)
    p.add_argument("--microbatches", type=int, default=4)
    p.add_argument("--seq_len", type=int, default=64)
    p.add_argument("--ckpt_dir", default=None)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.comm.mesh import create_mesh, set_global_mesh
    from deepspeed_tpu.config.config import MeshConfig
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from deepspeed_tpu.runtime.pipe.module import (llama_params_from_pipe,
                                                   llama_pipe_module)

    n_dev = len(jax.devices())
    if n_dev % args.stages:
        raise SystemExit(f"--stages {args.stages} must divide the device "
                         f"count ({n_dev})")
    cfg = LlamaConfig(vocab_size=512, hidden_size=64, intermediate_size=128,
                      num_layers=4, num_heads=4, num_kv_heads=2,
                      max_seq_len=args.seq_len, scan_layers=True,
                      dtype=jnp.float32)
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)

    def batch(bs):
        return rng.integers(0, cfg.vocab_size,
                            size=(bs, args.seq_len)).astype(np.int32)

    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": jnp.asarray(batch(2))})
    mesh = create_mesh(MeshConfig(pipe=args.stages,
                                  data=n_dev // args.stages))
    set_global_mesh(mesh)

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=llama_pipe_module(cfg, params), mesh=mesh,
        config={"gradient_accumulation_steps": args.microbatches,
                "train_micro_batch_size_per_gpu": 2,
                "gradient_clipping": 1.0,
                "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}}})

    b = args.microbatches * 2
    for step in range(args.steps):
        loss = engine.train_batch(batch(b))
        if step % 2 == 0:
            print(f"step {step:3d}  loss {loss:.4f}")
    eval_batch = batch(b)
    print(f"eval loss {engine.eval_batch(eval_batch):.4f}")

    if args.ckpt_dir:
        print("checkpoint:", engine.save_checkpoint(args.ckpt_dir))

    # consolidate PP weights back into the dense tree (serving / other
    # topologies load this directly)
    stacked, tied = engine.consolidated_module_params()
    dense = llama_params_from_pipe(cfg, stacked, tied)
    dense_loss = float(model.apply(jax.tree.map(jnp.asarray, dense),
                                   {"input_ids": jnp.asarray(eval_batch)}))
    print(f"dense-model loss on consolidated weights {dense_loss:.4f} "
          "(same batch as eval -> matches)")


if __name__ == "__main__":
    main()
