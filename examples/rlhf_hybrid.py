"""RLHF train<->generate flip on shared weights (DeepSpeed-Chat analog).

The hybrid engine trains (PPO-style update against a toy reward) and
generates rollouts from the SAME weight set — the generation side runs the
FastGen view with LoRA fused in, no weight copies.

`python examples/rlhf_hybrid.py --iters 3`
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# DSTPU_FORCE_CPU=1: run on virtual CPU devices (jax is pre-imported on some
# hosts, so env vars are too late — config updates still work pre-backend-init)
if os.environ.get("DSTPU_FORCE_CPU"):
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--rollout_len", type=int, default=8)
    args = p.parse_args()

    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import (
        TINY_LLAMA, LlamaForCausalLM, random_tokens)

    n_dev = len(jax.devices())
    config = {
        "train_batch_size": 2 * n_dev,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3},
        "hybrid_engine": {"enabled": True},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=LlamaForCausalLM(TINY_LLAMA), config=config,
        example_batch=random_tokens(2, 32, vocab_size=TINY_LLAMA.vocab_size))

    rng = np.random.default_rng(0)
    for it in range(args.iters):
        # 1) generate rollouts from current weights (FastGen view)
        prompts = [list(rng.integers(0, TINY_LLAMA.vocab_size, size=6))
                   for _ in range(2)]
        rollouts = engine.generate(prompts, max_new_tokens=args.rollout_len)
        # 2) toy "reward-weighted" SFT step on the rollouts (stands in for PPO)
        seqs = [p + r for p, r in zip(prompts, rollouts)]
        width = max(len(s) for s in seqs)
        ids = np.zeros((2 * n_dev, width), np.int32)
        for row in range(ids.shape[0]):
            s = seqs[row % len(seqs)]
            ids[row, :len(s)] = s
        loss = engine.train_batch(batch={"input_ids": ids})
        print(f"iter {it}: rollout lens {[len(r) for r in rollouts]}, "
              f"train loss {float(loss):.4f}")
    # per-phase flip instrumentation (reference hybrid_engine.py:30
    # _t_start/_t_gen family): train->generate view refresh cost
    print(f"rlhf hybrid flip OK: {engine.flip_count} flips, "
          f"mean flip latency "
          f"{engine.latency_report()['flip_mean_s'] * 1e3:.1f} ms")


if __name__ == "__main__":
    main()
