"""Radix prefix cache over KV pages + quantized host-tier KV.

Unit pieces (trie, planners, page codec, the pinned-scale release fix)
run without a model; engine-level tests share the tiny fp32 llama and
the KV/bucket shapes of tests/test_serving.py (one compile per shape per
process); the bench_serve multi_turn drill is the tier-1 acceptance gate
for the counter-conservation identity
``prefill_tokens_saved + prefill_tokens_computed == prefill_tokens_total``.
"""

import dataclasses as dc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  V2EngineConfig)
from deepspeed_tpu.inference.v2.kv_cache import BlockedKVCache, KVCacheConfig
from deepspeed_tpu.inference.v2.kv_offload import (dequantize_pages,
                                                   quantize_error_bound,
                                                   quantize_pages)
from deepspeed_tpu.inference.v2.prefix_cache import PrefixCache
from deepspeed_tpu.inference.v2.scheduler import SchedulerConfig
from deepspeed_tpu.models.llama import (TINY_LLAMA, LlamaConfig,
                                        LlamaForCausalLM)
from deepspeed_tpu.serving.kv_tier import plan_prefix_evictions

pytestmark = pytest.mark.prefix


@pytest.fixture(scope="module")
def model_and_params():
    cfg = LlamaConfig(**{**TINY_LLAMA.__dict__, "dtype": jnp.float32,
                         "max_seq_len": 512})
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": np.zeros((1, 8), np.int32)})["params"]
    return cfg, params


def _engine(cfg, params, prefix=True, kv_blocks=64, **kw):
    return InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=kv_blocks,
        scheduler=SchedulerConfig(max_tokens_per_step=64,
                                  prefill_buckets=(16, 32, 64)),
        prefix_cache_enabled=prefix, **kw))


# ---------------------------------------------------------------------------
# trie unit (pure bookkeeping — no model, no device)
# ---------------------------------------------------------------------------
def test_trie_lookup_pins_and_full_block_cap():
    c = PrefixCache(block_size=4)
    toks = list(range(100, 112))                       # 3 full blocks
    # nothing cached -> miss
    blocks, matched = c.admit_match(1, toks)
    assert blocks == [] and matched == 0
    assert c.stats.misses == 1
    # register 3 full blocks for uid 1 (pinned)
    assert c.insert_from_seq(1, toks, [5, 6, 7], seen_tokens=12) == 3
    assert c.cached_blocks() == 3 and c.pinned_blocks() == 3
    assert c.evictable_blocks() == 0
    # exact-length lookup caps at (len-1)//bs = 2 blocks: the last token
    # must always be computed to produce first-sample logits
    blocks, matched = c.admit_match(2, toks)
    assert blocks == [5, 6] and matched == 8
    # longer prompt with the same prefix matches all 3 blocks
    blocks, matched = c.admit_match(3, toks + [1, 2, 3, 4, 5])
    assert blocks == [5, 6, 7] and matched == 12
    assert sorted(c.pinned_block_ids()) == [5, 6, 7]
    # drop every reader: blocks STAY cached, now evictable
    for uid in (1, 2, 3):
        c.release_seq(uid)
    assert c.cached_blocks() == 3 and c.evictable_blocks() == 3
    snap = c.snapshot()
    assert snap["hit_tokens"] == 8 + 12
    assert snap["hits"] == 2 and snap["misses"] == 1


def test_trie_eviction_is_lru_leaf_first():
    c = PrefixCache(block_size=2)
    c.insert_from_seq(1, [1, 2, 3, 4, 5, 6], [10, 11, 12], 6)  # chain 10-11-12
    c.insert_from_seq(2, [1, 2, 9, 9], [10, 20], 4)            # branch 20
    c.release_seq(1)
    c.release_seq(2)
    # leaf-first: the root block 10 (shared by both chains) cannot go
    # before its children; oldest-stamp leaf goes first
    plan = c.plan_evictions(2)
    assert 10 not in plan and len(plan) == 2
    freed = c.evict_blocks(plan)
    assert freed == plan
    # the remaining chain evicts completely, deepest first
    rest = c.plan_evictions(10)
    assert rest[-1] == 10                  # root only after its subtree
    c.evict_blocks(rest)
    assert c.cached_blocks() == 0
    assert c.stats.evicted_blocks == 4
    # pinned nodes never evict
    c.insert_from_seq(3, [1, 2], [30], 2)
    assert c.plan_evictions(5) == []


def test_trie_soft_cap_and_planner():
    c = PrefixCache(block_size=2, max_cached_blocks=1)
    c.insert_from_seq(1, [1, 2, 3, 4], [10, 11], 4, pin=False)
    assert c.over_cap_blocks() == 1
    # planner: over-cap trim even without pressure
    assert plan_prefix_evictions(2, c.over_cap_blocks(),
                                 reserved_blocks=0,
                                 demote_line_blocks=100.0) == 1
    # pressure: evict down to the demote line, bounded by evictable
    assert plan_prefix_evictions(5, 0, reserved_blocks=12,
                                 demote_line_blocks=8.0) == 4
    assert plan_prefix_evictions(2, 0, reserved_blocks=12,
                                 demote_line_blocks=8.0) == 2
    assert plan_prefix_evictions(0, 0, 12, 8.0) == 0
    assert plan_prefix_evictions(5, 0, 4, 8.0) == 0


# ---------------------------------------------------------------------------
# the pinned-scale release fix (fp8 pages shared by refcount)
# ---------------------------------------------------------------------------
def test_release_skips_pages_pinned_by_prefix_cache():
    kv = BlockedKVCache(KVCacheConfig(
        num_layers=1, num_kv_heads=2, head_dim=4, block_size=4,
        num_blocks=8, dtype=jnp.float8_e4m3fn))
    blocks = kv.reserve(3)
    # grow the shared page's scale (as an outlier write would)
    kv.scales = kv.scales.at[:, :, :, blocks[0]].set(2.5)
    kv.scales = kv.scales.at[:, :, :, blocks[1]].set(3.5)
    free_before = kv.free_blocks
    # one reader releases its whole block list; page blocks[0] is still
    # pinned by the prefix cache (refcount > 0 — another reader)
    kv.release(blocks[:2], pinned=[blocks[0]])
    # the pinned page: NOT freed, scale NOT clobbered
    assert kv.free_blocks == free_before + 1
    assert float(kv.scales[0, 0, 0, blocks[0]]) == 2.5
    # the unpinned page was freed and its scale reset
    assert float(kv.scales[0, 0, 0, blocks[1]]) == 1.0
    # plain release (no pins) keeps the old semantics
    kv.release([blocks[2]])
    assert kv.free_blocks == free_before + 2


# ---------------------------------------------------------------------------
# host-tier page codec
# ---------------------------------------------------------------------------
def test_page_codec_round_trips_within_bound():
    rng = np.random.default_rng(0)
    data = (rng.normal(size=(2, 2, 2, 4, 8, 4)) * 3).astype(np.float32)
    data[0, 0, 0, 1] = 0.0                       # an all-zero page
    for codec, ratio in (("int8", 4), ("fp8", 4)):
        stored, qs = quantize_pages(data, codec)
        assert data.nbytes // stored.nbytes == ratio
        deq = dequantize_pages(stored, qs, codec, np.float32)
        bound = quantize_error_bound(qs, codec)
        assert bound > 0.0
        assert float(np.max(np.abs(deq - data))) <= bound
        # the all-zero page survives exactly (scale clamped to 1.0)
        assert np.all(deq[0, 0, 0, 1] == 0.0)
    # "none" is the identity in both directions
    stored, qs = quantize_pages(data, "none")
    assert stored is data and qs is None
    assert dequantize_pages(stored, qs, "none", np.float32) is data
    with pytest.raises(ValueError):
        quantize_pages(data, "int4")


def test_quantized_demote_promote_tolerance(model_and_params):
    cfg, params = model_and_params
    eng = _engine(cfg, params)
    rng = np.random.default_rng(1)
    prompt = [int(t) for t in rng.integers(1, 99, 40)]
    eng.put([1], [prompt])
    eng.put([2], [prompt[:20] + [7, 8, 9, 11, 12]])   # keeps prefix pinned
    seq = eng.state.get(1)
    before = np.asarray(eng.kv.data[:, :, :, np.asarray(seq.blocks)])
    eng.demote_kv(1, quantize="int8")
    entry = eng.host_kv.get(1)
    assert entry.codec == "int8"
    # the compression headline: stored bytes ~4x under raw (scale arrays
    # cost a little)
    assert entry.raw_nbytes / entry.nbytes > 3.5
    assert eng.host_kv.compression_ratio() > 3.5
    assert eng.promote_kv(1) is not None
    seq = eng.state.get(1)
    after = np.asarray(eng.kv.data[:, :, :, np.asarray(seq.blocks)])
    # the contract is the BOUND (a round-trip may even be exact)
    err = float(np.max(np.abs(after - before)))
    assert err <= quantize_error_bound(entry.qscales, "int8")
    # full-width demotion round-trips bit-identical
    eng.demote_kv(1, quantize="none")
    assert eng.host_kv.get(1).codec == "none"
    eng.promote_kv(1)
    seq = eng.state.get(1)
    again = np.asarray(eng.kv.data[:, :, :, np.asarray(seq.blocks)])
    assert bool((again == after).all())
    # both tiers drain to zero
    eng.flush(1)
    eng.flush(2)
    ledger = eng.kv_ledger()
    assert ledger["host_entries"] == 0 and ledger["host_bytes"] == 0
    assert ledger["device_blocks_reserved"] == 0


# ---------------------------------------------------------------------------
# engine composition: cache hits, conservation, speculative decoding
# ---------------------------------------------------------------------------
def test_prefix_hit_identical_tokens_and_conservation(model_and_params):
    cfg, params = model_and_params
    rng = np.random.default_rng(2)
    prompt = [int(t) for t in rng.integers(1, 99, 40)]
    warm = _engine(cfg, params)
    out1 = warm.generate(prompt, max_new_tokens=6, uid=1)
    out2 = warm.generate(prompt, max_new_tokens=6, uid=2)   # cache hit
    cold = _engine(cfg, params, prefix=False)
    ref = cold.generate(prompt, max_new_tokens=6, uid=1)
    assert out1 == ref and out2 == ref
    st = warm.prefix_stats()
    # 40-token prompt, 16-token blocks -> 2 full blocks reused
    assert st["prefill_tokens_saved"] == 32
    assert st["prefill_tokens_saved"] + st["prefill_tokens_computed"] == \
        st["prefill_tokens_total"]
    assert st["prefix_hit_ratio"] > 0.0
    # flush-time absorption kept the blocks cached, unpinned
    assert st["prefix_cached_blocks"] > 0
    assert st["prefix_pinned_blocks"] == 0


def test_speculative_decoding_composes_with_prefix_hits(model_and_params):
    """bench_decode's speculative_gate contract at tier-1 scale: a
    prefix-cache-hit prompt must produce IDENTICAL tokens to a
    cold-prefill run under speculative decoding (cache hits must not
    desync the draft/verify engines)."""
    cfg, params = model_and_params
    rng = np.random.default_rng(3)
    base = [int(t) for t in rng.integers(1, 99, 24)]
    # repeated n-grams in the prompt + the tiny model's looping argmax
    # chain give prompt-lookup real proposals within 24 decode tokens
    prompt = base + base
    warm = _engine(cfg, params, speculative_k=4)
    out1 = warm.generate(prompt, max_new_tokens=24, uid=1)
    out2 = warm.generate(prompt, max_new_tokens=24, uid=2)   # cache hit
    cold = _engine(cfg, params, prefix=False, speculative_k=4)
    ref = cold.generate(prompt, max_new_tokens=24, uid=1)
    assert out1 == ref and out2 == ref
    assert warm.prefix_stats()["prefill_tokens_saved"] > 0
    # speculation actually ran (the composition is exercised, not idle)
    assert warm.speculative_stats()["steps"] > 0


def test_eviction_order_shared_prefix_outlives_unshared(model_and_params):
    """The demotion-ordering acceptance drill: under pressure, unpinned
    cached pages evict first, unshared live pages demote to the host
    tier, and the pinned shared prefix outlives them all on device —
    when its last reader demotes, it survives via the host entry (never
    discarded)."""
    cfg, params = model_and_params
    eng = _engine(cfg, params)
    rng = np.random.default_rng(4)
    shared = [int(t) for t in rng.integers(1, 99, 40)]
    # A materializes the prefix; B shares it (pins refs to 2)
    eng.put([1], [shared])
    eng.put([2], [shared + [5, 6, 7]])
    shared_blocks = set(eng.state.get(1).blocks[:2])
    assert shared_blocks == set(eng.state.get(2).blocks[:2])
    # C is unshared traffic that finishes: its pages become unpinned cache
    eng.put([3], [[int(t) for t in rng.integers(1, 99, 36)]])
    eng.finish(3)
    unshared_cached = set(eng.state.get(3).blocks)
    eng.reap_finished()
    cache = eng.prefix_cache
    assert cache.evictable_blocks() > 0
    # pressure step 1: cache eviction — only unpinned pages go
    freed = eng.evict_prefix_blocks(100)
    assert freed == cache.stats.evicted_blocks and freed > 0
    assert all(not cache.owns(b) or b in shared_blocks
               for b in unshared_cached)
    assert all(cache.owns(b) for b in shared_blocks)   # prefix survives
    # pressure step 2: demote the unshared reader A — shared pages stay
    # on device (B still reads them), A's entry carries a copy
    eng.demote_kv(1, quantize="int8")
    assert all(cache.owns(b) for b in shared_blocks)
    assert sorted(cache.pinned_block_ids()) == sorted(shared_blocks)
    # B keeps decoding against the shared pages while A is away
    assert 2 in {s.uid for s in eng.state.decoding()}
    out = eng.step()
    assert 2 in out
    # pressure step 3: the LAST reader demotes — the prefix is still not
    # discarded: it stays cached (evictable) AND rides B's host entry
    eng.demote_kv(2, quantize="int8")
    assert all(cache.owns(b) for b in shared_blocks)
    assert cache.pinned_blocks() == 0
    assert eng.host_kv.get(2).codec == "int8"
    # promotion restores both; decode resumes
    assert eng.promote_kv(1) is not None
    assert eng.promote_kv(2) is not None
    out = eng.step()
    assert 1 in out and 2 in out
    ledger = eng.kv_ledger()
    assert ledger["host_entries"] == 0


# ---------------------------------------------------------------------------
# serving config + metrics surface
# ---------------------------------------------------------------------------
def test_serving_config_prefix_keys():
    from deepspeed_tpu.serving import ServingConfig
    cfg = ServingConfig.from_ds_config({"serving": {
        "prefix_cache_enabled": True, "host_kv_quantize": "int8",
        "prefix_cache_max_blocks": 8}})
    assert cfg.prefix_cache_enabled and cfg.host_kv_quantize == "int8"
    assert cfg.prefix_cache_max_blocks == 8

    class _Eng:
        pass

    from deepspeed_tpu.serving import InferenceServer
    with pytest.raises(ValueError, match="host_kv_quantize"):
        InferenceServer(_Eng(), ServingConfig(host_kv_quantize="int4"))


def test_prometheus_prefix_rows_one_type_block_each():
    from deepspeed_tpu.serving.metrics import ServingMetrics
    m = ServingMetrics()
    m.set_prefix_gauges({"prefill_tokens_total": 10,
                         "prefill_tokens_saved": 4,
                         "prefill_tokens_computed": 6,
                         "prefix_hits": 1, "prefix_misses": 2,
                         "prefix_hit_ratio": 0.4,
                         "prefix_cached_blocks": 3,
                         "prefix_pinned_blocks": 1},
                        resident_tokens=5, resident_bytes=50,
                        host_compression=2.0)
    m.on_prefix_evict(2)
    text = m.prometheus_text()
    for family, kind in (
            ("dstpu_serving_prefix_hits", "counter"),
            ("dstpu_serving_prefill_tokens_saved", "counter"),
            ("dstpu_serving_prefix_evictions", "counter"),
            ("dstpu_serving_prefix_cache_hit_ratio", "gauge"),
            ("dstpu_serving_host_kv_compression_ratio", "gauge"),
            ("dstpu_serving_bytes_per_resident_token", "gauge")):
        # exactly ONE TYPE metadata line per family (a duplicate fails
        # the whole Prometheus scrape — PR 8's lesson)
        assert text.count(f"# TYPE {family} {kind}\n") == 1, family
    snap = m.snapshot()
    assert snap["bytes_per_resident_token"] == 10.0
    assert snap["host_kv_compression_ratio"] == 2.0
    # the serve-tick stage-share gauges ride the SAME single
    # dstpu_trace_counter TYPE block as every other counter family (a
    # second metadata block would fail the whole scrape)
    from deepspeed_tpu.telemetry import get_tracer
    tracer = get_tracer()
    was_enabled = tracer.enabled
    tracer.configure(enabled=True)
    try:
        tracer.counter("serve/tick_stage_share", cat="serve",
                       admission=0.01, prefill=0.4, decode=0.3,
                       demote=0.05, promote=0.02, drain=0.02,
                       residual=0.2)
        tracer.counter("serve/kv_bytes", cat="mem",
                       projected=1024, observed=512)
        text = m.prometheus_text()
        assert text.count("# TYPE dstpu_trace_counter gauge\n") == 1
        assert 'counter="serve/tick_stage_share",series="decode"' in text
        assert 'stat="p99"' in text        # counter tracks report tails
    finally:
        tracer.configure(enabled=was_enabled)
        tracer.clear()


def test_env_report_serving_rows(tmp_path, monkeypatch):
    import json

    from deepspeed_tpu.env_report import serving_report
    art = tmp_path / "bench_serve.json"
    art.write_text(json.dumps({
        "scenario": {"name": "multi_turn"},
        "prefix": {"prefix_hit_ratio": 0.82,
                   "prefill_tokens_saved": 3280,
                   "prefill_tokens_total": 3997,
                   "host_compression_ratio": 3.9}}))
    monkeypatch.setenv("DSTPU_SERVE_REPORT", str(art))
    rows = dict(serving_report())
    assert "82" in rows["prefix cache"]
    assert "3.9" in rows["host kv tier"]
    monkeypatch.setenv("DSTPU_SERVE_REPORT", str(tmp_path / "nope.json"))
    rows = dict(serving_report())
    assert "no artifact" in rows["prefix cache"]


def test_warm_idle_cache_is_capacity_not_pressure(model_and_params):
    """An idle server with a warm absorbed-history cache must stay
    HEALTHY: evictable cached blocks are reclaimable capacity, so they
    count neither as ladder pressure (no brownout on an idle replica)
    nor as observed sequence occupancy (no spurious kv_drift
    recalibration of the admission watermark)."""
    import time

    from deepspeed_tpu.serving import InferenceServer, ServeLevel, \
        ServingConfig

    cfg, params = model_and_params
    eng = _engine(cfg, params, kv_blocks=16)
    server = InferenceServer(eng, ServingConfig(
        kv_offload_enabled=True, prefix_cache_enabled=True,
        # thresholds a warm cache WOULD trip if miscounted as pressure
        brownout_pressure=0.3, shed_pressure=0.95, ladder_hysteresis=0.05,
        ladder_cooldown_ticks=2, kv_demote_watermark=0.9,
        idle_poll_s=0.001)).start()
    try:
        rng = np.random.default_rng(7)
        reqs = [server.submit(list(rng.integers(1, 99, 40)),
                              max_new_tokens=3) for _ in range(3)]
        for r in reqs:
            r.result(timeout=120)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and \
                server.health()["inflight"] > 0:
            time.sleep(0.005)
        # flushed sequences were absorbed: the device pool is mostly
        # cache-held, and ALL of it is evictable (no live pins)
        cache = eng.prefix_cache
        assert cache.evictable_blocks() > 0
        frac = eng.kv_reserved_blocks() / eng.kv_usable_blocks()
        assert frac > 0.3        # unfixed, this WOULD read as brownout
        time.sleep(0.1)          # plenty of idle ticks past the cooldown
        # live traffic may legitimately brown out mid-run (pinned pages
        # ARE pressure while readers hold them); the contract here is
        # the idle steady state: the warm cache alone never holds the
        # ladder up...
        assert server.ladder.level is ServeLevel.HEALTHY
        # ...and never recalibrates admission as if it were leaked blocks
        assert server._kv_watermark_scale == 1.0
        assert server.metrics.snapshot()["kv_recalibrations"] == 0
    finally:
        server.stop(drain_timeout=10.0)


# ---------------------------------------------------------------------------
# the tier-1 acceptance gate: bench_serve multi_turn prefix proof
# ---------------------------------------------------------------------------
def test_bench_serve_multi_turn_prefix_proof(model_and_params):
    from deepspeed_tpu.serving import InferenceServer, ServingConfig
    from deepspeed_tpu.serving.bench_serve import SCENARIOS, run_scenario
    from deepspeed_tpu.telemetry.tracer import get_tracer

    cfg, params = model_and_params
    scenario = dc.replace(SCENARIOS["multi_turn"], num_requests=12,
                          concurrency=3)
    get_tracer().configure(enabled=True)
    get_tracer().clear()
    server = InferenceServer(_engine(cfg, params), ServingConfig(
        max_queue_depth=32, kv_offload_enabled=True,
        prefix_cache_enabled=True, host_kv_quantize="int8",
        kv_demote_watermark=0.5, kv_demote_watermark_brownout=0.3,
        idle_poll_s=0.001, retry_after_s=0.01)).start()
    try:
        report = run_scenario(server, scenario)
    finally:
        server.stop(drain_timeout=30.0)
    assert report["requests"]["states"] == {"finished": 48}
    p = report["prefix"]
    # the headline: the cache actually killed redundant prefill
    assert p["prefix_hit_ratio"] > 0.0
    assert p["prefill_tokens_saved"] > 0
    # counter conservation, exactly
    assert p["conservation_ok"] is True
    assert p["prefill_tokens_saved"] + p["prefill_tokens_computed"] == \
        p["prefill_tokens_total"]
    # the cache can never save more than the workload made shareable
    assert p["prefill_tokens_saved"] <= p["expected_reusable_tokens"]
    # proof-set counters mirror engine truth
    c = report["counters"]
    assert c["prefill_tokens_saved"] == p["prefill_tokens_saved"]
    # availability untouched by the cache machinery
    assert c["sticky_503"] == 0 and c["quarantined"] == 0
    # the drained ledger: no sequence holds blocks in either tier (a
    # warm cache legitimately remains)
    ledger = report["kv_ledger"]
    assert ledger["device_blocks_reserved"] == 0
    assert ledger["host_entries"] == 0 and ledger["host_bytes"] == 0
    # any demotion that happened was stored quantized
    if c["demotions"]:
        assert ledger["host_compression_ratio"] > 1.0


def test_shared_prefix_shape_is_deterministic():
    from deepspeed_tpu.serving.bench_serve import SCENARIOS, _request_shape
    sc = SCENARIOS["burst"]
    assert sc.shared_prefix_frac > 0.0
    a = _request_shape(sc, 7)
    b = _request_shape(sc, 7)
    assert a == b                           # pure function of (seed, index)
    p1, _, _, s1 = _request_shape(sc, 1)
    p2, _, _, s2 = _request_shape(sc, 2)
    assert s1 > 0 and s2 > 0
    # the shared run really is shared across indices
    assert p1[:min(s1, s2)] == p2[:min(s1, s2)]
