"""Resilience subsystem tests: the seed-pinned chaos suite.

Every injected fault here is deterministic (``ChaosConfig`` rolls are pure
functions of (seed, kind, step)), so this suite runs in tier-1 by default
(``chaos`` marker) and asserts *exact* recovery behavior:

  - NaN steps    -> engine-level skip, params stay clean, lr backs off,
                    quarantine aborts with a diagnostic bundle
  - ckpt I/O     -> save retries with backoff and commits; torn checkpoints
                    (checksum-mismatched or uncommitted) are NEVER loaded
  - preemption   -> SIGTERM triggers an atomic autosave; resume restores a
                    run whose loss/step/lr/curriculum state matches an
                    uninterrupted baseline bit-for-bit
  - hung steps   -> watchdog flags past-deadline steps and dumps stacks
"""

import json
import os
import signal

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.checkpoint.engine import (CheckpointCorruptionError,
                                             MANIFEST_FILE, is_committed)
from deepspeed_tpu.models.simple import SimpleModel, random_batch
from deepspeed_tpu.resilience import (BadStepError, ChaosConfig, ChaosMonkey,
                                      CheckpointSaveError, FaultTolerantRunner,
                                      QuarantineError, ResilienceConfig,
                                      find_latest_committed, list_tags)

pytestmark = pytest.mark.chaos

CFG = {
    "train_batch_size": 8,
    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
}


def _engine(seed=1, extra=None):
    cfg = dict(CFG)
    if extra:
        cfg.update(extra)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=32), config=cfg,
        example_batch=random_batch(4), seed=seed)
    return engine


def _rc(tmp_path, **kw):
    kw.setdefault("diagnostics_dir", str(tmp_path / "diag"))
    kw.setdefault("autosave", {})
    kw["autosave"].setdefault("io_backoff_s", 0.01)
    return ResilienceConfig(**kw)


def _runner(engine, tmp_path, rc=None, chaos=None, **rc_kw):
    return FaultTolerantRunner(
        engine, save_dir=str(tmp_path / "ckpt"),
        config=rc if rc is not None else _rc(tmp_path, **rc_kw),
        chaos=chaos)


def _params_finite(engine) -> bool:
    return all(bool(np.isfinite(np.asarray(jax.device_get(p))).all())
               for p in jax.tree.leaves(engine.state.params))


def _batch_fn(step):
    return random_batch(8, seed=step)


# ---------------------------------------------------------------------------
# step guards
# ---------------------------------------------------------------------------
def test_nan_step_skipped_params_clean_lr_backoff(tmp_path):
    """An injected NaN batch is detected on-device (overflow path), the
    update is dropped, params stay finite, and the guard backs the lr off."""
    engine = _engine()
    chaos = ChaosMonkey(ChaosConfig(seed=7, nan_steps=frozenset({2})))
    with _runner(engine, tmp_path, chaos=chaos,
                 step_guard={"backoff_after": 1, "quarantine_after": 0},
                 ) as runner:
        base_lr = engine.get_lr()[0]
        result = runner.run(num_steps=5, batch_fn=_batch_fn)
    assert result.stop_reason == "completed"
    assert result.steps_completed == 5
    assert chaos.injected["nan"] == 1
    assert engine.skipped_steps == 1          # the bad update never applied
    assert _params_finite(engine)
    assert np.isfinite(result.last_loss)
    # one bad step at backoff_after=1 -> lr halved, then counter reset
    assert runner.guard.lr_scale == pytest.approx(0.5)
    assert engine.get_lr()[0] == pytest.approx(base_lr * 0.5, rel=1e-6)
    assert runner.guard.consecutive_bad == 0
    assert runner.guard.total_bad == 1


def test_consecutive_nans_quarantine_with_bundle(tmp_path):
    engine = _engine()
    chaos = ChaosMonkey(ChaosConfig(seed=1, nan_prob=1.0))  # every step bad
    runner = _runner(engine, tmp_path, chaos=chaos,
                     step_guard={"backoff_after": 0, "quarantine_after": 3})
    with pytest.raises(QuarantineError) as ei:
        runner.run(num_steps=10, batch_fn=_batch_fn)
    runner.close()
    assert engine.skipped_steps == 3          # every bad step was still skipped
    assert _params_finite(engine)             # quarantined, not poisoned
    bundle = ei.value.bundle_path
    assert bundle and os.path.isdir(bundle)
    with open(os.path.join(bundle, "diag.json")) as f:
        diag = json.load(f)
    assert diag["reason"] == "quarantine"
    assert diag["guard"]["consecutive_bad"] == 3
    assert len(diag["history"]) == 3
    assert os.path.exists(os.path.join(bundle, "stacks.txt"))


def test_abort_policy_raises_on_first_bad_step(tmp_path):
    engine = _engine()
    chaos = ChaosMonkey(ChaosConfig(seed=1, nan_steps=frozenset({1})))
    runner = _runner(engine, tmp_path, chaos=chaos,
                     step_guard={"policy": "abort"})
    with pytest.raises(BadStepError):
        runner.run(num_steps=5, batch_fn=_batch_fn)
    runner.close()
    # the abort bundle exists too
    diags = os.listdir(tmp_path / "diag")
    assert any(d.startswith("abort_step") for d in diags)


# ---------------------------------------------------------------------------
# checkpoint I/O retry + torn-checkpoint protection
# ---------------------------------------------------------------------------
def test_ckpt_io_failure_retried_then_committed(tmp_path):
    engine = _engine()
    chaos = ChaosMonkey(ChaosConfig(seed=2, ckpt_fail_first=2))
    with _runner(engine, tmp_path, chaos=chaos,
                 autosave={"every_steps": 2, "io_retries": 3,
                           "io_backoff_s": 0.01}) as runner:
        runner.run(num_steps=2, batch_fn=_batch_fn)
    assert chaos.injected["ckpt"] == 2        # two injected failures consumed
    ckpt_dir = str(tmp_path / "ckpt")
    tag = find_latest_committed(ckpt_dir)
    assert tag == "global_step2"
    assert is_committed(ckpt_dir, tag)
    assert os.path.exists(os.path.join(ckpt_dir, tag, MANIFEST_FILE))


def test_ckpt_retry_budget_exhausted_raises(tmp_path):
    engine = _engine()
    chaos = ChaosMonkey(ChaosConfig(seed=2, ckpt_fail_first=99))
    runner = _runner(engine, tmp_path, chaos=chaos,
                     autosave={"io_retries": 2, "io_backoff_s": 0.01})
    runner.run(num_steps=1, batch_fn=_batch_fn)
    with pytest.raises(CheckpointSaveError):
        runner.save(reason="manual")
    runner.close()
    assert find_latest_committed(str(tmp_path / "ckpt")) is None


def test_torn_checkpoint_never_loaded_falls_back(tmp_path):
    """Corrupting the newest committed tag (post-commit bit rot / torn
    write) must fail verification and resume from the older clean tag —
    the 'latest' pointer is a hint, not trusted."""
    ckpt_dir = str(tmp_path / "ckpt")
    engine = _engine(seed=1)
    with _runner(engine, tmp_path, autosave={"every_steps": 2}) as runner:
        runner.run(num_steps=4, batch_fn=_batch_fn)
    assert list_tags(ckpt_dir) == ["global_step4", "global_step2"]

    # corrupt a manifest-listed file of the newest tag
    newest = os.path.join(ckpt_dir, "global_step4")
    with open(os.path.join(newest, MANIFEST_FILE)) as f:
        victim = sorted(json.load(f)["files"])[0]
    with open(os.path.join(newest, victim), "r+b") as f:
        f.write(b"\xde\xad\xbe\xef")

    # direct load of the torn tag refuses
    probe = _engine(seed=9)
    with pytest.raises(CheckpointCorruptionError):
        probe.load_checkpoint(ckpt_dir, tag="global_step4")

    # discovery skips it even though 'latest' points at it
    assert (tmp_path / "ckpt" / "latest").read_text() == "global_step4"
    assert find_latest_committed(ckpt_dir) == "global_step2"

    fresh = _engine(seed=5)
    runner2 = _runner(fresh, tmp_path)
    tag = runner2.resume_from_latest()
    runner2.close()
    assert tag == "global_step2"
    assert fresh.global_steps == 2


def test_uncommitted_tag_ignored(tmp_path):
    """A tag dir without a commit (crash mid-save: arrays written, sidecars/
    manifest never landed) is invisible to resume."""
    ckpt_dir = tmp_path / "ckpt"
    engine = _engine()
    with _runner(engine, tmp_path) as runner:
        runner.run(num_steps=1, batch_fn=_batch_fn)
        runner.save(reason="manual")
    # fabricate a newer, uncommitted tag (no ds_meta.json / manifest)
    (ckpt_dir / "global_step99").mkdir()
    (ckpt_dir / "global_step99" / "junk.bin").write_bytes(b"x" * 16)
    assert find_latest_committed(str(ckpt_dir)) == "global_step1"


def test_autosave_cadence_and_prune(tmp_path):
    engine = _engine()
    with _runner(engine, tmp_path,
                 autosave={"every_steps": 1, "keep_last": 2}) as runner:
        runner.run(num_steps=5, batch_fn=_batch_fn)
    ckpt_dir = str(tmp_path / "ckpt")
    tags = list_tags(ckpt_dir)
    assert tags == ["global_step5", "global_step4"]   # pruned to keep_last
    assert find_latest_committed(ckpt_dir) == "global_step5"


# ---------------------------------------------------------------------------
# preemption: SIGTERM -> autosave -> resume parity (save→kill→resume)
# ---------------------------------------------------------------------------
CURRICULUM_CFG = {
    "curriculum_learning": {
        "enabled": True, "curriculum_type": "seqlen",
        "min_difficulty": 2, "max_difficulty": 8,
        "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 8, "difficulty_step": 2},
    },
    "scheduler": {"type": "WarmupDecayLR",
                  "params": {"warmup_num_steps": 2, "total_num_steps": 12,
                             "warmup_max_lr": 1e-2}},
}


def _trajectory(engine, start, stop):
    """Per-step (loss, lr) + final (step, seqlen) fingerprints."""
    out = []
    for step in range(start, stop):
        loss = float(engine.train_batch(batch=_batch_fn(step)))
        out.append((loss, engine.get_lr()[0]))
    return out


def test_sigterm_autosave_then_resume_matches_uninterrupted(tmp_path):
    """The acceptance scenario: a SIGTERM mid-run commits an autosave; the
    relaunched run restores engine + lr-schedule + curriculum state and its
    loss trajectory matches an uninterrupted baseline step for step."""
    total = 6
    # --- baseline: uninterrupted ---------------------------------------
    base = _engine(seed=1, extra=CURRICULUM_CFG)
    base_traj = _trajectory(base, 0, total)
    base_seqlen = base.curriculum_seqlen()

    # --- interrupted: SIGTERM arrives during step 3 --------------------
    victim = _engine(seed=1, extra=CURRICULUM_CFG)
    runner = _runner(victim, tmp_path)
    fired = []

    def preempting_batches(step):
        if step == 3 and not fired:
            fired.append(step)
            os.kill(os.getpid(), signal.SIGTERM)   # delivered this step
        return _batch_fn(step)

    result = runner.run(num_steps=total, batch_fn=preempting_batches)
    runner.close()
    assert result.stop_reason == "preempted"
    assert result.steps_completed == 4            # step 3 completed, then stop
    saved = find_latest_committed(str(tmp_path / "ckpt"))
    assert saved == "global_step4"

    # --- relaunch: fresh process state, different init seed ------------
    resumed = _engine(seed=42, extra=CURRICULUM_CFG)
    runner2 = _runner(resumed, tmp_path)
    tag = runner2.resume_from_latest()
    assert tag == "global_step4"
    assert resumed.global_steps == 4
    assert int(jax.device_get(resumed.state.step)) == 4
    # lr schedule position restored exactly
    assert resumed.get_lr()[0] == pytest.approx(
        victim.get_lr()[0], rel=1e-7)
    # curriculum/data-schedule state restored exactly
    assert resumed.curriculum_seqlen() == victim.curriculum_seqlen()

    resumed_traj = _trajectory(resumed, 4, total)
    runner2.close()
    # post-resume trajectory identical to the uninterrupted baseline
    for (bl, blr), (rl, rlr) in zip(base_traj[4:], resumed_traj):
        assert abs(bl - rl) < 1e-6
        assert rlr == pytest.approx(blr, rel=1e-7)
    assert resumed.global_steps == total
    assert resumed.curriculum_seqlen() == base_seqlen


def test_guard_state_survives_resume(tmp_path):
    """lr backoff must not reset on restart — a crash-loop would otherwise
    retry at the lr that was melting the run."""
    engine = _engine()
    chaos = ChaosMonkey(ChaosConfig(seed=7, nan_steps=frozenset({1})))
    with _runner(engine, tmp_path, chaos=chaos,
                 step_guard={"backoff_after": 1, "quarantine_after": 0},
                 ) as runner:
        runner.run(num_steps=3, batch_fn=_batch_fn)
        assert runner.guard.lr_scale == pytest.approx(0.5)
        runner.save(reason="manual")

    fresh = _engine(seed=3)
    runner2 = _runner(fresh, tmp_path)
    runner2.resume_from_latest()
    base_lr = 1e-2
    assert runner2.guard.lr_scale == pytest.approx(0.5)
    assert fresh.get_lr()[0] == pytest.approx(base_lr * 0.5, rel=1e-6)
    runner2.close()


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------
def test_watchdog_flags_hung_step_with_snapshot(tmp_path):
    engine = _engine()
    # warm the compile cache so only the chaos stall (not XLA tracing) can
    # cross the tight test deadline; guard off so nothing re-traces mid-run
    engine.train_batch(batch=_batch_fn(0))
    chaos = ChaosMonkey(ChaosConfig(seed=5, slow_steps=frozenset({2}),
                                    slow_s=0.6))
    with _runner(engine, tmp_path, chaos=chaos,
                 step_guard={"enabled": False},
                 watchdog={"enabled": True, "step_deadline_s": 0.2,
                           "poll_s": 0.05}) as runner:
        runner.run(num_steps=3, batch_fn=_batch_fn)
        events = list(runner.watchdog.events)
    assert chaos.injected["slow"] == 1
    assert len(events) == 1
    assert events[0].step == 2
    assert events[0].elapsed_s >= 0.2
    snap = events[0].snapshot_path
    assert snap and os.path.isdir(snap)
    with open(os.path.join(snap, "context.json")) as f:
        ctx = json.load(f)
    assert ctx["step"] == 2
    assert "history_tail" in ctx
    stacks = open(os.path.join(snap, "stacks.txt")).read()
    assert "Thread" in stacks or "Current thread" in stacks


# ---------------------------------------------------------------------------
# config plumbing
# ---------------------------------------------------------------------------
def test_resilience_config_via_engine_json(tmp_path):
    """The "resilience" config group arms the engine guard and drives the
    runner without a separate config object."""
    engine = _engine(extra={"resilience": {
        "step_guard": {"backoff_after": 1},
        "autosave": {"every_steps": 2, "io_backoff_s": 0.01},
        "diagnostics_dir": str(tmp_path / "diag"),
    }})
    assert engine._guard_nonfinite                 # armed at init
    chaos = ChaosMonkey(ChaosConfig(seed=11, nan_steps=frozenset({0})))
    runner = FaultTolerantRunner(engine, save_dir=str(tmp_path / "ckpt"),
                                 chaos=chaos)     # config resolved from engine
    result = runner.run(num_steps=3, batch_fn=_batch_fn)
    runner.close()
    assert result.steps_completed == 3
    assert engine.skipped_steps == 1
    assert runner.cfg.autosave.every_steps == 2
    assert find_latest_committed(str(tmp_path / "ckpt")) is not None


def test_resilience_monitor_events(tmp_path):
    """Bad steps and saves fan resilience gauges out through the engine's
    monitor (skipped steps, lr scale, checkpoints saved)."""
    engine = _engine(extra={"csv_monitor": {"enabled": True,
                                            "output_path": str(tmp_path / "mon"),
                                            "job_name": "res"}})
    chaos = ChaosMonkey(ChaosConfig(seed=7, nan_steps=frozenset({1})))
    with _runner(engine, tmp_path, chaos=chaos,
                 step_guard={"backoff_after": 1, "quarantine_after": 0},
                 ) as runner:
        runner.run(num_steps=3, batch_fn=_batch_fn)
        runner.save(reason="manual")
    names = {p.stem for p in (tmp_path / "mon" / "res").glob("*.csv")}
    assert "Train_Resilience_skipped_steps" in names
    assert "Train_Resilience_lr_scale" in names
    assert "Train_Resilience_checkpoints_saved" in names


def test_lr_backoff_scales_the_actual_update(tmp_path):
    """Backoff must reach the REAL optimizer update (regression: the lr
    schedule is baked into the optax chain at engine construction, so
    rescaling only the reported schedule would silently keep training at
    full rate). First-step Adam updates scale ~linearly with lr."""
    a = _engine(seed=1)
    b = _engine(seed=1)
    p0 = [np.asarray(x) for x in jax.tree.leaves(jax.device_get(a.state.params))]
    ra = _runner(a, tmp_path)
    rb = _runner(b, tmp_path)
    rb.guard._set_lr_scale(0.5)
    batch = _batch_fn(0)
    ra.step(batch=batch)
    rb.step(batch=batch)
    ra.close()
    rb.close()

    def delta(engine):
        now = [np.asarray(x) for x in
               jax.tree.leaves(jax.device_get(engine.state.params))]
        return np.sqrt(sum(float(np.sum((n - o) ** 2))
                           for n, o in zip(now, p0)))

    da, db = delta(a), delta(b)
    assert da > 0
    assert db == pytest.approx(da * 0.5, rel=0.05)


def test_close_disarms_guard_unless_config_armed(tmp_path):
    """Runner close restores default bf16/fp32 NaN semantics — unless the
    engine's own config armed the guard explicitly."""
    engine = _engine()
    runner = _runner(engine, tmp_path)
    assert engine._guard_nonfinite
    runner.close()
    assert not engine._guard_nonfinite

    armed = _engine(extra={"resilience": {}})
    assert armed._guard_nonfinite
    runner2 = FaultTolerantRunner(armed, save_dir=str(tmp_path / "ckpt2"))
    runner2.close()
    assert armed._guard_nonfinite          # config-armed: stays armed


def test_chaos_die_once_spares_resumed_worker(monkeypatch):
    """A relaunched worker (DSTPU_RESUME set by the agent) is spared by
    die_once, so kill->restart->resume completes instead of crash-looping."""
    died = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: died.append(sig))
    m = ChaosMonkey(ChaosConfig(die_step=3))
    monkeypatch.delenv("DSTPU_RESUME", raising=False)
    m.maybe_die(2)
    assert not died
    m.maybe_die(3)
    assert len(died) == 1                  # first life: killed
    monkeypatch.setenv("DSTPU_RESUME", "latest")
    m.maybe_die(3)
    m.maybe_die(10)
    assert len(died) == 1                  # relaunched life: spared


def test_fp16_scaler_overflows_not_counted_as_bad_steps(tmp_path):
    """Routine fp16 loss-scale-search overflows (finite loss/grad-norm,
    overflow flag set) belong to the dynamic scaler, not the guard — they
    must not drive lr backoff or quarantine on a healthy run."""
    fp16_engine = _engine(extra={"fp16": {"enabled": True,
                                          "initial_scale_power": 6}})
    runner = _runner(fp16_engine, tmp_path,
                     step_guard={"backoff_after": 1, "quarantine_after": 2})
    # overflow-only: the scaler's domain
    assert runner.guard.observe(2.0, {"grad_norm": 1.0, "overflow": True}) \
        is False
    assert runner.guard.consecutive_bad == 0
    assert runner.guard.lr_scale == 1.0
    # a genuinely non-finite loss still counts, fp16 or not
    assert runner.guard.observe(float("nan"),
                                {"grad_norm": 1.0, "overflow": True}) is True
    assert runner.guard.consecutive_bad == 1
    runner.close()

    fp32_engine = _engine()
    runner32 = _runner(fp32_engine, tmp_path,
                       step_guard={"backoff_after": 0, "quarantine_after": 0})
    # without a scaler, overflow means non-finite grads -> bad
    assert runner32.guard.observe(2.0, {"grad_norm": 1.0, "overflow": True}) \
        is True
    runner32.close()


def test_keyboard_interrupt_in_batch_fn_gets_preemption_contract(tmp_path):
    """A KeyboardInterrupt landing OUTSIDE step() (in batch_fn / the loop
    head) still yields the preemption contract: autosave + RunResult, never
    an uncaught escape from run()."""
    engine = _engine()
    runner = _runner(engine, tmp_path)

    def interrupting_batches(step):
        if step == 2:
            raise KeyboardInterrupt
        return _batch_fn(step)

    result = runner.run(num_steps=5, batch_fn=interrupting_batches)
    runner.close()
    assert result.stop_reason == "preempted"
    assert result.steps_completed == 2
    assert find_latest_committed(str(tmp_path / "ckpt")) == "global_step2"


def test_maybe_resume_honors_relaunch_marker(tmp_path, monkeypatch):
    """maybe_resume(): the worker-side half of the agent's DSTPU_RESUME
    contract — fresh launches start clean, relaunches resume."""
    engine = _engine()
    with _runner(engine, tmp_path) as runner:
        runner.run(num_steps=2, batch_fn=_batch_fn)
        runner.save(reason="manual")

    fresh = _engine(seed=9)
    runner2 = _runner(fresh, tmp_path)
    monkeypatch.delenv("DSTPU_RESUME", raising=False)
    assert runner2.maybe_resume() is None
    assert fresh.global_steps == 0
    monkeypatch.setenv("DSTPU_RESUME", "latest")
    assert runner2.maybe_resume() == "global_step2"
    assert fresh.global_steps == 2
    runner2.close()


def test_resume_falls_back_past_tag_torn_before_manifest(tmp_path):
    """A tag torn BEFORE its manifest landed (crash mid-sidecar-write: has
    ds_meta.json, no manifest, no arrays) fails load with a non-corruption
    error — resume must still fall back to the older clean commit."""
    ckpt_dir = tmp_path / "ckpt"
    engine = _engine()
    with _runner(engine, tmp_path) as runner:
        runner.run(num_steps=1, batch_fn=_batch_fn)
        runner.save(reason="manual")
    # fabricate a newer half-written tag: committed-looking marker, no
    # manifest, no orbax payload
    half = ckpt_dir / "global_step7"
    half.mkdir()
    (half / "ds_meta.json").write_text('{"global_steps": 7}')

    fresh = _engine(seed=4)
    runner2 = _runner(fresh, tmp_path)
    tag = runner2.resume_from_latest()
    runner2.close()
    assert tag == "global_step1"
    assert fresh.global_steps == 1
