"""Block-sparse attention tests.

Reference analog: tests/unit/ops/sparse_attention/test_sparse_attention.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, LocalSlidingWindowSparsityConfig,
    SparseSelfAttention, VariableSparsityConfig, block_sparse_attention,
    pallas_block_sparse_attention, sparse_attention_reference)


def _qkv(b=2, s=64, h=4, d=16, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
                 for _ in range(3))


# ------------------------------------------------------------- layouts
def test_fixed_layout_pattern():
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2,
                              num_global_blocks=1, attention="unidirectional")
    lay = cfg.make_layout(128)         # 8x8 blocks
    assert lay.shape == (2, 8, 8)
    assert (lay == np.tril(lay)).all()                   # causal at block level
    assert lay[0, 1, 0] == 1 and lay[0, 1, 1] == 1       # local window
    assert lay[0, 2, 0] == 0                             # outside window...
    assert lay[0, 7, 1] == 1                             # ...except global col
    assert (lay[0] == lay[1]).all()                      # propagated head 0


def test_bigbird_layout_connectivity():
    cfg = BigBirdSparsityConfig(num_heads=2, block=16, num_random_blocks=1,
                                num_sliding_window_blocks=3, num_global_blocks=1)
    lay = cfg.make_layout(128)
    assert (lay[0, 0, :] == 1).all() and (lay[0, :, 0] == 1).all()  # ITC global
    for r in range(1, 7):
        assert lay[0, r, r - 1:r + 2].all()              # sliding diag
    assert lay.sum() < 2 * 8 * 8                         # actually sparse


def test_bslongformer_layout():
    cfg = BSLongformerSparsityConfig(num_heads=1, block=16,
                                     num_sliding_window_blocks=3,
                                     global_block_indices=[2])
    lay = cfg.make_layout(128)
    assert (lay[0, 2, :] == 1).all() and (lay[0, :, 2] == 1).all()
    assert lay[0, 7, 0] == 0


def test_dense_and_local_window_layouts():
    assert DenseSparsityConfig(num_heads=1, block=16).make_layout(64).all()
    lay = LocalSlidingWindowSparsityConfig(
        num_heads=1, block=16, num_sliding_window_blocks=3).make_layout(128)
    assert (lay == np.tril(lay)).all()
    assert lay[0, 5, 4] == 1 and lay[0, 5, 1] == 0


def test_variable_layout_random_seeded():
    cfg = VariableSparsityConfig(num_heads=1, block=16, num_random_blocks=2,
                                 seed=3)
    a = cfg.make_layout(256)
    b = VariableSparsityConfig(num_heads=1, block=16, num_random_blocks=2,
                               seed=3).make_layout(256)
    np.testing.assert_array_equal(a, b)


# ------------------------------------------------------------- compute
@pytest.mark.parametrize("causal", [False, True])
def test_block_sparse_matches_reference(causal):
    q, k, v = _qkv()
    cfg = BigBirdSparsityConfig(num_heads=4, block=16,
                                different_layout_per_head=True, seed=1)
    lay = cfg.make_layout(64)
    out = block_sparse_attention(q, k, v, lay, 16, causal=causal)
    ref = sparse_attention_reference(q, k, v, lay, 16, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_sparse_matches_reference(causal):
    q, k, v = _qkv()
    cfg = FixedSparsityConfig(num_heads=4, block=16, num_local_blocks=2,
                              attention="bidirectional")
    lay = cfg.make_layout(64)
    out = pallas_block_sparse_attention(q, k, v, lay, 16, causal, True)
    ref = sparse_attention_reference(q, k, v, lay, 16, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


def test_sparse_attention_grads():
    q, k, v = _qkv(s=32)
    lay = BSLongformerSparsityConfig(
        num_heads=4, block=8, num_sliding_window_blocks=3).make_layout(32)

    def loss_s(q, k, v):
        return jnp.sum(block_sparse_attention(q, k, v, lay, 8) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(sparse_attention_reference(q, k, v, lay, 8) ** 2)

    gs = jax.grad(loss_s, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gs, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4,
                                   rtol=1e-4)


def test_pallas_sparse_grad_via_recompute():
    q, k, v = _qkv(s=32)
    lay = FixedSparsityConfig(num_heads=4, block=8,
                              num_local_blocks=2).make_layout(32)

    def loss_p(q, k, v):
        return jnp.sum(pallas_block_sparse_attention(q, k, v, lay, 8, False,
                                                     True) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(sparse_attention_reference(q, k, v, lay, 8) ** 2)

    gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4,
                                   rtol=1e-4)


def test_sparse_self_attention_entry_point():
    q, k, v = _qkv(s=64)
    sa = SparseSelfAttention(LocalSlidingWindowSparsityConfig(
        num_heads=4, block=16, num_sliding_window_blocks=3))
    assert sa.causal                      # unidirectional config -> causal
    out = sa(q, k, v)
    ref = sparse_attention_reference(
        q, k, v, sa.layout(64), 16, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)
    assert 64 in sa._layouts              # layout cached
