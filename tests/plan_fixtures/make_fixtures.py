"""Regenerate the checked-in dstrace fixtures for tests/test_plan.py.

Run from the repo root (CPU is fine — the fixtures are frozen so the
golden attribution assertions stay deterministic across hosts):

    JAX_PLATFORMS=cpu python tests/plan_fixtures/make_fixtures.py

Two fixtures, both from the same SimpleModel micro workload:

  micro_sync_trace.json   async pipeline OFF — per-step readback, dispatch
                          dominates; `dstpu plan` must propose enabling the
                          async pipeline (the sync_every proposal the
                          Autotuner acceptance drill verifies). No
                          checkpoint here: on this model a save is ~50x a
                          step and would drown every other stage — ckpt
                          attribution is pinned by the synthetic-trace
                          golden test instead
  micro_async_trace.json  async pipeline ON (sync_every=4) with a mid-run
                          checkpoint — reconciled windows, drain spans, and
                          ckpt I/O for the full-ledger golden test

Also regenerates the repo-root ``plan_baseline.json`` from the async
fixture's attribution — fixtures and baseline are one artifact set and
must move together (the golden test pins their agreement byte-for-byte).

The regression-variant traces used by the exit-code matrix are derived
in-test (drain/dispatch durations scaled up) — never checked in.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, REPO)
HERE = os.path.dirname(os.path.abspath(__file__))


def _fresh_engine(extra=None):
    import deepspeed_tpu
    from deepspeed_tpu.models.simple import SimpleModel, random_batch
    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "steps_per_print": 4}
    if extra:
        cfg.update(extra)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=32), config=cfg,
        example_batch=random_batch(4), seed=7)
    return engine


def _batches(n):
    from deepspeed_tpu.models.simple import random_batch
    return iter([random_batch(8, seed=i) for i in range(n)])


def main():
    from deepspeed_tpu.telemetry import get_tracer
    tracer = get_tracer()

    # --- sync-mode fixture -------------------------------------------------
    import tempfile
    engine = _fresh_engine()
    warm = _batches(1)
    engine.train_batch(data_iter=warm)          # compile outside the trace
    tracer.clear()
    tracer.configure(enabled=True)
    it = _batches(8)
    for _ in range(8):
        engine.train_batch(data_iter=it)
    tracer.configure(enabled=False)
    path = os.path.join(HERE, "micro_sync_trace.json")
    with open(path, "w") as f:
        json.dump(tracer.to_chrome(), f, default=str)
    print(f"wrote {path} ({len(tracer.events_snapshot())} events)")

    # --- async-mode fixture ------------------------------------------------
    engine = _fresh_engine(extra={
        "async_pipeline": {"enabled": True, "sync_every": 4}})
    warm = _batches(1)
    engine.train_batch(data_iter=warm)
    engine.flush_metrics()
    tracer.clear()
    tracer.configure(enabled=True)
    it = _batches(12)
    for step in range(12):
        engine.train_batch(data_iter=it)
        if step == 7:
            with tempfile.TemporaryDirectory() as d:
                engine.save_checkpoint(d, tag="fixture")
    engine.flush_metrics()
    tracer.configure(enabled=False)
    path = os.path.join(HERE, "micro_async_trace.json")
    with open(path, "w") as f:
        json.dump(tracer.to_chrome(), f, default=str)
    print(f"wrote {path} ({len(tracer.events_snapshot())} events)")
    tracer.clear()

    # --- regression baseline (ratchet anchor for the async fixture) --------
    from deepspeed_tpu.telemetry import attribution
    report = attribution.analyze_path(path)
    bl = os.path.join(REPO, attribution.PLAN_BASELINE_NAME)
    attribution.write_plan_baseline(bl, report)
    print(f"wrote {bl}")


if __name__ == "__main__":
    main()
