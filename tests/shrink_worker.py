"""Elastic shrink-drill worker: FaultTolerantRunner + heartbeat membership.

Spawned by ``ElasticAgent`` in the shrink acceptance drill
(test_elastic_shrink.py::test_shrink_drill_end_to_end). Every generation
trains the SAME deterministic step-keyed global batches under a comm_guard
membership view; chaos (``DSTPU_CHAOS_PEER_DEAD_PERMANENT_RANKS``) silences
one rank's heartbeat forever, so the survivors classify it lost, autosave,
and exit 75 — the agent then shrinks the next generation. Per-step losses
land in ``losses_gen{G}_rank{R}.jsonl``; the dstrace timeline (with the
``elastic/`` instants) is dumped per generation/rank.

Env contract: the agent's rendezvous vars plus ``DSTPU_SW_DIR`` (workdir:
ckpt/ + members/ + loss logs), ``DSTPU_SW_TOTAL_STEPS``,
``DSTPU_SW_LOST_AFTER_S`` (membership staleness horizon, default 0.6), and
the generation-0 capacity-loss injection ``DSTPU_SW_KILL_RANK`` /
``DSTPU_SW_KILL_STEP`` (SIGKILL that rank right after that step's autosave
commits — permanent: a relaunch of the same rank dies again, forcing the
shrink instead of a same-world retry loop). A standalone baseline run (no
agent) passes ``DSTPU_SW_BASELINE=1`` with ``DSTPU_RESUME=latest`` to
replay the post-shrink trajectory directly.
"""

import json
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# device count must be pinned BEFORE jax import (the agent's env inherits the
# test harness's 8-device XLA_FLAGS; this worker wants its own small world)
_n_dev = int(os.environ.get("DSTPU_SW_LOCAL_DEVICES", "1"))
os.environ["XLA_FLAGS"] = " ".join(
    [f for f in os.environ.get("XLA_FLAGS", "").split()
     if "xla_force_host_platform_device_count" not in f]
    + [f"--xla_force_host_platform_device_count={_n_dev}"])
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

nproc = int(os.environ.get("DSTPU_NUM_PROCESSES", "1"))
rank = int(os.environ.get("DSTPU_PROCESS_ID", "0"))
if nproc > 1:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")


def main():
    import deepspeed_tpu
    from deepspeed_tpu.models.simple import SimpleModel, random_batch
    from deepspeed_tpu.resilience import FaultTolerantRunner, ResilienceConfig
    from deepspeed_tpu.telemetry import get_tracer

    workdir = os.environ["DSTPU_SW_DIR"]
    total_steps = int(os.environ["DSTPU_SW_TOTAL_STEPS"])
    gen = int(os.environ.get("DSTPU_ELASTIC_RESTART", "0"))
    batch = int(os.environ.get("DSTPU_ELASTIC_BATCH", "8"))
    lost_after_s = float(os.environ.get("DSTPU_SW_LOST_AFTER_S", "0.6"))
    baseline = os.environ.get("DSTPU_SW_BASELINE")
    label = "base" if baseline else f"gen{gen}"

    tracer = get_tracer()
    tracer.configure(enabled=True)

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=32),
        config={"train_batch_size": batch,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "comm_guard": {
                    "heartbeat_interval_s": 0.05,
                    "lost_after_s": lost_after_s,
                    "membership_dir": os.path.join(workdir, "members"),
                }},
        example_batch=random_batch(2))

    runner = FaultTolerantRunner(
        engine, save_dir=os.path.join(workdir, "ckpt"),
        config=ResilienceConfig(
            diagnostics_dir=os.path.join(workdir, "diag"),
            # every-step autosave: at world > 1 a post-peer-loss save is a
            # collective that can never commit, so the periodic cadence IS
            # the resume point the shrunk generation restores
            autosave={"every_steps": 1, "io_backoff_s": 0.01}))
    runner.maybe_resume()
    start = engine.global_steps

    local = batch // nproc
    kill_rank = int(os.environ.get("DSTPU_SW_KILL_RANK", "-1"))
    kill_step = int(os.environ.get("DSTPU_SW_KILL_STEP", "-1"))
    log = os.path.join(workdir, f"losses_{label}_rank{rank}.jsonl")
    logged = set()

    def flush_losses():
        # incremental: a survivor wedged in a dead-peer collective gets
        # SIGKILLed by the agent and never returns from run() — every
        # completed step's loss must already be on disk by then
        with open(log, "a") as f:
            for h in runner.history:
                if h.get("loss") is not None and h["step"] not in logged:
                    logged.add(h["step"])
                    f.write(json.dumps({"step": h["step"], "loss": h["loss"],
                                        "world": nproc}) + "\n")

    def batch_fn(step):
        flush_losses()
        # permanent capacity loss: SIGKILL fires at the top of step K+1,
        # i.e. right after step K's autosave committed — and fires AGAIN
        # on any same-world relaunch (step >= kill_step after resume), so
        # only a shrink makes progress
        if rank == kill_rank and 0 <= kill_step <= step and not baseline:
            os.kill(os.getpid(), signal.SIGKILL)
        # deterministic per-step GLOBAL batch sliced to this process's
        # shard: the assembled batch is identical at every world size, so
        # loss trajectories are comparable (and, from the same checkpoint,
        # bit-identical) across generations
        full = random_batch(batch, seed=step)
        return {k: v[rank * local:(rank + 1) * local]
                for k, v in full.items()}

    result = runner.run(num_steps=total_steps - start, batch_fn=batch_fn)
    runner.close()
    flush_losses()
    try:
        tracer.export_chrome(
            os.path.join(workdir, f"trace_{label}_rank{rank}.json"))
    except Exception:
        pass
    sys.exit(result.exit_code)


if __name__ == "__main__":
    main()
