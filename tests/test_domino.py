"""Domino TP compute/comm overlap tests.

Reference analog: ``tests/unit/runtime`` Domino coverage is indirect in the
reference; here we assert the TPU redesign's correctness contract directly —
chunking must not change the math, only expose independent per-chunk psums to
the scheduler (``deepspeed/runtime/domino/transformer.py:338-430``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.comm.mesh import create_mesh, set_global_mesh
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.runtime.domino import (
    DominoTransformerLayer, chunk_tokens, domino_overlap)


def _layer(n_chunks):
    return DominoTransformerLayer(num_heads=4, head_dim=8, intermediate=64,
                                  n_chunks=n_chunks, dtype=jnp.float32)


@pytest.mark.slow
def test_chunking_is_exact():
    x = np.random.default_rng(0).normal(size=(4, 8, 32)).astype(np.float32)
    params = _layer(1).init(jax.random.PRNGKey(0), x)["params"]
    base = _layer(1).apply({"params": params}, x)
    # params are chunk-count independent: same weights, chunked execution
    for n in (2, 4):
        out = _layer(n).apply({"params": params}, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(base),
                                   rtol=1e-5, atol=1e-5)


def _tp_setup():
    """data=4 x tensor=2 mesh with AutoTP-sharded layer params; caller must
    clear the global mesh (use try/finally) so later tests don't inherit it."""
    from deepspeed_tpu.module_inject import AutoTP
    from deepspeed_tpu.runtime.zero.partition import build_param_shardings
    mesh = create_mesh(MeshConfig(data=4, tensor=2))
    set_global_mesh(mesh)
    x = np.random.default_rng(1).normal(size=(4, 8, 32)).astype(np.float32)
    params = _layer(2).init(jax.random.PRNGKey(1), x)["params"]
    rules = AutoTP.infer_rules(params=params)
    shardings = build_param_shardings(params, mesh, stage=0,
                                      tensor_rules=rules)
    return mesh, x, params, jax.device_put(params, shardings)


@pytest.mark.slow
def test_domino_under_tp_mesh_matches_dense():
    try:
        mesh, x, params, sharded = _tp_setup()
        dense = _layer(1).apply({"params": params}, x)
        with mesh:
            out = jax.jit(
                lambda p, b: _layer(2).apply({"params": p}, b))(sharded, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                                   rtol=2e-4, atol=2e-4)
    finally:
        set_global_mesh(None)


@pytest.mark.slow
def test_domino_grads_match_unchunked():
    x = np.random.default_rng(2).normal(size=(4, 8, 32)).astype(np.float32)
    params = _layer(1).init(jax.random.PRNGKey(2), x)["params"]

    def loss(p, n):
        return jnp.sum(_layer(n).apply({"params": p}, x) ** 2)

    g1 = jax.grad(lambda p: loss(p, 1))(params)
    g2 = jax.grad(lambda p: loss(p, 2))(params)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4), g1, g2)


def test_domino_overlap_wrapper_and_chunk_errors():
    fn = lambda x: x * 2.0
    x = jnp.arange(8.0).reshape(4, 2)
    np.testing.assert_allclose(np.asarray(domino_overlap(fn, 2)(x)),
                               np.asarray(fn(x)))
    try:
        chunk_tokens(x, 3)
        raise AssertionError("expected ValueError")
    except ValueError:
        pass


@pytest.mark.slow
def test_domino_chunking_multiplies_schedulable_collectives():
    """The overlap claim's structural half, checkable without hardware: the
    n-chunk layer's lowered module carries n independent per-chunk
    all-reduces per row-projection (each data-independent of later chunks'
    matmuls — what XLA's latency-hiding scheduler needs), where the
    unchunked layer has exactly one."""
    try:
        mesh, x, _, sharded = _tp_setup()

        def count_allreduce(n_chunks):
            with mesh:
                txt = jax.jit(
                    lambda p, b: _layer(n_chunks).apply({"params": p}, b)
                ).lower(sharded, x).compile().as_text()
            return sum(1 for ln in txt.splitlines()
                       if "all-reduce" in ln and "f32" in ln and "= f32" in ln)

        one = count_allreduce(1)
        four = count_allreduce(4)
        assert one >= 2, one               # attn + mlp row projections
        # each of the 2 row projections must contribute one DISTINCT psum
        # per extra chunk (no CSE back into one collective): +2*(n-1) at n=4
        assert four - one >= 2 * 3, (one, four)
    finally:
        set_global_mesh(None)
