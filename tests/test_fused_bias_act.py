"""Fused bias+activation(+dropout) kernel tests.

Reference analog: ``tests/unit/ops/transformer`` gelu/dropout kernel cases —
each native op validated against a framework reference on random tensors.
Kernels run in interpret mode on CPU (real lowering exercised on TPU).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.pallas.fused_bias_act import (
    fused_bias_act, fused_bias_act_dropout)


@pytest.mark.parametrize("act", ["gelu", "relu", "silu"])
def test_bias_act_matches_jnp(act):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 8, 32)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(32,)).astype(np.float32))
    got = fused_bias_act(x, b, act, block_rows=8, interpret=True)
    want = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
            "silu": jax.nn.silu}[act](x + b)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_bias_act_grads_match_jnp():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(16, 24)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(24,)).astype(np.float32))

    def f_kernel(x, b):
        return jnp.sum(fused_bias_act(x, b, "gelu", 8, True) ** 2)

    def f_ref(x, b):
        return jnp.sum(jax.nn.gelu(x + b) ** 2)

    gx, gb = jax.grad(f_kernel, argnums=(0, 1))(x, b)
    rx, rb = jax.grad(f_ref, argnums=(0, 1))(x, b)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(rb), rtol=1e-4,
                               atol=1e-5)


def test_dropout_deterministic_and_statistical():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(64, 128)).astype(np.float32))
    b = jnp.zeros((128,), jnp.float32)
    a = fused_bias_act_dropout(x, b, 7, "identity", 0.25, 16, True)
    a2 = fused_bias_act_dropout(x, b, 7, "identity", 0.25, 16, True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(a2))  # same seed
    a3 = fused_bias_act_dropout(x, b, 8, "identity", 0.25, 16, True)
    assert not np.array_equal(np.asarray(a), np.asarray(a3))      # new seed
    drop_frac = float(np.mean(np.asarray(a) == 0.0))
    assert 0.18 < drop_frac < 0.33
    kept = np.asarray(a) != 0.0
    np.testing.assert_allclose(np.asarray(a)[kept],
                               (np.asarray(x) / 0.75)[kept], rtol=1e-5)


def test_dropout_backward_regenerates_identical_mask():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(32, 64)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(64,)).astype(np.float32))
    g = jnp.asarray(rng.normal(size=(32, 64)).astype(np.float32))

    out, vjp = jax.vjp(
        lambda x, b: fused_bias_act_dropout(x, b, 11, "gelu", 0.3, 8, True),
        x, b)
    dx, db = vjp(g)
    dropped = np.asarray(out) == 0.0
    # dropped positions contribute no gradient; kept positions match analytic
    assert np.all(np.asarray(dx)[dropped] == 0.0)
    act_grad = np.asarray(jax.grad(lambda v: jnp.sum(jax.nn.gelu(v)))(x + b))
    want_kept = (np.asarray(g) * act_grad / 0.7)[~dropped]
    np.testing.assert_allclose(np.asarray(dx)[~dropped], want_kept,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(db),
                               np.asarray(dx).sum(0), rtol=1e-5)


def test_rate_zero_falls_back_and_bad_rate_rejected():
    x = jnp.ones((4, 8))
    b = jnp.zeros((8,))
    out = fused_bias_act_dropout(x, b, 0, "relu", 0.0, 4, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))
    with pytest.raises(ValueError, match="rate"):
        fused_bias_act_dropout(x, b, 0, "relu", 1.5, 4, True)


def test_bwd_padding_path_uneven_rows():
    """Row counts NOT divisible by block_rows exercise the pad-then-slice
    backward path; padded rows must not pollute dx or db."""
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(13, 24)).astype(np.float32))  # 13 % 8 != 0
    b = jnp.asarray(rng.normal(size=(24,)).astype(np.float32))

    gx, gb = jax.grad(
        lambda x, b: jnp.sum(fused_bias_act(x, b, "gelu", 8, True) ** 2),
        argnums=(0, 1))(x, b)
    rx, rb = jax.grad(
        lambda x, b: jnp.sum(jax.nn.gelu(x + b) ** 2), argnums=(0, 1))(x, b)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(rb), rtol=1e-4,
                               atol=1e-5)

    # dropout variant on uneven rows: db consistent with dx. NOTE: under
    # interpret=True this exercises the jnp fallback, not _bwd_call's
    # seed-in-SMEM insertion — that branch only lowers on real TPU hardware
    # (pltpu PRNG has no CPU path) and is covered by on-chip smoke runs.
    g = jnp.asarray(rng.normal(size=(13, 24)).astype(np.float32))
    out, vjp = jax.vjp(
        lambda x, b: fused_bias_act_dropout(x, b, 13, "silu", 0.2, 8, True),
        x, b)
    dx, db = vjp(g)
    np.testing.assert_allclose(np.asarray(db),
                               np.asarray(dx).astype(np.float32).sum(0),
                               rtol=1e-5)
