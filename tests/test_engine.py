"""Engine tests — init, train_batch, fwd/bwd/step protocol, ZeRO stages, precision.

Reference analog: tests/unit/runtime/test_ds_initialize.py, zero/test_zero.py,
half_precision tests — config-dict-driven small models.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config.config import DeepSpeedTPUConfig
from deepspeed_tpu.models.simple import SimpleModel, random_batch


def make_engine(config_dict, mesh=None, hidden=32, seed=0):
    model = SimpleModel(hidden_dim=hidden)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=config_dict, mesh=mesh,
        example_batch=random_batch(4), seed=seed)
    return engine


BASE_CONFIG = {
    "train_batch_size": 8,
    "gradient_accumulation_steps": 1,
    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
}


def test_initialize_returns_tuple():
    model = SimpleModel()
    out = deepspeed_tpu.initialize(model=model, config=dict(BASE_CONFIG),
                                   example_batch=random_batch(4))
    assert len(out) == 4
    engine = out[0]
    assert engine.train_batch_size == 8


def test_train_batch_decreases_loss(mesh_dp8):
    engine = make_engine(dict(BASE_CONFIG), mesh=mesh_dp8)
    losses = []
    for i in range(20):
        batch = random_batch(8, seed=i % 4)
        losses.append(float(engine.train_batch(batch=batch)))
    assert losses[-1] < losses[0]
    assert engine.global_steps == 20


def test_gradient_accumulation_equivalence(mesh_dp8):
    """gas=2 with micro batches == gas=1 with the combined batch (same grads)."""
    cfg1 = {"train_batch_size": 16, "gradient_accumulation_steps": 1,
            "optimizer": {"type": "SGD", "params": {"lr": 0.1}}}
    cfg2 = {"train_batch_size": 16, "gradient_accumulation_steps": 2,
            "optimizer": {"type": "SGD", "params": {"lr": 0.1}}}
    e1 = make_engine(cfg1, mesh=mesh_dp8, seed=7)
    e2 = make_engine(cfg2, mesh=mesh_dp8, seed=7)

    big = random_batch(16, seed=3)          # [16, D]
    stacked = jax.tree.map(lambda x: x.reshape((2, 8) + x.shape[1:]), big)
    e1.train_batch(batch=big)
    e2.train_batch(batch=stacked)

    p1 = jax.device_get(e1.state.params)
    p2 = jax.device_get(e2.state.params)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_forward_backward_step_protocol(mesh_dp8):
    """The DeepSpeed 3-call loop trains and matches train_batch semantics."""
    cfg = {"train_batch_size": 16, "gradient_accumulation_steps": 2,
           "optimizer": {"type": "SGD", "params": {"lr": 0.1}}}
    e_compat = make_engine(cfg, mesh=mesh_dp8, seed=11)
    e_fused = make_engine(cfg, mesh=mesh_dp8, seed=11)

    m1, m2 = random_batch(8, seed=0), random_batch(8, seed=1)
    for m in (m1, m2):
        loss = e_compat.forward(m)
        assert np.isfinite(float(loss))
        e_compat.backward(loss)
        e_compat.step()
    assert e_compat.global_steps == 1

    stacked = jax.tree.map(lambda *xs: np.stack(xs), m1, m2)
    e_fused.train_batch(batch=stacked)

    pa = jax.device_get(e_compat.state.params)
    pb = jax.device_get(e_fused.state.params)
    for a, b in zip(jax.tree.leaves(pa), jax.tree.leaves(pb)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_zero_stages_converge_identically(stage, mesh8):
    """All ZeRO stages are numerically identical — they only change sharding."""
    cfg = dict(BASE_CONFIG)
    cfg["zero_optimization"] = {"stage": stage}
    engine = make_engine(cfg, mesh=mesh8, hidden=64, seed=5)
    batch = random_batch(8, seed=0)
    loss0 = float(engine.train_batch(batch=batch))
    loss5 = None
    for _ in range(5):
        loss5 = float(engine.train_batch(batch=batch))
    assert loss5 < loss0


def test_zero3_params_sharded(mesh8):
    cfg = dict(BASE_CONFIG)
    cfg["zero_optimization"] = {"stage": 3}
    engine = make_engine(cfg, mesh=mesh8, hidden=64)
    kernel_shardings = [
        s for p, s in jax.tree_util.tree_flatten_with_path(engine.param_shardings)[0]
        if "kernel" in jax.tree_util.keystr(p)
    ]
    assert any("fsdp" in str(s.spec) for s in kernel_shardings), \
        f"no fsdp-sharded kernels: {[str(s.spec) for s in kernel_shardings]}"


def test_zero1_opt_state_sharded_params_replicated(mesh8):
    cfg = dict(BASE_CONFIG)
    cfg["zero_optimization"] = {"stage": 1}
    engine = make_engine(cfg, mesh=mesh8, hidden=64)
    # params replicated
    for s in jax.tree.leaves(engine.param_shardings):
        assert "fsdp" not in str(s.spec)
    # some optimizer moment sharded
    opt_specs = [str(s.spec) for s in jax.tree.leaves(engine.opt_state_shardings)]
    assert any("fsdp" in sp for sp in opt_specs), opt_specs


def test_bf16_training(mesh_dp8):
    cfg = dict(BASE_CONFIG)
    cfg["bf16"] = {"enabled": True}
    engine = make_engine(cfg, mesh=mesh_dp8)
    loss = engine.train_batch(batch=random_batch(8))
    assert np.isfinite(float(loss))
    # master weights stay fp32
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(engine.state.params))


def test_fp16_loss_scale_dynamics(mesh_dp8):
    cfg = dict(BASE_CONFIG)
    cfg["fp16"] = {"enabled": True, "initial_scale_power": 4, "loss_scale_window": 2,
                   "hysteresis": 1}
    engine = make_engine(cfg, mesh=mesh_dp8)
    assert engine.cur_scale() == 16.0
    for i in range(4):
        engine.train_batch(batch=random_batch(8, seed=i))
    # 4 good steps with window 2 => scale doubled twice
    assert engine.cur_scale() == 64.0
    assert engine.skipped_steps == 0


def test_fp16_overflow_skips_step(mesh_dp8):
    cfg = dict(BASE_CONFIG)
    cfg["fp16"] = {"enabled": True, "initial_scale_power": 8, "hysteresis": 1}
    engine = make_engine(cfg, mesh=mesh_dp8)
    params_before = jax.device_get(engine.state.params)
    bad = random_batch(8)
    bad["x"] = bad["x"] * np.float32(np.inf)
    engine.train_batch(batch=bad)
    assert engine.skipped_steps == 1
    assert engine.cur_scale() == 128.0  # halved
    params_after = jax.device_get(engine.state.params)
    for a, b in zip(jax.tree.leaves(params_before), jax.tree.leaves(params_after)):
        np.testing.assert_array_equal(a, b)


def test_gradient_clipping(mesh_dp8):
    cfg = dict(BASE_CONFIG)
    cfg["optimizer"] = {"type": "SGD", "params": {"lr": 0.1}}
    cfg["gradient_clipping"] = 1e-8  # clip everything to ~zero step
    engine = make_engine(cfg, mesh=mesh_dp8, seed=2)
    before = jax.device_get(engine.state.params)
    engine.train_batch(batch=random_batch(8))
    after = jax.device_get(engine.state.params)
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_allclose(a, b, atol=1e-4)
    assert engine.get_global_grad_norm() > 0


def test_lr_schedule_applied(mesh_dp8):
    cfg = dict(BASE_CONFIG)
    cfg["scheduler"] = {"type": "WarmupLR",
                        "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 0.01,
                                   "warmup_num_steps": 10}}
    engine = make_engine(cfg, mesh=mesh_dp8)
    lr0 = engine.get_lr()[0]
    engine.train_batch(batch=random_batch(8))
    lr1 = engine.get_lr()[0]
    assert lr1 > lr0


def test_eval_batch(mesh_dp8):
    engine = make_engine(dict(BASE_CONFIG), mesh=mesh_dp8)
    loss = engine.eval_batch(random_batch(8))
    assert np.isfinite(float(loss))


def test_client_optimizer_authoritative(mesh_dp8):
    """Passing an optax optimizer to initialize() uses it (reference: client
    optimizer wins in _configure_optimizer)."""
    import optax
    from deepspeed_tpu.models.simple import SimpleModel
    engine, tx, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(), config={"train_batch_size": 8},
        optimizer=optax.sgd(0.5), mesh=mesh_dp8, example_batch=random_batch(4))
    before = jax.device_get(engine.state.params)
    engine.train_batch(batch=random_batch(8))
    after = jax.device_get(engine.state.params)
    # big sgd lr => parameters move substantially (default AdamW lr=1e-3 would not)
    deltas = [np.abs(a - b).max() for a, b in
              zip(jax.tree.leaves(before), jax.tree.leaves(after))]
    assert max(deltas) > 1e-3


def test_dataloader_drop_last():
    from deepspeed_tpu.runtime.dataloader import DeepSpeedTPUDataLoader
    from deepspeed_tpu.models.simple import random_dataset
    ds = random_dataset(10)
    keep = DeepSpeedTPUDataLoader(ds, batch_size=4, drop_last=False)
    batches = list(iter(keep))
    assert len(batches) == len(keep) == 3
    assert batches[-1]["x"].shape[0] == 2
    drop = DeepSpeedTPUDataLoader(ds, batch_size=4, drop_last=True)
    assert len(list(iter(drop))) == len(drop) == 2


def test_fp16_per_microbatch_overflow_detected():
    """A transient inf in one microbatch that cancels in the gas sum must still
    skip the step (reference checks per-reduction, not on the summed grads)."""
    import deepspeed_tpu.runtime.engine as eng_mod
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16),
        config={"train_batch_size": 16, "gradient_accumulation_steps": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "fp16": {"enabled": True, "loss_scale": 0.0}},
        example_batch=random_batch(4))
    orig = engine._grads_one_micro
    calls = {"n": 0}

    def poisoned(params, batch, rng, scale):
        loss, grads = orig(params, batch, rng, scale)
        calls["n"] += 1
        # inject +inf into microbatch 0 and -inf into microbatch 1 on the same
        # leaf: the accumulated sum is NaN-free only by cancellation
        leaves, tree = jax.tree_util.tree_flatten(grads)
        sign = jnp.where((calls["n"] % 2) == 1, jnp.inf, -jnp.inf)
        leaves[0] = leaves[0].at[(0,) * leaves[0].ndim].set(sign)
        return loss, jax.tree_util.tree_unflatten(tree, leaves)

    engine._grads_one_micro = poisoned
    engine._reset_compiled_fns()
    skipped_before = int(engine.state.skipped_steps)
    engine.train_batch(batch=random_batch(8, seed=0, gas=2))
    assert int(engine.state.skipped_steps) == skipped_before + 1


def test_debug_nans_mode_aborts_on_nan():
    """debug_nans (SURVEY §5.2 sanitizer): a NaN produced inside the compiled
    step raises instead of propagating silently."""
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16),
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "debug_nans": True},
        example_batch=random_batch(4))
    try:
        bad = random_batch(8, seed=0)
        bad["x"] = np.asarray(bad["x"])
        bad["x"][0, 0] = np.inf   # inf - inf / 0*inf chains produce NaN
        bad["x"][0, 1] = -np.inf
        with pytest.raises((FloatingPointError, Exception)) as e:
            float(engine.train_batch(batch=bad))
        assert "nan" in str(e.value).lower() or "NaN" in str(e.value)
    finally:
        jax.config.update("jax_debug_nans", False)


def test_engine_compile_train_eval_shims():
    """API parity: engine.compile() (jit-native no-op), train()/eval() mode
    tracking (reference engine.compile / module modes)."""
    import deepspeed_tpu
    from deepspeed_tpu.models.simple import SimpleModel, random_batch

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=32),
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}},
        example_batch=random_batch(4))
    assert engine.compile() is engine and engine._compiled
    assert engine.eval().training is False
    assert engine.train().training is True
    assert np.isfinite(float(engine.train_batch(batch=random_batch(8))))
