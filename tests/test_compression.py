"""Compression tests: STE quantizers, pruning masks, scheduler, Compressor
transform, layer reduction, engine QAT integration.

Reference analog: tests/unit/compression/ (quantizer/pruner behavior vs torch
reference implementations; init_compression config-driven).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.compression import (
    CompressionScheduler, init_compression, quantize_activation, quantize_weight,
    redundancy_clean, row_mask, head_mask, sparse_mask, student_initialization)
from deepspeed_tpu.models.simple import SimpleModel, random_batch


# ------------------------------------------------------------------ quantizers
def test_symmetric_quant_levels_and_error():
    rng = jax.random.PRNGKey(0)
    w = jax.random.normal(rng, (64, 64))
    q8 = quantize_weight(w, 8)
    q4 = quantize_weight(w, 4)
    assert jnp.abs(q8 - w).max() < jnp.abs(q4 - w).max()  # more bits, less error
    # 8-bit quantization keeps values close
    assert jnp.abs(q8 - w).max() < 0.05
    # distinct quantized levels bounded by 2^bits
    assert len(np.unique(np.asarray(q4))) <= 2 ** 4 + 1


def test_asymmetric_quant_handles_shifted_range():
    w = jnp.linspace(5.0, 6.0, 256).reshape(16, 16)
    qa = quantize_weight(w, 4, symmetric=False)
    qs = quantize_weight(w, 4, symmetric=True)
    assert jnp.abs(qa - w).mean() < jnp.abs(qs - w).mean()


def test_binary_ternary_quant():
    rng = jax.random.PRNGKey(1)
    w = jax.random.normal(rng, (32, 32))
    b = quantize_weight(w, 1)
    assert len(np.unique(np.round(np.asarray(jnp.abs(b)), 5))) <= 2  # {0?, alpha}
    assert (jnp.sign(b) == jnp.sign(w)).mean() > 0.99
    t = quantize_weight(w, 2)
    assert len(np.unique(np.round(np.asarray(t), 5))) <= 3  # {-a, 0, +a}


def test_grouped_quant_beats_per_tensor_on_mixed_scales():
    rng = jax.random.PRNGKey(2)
    w = jnp.concatenate([jax.random.normal(rng, (1, 64)) * 10,
                         jax.random.normal(rng, (1, 64)) * 0.1])
    per_tensor = quantize_weight(w, 4, num_groups=1)
    grouped = quantize_weight(w, 4, num_groups=2)
    assert jnp.abs(grouped - w)[1].mean() < jnp.abs(per_tensor - w)[1].mean()


def test_ste_gradient_is_identity():
    w = jnp.array([[0.3, -0.7], [0.1, 0.9]])
    g = jax.grad(lambda w: (quantize_weight(w, 4) ** 2).sum() / 2)(w)
    # STE: d/dw (q(w)^2/2) = q(w) * 1 — gradient flows as if q were identity
    np.testing.assert_allclose(np.asarray(g), np.asarray(quantize_weight(w, 4)))


def test_activation_quant_dynamic_and_static():
    x = jnp.linspace(-2, 2, 100)
    qd = quantize_activation(x, 8)
    assert jnp.abs(qd - x).max() < 0.05
    qs = quantize_activation(x, 8, static_range=jnp.float32(4.0))
    assert jnp.abs(qs - x).max() < 0.1


# ------------------------------------------------------------------ masks
def test_sparse_mask_ratio():
    rng = jax.random.PRNGKey(3)
    w = jax.random.normal(rng, (32, 32))
    m = sparse_mask(w, 0.25)
    assert abs(float(m.mean()) - 0.25) < 0.01
    # kept entries are the largest-magnitude ones
    assert float(jnp.abs(w * m).sum()) > 0.5 * float(jnp.abs(w).sum())


def test_row_mask_structured():
    rng = jax.random.PRNGKey(4)
    w = jax.random.normal(rng, (16, 8))
    m = row_mask(w, 0.5)
    assert m.shape == (8,)
    assert int(m.sum()) == 4


def test_head_mask_blocks():
    rng = jax.random.PRNGKey(5)
    w = jax.random.normal(rng, (32, 16))  # 4 heads x head_dim 4
    m = head_mask(w, 0.5, num_heads=4)
    assert m.shape == (16,)
    blocks = np.asarray(m).reshape(4, 4)
    assert ((blocks == 0) | (blocks == 1)).all()
    assert (blocks.std(axis=1) == 0).all()  # whole heads kept or dropped
    assert blocks.any(axis=1).sum() == 2


# ------------------------------------------------------------------ scheduler
def test_scheduler_offsets_and_bit_annealing():
    cfg = {
        "weight_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 10},
            "different_groups": {"g1": {
                "params": {"start_bits": 8, "target_bits": 4,
                           "quantization_period": 5},
                "modules": ["dense"]}}},
        "sparse_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 20,
                                  "schedule_offset_end": 30},
            "different_groups": {"g1": {"params": {"dense_ratio": 0.5},
                                        "modules": ["dense"]}}},
    }
    s = CompressionScheduler(cfg)
    assert s.state(step=0) == ()
    st10 = s.state(step=10)
    assert st10 and st10[0][0] == "weight_quantization"
    # the anneal clock starts at schedule_offset: at the activation step the
    # bits are still start_bits
    anneal = {"start_bits": 8, "target_bits": 4, "quantization_period": 5,
              "schedule_offset": 10}
    assert s.current_bits(anneal) == 8
    s.state(step=17)
    assert s.current_bits(anneal) == 8 - (17 - 10) // 5
    assert dict(s.state(step=25)).keys() >= {"sparse_pruning"}
    assert "sparse_pruning" not in dict(s.state(step=31))  # past offset_end
    s.state(step=100)
    assert s.current_bits(anneal) == 4  # floored at target


# ------------------------------------------------------------------ Compressor
def _toy_params(key=0):
    k = jax.random.PRNGKey(key)
    return {"layers_0": {"dense": {"kernel": jax.random.normal(k, (16, 16)),
                                   "bias": jnp.zeros(16)}},
            "layers_1": {"dense": {"kernel": jax.random.normal(k, (16, 16)) * 2,
                                   "bias": jnp.zeros(16)}}}


def test_compressor_transform_quantizes_matched_only():
    params = _toy_params()
    comp = init_compression(params, {"compression_training": {
        "weight_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0,
                                  "quantization_type": "symmetric"},
            "different_groups": {"g1": {
                "params": {"start_bits": 4, "target_bits": 4},
                "modules": [r"layers_0/dense"]}}}}})
    out = comp.transform(params)
    k0, k1 = out["layers_0"]["dense"]["kernel"], out["layers_1"]["dense"]["kernel"]
    assert not np.allclose(k0, params["layers_0"]["dense"]["kernel"])  # quantized
    np.testing.assert_array_equal(k1, params["layers_1"]["dense"]["kernel"])  # untouched
    assert len(np.unique(np.asarray(k0))) <= 2 ** 4 + 1


def test_compressor_pruning_freeze_and_apply():
    params = _toy_params()
    comp = init_compression(params, {"compression_training": {
        "row_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 5},
            "different_groups": {"g1": {"params": {"dense_ratio": 0.5},
                                        "modules": ["dense"]}}}}})
    comp.set_step(0)
    assert comp.transform(params)["layers_0"]["dense"]["kernel"].std() > 0
    comp.set_step(5)
    comp.maybe_freeze_masks(params)
    out = comp.transform(params)
    cols = np.abs(np.asarray(out["layers_0"]["dense"]["kernel"])).sum(axis=0)
    assert (cols == 0).sum() == 8  # half the output features zeroed
    baked = redundancy_clean(params, comp)
    cols_b = np.abs(np.asarray(baked["layers_0"]["dense"]["kernel"])).sum(axis=0)
    assert (cols_b == 0).sum() == 8


def test_student_initialization_layer_reduction():
    teacher = {"layers_0": {"w": jnp.full((4, 4), 0.0)},
               "layers_1": {"w": jnp.full((4, 4), 1.0)},
               "layers_2": {"w": jnp.full((4, 4), 2.0)},
               "layers_3": {"w": jnp.full((4, 4), 3.0)},
               "head": {"w": jnp.full((4, 2), 9.0)}}
    student = {"layers_0": {"w": jnp.zeros((4, 4))},
               "layers_1": {"w": jnp.zeros((4, 4))},
               "head": {"w": jnp.zeros((4, 2))}}
    out = student_initialization(student, teacher,
                                 {"module_name_prefix": "layers",
                                  "teacher_layer": [1, 3]})
    assert float(out["layers_0"]["w"][0, 0]) == 1.0
    assert float(out["layers_1"]["w"][0, 0]) == 3.0
    assert float(out["head"]["w"][0, 0]) == 9.0  # non-layer leaves copied


# ------------------------------------------------------------------ engine QAT
def test_engine_qat_trains_and_recompiles_on_schedule():
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "compression_training": {
            "weight_quantization": {
                "shared_parameters": {"enabled": True, "schedule_offset": 2,
                                      "quantization_type": "symmetric"},
                "different_groups": {"g1": {
                    "params": {"start_bits": 8, "target_bits": 8},
                    "modules": [".*"]}}}},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=32), config=config,
        example_batch=random_batch(4))
    assert engine.compressor is not None
    fixed = random_batch(8, seed=0)
    losses = [float(engine.train_batch(batch=fixed)) for _ in range(10)]
    # schedule transition at step 2 invalidated + rebuilt the compiled step
    assert losses[-1] < losses[0]
    assert dict(engine.compressor.schedule_key()).keys() == {"weight_quantization"}


@pytest.mark.slow
def test_pruning_masks_survive_checkpoint_resume(tmp_path):
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "compression_training": {
            "sparse_pruning": {
                "shared_parameters": {"enabled": True, "schedule_offset": 0},
                "different_groups": {"g1": {"params": {"dense_ratio": 0.5},
                                            "modules": [".*"]}}}},
    }

    def build(seed):
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=SimpleModel(hidden_dim=32), config=config,
            example_batch=random_batch(4), seed=seed)
        return engine

    engine = build(seed=0)
    for i in range(3):
        engine.train_batch(batch=random_batch(8, seed=i))
    masks_before = {m: dict(d) for m, d in engine.compressor._masks.items()}
    engine.save_checkpoint(str(tmp_path))

    # different seed → different init weights → refreezing would give different
    # masks; the checkpoint must restore the originals
    fresh = build(seed=123)
    fresh.load_checkpoint(str(tmp_path))
    for method, d in masks_before.items():
        for name, mask in d.items():
            np.testing.assert_array_equal(
                fresh.compressor._masks[method][name], mask)
