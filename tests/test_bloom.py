"""BLOOM family tests: ALiBi math, training, TP rules, HF conversion, serving.

Reference analog: the BLOOM container tests under ``tests/unit/inference``
(alibi softmax parity) and ``module_inject`` bloom policy cases.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.bloom import (
    TINY_BLOOM, BloomConfig, BloomForCausalLM, alibi_augment, alibi_slopes,
    bloom_tensor_rules, convert_hf_bloom)
from deepspeed_tpu.models.llama import random_tokens


def test_alibi_slopes_published_values():
    np.testing.assert_allclose(alibi_slopes(8),
                               [2.0 ** (-i) for i in range(1, 9)], rtol=1e-6)
    s6 = alibi_slopes(6)  # non-power-of-two: 4 base + 2 interpolated
    assert len(s6) == 6 and np.all(s6 > 0) and np.all(np.diff(s6[:4]) < 0)


def test_alibi_augmentation_equals_explicit_bias():
    """q'k' trick == softmax(qk/sqrt(d) + slope*(j-i)) exactly (module
    docstring derivation)."""
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 16, 4, 8
    q, k, v = (rng.normal(size=(b, s, h, d)).astype(np.float32) for _ in range(3))
    slopes = alibi_slopes(h)
    positions = np.broadcast_to(np.arange(s), (b, s))

    # explicit reference
    scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    i_idx, j_idx = np.arange(s)[:, None], np.arange(s)[None, :]
    scores = scores + slopes[None, :, None, None] * (j_idx - i_idx)
    scores = np.where(j_idx <= i_idx, scores, -np.inf)
    probs = jax.nn.softmax(jnp.asarray(scores), axis=-1)
    want = np.einsum("bhqk,bkhd->bqhd", np.asarray(probs), v)

    from deepspeed_tpu.models.llama import _xla_attention
    qa, ka, va = alibi_augment(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                               jnp.asarray(slopes), jnp.asarray(positions))
    got = np.asarray(_xla_attention(qa, ka, va, True, None))[..., :d]
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_bloom_trains_and_tp_rules():
    model = BloomForCausalLM(TINY_BLOOM)
    config = {"train_batch_size": 8,
              "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
              "zero_optimization": {"stage": 3},
              "mesh": {"data": 2, "fsdp": 2, "tensor": 2}}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=config,
        example_batch=random_tokens(8, 16, vocab_size=TINY_BLOOM.vocab_size),
        tensor_rules=bloom_tensor_rules)
    fixed = random_tokens(8, 16, vocab_size=TINY_BLOOM.vocab_size, seed=0)
    losses = [float(engine.train_batch(batch=fixed)) for _ in range(5)]
    assert losses[-1] < losses[0] and all(np.isfinite(losses))


def test_bloom_hf_conversion_shapes_and_forward():
    cfg = TINY_BLOOM
    rng = np.random.default_rng(2)
    d, h, dh = cfg.hidden_size, cfg.num_heads, cfg.head_dim_

    hf = {"transformer.word_embeddings.weight":
          rng.normal(size=(cfg.vocab_size, d)).astype(np.float32) * 0.02,
          "transformer.word_embeddings_layernorm.weight": np.ones(d, np.float32),
          "transformer.word_embeddings_layernorm.bias": np.zeros(d, np.float32),
          "transformer.ln_f.weight": np.ones(d, np.float32),
          "transformer.ln_f.bias": np.zeros(d, np.float32)}
    per_head_q = rng.normal(size=(h, dh, d)).astype(np.float32) * 0.02
    per_head_k = rng.normal(size=(h, dh, d)).astype(np.float32) * 0.02
    per_head_v = rng.normal(size=(h, dh, d)).astype(np.float32) * 0.02
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}."
        fused = np.stack([per_head_q, per_head_k, per_head_v], axis=1)  # [h,3,dh,d]
        hf[p + "self_attention.query_key_value.weight"] = fused.reshape(3 * h * dh, d)
        hf[p + "self_attention.query_key_value.bias"] = np.zeros(3 * h * dh, np.float32)
        hf[p + "self_attention.dense.weight"] = \
            rng.normal(size=(d, d)).astype(np.float32) * 0.02
        hf[p + "self_attention.dense.bias"] = np.zeros(d, np.float32)
        hf[p + "input_layernorm.weight"] = np.ones(d, np.float32)
        hf[p + "input_layernorm.bias"] = np.zeros(d, np.float32)
        hf[p + "post_attention_layernorm.weight"] = np.ones(d, np.float32)
        hf[p + "post_attention_layernorm.bias"] = np.zeros(d, np.float32)
        hf[p + "mlp.dense_h_to_4h.weight"] = \
            rng.normal(size=(4 * d, d)).astype(np.float32) * 0.02
        hf[p + "mlp.dense_h_to_4h.bias"] = np.zeros(4 * d, np.float32)
        hf[p + "mlp.dense_4h_to_h.weight"] = \
            rng.normal(size=(d, 4 * d)).astype(np.float32) * 0.02
        hf[p + "mlp.dense_4h_to_h.bias"] = np.zeros(d, np.float32)

    params = convert_hf_bloom(hf, cfg)
    # fused split: wq kernel row h0 equals per-head q transposed
    np.testing.assert_allclose(params["model"]["layer_0"]["wq"]["kernel"],
                               per_head_q.transpose(2, 0, 1))
    model = BloomForCausalLM(cfg)
    batch = random_tokens(2, 12, vocab_size=cfg.vocab_size)
    ref = model.init(jax.random.PRNGKey(0), batch)["params"]
    assert jax.tree.structure(ref) == jax.tree.structure(
        jax.tree.map(jnp.asarray, params))
    loss = model.apply({"params": jax.tree.map(jnp.asarray, params)}, batch)
    assert np.isfinite(float(loss))


@pytest.mark.slow
def test_serve_bloom_paged_matches_full():
    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, V2EngineConfig)
    from deepspeed_tpu.inference.v2.modules import BloomPolicy, policy_for
    from deepspeed_tpu.inference.v2.scheduler import SchedulerConfig

    cfg = TINY_BLOOM
    assert policy_for(cfg) is BloomPolicy
    model = BloomForCausalLM(cfg)
    prompt = list(np.random.default_rng(5).integers(0, cfg.vocab_size, 11))
    params = model.init(jax.random.PRNGKey(3),
                        random_tokens(1, 8, vocab_size=cfg.vocab_size))["params"]
    engine = InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=64,
        scheduler=SchedulerConfig(max_tokens_per_step=64,
                                  prefill_buckets=(16, 32, 64))))
    got = engine.generate(list(prompt), max_new_tokens=4)
    ids = list(prompt)
    for _ in range(4):
        logits = model.apply({"params": params},
                             jnp.asarray([ids], jnp.int32),
                             method=lambda m, x: m.model(x))
        ids.append(int(np.argmax(np.asarray(logits)[0, -1])))
    assert got == ids[len(prompt):], (got, ids[len(prompt):])
