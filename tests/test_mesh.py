"""Mesh factory + collective facade tests (reference analog:
tests/unit/comm/test_dist.py + utils/groups tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.comm import (
    all_gather,
    all_reduce,
    all_to_all,
    create_mesh,
    get_data_parallel_world_size,
    get_seq_data_parallel_world_size,
    reduce_scatter,
)
from deepspeed_tpu.comm.mesh import MESH_AXES, resolve_axis_sizes
from deepspeed_tpu.config.config import MeshConfig


def test_resolve_axis_sizes_fill():
    sizes = resolve_axis_sizes(MeshConfig(data=-1, fsdp=2), 8)
    assert sizes["data"] == 4 and sizes["fsdp"] == 2


def test_resolve_axis_sizes_mismatch():
    with pytest.raises(ValueError):
        resolve_axis_sizes(MeshConfig(data=3, fsdp=2), 8)


def test_create_mesh_axes(mesh8):
    assert mesh8.axis_names == MESH_AXES
    assert mesh8.shape["data"] == 2 and mesh8.shape["fsdp"] == 4
    assert get_data_parallel_world_size(mesh8) == 8
    assert get_seq_data_parallel_world_size(mesh8) == 8


def test_collectives_under_shard_map(mesh_dp8):
    x = jnp.arange(8.0)

    @jax.jit
    def f(v):
        def body(v):
            s = all_reduce(v, "data")
            g = all_gather(v, "data", axis=0)
            rs = reduce_scatter(g, "data", scatter_dimension=0)
            return s, g, rs
        return jax.shard_map(
            body, mesh=mesh_dp8,
            in_specs=PartitionSpec("data"),
            out_specs=(PartitionSpec("data"), PartitionSpec(), PartitionSpec("data")),
            check_vma=False,
        )(v)

    s, g, rs = f(x)
    np.testing.assert_allclose(np.asarray(s), np.full((8,), 28.0))
    np.testing.assert_allclose(np.asarray(g), np.arange(8.0))
    # reduce_scatter over an all_gathered copy: each shard = 8 * own value
    np.testing.assert_allclose(np.asarray(rs), np.arange(8.0) * 8)


def test_all_to_all(mesh_dp8):
    x = jnp.arange(64.0).reshape(8, 8)

    @jax.jit
    def f(v):
        def body(v):
            return all_to_all(v, "data", split_axis=1, concat_axis=0)
        return jax.shard_map(body, mesh=mesh_dp8,
                             in_specs=PartitionSpec("data", None),
                             out_specs=PartitionSpec("data", None))(v)

    out = f(x)
    # tiled all_to_all: dim-1 split into world pieces, concatenated on dim 0 —
    # a global transpose laid out as (64, 1)
    assert out.shape == (64, 1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).T.reshape(64, 1))


def test_comms_logger_traced(mesh_dp8):
    from deepspeed_tpu.comm import get_comms_logger

    logger_ = get_comms_logger()
    logger_.configure(enabled=True)
    logger_.reset()

    x = jnp.arange(8.0)

    def body(v):
        return all_reduce(v, "data")

    jax.jit(lambda v: jax.shard_map(body, mesh=mesh_dp8,
                                    in_specs=PartitionSpec("data"),
                                    out_specs=PartitionSpec("data"))(v))(x)
    assert logger_.traced["all_reduce"]["count"] >= 1
    lines = logger_.log_summary()
    assert any("all_reduce" in l for l in lines)
    logger_.configure(enabled=False)
