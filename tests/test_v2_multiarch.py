"""Inference v2 multi-arch serving + sampling tests.

Reference analog: tests/unit/inference/v2/model_implementations (per-arch
serving parity) + the module registry/heuristics layer
(deepspeed/inference/v2/modules/).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2, V2EngineConfig
from deepspeed_tpu.inference.v2.modules import (
    DECODE_POLICIES, FalconPolicy, LlamaPolicy, MixtralPolicy, OPTPolicy,
    policy_for)
from deepspeed_tpu.inference.v2.sampling import SamplingConfig, sample_tokens
from deepspeed_tpu.inference.v2.scheduler import SchedulerConfig
from deepspeed_tpu.models.falcon import TINY_FALCON, FalconForCausalLM
from deepspeed_tpu.models.llama import TINY_LLAMA, LlamaConfig, random_tokens
from deepspeed_tpu.models.mixtral import TINY_MIXTRAL, MixtralConfig, MixtralForCausalLM
from deepspeed_tpu.models.opt import TINY_OPT, OPTConfig, OPTForCausalLM
from deepspeed_tpu.moe.sharded_moe import MoEConfig


def test_registry_and_heuristics():
    assert set(DECODE_POLICIES) >= {"llama", "falcon", "opt", "mixtral"}
    assert policy_for(TINY_LLAMA) is LlamaPolicy
    assert policy_for(TINY_FALCON) is FalconPolicy
    assert policy_for(TINY_OPT) is OPTPolicy
    assert policy_for(TINY_MIXTRAL) is MixtralPolicy
    with pytest.raises(ValueError, match="no decode policy"):
        policy_for(object())


def _serve_and_reference(model, params, cfg, logits_method, prompt, n=4):
    """Serve via the paged engine; reference is the training model's iterative
    full-forward argmax chain."""
    engine = InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=64,
        scheduler=SchedulerConfig(max_tokens_per_step=64,
                                  prefill_buckets=(16, 32, 64))))
    got = engine.generate(list(prompt), max_new_tokens=n)
    ids = list(prompt)
    for _ in range(n):
        logits = logits_method({"input_ids": np.asarray([ids], np.int32)})
        ids.append(int(np.argmax(np.asarray(logits)[0, -1])))
    assert got == ids[len(prompt):], (got, ids[len(prompt):])


@pytest.mark.slow
def test_serve_falcon():
    cfg = dataclasses.replace(TINY_FALCON, dtype=jnp.float32)
    model = FalconForCausalLM(cfg)
    prompt = list(np.random.default_rng(0).integers(0, cfg.vocab_size, 12))
    params = model.init(jax.random.PRNGKey(0),
                       random_tokens(1, 8, vocab_size=cfg.vocab_size))["params"]
    _serve_and_reference(
        model, params, cfg,
        lambda b: model.apply({"params": params}, jnp.asarray(b["input_ids"]),
                              method=lambda m, x: m.model(x)),
        prompt)


@pytest.mark.slow
def test_serve_falcon_new_decoder_architecture():
    cfg = dataclasses.replace(TINY_FALCON, dtype=jnp.float32, num_heads=4,
                              num_kv_heads=2, new_decoder_architecture=True)
    model = FalconForCausalLM(cfg)
    prompt = list(np.random.default_rng(4).integers(0, cfg.vocab_size, 9))
    params = model.init(jax.random.PRNGKey(1),
                       random_tokens(1, 8, vocab_size=cfg.vocab_size))["params"]
    _serve_and_reference(
        model, params, cfg,
        lambda b: model.apply({"params": params}, jnp.asarray(b["input_ids"]),
                              method=lambda m, x: m.model(x)),
        prompt)


@pytest.mark.slow
def test_serve_opt():
    cfg = dataclasses.replace(TINY_OPT, dtype=jnp.float32)
    model = OPTForCausalLM(cfg)
    prompt = list(np.random.default_rng(1).integers(0, cfg.vocab_size, 10))
    params = model.init(jax.random.PRNGKey(0),
                       random_tokens(1, 8, vocab_size=cfg.vocab_size))["params"]
    _serve_and_reference(
        model, params, cfg,
        lambda b: model.apply({"params": params}, jnp.asarray(b["input_ids"]),
                              method=lambda m, x: m.model(x)),
        prompt)


@pytest.mark.slow
def test_serve_mixtral():
    cfg = dataclasses.replace(
        TINY_MIXTRAL,
        base=dataclasses.replace(TINY_MIXTRAL.base, dtype=jnp.float32),
        moe=dataclasses.replace(TINY_MIXTRAL.moe, dtype=jnp.float32))
    model = MixtralForCausalLM(cfg)
    prompt = list(np.random.default_rng(2).integers(0, cfg.base.vocab_size, 11))
    params = model.init(jax.random.PRNGKey(0),
                       random_tokens(1, 8, vocab_size=cfg.base.vocab_size))["params"]
    _serve_and_reference(
        model, params, cfg,
        lambda b: model.apply({"params": params}, b,
                              method=MixtralForCausalLM.logits),
        prompt)


# ---------------------------------------------------------------- sampling
def test_sampling_greedy_is_argmax():
    logits = jnp.asarray(np.random.default_rng(0).normal(size=(4, 50)),
                         jnp.float32)
    toks = sample_tokens(logits, jax.random.PRNGKey(0), SamplingConfig())
    np.testing.assert_array_equal(np.asarray(toks),
                                  np.argmax(np.asarray(logits), -1))


def test_sampling_top_k_restricts_support():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(2, 100)), jnp.float32)
    cfg = SamplingConfig(temperature=1.0, top_k=5)
    top5 = np.argsort(np.asarray(logits), -1)[:, -5:]
    for i in range(50):
        toks = np.asarray(sample_tokens(logits, jax.random.PRNGKey(i), cfg))
        for b in range(2):
            assert toks[b] in top5[b]


def test_sampling_top_p_restricts_support():
    # peaked distribution: top_p=0.9 keeps only the head tokens
    logits = jnp.asarray(np.log(np.array(
        [[0.5, 0.3, 0.1, 0.05, 0.03, 0.02]] * 2)), jnp.float32)
    cfg = SamplingConfig(temperature=1.0, top_p=0.85)
    for i in range(50):
        toks = np.asarray(sample_tokens(logits, jax.random.PRNGKey(i), cfg))
        assert (toks <= 2).all()      # 0.5+0.3=0.8 <0.85 -> token 2 included


def test_sampling_top_p_zero_degrades_to_greedy():
    logits = jnp.asarray([[0.0, 10.0, 1.0, 2.0]], jnp.float32)
    cfg = SamplingConfig(temperature=1.0, top_p=0.0)
    for i in range(8):
        assert int(sample_tokens(logits, jax.random.PRNGKey(i), cfg)[0]) == 1


def test_sampling_temperature_flattens():
    logits = jnp.asarray([[2.0, 1.0, 0.0, -1.0]] * 1, jnp.float32)
    hot = [int(sample_tokens(logits, jax.random.PRNGKey(i),
                             SamplingConfig(temperature=0.1))[0])
           for i in range(30)]
    assert all(t == 0 for t in hot)    # near-greedy at low temperature


def test_engine_sampled_generation_differs_and_is_seeded():
    cfg = LlamaConfig(**{**TINY_LLAMA.__dict__, "dtype": jnp.float32})
    from deepspeed_tpu.models.llama import LlamaForCausalLM
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                       random_tokens(1, 8, vocab_size=cfg.vocab_size))["params"]
    prompt = list(np.random.default_rng(3).integers(0, cfg.vocab_size, 8))

    def gen(seed):
        eng = InferenceEngineV2(params, cfg, V2EngineConfig(
            sampling=SamplingConfig(temperature=1.0, top_k=50, seed=seed)))
        return eng.generate(list(prompt), max_new_tokens=8)

    assert gen(0) == gen(0)            # deterministic per seed
    runs = {tuple(gen(s)) for s in range(5)}
    assert len(runs) > 1               # actually stochastic across seeds
