"""Static regression gate for the hot paths — a thin wrapper over the
dslint DS002 taint rule, so this tripwire and ``bin/dslint`` can never
drift apart: both read the SAME declarations
(``deepspeed_tpu/tools/dslint/hotpath.HOT_ROOTS`` / ``ESCAPE_HATCHES``).

What the declarations enforce (see hotpath.py for the full spec):

  * everything reachable from a registered hot ROOT (the training
    dispatch, the serving tick, the router poll, ...) never regrows
    ``float()``/``.item()``/``device_get``/``block_until_ready`` —
    readback belongs in the declared escape hatches (the drains, the
    guarded fallback branches, the deliberately-synchronous offload
    paths)
  * a registered root or hatch disappearing (renamed without a
    declaration update) is itself a DS002 drift finding

Plus the superset/necessity proof: the taint closure covers every
function the old hand-written per-function registry named (nothing lost
in the v2 migration), and every declared root uniquely covers part of
it (deleting any single root fails here — roots cannot silently rot).
"""

import pathlib

import pytest

from deepspeed_tpu.tools.dslint import lint_paths
from deepspeed_tpu.tools.dslint.hotpath import ESCAPE_HATCHES, HOT_ROOTS
from deepspeed_tpu.tools.dslint.rules.ds002_hot_sync import HotPathSyncRule

pytestmark = pytest.mark.lint

REPO = pathlib.Path(__file__).resolve().parent.parent

# ----------------------------------------------------------------------
# the frozen pre-v2 registry: every function the old per-function
# HOT_PATHS spec table named, as (path, qualname). The taint closure
# from HOT_ROOTS must keep covering ALL of them — this list is a
# snapshot and should only ever GROW (append new entries when a refactor
# moves hot code; never delete to make the proof pass).
# ----------------------------------------------------------------------
LEGACY_COVERAGE = tuple(
    (path, f"{cls}.{fn}" if cls else fn)
    for path, cls, fns in [
        ("deepspeed_tpu/runtime/engine.py", "DeepSpeedTPUEngine",
         ("train_batch", "stack_microbatches", "_shard_batch",
          "_advance_data_schedules", "_ensure_prefetcher",
          "_emit_overlap_spans", "_record_metrics")),
        ("deepspeed_tpu/runtime/sched.py", "DispatchRing",
         ("push", "rearm_if_idle", "store", "take", "requeue", "__len__")),
        ("deepspeed_tpu/runtime/sched.py", "StagedPrefetcher", ("ensure",)),
        ("deepspeed_tpu/runtime/sched.py", "TickLedger",
         ("observe_tick", "reset_window")),
        ("deepspeed_tpu/inference/v2/scheduler.py", None,
         ("snap_bucket", "plan_step")),
        ("deepspeed_tpu/serving/disagg.py", "DisaggregatedEngine",
         ("step", "_handoff", "can_schedule", "has_work")),
        ("deepspeed_tpu/inference/v2/engine_v2.py", "InferenceEngineV2",
         ("adopt_kv_handoff",)),
        ("deepspeed_tpu/serving/server.py", "InferenceServer",
         ("_serve_once", "_admit_from_queue", "_fan_out", "_reap",
          "_settle_reaped", "_rebalance_kv_tiers", "_observe_ladder",
          "_reconcile_kv", "_active_worstcase", "_active_uids",
          "_note_clean_step", "_trim_prefix_cache", "_prefix_gauges",
          "_cache_evictable_blocks", "_mark", "_emit_tick_spans",
          "_tick_stage_gauges")),
        ("deepspeed_tpu/serving/degradation.py", "DegradationLadder",
         ("observe", "_transition")),
        ("deepspeed_tpu/serving/kv_tier.py", None,
         ("effective_usable_blocks", "plan_demotions",
          "plan_prefix_evictions", "plan_promotions", "tier_pressure")),
        ("deepspeed_tpu/serving/fleet.py", None,
         ("affinity_key", "pick_replica", "plan_scale")),
        ("deepspeed_tpu/serving/fleet.py", "ReplicaHandle",
         ("in_rotation", "snapshot")),
        ("deepspeed_tpu/inference/v2/prefix_cache.py", "PrefixCache",
         ("lookup", "admit_match", "_pin", "_keys", "insert_from_seq",
          "release_seq", "plan_evictions", "evict_blocks",
          "evictable_blocks", "over_cap_blocks", "cached_blocks",
          "pinned_blocks", "pinned_block_ids", "owns", "snapshot")),
        ("deepspeed_tpu/inference/v2/kv_offload.py", None,
         ("quantize_pages", "dequantize_pages", "_page_absmax")),
        ("deepspeed_tpu/runtime/dataloader.py", "PrefetchLoader",
         ("_worker", "__next__")),
        ("deepspeed_tpu/telemetry/tracer.py", "Tracer",
         ("span", "instant", "complete", "counter", "_emit")),
        ("deepspeed_tpu/telemetry/tracer.py", "_Span",
         ("__enter__", "__exit__")),
        ("deepspeed_tpu/comm/compress.py", None,
         ("quantize_wire", "dequantize_wire", "ef_step",
          "reduce_scatter_impl", "all_reduce_impl", "_exchange",
          "_regather", "axis_world", "plan_buckets")),
        ("deepspeed_tpu/comm/compress.py", "GradCompressor",
         ("make_sync_fn", "bucket_summaries")),
        ("deepspeed_tpu/comm/guard.py", None,
         ("note_comm_op", "next_op_seq")),
        ("deepspeed_tpu/resilience/membership.py", "Heartbeat",
         ("note_op",)),
        ("deepspeed_tpu/telemetry/memory.py", "MemorySampler",
         ("on_drain", "sample", "_collect")),
        ("deepspeed_tpu/telemetry/compiles.py", "CompileWatched",
         ("__call__",)),
    ]
    for fn in fns
)


def _resolved_roots(graph, roots=HOT_ROOTS):
    keys = {}
    for root in roots:
        k = graph.resolve(root.path, root.qualname)
        assert k is not None, (
            f"hot root {root.qualname} no longer resolves in {root.path} "
            f"— update hotpath.py HOT_ROOTS alongside the refactor")
        keys[k] = root
    return keys

def _prune_keys(graph):
    out = set()
    for h in ESCAPE_HATCHES:
        if h.mode != "prune":
            continue
        k = graph.resolve(h.path, h.qualname)
        if k is not None:
            out.add(k)
    return out


def test_declared_roots_still_cover_the_load_bearing_surfaces():
    """The declaration content IS the contract: shrinking it is loud."""
    by_qn = {r.qualname: r for r in HOT_ROOTS}
    for qn in ("DeepSpeedTPUEngine.train_batch", "FaultTolerantRunner.step",
               "InferenceServer._serve_once", "DisaggregatedEngine.step",
               "InferenceEngineV2.step", "FleetRouter.route_generate",
               "FleetRouter._poll_once"):
        assert qn in by_qn, f"hot root {qn} was dropped from HOT_ROOTS"
    hatches = {(h.qualname, h.mode) for h in ESCAPE_HATCHES}
    assert ("DispatchRing.drain", "sync_ok") in hatches
    assert ("DeepSpeedTPUEngine._drain_metric_ring", "sync_ok") in hatches
    guarded = {h.qualname: h.guard_attr for h in ESCAPE_HATCHES
               if h.mode == "guarded"}
    assert guarded.get("DeepSpeedTPUEngine._record_metrics") == \
        "_async_enabled"


def test_hot_paths_have_no_host_sync():
    """Lint the whole package with DS002 only (the taint needs every
    file to chase call edges); any finding — including root/hatch drift
    from a rename — fails."""
    result = lint_paths([str(REPO / "deepspeed_tpu")], root=str(REPO),
                        rules=[HotPathSyncRule()])
    assert not result.findings, (
        "hot path gained host synchronization (or a declaration "
        "drifted):\n  "
        + "\n  ".join(f.render() for f in result.findings)
        + "\nroute readback through a declared escape hatch, or update "
          "deepspeed_tpu/tools/dslint/hotpath.py alongside a deliberate "
          "refactor")


def test_taint_closure_is_a_superset_of_the_legacy_registry(
        package_callgraph):
    """Nothing the old per-function registry covered fell out of the
    taint closure: every frozen legacy entry is reachable from the
    declared roots (minus the declared prune hatches)."""
    g = package_callgraph
    reached = g.reachable_from(sorted(_resolved_roots(g)),
                               prune=_prune_keys(g))
    missing = []
    for path, qn in LEGACY_COVERAGE:
        k = g.resolve(path, qn)
        assert k is not None, (
            f"legacy-coverage entry {path}::{qn} no longer exists — "
            f"append its successor to LEGACY_COVERAGE (do not delete)")
        if k not in reached:
            missing.append(k)
    assert not missing, (
        "taint closure LOST legacy hot-path coverage (a call edge or "
        "root declaration broke):\n  " + "\n  ".join(missing))


def test_every_root_is_necessary(package_callgraph):
    """Deleting any single HOT_ROOTS entry loses coverage: each root
    uniquely covers at least one function (a legacy entry or itself).
    A root that covers nothing uniquely is dead weight that would let
    its surface silently drop out of the taint."""
    g = package_callgraph
    roots = _resolved_roots(g)
    prune = _prune_keys(g)
    full = g.reachable_from(sorted(roots), prune=prune)
    legacy_keys = {g.resolve(p, q) for p, q in LEGACY_COVERAGE}
    for key, root in sorted(roots.items()):
        rest = [k for k in roots if k != key]
        without = g.reachable_from(sorted(rest), prune=prune)
        unique = (set(full) - set(without)) & (legacy_keys | {key})
        assert unique, (
            f"root {root.qualname} covers nothing uniquely — removing "
            f"it from HOT_ROOTS changes no coverage, so either a new "
            f"root subsumed it (delete the stale one deliberately and "
            f"update this proof) or the declaration drifted")
