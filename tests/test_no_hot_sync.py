"""Static regression gate for the hot paths — now a thin wrapper over the
dslint DS002 rule, so this tripwire and ``bin/dslint`` can never drift
apart: both read the SAME registry (``deepspeed_tpu/tools/dslint/hotpath
.HOT_PATHS``).

What the registry enforces (see hotpath.py for the full spec):

  * ``train_batch`` + the per-step fused path never regrow ``float()``/
    ``.item()``/``device_get``/``block_until_ready`` — step-output
    readback belongs in ``_drain_metric_ring`` (the designated drain)
  * the ``_async_enabled`` push branch of ``_record_metrics`` queues
    device arrays verbatim (a transfer there re-serializes every step)
  * ``jax.device_get`` in engine.py stays confined to the drain and the
    explicitly host-synchronous paths
  * the serving tick and the prefetch worker stay sync-free too

A registered function disappearing (renamed without a registry update) is
itself a DS002 finding, preserving the old test's rename detection.
"""

import pathlib

import pytest

from deepspeed_tpu.tools.dslint import lint_paths
from deepspeed_tpu.tools.dslint.hotpath import HOT_PATHS
from deepspeed_tpu.tools.dslint.rules.ds002_hot_sync import HotPathSyncRule

pytestmark = pytest.mark.lint

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_registry_still_covers_the_engine_hot_path():
    """The registry content IS the contract: shrinking it must be loud."""
    spec = next(s for s in HOT_PATHS
                if s.path == "deepspeed_tpu/runtime/engine.py")
    assert spec.cls == "DeepSpeedTPUEngine"
    assert {"train_batch", "stack_microbatches", "_shard_batch",
            "_advance_data_schedules",
            "_ensure_prefetcher"} <= set(spec.hot_functions)
    assert ("_record_metrics", "_async_enabled") in spec.guard_branches
    assert "_drain_metric_ring" in spec.confine[".device_get"]


def test_hot_paths_have_no_host_sync():
    """Lint every registered hot-path file with DS002 only; any finding —
    including registry drift from a rename — fails."""
    paths = sorted({str(REPO / s.path) for s in HOT_PATHS})
    for p in paths:
        assert pathlib.Path(p).exists(), f"registered hot-path file gone: {p}"
    result = lint_paths(paths, root=str(REPO),
                        rules=[HotPathSyncRule()])
    assert not result.findings, (
        "hot path gained host synchronization (or the registry drifted):\n  "
        + "\n  ".join(f.render() for f in result.findings)
        + "\nroute readback through the designated drain, or update "
          "deepspeed_tpu/tools/dslint/hotpath.py alongside a deliberate "
          "refactor")
