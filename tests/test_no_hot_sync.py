"""Static regression gate for the async step pipeline (AST, no jax import
needed): the ``train_batch`` hot path must never regrow a host
synchronization on step outputs — ``float(...)``, ``jax.device_get``, or
``block_until_ready`` belong ONLY in the designated drain
(``_drain_metric_ring``) and in the explicitly host-synchronous paths
(offload step, accessors). A new sync sneaking into the hot path would
silently serialize the pipeline while every timing test keeps passing —
this file is the tripwire.
"""

import ast
import pathlib

ENGINE_PATH = (pathlib.Path(__file__).resolve().parent.parent
               / "deepspeed_tpu" / "runtime" / "engine.py")

# the per-step fused path: everything that runs on EVERY train_batch call
HOT_FUNCS = {
    "train_batch",
    "stack_microbatches",
    "_shard_batch",
    "_advance_data_schedules",
    "_ensure_prefetcher",
}

FORBIDDEN_ATTRS = {"device_get", "block_until_ready", "copy_to_host_async"}


def _engine_class(tree):
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "DeepSpeedTPUEngine":
            return node
    raise AssertionError("DeepSpeedTPUEngine not found in engine.py")


def _methods(cls):
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _forbidden_calls(node):
    bad = []
    for n in ast.walk(node):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if isinstance(f, ast.Name) and f.id == "float":
            bad.append(("float()", n.lineno))
        elif isinstance(f, ast.Attribute) and f.attr in FORBIDDEN_ATTRS:
            bad.append((f.attr, n.lineno))
    return bad


def test_train_batch_hot_path_has_no_host_sync():
    tree = ast.parse(ENGINE_PATH.read_text())
    methods = _methods(_engine_class(tree))
    missing = HOT_FUNCS - set(methods)
    assert not missing, (
        f"hot-path functions renamed/removed: {sorted(missing)} — update "
        "tests/test_no_hot_sync.py alongside the refactor")
    for name in sorted(HOT_FUNCS):
        bad = _forbidden_calls(methods[name])
        assert not bad, (
            f"engine.{name} gained host synchronization {bad}: step-output "
            "readback belongs in _drain_metric_ring (the designated drain), "
            "not the per-step hot path")


def test_deferred_record_branch_has_no_host_sync():
    """The async push branch of ``_record_metrics`` (everything guarded by
    ``_async_enabled``) queues device arrays verbatim — any transfer there
    would re-serialize every step."""
    tree = ast.parse(ENGINE_PATH.read_text())
    methods = _methods(_engine_class(tree))
    rec = methods["_record_metrics"]
    async_branches = [
        n for n in ast.walk(rec)
        if isinstance(n, ast.If)
        and any(isinstance(x, ast.Attribute) and x.attr == "_async_enabled"
                for x in ast.walk(n.test))]
    assert async_branches, "_record_metrics lost its _async_enabled branch"
    for branch in async_branches:
        bad = [b for stmt in branch.body for b in _forbidden_calls(stmt)]
        assert not bad, (
            f"_record_metrics deferred branch gained host sync {bad}")


def test_drain_is_the_designated_device_get():
    """``jax.device_get`` in engine.py stays confined to the drain and the
    explicitly host-synchronous paths — growing the list is a conscious
    decision, not an accident."""
    allowed = {
        "_drain_metric_ring",           # THE drain
        "_offload_host_update",         # host optimizer is synchronous by design
        "_train_batch_param_offload",   # ditto (streamed host step)
        "_host_init_params",            # init-time, not per-step
        "__init__",                     # offload master construction (init)
        "get_lr", "get_global_grad_norm", "cur_scale", "skipped_steps",
        "module_state_dict",            # accessors: sync on request
    }
    tree = ast.parse(ENGINE_PATH.read_text())
    methods = _methods(_engine_class(tree))
    offenders = {}
    for name, node in methods.items():
        hits = [ln for attr, ln in _forbidden_calls(node)
                if attr == "device_get"]
        if hits and name not in allowed:
            offenders[name] = hits
    assert not offenders, (
        f"device_get appeared outside the designated functions: {offenders} "
        "— route readback through the drain or add a deliberate exemption "
        "here with a comment explaining why it cannot lag")
