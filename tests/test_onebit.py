"""1-bit optimizer + compressed collective tests.

Reference analog: tests/unit/onebit/ (convergence of Onebit optimizers vs plain
Adam on small problems; compressed-backend correctness).
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.comm.compressed import (
    compress_local, compressed_allreduce, error_buffer_shapes, pack_signs,
    unpack_signs)
from deepspeed_tpu.ops.onebit import onebit_adam, onebit_lamb, zero_one_adam
from deepspeed_tpu.models.simple import SimpleModel, random_batch


# ---------------------------------------------------------------- packing
def test_pack_unpack_roundtrip():
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (128,))
    bits = (x >= 0).astype(jnp.uint8)
    packed = pack_signs(bits)
    assert packed.shape == (16,) and packed.dtype == jnp.uint8
    signs = unpack_signs(packed, 128)
    np.testing.assert_array_equal(np.asarray(signs), np.where(np.asarray(x) >= 0, 1, -1))


def test_error_feedback_accumulates_to_truth():
    # With error feedback, the running sum of compressed outputs tracks the
    # running sum of inputs (the compression error does not accumulate).
    rng = jax.random.PRNGKey(1)
    x = jax.random.normal(rng, (256,)) * jnp.linspace(0.1, 10, 256)
    err = jnp.zeros_like(x)
    total_out = jnp.zeros_like(x)
    # running-average error decays as O(1/T) — the bounded per-step compression
    # error is carried, not accumulated
    rels = []
    for t in range(1, 201):
        out, err = compress_local(x, err)
        total_out += out
        rels.append(float(jnp.linalg.norm(total_out / t - x) / jnp.linalg.norm(x)))
    assert rels[199] < rels[49] < rels[9]
    assert rels[199] < 0.05, rels[199]


# ---------------------------------------------------------------- collective
def _mesh8():
    return Mesh(np.array(jax.devices()[:8]), ("dp",))


@pytest.mark.slow
def test_compressed_allreduce_approximates_mean():
    mesh = _mesh8()
    w = 8
    n_local, chunk = error_buffer_shapes(512, w)
    rng = jax.random.PRNGKey(2)
    xs = jax.random.normal(rng, (w, n_local))  # one row per worker

    @partial(shard_map, mesh=mesh, in_specs=(P("dp", None), P("dp", None), P("dp", None)),
             out_specs=(P("dp", None), P("dp", None), P("dp", None)))
    def run(x, we, se):
        out, nwe, nse = compressed_allreduce(x[0], we[0], se[0], "dp")
        return out[None], nwe[None], nse[None]

    we = jnp.zeros((w, n_local))
    se = jnp.zeros((w, chunk))
    true_mean = xs.mean(0)
    # iterate: error feedback drives the estimate toward the true mean
    est_sum = jnp.zeros_like(true_mean)
    iters = 30
    for _ in range(iters):
        out, we, se = run(xs, we, se)
        # every worker receives the same full-length result
        np.testing.assert_allclose(np.asarray(out[0]), np.asarray(out[3]), rtol=1e-5)
        est_sum += out[0]
    rel = jnp.linalg.norm(est_sum / iters - true_mean) / jnp.linalg.norm(true_mean)
    assert float(rel) < 0.1, float(rel)


# ---------------------------------------------------------------- optimizers
def _quadratic_problem(d=32, seed=0):
    k = jax.random.PRNGKey(seed)
    target = jax.random.normal(k, (d,))

    def loss(p):
        return jnp.sum((p - target) ** 2)
    return loss, jnp.zeros((d,)), target


def _run_opt(tx, loss, p0, steps):
    state = tx.init(p0)
    p = p0
    for _ in range(steps):
        g = jax.grad(loss)(p)
        upd, state = tx.update(g, state, p)
        p = optax.apply_updates(p, upd)
    return p, state


@pytest.mark.slow
def test_onebit_adam_converges_through_freeze():
    loss, p0, target = _quadratic_problem()
    tx = onebit_adam(0.01, freeze_step=30)
    p, state = _run_opt(tx, loss, p0, 120)
    assert int(state.count) == 120
    assert float(loss(p)) < 0.02 * float(loss(p0))


@pytest.mark.slow
def test_onebit_adam_variance_frozen_after_freeze_step():
    loss, p0, _ = _quadratic_problem()
    tx = onebit_adam(0.05, freeze_step=5)
    state = tx.init(p0)
    p = p0
    for i in range(5):
        g = jax.grad(loss)(p)
        upd, state = tx.update(g, state, p)
        p = optax.apply_updates(p, upd)
    v_at_freeze = np.asarray(state.exp_avg_sq)
    for i in range(10):
        g = jax.grad(loss)(p)
        upd, state = tx.update(g, state, p)
        p = optax.apply_updates(p, upd)
    np.testing.assert_array_equal(np.asarray(state.exp_avg_sq), v_at_freeze)
    # worker error buffers are live (compression active)
    assert float(jnp.abs(state.worker_error).sum()) > 0


def test_onebit_adam_matches_adam_during_warmup():
    loss, p0, _ = _quadratic_problem()
    tx1 = onebit_adam(0.05, freeze_step=1000)
    txa = optax.adam(0.05)
    p1, _ = _run_opt(tx1, loss, p0, 20)
    pa, _ = _run_opt(txa, loss, p0, 20)
    np.testing.assert_allclose(np.asarray(p1), np.asarray(pa), atol=1e-5)


def test_zero_one_adam_variance_refresh_policy():
    loss, p0, _ = _quadratic_problem()
    tx = zero_one_adam(0.01, var_freeze_step=1000, var_update_scaler=2)
    p, state = _run_opt(tx, loss, p0, 40)
    assert float(loss(p)) < 0.1 * float(loss(p0))
    assert int(state.var_interval) > 1  # exponential policy kicked in


def test_zero_one_adam_variance_hard_freeze():
    loss, p0, _ = _quadratic_problem()
    tx = zero_one_adam(0.05, var_freeze_step=3, var_update_scaler=100)
    state = tx.init(p0)
    p = p0
    for _ in range(3):
        g = jax.grad(loss)(p)
        upd, state = tx.update(g, state, p)
        p = optax.apply_updates(p, upd)
    v3 = np.asarray(state.exp_avg_sq)
    for _ in range(10):
        g = jax.grad(loss)(p)
        upd, state = tx.update(g, state, p)
        p = optax.apply_updates(p, upd)
    np.testing.assert_array_equal(np.asarray(state.exp_avg_sq), v3)


@pytest.mark.slow
def test_onebit_lamb_converges_and_freezes_ratio():
    loss, p0, _ = _quadratic_problem()
    p0 = p0 + 1.0  # nonzero params so trust ratio is meaningful
    tx = onebit_lamb(0.01, freeze_step=20)
    p, state = _run_opt(tx, loss, p0, 80)
    assert float(loss(p)) < 0.1 * float(loss(p0 * 0 + p0))
    r_frozen = np.asarray(state.frozen_ratio)
    # frozen ratios stay fixed in compressed stage
    g = jax.grad(loss)(p)
    _, state2 = tx.update(g, state, p)
    np.testing.assert_array_equal(np.asarray(state2.frozen_ratio), r_frozen)


# ---------------------------------------------------------------- engine
def test_engine_with_onebit_adam():
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "OneBitAdam",
                      "params": {"lr": 1e-2, "freeze_step": 3}},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=32), config=config,
        example_batch=random_batch(4))
    fixed = random_batch(8, seed=0)
    losses = [float(engine.train_batch(batch=fixed)) for _ in range(20)]
    assert losses[-1] < 0.2 * losses[0]
