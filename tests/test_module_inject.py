"""AutoTP / module injection tests.

Reference analog: ``tests/unit/model_parallelism/test_autotp_training.py`` and
``tests/unit/inference`` AutoTP cases — policy resolution per arch, fused-qkv
splitting vs per-matrix reference, and TP-sharded forward == unsharded forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import create_mesh, set_global_mesh
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.module_inject import (
    AutoTP,
    ColumnParallelLinear,
    RowParallelLinear,
    TPPolicy,
    get_policy,
    shard_qkv_param,
    split_fused_qkv,
    unfuse_qkv,
)


class _Key:
    def __init__(self, key):
        self.key = key


def _path(s):
    return tuple(_Key(p) for p in s.split("/"))


def test_policy_registry_covers_major_archs():
    for arch in ["llama", "mistral", "mixtral", "qwen2", "phi", "phi3",
                 "falcon", "gpt_neox", "bloom", "gpt2", "gptj", "opt", "bert"]:
        assert get_policy(arch) is not None, arch
    assert get_policy("LlamaForCausalLM").arch == "llama"
    assert get_policy("MixtralForCausalLM").arch == "mixtral"
    assert get_policy("no_such_arch") is None


@pytest.mark.parametrize("arch,col_path,row_path", [
    ("llama", "model/layers_0/self_attn/q_proj/kernel",
     "model/layers_0/self_attn/o_proj/kernel"),
    ("opt", "model/decoder/layers_0/fc1/kernel",
     "model/decoder/layers_0/fc2/kernel"),
    ("falcon", "transformer/h_0/mlp/dense_h_to_4h/kernel",
     "transformer/h_0/mlp/dense_4h_to_h/kernel"),
    ("bert", "encoder/layer_0/attention/self/query/kernel",
     "encoder/layer_0/attention/output/dense/kernel"),
])
def test_policy_rules_col_row(arch, col_path, row_path):
    rules = get_policy(arch).tensor_rules()
    w = np.zeros((8, 8))
    assert rules(_path(col_path), w) == PartitionSpec(None, "tensor")
    assert rules(_path(row_path), w) == PartitionSpec("tensor", None)


def test_policy_rules_vocab_and_bias():
    rules = get_policy("llama").tensor_rules()
    emb = np.zeros((100, 16))
    assert rules(_path("model/embed_tokens/embedding"), emb) == \
        PartitionSpec("tensor", None)
    assert rules(_path("lm_head/kernel"), np.zeros((16, 100))) == \
        PartitionSpec(None, "tensor")
    # column bias sharded, row bias replicated
    assert rules(_path("model/layers_0/self_attn/q_proj/bias"),
                 np.zeros((8,))) == PartitionSpec("tensor")
    assert rules(_path("model/layers_0/self_attn/o_proj/bias"),
                 np.zeros((8,))) is None
    # norms stay replicated
    assert rules(_path("model/norm/scale"), np.zeros((8,))) is None


def test_autotp_generic_fallback_matches_our_model_zoo():
    from deepspeed_tpu.models.llama import TINY_LLAMA, LlamaForCausalLM, random_tokens
    model = LlamaForCausalLM(TINY_LLAMA)
    params = jax.eval_shape(
        lambda r: model.init(r, random_tokens(1, 8, TINY_LLAMA.vocab_size)),
        jax.random.PRNGKey(0))["params"]
    rules = AutoTP.infer_rules(model, params=params)
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    matched = [p for p, leaf in leaves if rules(p, leaf) is not None]
    assert len(matched) >= 7 * TINY_LLAMA.num_layers  # qkv,o,gate,up,down per layer


def test_unfuse_and_split_fused_qkv_concat():
    n_heads, n_kv, hd, d_in = 8, 4, 4, 16
    rng = np.random.default_rng(0)
    q = rng.normal(size=(d_in, n_heads * hd))
    k = rng.normal(size=(d_in, n_kv * hd))
    v = rng.normal(size=(d_in, n_kv * hd))
    fused = np.concatenate([q, k, v], axis=-1)
    uq, uk, uv = unfuse_qkv(fused, n_heads, n_kv, hd)
    np.testing.assert_array_equal(uq, q)
    np.testing.assert_array_equal(uv, v)
    tp = 2
    for r in range(tp):
        shard = split_fused_qkv(fused, n_heads, n_kv, hd, tp, r)
        expect = np.concatenate([
            np.split(q, tp, -1)[r], np.split(k, tp, -1)[r],
            np.split(v, tp, -1)[r]], axis=-1)
        np.testing.assert_array_equal(shard, expect)
    stacked = shard_qkv_param(fused, n_heads, n_kv, hd, tp)
    assert stacked.shape == (tp, d_in, (n_heads + 2 * n_kv) * hd // tp)


def test_split_fused_qkv_interleaved_roundtrip():
    n_heads, hd, d_in = 4, 8, 16
    rng = np.random.default_rng(1)
    per_head = rng.normal(size=(d_in, n_heads, 3, hd))
    fused = per_head.reshape(d_in, n_heads * 3 * hd)
    q, k, v = unfuse_qkv(fused, n_heads, n_heads, hd, layout="interleaved")
    np.testing.assert_array_equal(
        q.reshape(d_in, n_heads, hd), per_head[:, :, 0, :])
    # sharding must PRESERVE the interleaved layout: rank r's shard is exactly
    # the per-head chunk of heads [r*heads/tp, (r+1)*heads/tp)
    tp = 2
    for r in range(tp):
        shard = split_fused_qkv(fused, n_heads, n_heads, hd, tp, r,
                                layout="interleaved")
        expect = per_head[:, r * n_heads // tp:(r + 1) * n_heads // tp] \
            .reshape(d_in, n_heads // tp * 3 * hd)
        np.testing.assert_array_equal(shard, expect)
    with pytest.raises(ValueError):
        unfuse_qkv(fused, n_heads, n_heads // 2, hd, layout="interleaved")


def test_split_fused_qkv_rejects_indivisible_heads():
    with pytest.raises(ValueError):
        split_fused_qkv(np.zeros((4, 3 * 8)), 2, 1, 4, tp_size=4, rank=0)


def test_parallel_layers_match_unsharded():
    mesh = create_mesh(MeshConfig(data=4, tensor=2))
    set_global_mesh(mesh)

    import flax.linen as nn

    class Block(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = ColumnParallelLinear(64, name="up")(x)
            h = nn.relu(h)
            return RowParallelLinear(16, name="down")(h)

    model = Block()
    x = np.random.default_rng(0).normal(size=(8, 16)).astype(np.float32)
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    dense_out = model.apply({"params": params}, x)

    # shard params over the tensor axis via generic AutoTP rules and re-run
    from deepspeed_tpu.runtime.zero.partition import build_param_shardings
    rules = AutoTP.infer_rules(params=params)
    shardings = build_param_shardings(params, mesh, stage=0, tensor_rules=rules)
    sharded = jax.device_put(params, shardings)
    spec = shardings["up"]["col_kernel"].spec
    assert spec[-1] == "tensor"
    with mesh:
        tp_out = jax.jit(lambda p, b: model.apply({"params": p}, b))(sharded, x)
    np.testing.assert_allclose(np.asarray(tp_out), np.asarray(dense_out),
                               rtol=2e-5, atol=2e-5)


def test_init_inference_autotp_llama():
    from deepspeed_tpu.models.llama import TINY_LLAMA, LlamaForCausalLM, random_tokens
    model = LlamaForCausalLM(TINY_LLAMA)
    batch = random_tokens(2, 16, TINY_LLAMA.vocab_size)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    ref_logits = model.apply({"params": params}, batch)

    mesh = create_mesh(MeshConfig(data=4, tensor=2))
    set_global_mesh(mesh)
    engine = deepspeed_tpu.init_inference(
        model, config={"dtype": "fp32", "tensor_parallel": {"tp_size": 2}},
        params=params, mesh=mesh)
    out = engine.forward(batch)
    # TP reduction reordering drifts the sum slightly (same as the reference's
    # NCCL allreduce vs single-GPU)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_logits),
                               rtol=2e-3, atol=2e-3)


HF_PARAM_TREES = {
    # representative HF state-dict shapes: {path: (shape, expected sharded dim)}
    # expected: "col" (last dim sharded), "row" (first dim), None (replicated)
    "gpt_neo": {
        "transformer/h/0/attn/attention/q_proj/kernel": ((64, 64), "col"),
        "transformer/h/0/attn/attention/out_proj/kernel": ((64, 64), "row"),
        "transformer/h/0/mlp/c_fc/kernel": ((64, 256), "col"),
        "transformer/h/0/mlp/c_proj/kernel": ((256, 64), "row"),
        "transformer/wte/embedding": ((1000, 64), "row"),
        "transformer/h/0/ln_1/scale": ((64,), None),
    },
    "gpt_bigcode": {
        "transformer/h/0/attn/c_attn/kernel": ((64, 80), "col"),   # fused MQA
        "transformer/h/0/attn/c_proj/kernel": ((64, 64), "row"),
        "transformer/h/0/mlp/c_fc/kernel": ((64, 256), "col"),
    },
    "t5": {
        "encoder/block/0/layer/0/SelfAttention/q/kernel": ((64, 64), "col"),
        "encoder/block/0/layer/0/SelfAttention/o/kernel": ((64, 64), "row"),
        "encoder/block/0/layer/1/DenseReluDense/wi_0/kernel": ((64, 256), "col"),
        "encoder/block/0/layer/1/DenseReluDense/wo/kernel": ((256, 64), "row"),
        "shared/embedding": ((1000, 64), "row"),
    },
    "chatglm": {
        "transformer/layers/0/self_attention/query_key_value/kernel":
            ((64, 192), "col"),
        "transformer/layers/0/self_attention/dense/kernel": ((64, 64), "row"),
        "transformer/layers/0/mlp/dense_h_to_4h/kernel": ((64, 256), "col"),
        "transformer/layers/0/mlp/dense_4h_to_h/kernel": ((256, 64), "row"),
    },
    "whisper": {
        "model/encoder/layers/0/self_attn/q_proj/kernel": ((64, 64), "col"),
        "model/encoder/layers/0/self_attn/out_proj/kernel": ((64, 64), "row"),
        "model/encoder/layers/0/fc1/kernel": ((64, 256), "col"),
        "model/encoder/layers/0/fc2/kernel": ((256, 64), "row"),
    },
}


@pytest.mark.parametrize("arch", sorted(HF_PARAM_TREES))
def test_policy_breadth_hf_param_trees(arch):
    """AutoTP policies map real HF-style parameter paths of the broader model
    zoo (reference: module_inject/containers/ per-arch coverage)."""
    from jax.sharding import PartitionSpec
    policy = get_policy(arch)
    assert policy is not None, arch
    rules = policy.tensor_rules()

    class K:  # minimal DictKey stand-in
        def __init__(self, key):
            self.key = key

    for path, (shape, expected) in HF_PARAM_TREES[arch].items():
        spec = rules([K(p) for p in path.split("/")], np.zeros(shape))
        if expected is None:
            assert spec is None or all(s is None for s in spec), (path, spec)
        elif expected == "col":
            assert spec is not None and spec[-1] == "tensor", (path, spec)
        elif expected == "row":
            assert spec is not None and spec[0] == "tensor", (path, spec)


def test_policy_alias_lookup_breadth():
    for alias, canon in [("GPTNeoForCausalLM", "gpt_neo"),
                         ("starcoder", "gpt_bigcode"),
                         ("T5ForConditionalGeneration", "t5"),
                         ("WhisperForConditionalGeneration", "whisper"),
                         ("Gemma2ForCausalLM", "gemma"),
                         ("CLIPTextModel", "clip"),
                         ("megatron", "megatron_gpt")]:
        p = get_policy(alias)
        assert p is not None and p.arch == canon, (alias, p)


def test_diffusion_policies_unet_vae():
    """UNet/VAE containers (reference module_inject/containers/{unet,vae}.py):
    attention projections shard, convs replicate."""
    for arch, cls in [("unet", "UNet2DConditionModel"), ("vae", "AutoencoderKL")]:
        pol = get_policy(arch)
        assert pol is not None and get_policy(cls) is pol
    rules = get_policy("unet").tensor_rules()
    w = np.zeros((64, 64))
    assert rules(_path("down_blocks_0/attentions_0/transformer_blocks_0/attn1/to_q/kernel"), w) \
        == PartitionSpec(None, "tensor")
    assert rules(_path("down_blocks_0/attentions_0/transformer_blocks_0/attn1/to_out/0/kernel"), w) \
        == PartitionSpec("tensor", None)
    # convs replicate (no rule)
    assert rules(_path("down_blocks_0/resnets_0/conv1/kernel"), np.zeros((3, 3, 8, 8))) is None
