"""FastGen-equivalent engine tests: allocator, scheduler, paged decode vs full
forward, continuous batching.

Reference analog: tests/unit/inference/v2/{ragged,model_implementations}.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.v2.blocked_allocator import BlockedAllocator
from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2, V2EngineConfig
from deepspeed_tpu.inference.v2.scheduler import SchedulerConfig, plan_step, snap_bucket
from deepspeed_tpu.inference.v2.ragged_manager import StateManager
from deepspeed_tpu.models.llama import (
    LlamaConfig, LlamaForCausalLM, random_tokens, TINY_LLAMA)


def test_allocator_roundtrip():
    a = BlockedAllocator(8)
    blocks = a.allocate(5)
    assert len(set(blocks)) == 5 and a.free_blocks == 3
    a.free(blocks[:2])
    assert a.free_blocks == 5
    with pytest.raises(ValueError):
        a.allocate(6)
    more = a.allocate(5)
    assert a.free_blocks == 0
    assert len(set(more) | set(blocks[2:])) == 8


def test_allocator_invalid_free():
    a = BlockedAllocator(4)
    with pytest.raises(ValueError):
        a.free([9])


def test_scheduler_splitfuse():
    sm = StateManager()
    long_seq = sm.create(1, np.arange(5000) % 100)
    dec = sm.create(2, [1, 2, 3])
    dec.seen_tokens = 3
    dec.generated.append(7)
    cfg = SchedulerConfig(max_tokens_per_step=2048, prefill_buckets=(128, 512, 2048))
    plan = plan_step(sm.decoding(), sm.prefilling(), cfg)
    assert [s.uid for s in plan.decode_seqs] == [2]
    assert len(plan.prefill_chunks) == 1
    chunk = plan.prefill_chunks[0]
    assert chunk.length == 2047  # budget minus 1 decode token
    assert chunk.bucket == 2048


def test_snap_bucket():
    assert snap_bucket(3, (4, 8)) == 4
    assert snap_bucket(9, (4, 8)) == 8  # clamps to max


def _tiny_fp32():
    return LlamaConfig(**{**TINY_LLAMA.__dict__, "dtype": jnp.float32,
                          "max_seq_len": 512})


@pytest.fixture(scope="module")
def model_and_params():
    cfg = _tiny_fp32()
    model = LlamaForCausalLM(cfg)
    batch = random_tokens(1, 8, vocab_size=cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    return cfg, model, params


@pytest.mark.slow
def test_paged_forward_matches_full(model_and_params):
    """Greedy generation via paged prefill+decode == argmax chain of the training
    model's full forward."""
    cfg, model, params = model_and_params
    prompt = list(np.random.default_rng(0).integers(0, cfg.vocab_size, 12))

    engine = InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=64,
        scheduler=SchedulerConfig(max_tokens_per_step=64,
                                  prefill_buckets=(16, 32, 64))))
    generated = engine.generate(prompt, max_new_tokens=5)

    # reference: iterative full-forward argmax
    ids = list(prompt)
    for _ in range(5):
        logits = model.apply({"params": params},
                             {"input_ids": np.asarray([ids], np.int32)},
                             method=LlamaForCausalLM.logits)
        ids.append(int(np.argmax(np.asarray(logits)[0, -1])))
    assert generated == ids[len(prompt):]


def test_chunked_prefill_matches_single_shot(model_and_params):
    """A prompt prefix processed in multiple SplitFuse chunks produces the same
    next token as one-shot prefill."""
    cfg, model, params = model_and_params
    prompt = list(np.random.default_rng(1).integers(0, cfg.vocab_size, 40))

    small = InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=64,
        scheduler=SchedulerConfig(max_tokens_per_step=16, prefill_buckets=(16,))))
    big = InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=64,
        scheduler=SchedulerConfig(max_tokens_per_step=64, prefill_buckets=(64,))))
    t_small = small.generate(prompt, max_new_tokens=3)
    t_big = big.generate(prompt, max_new_tokens=3)
    assert t_small == t_big


def test_continuous_batching_two_sequences(model_and_params):
    """Two sequences served concurrently produce the same tokens as served alone."""
    cfg, model, params = model_and_params
    rng = np.random.default_rng(2)
    p1 = list(rng.integers(0, cfg.vocab_size, 10))
    p2 = list(rng.integers(0, cfg.vocab_size, 17))

    solo1 = InferenceEngineV2(params, cfg).generate(p1, max_new_tokens=4, uid=0)
    solo2 = InferenceEngineV2(params, cfg).generate(p2, max_new_tokens=4, uid=0)

    eng = InferenceEngineV2(params, cfg)
    eng.put([10, 20], [p1, p2])
    for _ in range(10):
        eng.step()
        if len(eng.state.get(10).generated) >= 4 and \
           len(eng.state.get(20).generated) >= 4:
            break
    g1 = eng.flush(10)[:4]
    g2 = eng.flush(20)[:4]
    assert g1 == solo1[:4]
    assert g2 == solo2[:4]
    # all blocks returned
    assert eng.kv.free_blocks == eng.kv.allocator.total_blocks


def test_admission_control(model_and_params):
    cfg, model, params = model_and_params
    eng = InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=5))  # 4 usable blocks = 64 tokens
    assert eng.can_schedule([1], [32])
    assert not eng.can_schedule([1], [1000])
    with pytest.raises(RuntimeError):
        eng.put([1], [list(range(100))])


def test_paged_kernel_matches_gather_decode(model_and_params):
    """The Pallas paged-attention decode path (interpret mode) produces the same
    logits as the gather reference path."""
    from deepspeed_tpu.inference.v2.kv_cache import BlockedKVCache, KVCacheConfig
    from deepspeed_tpu.inference.v2.llama_decode import decode_step, prefill_chunk
    cfg, model, params = model_and_params
    kv = BlockedKVCache(KVCacheConfig(
        num_layers=cfg.num_layers, num_kv_heads=cfg.num_kv_heads,
        head_dim=cfg.head_dim_, block_size=16, num_blocks=32,
        dtype=jnp.float32))
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, cfg.vocab_size, 20)
    table = np.array([0, 1, 2, 3], np.int32)
    tokens = np.zeros(32, np.int32)
    tokens[:20] = prompt
    logits_g, cache_g = prefill_chunk(
        params, kv.data, jnp.asarray(tokens), 0, jnp.asarray(table), 20,
        cfg=cfg, block_size=16, attn_impl="gather")
    logits_k, cache_k = prefill_chunk(
        params, kv.data, jnp.asarray(tokens), 0, jnp.asarray(table), 20,
        cfg=cfg, block_size=16, attn_impl="kernel_interpret")
    np.testing.assert_allclose(np.asarray(logits_k), np.asarray(logits_g),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(cache_k), np.asarray(cache_g),
                               atol=1e-5, rtol=1e-5)

    dtok = jnp.asarray([int(np.argmax(np.asarray(logits_g))), 0], jnp.int32)
    dpos = jnp.asarray([20, 0], jnp.int32)
    tables = jnp.asarray([[0, 1, 2, 3], [31, 31, 31, 31]], jnp.int32)
    valid = jnp.asarray([True, False])
    out_g, _ = decode_step(params, cache_g, dtok, dpos, tables, valid,
                           cfg=cfg, block_size=16, attn_impl="gather")
    out_k, _ = decode_step(params, cache_g, dtok, dpos, tables, valid,
                           cfg=cfg, block_size=16, attn_impl="kernel_interpret")
    np.testing.assert_allclose(np.asarray(out_k)[0], np.asarray(out_g)[0],
                               atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_fp8_kv_cache_pages(model_and_params):
    """kv_cache_dtype='fp8': float8_e4m3 pages (half the KV memory of
    bf16 — 2x capacity),
    dequantized on load in both attention paths; greedy generation stays
    close to full-precision KV (identical on this model) and the pool
    really allocates fp8."""
    cfg, model, params = model_and_params
    prompt = [int(t)
              for t in np.random.default_rng(3).integers(0, cfg.vocab_size,
                                                         20)]

    def make(kvd):
        return InferenceEngineV2(params, cfg, V2EngineConfig(
            kv_block_size=16, kv_num_blocks=64,
            scheduler=SchedulerConfig(max_tokens_per_step=64,
                                      prefill_buckets=(16, 32, 64)),
            kv_cache_dtype=kvd))

    e8 = make("fp8")
    assert e8.kv.data.dtype == jnp.float8_e4m3fn
    g_full = make("model").generate(prompt, max_new_tokens=8)
    g_fp8 = e8.generate(prompt, max_new_tokens=8)
    # fp8 rounding can flip a late token on near-ties; the prefix must hold
    assert g_fp8[:4] == g_full[:4], (g_fp8, g_full)
