"""FastGen-equivalent engine tests: allocator, scheduler, paged decode vs full
forward, continuous batching.

Reference analog: tests/unit/inference/v2/{ragged,model_implementations}.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.v2.blocked_allocator import BlockedAllocator
from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2, V2EngineConfig
from deepspeed_tpu.inference.v2.scheduler import SchedulerConfig, plan_step, snap_bucket
from deepspeed_tpu.inference.v2.ragged_manager import StateManager
from deepspeed_tpu.models.llama import (
    LlamaConfig, LlamaForCausalLM, random_tokens, TINY_LLAMA)


def test_allocator_roundtrip():
    a = BlockedAllocator(8)
    blocks = a.allocate(5)
    assert len(set(blocks)) == 5 and a.free_blocks == 3
    a.free(blocks[:2])
    assert a.free_blocks == 5
    with pytest.raises(ValueError):
        a.allocate(6)
    more = a.allocate(5)
    assert a.free_blocks == 0
    assert len(set(more) | set(blocks[2:])) == 8


def test_allocator_invalid_free():
    a = BlockedAllocator(4)
    with pytest.raises(ValueError):
        a.free([9])


def test_scheduler_splitfuse():
    sm = StateManager()
    long_seq = sm.create(1, np.arange(5000) % 100)
    dec = sm.create(2, [1, 2, 3])
    dec.seen_tokens = 3
    dec.generated.append(7)
    cfg = SchedulerConfig(max_tokens_per_step=2048, prefill_buckets=(128, 512, 2048))
    plan = plan_step(sm.decoding(), sm.prefilling(), cfg)
    assert [s.uid for s in plan.decode_seqs] == [2]
    assert len(plan.prefill_chunks) == 1
    chunk = plan.prefill_chunks[0]
    assert chunk.length == 2047  # budget minus 1 decode token
    assert chunk.bucket == 2048


def test_snap_bucket():
    assert snap_bucket(3, (4, 8)) == 4
    assert snap_bucket(9, (4, 8)) == 8  # clamps to max


def _tiny_fp32():
    return LlamaConfig(**{**TINY_LLAMA.__dict__, "dtype": jnp.float32,
                          "max_seq_len": 512})


@pytest.fixture(scope="module")
def model_and_params():
    cfg = _tiny_fp32()
    model = LlamaForCausalLM(cfg)
    batch = random_tokens(1, 8, vocab_size=cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    return cfg, model, params


@pytest.mark.slow
def test_paged_forward_matches_full(model_and_params):
    """Greedy generation via paged prefill+decode == argmax chain of the training
    model's full forward."""
    cfg, model, params = model_and_params
    prompt = list(np.random.default_rng(0).integers(0, cfg.vocab_size, 12))

    engine = InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=64,
        scheduler=SchedulerConfig(max_tokens_per_step=64,
                                  prefill_buckets=(16, 32, 64))))
    generated = engine.generate(prompt, max_new_tokens=5)

    # reference: iterative full-forward argmax
    ids = list(prompt)
    for _ in range(5):
        logits = model.apply({"params": params},
                             {"input_ids": np.asarray([ids], np.int32)},
                             method=LlamaForCausalLM.logits)
        ids.append(int(np.argmax(np.asarray(logits)[0, -1])))
    assert generated == ids[len(prompt):]


def test_chunked_prefill_matches_single_shot(model_and_params):
    """A prompt prefix processed in multiple SplitFuse chunks produces the same
    next token as one-shot prefill."""
    cfg, model, params = model_and_params
    prompt = list(np.random.default_rng(1).integers(0, cfg.vocab_size, 40))

    small = InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=64,
        scheduler=SchedulerConfig(max_tokens_per_step=16, prefill_buckets=(16,))))
    big = InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=64,
        scheduler=SchedulerConfig(max_tokens_per_step=64, prefill_buckets=(64,))))
    t_small = small.generate(prompt, max_new_tokens=3)
    t_big = big.generate(prompt, max_new_tokens=3)
    assert t_small == t_big


def test_continuous_batching_two_sequences(model_and_params):
    """Two sequences served concurrently produce the same tokens as served alone."""
    cfg, model, params = model_and_params
    rng = np.random.default_rng(2)
    p1 = list(rng.integers(0, cfg.vocab_size, 10))
    p2 = list(rng.integers(0, cfg.vocab_size, 17))

    solo1 = InferenceEngineV2(params, cfg).generate(p1, max_new_tokens=4, uid=0)
    solo2 = InferenceEngineV2(params, cfg).generate(p2, max_new_tokens=4, uid=0)

    eng = InferenceEngineV2(params, cfg)
    eng.put([10, 20], [p1, p2])
    for _ in range(10):
        eng.step()
        if len(eng.state.get(10).generated) >= 4 and \
           len(eng.state.get(20).generated) >= 4:
            break
    g1 = eng.flush(10)[:4]
    g2 = eng.flush(20)[:4]
    assert g1 == solo1[:4]
    assert g2 == solo2[:4]
    # all blocks returned
    assert eng.kv.free_blocks == eng.kv.allocator.total_blocks


def test_admission_control(model_and_params):
    cfg, model, params = model_and_params
    eng = InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=5))  # 4 usable blocks = 64 tokens
    assert eng.can_schedule([1], [32])
    assert not eng.can_schedule([1], [1000])
    with pytest.raises(RuntimeError):
        eng.put([1], [list(range(100))])


def test_paged_kernel_matches_gather_decode(model_and_params):
    """The Pallas paged-attention decode path (interpret mode) produces the same
    logits as the gather reference path."""
    from deepspeed_tpu.inference.v2.kv_cache import BlockedKVCache, KVCacheConfig
    from deepspeed_tpu.inference.v2.llama_decode import decode_step, prefill_chunk
    cfg, model, params = model_and_params
    kv = BlockedKVCache(KVCacheConfig(
        num_layers=cfg.num_layers, num_kv_heads=cfg.num_kv_heads,
        head_dim=cfg.head_dim_, block_size=16, num_blocks=32,
        dtype=jnp.float32))
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, cfg.vocab_size, 20)
    table = np.array([0, 1, 2, 3], np.int32)
    tokens = np.zeros(32, np.int32)
    tokens[:20] = prompt
    logits_g, cache_g = prefill_chunk(
        params, kv.data, jnp.asarray(tokens), 0, jnp.asarray(table), 20,
        cfg=cfg, block_size=16, attn_impl="gather")
    logits_k, cache_k = prefill_chunk(
        params, kv.data, jnp.asarray(tokens), 0, jnp.asarray(table), 20,
        cfg=cfg, block_size=16, attn_impl="kernel_interpret")
    np.testing.assert_allclose(np.asarray(logits_k), np.asarray(logits_g),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(cache_k), np.asarray(cache_g),
                               atol=1e-5, rtol=1e-5)

    dtok = jnp.asarray([int(np.argmax(np.asarray(logits_g))), 0], jnp.int32)
    dpos = jnp.asarray([20, 0], jnp.int32)
    tables = jnp.asarray([[0, 1, 2, 3], [31, 31, 31, 31]], jnp.int32)
    valid = jnp.asarray([True, False])
    out_g, _ = decode_step(params, cache_g, dtok, dpos, tables, valid,
                           cfg=cfg, block_size=16, attn_impl="gather")
    out_k, _ = decode_step(params, cache_g, dtok, dpos, tables, valid,
                           cfg=cfg, block_size=16, attn_impl="kernel_interpret")
    np.testing.assert_allclose(np.asarray(out_k)[0], np.asarray(out_g)[0],
                               atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_fp8_kv_cache_pages(model_and_params):
    """kv_cache_dtype='fp8': float8_e4m3 pages (half the KV memory of
    bf16 — 2x capacity),
    dequantized on load in both attention paths; greedy generation stays
    close to full-precision KV (identical on this model) and the pool
    really allocates fp8."""
    cfg, model, params = model_and_params
    prompt = [int(t)
              for t in np.random.default_rng(3).integers(0, cfg.vocab_size,
                                                         20)]

    def make(kvd):
        return InferenceEngineV2(params, cfg, V2EngineConfig(
            kv_block_size=16, kv_num_blocks=64,
            scheduler=SchedulerConfig(max_tokens_per_step=64,
                                      prefill_buckets=(16, 32, 64)),
            kv_cache_dtype=kvd))

    e8 = make("fp8")
    assert e8.kv.data.dtype == jnp.float8_e4m3fn
    g_full = make("model").generate(prompt, max_new_tokens=8)
    g_fp8 = e8.generate(prompt, max_new_tokens=8)
    # fp8 rounding can flip a late token on near-ties; the prefix must hold
    assert g_fp8[:4] == g_full[:4], (g_fp8, g_full)


def test_fp8_scaled_pages_outlier_accuracy():
    """Per-(head, page) scales keep fp8 pages accurate under outlier K/V
    magnitudes that the old scaleless clamp saturates (reference analog:
    group-scaled fp quantizer, csrc/fp_quantizer/fp_quantize.cu). Covers the
    write path (write_kv_scaled grow+requantize), the gather read path, and
    the Pallas kernel's scalar-prefetch scale indexing (interpret mode)."""
    from deepspeed_tpu.inference.v2.kv_cache import (cast_to_page_dtype,
                                                     write_kv_scaled)
    from deepspeed_tpu.ops.pallas.paged_attention import (
        paged_attention, paged_attention_reference)
    rng = np.random.default_rng(0)
    hkv, nb, bs, d, rep = 2, 8, 16, 32, 2
    t = 64                                       # context length (4 pages)
    k_ctx = rng.normal(size=(t, hkv, d)).astype(np.float32)
    v_ctx = rng.normal(size=(t, hkv, d)).astype(np.float32)
    k_ctx[10, 0] *= 2000.0                       # far beyond e4m3's 448
    v_ctx[33, 1] *= 1500.0
    block_ids = jnp.asarray(np.arange(t) // bs)
    offsets = jnp.asarray(np.arange(t) % bs)
    q = jnp.asarray(rng.normal(size=(1, 1, hkv * rep, d)), jnp.float32)
    tables = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    start = jnp.asarray([t - 1], jnp.int32)

    # oracle: exact f32 pages
    f32p = jnp.zeros((2, hkv, nb, bs, d), jnp.float32)
    f32p = f32p.at[0, :, block_ids, offsets].set(jnp.asarray(k_ctx))
    f32p = f32p.at[1, :, block_ids, offsets].set(jnp.asarray(v_ctx))
    oracle = paged_attention_reference(q, f32p[0], f32p[1], tables, start)

    # scaled fp8 via the real write path (two calls exercise regrowth too)
    data = jnp.zeros((1, 2, hkv, nb, bs, d), jnp.float8_e4m3fn)
    scales = jnp.ones((1, 2, hkv, nb), jnp.float32)
    half = t // 2
    for kv, ctx in ((0, k_ctx), (1, v_ctx)):
        data, scales = write_kv_scaled(
            data, scales, 0, kv, jnp.asarray(ctx[:half]), block_ids[:half],
            offsets[:half], jnp.asarray([0, 1]))
        data, scales = write_kv_scaled(
            data, scales, 0, kv, jnp.asarray(ctx[half:]), block_ids[half:],
            offsets[half:], jnp.asarray([2, 3]))
    assert float(scales[0, 0, 0, 0]) > 1.0       # k outlier page grew
    assert float(scales[0, 1, 1, 2]) > 1.0       # v outlier page grew
    out_scaled = paged_attention_reference(
        q, data[0, 0], data[0, 1], tables, start,
        k_scales=scales[0, 0], v_scales=scales[0, 1])

    # old scaleless clamp
    datac = jnp.zeros((2, hkv, nb, bs, d), jnp.float8_e4m3fn)
    datac = datac.at[0, :, block_ids, offsets].set(
        cast_to_page_dtype(jnp.asarray(k_ctx), jnp.float8_e4m3fn))
    datac = datac.at[1, :, block_ids, offsets].set(
        cast_to_page_dtype(jnp.asarray(v_ctx), jnp.float8_e4m3fn))
    out_clamp = paged_attention_reference(q, datac[0], datac[1], tables, start)

    denom = float(jnp.max(jnp.abs(oracle)))
    err_scaled = float(jnp.max(jnp.abs(out_scaled - oracle))) / denom
    err_clamp = float(jnp.max(jnp.abs(out_clamp - oracle))) / denom
    assert err_scaled < 0.08, (err_scaled, err_clamp)
    assert err_clamp > 4 * err_scaled, (err_scaled, err_clamp)

    # Pallas kernel (interpret) with the scale prefetch == gather with scales
    out_kernel = paged_attention(
        q, data[0, 0], data[0, 1], tables, start,
        k_scales=scales[0, 0], v_scales=scales[0, 1], interpret=True)
    np.testing.assert_allclose(np.asarray(out_kernel), np.asarray(out_scaled),
                               atol=1e-5, rtol=1e-4)


@pytest.mark.slow
def test_fp8_scaled_prefill_logit_error_bound(model_and_params):
    """64+-token prefill with outlier-inflated K/V projections: scaled fp8
    pages keep the last-token logits within a tight bound of the f32-cache
    logits (the scaleless clamp would saturate every K/V row of layer 0)."""
    from deepspeed_tpu.inference.v2.generic_decode import prefill_chunk_g
    from deepspeed_tpu.inference.v2.kv_cache import BlockedKVCache, KVCacheConfig
    from deepspeed_tpu.inference.v2.modules import LlamaPolicy
    cfg, model, params = model_and_params
    big = jax.tree.map(lambda x: x, params)      # shallow rebuild
    for w in ("wk", "wv"):
        big["model"]["layer_0"]["attn"][w] = jax.tree.map(
            lambda x: x * 30.0, big["model"]["layer_0"]["attn"][w])

    rngp = np.random.default_rng(7)
    tokens = np.zeros(128, np.int32)
    tokens[:80] = rngp.integers(0, cfg.vocab_size, 80)
    table = jnp.asarray(np.arange(8), jnp.int32)

    def run(dtype):
        kv = BlockedKVCache(KVCacheConfig(
            num_layers=cfg.num_layers, num_kv_heads=cfg.num_kv_heads,
            head_dim=cfg.head_dim_, block_size=16, num_blocks=32,
            dtype=dtype))
        cache = kv.data if kv.scales is None else (kv.data, kv.scales)
        logits, _ = prefill_chunk_g(
            big, cache, jnp.asarray(tokens), 0, table, 80,
            policy=LlamaPolicy, cfg=cfg, block_size=16, attn_impl="gather")
        return np.asarray(logits)

    exact = run(jnp.float32)
    fp8 = run(jnp.float8_e4m3fn)
    err = float(np.max(np.abs(fp8 - exact)))
    spread = float(np.max(exact) - np.min(exact))
    assert err < 0.05 * spread, (err, spread)


def test_speculative_decode_fast_oracle(model_and_params):
    """Fast stand-in: oracle proposals are fully accepted and the emitted
    chain is exactly the plain greedy chain (full hit/miss/lookup matrix in
    the slow test below)."""
    cfg, model, params = model_and_params
    mk = lambda k: InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=64,
        scheduler=SchedulerConfig(max_tokens_per_step=64,
                                  prefill_buckets=(16, 32, 64)),
        speculative_k=k))
    prompt = list(np.random.default_rng(11).integers(0, cfg.vocab_size, 16))
    plain = mk(0).generate(prompt, max_new_tokens=8)
    eng = mk(4)
    eng._propose = lambda seq: plain[len(seq.generated):
                                     len(seq.generated) + 4]
    spec = eng.generate(prompt, max_new_tokens=8)
    assert spec == plain, (spec, plain)
    st = eng.speculative_stats()
    assert st["accepted"] == st["proposed"] > 0 and st["tokens_per_step"] > 2


def test_speculative_with_sampling_rejected_at_construction(model_and_params):
    """speculative_k + sampling must fail BEFORE any sequence state exists —
    failing inside the step would leave a half-processed sequence whose
    prefill already consumed KV blocks (round-4 advisor finding)."""
    cfg, _, params = model_and_params
    with pytest.raises(ValueError, match="greedy"):
        InferenceEngineV2(params, cfg, V2EngineConfig(
            greedy=False, speculative_k=4))


@pytest.mark.slow
def test_speculative_decode_exact_greedy_equivalence(model_and_params):
    """Speculative decoding (speculative_k>0): generation is EXACTLY the
    plain greedy chain whether proposals all hit (oracle), all miss
    (adversarial), or come from real prompt-lookup. Beyond-reference:
    FastGen has no speculative decoding."""
    cfg, model, params = model_and_params

    def make(spec_k):
        return InferenceEngineV2(params, cfg, V2EngineConfig(
            kv_block_size=16, kv_num_blocks=64,
            scheduler=SchedulerConfig(max_tokens_per_step=64,
                                      prefill_buckets=(16, 32, 64)),
            speculative_k=spec_k))

    prompt = list(np.random.default_rng(11).integers(0, cfg.vocab_size, 20))
    plain = make(0).generate(prompt, max_new_tokens=24)

    # oracle proposals (the true continuation): every proposal accepted,
    # ~k+1 tokens per verify step, output identical
    eng = make(4)
    eng._propose = lambda seq: plain[len(seq.generated):
                                     len(seq.generated) + 4]
    spec = eng.generate(prompt, max_new_tokens=24)
    assert spec[:len(plain)] == plain, (spec, plain)
    stats = eng.speculative_stats()
    assert stats["accepted"] == stats["proposed"] > 0, stats
    assert stats["tokens_per_step"] > 2.0, stats

    # adversarial proposals (always wrong): every proposal rejected, the
    # bonus/corrected token keeps the chain exact
    eng_bad = make(4)
    eng_bad._propose = lambda seq: [
        (plain[len(seq.generated) + i] + 1 + i) % cfg.vocab_size
        if len(seq.generated) + i < len(plain) else 1 for i in range(4)]
    spec_bad = eng_bad.generate(prompt, max_new_tokens=24)
    assert spec_bad[:len(plain)] == plain, (spec_bad, plain)
    assert eng_bad.speculative_stats()["accepted"] == 0

    # real prompt-lookup path end-to-end (proposals may or may not hit on a
    # random model — output must stay exact either way)
    spec_real = make(4).generate(prompt, max_new_tokens=24)
    assert spec_real[:len(plain)] == plain, (spec_real, plain)

    # sampling configs refuse AT CONSTRUCTION (acceptance compares argmax
    # chains; a step-time failure would leak a half-processed sequence)
    with pytest.raises(ValueError, match="greedy"):
        InferenceEngineV2(params, cfg, V2EngineConfig(
            kv_block_size=16, kv_num_blocks=64, greedy=False,
            speculative_k=4))


def test_speculative_propose_prompt_lookup(model_and_params):
    """_propose finds the continuation of the most recent earlier occurrence
    of the trailing n-gram (prompt-lookup decoding)."""
    cfg, model, params = model_and_params
    eng = InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=64, speculative_k=4,
        speculative_ngram=3))
    from deepspeed_tpu.inference.v2.ragged_manager import SequenceDescriptor
    seq = SequenceDescriptor(
        uid=0, prompt_tokens=np.asarray(
            [5, 6, 7, 9, 9, 1, 2, 3, 8, 8, 8, 8, 1, 2, 3], np.int32))
    # tail [1, 2, 3] occurred at index 5; continuation is [8, 8, 8, 8]
    assert eng._propose(seq) == [8, 8, 8, 8]
    # generated tokens extend the lookup context
    seq2 = SequenceDescriptor(
        uid=1, prompt_tokens=np.asarray([4, 1, 2, 3, 7, 7], np.int32))
    seq2.generated = [1, 2, 3]
    assert eng._propose(seq2) == [7, 7, 1, 2]     # continuation at index 1
    # no earlier occurrence -> no proposal
    seq3 = SequenceDescriptor(
        uid=2, prompt_tokens=np.asarray([1, 2, 3, 4, 5, 6], np.int32))
    assert eng._propose(seq3) == []


def test_speculative_decode_with_fp8_kv(model_and_params):
    """Speculation composes with scaled fp8 pages (the verifier chunk runs
    the scaled write path); greedy prefix still matches plain fp8 decode."""
    cfg, model, params = model_and_params
    base = list(np.random.default_rng(13).integers(0, cfg.vocab_size, 5))
    prompt = base * 4

    def make(spec_k):
        return InferenceEngineV2(params, cfg, V2EngineConfig(
            kv_block_size=16, kv_num_blocks=64,
            scheduler=SchedulerConfig(max_tokens_per_step=64,
                                      prefill_buckets=(16, 32, 64)),
            kv_cache_dtype="fp8", speculative_k=spec_k))

    plain = make(0).generate(prompt, max_new_tokens=12)
    spec = make(4).generate(prompt, max_new_tokens=12)
    assert spec[:4] == plain[:4], (spec, plain)   # fp8 near-tie tolerance


def test_fp8_scaled_cache_tuple_fast(model_and_params):
    """Fast stand-in: the (pages, scales) tuple cache flows through
    prefill_chunk_g — fp8 pool stays fp8, scales array round-trips, logits
    finite (the 80-token logit-error bound lives in the slow test)."""
    from deepspeed_tpu.inference.v2.generic_decode import prefill_chunk_g
    from deepspeed_tpu.inference.v2.kv_cache import BlockedKVCache, KVCacheConfig
    from deepspeed_tpu.inference.v2.modules import LlamaPolicy
    cfg, model, params = model_and_params
    kv = BlockedKVCache(KVCacheConfig(
        num_layers=cfg.num_layers, num_kv_heads=cfg.num_kv_heads,
        head_dim=cfg.head_dim_, block_size=16, num_blocks=16,
        dtype=jnp.float8_e4m3fn))
    assert kv.scales is not None
    tokens = np.zeros(16, np.int32)
    tokens[:10] = np.random.default_rng(2).integers(0, cfg.vocab_size, 10)
    logits, (data, scales) = prefill_chunk_g(
        params, (kv.data, kv.scales), jnp.asarray(tokens), 0,
        jnp.asarray(np.arange(4), np.int32), 10, policy=LlamaPolicy,
        cfg=cfg, block_size=16, attn_impl="gather")
    assert np.isfinite(np.asarray(logits)).all()
    assert data.dtype == jnp.float8_e4m3fn
    assert scales.shape == kv.scales.shape and bool((scales >= 1.0).all())
