"""comm/compress — quantized error-feedback collectives + bucketed overlap.

Proof discipline (ROADMAP): deterministic wire-byte counters and
parity-vs-fp32 numerics pins, never CPU wall-clock A/B. The acceptance
assertions here are EXACT: recorded counters equal the analytic wire model,
and the logical/wire ratio clears 3.5x on every exercised mesh axis.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.comm import compress
from deepspeed_tpu.comm.comm import (quantized_all_reduce,
                                     quantized_reduce_scatter)
from deepspeed_tpu.comm.comms_logging import (calc_bw, canonical_op_kind,
                                              get_comms_logger)
from deepspeed_tpu.comm.mesh import create_mesh
from deepspeed_tpu.config.config import DeepSpeedTPUConfig, MeshConfig
from deepspeed_tpu.models.simple import SimpleModel, random_batch
from deepspeed_tpu.telemetry.tracer import COMM_OVERLAP_TID, get_tracer

pytestmark = pytest.mark.comm_compress

CFG = {
    "train_batch_size": 8,
    "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
    "zero_optimization": {"stage": 3},
}


@pytest.fixture
def comms():
    """Comms logger enabled + reset for one test, restored after."""
    cl = get_comms_logger()
    was = cl.enabled
    cl.reset()
    cl.configure(enabled=True)
    try:
        yield cl
    finally:
        cl.reset()
        cl.configure(enabled=was)


@pytest.fixture
def tracing():
    t = get_tracer()
    t.clear()
    t.detach_sink()
    t.configure(enabled=True)
    try:
        yield t
    finally:
        t.configure(enabled=False)
        t.detach_sink()
        t.clear()


def _engine(extra=None, mesh_cfg=None, seed=1):
    cfg = dict(CFG)
    if extra:
        cfg.update(extra)
    mesh = create_mesh(MeshConfig(**(mesh_cfg or {"data": 2, "fsdp": 4})))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=64), config=cfg, mesh=mesh,
        example_batch=random_batch(4), seed=seed)
    return engine


# ---------------------------------------------------------------------------
# codec + error-feedback units
# ---------------------------------------------------------------------------
def test_codec_roundtrip_bound():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4096,)), jnp.float32)
    codes, scales = compress.quantize_wire(x, "int8", 256)
    assert codes.dtype == jnp.int8 and scales.shape == (16,)
    deq = compress.dequantize_wire(codes, scales, 256)
    # per-chunk absmax scaling: round-off is at most half a step per element
    err = np.abs(np.asarray(deq) - np.asarray(x)).reshape(16, 256)
    step = np.asarray(scales)[:, None]
    assert (err <= 0.5 * step + 1e-7).all()


def test_ef_step_invariant_exact():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(512,)), jnp.float32)
    e = jnp.asarray(rng.normal(size=(512,)) * 0.01, jnp.float32)
    codes, scales, new_e = compress.ef_step(x, e, "int8", 256)
    comp = np.asarray(x) + np.asarray(e)
    deq = np.asarray(compress.dequantize_wire(codes, scales, 256))
    np.testing.assert_array_equal(np.asarray(new_e), comp - deq)
    # feedback off: zero residual, None out
    codes2, scales2, none_e = compress.ef_step(x, None, "int8", 256)
    assert none_e is None
    np.testing.assert_array_equal(np.asarray(codes2)[:512],
                                  np.asarray(compress.quantize_wire(
                                      x, "int8", 256)[0]))


def test_wire_model_ratio_clears_floor():
    for n in (2048, 4096, 1 << 20):
        for world in (2, 4, 8):
            logical = compress.padded_elems(n, world, 256) * 4
            wire = compress.all_reduce_wire_bytes(n, world, "int8", 256)
            assert logical / wire >= 3.5
    # the exact formula: codes + fp32 scale per chunk
    assert compress.wire_payload_bytes(4096, "int8", 256) == 4096 + 4 * 16
    assert compress.wire_payload_bytes(4096, "fp8", 256) == 4096 + 4 * 16


# ---------------------------------------------------------------------------
# collectives: parity + EXACT per-axis wire counters (the acceptance gate)
# ---------------------------------------------------------------------------
def _reduce_on_axes(axes, wire_dtype="int8", n=4096, seed=3):
    mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    w = 1
    for a in axes:
        w *= mesh.shape[a]
    spec = P(axes[0] if len(axes) == 1 else axes)

    def body(x):
        out, _ = quantized_all_reduce(x[0], axes, wire_dtype=wire_dtype)
        return out[:n]

    f = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=(spec,),
                              out_specs=P(), axis_names=frozenset(axes),
                              check_vma=False))
    x = jnp.asarray(np.random.default_rng(seed).normal(size=(w, n)),
                    jnp.float32)
    out = np.asarray(f(x))
    return out, np.asarray(x).mean(0), w


def test_quantized_all_reduce_every_mesh_axis_exact_counters(comms):
    """Acceptance: on EVERY exercised mesh axis (data / fsdp / tensor and a
    hierarchical tuple) the recorded wire-byte counters equal the analytic
    model exactly and show >= 3.5x reduction vs the fp32 payload."""
    n = 4096
    for axes in ("data", "fsdp", "tensor", ("data", "fsdp")):
        comms.reset()
        out, exact, w = _reduce_on_axes(axes)
        rel = np.abs(out - exact).max() / np.abs(exact).max()
        assert rel < 0.03, (axes, rel)
        totals = comms.per_op_totals()["quantized_all_reduce"]
        assert totals["count"] == 1
        assert totals["bytes"] == n * 4     # the dense fp32 payload
        assert totals["wire_bytes"] == compress.all_reduce_wire_bytes(
            n, w, "int8", compress.DEFAULT_CHUNK)
        assert totals["bytes"] / totals["wire_bytes"] >= 3.5, axes


def test_quantized_reduce_scatter_matches_psum_scatter(comms):
    mesh = create_mesh(MeshConfig(data=4, fsdp=2))
    n, w = 2048, 4

    def body(x):
        shard, _ = quantized_reduce_scatter(x[0], "data")
        return shard[None]

    f = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=(P("data"),),
                              out_specs=P("data"),
                              axis_names=frozenset({"data"}),
                              check_vma=False))
    x = jnp.asarray(np.random.default_rng(5).normal(size=(w, n)), jnp.float32)
    out = np.asarray(f(x)).reshape(-1)         # [w * n/w] = mean over w
    exact = np.asarray(x).mean(0)
    rel = np.abs(out - exact).max() / np.abs(exact).max()
    assert rel < 0.03, rel
    totals = comms.per_op_totals()["quantized_reduce_scatter"]
    assert totals["wire_bytes"] == compress.reduce_scatter_wire_bytes(
        n, w, "int8", compress.DEFAULT_CHUNK)
    assert totals["bytes"] / totals["wire_bytes"] >= 3.5


def test_fp8_wire_dtype_parity():
    out, exact, _ = _reduce_on_axes("data", wire_dtype="fp8")
    rel = np.abs(out - exact).max() / np.abs(exact).max()
    assert rel < 0.06, rel          # e4m3 has ~2 fewer mantissa bits


def test_error_feedback_kills_the_bias():
    """Repeatedly reducing the SAME payload with residual feedback: the
    running mean of the outputs converges toward the exact mean (each
    step's quantization error is repaid on the next) — without feedback
    the bias is constant."""
    mesh = create_mesh(MeshConfig(data=4, fsdp=2))
    n, w = 1024, 4

    def body(x, ef_w, ef_s):
        err = compress.TensorEF(worker=ef_w[0], server=ef_s[0])
        out, new = quantized_all_reduce(x[0], ("data",), error=err)
        return out[:n], new.worker[None], new.server[None]

    f = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P("data"),) * 3,
        out_specs=(P(), P("data"), P("data")),
        axis_names=frozenset({"data"}), check_vma=False))
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(w, n)), jnp.float32)
    exact = np.asarray(x).mean(0)
    n_pad = compress.padded_elems(n, w, compress.DEFAULT_CHUNK)
    ef_w = jnp.zeros((w, n_pad), jnp.float32)
    ef_s = jnp.zeros((w, n_pad // w), jnp.float32)
    acc = np.zeros(n)
    errs = []
    for t in range(1, 21):
        out, ef_w, ef_s = f(x, ef_w, ef_s)
        acc += np.asarray(out)
        errs.append(np.abs(acc / t - exact).max() / np.abs(exact).max())
    assert errs[-1] < errs[0] / 5, (errs[0], errs[-1])


def test_reshard_error_feedback_preserves_worker_mean():
    ef = compress.TensorEF(
        worker=jnp.asarray(np.arange(16, dtype=np.float32).reshape(2, 8)),
        server=jnp.asarray(np.ones((2, 4), np.float32)))
    out = compress.reshard_error_feedback(ef, 4)
    assert out.worker.shape == (4, 8) and out.server.shape == (4, 2)
    mean = np.arange(16, dtype=np.float32).reshape(2, 8).mean(0)
    for row in np.asarray(out.worker):
        np.testing.assert_array_equal(row, mean)
    assert float(jnp.abs(out.server).sum()) == 0.0


# ---------------------------------------------------------------------------
# bucket scheduler
# ---------------------------------------------------------------------------
def test_bucket_plan_deterministic_and_bounded():
    cfg = compress.CommCompressionConfig(enabled=True, bucket_bytes=64 * 4)
    leaves = [(f"leaf{i}", (32,)) for i in range(8)]   # 32 el = 128 B each
    buckets = compress.plan_buckets(leaves, world=2, cfg=cfg)
    # 2 leaves fill a 256-byte bucket -> 4 buckets, order preserved
    assert [b.paths for b in buckets] == [
        ("leaf0", "leaf1"), ("leaf2", "leaf3"),
        ("leaf4", "leaf5"), ("leaf6", "leaf7")]
    for b in buckets:
        assert b.n == 64
        assert b.n_pad == compress.padded_elems(64, 2, cfg.chunk)
        assert b.wire_bytes == compress.wire_payload_bytes(
            b.n_pad, cfg.wire_dtype, cfg.chunk)
    # overlap off -> ONE fused bucket (compression without the schedule)
    fused = compress.plan_buckets(
        leaves, world=2,
        cfg=compress.CommCompressionConfig(enabled=True, bucket_bytes=64 * 4,
                                           overlap=False))
    assert len(fused) == 1 and fused[0].n == 8 * 32


def test_bucket_count_drives_collective_count(comms):
    """Each planned bucket issues exactly ONE facade-recorded collective
    per traced reduction — the deterministic schedule proof."""
    engine = _engine({"comm_compression": {"enabled": True,
                                           "bucket_bytes": 1 << 12}})
    assert engine._comm_compress is not None
    n_buckets = len(engine._comm_compress.buckets)
    assert n_buckets > 1            # 4 KiB buckets split this model
    comms.reset()
    engine.train_batch(batch=random_batch(8, seed=0))
    totals = comms.per_op_totals()["quantized_all_reduce"]
    assert totals["count"] == n_buckets
    assert totals["bytes"] == sum(
        b.logical_bytes for b in engine._comm_compress.buckets)
    assert totals["wire_bytes"] == sum(
        b.wire_bytes for b in engine._comm_compress.buckets)
    assert totals["bytes"] / totals["wire_bytes"] >= 3.5


# ---------------------------------------------------------------------------
# engine: default-off semantics, parity-vs-fp32, checkpointed EF state
# ---------------------------------------------------------------------------
def test_compression_off_is_bit_identical_to_absent_group():
    fixed = random_batch(8, seed=0)
    e_absent = _engine()
    e_off = _engine({"comm_compression": {"enabled": False}})
    a = [float(e_absent.train_batch(batch=fixed)) for _ in range(3)]
    b = [float(e_off.train_batch(batch=fixed)) for _ in range(3)]
    assert a == b
    assert e_off._comm_compress is None


def test_engine_parity_vs_fp32_with_error_feedback():
    """The acceptance numerics pin: N steps of quantized error-feedback
    training converge to the same loss as fp32 within the pinned
    tolerance (mirrors the qgZ parity envelope)."""
    fixed = random_batch(8, seed=0)
    e_fp = _engine(seed=1)
    e_q = _engine({"comm_compression": {"enabled": True,
                                        "bucket_bytes": 1 << 14}}, seed=1)
    assert e_q._comm_compress is not None
    assert e_q._comm_compress.ef_enabled()
    fp = [float(e_fp.train_batch(batch=fixed)) for _ in range(10)]
    qg = [float(e_q.train_batch(batch=fixed)) for _ in range(10)]
    assert qg[-1] < 0.2 * qg[0], qg              # converges
    assert abs(qg[-1] - fp[-1]) < 0.05 + 0.5 * fp[-1], (qg[-1], fp[-1])


def test_no_replica_axis_warns_and_disables():
    with pytest.warns(UserWarning, match="NO\\s+replica batch axis"):
        engine = _engine({"comm_compression": {"enabled": True}},
                         mesh_cfg={"fsdp": 8})
    assert engine._comm_compress is None


def test_compression_supersedes_qgz():
    engine = _engine({"comm_compression": {"enabled": True},
                      "zero_optimization": {
                          "stage": 3, "zero_quantized_gradients": True}})
    assert engine._comm_compress is not None
    assert engine._qgz_axes == ()    # one compression layer owns the wire
    # and the per-microbatch int8 numerics-simulation fallback must not
    # re-arm either — that would double-quantize every gradient
    assert engine._quantized_gradients is False


def test_checkpoint_carries_error_feedback_bit_identically(tmp_path):
    fixed = random_batch(8, seed=0)
    extra = {"comm_compression": {"enabled": True, "bucket_bytes": 1 << 14}}
    e1 = _engine(extra, seed=1)
    for _ in range(3):
        e1.train_batch(batch=fixed)
    e1.save_checkpoint(str(tmp_path))
    ef1 = jax.device_get(e1.state.opt_state.error_feedback)
    cont = [float(e1.train_batch(batch=fixed)) for _ in range(3)]

    e2 = _engine(extra, seed=1)
    e2.load_checkpoint(str(tmp_path))
    ef2 = jax.device_get(e2.state.opt_state.error_feedback)
    for a, b in zip(jax.tree_util.tree_leaves(ef1),
                    jax.tree_util.tree_leaves(ef2)):
        np.testing.assert_array_equal(a, b)
    # residuals were non-trivial (the test would pass vacuously on zeros)
    assert any(np.abs(leaf).max() > 0
               for leaf in jax.tree_util.tree_leaves(ef1))
    resumed = [float(e2.train_batch(batch=fixed)) for _ in range(3)]
    assert cont == resumed


def test_error_feedback_survives_elastic_reshard(tmp_path):
    """Mesh-portable resume at a DIFFERENT replica world: optimizer moments
    survive via the mining fallback AND the error-feedback residuals are
    adopted (mean-preserving worker reshard) instead of silently resetting."""
    fixed = random_batch(8, seed=0)
    extra = {"comm_compression": {"enabled": True, "bucket_bytes": 1 << 20}}
    e1 = _engine(extra, mesh_cfg={"data": 2, "fsdp": 4}, seed=1)
    for _ in range(3):
        e1.train_batch(batch=fixed)
    e1.save_checkpoint(str(tmp_path))
    ef1 = jax.device_get(e1.state.opt_state.error_feedback)

    e2 = _engine(extra, mesh_cfg={"data": 4, "fsdp": 2}, seed=1)
    assert e2._comm_compress.world == 4
    e2.load_checkpoint(str(tmp_path))
    ef2 = jax.device_get(e2.state.opt_state.error_feedback)
    # every new participant holds the OLD participants' mean residual
    for saved, adopted in zip(ef1, ef2):
        mean = np.asarray(saved.worker).mean(0)
        assert np.abs(mean).max() > 0          # non-trivial adoption
        assert adopted.worker.shape[0] == 4
        for row in np.asarray(adopted.worker):
            np.testing.assert_allclose(row, mean, rtol=1e-6, atol=1e-7)
    # moments survived the topology change too (mined, not reset)
    inner1 = jax.device_get(jax.tree_util.tree_leaves(
        e1.state.opt_state.inner))
    inner2 = jax.device_get(jax.tree_util.tree_leaves(
        e2.state.opt_state.inner))
    nonzero = [np.abs(a).max() for a in inner1 if np.ndim(a) > 0]
    assert any(v > 0 for v in nonzero)
    for a, b in zip(inner1, inner2):
        if np.ndim(a) > 0 and a.shape == np.shape(b):
            np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
    # and the resumed engine still trains
    assert np.isfinite(float(e2.train_batch(batch=fixed)))


# ---------------------------------------------------------------------------
# adapters: qgZ + sparse produce identical accounting through the layer
# ---------------------------------------------------------------------------
def test_qgz_adapter_accounting_identical_to_direct_layer_call(comms):
    from deepspeed_tpu.runtime.zero.qgz import quantized_grad_sync
    mesh = create_mesh(MeshConfig(data=2, fsdp=4))
    g = jnp.asarray(np.random.default_rng(0).normal(size=(2, 64, 64)),
                    jnp.float32)

    def via_adapter(x):
        return quantized_grad_sync({"w": x[0]}, ("data",))["w"]

    def via_layer(x):
        out, _ = quantized_all_reduce(x[0].reshape(-1), ("data",))
        return out[:64 * 64].reshape(64, 64)

    for fn in (via_adapter, via_layer):
        comms.reset()
        f = jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=(P("data"),),
                                  out_specs=P(),
                                  axis_names=frozenset({"data"}),
                                  check_vma=False))
        np.asarray(f(g))
        totals = comms.per_op_totals()["quantized_all_reduce"]
        if fn is via_adapter:
            adapter_totals = dict(totals)
        else:
            assert totals == adapter_totals   # identical wire accounting


def test_qgz_adapter_still_moves_int8_on_the_wire():
    from deepspeed_tpu.runtime.zero.qgz import quantized_grad_sync
    mesh = create_mesh(MeshConfig(data=2, fsdp=4))

    def body(x):
        return quantized_grad_sync({"w": x[0]}, ("data",))["w"]

    f = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=(P("data"),),
                              out_specs=P(), axis_names=frozenset({"data"}),
                              check_vma=False))
    x = jnp.zeros((2, 64, 64), jnp.float32)
    txt = f.lower(x).as_text()
    assert any("all_to_all" in ln and "i8" in ln for ln in txt.splitlines())
    assert any("all_gather" in ln and "i8" in ln for ln in txt.splitlines())


def test_sparse_grad_sync_records_wire_bytes(comms):
    from deepspeed_tpu.runtime.sparse_tensor import sparse_grad_sync
    mesh = create_mesh(MeshConfig(data=2, fsdp=4))
    v, d, k = 512, 16, 8

    def body(g):
        return sparse_grad_sync(g[0], ("data",), k)

    f = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=(P("data"),),
                              out_specs=P(), axis_names=frozenset({"data"}),
                              check_vma=False))
    g = jnp.asarray(np.random.default_rng(2).normal(size=(2, v, d)),
                    jnp.float32)
    np.asarray(f(g))
    totals = comms.per_op_totals()["sparse_all_gather"]
    assert totals["count"] == 1
    assert totals["bytes"] == v * d * 4            # the dense alternative
    assert totals["wire_bytes"] == k * 4 + k * d * 4   # indices + values
    assert totals["bytes"] / totals["wire_bytes"] > 3.5


# ---------------------------------------------------------------------------
# comms_logging satellites: exact op-kind classification + wire columns
# ---------------------------------------------------------------------------
def test_op_kind_classification_is_exact_not_substring():
    assert canonical_op_kind("quantized_all_reduce") == "all_reduce"
    assert canonical_op_kind("quantized_reduce_scatter") == "reduce_scatter"
    assert canonical_op_kind("sparse_all_gather") == "all_gather"
    # a name that merely CONTAINS a collective substring is "other" — the
    # old substring classifier would have given it the allreduce factor
    assert canonical_op_kind("my_all_reduce_shim") == "other"
    alg, bus = calc_bw("quantized_all_reduce", 1 << 20, 1.0, 4)
    assert bus == pytest.approx(alg * 1.5)          # 2(n-1)/n at n=4
    alg, bus = calc_bw("my_all_reduce_shim", 1 << 20, 1.0, 4)
    assert bus == alg                               # exact: no factor
    # explicit kind wins over the registry
    alg, bus = calc_bw("custom_op", 1 << 20, 1.0, 4, kind="all_gather")
    assert bus == pytest.approx(alg * 0.75)


def test_env_rows_report_compression_status(comms):
    comms.record_traced("quantized_all_reduce", 4096, 4, wire_bytes=1100)
    rows = dict(comms.env_report_rows())
    assert "wire" in rows["comms[quantized_all_reduce]"]
    assert rows["comm compression"].startswith("active:")
    comms.reset()
    comms.record_traced("all_reduce", 4096, 4)
    rows = dict(comms.env_report_rows())
    assert rows["comm compression"].startswith("no compressed ops")


# ---------------------------------------------------------------------------
# overlap spans + dstpu plan rollups
# ---------------------------------------------------------------------------
def test_overlap_spans_ride_their_own_track_and_plan_attributes(tracing):
    from deepspeed_tpu.telemetry import attribution
    engine = _engine({"comm_compression": {"enabled": True,
                                           "bucket_bytes": 1 << 12}})
    n_buckets = len(engine._comm_compress.buckets)
    fixed = random_batch(8, seed=0)
    for _ in range(3):
        engine.train_batch(batch=fixed)
    ov = [e for e in tracing.events_snapshot()
          if e[1] == "comm/overlap" and e[3] == "X"]
    assert len(ov) == 3 * n_buckets
    assert all(e[6] == COMM_OVERLAP_TID for e in ov)
    assert all("wire_bytes" in e[7] and "bytes" in e[7] for e in ov)
    # the track is labeled in the chrome dump
    chrome = tracing.to_chrome()
    labels = [m["args"]["name"] for m in chrome["traceEvents"]
              if m.get("ph") == "M" and m["name"] == "thread_name"]
    assert "comm-overlap" in labels
    # plan replay: rollups carry wire bytes; comm/overlap attributes as
    # overlapped comm, never step cost
    rep = attribution.attribute(attribution.events_from_tracer(tracing))
    quant = [r for key, r in rep["comm"].items()
             if r["op"] == "quantized_all_reduce"]
    assert quant and all(r["compression"] >= 3.5 for r in quant)
    assert "overlap" not in {r["op"] for r in rep["comm"].values()}
    co = rep["comm_overlap"]
    assert co["overlap_us"] > 0
    assert 0 < co["overlap_fraction"] <= 1


def _ev(name, ts, dur, tid=1, cat="train", ph="X", **args):
    return {"name": name, "cat": cat, "ph": ph, "ts": ts, "dur": dur,
            "tid": tid, "args": args}


def test_synthetic_overlap_fraction_exact():
    from deepspeed_tpu.telemetry import attribution
    ev = [_ev("engine/dispatch", 0, 10_000, step=1),
          _ev("comm/all_reduce", 1_000, 1_000, cat="comm", bytes=1 << 20,
              world=8, algbw_gbps=1.0, busbw_gbps=1.0),
          _ev("comm/overlap", 2_000, 2_000, tid=COMM_OVERLAP_TID,
              cat="comm", bytes=1 << 20, wire_bytes=266_240)]
    rep = attribution.attribute(attribution.events_from_chrome(ev))
    co = rep["comm_overlap"]
    assert co["on_track_us"] == 1_000
    assert co["overlap_us"] == 2_000
    assert co["overlap_fraction"] == pytest.approx(2_000 / 3_000, abs=1e-4)
    (w,) = rep["windows"]
    assert w["overlapped_us"].get("comm") == 2_000.0


def test_plan_proposes_enabling_compression_when_wire_is_full_width():
    from deepspeed_tpu.telemetry import attribution
    base = [_ev("engine/dispatch", 0, 10_000, step=1),
            _ev("comm/all_reduce", 1_000, 3_000, cat="comm",
                bytes=1 << 20, world=8, algbw_gbps=1.0, busbw_gbps=1.75)]
    rep = attribution.attribute(attribution.events_from_chrome(base))
    props = {p["id"]: p for p in rep["proposals"]}
    assert "enable_comm_compression" in props
    p = props["enable_comm_compression"]
    assert p["overrides"] == {"comm_compression": {"enabled": True}}
    assert p["predicted"]["metric"] == "wire_bytes"
    assert p["predicted"]["current"] == 1 << 20
    assert p["predicted"]["proposed"] == attribution._predicted_wire_bytes(
        1 << 20)
    assert "raise_gas" not in props
    # already compressed: the gas rule takes over
    compressed = json.loads(json.dumps(base))
    compressed[1]["args"]["wire_bytes"] = 266_240
    rep2 = attribution.attribute(attribution.events_from_chrome(compressed))
    ids = {p["id"] for p in rep2["proposals"]}
    assert "raise_gas" in ids and "enable_comm_compression" not in ids


def test_compression_proposal_never_fires_on_incompressible_comm():
    """A trace dominated by param all-gathers (pure-fsdp ZeRO-3) must NOT
    propose comm_compression — the knob cannot compress that volume (the
    engine would warn and disable); the gas rule takes the comm stage."""
    from deepspeed_tpu.telemetry import attribution
    ev = [_ev("engine/dispatch", 0, 10_000, step=1),
          _ev("comm/all_gather", 1_000, 3_000, cat="comm",
              bytes=1 << 20, world=8, algbw_gbps=1.0, busbw_gbps=0.875,
              kind="all_gather")]
    rep = attribution.attribute(attribution.events_from_chrome(ev))
    ids = {p["id"] for p in rep["proposals"]}
    assert "enable_comm_compression" not in ids
    assert "raise_gas" in ids
    # rollup rows carry the canonical kind (explicit arg or exact-name map)
    assert rep["comm"]["all_gather@8"]["kind"] == "all_gather"


def test_predicted_wire_model_pinned_to_compress_layer():
    """The proposal table's standalone copy of the wire model must equal
    the authoritative one in comm/compress.py (same contract as the
    quantile-copy pins)."""
    from deepspeed_tpu.telemetry import attribution
    for logical in (4096, 1 << 20, 12_345_678):
        n = logical // 4
        assert attribution._predicted_wire_bytes(logical) == \
            compress.wire_payload_bytes(n, "int8", attribution._WIRE_CHUNK)


# ---------------------------------------------------------------------------
# config + registry satellites
# ---------------------------------------------------------------------------
def test_config_group_parses_and_validates():
    cfg = DeepSpeedTPUConfig({"train_batch_size": 8,
                              "comm_compression": {"enabled": True,
                                                   "wire_dtype": "fp8",
                                                   "chunk": 128}},
                             dp_world_size=8)
    assert cfg.comm_compression.enabled
    assert cfg.comm_compression.wire_dtype == "fp8"
    assert cfg.comm_compression.chunk == 128
    assert not DeepSpeedTPUConfig({"train_batch_size": 8},
                                  dp_world_size=8).comm_compression.enabled
    with pytest.raises(Exception):
        DeepSpeedTPUConfig({"train_batch_size": 8,
                            "comm_compression": {"wire_dtype": "int3"}},
                           dp_world_size=8)


def test_hotpath_taint_covers_the_compress_layer(package_callgraph,
                                                 hot_reached):
    """The DS002 taint closure from the declared roots keeps covering
    the compress layer — the old per-function registry entries, now
    proven reachable instead of hand-listed."""
    g = package_callgraph
    path = "deepspeed_tpu/comm/compress.py"
    for qn in ("quantize_wire", "dequantize_wire", "ef_step",
               "all_reduce_impl", "plan_buckets",
               "GradCompressor.make_sync_fn"):
        key = g.resolve(path, qn)
        assert key is not None, f"{qn} gone from {path}"
        assert key in hot_reached, f"{qn} fell out of the hot taint"
    eng = g.resolve("deepspeed_tpu/runtime/engine.py",
                    "DeepSpeedTPUEngine._emit_overlap_spans")
    assert eng is not None and eng in hot_reached
