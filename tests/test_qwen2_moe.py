"""Qwen2-MoE family tests: shared-expert gating, EP training, paged serving.

Reference analog: ``inference/v2/model_implementations/qwen_v2_moe`` cases.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.llama import random_tokens
from deepspeed_tpu.models.qwen2_moe import (
    TINY_QWEN2_MOE, Qwen2MoEForCausalLM, qwen2_moe_tensor_rules)


def test_shared_expert_params_and_forward():
    model = Qwen2MoEForCausalLM(TINY_QWEN2_MOE)
    batch = random_tokens(2, 16, vocab_size=512)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    lp = params["layer_0"]
    assert set(lp["shared_expert"]) == {"w_gate", "w_up", "w_down", "gate"}
    assert lp["shared_expert"]["gate"]["kernel"].shape[-1] == 1
    # experts use the (smaller) moe_intermediate_size, shared uses its own
    assert lp["moe"]["experts"]["w_up"].shape[-1] == \
        TINY_QWEN2_MOE.moe_intermediate_size
    assert lp["shared_expert"]["w_up"]["kernel"].shape[-1] == \
        TINY_QWEN2_MOE.shared_expert_intermediate_size
    assert np.isfinite(float(model.apply({"params": params}, batch)))


@pytest.mark.slow
def test_qwen2_moe_trains_ep():
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=Qwen2MoEForCausalLM(TINY_QWEN2_MOE),
        config={"train_batch_size": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
                "zero_optimization": {"stage": 1},
                "bf16": {"enabled": True},
                "mesh": {"data": 2, "expert": 2, "tensor": 2}},
        example_batch=random_tokens(2, 16, vocab_size=512),
        tensor_rules=qwen2_moe_tensor_rules)
    fixed = random_tokens(4, 16, vocab_size=512, seed=0)
    losses = [float(engine.train_batch(batch=fixed)) for _ in range(6)]
    assert losses[-1] < losses[0] and all(np.isfinite(losses))


@pytest.mark.slow
def test_serve_qwen2_moe_paged_matches_full():
    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, V2EngineConfig)
    from deepspeed_tpu.inference.v2.modules import Qwen2MoEPolicy, policy_for
    from deepspeed_tpu.inference.v2.scheduler import SchedulerConfig

    import dataclasses
    cfg = dataclasses.replace(
        TINY_QWEN2_MOE,
        base=dataclasses.replace(TINY_QWEN2_MOE.base, dtype=jnp.float32),
        moe=dataclasses.replace(TINY_QWEN2_MOE.moe, dtype=jnp.float32))
    assert policy_for(cfg) is Qwen2MoEPolicy
    model = Qwen2MoEForCausalLM(cfg)
    prompt = list(np.random.default_rng(9).integers(0, 512, 10))
    params = model.init(jax.random.PRNGKey(1),
                        random_tokens(1, 8, vocab_size=512))["params"]
    engine = InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=64,
        scheduler=SchedulerConfig(max_tokens_per_step=64,
                                  prefill_buckets=(16, 32, 64))))
    got = engine.generate(list(prompt), max_new_tokens=4)
    ids = list(prompt)
    for _ in range(4):
        logits = model.apply({"params": params},
                             {"input_ids": np.asarray([ids], np.int32)},
                             method=Qwen2MoEForCausalLM.logits)
        ids.append(int(np.argmax(np.asarray(logits)[0, -1])))
    assert got == ids[len(prompt):], (got, ids[len(prompt):])
