"""Qwen2-MoE family tests: shared-expert gating, EP training, paged serving.

Reference analog: ``inference/v2/model_implementations/qwen_v2_moe`` cases.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.llama import random_tokens
from deepspeed_tpu.models.qwen2_moe import (
    TINY_QWEN2_MOE, Qwen2MoEForCausalLM, qwen2_moe_tensor_rules)


def test_shared_expert_params_and_forward():
    model = Qwen2MoEForCausalLM(TINY_QWEN2_MOE)
    batch = random_tokens(2, 16, vocab_size=512)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    lp = params["layer_0"]
    assert set(lp["shared_expert"]) == {"w_gate", "w_up", "w_down", "gate"}
    assert lp["shared_expert"]["gate"]["kernel"].shape[-1] == 1
    # experts use the (smaller) moe_intermediate_size, shared uses its own
    assert lp["moe"]["experts"]["w_up"].shape[-1] == \
        TINY_QWEN2_MOE.moe_intermediate_size
    assert lp["shared_expert"]["w_up"]["kernel"].shape[-1] == \
        TINY_QWEN2_MOE.shared_expert_intermediate_size
    assert np.isfinite(float(model.apply({"params": params}, batch)))


@pytest.mark.slow
def test_qwen2_moe_trains_ep():
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=Qwen2MoEForCausalLM(TINY_QWEN2_MOE),
        config={"train_batch_size": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
                "zero_optimization": {"stage": 1},
                "bf16": {"enabled": True},
                "mesh": {"data": 2, "expert": 2, "tensor": 2}},
        example_batch=random_tokens(2, 16, vocab_size=512),
        tensor_rules=qwen2_moe_tensor_rules)
    fixed = random_tokens(4, 16, vocab_size=512, seed=0)
    losses = [float(engine.train_batch(batch=fixed)) for _ in range(6)]
    assert losses[-1] < losses[0] and all(np.isfinite(losses))


@pytest.mark.slow
def test_serve_qwen2_moe_paged_matches_full():
    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, V2EngineConfig)
    from deepspeed_tpu.inference.v2.modules import Qwen2MoEPolicy, policy_for
    from deepspeed_tpu.inference.v2.scheduler import SchedulerConfig

    import dataclasses
    cfg = dataclasses.replace(
        TINY_QWEN2_MOE,
        base=dataclasses.replace(TINY_QWEN2_MOE.base, dtype=jnp.float32),
        moe=dataclasses.replace(TINY_QWEN2_MOE.moe, dtype=jnp.float32))
    assert policy_for(cfg) is Qwen2MoEPolicy
    model = Qwen2MoEForCausalLM(cfg)
    prompt = list(np.random.default_rng(9).integers(0, 512, 10))
    params = model.init(jax.random.PRNGKey(1),
                        random_tokens(1, 8, vocab_size=512))["params"]
    engine = InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=64,
        scheduler=SchedulerConfig(max_tokens_per_step=64,
                                  prefill_buckets=(16, 32, 64))))
    got = engine.generate(list(prompt), max_new_tokens=4)
    ids = list(prompt)
    for _ in range(4):
        logits = model.apply({"params": params},
                             {"input_ids": np.asarray([ids], np.int32)},
                             method=Qwen2MoEForCausalLM.logits)
        ids.append(int(np.argmax(np.asarray(logits)[0, -1])))
    assert got == ids[len(prompt):], (got, ids[len(prompt):])


@pytest.mark.slow
def test_hf_qwen2_moe_torch_parity():
    """Gold-standard interop check: convert a random torch-transformers
    Qwen2Moe checkpoint and match its logits (no token drops at high
    capacity; norm_topk_prob=False semantics)."""
    import dataclasses

    import torch
    from transformers import Qwen2MoeConfig as HFConfig
    from transformers import Qwen2MoeForCausalLM as HFModel

    from deepspeed_tpu.models.qwen2_moe import (
        Qwen2MoEForCausalLM, convert_hf_qwen2_moe, qwen2_moe_config_from_hf)

    hf_cfg = HFConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        moe_intermediate_size=32, shared_expert_intermediate_size=64,
        num_experts=4, num_experts_per_tok=2, decoder_sparse_step=1,
        max_position_embeddings=128, rms_norm_eps=1e-6, rope_theta=10000.0,
        norm_topk_prob=False, output_router_logits=False)
    torch.manual_seed(0)
    hf_model = HFModel(hf_cfg).eval()

    cfg = qwen2_moe_config_from_hf(hf_cfg.to_dict())
    # fp32 compute + generous eval capacity so no token drops and the
    # GShard dispatch equals HF's dense per-token routing
    cfg = dataclasses.replace(
        cfg,
        base=dataclasses.replace(cfg.base, dtype=jnp.float32),
        moe=dataclasses.replace(cfg.moe, dtype=jnp.float32,
                                eval_capacity_factor=float(
                                    cfg.moe.num_experts)))
    params = convert_hf_qwen2_moe(hf_model.state_dict(), cfg)

    ids = np.random.default_rng(0).integers(0, 256, size=(2, 16))
    with torch.no_grad():
        ref = hf_model(torch.tensor(ids)).logits.numpy()
    ours = Qwen2MoEForCausalLM(cfg).apply(
        {"params": jax.tree.map(jnp.asarray, params)},
        {"input_ids": jnp.asarray(ids.astype(np.int32))},
        method=Qwen2MoEForCausalLM.logits)
    np.testing.assert_allclose(np.asarray(ours), ref, atol=2e-4, rtol=2e-3)


def test_qwen2_moe_config_from_hf_fields():
    from deepspeed_tpu.models.qwen2_moe import qwen2_moe_config_from_hf
    hf = {"vocab_size": 151936, "hidden_size": 2048,
          "num_hidden_layers": 24, "num_attention_heads": 16,
          "num_key_value_heads": 16, "moe_intermediate_size": 1408,
          "shared_expert_intermediate_size": 5632, "num_experts": 60,
          "num_experts_per_tok": 4, "norm_topk_prob": False,
          "rope_theta": 1000000.0, "router_aux_loss_coef": 0.001}
    cfg = qwen2_moe_config_from_hf(hf)
    assert cfg.moe.num_experts == 60 and cfg.moe.top_k == 4
    assert cfg.moe.norm_topk_prob is False
    assert cfg.base.attention_bias and cfg.base.rope_theta == 1000000.0
    assert cfg.moe_intermediate_size == 1408
    with pytest.raises(ValueError):
        qwen2_moe_config_from_hf({**hf, "mlp_only_layers": [0]})
