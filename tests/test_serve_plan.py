"""``dstpu plan --serve`` — serving-tick attribution / siege-knob planning.

Contracts pinned here:

  golden       : the checked-in micro fixture (bench_serve report + trace
                 + serve_plan_baseline.json, ONE artifact set regenerated
                 by tests/serve_plan_fixtures/make_fixtures.py) attributes
                 to a per-tick ledger whose stages (incl. residual) sum
                 EXACTLY to each tick window, tie-out bounded
  synthetic    : a hand-built serve trace with known durations exercises
                 every stage, the priority sweep's nesting rules, the
                 per-level request-latency join, and the counter-track
                 tails, to exact microseconds
  rules        : the proposal table maps each pressure signal to ONE
                 serving override + an exact counter predicate,
                 deterministically ordered
  ratchet + CLI: serve_plan_baseline.json follows the dslint/plan idiom
                 (workload-scoped, stale-entry expiry via
                 --write-baseline); exit matrix 0/1/2 via both
                 serve_attribution.main and `bin/dstpu plan --serve`
  offline-only : serve_attribution is OFFLINE_ONLY (never imports jax, no
                 hot path reaches it — the registry loop in test_plan.py
                 covers both directions automatically) and the serve-tick
                 helpers are DS002-registered hot paths
  slicing      : dstpu_trace --request UID exports one request's
                 retro-spans plus intersecting serve ticks as a
                 plan-loadable slice
  loop         : the acceptance drills — seeded overload and multi_turn
                 presets run end-to-end through plan -> verify with at
                 least one VERIFIED verdict persisted under
                 plan.serve_verifications in autotuning_results.json,
                 judged by exact counter comparison (no wall-clock A/B)
"""

import dataclasses
import json
import os
import subprocess
import sys

import pytest

from deepspeed_tpu.telemetry import report as trace_report
from deepspeed_tpu.telemetry import serve_attribution as sa
from deepspeed_tpu.telemetry.tracer import Tracer, _quantile, get_tracer

pytestmark = pytest.mark.serve_plan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "serve_plan_fixtures")
REPORT = os.path.join(FIXTURES, "micro_serve_report.json")
TRACE = os.path.join(FIXTURES, "micro_serve_trace.json")
BASELINE = os.path.join(REPO, sa.SERVE_PLAN_BASELINE_NAME)


def _stage_sum_us(window):
    return sum(window["stages_us"].values())


# ---------------------------------------------------------------------------
# golden attribution on the checked-in fixture artifact set
# ---------------------------------------------------------------------------
def test_golden_fixture_ledger_ties_out():
    rep = sa.analyze_serve_path(REPORT)
    assert rep["window_mode"] == "tick"
    assert rep["ticks_total"] >= 10
    for w in rep["windows"]:
        # exclusive stages + residual sum EXACTLY to the tick window
        # (residual is the remainder by construction)
        assert _stage_sum_us(w) == pytest.approx(w["dur_us"], abs=0.01)
        assert w["tie_out_error"] <= sa.TIE_OUT_TOLERANCE
    agg = rep["aggregate"]
    shares = sum(agg[s]["share"] for s in sa.STAGES)
    assert shares == pytest.approx(1.0, abs=0.01)
    # the siege fixture exercises the whole ledger: step phases, the
    # offload tier's page movers, and request settling all attribute
    for stage in ("prefill", "decode", "demote", "promote", "admission",
                  "drain"):
        assert agg[stage]["total_ms"] > 0, stage
    # the report-input path resolved the trace and joined the provenance
    assert rep["trace"].endswith("micro_serve_trace.json")
    assert rep["provenance"]["preset"] == "overload"
    assert rep["config_observed"]["kv_demote_watermark"] == 0.45


def test_golden_fixture_is_pure_function():
    assert sa.analyze_serve_path(REPORT) == sa.analyze_serve_path(REPORT)


def test_golden_fixture_clean_against_checked_in_baseline():
    """fixture + serve_plan_baseline.json are ONE artifact set: the
    checked-in baseline must be exactly clean (no regressions, no stale
    entries) against the checked-in fixture it was generated from."""
    rep = sa.analyze_serve_path(REPORT)
    baseline = sa.load_serve_plan_baseline(BASELINE)
    regressions, stale = sa.check_baseline(rep, baseline)
    assert regressions == []
    assert stale == []
    assert set(baseline["entries"]) == set(sa.STAGES)
    assert baseline["workload"] == "micro_serve_trace.json"


def test_golden_fixture_proposals_structured():
    rep = sa.analyze_serve_path(REPORT)
    assert rep["proposals"], "the siege fixture must trip the rule table"
    known = {"raise_kv_demote_watermark", "raise_host_kv_budget_bytes",
             "raise_prefix_cache_max_blocks", "widen_ladder_hysteresis"}
    for p in rep["proposals"]:
        assert p["id"] in known
        assert list(p["overrides"]) == ["serving"]      # ONE serving knob
        assert len(p["overrides"]["serving"]) == 1
        pred = p["predicted"]
        assert pred["op"] in ("<=", ">=", "<", ">", "==")
        assert pred["counter"] and "value" in pred
    # request latency joined per ladder level from the retro-spans
    req = rep["requests"]
    assert req["requests"] > 0
    assert "healthy" in req["levels"]
    assert req["levels"]["healthy"]["ttft_p99_ms"] >= \
        req["levels"]["healthy"]["ttft_p50_ms"] > 0
    # counter tracks report tails, not just last/max
    kv = rep["counters"]["serve/kv_bytes"]["observed"]
    assert {"last", "max", "p95", "p99", "count"} <= set(kv)
    assert "serve/tick_stage_share" in rep["counters"]


# ---------------------------------------------------------------------------
# synthetic full-ledger golden (exact microseconds, every stage)
# ---------------------------------------------------------------------------
def _ev(name, ts, dur, tid=1, cat="serve", ph="X", **args):
    return {"name": name, "cat": cat, "ph": ph, "ts": ts, "dur": dur,
            "tid": tid, "args": args}


SYNTHETIC = {"traceEvents": [
    {"name": "thread_name", "ph": "M", "tid": 1,
     "args": {"name": "dstpu-serve"}},
    _ev("serve/tick", 0, 10_000, tick=1, worked=True),
    _ev("serve/admit", 100, 400, tick=1),
    _ev("serve/engine_step", 600, 5_000, tick=1),     # NOT a stage
    _ev("serve/step_prefill", 700, 2_000, chunks=2),  # interior attributes
    _ev("serve/step_decode", 2_700, 2_500, batch=4),
    _ev("serve/demote", 5_700, 400, uid=3, bytes=1024),
    _ev("serve/promote", 6_100, 300, uid=2, bytes=512),
    _ev("serve/drain", 6_500, 600, tick=1),
    _ev("serve/drain", 9_000, 200, tick=1),
    _ev("serve/demote", 9_100, 50, uid=5, bytes=64),  # nested: demote wins
    # request retro-spans on a synthetic request track: latency join only,
    # never part of the tick ledger
    _ev("serve/queued", 0, 1_000, tid=1_000_007, uid=7, level="healthy"),
    _ev("serve/prefill", 1_000, 2_000, tid=1_000_007, uid=7,
        level="healthy"),
    _ev("serve/decode", 3_000, 4_000, tid=1_000_007, uid=7,
        level="healthy", tokens=5),
]}


def test_synthetic_exclusive_sweep_exact():
    rep = sa.attribute_serve(sa.events_from_chrome(SYNTHETIC),
                             source="synthetic")
    assert rep["window_mode"] == "tick"
    (w,) = rep["windows"]
    st = w["stages_us"]
    assert st["admission"] == 400
    assert st["prefill"] == 2_000
    assert st["decode"] == 2_500
    assert st["demote"] == 450            # 400 + 50 carved out of drain
    assert st["promote"] == 300
    assert st["drain"] == 750             # 600 + (200 - nested demote 50)
    assert st["residual"] == 3_600        # exact remainder
    assert _stage_sum_us(w) == w["dur_us"] == 10_000
    assert w["tie_out_error"] == 0.0
    # the per-request retro-spans joined as latency, not ledger
    req = rep["requests"]
    assert req["levels"]["healthy"]["count"] == 1
    assert req["levels"]["healthy"]["ttft_p50_ms"] == 3.0   # 1000+2000 us
    assert req["levels"]["healthy"]["tpot_p50_ms"] == 1.0   # 4000/(5-1)
    assert req["ttft_p99_ms"] == 3.0


def test_synthetic_per_level_latency_split():
    obj = {"traceEvents": [
        _ev("serve/tick", 0, 1_000, tick=1),
        _ev("serve/queued", 0, 100, tid=1_000_001, uid=1, level="healthy"),
        _ev("serve/prefill", 100, 100, tid=1_000_001, uid=1,
            level="healthy"),
        _ev("serve/queued", 0, 5_000, tid=1_000_002, uid=2,
            level="brownout"),
        _ev("serve/prefill", 5_000, 1_000, tid=1_000_002, uid=2,
            level="brownout"),
    ]}
    req = sa.attribute_serve(sa.events_from_chrome(obj))["requests"]
    assert req["levels"]["healthy"]["ttft_p50_ms"] == 0.2
    assert req["levels"]["brownout"]["ttft_p50_ms"] == 6.0
    assert req["ttft_p99_ms"] == 6.0      # overall tail is the brownout one


def test_engine_step_fallback_windows_and_errors():
    """Dumps from before serve/tick existed fall back to engine_step
    windows; traces with no serving spans at all are exit-2 material."""
    obj = {"traceEvents": [_ev("serve/engine_step", i * 1_000, 600, tick=i)
                           for i in range(3)]}
    rep = sa.attribute_serve(sa.events_from_chrome(obj))
    assert rep["window_mode"] == "engine_step"
    assert rep["ticks_total"] == 3
    with pytest.raises(sa.PlanError):
        sa.attribute_serve(sa.events_from_chrome(
            {"traceEvents": [_ev("engine/dispatch", 0, 10, cat="train")]}))
    with pytest.raises(sa.PlanError):
        sa.events_from_chrome({"no": "traceEvents"})


def test_counter_track_tails_exact_and_quantile_parity():
    obj = {"traceEvents": [
        _ev("serve/tick", 0, 100, tick=1),
        *[_ev("serve/kv_bytes", i * 10, 0, ph="C", cat="mem",
              observed=i + 1, projected=100) for i in range(20)],
    ]}
    rep = sa.attribute_serve(sa.events_from_chrome(obj))
    obs = rep["counters"]["serve/kv_bytes"]["observed"]
    # shared exact-quantile rule: sorted[min(int(q*n), n-1)] over n=20
    assert obs == {"last": 20.0, "max": 20.0, "p95": 20.0, "p99": 20.0,
                   "count": 20}
    vals = [float(v) for v in range(1, 21)]
    for q in (0.5, 0.95, 0.99):
        assert sa.quantile(vals, q) == _quantile(vals, q)
    assert sa.quantile([], 0.5) == 0.0


def test_instant_families_counted():
    obj = {"traceEvents": [
        _ev("serve/tick", 0, 100, tick=1),
        _ev("serve/ladder", 1, 0, ph="i", frm="healthy", to="brownout"),
        _ev("serve/ladder", 2, 0, ph="i", frm="brownout", to="healthy"),
        _ev("serve/ladder", 3, 0, ph="i", frm="healthy", to="brownout"),
        _ev("serve/backpressure", 4, 0, ph="i", kind="shed"),
        _ev("serve/backpressure", 5, 0, ph="i", kind="queue_full"),
        _ev("serve/kv_demote", 6, 0, ph="i", uid=1, bytes=100),
        _ev("serve/prefix_evict", 7, 0, ph="i", blocks=3),
    ]}
    inst = sa.attribute_serve(sa.events_from_chrome(obj))["instants"]
    assert inst["ladder_edges"] == {"healthy->brownout": 2,
                                    "brownout->healthy": 1}
    assert inst["backpressure"] == {"queue_full": 1, "shed": 1}
    assert inst["demoted_bytes"] == 100
    assert inst["prefix_evicted_blocks"] == 3


# ---------------------------------------------------------------------------
# the proposal rule table (pure function, exact overrides + predicates)
# ---------------------------------------------------------------------------
def _mk_report(shares=None, cfg=None, bench=None, prefix=None,
               tracks=None, instants=None):
    agg = {s: {"share": 0.0, "total_ms": 0.0, "mean_tick_ms": 0.0,
               "p50_tick_ms": 0.0, "p95_tick_ms": 0.0, "p99_tick_ms": 0.0}
           for s in sa.STAGES}
    for k, v in (shares or {}).items():
        agg[k]["share"] = v
    config = dict(sa.SERVING_DEFAULTS)
    config.update(cfg or {})
    return {"aggregate": agg, "config_observed": config,
            "bench_counters": bench, "prefix": prefix,
            "counters": tracks or {},
            "instants": instants or {"counts": {}, "ladder_edges": {},
                                     "backpressure": {}, "demoted_bytes": 0,
                                     "promoted_bytes": 0,
                                     "prefix_evicted_blocks": 0}}


def test_rule_raise_kv_demote_watermark():
    rep = _mk_report(shares={"demote": 0.2, "promote": 0.05},
                     cfg={"kv_demote_watermark": 0.6},
                     bench={"demotions": 5, "demoted_bytes": 1000})
    (p,) = [q for q in sa.propose_serve(rep)
            if q["id"] == "raise_kv_demote_watermark"]
    assert p["overrides"] == {"serving": {"kv_demote_watermark": 0.85}}
    assert p["predicted"] == {"counter": "demoted_bytes", "op": "<=",
                              "value": 1000, "baseline": 1000,
                              "unit": "bytes"}
    # capped at 0.95; never proposed once already there
    rep["config_observed"]["kv_demote_watermark"] = 0.95
    assert not [q for q in sa.propose_serve(rep)
                if q["id"] == "raise_kv_demote_watermark"]
    # below the churn floor the rule stays quiet
    rep2 = _mk_report(shares={"demote": 0.04},
                      bench={"demotions": 5, "demoted_bytes": 1000})
    assert not [q for q in sa.propose_serve(rep2)
                if q["id"] == "raise_kv_demote_watermark"]


def test_rule_raise_host_kv_budget():
    tracks = {"serve/kv_tier": {"host_bytes": {
        "last": 0.0, "max": 10 * 2 ** 20, "p95": 0.0, "p99": 0.0,
        "count": 4}}}
    rep = _mk_report(cfg={"kv_offload_enabled": True,
                          "host_kv_budget_bytes": 64 * 2 ** 20},
                     bench={"sheds": 5}, tracks=tracks)
    (p,) = [q for q in sa.propose_serve(rep)
            if q["id"] == "raise_host_kv_budget_bytes"]
    assert p["overrides"]["serving"]["host_kv_budget_bytes"] == 128 * 2 ** 20
    assert p["predicted"]["counter"] == "sheds"
    assert p["predicted"]["value"] == 4           # sheds AVOIDED: strict
    # a busy host tier means the budget was not idle: no proposal
    tracks["serve/kv_tier"]["host_bytes"]["max"] = 60 * 2 ** 20
    assert not [q for q in sa.propose_serve(rep)
                if q["id"] == "raise_host_kv_budget_bytes"]


def test_rule_raise_prefix_cache_cap_and_hysteresis():
    rep = _mk_report(cfg={"prefix_cache_enabled": True,
                          "prefix_cache_max_blocks": 8,
                          "ladder_hysteresis": 0.1},
                     bench={"prefix_evictions": 12, "brownout_entries": 3},
                     prefix={"prefix_hit_ratio": 0.3})
    by_id = {p["id"]: p for p in sa.propose_serve(rep)}
    cap = by_id["raise_prefix_cache_max_blocks"]
    assert cap["overrides"] == {"serving": {"prefix_cache_max_blocks": 16}}
    assert cap["predicted"]["counter"] == "prefix_evictions"
    assert cap["predicted"]["value"] == 12
    hyst = by_id["widen_ladder_hysteresis"]
    assert hyst["overrides"] == {"serving": {"ladder_hysteresis": 0.2}}
    assert hyst["predicted"] == {"counter": "brownout_entries", "op": "<=",
                                 "value": 3, "baseline": 3,
                                 "unit": "entries"}
    # a healthy hit ratio under the same eviction pressure: cap rule quiet
    rep["prefix"]["prefix_hit_ratio"] = 0.8
    assert "raise_prefix_cache_max_blocks" not in {
        p["id"] for p in sa.propose_serve(rep)}


def test_rules_deterministically_ordered():
    rep = _mk_report(shares={"demote": 0.3, "promote": 0.1},
                     cfg={"prefix_cache_enabled": True,
                          "prefix_cache_max_blocks": 8},
                     bench={"demotions": 2, "demoted_bytes": 10,
                            "prefix_evictions": 4, "brownout_entries": 9},
                     prefix={"prefix_hit_ratio": 0.5})
    props = sa.propose_serve(rep)
    assert props == sa.propose_serve(rep)
    scores = [p["score"] for p in props]
    assert scores == sorted(scores, reverse=True)


def test_serving_defaults_pinned_to_config():
    """The stdlib-only defaults literal must track ServingConfig (the
    standalone-load contract forbids importing it in serve_attribution)."""
    from deepspeed_tpu.serving.server import ServingConfig
    cfg = ServingConfig()
    for key, val in sa.SERVING_DEFAULTS.items():
        assert getattr(cfg, key) == val, key


# ---------------------------------------------------------------------------
# regression ledger + CLI exit matrix
# ---------------------------------------------------------------------------
def _dilated_trace(factor=5):
    """Time-dilate every event by ``factor`` (ts and dur): every stage's
    per-tick ms grows uniformly and the ledger still ties out — the
    deterministic 'tick time grew Nx' regression seed."""
    with open(TRACE) as f:
        obj = json.load(f)
    for e in obj["traceEvents"]:
        if e.get("ph") == "M":
            continue
        e["ts"] = float(e.get("ts", 0)) * factor
        if "dur" in e:
            e["dur"] = float(e["dur"]) * factor
    return obj


def _write(tmp_path, name, obj):
    p = tmp_path / name
    p.write_text(json.dumps(obj))
    return str(p)


def test_seeded_regression_detected_and_stale_direction(tmp_path):
    bad = _write(tmp_path, "regressed.json", _dilated_trace())
    rep = sa.analyze_serve_path(bad)
    regressions, _ = sa.check_baseline(
        rep, sa.load_serve_plan_baseline(BASELINE))
    assert regressions
    assert all(r["ratio"] is None or r["ratio"] > 2.0 for r in regressions)
    # the other ratchet direction: a baseline recorded from the WORSE run
    # goes stale once the stage improves — explicit expiry only
    bl = tmp_path / "bl.json"
    sa.write_serve_plan_baseline(str(bl), rep)
    good = sa.analyze_serve_path(REPORT)
    regressions, stale = sa.check_baseline(
        good, sa.load_serve_plan_baseline(str(bl)))
    assert regressions == []
    assert stale


def test_cli_exit_matrix(tmp_path, capsys):
    # 0: the checked-in artifact set is clean
    assert sa.main([REPORT, "--baseline", BASELINE]) == sa.EXIT_OK
    # 1: seeded regression (explicit --baseline always compares)
    bad = _write(tmp_path, "regressed.json", _dilated_trace())
    assert sa.main([bad, "--baseline", BASELINE]) == sa.EXIT_REGRESSION
    err = capsys.readouterr().err
    assert "REGRESSION" in err
    # --tolerance applies to the CHECK
    assert sa.main([bad, "--baseline", BASELINE,
                    "--tolerance", "1000"]) == sa.EXIT_OK
    # 2: garbage / no serving spans / report without a locatable trace
    garbage = tmp_path / "garbage.json"
    garbage.write_text("not json {")
    assert sa.main([str(garbage)]) == sa.EXIT_UNREADABLE
    nostep = _write(tmp_path, "nostep.json",
                    {"traceEvents": [_ev("engine/dispatch", 0, 10)]})
    assert sa.main([nostep]) == sa.EXIT_UNREADABLE
    orphan = _write(tmp_path, "orphan_report.json",
                    {"counters": {}, "provenance":
                     {"trace_path": "absent_trace.json"}})
    assert sa.main([orphan]) == sa.EXIT_UNREADABLE
    capsys.readouterr()


def test_workload_scoping_and_write_baseline(tmp_path, capsys):
    """Discovered baselines only judge their own workload; --write-baseline
    redirects rather than clobbering another workload's ratchet; stored
    tolerance survives ratchet rewrites (the plan_baseline contract)."""
    import shutil
    # discovered baseline of ANOTHER workload: comparison skipped, exit 0
    shutil.copy(BASELINE, tmp_path / sa.SERVE_PLAN_BASELINE_NAME)
    other = _write(tmp_path, "other_trace.json", _dilated_trace())
    assert sa.main([other, "--json"]) == sa.EXIT_OK
    assert json.loads(capsys.readouterr().out)["baseline"]["path"] is None
    # same basename: compared, regression detected
    same = _write(tmp_path, "micro_serve_trace.json", _dilated_trace())
    assert sa.main([same]) == sa.EXIT_REGRESSION
    capsys.readouterr()
    # write-baseline with explicit path stores the chosen tolerance and
    # keeps it across ratchet rewrites; fresh baseline is clean
    bl = tmp_path / "bl.json"
    assert sa.main([REPORT, "--baseline", str(bl), "--write-baseline",
                    "--tolerance", "3"]) == 0
    assert sa.load_serve_plan_baseline(str(bl))["tolerance"] == 3.0
    assert sa.main([REPORT, "--baseline", str(bl), "--write-baseline"]) == 0
    assert sa.load_serve_plan_baseline(str(bl))["tolerance"] == 3.0
    assert sa.main([REPORT, "--baseline", str(bl)]) == 0
    capsys.readouterr()


def test_cli_artifact_out_json(tmp_path, capsys):
    out = tmp_path / "serve_plan.json"
    rc = sa.main([REPORT, "--baseline", BASELINE, "--out", str(out),
                  "--json"])
    assert rc == 0
    printed = json.loads(capsys.readouterr().out)
    assert json.loads(out.read_text()) == printed
    assert printed["baseline"]["path"] == BASELINE
    assert printed["tie_out_violations"] == []


def test_bin_dstpu_plan_serve_subcommand_stays_jaxless():
    """`dstpu plan --serve` file-loads the stdlib-only analyzer — the
    jax-less contract itself is now the DS009 offline-purity rule (one
    subprocess keep-alive lives in test_plan.py); here we only pin that
    the subcommand works and the analyzer is DECLARED offline."""
    from deepspeed_tpu.tools.dslint.hotpath import OFFLINE_ONLY_MODULES
    assert "deepspeed_tpu/telemetry/serve_attribution.py" in \
        OFFLINE_ONLY_MODULES
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "dstpu"),
         "plan", "--serve", REPORT, "--baseline", BASELINE],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dstpu plan --serve" in proc.stdout


# ---------------------------------------------------------------------------
# satellites: request slicing, hotpath registration, env_report rows
# ---------------------------------------------------------------------------
def test_request_slice_plan_loadable(tmp_path, capsys):
    events = trace_report.load_events(TRACE)
    uids = sorted({(e.get("args") or {}).get("uid")
                   for e in events
                   if e.get("ph") == "X" and e.get("name") == "serve/prefill"
                   and (e.get("args") or {}).get("uid") is not None})
    assert uids
    uid = uids[0]
    sliced = trace_report.filter_request(events, uid)
    names = {e.get("name") for e in sliced}
    # the request's own retro-spans plus intersecting serve ticks ride
    assert {"serve/queued", "serve/prefill", "serve/tick"} <= names
    assert any(e.get("ph") == "M" for e in sliced)        # labels kept
    for e in sliced:      # no OTHER request's track leaks into the slice
        if e.get("ph") == "M":
            continue
        args = e.get("args") or {}
        if "uid" in args and e.get("name", "").startswith("serve/queued"):
            assert args["uid"] == uid
    # CLI round-trip: the slice is itself a plan-loadable trace
    out = tmp_path / "req_slice.json"
    rc = trace_report.main([TRACE, "--request", str(uid),
                            "--out", str(out), "--json"])
    assert rc == 0
    capsys.readouterr()
    rep = sa.analyze_serve_path(str(out))
    assert rep["ticks_total"] >= 1
    assert rep["requests"]["requests"] >= 1
    # unknown uid: exit 2, with the known uids in the message
    assert trace_report.main([TRACE, "--request", "999999"]) == 2
    capsys.readouterr()


def test_serve_plan_offline_only_and_hotpath_coverage(package_callgraph,
                                                      hot_reached):
    from deepspeed_tpu.tools.dslint.hotpath import OFFLINE_ONLY_MODULES
    assert "deepspeed_tpu/telemetry/serve_attribution.py" in \
        OFFLINE_ONLY_MODULES
    # the serve-tick clocks are inside the DS002 taint from _serve_once:
    # the lint PROVES the attribution substrate never host-syncs the tick
    g = package_callgraph
    for fn in ("_mark", "_emit_tick_spans", "_tick_stage_gauges"):
        key = g.resolve("deepspeed_tpu/serving/server.py",
                        f"InferenceServer.{fn}")
        assert key is not None, f"InferenceServer.{fn} gone"
        assert key in hot_reached, f"{fn} fell out of the hot taint"


def test_telemetry_lazy_serve_plan_reexport():
    code = (
        "import sys\n"
        "import deepspeed_tpu.telemetry as T\n"
        "assert 'deepspeed_tpu.telemetry.serve_attribution' "
        "not in sys.modules\n"
        "T.analyze_serve_path\n"
        "assert 'deepspeed_tpu.telemetry.serve_attribution' "
        "in sys.modules\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_tracer_counter_series_tails():
    """Satellite: counter_series reports p95/p99 via the shared quantile
    rule, and prometheus_lines exposes the tail stats under the single
    dstpu_trace_counter TYPE block."""
    t = Tracer(capacity=256)
    t.configure(enabled=True)
    for v in range(1, 21):
        t.counter("serve/kv_bytes", observed=v * 10)
    s = t.counter_series()["serve/kv_bytes"]["observed"]
    assert s == {"last": 200.0, "max": 200.0, "p95": 200.0, "p99": 200.0,
                 "count": 20}
    lines = t.prometheus_lines(prefix="serve/")
    assert sum(1 for ln in lines
               if ln.startswith("# TYPE dstpu_trace_counter")) == 1
    for stat in ("last", "max", "p95", "p99"):
        assert any(f'stat="{stat}"' in ln for ln in lines), stat


def test_env_report_serve_plan_rows(tmp_path, monkeypatch):
    from deepspeed_tpu.env_report import serve_plan_report
    monkeypatch.chdir(tmp_path)
    monkeypatch.delenv(sa.SERVE_PLAN_ARTIFACT_ENV, raising=False)
    rows = dict(serve_plan_report())
    assert "no artifact" in rows["serve plan"]
    assert "ratcheted" in rows["serve plan baseline"]   # repo baseline
    art = tmp_path / "serve_plan.json"
    rep = sa.analyze_serve_path(REPORT)
    rep["verifications"] = [{"verdict": "verified"},
                            {"verdict": "refuted"},
                            {"verdict": "verified"}]
    art.write_text(json.dumps(rep, default=str))
    monkeypatch.setenv(sa.SERVE_PLAN_ARTIFACT_ENV, str(art))
    rows = dict(serve_plan_report())
    assert str(art) in rows["serve plan"]
    assert "% of tick time" in rows["serve plan"]
    assert "2 verified/1 refuted/0 unverified" in rows["serve plan"]
    n = len(sa.load_serve_plan_baseline(BASELINE)["entries"])
    assert f"{n} stages ratcheted" in rows["serve plan baseline"]


# ---------------------------------------------------------------------------
# the closed loop: plan -> verify on the seeded presets (acceptance)
# ---------------------------------------------------------------------------
def _run_preset(tmp_path, scenario, builder, trace_name):
    """One seeded bench_serve run with the dstrace ring captured: returns
    the report path (provenance wired for the verify runner). Warmed once
    untraced first — a mid-run XLA compile stalls ticks and skews the
    BASELINE counters the predictions anchor on (the verify re-runs are
    warm by construction, so a cold baseline would compare apples to
    oranges; make_fixtures.py applies the same discipline)."""
    import dataclasses as dc

    from deepspeed_tpu.serving import bench_serve
    warm = bench_serve.build_tiny_server(**builder).start()
    try:
        bench_serve.run_scenario(warm, dc.replace(scenario, num_requests=6))
    finally:
        warm.stop(drain_timeout=30.0)
    tracer = get_tracer()
    tracer.clear()
    tracer.configure(enabled=True)
    server = bench_serve.build_tiny_server(**builder).start()
    try:
        report = bench_serve.run_scenario(server, scenario, provenance={
            "builder": builder, "trace_path": trace_name})
    finally:
        server.stop(drain_timeout=30.0)
    tracer.export_chrome(str(tmp_path / trace_name))
    tracer.configure(enabled=False)
    report_path = tmp_path / f"{scenario.name}_report.json"
    report_path.write_text(json.dumps(report, default=str))
    return str(report_path), report


def _verify_loop(tmp_path, report_path, max_proposals=3):
    from deepspeed_tpu.autotuning.serve_verify import verify_serve_plan
    plan = sa.analyze_serve_path(report_path)
    for w in plan["windows"]:
        assert _stage_sum_us(w) == pytest.approx(w["dur_us"], abs=0.01)
        assert w["tie_out_error"] <= sa.TIE_OUT_TOLERANCE
    assert plan["proposals"], "the engineered siege must trip a rule"
    art = tmp_path / "serve_plan.json"
    art.write_text(json.dumps(plan, default=str))
    verdicts = verify_serve_plan(str(art), results_dir=str(tmp_path),
                                 max_proposals=max_proposals)
    get_tracer().configure(enabled=False)
    assert verdicts
    assert all(v["verdict"] in ("verified", "refuted", "unverified")
               for v in verdicts)
    # the acceptance bar: at least one prediction held EXACTLY
    assert any(v["verdict"] == "verified" for v in verdicts), verdicts
    # persisted under plan.serve_verifications in autotuning_results.json
    results = json.load(open(tmp_path / "autotuning_results.json"))
    assert results["plan"]["serve_verifications"] == verdicts
    # and written back into the artifact for env_report's tally
    assert json.loads(art.read_text())["verifications"] == verdicts
    return plan, verdicts


def test_overload_proposal_verify_loop(tmp_path):
    """Acceptance drill 1: the seeded overload preset with a starved
    prefix-cache cap and NO offload tier — every cache trim is cap-driven
    (the demote line does not exist, so `plan_prefix_evictions` evicts
    over-cap only), which makes `prefix_evictions` strictly monotone in
    the cap: the one serving counter whose response to its knob dwarfs
    open-loop scheduler jitter even on a loaded CI host. (Demotion VOLUME,
    by contrast, saturates under deep overload — everything admitted past
    the device eventually spills whatever the watermark says — so the
    demote-watermark rule is exercised by the fixture goldens and unit
    tests, and verified honestly in the wild where it may refute.)"""
    import dataclasses as dc

    from deepspeed_tpu.serving import bench_serve
    builder = {"kv_num_blocks": 64, "kv_block_size": 16,
               "kv_offload": False, "prefix_cache": True,
               "host_kv_quantize": "none",
               "serving_overrides": {"prefix_cache_max_blocks": 6,
                                     "max_queue_depth": 16}}
    scenario = dc.replace(bench_serve.SCENARIOS["overload"],
                          num_requests=24)
    report_path, report = _run_preset(tmp_path, scenario, builder,
                                      "overload_trace.json")
    assert report["counters"]["prefix_evictions"] > 0   # cap-driven trims
    plan, verdicts = _verify_loop(tmp_path, report_path)
    assert any(p["id"] == "raise_prefix_cache_max_blocks"
               for p in plan["proposals"])


def test_multi_turn_proposal_verify_loop(tmp_path):
    """Acceptance drill 2: the seeded multi_turn preset with a starved
    prefix-cache cap — the plan proposes raising the cap and the verify
    re-run proves the eviction-pressure prediction exactly."""
    import dataclasses as dc

    from deepspeed_tpu.serving import bench_serve
    builder = {"kv_num_blocks": 32, "kv_block_size": 16, "kv_offload": True,
               "prefix_cache": True, "host_kv_quantize": "int8",
               "serving_overrides": {"prefix_cache_max_blocks": 4,
                                     "kv_demote_watermark": 0.5}}
    scenario = dc.replace(bench_serve.SCENARIOS["multi_turn"],
                          num_requests=8)
    report_path, report = _run_preset(tmp_path, scenario, builder,
                                      "multi_turn_trace.json")
    assert report["counters"]["prefix_evictions"] > 0
    plan, _verdicts = _verify_loop(tmp_path, report_path)
    assert {p["id"] for p in plan["proposals"]} & \
        {"raise_prefix_cache_max_blocks", "raise_kv_demote_watermark"}
