"""GPT-NeoX / GPT-J family tests: partial rotary, parallel residual, training,
HF conversion, paged serving.

Reference analog: gptneox/gptj container cases under ``tests/unit/inference``.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt_neox import (
    GPTJ_6B, TINY_NEOX, GPTNeoXConfig, GPTNeoXForCausalLM,
    apply_partial_rotary, convert_hf_gpt_neox, gpt_neox_tensor_rules)
from deepspeed_tpu.models.llama import random_tokens


def test_partial_rotary_rotates_prefix_only():
    x = np.random.default_rng(0).normal(size=(2, 8, 4, 16)).astype(np.float32)
    pos = np.broadcast_to(np.arange(8), (2, 8))
    out = np.asarray(apply_partial_rotary(jnp.asarray(x), jnp.asarray(pos),
                                          8, 10000.0, 64))
    # tail passes through untouched; rotated prefix differs (except pos 0)
    np.testing.assert_allclose(out[..., 8:], x[..., 8:])
    assert not np.allclose(out[:, 1:, :, :8], x[:, 1:, :, :8])
    np.testing.assert_allclose(out[:, 0], x[:, 0], rtol=1e-6)  # angle 0


def test_presets():
    assert TINY_NEOX.rotary_dim_ == int(32 * 0.25) * 0 + (int(32 * 0.25) // 2) * 2
    assert GPTJ_6B.rotary_dim_ == 64
    assert GPTJ_6B.head_dim_ == 256


@pytest.mark.parametrize("parallel", [
    True, pytest.param(False, marks=pytest.mark.slow)])
def test_neox_trains(parallel):
    cfg = dataclasses.replace(TINY_NEOX, parallel_residual=parallel)
    model = GPTNeoXForCausalLM(cfg)
    config = {"train_batch_size": 8,
              "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
              "zero_optimization": {"stage": 3},
              "mesh": {"data": 2, "fsdp": 2, "tensor": 2}}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=config,
        example_batch=random_tokens(8, 16, vocab_size=cfg.vocab_size),
        tensor_rules=gpt_neox_tensor_rules)
    fixed = random_tokens(8, 16, vocab_size=cfg.vocab_size, seed=0)
    losses = [float(engine.train_batch(batch=fixed)) for _ in range(5)]
    assert losses[-1] < losses[0] and all(np.isfinite(losses))


def test_hf_conversion_roundtrip_forward():
    cfg = TINY_NEOX
    rng = np.random.default_rng(3)
    d, h, dh = cfg.hidden_size, cfg.num_heads, cfg.head_dim_
    hf = {"gpt_neox.embed_in.weight":
          rng.normal(size=(cfg.vocab_size, d)).astype(np.float32) * 0.02,
          "gpt_neox.final_layer_norm.weight": np.ones(d, np.float32),
          "gpt_neox.final_layer_norm.bias": np.zeros(d, np.float32),
          "embed_out.weight":
          rng.normal(size=(cfg.vocab_size, d)).astype(np.float32) * 0.02}
    for i in range(cfg.num_layers):
        p = f"gpt_neox.layers.{i}."
        hf[p + "input_layernorm.weight"] = np.ones(d, np.float32)
        hf[p + "input_layernorm.bias"] = np.zeros(d, np.float32)
        hf[p + "post_attention_layernorm.weight"] = np.ones(d, np.float32)
        hf[p + "post_attention_layernorm.bias"] = np.zeros(d, np.float32)
        hf[p + "attention.query_key_value.weight"] = \
            rng.normal(size=(3 * h * dh, d)).astype(np.float32) * 0.02
        hf[p + "attention.query_key_value.bias"] = np.zeros(3 * h * dh, np.float32)
        hf[p + "attention.dense.weight"] = \
            rng.normal(size=(d, d)).astype(np.float32) * 0.02
        hf[p + "attention.dense.bias"] = np.zeros(d, np.float32)
        hf[p + "mlp.dense_h_to_4h.weight"] = \
            rng.normal(size=(cfg.intermediate_size, d)).astype(np.float32) * 0.02
        hf[p + "mlp.dense_h_to_4h.bias"] = np.zeros(cfg.intermediate_size, np.float32)
        hf[p + "mlp.dense_4h_to_h.weight"] = \
            rng.normal(size=(d, cfg.intermediate_size)).astype(np.float32) * 0.02
        hf[p + "mlp.dense_4h_to_h.bias"] = np.zeros(d, np.float32)

    params = jax.tree.map(jnp.asarray, convert_hf_gpt_neox(hf, cfg))
    model = GPTNeoXForCausalLM(cfg)
    batch = random_tokens(2, 12, vocab_size=cfg.vocab_size)
    ref = model.init(jax.random.PRNGKey(0), batch)["params"]
    assert jax.tree.structure(ref) == jax.tree.structure(params)
    assert np.isfinite(float(model.apply({"params": params}, batch)))


@pytest.mark.parametrize("parallel", [
    pytest.param(True, marks=pytest.mark.slow),
    pytest.param(False, marks=pytest.mark.slow)])
def test_serve_neox_paged_matches_full(parallel):
    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, V2EngineConfig)
    from deepspeed_tpu.inference.v2.modules import GPTNeoXPolicy, policy_for
    from deepspeed_tpu.inference.v2.scheduler import SchedulerConfig

    cfg = dataclasses.replace(TINY_NEOX, parallel_residual=parallel)
    assert policy_for(cfg) is GPTNeoXPolicy
    model = GPTNeoXForCausalLM(cfg)
    prompt = list(np.random.default_rng(6).integers(0, cfg.vocab_size, 10))
    params = model.init(jax.random.PRNGKey(2),
                        random_tokens(1, 8, vocab_size=cfg.vocab_size))["params"]
    engine = InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=64,
        scheduler=SchedulerConfig(max_tokens_per_step=64,
                                  prefill_buckets=(16, 32, 64))))
    got = engine.generate(list(prompt), max_new_tokens=4)
    ids = list(prompt)
    for _ in range(4):
        logits = model.apply({"params": params},
                             jnp.asarray([ids], jnp.int32),
                             method=lambda m, x: m.model(x))
        ids.append(int(np.argmax(np.asarray(logits)[0, -1])))
    assert got == ids[len(prompt):], (got, ids[len(prompt):])
