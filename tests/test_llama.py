"""Llama model tests: forward/loss, TP equivalence, ZeRO composition, remat/scan.

Reference analog: tests/unit/model_parallelism + inference model tests — numerical
equivalence across parallelism configs on random weights.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import create_mesh
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.models.llama import (
    TINY_LLAMA,
    LlamaConfig,
    LlamaForCausalLM,
    llama_tensor_rules,
    random_tokens,
)

CFG = {
    "train_batch_size": 8,
    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
    "bf16": {"enabled": True},
}


def test_forward_loss_finite():
    model = LlamaForCausalLM(TINY_LLAMA)
    batch = random_tokens(2, 16)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    loss = model.apply({"params": params}, batch)
    assert np.isfinite(float(loss))
    # random init => loss ~ log(vocab)
    assert abs(float(loss) - np.log(TINY_LLAMA.vocab_size)) < 1.0


def test_logits_shape():
    model = LlamaForCausalLM(TINY_LLAMA)
    batch = random_tokens(2, 16)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    logits = model.apply({"params": params}, batch, method=LlamaForCausalLM.logits)
    assert logits.shape == (2, 16, TINY_LLAMA.vocab_size)


def test_causality():
    """Changing a future token must not change past logits."""
    model = LlamaForCausalLM(TINY_LLAMA)
    batch = random_tokens(1, 16, seed=0)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    logits1 = model.apply({"params": params}, batch, method=LlamaForCausalLM.logits)
    batch2 = {"input_ids": batch["input_ids"].copy()}
    batch2["input_ids"][0, -1] = (batch2["input_ids"][0, -1] + 1) % TINY_LLAMA.vocab_size
    logits2 = model.apply({"params": params}, batch2, method=LlamaForCausalLM.logits)
    np.testing.assert_allclose(np.asarray(logits1[0, :-1]), np.asarray(logits2[0, :-1]),
                               atol=1e-5)


def test_tp_matches_single_device():
    """TP=4 sharded logits == replicated logits (AutoTP-rule correctness)."""
    cfg = LlamaConfig(**{**TINY_LLAMA.__dict__, "dtype": jnp.float32})
    model = LlamaForCausalLM(cfg)
    batch = random_tokens(2, 16)
    params = model.init(jax.random.PRNGKey(1), batch)["params"]
    ref = model.apply({"params": params}, batch, method=LlamaForCausalLM.logits)

    mesh = create_mesh(MeshConfig(data=2, tensor=4))
    from deepspeed_tpu.runtime.zero.partition import build_param_shardings
    shardings = build_param_shardings(params, mesh, stage=0,
                                      tensor_rules=llama_tensor_rules)
    sharded = jax.device_put(params, shardings)
    # at least one param actually TP-sharded
    specs = [str(s.spec) for s in jax.tree.leaves(shardings)]
    assert any("tensor" in s for s in specs), specs
    out = jax.jit(lambda p, b: model.apply({"params": p}, b,
                                           method=LlamaForCausalLM.logits))(sharded, batch)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-4, rtol=1e-4)


def test_train_llama_zero3_tp(mesh8=None):
    """End-to-end: ZeRO-3 + TP on a (data=2, fsdp=2, tensor=2) mesh; loss decreases."""
    mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    cfg = dict(CFG)
    cfg["zero_optimization"] = {"stage": 3}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=LlamaForCausalLM(TINY_LLAMA), config=cfg, mesh=mesh,
        example_batch=random_tokens(2, 16), tensor_rules=llama_tensor_rules)
    batch = random_tokens(8, 16, seed=0)
    losses = [float(engine.train_batch(batch=batch)) for _ in range(8)]
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_remat_and_scan_variants_match():
    """remat and scan_layers change compilation, not numerics."""
    batch = random_tokens(2, 16)
    base = LlamaForCausalLM(TINY_LLAMA)
    params = base.init(jax.random.PRNGKey(2), batch)["params"]
    ref = base.apply({"params": params}, batch)

    remat_model = LlamaForCausalLM(
        LlamaConfig(**{**TINY_LLAMA.__dict__, "remat": True}))
    out = remat_model.apply({"params": params}, batch)
    np.testing.assert_allclose(float(ref), float(out), rtol=1e-5)

    scan_model = LlamaForCausalLM(
        LlamaConfig(**{**TINY_LLAMA.__dict__, "scan_layers": True}))
    scan_params = scan_model.init(jax.random.PRNGKey(2), batch)["params"]
    out2 = scan_model.apply({"params": scan_params}, batch)
    assert np.isfinite(float(out2))


def test_gqa_heads():
    """num_kv_heads < num_heads (GQA) works."""
    cfg = LlamaConfig(**{**TINY_LLAMA.__dict__, "num_heads": 8, "num_kv_heads": 2})
    model = LlamaForCausalLM(cfg)
    batch = random_tokens(2, 8)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    assert np.isfinite(float(model.apply({"params": params}, batch)))


@pytest.mark.slow
def test_chunked_loss_matches_dense():
    """Chunked head+CE fusion (sequence/cross_entropy.py:chunked_cross_entropy)
    must reproduce the dense log_softmax loss and grads, tied and untied."""
    import dataclasses

    from deepspeed_tpu.models.llama import TINY_LLAMA, LlamaForCausalLM, random_tokens

    cfg_d = dataclasses.replace(TINY_LLAMA, dtype=jnp.float32)
    cfg_c = dataclasses.replace(cfg_d, loss_chunk_size=24)
    batch = random_tokens(2, 36, vocab_size=cfg_d.vocab_size)
    m_d, m_c = LlamaForCausalLM(cfg_d), LlamaForCausalLM(cfg_c)
    p = m_d.init(jax.random.PRNGKey(0), batch)["params"]
    assert jax.tree.structure(p) == jax.tree.structure(
        m_c.init(jax.random.PRNGKey(0), batch)["params"])
    np.testing.assert_allclose(
        float(m_d.apply({"params": p}, batch)),
        float(m_c.apply({"params": p}, batch)), rtol=1e-6)
    gd = jax.grad(lambda v: m_d.apply({"params": v}, batch))(p)
    gc = jax.grad(lambda v: m_c.apply({"params": v}, batch))(p)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6), gd, gc)

    cfg_t = dataclasses.replace(cfg_d, tie_embeddings=True)
    cfg_tc = dataclasses.replace(cfg_t, loss_chunk_size=24)
    pt = LlamaForCausalLM(cfg_t).init(jax.random.PRNGKey(1), batch)["params"]
    np.testing.assert_allclose(
        float(LlamaForCausalLM(cfg_t).apply({"params": pt}, batch)),
        float(LlamaForCausalLM(cfg_tc).apply({"params": pt}, batch)), rtol=1e-6)


def test_chunked_cross_entropy_function_parity():
    """Fast default-run coverage of the chunked head+CE fusion at the
    function level (the full-model integration runs under -m slow)."""
    from deepspeed_tpu.sequence.cross_entropy import chunked_cross_entropy

    rng = np.random.default_rng(0)
    b, s, h, v = 2, 10, 16, 64
    hidden = jnp.asarray(rng.normal(size=(b, s, h)).astype(np.float32))
    kernel = jnp.asarray(rng.normal(size=(h, v)).astype(np.float32) * 0.1)
    labels = jnp.asarray(rng.integers(0, v, size=(b, s)).astype(np.int32))
    mask = jnp.asarray((rng.random((b, s)) > 0.2).astype(np.float32))

    def dense(hid, k):
        logits = (hid @ k).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, -1)
        ll = jnp.take_along_axis(logp, labels[..., None], -1)[..., 0]
        return -jnp.sum(ll * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    def chunked(hid, k, unroll=False):
        return chunked_cross_entropy(hid, labels, mask, kernel=k,
                                     chunk_size=6,  # uneven: pads 20 -> 24
                                     compute_dtype=jnp.float32, unroll=unroll)

    np.testing.assert_allclose(float(chunked(hidden, kernel)),
                               float(dense(hidden, kernel)), rtol=1e-6)
    gc = jax.grad(chunked, argnums=(0, 1))(hidden, kernel)
    gd = jax.grad(dense, argnums=(0, 1))(hidden, kernel)
    for a, c in zip(gc, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-5, atol=1e-6)
    # embedding (tied) spelling matches the kernel spelling
    from deepspeed_tpu.sequence.cross_entropy import chunked_cross_entropy as cce
    tied = cce(hidden, labels, mask, embedding=kernel.T, chunk_size=6,
               compute_dtype=jnp.float32)
    np.testing.assert_allclose(float(tied), float(dense(hidden, kernel)),
                               rtol=1e-6)
    # unrolled chunk loop: same value and grads as the scan formulation
    np.testing.assert_allclose(float(chunked(hidden, kernel, unroll=True)),
                               float(dense(hidden, kernel)), rtol=1e-6)
    gu = jax.grad(lambda hh, kk: chunked(hh, kk, unroll=True),
                  argnums=(0, 1))(hidden, kernel)
    for a, c in zip(gu, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-5, atol=1e-6)
