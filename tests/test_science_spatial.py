"""Evoformer attention + spatial (diffusion) ops tests.

Reference analog: tests/unit/ops/spatial/test_nhwc_bias_add.py and the
DS4Science evoformer kernel tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.evoformer_attn import (
    DS4Sci_EvoformerAttention, evoformer_attention,
    evoformer_attention_reference)
from deepspeed_tpu.ops.spatial import (
    group_norm, nhwc_bias_add, nhwc_bias_add_add, nhwc_bias_add_bias_add)


def _evo_inputs(seed=0, b=2, n=3, l=48, h=4, d=16):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, n, l, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, n, l, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, n, l, h, d)), jnp.float32)
    # AlphaFold-style: mask bias [B, N, 1, 1, L], pair bias [B, 1, H, L, L]
    bias1 = jnp.asarray(np.where(rng.random((b, n, 1, 1, l)) < 0.1, -1e9, 0.0),
                        jnp.float32)
    bias2 = jnp.asarray(rng.normal(size=(b, 1, h, l, l)), jnp.float32)
    return q, k, v, bias1, bias2


@pytest.mark.parametrize("nbias", [0, 1, 2])
def test_evoformer_attention_matches_reference(nbias):
    q, k, v, bias1, bias2 = _evo_inputs()
    biases = [bias1, bias2][:nbias]
    out = DS4Sci_EvoformerAttention(q, k, v, biases)
    ref = evoformer_attention_reference(q, k, v, biases)
    assert out.shape == q.shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


def test_evoformer_blockwise_matches_full():
    """block_k smaller than L exercises the online-softmax accumulation."""
    q, k, v, bias1, bias2 = _evo_inputs(l=50)   # non-divisible -> padding
    out = evoformer_attention(q, k, v, (bias1, bias2), block_k=16)
    ref = evoformer_attention_reference(q, k, v, (bias1, bias2))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


def test_evoformer_grads_including_bias():
    q, k, v, bias1, bias2 = _evo_inputs(l=32)

    def loss_b(q, k, v, b1, b2):
        return jnp.sum(evoformer_attention(q, k, v, (b1, b2), block_k=8) ** 2)

    def loss_r(q, k, v, b1, b2):
        return jnp.sum(evoformer_attention_reference(q, k, v, (b1, b2)) ** 2)

    gb = jax.grad(loss_b, argnums=(0, 1, 2, 3, 4))(q, k, v, bias1, bias2)
    gr = jax.grad(loss_r, argnums=(0, 1, 2, 3, 4))(q, k, v, bias1, bias2)
    for a, b in zip(gb, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3,
                                   rtol=1e-3)


# ------------------------------------------------------------- spatial
def test_nhwc_bias_add_family():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 16, 16, 192)), jnp.float32)
    other = jnp.asarray(rng.normal(size=(2, 16, 16, 192)), jnp.float32)
    b1 = jnp.asarray(rng.normal(size=(192,)), jnp.float32)
    b2 = jnp.asarray(rng.normal(size=(192,)), jnp.float32)
    np.testing.assert_allclose(np.asarray(nhwc_bias_add(x, b1)),
                               np.asarray(x) + np.asarray(b1), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(nhwc_bias_add_add(x, b1, other)),
        np.asarray(x) + np.asarray(b1) + np.asarray(other), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(nhwc_bias_add_bias_add(x, b1, other, b2)),
        np.asarray(x) + np.asarray(b1) + np.asarray(other) + np.asarray(b2),
        atol=1e-6)


def test_nhwc_bias_add_nchw_axis():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 192, 8, 8)), jnp.float32)  # NCHW
    b = jnp.asarray(rng.normal(size=(192,)), jnp.float32)
    out = nhwc_bias_add(x, b, channel_axis=1)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(x) + np.asarray(b)[None, :, None, None],
                               atol=1e-6)


def test_group_norm_matches_manual():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(2, 8, 8, 32)) * 3 + 1, jnp.float32)
    scale = jnp.asarray(rng.normal(size=(32,)), jnp.float32)
    bias = jnp.asarray(rng.normal(size=(32,)), jnp.float32)
    out = group_norm(x, scale, bias, num_groups=4)
    xr = np.asarray(x).reshape(2, -1, 4, 8)
    mu = xr.mean(axis=(1, 3), keepdims=True)
    var = xr.var(axis=(1, 3), keepdims=True)
    ref = ((xr - mu) / np.sqrt(var + 1e-5)).reshape(x.shape) * \
        np.asarray(scale) + np.asarray(bias)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4, rtol=1e-4)


def test_evoformer_memory_scales_linearly_not_quadratically():
    """The CUTLASS-memory-efficiency claim, measured: the blockwise scan's
    compiled peak temp memory grows O(L), not O(L^2) — the [.., L, L]
    attention matrix never materializes (XLA memory_analysis on the
    compiled module; 4x sequence -> <6x temps, a full-logits version
    would be ~16x)."""
    from deepspeed_tpu.ops.evoformer_attn import evoformer_attention

    def peak_temp(L):
        rng = np.random.default_rng(0)
        mk = lambda *s: jnp.asarray(rng.normal(size=s), jnp.float32)
        q, k, v = (mk(1, 2, L, 4, 16) for _ in range(3))
        b1 = mk(1, 1, 1, L, L)
        f = jax.jit(lambda q, k, v, b: jnp.sum(
            evoformer_attention(q, k, v, (b,), block_k=128)))
        return f.lower(q, k, v, b1).compile().memory_analysis() \
            .temp_size_in_bytes

    t256, t1024 = peak_temp(256), peak_temp(1024)
    assert t1024 < 6 * t256, (t256, t1024)
