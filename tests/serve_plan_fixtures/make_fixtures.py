"""Regenerate the checked-in serve-plan fixtures for tests/test_serve_plan.py.

Run from the repo root (CPU is fine — the fixtures are frozen so the
golden attribution assertions stay deterministic across hosts):

    JAX_PLATFORMS=cpu python tests/serve_plan_fixtures/make_fixtures.py

One pinned artifact set, regenerated together (the golden test pins their
agreement):

  micro_serve_trace.json    dstrace dump of a small seeded siege run on
                            the tiny CPU llama: kv offload with a LOW
                            demote watermark (demote churn shows at micro
                            request counts), prefix cache with a small
                            soft cap (eviction pressure shows), open-loop
                            arrivals (backpressure shows) — so the tick
                            ledger carries every stage
  micro_serve_report.json   the bench_serve report for the same run, with
                            provenance (preset/seed/scenario/serving
                            config/builder + relative trace_path) — the
                            preferred `dstpu plan --serve` input
  ../../serve_plan_baseline.json   the regression ratchet anchored to the
                            trace's attribution (workload-scoped by trace
                            basename, dslint/plan idiom)

The run is warmed once untraced first so XLA compiles don't dominate the
frozen tick quantiles. Regression-variant traces for the exit-code matrix
are derived in-test (demote spans grown into their windows) — never
checked in.
"""

import dataclasses
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, REPO)
HERE = os.path.dirname(os.path.abspath(__file__))

#: the fixture workload: a scaled seeded overload mix (open-loop arrivals,
#: shared prefixes, low-priority lanes) small enough to trace in seconds
BUILDER = {"kv_num_blocks": 48, "kv_block_size": 16, "kv_offload": True,
           "prefix_cache": True, "host_kv_quantize": "int8",
           "serving_overrides": {"kv_demote_watermark": 0.45,
                                 "kv_demote_watermark_brownout": 0.3,
                                 "prefix_cache_max_blocks": 6,
                                 "max_queue_depth": 16}}


def _scenario():
    from deepspeed_tpu.serving.bench_serve import SCENARIOS
    return dataclasses.replace(SCENARIOS["overload"], num_requests=24)


def main():
    from deepspeed_tpu.serving.bench_serve import (build_tiny_server,
                                                   run_scenario)
    from deepspeed_tpu.telemetry import get_tracer

    tracer = get_tracer()
    scenario = _scenario()

    # --- warmup (compile the siege shapes outside the trace) ---------------
    server = build_tiny_server(**BUILDER).start()
    try:
        run_scenario(server, dataclasses.replace(scenario, num_requests=6))
    finally:
        server.stop(drain_timeout=30.0)
    tracer.clear()

    # --- the traced fixture run --------------------------------------------
    tracer.configure(enabled=True)
    server = build_tiny_server(**BUILDER).start()
    try:
        report = run_scenario(server, scenario, provenance={
            "builder": BUILDER, "trace_path": "micro_serve_trace.json"})
    finally:
        server.stop(drain_timeout=30.0)
    tracer.configure(enabled=False)

    trace_path = os.path.join(HERE, "micro_serve_trace.json")
    with open(trace_path, "w") as f:
        json.dump(tracer.to_chrome(), f, default=str)
    print(f"wrote {trace_path} ({len(tracer.events_snapshot())} events)")
    tracer.clear()

    report_path = os.path.join(HERE, "micro_serve_report.json")
    with open(report_path, "w") as f:
        json.dump(report, f, indent=2, default=str)
        f.write("\n")
    print(f"wrote {report_path}")

    # --- regression baseline (ratchet anchor, one artifact set) ------------
    from deepspeed_tpu.telemetry import serve_attribution
    rep = serve_attribution.analyze_serve_path(report_path)
    bl = os.path.join(REPO, serve_attribution.SERVE_PLAN_BASELINE_NAME)
    serve_attribution.write_serve_plan_baseline(bl, rep)
    print(f"wrote {bl}")
    print(f"ticks={rep['ticks_total']} proposals="
          f"{[p['id'] for p in rep['proposals']]}")


if __name__ == "__main__":
    main()
