"""Config system tests (reference analog: tests/unit/runtime/test_ds_config_dict.py)."""

import pytest

from deepspeed_tpu.config.config import DeepSpeedTPUConfig, MeshConfig


def test_batch_size_reconciliation_all_given():
    cfg = DeepSpeedTPUConfig({
        "train_batch_size": 32,
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 2,
    }, dp_world_size=4)
    assert cfg.train_batch_size == 32
    assert cfg.train_micro_batch_size_per_gpu == 4
    assert cfg.gradient_accumulation_steps == 2


def test_batch_size_derive_gas():
    cfg = DeepSpeedTPUConfig({
        "train_batch_size": 64,
        "train_micro_batch_size_per_gpu": 4,
    }, dp_world_size=4)
    assert cfg.gradient_accumulation_steps == 4


def test_batch_size_derive_train_batch():
    cfg = DeepSpeedTPUConfig({
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 3,
    }, dp_world_size=8)
    assert cfg.train_batch_size == 48


def test_batch_size_mismatch_raises():
    with pytest.raises(ValueError):
        DeepSpeedTPUConfig({
            "train_batch_size": 30,
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 2,
        }, dp_world_size=4)


def test_zero_config_defaults():
    cfg = DeepSpeedTPUConfig({"zero_optimization": {"stage": 3}})
    assert cfg.zero_config.stage == 3
    assert cfg.zero_enabled
    assert cfg.zero_config.offload_optimizer.device == "none"


def test_zero_invalid_stage():
    with pytest.raises(Exception):
        DeepSpeedTPUConfig({"zero_optimization": {"stage": 5}})


def test_fp16_bf16_precision_dtype():
    import jax.numpy as jnp
    assert DeepSpeedTPUConfig({"bf16": {"enabled": True}}).precision_dtype == jnp.bfloat16
    assert DeepSpeedTPUConfig({"fp16": {"enabled": True}}).precision_dtype == jnp.float16
    assert DeepSpeedTPUConfig({}).precision_dtype == jnp.float32


def test_fp16_dynamic_vs_static():
    cfg = DeepSpeedTPUConfig({"fp16": {"enabled": True, "loss_scale": 128.0}})
    assert not cfg.fp16.dynamic
    cfg = DeepSpeedTPUConfig({"fp16": {"enabled": True}})
    assert cfg.fp16.dynamic


def test_cuda_only_keys_ignored():
    cfg = DeepSpeedTPUConfig({"amp": {"enabled": True}, "train_batch_size": 8})
    assert cfg.train_batch_size == 8


def test_mesh_config_defaults():
    m = MeshConfig()
    assert m.data == -1 and m.fsdp == 1 and m.tensor == 1


def test_optimizer_scheduler_parse():
    cfg = DeepSpeedTPUConfig({
        "optimizer": {"type": "AdamW", "params": {"lr": 3e-4}},
        "scheduler": {"type": "WarmupLR", "params": {"warmup_num_steps": 10}},
    })
    assert cfg.optimizer.type == "AdamW"
    assert cfg.scheduler.type == "WarmupLR"


def test_initialize_accepts_megatron_mpu():
    """reference: deepspeed.initialize(..., mpu=) reads world sizes off the
    Megatron mpu object (engine.py:1184)."""
    import deepspeed_tpu
    from deepspeed_tpu.models.simple import SimpleModel, random_batch

    class FakeMPU:
        def get_tensor_model_parallel_world_size(self):
            return 2

        def get_pipeline_model_parallel_world_size(self):
            return 1

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=32),
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}},
        mpu=FakeMPU(), example_batch=random_batch(4))
    assert engine.mesh.shape["tensor"] == 2
    assert engine.mesh.shape["data"] == 4
    import numpy as np
    assert np.isfinite(float(engine.train_batch(batch=random_batch(8))))


def test_batch_size_gas_only_preserved():
    """gas alone must survive resolution (micro defaults to 1, train batch
    follows) — regression: the missing branch used to clobber gas to 1,
    silently degenerating the pipeline engine's 1F1B microbatching."""
    cfg = DeepSpeedTPUConfig({"gradient_accumulation_steps": 4})
    cfg.resolve_batch_sizes(2)
    assert cfg.gradient_accumulation_steps == 4
    assert cfg.train_micro_batch_size_per_gpu == 1
    assert cfg.train_batch_size == 8
