"""Examples stay runnable (reference: DeepSpeedExamples smoke coverage).

Each example runs in a fresh process on the virtual CPU platform; slow-marked
(each pays jax startup + compiles).
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args, timeout=420):
    env = dict(os.environ, DSTPU_FORCE_CPU="1")
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "examples", script), *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=_ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    return proc.stdout


def test_pretrain_example_with_resume(tmp_path):
    out = _run("pretrain_llama.py", "--steps", "6",
               "--ckpt_dir", str(tmp_path / "ckpt"))
    assert "checkpoint saved" in out
    out2 = _run("pretrain_llama.py", "--steps", "2", "--resume",
                "--ckpt_dir", str(tmp_path / "ckpt"))
    assert "step 1:" in out2


def test_offload_example():
    assert "loss" in _run("offload_infinity.py", "--steps", "5")


def test_serve_example_two_archs():
    for arch in ("llama", "gpt_neox"):
        out = _run("serve_fastgen.py", "--arch", arch, "--requests", "3",
                   "--max_new_tokens", "3")
        assert f"{arch}: served 3 requests" in out


def test_rlhf_example():
    assert "rlhf hybrid flip OK" in _run("rlhf_hybrid.py", "--iters", "2")


def test_long_context_example():
    for backend in ("ring", "ulysses"):
        out = _run("long_context.py", "--backend", backend, "--seq", "256",
                   "--steps", "3")
        assert f"{backend} sp=4 seq=256" in out


def test_llama70b_north_star_dryrun():
    """Both v5e-16 memory plans (ZeRO-3+offload_optimizer / offload_param
    streaming) run the full config mechanics on 16 virtual devices."""
    for mode in ("fsdp", "stream"):
        out = _run("llama70b_v5e16.py", "--dryrun", "--mode", mode)
        assert "ok" in out and "losses" in out


def test_pretrain_packed():
    out = _run("pretrain_llama.py", "--steps", "4", "--packed")
    assert "slot utilization" in out and "step 3:" in out
