"""Activation checkpointing subsystem tests.

Reference analog: ``tests/unit/runtime/activation_checkpointing/`` — recompute
must not change values/grads; partition/offload options must compose.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.comm.mesh import create_mesh, set_global_mesh
from deepspeed_tpu.config.config import (
    ActivationCheckpointingConfig, DeepSpeedTPUConfig)
from deepspeed_tpu.runtime.activation_checkpointing import (
    checkpoint, checkpoint_name, partition_sequence, resolve_policy)


def _block(w):
    def fn(x):
        h = checkpoint_name(jnp.tanh(x @ w), "attn_out")
        return checkpoint_name(h @ w.T + x, "block_out")
    return fn


def test_checkpoint_preserves_values_and_grads():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(16, 32)).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(4, 16)).astype(np.float32))
    fn = _block(w)
    for policy in ("nothing_saveable", "dots_saveable", "save_only_names"):
        cfg = ActivationCheckpointingConfig(policy=policy)
        ck = checkpoint(fn, cfg)
        np.testing.assert_allclose(np.asarray(ck(x)), np.asarray(fn(x)),
                                   rtol=1e-6)
        g0 = jax.grad(lambda v: jnp.sum(fn(v) ** 2))(x)
        g1 = jax.grad(lambda v: jnp.sum(ck(v) ** 2))(x)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), rtol=1e-5)


def test_cpu_checkpointing_offload_policy_compiles():
    # offload to pinned_host inside grad: value/grad parity is the contract
    # (reference checkpoint_in_cpu, checkpointing.py:527)
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=(8, 8)).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(4, 8)).astype(np.float32))
    fn = _block(w)
    cfg = ActivationCheckpointingConfig(cpu_checkpointing=True)
    ck = checkpoint(fn, cfg)
    g0 = jax.grad(lambda v: jnp.sum(fn(v) ** 2))(x)
    g1 = jax.jit(jax.grad(lambda v: jnp.sum(ck(v) ** 2)))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), rtol=1e-5)


def test_partition_activations_shards_saved_inputs():
    from deepspeed_tpu.config.config import MeshConfig
    mesh = create_mesh(MeshConfig(data=2, sequence=4))
    set_global_mesh(mesh)
    try:
        x = jnp.ones((2, 8, 4))
        with mesh:
            y = jax.jit(partition_sequence)(x)
        assert "sequence" in str(y.sharding.spec)
        cfg = ActivationCheckpointingConfig(partition_activations=True)
        w = jnp.ones((4, 4))
        ck = checkpoint(lambda v: jnp.sum(jnp.tanh(v @ w)), cfg)
        with mesh:
            g = jax.jit(jax.grad(ck))(x)
        assert np.isfinite(np.asarray(g)).all()
    finally:
        set_global_mesh(None)


def test_config_block_parses_and_rejects_bad_policy():
    cfg = DeepSpeedTPUConfig({
        "train_batch_size": 8,
        "activation_checkpointing": {
            "partition_activations": True,
            "cpu_checkpointing": False,
            "contiguous_memory_optimization": True,
            "policy": "dots_saveable",
        },
    }, dp_world_size=1)
    assert cfg.activation_checkpointing.partition_activations
    assert resolve_policy(cfg.activation_checkpointing) is \
        jax.checkpoint_policies.dots_saveable
    with pytest.raises(ValueError):
        resolve_policy(ActivationCheckpointingConfig(policy="bogus"))
