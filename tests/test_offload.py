"""Offload tier tests: native C++ ops (cpu_adam, aio) and end-to-end
ZeRO-Offload / ZeRO-Infinity training.

Reference analog: tests/unit/ops/adam/test_cpu_adam.py, ops/aio tests, and
runtime/zero offload tests.
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.simple import SimpleModel, random_batch
from deepspeed_tpu.ops.async_io import AsyncIOHandle
from deepspeed_tpu.ops.cpu_adam import CPUAdam


def _ref_adamw(params, grads, m, v, lr, b1, b2, eps, wd, step):
    m = b1 * m + (1 - b1) * grads
    v = b2 * v + (1 - b2) * grads ** 2
    mhat = m / (1 - b1 ** step)
    vhat = v / (1 - b2 ** step)
    params = params - lr * (mhat / (np.sqrt(vhat) + eps) + wd * params)
    return params, m, v


def test_cpu_adam_matches_reference():
    rng = np.random.default_rng(0)
    n = 4097  # odd size exercises vector tail
    p = rng.normal(size=n).astype(np.float32)
    p_ref = p.copy()
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    m_ref, v_ref = m.copy(), v.copy()
    opt = CPUAdam(lr=1e-2, betas=(0.9, 0.99), eps=1e-8, weight_decay=0.01)
    for step in range(1, 4):
        g = rng.normal(size=n).astype(np.float32)
        opt.step(p, g, m, v)
        p_ref, m_ref, v_ref = _ref_adamw(p_ref, g, m_ref, v_ref,
                                         1e-2, 0.9, 0.99, 1e-8, 0.01, step)
    np.testing.assert_allclose(p, p_ref, atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(m, m_ref, atol=1e-6)


def test_cpu_adam_native_loaded():
    """The C++ kernel must actually build in this image (g++ present)."""
    opt = CPUAdam()
    assert opt._fn is not None, "native cpu_adam failed to build"


def test_aio_roundtrip(tmp_path):
    h = AsyncIOHandle(num_threads=4)
    rng = np.random.default_rng(1)
    data = rng.normal(size=(1 << 16,)).astype(np.float32)
    path = str(tmp_path / "swap.bin")
    wid = h.async_pwrite(data, path)
    assert h.wait(wid) == 0
    out = np.empty_like(data)
    rid = h.async_pread(out, path)
    assert h.wait(rid) == 0
    np.testing.assert_array_equal(out, data)


def test_aio_many_concurrent(tmp_path):
    h = AsyncIOHandle(num_threads=8)
    arrays = [np.full((4096,), i, np.float32) for i in range(16)]
    reqs = [h.async_pwrite(a, str(tmp_path / f"f{i}.bin"))
            for i, a in enumerate(arrays)]
    assert h.drain() == 0
    outs = [np.empty_like(a) for a in arrays]
    reqs = [h.async_pread(o, str(tmp_path / f"f{i}.bin"))
            for i, o in enumerate(outs)]
    for r in reqs:
        h.wait(r)
    for i, o in enumerate(outs):
        np.testing.assert_array_equal(o, arrays[i])


def _train(config, steps=10, mesh=None, seed=3):
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=64), config=config, mesh=mesh,
        example_batch=random_batch(4), seed=seed)
    losses = []
    for i in range(steps):
        losses.append(float(engine.train_batch(batch=random_batch(8, seed=i % 3))))
    return engine, losses


def test_zero_offload_cpu_training(mesh_dp8):
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2,
                              "offload_optimizer": {"device": "cpu"}},
    }
    engine, losses = _train(cfg, mesh=mesh_dp8)
    assert losses[-1] < losses[0]
    assert engine._offload is not None


def test_zero_infinity_nvme_training(tmp_path, mesh_dp8):
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2,
                              "offload_optimizer": {"device": "nvme",
                                                    "nvme_path": str(tmp_path)}},
    }
    engine, losses = _train(cfg, mesh=mesh_dp8)
    assert losses[-1] < losses[0]
    # moment files exist on "nvme"
    import glob
    assert glob.glob(str(tmp_path / "proc0" / "state0_*.bin"))


def test_offload_unsupported_optimizer_raises(mesh_dp8):
    """sgd has no fused host kernel — must fail loudly, not silently run Adam."""
    from deepspeed_tpu.runtime.offload import UnsupportedOffloadOptimizer
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "sgd", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1,
                              "offload_optimizer": {"device": "cpu"}},
    }
    with pytest.raises(UnsupportedOffloadOptimizer):
        _train(cfg, steps=0, mesh=mesh_dp8)


@pytest.mark.slow
def test_offload_lion_and_adagrad_train(mesh_dp8):
    for opt in ("lion", "adagrad"):
        cfg = {
            "train_batch_size": 8,
            "optimizer": {"type": opt, "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1,
                                  "offload_optimizer": {"device": "cpu"}},
        }
        engine, losses = _train(cfg, mesh=mesh_dp8)
        assert losses[-1] < losses[0], f"{opt} loss did not decrease: {losses}"


def test_offload_device_holds_no_optimizer_state(mesh_dp8):
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1,
                              "offload_optimizer": {"device": "cpu"}},
    }
    engine, _ = _train(cfg, steps=1, mesh=mesh_dp8)
    import jax
    assert jax.tree.leaves(engine.state.opt_state) == []  # nothing in HBM


@pytest.mark.slow
def test_offload_checkpoint_roundtrip(tmp_path, mesh_dp8):
    """save → load restores masters AND host moments; training continues from
    the restored weights (not stale masters)."""
    import jax
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1,
                              "offload_optimizer": {"device": "cpu"}},
    }
    e1, _ = _train(cfg, steps=4, mesh=mesh_dp8, seed=11)
    moments_before = [s.copy() for leaf in e1._offload.leaves for s in leaf.states]
    e1.save_checkpoint(str(tmp_path), tag="t0")

    e2, _ = _train(cfg, steps=0, mesh=mesh_dp8, seed=99)  # different init
    e2.load_checkpoint(str(tmp_path), tag="t0")
    # masters resynced to the checkpoint
    for a, b in zip(e1._offload.masters(), e2._offload.masters()):
        np.testing.assert_allclose(a, b, atol=1e-6)
    # host moments restored
    moments_after = [s for leaf in e2._offload.leaves for s in leaf.states]
    for a, b in zip(moments_before, moments_after):
        np.testing.assert_allclose(a, b, atol=1e-6)
    assert e2._offload.kernel.step_count == e1._offload.kernel.step_count
    # one more step trains FROM the restored weights (regression: stale masters
    # used to silently revert the load)
    p_loaded = [x.copy() for x in e2._offload.masters()]
    e2.train_batch(batch=random_batch(8, seed=0))
    drift = sum(float(np.abs(a - b).max())
                for a, b in zip(p_loaded, e2._offload.masters()))
    ref_drift = sum(float(np.abs(a - b).max())
                    for a, b in zip(p_loaded, e1._offload.masters()))
    assert drift > 0 and drift < 1.0  # moved, but from the loaded point


def test_offload_fp16_overflow_skips_step(mesh_dp8):
    """A non-finite grad must skip the host update and shrink the loss scale —
    never write NaN into masters/moments."""
    import jax

    def exploding_model(params, batch, rng):
        return (params["w"] * np.float32("inf")).sum()

    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "fp16": {"enabled": True, "initial_scale_power": 4, "hysteresis": 1},
        "zero_optimization": {"stage": 1,
                              "offload_optimizer": {"device": "cpu"}},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=exploding_model, config=cfg, mesh=mesh_dp8,
        model_parameters={"w": np.ones((4,), np.float32)})
    scale_before = engine.cur_scale()
    engine.train_batch(batch=np.zeros((8, 1), np.float32))
    assert engine.skipped_steps == 1
    assert engine.cur_scale() < scale_before
    for m in engine._offload.masters():
        assert np.isfinite(m).all()
    for leaf in engine._offload.leaves:
        for s in leaf.states:
            assert np.isfinite(s).all()


def test_offload_compat_fwd_bwd_step(mesh_dp8):
    """forward/backward/step protocol must use the host optimizer too."""
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1,
                              "offload_optimizer": {"device": "cpu"}},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=64), config=cfg, mesh=mesh_dp8,
        example_batch=random_batch(4), seed=3)
    masters_before = [x.copy() for x in engine._offload.masters()]
    losses = []
    for i in range(5):
        loss = engine.forward(random_batch(8, seed=i % 3))
        engine.backward()
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # masters moved (the host optimizer ran), device params track them
    moved = sum(float(np.abs(a - b).max())
                for a, b in zip(masters_before, engine._offload.masters()))
    assert moved > 0
    import jax
    for dev, host in zip(jax.tree.leaves(jax.device_get(engine.state.params)),
                         engine._offload.masters()):
        np.testing.assert_allclose(np.asarray(dev, np.float32), host,
                                   atol=1e-6, rtol=1e-5)


def test_offload_bf16_shadows_on_device(mesh_dp8):
    """With bf16 compute, device params are bf16 shadows (half the H2D bytes)."""
    import jax
    import jax.numpy as jnp
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1,
                              "offload_optimizer": {"device": "cpu"}},
    }
    # 6 steps, not 3: the convergence assertion compares losses on
    # DIFFERENT batches (seed=i%3), and under bf16 shadows the first
    # couple of steps are noisy enough on the CPU backend that a 3-step
    # horizon flips sign; by step 6 the drop is decisive
    engine, losses = _train(cfg, steps=6, mesh=mesh_dp8)
    for p in jax.tree.leaves(engine.state.params):
        assert p.dtype == jnp.bfloat16
    assert losses[-1] < losses[0]
    for m in engine._offload.masters():  # masters stay fp32
        assert m.dtype == np.float32


@pytest.mark.slow
def test_offload_matches_in_hbm_adamw(mesh_dp8):
    """Host CPU-Adam path == in-HBM optax path numerically."""
    base = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW",
                      "params": {"lr": 1e-2, "betas": (0.9, 0.999),
                                 "eps": 1e-8, "weight_decay": 0.0}},
    }
    off = dict(base)
    off["zero_optimization"] = {"stage": 1, "offload_optimizer": {"device": "cpu"}}
    e1, _ = _train(base, steps=5, mesh=mesh_dp8, seed=9)
    e2, _ = _train(off, steps=5, mesh=mesh_dp8, seed=9)
    import jax
    p1 = jax.device_get(e1.state.params)
    p2 = jax.device_get(e2.state.params)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-4)


def test_nvme_masters_swapped_full_infinity(tmp_path, mesh_dp8):
    """Full ZeRO-Infinity: with device=nvme the fp32 MASTERS live in files
    too (reference swaps the flat fp32 param shard alongside the moments);
    training matches the cpu tier numerically."""
    import glob as _glob
    nvme = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2,
                              "offload_optimizer": {"device": "nvme",
                                                    "nvme_path": str(tmp_path)}},
    }
    e1, l1 = _train(nvme, steps=4, mesh=mesh_dp8, seed=5)
    assert l1[-1] < l1[0]
    assert _glob.glob(str(tmp_path / "proc0" / "master_*.bin"))
    # swapped-out masters are not RAM-resident between steps
    assert all(l.master is None for l in e1._offload.leaves if l.master_path)
    cpu = {**nvme, "zero_optimization": {
        "stage": 2, "offload_optimizer": {"device": "cpu"}}}
    e2, l2 = _train(cpu, steps=4, mesh=mesh_dp8, seed=5)
    np.testing.assert_allclose(l1, l2, rtol=1e-6)
    for a, b in zip(e1._offload.masters(), e2._offload.masters()):
        np.testing.assert_allclose(a, b, atol=1e-7)


def test_nvme_swap_masters_false_keeps_masters_in_ram(tmp_path, mesh_dp8):
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2, "offload_optimizer": {
            "device": "nvme", "nvme_path": str(tmp_path),
            "swap_masters": False}},
    }
    # 6 steps for the same different-batch-comparison reason as the bf16
    # shadow test above: 3 steps is not a decisive convergence horizon
    e, losses = _train(cfg, steps=6, mesh=mesh_dp8)
    assert losses[-1] < losses[0]
    assert all(l.master is not None for l in e._offload.leaves)
    import glob as _glob
    assert not _glob.glob(str(tmp_path / "proc0" / "master_*.bin"))


def test_param_offload_nvme_with_master_swap(tmp_path):
    """offload_param nvme + masters-on-nvme compose: weights stream from
    files AND the fp32 masters round-trip through files each step."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                            random_tokens)
    cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                      num_layers=2, num_heads=2, num_kv_heads=2,
                      max_seq_len=32, dtype=jnp.float32,
                      attention_backend="xla", remat=False)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=LlamaForCausalLM(cfg),
        config={"train_batch_size": jax.device_count(),
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
                "zero_optimization": {
                    "stage": 0,
                    "offload_param": {"device": "nvme",
                                      "nvme_path": str(tmp_path)},
                    "offload_optimizer": {"device": "nvme",
                                          "nvme_path": str(tmp_path)}}},
        example_batch=random_tokens(2, 16, vocab_size=128))
    fixed = random_tokens(jax.device_count(), 16, vocab_size=128, seed=0)
    losses = [float(jax.device_get(engine.train_batch(batch=fixed)))
              for _ in range(4)]
    assert losses[-1] < losses[0], losses
    import glob as _glob
    assert _glob.glob(str(tmp_path / "proc0" / "master_*.bin"))
    assert _glob.glob(str(tmp_path / "params_proc0" / "group*.bin"))
