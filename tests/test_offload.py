"""Offload tier tests: native C++ ops (cpu_adam, aio) and end-to-end
ZeRO-Offload / ZeRO-Infinity training.

Reference analog: tests/unit/ops/adam/test_cpu_adam.py, ops/aio tests, and
runtime/zero offload tests.
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.simple import SimpleModel, random_batch
from deepspeed_tpu.ops.async_io import AsyncIOHandle
from deepspeed_tpu.ops.cpu_adam import CPUAdam


def _ref_adamw(params, grads, m, v, lr, b1, b2, eps, wd, step):
    m = b1 * m + (1 - b1) * grads
    v = b2 * v + (1 - b2) * grads ** 2
    mhat = m / (1 - b1 ** step)
    vhat = v / (1 - b2 ** step)
    params = params - lr * (mhat / (np.sqrt(vhat) + eps) + wd * params)
    return params, m, v


def test_cpu_adam_matches_reference():
    rng = np.random.default_rng(0)
    n = 4097  # odd size exercises vector tail
    p = rng.normal(size=n).astype(np.float32)
    p_ref = p.copy()
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    m_ref, v_ref = m.copy(), v.copy()
    opt = CPUAdam(lr=1e-2, betas=(0.9, 0.99), eps=1e-8, weight_decay=0.01)
    for step in range(1, 4):
        g = rng.normal(size=n).astype(np.float32)
        opt.step(p, g, m, v)
        p_ref, m_ref, v_ref = _ref_adamw(p_ref, g, m_ref, v_ref,
                                         1e-2, 0.9, 0.99, 1e-8, 0.01, step)
    np.testing.assert_allclose(p, p_ref, atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(m, m_ref, atol=1e-6)


def test_cpu_adam_native_loaded():
    """The C++ kernel must actually build in this image (g++ present)."""
    opt = CPUAdam()
    assert opt._fn is not None, "native cpu_adam failed to build"


def test_aio_roundtrip(tmp_path):
    h = AsyncIOHandle(num_threads=4)
    rng = np.random.default_rng(1)
    data = rng.normal(size=(1 << 16,)).astype(np.float32)
    path = str(tmp_path / "swap.bin")
    wid = h.async_pwrite(data, path)
    assert h.wait(wid) == 0
    out = np.empty_like(data)
    rid = h.async_pread(out, path)
    assert h.wait(rid) == 0
    np.testing.assert_array_equal(out, data)


def test_aio_many_concurrent(tmp_path):
    h = AsyncIOHandle(num_threads=8)
    arrays = [np.full((4096,), i, np.float32) for i in range(16)]
    reqs = [h.async_pwrite(a, str(tmp_path / f"f{i}.bin"))
            for i, a in enumerate(arrays)]
    assert h.drain() == 0
    outs = [np.empty_like(a) for a in arrays]
    reqs = [h.async_pread(o, str(tmp_path / f"f{i}.bin"))
            for i, o in enumerate(outs)]
    for r in reqs:
        h.wait(r)
    for i, o in enumerate(outs):
        np.testing.assert_array_equal(o, arrays[i])


def _train(config, steps=10, mesh=None, seed=3):
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=64), config=config, mesh=mesh,
        example_batch=random_batch(4), seed=seed)
    losses = []
    for i in range(steps):
        losses.append(float(engine.train_batch(batch=random_batch(8, seed=i % 3))))
    return engine, losses


def test_zero_offload_cpu_training(mesh_dp8):
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2,
                              "offload_optimizer": {"device": "cpu"}},
    }
    engine, losses = _train(cfg, mesh=mesh_dp8)
    assert losses[-1] < losses[0]
    assert engine._offload is not None


def test_zero_infinity_nvme_training(tmp_path, mesh_dp8):
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2,
                              "offload_optimizer": {"device": "nvme",
                                                    "nvme_path": str(tmp_path)}},
    }
    engine, losses = _train(cfg, mesh=mesh_dp8)
    assert losses[-1] < losses[0]
    # moment files exist on "nvme"
    import glob
    assert glob.glob(str(tmp_path / "proc0" / "exp_avg_*.bin"))


def test_offload_matches_in_hbm_adamw(mesh_dp8):
    """Host CPU-Adam path == in-HBM optax path numerically."""
    base = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW",
                      "params": {"lr": 1e-2, "betas": (0.9, 0.999),
                                 "eps": 1e-8, "weight_decay": 0.0}},
    }
    off = dict(base)
    off["zero_optimization"] = {"stage": 1, "offload_optimizer": {"device": "cpu"}}
    e1, _ = _train(base, steps=5, mesh=mesh_dp8, seed=9)
    e2, _ = _train(off, steps=5, mesh=mesh_dp8, seed=9)
    import jax
    p1 = jax.device_get(e1.state.params)
    p2 = jax.device_get(e2.state.params)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-4)
