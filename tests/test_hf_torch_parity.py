"""Torch-transformers logits parity for every ingestable family.

The per-family converter tests use synthetic (export/reimport) state dicts;
these tests hold the REAL contract: a random torch-transformers checkpoint
converted through from_hf_checkpoint must reproduce HF's logits. (MoE
families and gemma2 have their own parity tests alongside their models.)
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from deepspeed_tpu.models.hf import from_hf_checkpoint  # noqa: E402


def _parity(hf_model, hf_cfg_dict, ids, atol=3e-4, rtol=3e-3,
            batch=None, ref_fn=None):
    """Convert + compare logits vs torch. ``batch``/``ref_fn`` override the
    decoder-only defaults (seq2seq models pass decoder inputs)."""
    model, cfg, params = from_hf_checkpoint(hf_cfg_dict,
                                            hf_model.state_dict())
    # fp32 compute for tight comparison; dtype is shape-preserving so the
    # converted params carry over
    model = type(model)(dataclasses.replace(cfg, dtype=jnp.float32))
    with torch.no_grad():
        ref = ref_fn(hf_model) if ref_fn else \
            hf_model(torch.tensor(ids)).logits.numpy()
    if batch is None:
        batch = {"input_ids": jnp.asarray(ids.astype(np.int32))}
    ours = model.apply({"params": jax.tree.map(jnp.asarray, params)},
                       batch, method=type(model).logits)
    np.testing.assert_allclose(np.asarray(ours), ref, atol=atol, rtol=rtol)


def _ids(vocab, b=2, s=16, seed=0):
    return np.random.default_rng(seed).integers(1, vocab, size=(b, s))


@pytest.mark.slow
def test_hf_gpt2_torch_parity():
    from transformers import GPT2Config, GPT2LMHeadModel
    hf_cfg = GPT2Config(vocab_size=256, n_embd=64, n_layer=2, n_head=4,
                        n_positions=64, resid_pdrop=0.0, embd_pdrop=0.0,
                        attn_pdrop=0.0)
    torch.manual_seed(0)
    hf_model = GPT2LMHeadModel(hf_cfg).eval()
    _parity(hf_model, hf_cfg.to_dict(), _ids(256))


@pytest.mark.slow
def test_hf_opt_torch_parity():
    from transformers import OPTConfig, OPTForCausalLM
    hf_cfg = OPTConfig(vocab_size=256, hidden_size=64, ffn_dim=128,
                       num_hidden_layers=2, num_attention_heads=4,
                       max_position_embeddings=64, dropout=0.0,
                       word_embed_proj_dim=64, do_layer_norm_before=True)
    torch.manual_seed(0)
    hf_model = OPTForCausalLM(hf_cfg).eval()
    _parity(hf_model, hf_cfg.to_dict(), _ids(256))


@pytest.mark.slow
def test_hf_bloom_torch_parity():
    from transformers import BloomConfig, BloomForCausalLM
    hf_cfg = BloomConfig(vocab_size=256, hidden_size=64, n_layer=2,
                         n_head=4, hidden_dropout=0.0,
                         attention_dropout=0.0)
    torch.manual_seed(0)
    hf_model = BloomForCausalLM(hf_cfg).eval()
    _parity(hf_model, hf_cfg.to_dict(), _ids(256))


@pytest.mark.slow
def test_hf_gpt_neox_torch_parity():
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM
    hf_cfg = GPTNeoXConfig(vocab_size=256, hidden_size=64,
                           intermediate_size=128, num_hidden_layers=2,
                           num_attention_heads=4,
                           max_position_embeddings=64, rotary_pct=0.25,
                           hidden_dropout=0.0, attention_dropout=0.0,
                           use_parallel_residual=True)
    torch.manual_seed(0)
    hf_model = GPTNeoXForCausalLM(hf_cfg).eval()
    _parity(hf_model, hf_cfg.to_dict(), _ids(256))


@pytest.mark.slow
def test_hf_falcon_torch_parity():
    from transformers import FalconConfig, FalconForCausalLM
    hf_cfg = FalconConfig(vocab_size=256, hidden_size=64,
                          num_hidden_layers=2, num_attention_heads=4,
                          multi_query=True, parallel_attn=True, bias=False,
                          alibi=False, new_decoder_architecture=False,
                          hidden_dropout=0.0, attention_dropout=0.0)
    torch.manual_seed(0)
    hf_model = FalconForCausalLM(hf_cfg).eval()
    _parity(hf_model, hf_cfg.to_dict(), _ids(256))


@pytest.mark.slow
def test_hf_t5_torch_parity():
    from transformers import T5Config, T5ForConditionalGeneration
    hf_cfg = T5Config(vocab_size=256, d_model=64, d_kv=16, d_ff=128,
                      num_layers=2, num_decoder_layers=2, num_heads=4,
                      relative_attention_num_buckets=8,
                      relative_attention_max_distance=32,
                      dropout_rate=0.0, feed_forward_proj="relu",
                      tie_word_embeddings=True, decoder_start_token_id=0)
    torch.manual_seed(0)
    hf_model = T5ForConditionalGeneration(hf_cfg).eval()

    enc_ids = _ids(256, s=12)
    dec_ids = _ids(256, s=8, seed=1)
    _parity(
        hf_model, hf_cfg.to_dict(), enc_ids,
        batch={"input_ids": jnp.asarray(enc_ids.astype(np.int32)),
               "labels": jnp.asarray(dec_ids.astype(np.int32)),
               "decoder_input_ids": jnp.asarray(dec_ids.astype(np.int32))},
        ref_fn=lambda m: m(
            input_ids=torch.tensor(enc_ids),
            decoder_input_ids=torch.tensor(dec_ids)).logits.numpy())


@pytest.mark.slow
@pytest.mark.parametrize("mt", ["llama", "mistral", "qwen2", "gemma"])
def test_hf_llama_family_torch_parity(mt):
    """The flagship families against REAL HF logits (the roundtrip test
    only proves converter self-consistency). mistral exercises the sliding
    window, qwen2 the qkv biases, gemma the scaled-embed/tied/gelu path."""
    import transformers as tf
    mk = {
        "llama": (tf.LlamaConfig, tf.LlamaForCausalLM, {}),
        "mistral": (tf.MistralConfig, tf.MistralForCausalLM,
                    dict(sliding_window=8)),
        "qwen2": (tf.Qwen2Config, tf.Qwen2ForCausalLM,
                  dict(use_sliding_window=False)),
        "gemma": (tf.GemmaConfig, tf.GemmaForCausalLM,
                  dict(head_dim=16, hidden_activation="gelu_pytorch_tanh")),
    }[mt]
    cfg_cls, model_cls, extra = mk
    hf_cfg = cfg_cls(vocab_size=256, hidden_size=64, intermediate_size=128,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=2, max_position_embeddings=64,
                     rms_norm_eps=1e-6, attention_dropout=0.0, **extra)
    torch.manual_seed(0)
    hf_model = model_cls(hf_cfg).eval()
    _parity(hf_model, hf_cfg.to_dict(), _ids(256, s=32))
