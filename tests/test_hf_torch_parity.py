"""Torch-transformers logits parity for every ingestable family.

The per-family converter tests use synthetic (export/reimport) state dicts;
these tests hold the REAL contract: a random torch-transformers checkpoint
converted through from_hf_checkpoint must reproduce HF's logits. (MoE
families and gemma2 have their own parity tests alongside their models.)
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from deepspeed_tpu.models.hf import from_hf_checkpoint  # noqa: E402


def _parity(hf_model, hf_cfg_dict, ids, atol=3e-4, rtol=3e-3):
    model, cfg, params = from_hf_checkpoint(hf_cfg_dict,
                                            hf_model.state_dict())
    # fp32 compute for tight comparison; dtype is shape-preserving so the
    # converted params carry over
    model = type(model)(dataclasses.replace(cfg, dtype=jnp.float32))
    with torch.no_grad():
        ref = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply({"params": jax.tree.map(jnp.asarray, params)},
                       {"input_ids": jnp.asarray(ids.astype(np.int32))},
                       method=type(model).logits)
    np.testing.assert_allclose(np.asarray(ours), ref, atol=atol, rtol=rtol)


def _ids(vocab, b=2, s=16, seed=0):
    return np.random.default_rng(seed).integers(1, vocab, size=(b, s))


@pytest.mark.slow
def test_hf_gpt2_torch_parity():
    from transformers import GPT2Config, GPT2LMHeadModel
    hf_cfg = GPT2Config(vocab_size=256, n_embd=64, n_layer=2, n_head=4,
                        n_positions=64, resid_pdrop=0.0, embd_pdrop=0.0,
                        attn_pdrop=0.0)
    torch.manual_seed(0)
    hf_model = GPT2LMHeadModel(hf_cfg).eval()
    _parity(hf_model, hf_cfg.to_dict(), _ids(256))


@pytest.mark.slow
def test_hf_opt_torch_parity():
    from transformers import OPTConfig, OPTForCausalLM
    hf_cfg = OPTConfig(vocab_size=256, hidden_size=64, ffn_dim=128,
                       num_hidden_layers=2, num_attention_heads=4,
                       max_position_embeddings=64, dropout=0.0,
                       word_embed_proj_dim=64, do_layer_norm_before=True)
    torch.manual_seed(0)
    hf_model = OPTForCausalLM(hf_cfg).eval()
    _parity(hf_model, hf_cfg.to_dict(), _ids(256))


@pytest.mark.slow
def test_hf_bloom_torch_parity():
    from transformers import BloomConfig, BloomForCausalLM
    hf_cfg = BloomConfig(vocab_size=256, hidden_size=64, n_layer=2,
                         n_head=4, hidden_dropout=0.0,
                         attention_dropout=0.0)
    torch.manual_seed(0)
    hf_model = BloomForCausalLM(hf_cfg).eval()
    _parity(hf_model, hf_cfg.to_dict(), _ids(256))


@pytest.mark.slow
def test_hf_gpt_neox_torch_parity():
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM
    hf_cfg = GPTNeoXConfig(vocab_size=256, hidden_size=64,
                           intermediate_size=128, num_hidden_layers=2,
                           num_attention_heads=4,
                           max_position_embeddings=64, rotary_pct=0.25,
                           hidden_dropout=0.0, attention_dropout=0.0,
                           use_parallel_residual=True)
    torch.manual_seed(0)
    hf_model = GPTNeoXForCausalLM(hf_cfg).eval()
    _parity(hf_model, hf_cfg.to_dict(), _ids(256))


@pytest.mark.slow
def test_hf_falcon_torch_parity():
    from transformers import FalconConfig, FalconForCausalLM
    hf_cfg = FalconConfig(vocab_size=256, hidden_size=64,
                          num_hidden_layers=2, num_attention_heads=4,
                          multi_query=True, parallel_attn=True, bias=False,
                          alibi=False, new_decoder_architecture=False,
                          hidden_dropout=0.0, attention_dropout=0.0)
    torch.manual_seed(0)
    hf_model = FalconForCausalLM(hf_cfg).eval()
    _parity(hf_model, hf_cfg.to_dict(), _ids(256))
