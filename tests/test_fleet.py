"""Fleet-router tests: pure routing decisions, the shared HTTP retry
client, ladder-aware spill (the within-run counterfactual counter
proof), zero-loss failover (exact ledger arithmetic on fake replicas,
then the subprocess SIGKILL chaos drill), prefix handoff round-trips,
and the elastic retire+handoff path.

Fake replicas (stdlib HTTP servers with scripted healthz/generate
behavior) pin the router's arithmetic exactly — every assertion is a
counter, never a wall-clock judgment. The real-engine tests share the
KV/bucket shapes of tests/test_serving.py so jit compiles are shared
across the module; the subprocess drill pays two real worker startups
and runs last.
"""

import json
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from deepspeed_tpu.resilience.chaos import (REPLICA_ID_ENV, ChaosConfig,
                                            ChaosMonkey)
from deepspeed_tpu.serving import http_util
from deepspeed_tpu.serving.fleet import (FleetConfig, FleetRouter,
                                         ReplicaHandle, affinity_key,
                                         pick_replica, plan_scale,
                                         subprocess_launcher)

pytestmark = pytest.mark.fleet


@pytest.fixture(scope="module", autouse=True)
def _clear_tracer_after_module():
    """Routers and in-process replicas emit fleet/serve instants into the
    GLOBAL tracer ring; later suites (test_mem) count instants exactly.
    Leave the ring as clean as we found it."""
    yield
    from deepspeed_tpu.telemetry.tracer import get_tracer
    get_tracer().clear()


# ---------------------------------------------------------------------------
# pure routing decisions
# ---------------------------------------------------------------------------
def test_affinity_key_full_blocks_only():
    # same cap as PrefixCache.lookup: (len-1)//block full blocks — the
    # last prompt token is always computed, never part of a cached block
    assert affinity_key([1] * 16, 16) is None          # (16-1)//16 == 0
    assert affinity_key([1] * 17, 16) is not None      # one full block
    assert affinity_key([], 16) is None
    assert affinity_key([1, 2, 3], 0) is None
    # keyed by the HEAD block only: shared-system-prompt requests that
    # diverge after the head still land on the same replica
    a = affinity_key(list(range(40)), 16)
    b = affinity_key(list(range(16)) + [99] * 24, 16)
    assert a == b
    # a different head block is a different key
    assert affinity_key(list(range(1, 41)), 16) != a
    # deterministic for equal token content
    assert affinity_key(tuple(range(40)), 16) == a


def _snap(rid, level="healthy", queued=0, inflight=0, draining=False,
          in_rotation=True, **kw):
    return dict({"id": rid, "level": level, "queued": queued,
                 "inflight": inflight, "draining": draining,
                 "in_rotation": in_rotation}, **kw)


def test_pick_replica_matrix():
    healthy = [_snap(0), _snap(1, queued=2)]
    # least-loaded with id tie-break
    assert pick_replica(healthy, None, True, frozenset()) == \
        (0, "least_loaded")
    # the router's own pending count breaks healthz staleness: a request
    # routed between two polls steers the next one elsewhere
    assert pick_replica([_snap(0, pending=1), _snap(1)], None, True,
                        frozenset()) == (1, "least_loaded")
    # affinity wins over load when the target is in rotation
    assert pick_replica(healthy, 1, True, frozenset()) == (1, "affinity")
    # affinity target excluded (already tried) -> least-loaded fallback
    assert pick_replica(healthy, 1, True, frozenset({1})) == \
        (0, "least_loaded")
    # shedding first choice spills to the accepting peer
    shed0 = [_snap(0, level="shed"), _snap(1, queued=5)]
    assert pick_replica(shed0, None, True, frozenset()) == (1, "spill")
    # spill disabled: pinned to the shedding first choice (the
    # ladder-blind baseline — its 429 is relayed to the client)
    assert pick_replica(shed0, None, False, frozenset()) == \
        (0, "pinned_shedding")
    # nobody accepts
    all_shed = [_snap(0, level="shed"), _snap(1, draining=True)]
    assert pick_replica(all_shed, None, True, frozenset()) == \
        (None, "shed_all")
    # rotation empty after exclusion
    assert pick_replica(healthy, None, True, frozenset({0, 1})) == \
        (None, "no_replicas")
    assert pick_replica([], None, True, frozenset()) == \
        (None, "no_replicas")
    # out-of-rotation snapshots are invisible to routing
    assert pick_replica([_snap(0, in_rotation=False), _snap(1)], 0, True,
                        frozenset()) == (1, "least_loaded")


def test_plan_scale_streaks():
    cfg = FleetConfig(scale_out_enabled=True, scale_out_pressure_polls=2,
                      scale_out_queue_depth=4, retire_idle_polls=3,
                      min_replicas=1, max_replicas=3)
    pressured = [_snap(0, queued=5), _snap(1, level="shed")]
    idle = [_snap(0), _snap(1)]
    busy = [_snap(0, inflight=1), _snap(1)]
    # pressure must SUSTAIN scale_out_pressure_polls polls
    action, p, i = plan_scale(pressured, cfg, 0, 0)
    assert (action, p, i) == (None, 1, 0)
    action, p, i = plan_scale(pressured, cfg, 1, 0)
    assert (action, p) == ("out", 0)
    # a busy poll resets the idle streak
    action, p, i = plan_scale(idle, cfg, 0, 1)
    assert (action, i) == (None, 2)
    action, p, i = plan_scale(busy, cfg, 0, 2)
    assert (action, i) == (None, 0)
    action, p, i = plan_scale(idle, cfg, 0, 2)
    assert (action, i) == ("retire", 0)
    # floors/ceilings: no retire at min_replicas, no scale-out at max
    one = [_snap(0)]
    assert plan_scale(one, cfg, 0, 99)[0] is None
    three = [_snap(0, queued=9), _snap(1, queued=9), _snap(2, queued=9)]
    assert plan_scale(three, cfg, 99, 0)[0] is None
    # disabled: never acts, streaks still tracked
    off = FleetConfig(scale_out_enabled=False)
    assert plan_scale(idle, off, 0, 999)[0] is None


# ---------------------------------------------------------------------------
# http_util: backoff + retry discipline
# ---------------------------------------------------------------------------
def test_backoff_delay_deterministic_and_floored():
    pol = http_util.RetryPolicy(backoff_s=0.05, backoff_max_s=0.4,
                                jitter_frac=0.25, seed=3)
    # pure function of (seed, salt, attempt): replays bit-identically
    assert http_util.backoff_delay(pol, 2, salt=7) == \
        http_util.backoff_delay(pol, 2, salt=7)
    assert http_util.backoff_delay(pol, 2, salt=7) != \
        http_util.backoff_delay(pol, 2, salt=8)
    # exponential base, capped
    for attempt, base in ((1, 0.05), (2, 0.10), (3, 0.20), (4, 0.40),
                          (9, 0.40)):
        d = http_util.backoff_delay(pol, attempt)
        assert base <= d <= base * 1.25
    # a server-sent Retry-After is a FLOOR over the schedule
    assert http_util.backoff_delay(pol, 1, retry_after_s=5.0) == 5.0
    assert http_util.backoff_delay(pol, 9, retry_after_s=0.001) >= 0.4


class _CountingHandler(BaseHTTPRequestHandler):
    """Scripted status sequence; counts hits per (method, path)."""

    def log_message(self, *a):
        pass

    def _serve(self):
        srv = self.server
        srv.hits.append((self.command, self.path))
        statuses = srv.script
        status = statuses[min(len(srv.hits) - 1, len(statuses) - 1)]
        body = json.dumps({"n": len(srv.hits)}).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if status == 429:
            self.send_header("Retry-After", "0")
        self.end_headers()
        self.wfile.write(body)

    do_GET = _serve
    do_POST = _serve


def _counting_server(script):
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _CountingHandler)
    srv.daemon_threads = True
    srv.script = list(script)
    srv.hits = []
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


def test_request_json_retry_and_idempotency_clamp():
    pol = http_util.RetryPolicy(max_attempts=3, backoff_s=0.001,
                                backoff_max_s=0.002)
    srv, url = _counting_server([429, 429, 200])
    try:
        # GET retries retry_status until success, attempts recorded
        r = http_util.request_json("GET", url + "/healthz", retry=pol,
                                   retry_status=(429,))
        assert r.status == 200 and r.attempts == 3
        # non-GET WITHOUT an idempotency key: clamped to ONE attempt no
        # matter the policy — a retried submit could double-admit
        srv.hits.clear()
        r = http_util.request_json("POST", url + "/generate", payload={},
                                   retry=pol, retry_status=(429,))
        assert r.status == 429 and len(srv.hits) == 1
        # WITH the dedupe key the same POST retries
        srv.hits.clear()
        r = http_util.request_json("POST", url + "/generate", payload={},
                                   retry=pol, retry_status=(429,),
                                   idempotency_key=17)
        assert r.status == 200 and len(srv.hits) == 3
    finally:
        srv.shutdown()
        srv.server_close()


def test_request_json_transport_classification(monkeypatch):
    pol = http_util.RetryPolicy(max_attempts=3, backoff_s=0.001)
    calls = {"n": 0}

    def fatal(*a, **k):
        calls["n"] += 1
        raise PermissionError("UNAUTHENTICATED: bad credentials")

    monkeypatch.setattr(http_util, "_one_request", fatal)
    # auth-shaped failures are FATAL in the comm-guard taxonomy: never
    # retried (an auth failure retried is an account lockout)
    with pytest.raises(PermissionError):
        http_util.request_json("GET", "http://127.0.0.1:1/x", retry=pol)
    assert calls["n"] == 1

    calls["n"] = 0

    def transient(*a, **k):
        calls["n"] += 1
        raise ConnectionRefusedError("connection refused")

    monkeypatch.setattr(http_util, "_one_request", transient)
    with pytest.raises(ConnectionRefusedError):
        http_util.request_json("GET", "http://127.0.0.1:1/x", retry=pol)
    assert calls["n"] == 3   # TRANSIENT: the full budget was spent


# ---------------------------------------------------------------------------
# chaos: the replica-kill knob
# ---------------------------------------------------------------------------
def test_chaos_replica_kill_parsing_and_gating(monkeypatch):
    monkeypatch.setenv("DSTPU_CHAOS_REPLICA_KILL", "2:5")
    cfg = ChaosConfig.from_env()
    assert (cfg.replica_kill_id, cfg.replica_kill_tick) == (2, 5)
    assert cfg.replica_kill_once and cfg.active

    kills = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: kills.append((pid,
                                                                   sig)))
    monkey = ChaosMonkey(cfg)
    monkeypatch.delenv("DSTPU_RESUME", raising=False)
    # wrong replica: never fires
    monkeypatch.setenv(REPLICA_ID_ENV, "0")
    monkey.maybe_kill_replica(99, mid_decode=True)
    # right replica, before the due tick: no
    monkeypatch.setenv(REPLICA_ID_ENV, "2")
    monkey.maybe_kill_replica(4, mid_decode=True)
    # due tick but idle: the contract is death MID-DECODE
    monkey.maybe_kill_replica(5, mid_decode=False)
    assert kills == [] and monkey.injected["replica_kill"] == 0
    # DSTPU_RESUME relaunch is spared (die-once contract)
    monkeypatch.setenv("DSTPU_RESUME", "relaunch")
    monkey.maybe_kill_replica(5, mid_decode=True)
    assert kills == []
    monkeypatch.delenv("DSTPU_RESUME")
    monkey.maybe_kill_replica(5, mid_decode=True)
    assert kills == [(os.getpid(), __import__("signal").SIGKILL)]
    assert monkey.injected["replica_kill"] == 1
    # unset env parses to inactive
    monkeypatch.delenv("DSTPU_CHAOS_REPLICA_KILL")
    assert ChaosConfig.from_env().replica_kill_id == -1


# ---------------------------------------------------------------------------
# frontend hardening (no engine needed: the guards fire before submit)
# ---------------------------------------------------------------------------
def test_frontend_slow_and_oversized_clients():
    from deepspeed_tpu.serving.frontend import ServingFrontend

    class _Stub:     # only the attributes the touched routes use
        def health(self):
            return {"ok": True, "status": "serving"}

    fe = ServingFrontend(_Stub(), max_body_bytes=128,
                         read_timeout_s=0.3).start()
    try:
        # oversized declared body: 413 WITHOUT reading it
        r = http_util.request_json(
            "POST", fe.url + "/generate",
            payload={"prompt_tokens": [1] * 4096})
        assert r.status == 413

        # stalled body: socket-level deadline -> 408
        conn = socket.create_connection(("127.0.0.1", fe.port), timeout=5)
        try:
            conn.sendall(b"POST /generate HTTP/1.1\r\n"
                         b"Host: x\r\nContent-Length: 50\r\n\r\nshort")
            data = conn.recv(4096)
            assert b"408" in data.split(b"\r\n", 1)[0]
        finally:
            conn.close()

        # unparseable Content-Length: 400
        conn = socket.create_connection(("127.0.0.1", fe.port), timeout=5)
        try:
            conn.sendall(b"POST /generate HTTP/1.1\r\n"
                         b"Host: x\r\nContent-Length: nope\r\n\r\n")
            data = conn.recv(4096)
            assert b"400" in data.split(b"\r\n", 1)[0]
        finally:
            conn.close()
    finally:
        fe.stop()


# ---------------------------------------------------------------------------
# fake replicas: scripted doors for exact router arithmetic
# ---------------------------------------------------------------------------
class _FakeReplica:
    """A stdlib HTTP server impersonating one serving replica: healthz
    reports a scripted ladder level; /generate streams ``max_new`` tokens
    — or 429s (shed door), or dies abruptly after ``die_after`` tokens
    (no final record: the router must treat it as a death)."""

    def __init__(self, rid, level="healthy", die_after=None):
        self.rid = rid
        self.level = level
        self.die_after = die_after
        self.generate_hits = 0
        fake = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, code, payload, headers=()):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._json(200, {"status": "serving", "ok": True,
                                 "level": fake.level, "queued": 0,
                                 "inflight": 0, "draining": False,
                                 "replica_id": fake.rid,
                                 "prefix_cache_blocks": 0})

            def do_POST(self):
                raw = self.rfile.read(
                    int(self.headers.get("Content-Length", 0) or 0))
                fake.generate_hits += 1
                if fake.level == "shed":
                    self._json(429, {"error": "shedding",
                                     "retry_after_s": 0.01},
                               headers=[("Retry-After", "0")])
                    return
                body = json.loads(raw)
                max_new = int(body["max_new_tokens"])
                self.send_response(200)
                self.send_header("Content-Type", "application/jsonlines")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(obj):
                    data = (json.dumps(obj) + "\n").encode()
                    self.wfile.write(f"{len(data):x}\r\n".encode()
                                     + data + b"\r\n")
                    self.wfile.flush()

                for i in range(max_new):
                    if fake.die_after is not None and i == fake.die_after:
                        # abrupt transport death mid-stream: no final
                        # record, no chunk terminator
                        self.connection.close()
                        self.close_connection = True
                        return
                    chunk({"token": fake.rid * 1000 + i})
                chunk({"done": True, "state": "finished",
                       "finish_reason": "length", "uid": 7})
                self.wfile.write(b"0\r\n\r\n")
                self.close_connection = True

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _router_over(fakes, **cfg_kw):
    cfg = FleetConfig(replicas=len(fakes), poll_interval_s=0.05,
                      poll_timeout_s=2.0, retry_backoff_s=0.001,
                      retry_backoff_max_s=0.005, **cfg_kw)
    handles = [ReplicaHandle(f.rid, f.url) for f in fakes]
    return FleetRouter(cfg, handles=handles).start()


def test_failover_ledger_exact_arithmetic():
    """A replica dying mid-stream costs the client NOTHING: the router
    re-admits prompt + sent tokens to the survivor and the ledger records
    the exact recompute bill."""
    dying = _FakeReplica(0, die_after=3)     # id 0: the tie-break winner
    healthy = _FakeReplica(1)
    router = _router_over([dying, healthy], affinity_enabled=False)
    try:
        prompt = list(range(10))
        reply = http_util.request_json(
            "POST", router.url + "/generate",
            payload={"prompt_tokens": prompt, "max_new_tokens": 8},
            timeout_s=30.0)
        assert reply.status == 200
        out = reply.json()
        # exact token count: 3 from the corpse + 5 from the survivor
        assert len(out["tokens"]) == 8
        assert out["tokens"][:3] == [0, 1, 2]          # replica 0's tokens
        assert out["tokens"][3:] == [1000, 1001, 1002, 1003, 1004]
        assert out["rerouted"] == 1
        # recompute bill: the full re-admitted context, prompt + sent
        assert out["recomputed_tokens"] == len(prompt) + 3
        assert out["replicas"] == [0, 1]
        assert out["state"] == "finished"
        c = router.counters_snapshot()
        assert c["submitted"] == c["completed"] == 1
        assert c["reroutes"] == 1 and c["requests_lost"] == 0
        assert c["recomputed_tokens"] == len(prompt) + 3
        ledger = router.ledger_snapshot()
        assert len(ledger) == 1
        entry = next(iter(ledger.values()))
        assert entry["rerouted"] == 1 and entry["tokens"] == 8
        assert entry["state"] == "finished"
    finally:
        router.stop(terminate_replicas=False)
        dying.close()
        healthy.close()


def test_failover_budget_exhaustion_is_counted_lost():
    """Every replica dying mid-stream exhausts the retry budget: the
    request is COUNTED lost (503), never silently dropped."""
    a = _FakeReplica(0, die_after=1)
    b = _FakeReplica(1, die_after=1)
    router = _router_over([a, b], affinity_enabled=False, retry_budget=2,
                          request_timeout_s=10.0)
    try:
        reply = http_util.request_json(
            "POST", router.url + "/generate",
            payload={"prompt_tokens": [1, 2, 3], "max_new_tokens": 6},
            timeout_s=30.0)
        assert reply.status == 503
        c = router.counters_snapshot()
        assert c["requests_lost"] == 1 and c["completed"] == 0
        assert c["reroutes"] == 2          # the whole budget was spent
        entry = next(iter(router.ledger_snapshot().values()))
        assert entry["state"] == "lost"
    finally:
        router.stop(terminate_replicas=False)
        a.close()
        b.close()


def test_spill_counterfactual_counters():
    """The ladder-aware spill proof, no wall-clock: with spill ON the
    shedding first choice costs the client NOTHING (client_sheds == 0 <
    first_choice_sheds == K); the spill-blind router over the SAME
    replicas relays every one (client_sheds == first_choice_sheds == K)."""
    shedding = _FakeReplica(0, level="shed")   # id 0: first choice by tie
    healthy = _FakeReplica(1)
    K = 6

    def drive(router):
        for i in range(K):
            r = http_util.request_json(
                "POST", router.url + "/generate",
                payload={"prompt_tokens": [i, i + 1, i + 2],
                         "max_new_tokens": 2},
                timeout_s=30.0)
            yield r

    with_spill = _router_over([shedding, healthy], spill_enabled=True,
                              affinity_enabled=False)
    try:
        assert all(r.status == 200 for r in drive(with_spill))
        c = with_spill.counters_snapshot()
        assert c["first_choice_sheds"] == K     # the would-be client 429s
        assert c["client_sheds"] == 0           # ...none reached a client
        assert c["spills"] == K
        assert c["completed"] == K
        assert c["client_sheds"] < c["first_choice_sheds"]
    finally:
        with_spill.stop(terminate_replicas=False)

    no_spill = _router_over([shedding, healthy], spill_enabled=False,
                            affinity_enabled=False)
    try:
        replies = list(drive(no_spill))
        assert all(r.status == 429 for r in replies)
        assert all(r.retry_after_s() is not None for r in replies)
        c = no_spill.counters_snapshot()
        # the counterfactual closes: spill-blind relays EVERY first-choice
        # shed straight to the client
        assert c["client_sheds"] == c["first_choice_sheds"] == K
        assert c["spills"] == 0 and c["completed"] == 0
    finally:
        no_spill.stop(terminate_replicas=False)
        shedding.close()
        healthy.close()


def test_router_health_and_metrics_endpoints():
    fake = _FakeReplica(0)
    router = _router_over([fake])
    try:
        h = http_util.request_json("GET", router.url + "/healthz").json()
        assert h["ok"] is True
        assert [s["id"] for s in h["replicas"]] == [0]
        assert h["replicas"][0]["in_rotation"] is True
        assert set(h["counters"]) >= {"submitted", "reroutes",
                                      "first_choice_sheds"}
        m = http_util.request_json("GET", router.url + "/metrics")
        text = m.body.decode()
        assert "# TYPE dstpu_fleet_submitted counter" in text
        assert "dstpu_fleet_replicas_in_rotation 1" in text
    finally:
        router.stop(terminate_replicas=False)
        fake.close()


def test_router_marks_dead_replica_lost_and_drops_affinity():
    fake0 = _FakeReplica(0)
    fake1 = _FakeReplica(1)
    router = _router_over([fake0, fake1], lost_after_s=0.15)
    try:
        # seed an affinity entry pointing at replica 0
        with router._lock:
            router._affinity[1234] = 0
        fake0.close()                     # the replica vanishes
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if router.counters_snapshot()["replicas_lost"] == 1:
                break
            time.sleep(0.05)
        c = router.counters_snapshot()
        assert c["replicas_lost"] == 1
        h = router.health()
        assert h["ok"] is True            # the survivor keeps rotation
        snap = {s["id"]: s for s in h["replicas"]}
        assert snap[0]["lost"] and not snap[0]["in_rotation"]
        assert snap[1]["in_rotation"]
        # the corpse's affinity entries were dropped, not left to steer
        # new requests into the failover path
        with router._lock:
            assert 1234 not in router._affinity
    finally:
        router.stop(terminate_replicas=False)
        fake1.close()


def test_fleet_status_artifact_and_env_report(tmp_path):
    from deepspeed_tpu.env_report import fleet_report
    path = str(tmp_path / "fleet_status.json")
    fake = _FakeReplica(0)
    router = _router_over([fake], status_path=path)
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not os.path.exists(path):
            time.sleep(0.05)
        with open(path) as f:
            doc = json.load(f)
        assert doc["replicas"][0]["in_rotation"] is True
        assert "counters" in doc
    finally:
        router.stop(terminate_replicas=False)
        fake.close()
    os.environ["DSTPU_FLEET_STATUS"] = path
    try:
        rows = dict(fleet_report())
        assert "1 in rotation" in rows["fleet replicas"]
        assert "fleet failover" in dict(rows)
    finally:
        del os.environ["DSTPU_FLEET_STATUS"]
    # artifact-less: a hint row, never an exception
    rows = fleet_report()
    assert rows and rows[0][0] == "fleet"


def test_fleet_config_from_ds_config():
    cfg = FleetConfig.from_ds_config(
        {"fleet": {"replicas": 3, "spill_enabled": False,
                   "affinity_block_tokens": 16}})
    assert (cfg.replicas, cfg.spill_enabled,
            cfg.affinity_block_tokens) == (3, False, 16)
    with pytest.raises(ValueError, match="unknown 'fleet' config keys"):
        FleetConfig.from_ds_config({"fleet": {"replica_count": 3}})
    assert FleetConfig.from_ds_config({}).replicas == 2


# ---------------------------------------------------------------------------
# real engines: prefix handoff + fleet hit ratio + retire lifecycle
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def bench_serve_mod():
    from deepspeed_tpu.serving import bench_serve
    return bench_serve


def test_prefix_handoff_roundtrip(bench_serve_mod, tmp_path):
    """A retiring replica's warm prefix cache survives the handoff file:
    the successor adopts the chains and serves the same prompt as a
    prefix HIT (suffix-only prefill)."""
    import dataclasses

    from deepspeed_tpu.serving.frontend import ServingFrontend
    sc = dataclasses.replace(bench_serve_mod.SCENARIOS["micro"],
                             num_requests=6, concurrency=2,
                             prompt_len=(34, 40), max_new_tokens=(2, 3),
                             shared_prefix_frac=0.5)
    donor = bench_serve_mod.build_tiny_server().start()
    path = str(tmp_path / "handoff.npz")
    try:
        bench_serve_mod.run_scenario(donor, sc)
        donor.stop(drain_timeout=30.0)
        got = donor.export_prefix_handoff(path, quantize="int8")
        assert got["chains"] > 0 and got["blocks"] > 0
        assert os.path.exists(path)
        # int8 pages travel narrow: stored < raw
        assert got["stored_bytes"] < got["raw_bytes"]
    finally:
        if donor.running:
            donor.stop(drain_timeout=5.0)

    heir = bench_serve_mod.build_tiny_server().start()
    fe = ServingFrontend(heir).start()
    try:
        r = http_util.request_json("POST", fe.url + "/admin/adopt",
                                   payload={"handoff_path": path},
                                   timeout_s=30.0)
        assert r.status == 200
        # adoption happens on the serve loop between ticks; poll counters
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if heir.handoff_stats["imported_chains"] > 0:
                break
            time.sleep(0.05)
        assert heir.handoff_stats["imported_chains"] > 0
        assert heir.handoff_stats["imported_blocks"] > 0
        h = http_util.request_json("GET", fe.url + "/healthz").json()
        assert h["prefix_cache_blocks"] > 0
        pre = heir.engine.prefix_stats()
        # the shared pool's head is now warm: serving it hits the cache
        pool = bench_serve_mod._shared_pool(sc)
        reply = http_util.request_json(
            "POST", fe.url + "/generate",
            payload={"prompt_tokens": pool[:34], "max_new_tokens": 2},
            timeout_s=60.0)
        assert reply.status == 200
        post = heir.engine.prefix_stats()
        assert post["prefix_hit_tokens"] > pre.get("prefix_hit_tokens", 0)
    finally:
        fe.stop()
        if heir.running:
            heir.stop(drain_timeout=30.0)


def test_fleet_hit_ratio_and_report_gates(bench_serve_mod):
    """Affinity keeps the FLEET-wide prefix hit ratio at the
    single-replica level (within epsilon) on a shared-prefix workload —
    and the fleet report's conservation gates close exactly."""
    import dataclasses
    sc = dataclasses.replace(bench_serve_mod.SCENARIOS["micro"],
                             num_requests=16, concurrency=4,
                             prompt_len=(34, 48), max_new_tokens=(2, 4),
                             shared_prefix_frac=0.5)
    single = bench_serve_mod.build_tiny_server().start()
    try:
        solo = bench_serve_mod.run_scenario(single, sc)
    finally:
        single.stop(drain_timeout=30.0)
    router = bench_serve_mod.build_tiny_fleet(replicas=2)
    try:
        rep = bench_serve_mod.run_fleet_scenario(router, sc)
    finally:
        bench_serve_mod.stop_tiny_fleet(router)
    assert rep["requests"]["states"] == {"finished": 16}
    assert rep["routing_conservation_ok"]
    assert rep["prefix"]["conservation_ok"]
    c = rep["counters"]
    assert c["completed"] == 16 and c["requests_lost"] == 0
    # prompts >= 34 with frac 0.5 share a FULL first block (17+ pool
    # tokens): one affinity key routes them together after the first hit
    assert c["affinity_hits"] > 0
    # fleet topology rides provenance for plan/verify tooling
    fleet_prov = rep["provenance"]["fleet"]
    assert len(fleet_prov["replicas"]) == 2
    assert fleet_prov["affinity_block_tokens"] == 16
    solo_ratio = solo["prefix"]["prefix_hit_ratio"]
    fleet_ratio = rep["prefix"]["prefix_hit_ratio"]
    assert fleet_ratio >= solo_ratio - 0.15, \
        f"fleet hit ratio {fleet_ratio:.3f} fell >0.15 below " \
        f"single-replica {solo_ratio:.3f}"


def test_retire_ships_prefix_handoff_to_survivor(bench_serve_mod):
    """The elastic retire path end to end over real replicas: sustained
    idle drains the newest replica, exports its warm prefix cache, and
    the survivor adopts it (handoffs == 1, retirements == 1)."""
    import dataclasses
    sc = dataclasses.replace(bench_serve_mod.SCENARIOS["micro"],
                             num_requests=8, concurrency=2,
                             prompt_len=(34, 40), max_new_tokens=(2, 3),
                             shared_prefix_frac=0.5)
    router = bench_serve_mod.build_tiny_fleet(
        replicas=2,
        fleet_overrides={"scale_out_enabled": True, "min_replicas": 1,
                         "retire_idle_polls": 8, "poll_interval_s": 0.05,
                         "drain_deadline_s": 60.0})
    try:
        rep = bench_serve_mod.run_fleet_scenario(router, sc)
        assert rep["counters"]["requests_lost"] == 0
        # warm the victim-to-be DIRECTLY (replica 1 retires LIFO) so the
        # handoff provably carries chains — scenario routing may have
        # favored replica 0
        pool = bench_serve_mod._shared_pool(sc)
        r = http_util.request_json(
            "POST", router._members[1][1].url + "/generate",
            payload={"prompt_tokens": pool[:34], "max_new_tokens": 2},
            timeout_s=60.0)
        assert r.status == 200
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            c = router.counters_snapshot()
            if c["retirements"] >= 1 and c["handoffs"] >= 1:
                break
            time.sleep(0.1)
        c = router.counters_snapshot()
        assert c["retirements"] == 1
        assert c["handoffs"] == 1
        # LIFO: the newest replica retired; the survivor holds rotation
        snaps = {s["id"]: s for s in router.health()["replicas"]}
        assert snaps[1]["retired"] and not snaps[1]["in_rotation"]
        assert snaps[0]["in_rotation"]
        # the survivor actually imported the retiree's chains — the
        # handoffs counter ticks when the file is SHIPPED; the survivor
        # adopts it between serve ticks, so give the import a moment
        survivor = router._members[0][0]
        while time.monotonic() < deadline:
            if survivor.handoff_stats["imported_chains"] > 0:
                break
            time.sleep(0.05)
        assert survivor.handoff_stats["imported_chains"] > 0
    finally:
        bench_serve_mod.stop_tiny_fleet(router)


# ---------------------------------------------------------------------------
# the acceptance drill: SIGKILL a real replica process mid-decode
# ---------------------------------------------------------------------------
def test_fleet_chaos_replica_kill_drill(tmp_path, monkeypatch):
    """ISSUE acceptance: 2 subprocess replicas, chaos SIGKILLs replica 1
    mid-decode, concurrent streamed clients — judged by exact counters:
    ZERO requests lost (every client holds its full token count),
    replica 1 lost exactly once, rerouted streams recomputed on the
    survivor, and the DSTPU_RESUME relaunch rejoins rotation (die-once
    spares it).

    Doubles as the reqtrace acceptance: every client sends an
    X-Dstpu-Trace header, the SIGKILLed replica leaves a flight-recorder
    dump behind, and the router ring + flight dumps stitch into
    per-request timelines whose tie-out holds."""
    from deepspeed_tpu.telemetry import reqtrace
    from deepspeed_tpu.telemetry.tracer import get_tracer
    tracer = get_tracer()
    was_enabled = tracer.enabled
    tracer.clear()          # stitch THIS drill's spans, not the module's
    tracer.configure(enabled=True)
    monkeypatch.setenv("DSTPU_CHAOS_REPLICA_KILL", "1:4")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    workdir = str(tmp_path)
    launcher = subprocess_launcher(
        workdir,
        worker_args=["--kv-num-blocks", "64", "--kv-block-size", "16",
                     "--serving-overrides", json.dumps(
                         {"idle_poll_s": 0.001, "max_queue_depth": 32})],
        start_timeout_s=300.0)
    cfg = FleetConfig(replicas=2, poll_interval_s=0.1, poll_timeout_s=2.0,
                      lost_after_s=0.5, retry_budget=3,
                      retry_backoff_s=0.01, retry_backoff_max_s=0.1,
                      relaunch_budget=1, affinity_enabled=False,
                      request_timeout_s=240.0, flight_dir=workdir)
    router = FleetRouter(cfg, launcher=launcher).start()
    N, MAX_NEW = 12, 6
    results = {}
    lock = threading.Lock()

    def client(i):
        tokens, final = [], {}
        try:
            reply = http_util.open_stream(
                router.url + "/generate",
                {"prompt_tokens": [(i * 7 + j) % 96 + 1
                                   for j in range(8 + i % 4)],
                 "max_new_tokens": MAX_NEW, "stream": True},
                timeout_s=240.0,
                headers={"X-Dstpu-Trace": f"drill-{i}"})
            if reply.status != 200:
                with lock:
                    results[i] = {"status": reply.status,
                                  "error": reply.error}
                return
            for rec in reply.records():
                if "token" in rec:
                    tokens.append(rec["token"])
                elif rec.get("done"):
                    final = rec
            with lock:
                results[i] = {"status": 200, "tokens": tokens,
                              "final": final}
        except Exception as e:
            with lock:
                results[i] = {"status": -1, "error": repr(e)}

    try:
        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(N)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300.0)
        assert len(results) == N
        # ZERO LOSS: every client finished with its exact token budget —
        # streams cut by the SIGKILL were re-admitted with their sent
        # tokens and completed on the survivor
        for i, rec in sorted(results.items()):
            assert rec["status"] == 200, f"client {i}: {rec}"
            assert len(rec["tokens"]) == MAX_NEW, f"client {i}: {rec}"
            assert rec["final"].get("state") == "finished"
        c = router.counters_snapshot()
        assert c["requests_lost"] == 0
        assert c["completed"] == N
        assert c["replicas_lost"] == 1      # exactly the chaos victim
        assert c["reroutes"] >= 1           # live streams failed over
        # the reroute bill is real and recorded
        assert c["recomputed_tokens"] > 0
        rerouted = [r for r in results.values()
                    if r["final"].get("rerouted", 0) > 0]
        assert len(rerouted) >= 1
        assert sum(r["final"]["recomputed_tokens"] for r in rerouted) \
            == c["recomputed_tokens"]
        # the relaunch (DSTPU_RESUME, spared by die-once) rejoins rotation
        deadline = time.monotonic() + 300.0
        rejoined = False
        while time.monotonic() < deadline:
            c = router.counters_snapshot()
            snaps = {s["id"]: s for s in router.health()["replicas"]}
            if (c["relaunches"] == 1 and snaps[1]["in_rotation"]
                    and snaps[1]["relaunches"] == 1):
                rejoined = True
                break
            time.sleep(0.25)
        assert rejoined, f"replica 1 never rejoined: {router.health()}"
        # --- reqtrace acceptance: flight recorder + stitched timelines ---
        # the client-sent trace id survives router -> replica -> final
        for i, rec in sorted(results.items()):
            assert rec["final"].get("trace_id") == f"drill-{i}", rec
        # the SIGKILLed replica dumped its ring + in-flight ledger before
        # dying (write-then-rename, so an existing file is complete)
        flight_dumps = router.discover_flight_dumps()
        assert any(os.path.basename(p).startswith("flight_replica1_")
                   for p in flight_dumps), flight_dumps
        # stitch the router's own ring with the recovered flight dumps
        router_dump = os.path.join(workdir, "router_ring.json")
        tracer.export_chrome(router_dump)
        report = reqtrace.stitch_requests([router_dump] + flight_dumps)
        assert report["alignment"] == "wall_anchor"
        assert report["flight_dumps"] >= 1
        # every drill request has a router wall envelope that closed
        # "finished" — requests_lost == 0, seen end to end
        for i in range(N):
            t = report["traces"].get(f"drill-{i}")
            assert t is not None, f"drill-{i} missing: {report['traces'].keys()}"
            assert t["wall"]["outcome"] == "finished", (i, t["wall"])
        # the tie-out invariant holds on a REAL two-process stitch
        assert report["tie_out_violations"] == [], report
        assert report["max_tie_out_error"] <= reqtrace.TIE_OUT_TOLERANCE
        # the killed attempt is visible: flight ledger entries carry the
        # drill trace ids, and the rerouted stream's timeline links the
        # dead attempt to the survivor via req/reroute
        recovered_ids = {e["trace_id"]
                         for t in report["traces"].values()
                         for e in t.get("recovered", [])}
        assert any(tid.startswith("drill-") for tid in recovered_ids), \
            report["recovered_requests"]
        rerouted_ids = {r["final"]["trace_id"] for r in rerouted}
        traced_reroutes = {tid for tid, t in report["traces"].items()
                           if t["reroutes"] >= 1}
        assert rerouted_ids <= traced_reroutes, (rerouted_ids,
                                                 traced_reroutes)
    finally:
        router.stop()
        tracer.configure(enabled=was_enabled)
