"""Attention backend tests: flash/ulysses/ring vs naive reference.

Reference analog: tests/unit/sequence_parallelism/test_ulysses.py + kernel tests in
tests/unit/ops (each kernel vs a reference implementation on random tensors).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.comm.mesh import create_mesh, set_global_mesh
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.ops.flash_attention import attention_reference, flash_attention


def make_qkv(b=2, s=64, h=4, hkv=None, d=16, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    hkv = hkv or h
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), dtype)
    k = jnp.asarray(rng.normal(size=(b, s, hkv, d)), dtype)
    v = jnp.asarray(rng.normal(size=(b, s, hkv, d)), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_reference(causal):
    q, k, v = make_qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_flash_gqa_and_unaligned():
    q, k, v = make_qkv(s=50, h=8, hkv=2)   # padding path + GQA
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_flash_grad_matches_reference():
    q, k, v = make_qkv(s=32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=8, block_k=8) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


@pytest.fixture
def sp_mesh():
    mesh = create_mesh(MeshConfig(data=2, sequence=4))
    set_global_mesh(mesh)
    return mesh


@pytest.fixture
def sp_tp_mesh():
    mesh = create_mesh(MeshConfig(sequence=4, tensor=2))
    set_global_mesh(mesh)
    return mesh


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_reference(sp_mesh, causal):
    from deepspeed_tpu.sequence.ring import ring_attention
    q, k, v = make_qkv(s=64, h=4)
    out = ring_attention(q, k, v, causal=causal, mesh=sp_mesh)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_ring_gqa(sp_mesh):
    from deepspeed_tpu.sequence.ring import ring_attention
    q, k, v = make_qkv(s=64, h=8, hkv=2)
    out = ring_attention(q, k, v, causal=True, mesh=sp_mesh)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_matches_reference(sp_mesh, causal):
    """Flash-kernel ring (impl='interpret' = the Pallas path in interpreter
    mode): the per-step [S_l,S_l] panel never materializes; fwd + full grads
    vs the dense reference (bwd = flash multi-block vs the FINAL lse with
    dk/dv accumulators riding the ring home)."""
    from deepspeed_tpu.sequence.ring import ring_attention
    q, k, v = make_qkv(s=64, h=4, hkv=2)   # GQA inside the kernel
    out = ring_attention(q, k, v, causal=causal, mesh=sp_mesh,
                         impl="interpret")
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, causal=causal, mesh=sp_mesh,
                                      impl="interpret") ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)
    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4, err_msg=name)


def test_ring_flash_striped_and_contiguous_agree(sp_mesh):
    """Causal flash ring runs STRIPED (load-balanced: every step a uniform
    shifted-causal block) when S_l % sp == 0; both layouts must equal the
    dense reference — fwd and grads."""
    from deepspeed_tpu.sequence.ring import ring_attention
    q, k, v = make_qkv(s=64, h=4, hkv=2)
    ref = attention_reference(q, k, v, causal=True)
    for impl in ("interpret", "interpret_contiguous"):
        out = ring_attention(q, k, v, causal=True, mesh=sp_mesh, impl=impl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5, err_msg=impl)

    def loss(impl):
        return lambda q, k, v: jnp.sum(ring_attention(
            q, k, v, causal=True, mesh=sp_mesh, impl=impl) ** 2)
    g_ref = jax.grad(lambda q, k, v: jnp.sum(
        attention_reference(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for impl in ("interpret", "interpret_contiguous"):
        g_i = jax.grad(loss(impl), argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g_i, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4,
                                       err_msg=f"{impl}:{name}")


def test_ring_flash_unaligned_seq(sp_mesh):
    """S_l not a multiple of the kernel block: padding inside the impl."""
    from deepspeed_tpu.sequence.ring import ring_attention
    q, k, v = make_qkv(s=40, h=4)          # S_l = 10 per device
    out = ring_attention(q, k, v, causal=True, mesh=sp_mesh,
                         impl="interpret")
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_ulysses_matches_reference(sp_mesh):
    from deepspeed_tpu.sequence.ulysses import ulysses_attention
    q, k, v = make_qkv(s=64, h=8, hkv=8)
    out = ulysses_attention(q, k, v, causal=True, mesh=sp_mesh)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_ulysses_with_tp(sp_tp_mesh):
    """Ulysses composes with TP: heads split over tensor then sequence."""
    from deepspeed_tpu.sequence.ulysses import ulysses_attention
    q, k, v = make_qkv(s=64, h=8, hkv=8)
    out = ulysses_attention(q, k, v, causal=True, mesh=sp_tp_mesh)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("h,hkv", [(6, 6), (2, 2), (6, 2)])
def test_ulysses_uneven_heads(sp_mesh, h, hkv):
    """heads not divisible by sp=4 -> padded uneven-heads all-to-all (reference:
    uneven_heads_all2all sequence/layer.py:43), incl. GQA densification."""
    from deepspeed_tpu.sequence.ulysses import ulysses_attention
    q, k, v = make_qkv(s=64, h=h, hkv=hkv)
    out = ulysses_attention(q, k, v, causal=True, mesh=sp_mesh)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)
    # the remainder heads' flash-ring path (TPU default), interpret mode
    out2 = ulysses_attention(q, k, v, causal=True, mesh=sp_mesh,
                             ring_impl="interpret")
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.slow
def test_train_llama_with_ring_attention():
    """End-to-end: Llama trains under sequence parallelism with ring attention."""
    import deepspeed_tpu
    from deepspeed_tpu.models.llama import TINY_LLAMA, LlamaConfig, LlamaForCausalLM, random_tokens

    mesh = create_mesh(MeshConfig(data=2, sequence=4))
    set_global_mesh(mesh)
    cfg = LlamaConfig(**{**TINY_LLAMA.__dict__, "attention_backend": "ring",
                         "dtype": jnp.float32})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=LlamaForCausalLM(cfg),
        config={"train_batch_size": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}},
        mesh=mesh, example_batch=random_tokens(2, 32))
    batch = random_tokens(4, 32, seed=0)
    losses = [float(engine.train_batch(batch=batch)) for _ in range(5)]
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_distributed_attention_api_compat(sp_mesh):
    """DistributedAttention (reference sequence/layer.py:271): wraps a
    user-supplied local attention; output matches full-sequence reference."""
    from deepspeed_tpu.sequence.layer import DistributedAttention

    q, k, v = make_qkv(s=64, h=8, hkv=8)
    calls = []

    def my_local_attention(qg, kg, vg, scale_note=None):
        calls.append((qg.shape, scale_note))
        return attention_reference(qg, kg, vg, causal=True)

    dist_attn = DistributedAttention(my_local_attention, mesh=sp_mesh)
    out = dist_attn(q, k, v, scale_note="hi")
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
    # the wrapped fn saw gathered-sequence shards: local batch, full S,
    # H/sp heads, full head dim
    (shape, note), = {(s, n) for s, n in calls}
    dp = np.prod([sp_mesh.shape[a] for a in ("data", "fsdp")
                  if a in sp_mesh.shape])
    sp = sp_mesh.shape["sequence"]
    assert shape == (q.shape[0] // dp, q.shape[1], q.shape[2] // sp,
                     q.shape[3]) and note == "hi", (shape, note)


@pytest.mark.parametrize("h,hkv", [(6, 6), (6, 2)])
def test_distributed_attention_uneven_heads_with_custom_fn(sp_mesh, h, hkv):
    """Uneven heads keep the custom/kernel attention path: heads are padded
    to the next sp multiple and EVERY head runs through the wrapped local
    attention (ceil(H/sp) per device, kv densified to q's head count) —
    output still matches dense attention."""
    from deepspeed_tpu.sequence.layer import DistributedAttention
    q, k, v = make_qkv(s=64, h=h, hkv=hkv)   # 6 heads over sp=4: uneven
    shapes = []

    def my_attn(qg, kg, vg):
        shapes.append((qg.shape, kg.shape))
        return attention_reference(qg, kg, vg, causal=True)

    out = DistributedAttention(my_attn, mesh=sp_mesh)(q, k, v)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
    sp = sp_mesh.shape["sequence"]
    (qshape, kshape), = set(shapes)
    assert qshape[2] == -(-h // sp), qshape       # ceil(H/sp) heads/device
    assert kshape[2] == qshape[2], (kshape, qshape)  # kv densified to match
    assert qshape[1] == q.shape[1], qshape        # full gathered sequence


def test_flash_segment_ids_matches_reference():
    """Packed-sequence masking runs IN-KERNEL (fwd + all grads); previously
    segment_ids forced the XLA fallback."""
    from deepspeed_tpu.ops.pallas.flash_attention import pallas_flash_attention
    q, k, v = make_qkv(s=48, h=4, hkv=2)
    rng = np.random.default_rng(7)
    # 3 packed segments of uneven lengths per batch row
    seg = jnp.asarray(np.sort(rng.integers(0, 3, size=(2, 48)), axis=1),
                      jnp.int32)
    for causal in (True, False):
        out = pallas_flash_attention(q, k, v, causal, 16, 16, True, None, seg)
        ref = attention_reference(q, k, v, causal=causal, segment_ids=seg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5, err_msg=str(causal))

    def loss_k(q, k, v):
        return jnp.sum(pallas_flash_attention(
            q, k, v, True, 16, 16, True, None, seg) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(attention_reference(
            q, k, v, causal=True, segment_ids=seg) ** 2)
    g1 = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4, err_msg=name)


def test_ulysses_segment_ids(sp_mesh):
    """Packed sequences under Ulysses: ids all-gather inside the shard_map
    and mask the gathered-sequence attention (was: silently dropped)."""
    from deepspeed_tpu.sequence.ulysses import ulysses_attention
    q, k, v = make_qkv(s=64, h=8, hkv=8)
    rng = np.random.default_rng(3)
    seg = jnp.asarray(np.sort(rng.integers(0, 3, size=(2, 64)), axis=1),
                      jnp.int32)
    out = ulysses_attention(q, k, v, causal=True, mesh=sp_mesh,
                            segment_ids=seg)
    ref = attention_reference(q, k, v, causal=True, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
    # uneven heads + segments: clear rejection, not silent wrongness
    q2, k2, v2 = make_qkv(s=64, h=6, hkv=6)
    with pytest.raises(NotImplementedError, match="uneven"):
        ulysses_attention(q2, k2, v2, causal=True, mesh=sp_mesh,
                          segment_ids=seg)


def test_ring_segment_ids_flash(sp_mesh):
    """Packed sequences under ring CP: the KV block's ids ride the ring and
    feed the kernel's in-kernel mask — both layouts, fwd + grads."""
    from deepspeed_tpu.sequence.ring import ring_attention
    q, k, v = make_qkv(s=64, h=4, hkv=2)
    rng = np.random.default_rng(5)
    seg = jnp.asarray(np.sort(rng.integers(0, 3, size=(2, 64)), axis=1),
                      jnp.int32)
    ref = attention_reference(q, k, v, causal=True, segment_ids=seg)
    for impl in ("interpret", "interpret_contiguous"):
        out = ring_attention(q, k, v, causal=True, mesh=sp_mesh, impl=impl,
                             segment_ids=seg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5, err_msg=impl)

    def loss_r(q, k, v):
        return jnp.sum(ring_attention(q, k, v, causal=True, mesh=sp_mesh,
                                      impl="interpret",
                                      segment_ids=seg) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True,
                                           segment_ids=seg) ** 2)
    g1 = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4, err_msg=name)
    # the jnp ring body has no segment carry: loud rejection
    with pytest.raises(NotImplementedError, match="flash"):
        ring_attention(q, k, v, causal=True, mesh=sp_mesh, impl="xla",
                       segment_ids=seg)


def test_flash_window_and_segments_compose():
    """Sliding window AND packed segments in one kernel mask (mistral-style
    packed training)."""
    from deepspeed_tpu.ops.pallas.flash_attention import pallas_flash_attention
    q, k, v = make_qkv(s=48, h=4, hkv=2)
    rng = np.random.default_rng(9)
    seg = jnp.asarray(np.sort(rng.integers(0, 3, size=(2, 48)), axis=1),
                      jnp.int32)
    out = pallas_flash_attention(q, k, v, True, 16, 16, True, 12, seg)
    ref = attention_reference(q, k, v, causal=True, window=12,
                              segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
