"""Pallas kernel tests (interpret mode on CPU; same code path compiles on TPU).

Reference analog: tests/unit/ops/* — each native kernel vs a reference
implementation on random tensors.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.flash_attention import attention_reference
from deepspeed_tpu.ops.pallas.flash_attention import pallas_flash_attention
from deepspeed_tpu.ops.pallas.quant import dequantize_int8, quantize_int8
from deepspeed_tpu.ops.pallas.rms_norm import pallas_rms_norm, rms_norm_reference


def qkv(b=2, s=128, h=4, hkv=None, d=32, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    hkv = hkv or h
    return (jnp.asarray(rng.normal(size=(b, s, h, d)), dtype),
            jnp.asarray(rng.normal(size=(b, s, hkv, d)), dtype),
            jnp.asarray(rng.normal(size=(b, s, hkv, d)), dtype))


@pytest.mark.parametrize("causal", [True, False])
def test_pallas_flash_matches_reference(causal):
    q, k, v = qkv()
    out = pallas_flash_attention(q, k, v, causal, 64, 64, True)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_pallas_flash_gqa_unaligned():
    q, k, v = qkv(s=100, h=8, hkv=2)   # padding + GQA index mapping
    out = pallas_flash_attention(q, k, v, True, 64, 64, True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_pallas_flash_grad(causal):
    q, k, v = qkv(s=64)

    def loss_p(q, k, v):
        return jnp.sum(pallas_flash_attention(q, k, v, causal, 32, 32, True) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

    gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_pallas_flash_grad_gqa_unaligned():
    # GQA (in-kernel group accumulation for dk/dv) + q/k padding in backward
    q, k, v = qkv(s=100, h=8, hkv=2)

    def loss_p(q, k, v):
        return jnp.sum(pallas_flash_attention(q, k, v, True, 32, 32, True) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_pallas_flash_grad_weighted_loss():
    # asymmetric cotangent exercises delta = rowsum(dO*O) properly
    q, k, v = qkv(s=64, h=2)
    w = jnp.asarray(np.random.default_rng(9).normal(size=(2, 64, 2, 32)),
                    jnp.float32)

    def loss_p(q, k, v):
        return jnp.sum(w * pallas_flash_attention(q, k, v, True, 32, 32, True))

    def loss_r(q, k, v):
        return jnp.sum(w * attention_reference(q, k, v, causal=True))

    gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_pallas_rms_norm():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 37, 256)), jnp.float32)
    scale = jnp.asarray(rng.normal(size=(256,)), jnp.float32)
    out = pallas_rms_norm(x, scale, 1e-5, 64, True)
    ref = rms_norm_reference(x, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_pallas_rms_norm_grad():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(8, 128)), jnp.float32)
    scale = jnp.asarray(1.0 + 0.1 * rng.normal(size=(128,)), jnp.float32)

    def loss_p(x, s):
        return jnp.sum(pallas_rms_norm(x, s, 1e-5, 8, True) ** 3)

    def loss_r(x, s):
        return jnp.sum(rms_norm_reference(x, s) ** 3)

    gp = jax.grad(loss_p, argnums=(0, 1))(x, scale)
    gr = jax.grad(loss_r, argnums=(0, 1))(x, scale)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_int8_quant_roundtrip():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(16, 512)) * 3.0, jnp.float32)
    q, s = quantize_int8(x, interpret=True)
    assert q.dtype == jnp.int8 and s.shape == (16, 1)
    back = dequantize_int8(q, s, dtype=jnp.float32, interpret=True)
    # int8 symmetric: relative error bounded by ~scale/2 = absmax/254
    err = np.abs(np.asarray(back) - np.asarray(x))
    bound = np.abs(np.asarray(x)).max(axis=-1, keepdims=True) / 127
    assert (err <= bound).all()


def test_int8_quant_extremes():
    x = jnp.zeros((4, 128), jnp.float32)
    q, s = quantize_int8(x, interpret=True)
    assert np.allclose(np.asarray(q), 0)
    back = dequantize_int8(q, s, dtype=jnp.float32, interpret=True)
    assert np.allclose(np.asarray(back), 0)


def test_quantized_all_gather(mesh_dp8):
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.ops.pallas.quant import quantized_all_gather
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(16, 128)), jnp.float32)

    def body(x_l):
        return quantized_all_gather(x_l, "data")

    out = jax.jit(lambda v: jax.shard_map(
        body, mesh=mesh_dp8, in_specs=P("data"), out_specs=P(),
        check_vma=False)(v))(x)
    rel = np.abs(np.asarray(out) - np.asarray(x)) / (np.abs(np.asarray(x)).max())
    assert rel.max() < 0.02  # int8 quantization error bound


@pytest.mark.parametrize("window,softcap", [(None, None), (24, None),
                                            (None, 20.0)])
def test_paged_attention_kernel(window, softcap):
    """Paged decode/prefill kernel vs gather reference (GQA, ragged lengths,
    trash-padded tables, sliding window)."""
    from deepspeed_tpu.ops.pallas.paged_attention import (
        paged_attention, paged_attention_reference)
    rng = np.random.default_rng(0)
    hkv, nb, bs, d = 2, 16, 16, 32
    kp = jnp.asarray(rng.normal(size=(hkv, nb, bs, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(hkv, nb, bs, d)), jnp.float32)
    # decode: B=3, rep=4
    q = jnp.asarray(rng.normal(size=(3, 1, 8, d)), jnp.float32)
    tables = jnp.asarray(rng.permutation(nb - 1)[:12].reshape(3, 4), jnp.int32)
    start = jnp.asarray([37, 5, 63], jnp.int32)
    out_k = paged_attention(q, kp, vp, tables, start, window=window,
                            softcap=softcap, interpret=True)
    out_r = paged_attention_reference(q, kp, vp, tables, start, window=window,
                                      softcap=softcap)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_r),
                               atol=2e-5, rtol=2e-5)
    # prefill chunk: B=1, T=24 at offset 16
    q = jnp.asarray(rng.normal(size=(1, 24, 4, d)), jnp.float32)
    tables = jnp.asarray([[3, 7, 1, 9]], jnp.int32)
    start = jnp.asarray([16], jnp.int32)
    out_k = paged_attention(q, kp, vp, tables, start, window=window,
                            softcap=softcap, interpret=True)
    out_r = paged_attention_reference(q, kp, vp, tables, start, window=window,
                                      softcap=softcap)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_r),
                               atol=2e-5, rtol=2e-5)


def test_quantized_psum_scatter(mesh_dp8):
    """qgZ reduce-scatter building block: int8-wire sum matches psum_scatter
    within quantization error."""
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.ops.pallas.quant import quantized_psum_scatter
    rng = np.random.default_rng(4)
    # 8 devices, each holding a [16, 64] partial
    parts = jnp.asarray(rng.normal(size=(8, 16, 64)), jnp.float32)

    def body(x_l):
        return quantized_psum_scatter(x_l[0], "data")

    out = jax.jit(lambda v: jax.shard_map(
        body, mesh=mesh_dp8, in_specs=P("data"), out_specs=P("data"),
        check_vma=False)(v))(parts)
    exact = np.asarray(parts).sum(0)               # [16, 64] global sum
    got = np.asarray(out)                          # same, reassembled
    rel = np.abs(got - exact).max() / np.abs(exact).max()
    assert rel < 0.05, rel


def test_all_to_all_quant_reduce_hierarchical(mesh8):
    """Two-level qgZ over (fsdp, data): result matches the exact global sum."""
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.ops.pallas.quant import all_to_all_quant_reduce
    rng = np.random.default_rng(5)
    parts = jnp.asarray(rng.normal(size=(8, 16, 64)), jnp.float32)

    def body(x_l):
        return all_to_all_quant_reduce(x_l[0], "fsdp", outer_axis_name="data")

    out = jax.jit(lambda v: jax.shard_map(
        body, mesh=mesh8, in_specs=P(("data", "fsdp")),
        out_specs=P(("fsdp", "data")), check_vma=False)(v))(parts)
    exact = np.asarray(parts).sum(0)
    got = np.asarray(out)
    rel = np.abs(got - exact).max() / np.abs(exact).max()
    assert rel < 0.05, rel


@pytest.mark.parametrize("fmt", ["e4m3", "e5m2"])
def test_fp8_quant_roundtrip(fmt):
    from deepspeed_tpu.ops.pallas.fp_quant import (
        FP8_FORMATS, dequantize_fp8, quantize_fp8)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(16, 256)) * 5.0, jnp.float32)
    q, s = quantize_fp8(x, fmt=fmt, interpret=True)
    assert q.dtype == FP8_FORMATS[fmt][0] and s.shape == (16, 1)
    back = dequantize_fp8(q, s, dtype=jnp.float32, interpret=True)
    # jnp reference: scale to fmax, cast, cast back. The fp8 cast itself
    # must go through jnp so reference and kernel share XLA's convert
    # rounding — numpy/ml_dtypes rounds a handful of near-tie values one
    # ulp differently on this backend, which is cast-library drift, not a
    # kernel defect
    dt, fmax = FP8_FORMATS[fmt]
    scale = np.maximum(np.abs(np.asarray(x)).max(-1, keepdims=True) / fmax, 1e-12)
    ref = np.asarray(
        jnp.asarray(np.asarray(x) / scale).astype(dt).astype(jnp.float32)
    ) * scale
    np.testing.assert_allclose(np.asarray(back), ref, rtol=1e-6, atol=1e-6)
    # error bound: e4m3 has 3 mantissa bits -> rel err <= 2^-4 per element
    rel = np.abs(np.asarray(back) - np.asarray(x)) / \
        (np.abs(np.asarray(x)) + 1e-3)
    assert rel.max() < (0.07 if fmt == "e4m3" else 0.3)


def test_fp8_selective_dequantize():
    from deepspeed_tpu.ops.pallas.fp_quant import (
        dequantize_fp8, quantize_fp8, selective_dequantize_fp8)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(64, 128)), jnp.float32)
    q, s = quantize_fp8(x, interpret=True)
    rows = jnp.asarray([3, 17, 42], jnp.int32)
    got = selective_dequantize_fp8(q, s, rows, dtype=jnp.float32,
                                   interpret=True)
    full = dequantize_fp8(q, s, dtype=jnp.float32, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(full)[[3, 17, 42]])


def test_fp8_all_gather(mesh_dp8):
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.ops.pallas.fp_quant import quantized_all_gather_fp8
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(16, 128)), jnp.float32)
    out = jax.jit(lambda v: jax.shard_map(
        lambda x_l: quantized_all_gather_fp8(x_l, "data"),
        mesh=mesh_dp8, in_specs=P("data"), out_specs=P(),
        check_vma=False)(v))(x)
    rel = np.abs(np.asarray(out) - np.asarray(x)) / np.abs(np.asarray(x)).max()
    assert rel.max() < 0.07


def test_fp8_matmul_close_to_fp32():
    from deepspeed_tpu.ops.pallas.fp_quant import fp8_matmul, quantize_fp8
    rng = np.random.default_rng(3)
    a = jnp.asarray(rng.normal(size=(8, 128)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(128, 64)) * 0.1, jnp.float32)
    # fp8_matmul expects per-K-row scales: quantize_fp8 groups over the last
    # dim, so quantizing b [K, N] directly yields scales [K, 1] as required
    q, s = quantize_fp8(b, interpret=True)
    out = fp8_matmul(a, q, s)
    ref = np.asarray(a) @ np.asarray(b)
    rel = np.abs(np.asarray(out) - ref).max() / np.abs(ref).max()
    assert rel < 0.1, rel


def test_quantized_all_to_all(mesh_dp8):
    """MoE-dispatch int8 all-to-all: permutation semantics match the fp
    all_to_all within quantization error."""
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.ops.pallas.quant import quantized_all_to_all
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.normal(size=(64, 128)), jnp.float32)  # 8 rows/device

    def body_q(x_l):
        return quantized_all_to_all(x_l, "data")

    def body_f(x_l):
        return jax.lax.all_to_all(x_l, "data", split_axis=0, concat_axis=0,
                                  tiled=True)

    run = lambda body: np.asarray(jax.jit(lambda v: jax.shard_map(
        body, mesh=mesh_dp8, in_specs=P("data"), out_specs=P("data"),
        check_vma=False)(v))(x))
    got, ref = run(body_q), run(body_f)
    rel = np.abs(got - ref).max() / np.abs(ref).max()
    assert rel < 0.02, rel


def test_quantized_psum_grad(mesh_dp8):
    """quantized_psum's straight-through vjp matches lax.psum's transpose —
    convention regression guard for the calibration documented in
    quant.py:_quantized_psum_bwd (check_vma=False hands dL/dy / w)."""
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.ops.pallas.quant import quantized_psum
    x = jnp.asarray(np.random.default_rng(5).normal(size=(16, 64)), jnp.float32)

    def mk(body):
        f = jax.shard_map(body, mesh=mesh_dp8, in_specs=P("data"),
                          out_specs=P(), axis_names=frozenset({"data"}),
                          check_vma=False)
        return jax.grad(lambda v: jnp.sum(jax.jit(f)(v) ** 2))(x)

    g_ref = mk(lambda xl: jax.lax.psum(xl, "data"))
    g_q = mk(lambda xl: quantized_psum(xl, ("data",)))
    rel = np.abs(np.asarray(g_q) - np.asarray(g_ref)).max() / \
        np.abs(np.asarray(g_ref)).max()
    assert rel < 0.03, rel   # identical up to int8 fwd rounding in g_ref's y


@pytest.mark.slow
def test_quantized_psum_grad_two_axes():
    """Same convention guard over TWO manual axes (the MoE dispatch path
    reduces over composed batch axes): bwd scaling must be 1/(w1*w2)."""
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.comm.mesh import create_mesh
    from deepspeed_tpu.config.config import MeshConfig
    from deepspeed_tpu.ops.pallas.quant import quantized_psum
    mesh = create_mesh(MeshConfig(data=4, fsdp=2))
    x = jnp.asarray(np.random.default_rng(6).normal(size=(16, 64)), jnp.float32)

    def mk(body):
        f = jax.shard_map(body, mesh=mesh, in_specs=P(("data", "fsdp")),
                          out_specs=P(),
                          axis_names=frozenset({"data", "fsdp"}),
                          check_vma=False)
        return jax.grad(lambda v: jnp.sum(jax.jit(f)(v) ** 2))(x)

    g_ref = mk(lambda xl: jax.lax.psum(xl, ("data", "fsdp")))
    g_q = mk(lambda xl: quantized_psum(xl, ("data", "fsdp")))
    rel = np.abs(np.asarray(g_q) - np.asarray(g_ref)).max() / \
        np.abs(np.asarray(g_ref)).max()
    assert rel < 0.03, rel


@pytest.mark.slow
@pytest.mark.parametrize("window", [16, 40])
def test_pallas_flash_sliding_window(window):
    """Sliding-window masking in the flash fwd + both backward kernels
    (mistral-style training on the kernel path; below-window blocks are
    skipped like above-diagonal ones). GQA + unaligned seq included."""
    from deepspeed_tpu.models.llama import _xla_attention
    q, k, v = qkv(s=100, h=8, hkv=2)

    out = pallas_flash_attention(q, k, v, True, 32, 32, True, window)
    ref = _xla_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    def lp(q, k, v):
        return jnp.sum(
            pallas_flash_attention(q, k, v, True, 32, 32, True, window) ** 2)

    def lr(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, causal=True,
                                      window=window) ** 2)

    gp = jax.grad(lp, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)
