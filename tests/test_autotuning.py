"""Autotuning tests.

Reference analog: ``tests/unit/autotuning/test_autotuning.py`` — tuner strategy
behavior and experiment bookkeeping on tiny search spaces, no real cluster runs.
"""

import json
import os

import numpy as np
import pytest

from deepspeed_tpu.autotuning import (
    Autotuner,
    CostModel,
    Experiment,
    GridSearchTuner,
    ModelBasedTuner,
    RandomTuner,
    estimate_state_bytes,
    merge_config,
)
from deepspeed_tpu.models.simple import SimpleModel, random_batch

BASE = {"optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "gradient_accumulation_steps": 1}


def _mk_exps(mbs_list, stage=1):
    return [Experiment(f"z{stage}_mbs{m}",
                       {"zero_optimization": {"stage": stage},
                        "train_micro_batch_size_per_gpu": m})
            for m in mbs_list]


def _synthetic_runner(peak_mbs=8):
    """Throughput rises then falls around peak_mbs; deterministic."""
    def run(exp):
        mbs = exp.overrides["train_micro_batch_size_per_gpu"]
        exp.metrics = {"throughput": 100.0 - (np.log2(mbs) - np.log2(peak_mbs)) ** 2,
                       "latency": 1.0 + abs(mbs - peak_mbs)}
        exp.status = "done"
    return run


def test_merge_config_nested():
    out = merge_config({"a": {"x": 1, "y": 2}, "b": 3}, {"a": {"y": 9}, "c": 4})
    assert out == {"a": {"x": 1, "y": 9}, "b": 3, "c": 4}


def test_autotuning_config_group_overrides_kwargs(mesh_dp8):
    """The ds-config "autotuning" group configures the tuner (single-JSON
    contract): group values beat constructor defaults; unknown keys warn
    and are ignored."""
    cfg = {**BASE, "autotuning": {
        "metric": "latency", "tuner_type": "gridsearch",
        "zero_stages": [0, 1], "max_micro_batch": 4,
        "num_tuning_trials": 7, "bogus_knob": True}}
    tuner = Autotuner(SimpleModel(hidden_dim=32), cfg,
                      batch_fn=random_batch, mesh=mesh_dp8)
    assert tuner.metric == "latency"
    assert tuner.tuner_type == "gridsearch"
    assert tuner.zero_stages == [0, 1]
    assert tuner.max_micro_batch == 4
    assert tuner.n_trials == 7
    with pytest.raises(ValueError):
        Autotuner(SimpleModel(hidden_dim=32),
                  {**BASE, "autotuning": {"metric": "nope"}},
                  batch_fn=random_batch, mesh=mesh_dp8)
    # enabled=false: tune() is a pass-through, no trials burned
    off = Autotuner(SimpleModel(hidden_dim=32),
                    {**BASE, "autotuning": {"enabled": False}},
                    batch_fn=random_batch, mesh=mesh_dp8)
    best_cfg, metrics = off.tune()
    assert metrics == {} and best_cfg == off.base_config
    assert off.records == []
    # bare-bool shorthand: `"autotuning": false` disables, `true` enables
    assert not Autotuner(SimpleModel(hidden_dim=32),
                         {**BASE, "autotuning": False},
                         batch_fn=random_batch, mesh=mesh_dp8).enabled
    assert Autotuner(SimpleModel(hidden_dim=32),
                     {**BASE, "autotuning": True},
                     batch_fn=random_batch, mesh=mesh_dp8).enabled
    # any other non-dict is a config error, not a cryptic TypeError
    with pytest.raises(ValueError, match="must be a dict"):
        Autotuner(SimpleModel(hidden_dim=32),
                  {**BASE, "autotuning": "yes"},
                  batch_fn=random_batch, mesh=mesh_dp8)


def test_grid_search_finds_best():
    exps = _mk_exps([1, 2, 4, 8, 16, 32])
    t = GridSearchTuner(exps, _synthetic_runner(), metric="throughput")
    best = t.tune()
    assert best.overrides["train_micro_batch_size_per_gpu"] == 8
    assert len(t.records) == 6


def test_random_tuner_explores_all():
    exps = _mk_exps([1, 2, 4, 8])
    t = RandomTuner(exps, _synthetic_runner(), metric="latency",
                    higher_is_better=False, seed=3)
    best = t.tune()
    assert best.overrides["train_micro_batch_size_per_gpu"] == 8
    assert len(t.records) == 4


def test_early_stopping_limits_trials():
    exps = _mk_exps([8, 16, 32, 1, 2, 4])  # best first -> stops early
    t = GridSearchTuner(exps, _synthetic_runner(), metric="throughput")
    t.tune(early_stopping=2)
    assert len(t.records) < 6


def test_cost_model_orders_candidates():
    train = _mk_exps([1, 2, 32])
    run = _synthetic_runner()
    for e in train:
        run(e)
    cm = CostModel()
    cm.fit(train, "throughput")
    lo, hi = _mk_exps([1])[0], _mk_exps([4])[0]
    assert cm.predict(hi) > cm.predict(lo)


def test_model_based_tuner_converges_with_budget():
    exps = _mk_exps([1, 2, 4, 8, 16, 32, 64, 128])
    t = ModelBasedTuner(exps, _synthetic_runner(), metric="throughput",
                        seed_trials=3)
    best = t.tune(n_trials=6)
    assert best.overrides["train_micro_batch_size_per_gpu"] == 8


def test_estimate_state_bytes_monotone_in_stage():
    n = 1_000_000
    vals = [estimate_state_bytes(n, s, fsdp_size=8) for s in range(4)]
    assert vals[0] > vals[1] > vals[2] > vals[3]
    assert vals[0] == (2 + 4 + 12) * n


@pytest.mark.slow
def test_autotuner_end_to_end(tmp_path, mesh_dp8):
    model = SimpleModel(hidden_dim=16)
    tuner = Autotuner(
        model, BASE, batch_fn=random_batch, mesh=mesh_dp8,
        zero_stages=[0, 1], max_micro_batch=2, num_micro_batches=2,
        tuner_type="gridsearch", warmup_steps=1, measure_steps=1,
        results_dir=str(tmp_path))
    info = tuner.model_info()
    assert info["num_params"] > 0
    best_config, metrics = tuner.tune()
    assert best_config is not None
    assert metrics["throughput"] > 0
    assert best_config["zero_optimization"]["stage"] in (0, 1)
    results = json.loads((tmp_path / "autotuning_results.json").read_text())
    assert results["best"] is not None
    assert len(results["experiments"]) == 4  # 2 stages x 2 mbs
    assert all(e["status"] == "done" for e in results["experiments"])


def test_autotuner_survives_failing_candidate(mesh_dp8):
    model = SimpleModel(hidden_dim=16)
    tuner = Autotuner(model, {**BASE, "optimizer": {"type": "nope", "params": {}}},
                      batch_fn=random_batch, mesh=mesh_dp8,
                      zero_stages=[0], max_micro_batch=1, num_micro_batches=1,
                      tuner_type="gridsearch")
    best_config, metrics = tuner.tune()
    assert best_config is None
    assert tuner.records[0].status in ("failed", "oom")


def test_feasible_stages_pruned_by_hbm():
    model = SimpleModel(hidden_dim=64)
    tuner = Autotuner(model, BASE, batch_fn=random_batch,
                      zero_stages=[0, 1, 2, 3], hbm_bytes=1)  # nothing fits
    stages = tuner.feasible_stages(fsdp_size=8)
    assert stages == [3]  # falls back to the most-sharded stage


# ---------------------------------------------------------------------------
# process-isolated experiments (reference: scheduler.py:414 _launch_exp —
# a candidate that dies or hangs must not kill the tune)
# ---------------------------------------------------------------------------

def _isolated_factory():
    """Rebuilt inside each experiment child. The experiment name rides in
    DSTPU_TUNE_NAME; 'killer' candidates hard-kill the child mid-step (a hard
    OOM stand-in no try/except can catch), 'hang' candidates sleep past the
    experiment timeout (a pathological-compile stand-in)."""
    name = os.environ.get("DSTPU_TUNE_NAME", "")

    def batch_fn(n):
        if "kill" in name:
            os._exit(137)          # simulated hard OOM kill
        if "hang" in name:
            import time
            time.sleep(600)        # simulated hung compile
        return random_batch(max(n, 1))

    return {"model": SimpleModel(hidden_dim=32), "batch_fn": batch_fn}


@pytest.mark.slow
def test_process_isolated_tune_survives_kill_and_hang(mesh_dp8):
    """One candidate hard-kills its child, one hangs past the timeout; the
    tune records both infeasible and still returns the best feasible config
    (reference: launched experiments die without killing the scheduler)."""
    from deepspeed_tpu.autotuning.scheduler import ProcessIsolatedRunner
    from deepspeed_tpu.autotuning.tuner import GridSearchTuner

    runner = ProcessIsolatedRunner(
        _isolated_factory, BASE, warmup_steps=1, measure_steps=1,
        timeout=20.0, cpu_devices=1)
    exps = [Experiment("z0_mbs1_kill",
                       {"zero_optimization": {"stage": 0},
                        "train_micro_batch_size_per_gpu": 1}),
            Experiment("z0_mbs1_hang",
                       {"zero_optimization": {"stage": 0},
                        "train_micro_batch_size_per_gpu": 1}),
            Experiment("z0_mbs2_ok",
                       {"zero_optimization": {"stage": 0},
                        "train_micro_batch_size_per_gpu": 2})]
    tuner = GridSearchTuner(exps, runner, metric="throughput",
                            higher_is_better=True)
    best = tuner.tune()
    by_name = {e.name: e for e in tuner.records}
    assert by_name["z0_mbs1_kill"].status == "oom", by_name["z0_mbs1_kill"]
    assert by_name["z0_mbs1_hang"].status == "timeout"
    assert by_name["z0_mbs2_ok"].status == "done"
    assert best is not None and best.name == "z0_mbs2_ok"
    assert best.metrics["throughput"] > 0


def test_autotuner_process_isolation_requires_factory():
    with pytest.raises(ValueError, match="model_factory"):
        Autotuner(SimpleModel(hidden_dim=32), BASE, batch_fn=random_batch,
                  isolation="process")


def test_offload_dimension_in_search_space():
    """Stages that fit only with the host optimizer tier enter the space
    offloaded; try_offload=True adds offload variants everywhere
    (reference: the autotuner's offloading dimension)."""
    from deepspeed_tpu.autotuning.autotuner import (Autotuner,
                                                    estimate_state_bytes)
    from deepspeed_tpu.models.simple import SimpleModel, random_batch
    n = 1_000_000
    # offload zeroes device optimizer bytes and shrinks the grad buffer
    assert estimate_state_bytes(n, 0, 1, offload_optimizer=True) < \
        estimate_state_bytes(n, 0, 1)

    def mk(**kw):
        return Autotuner(SimpleModel(hidden_dim=512),
                         {"train_batch_size": 8,
                          "optimizer": {"type": "AdamW",
                                        "params": {"lr": 1e-3}}},
                         batch_fn=random_batch, **kw)
    # HBM budget between the offloaded (4n) and plain (18n) footprints:
    # plain stages can't fit, offloaded ones can
    n_model = mk().model_info()["num_params"]
    t = mk(hbm_bytes=8 * n_model)
    pairs = t.feasible_configs(1)
    assert pairs and all(off for _, off in pairs), pairs
    names = [e.name for e in t.generate_experiments(pairs)]
    assert all(n.endswith("_off") for n in names), names
    # generous HBM: plain stages only unless try_offload=True
    t2 = mk(hbm_bytes=int(1e12))
    assert all(not off for _, off in t2.feasible_configs(1))
    t3 = mk(hbm_bytes=int(1e12), try_offload=True)
    offs = [off for _, off in t3.feasible_configs(1)]
    assert any(offs) and not all(offs)
