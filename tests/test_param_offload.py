"""ZeRO-Infinity training-side parameter offload (runtime/param_offload.py).

Reference parity target: runtime/swap_tensor/partitioned_param_swapper.py —
params stream from host/NVMe around fwd/bwd instead of living in device HBM.
"""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                        random_tokens)

VOCAB = 256


def tiny_cfg(**kw):
    base = dict(vocab_size=VOCAB, hidden_size=64, intermediate_size=128,
                num_layers=4, num_heads=4, num_kv_heads=2, max_seq_len=64,
                dtype=jnp.float32, attention_backend="xla", remat=False)
    base.update(kw)
    return LlamaConfig(**base)


ADAMW = {"type": "AdamW", "params": {"lr": 1e-2, "betas": (0.9, 0.999),
                                     "eps": 1e-8, "weight_decay": 0.0}}


def make_engine(model, zero=None, mesh=None, gas=2, micro=2, seed=0, **cfg_kw):
    dp = mesh.shape.get("data", 1) if mesh is not None else jax.device_count()
    config = {"train_batch_size": micro * gas * dp,
              "gradient_accumulation_steps": gas,
              "optimizer": ADAMW, **cfg_kw}
    if zero is not None:
        config["zero_optimization"] = zero
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=config, mesh=mesh, seed=seed,
        example_batch=random_tokens(2, 32, vocab_size=VOCAB))
    return engine


def run_steps(engine, steps=3, gas=2, seq=32):
    losses = []
    n = engine.train_batch_size // gas
    for i in range(steps):
        b = random_tokens(n, seq, vocab_size=VOCAB, seed=i, gas=gas)
        losses.append(float(jax.device_get(
            engine.train_batch(batch=b, stacked=True))))
    return losses


def max_param_diff(a_tree, b_tree):
    return max(float(np.max(np.abs(np.asarray(a, np.float32)
                                   - np.asarray(b, np.float32))))
               for a, b in zip(jax.tree.leaves(a_tree), jax.tree.leaves(b_tree)))


def test_param_offload_cpu_matches_dense():
    # NOT slow-marked: the one dense-vs-offload parity assert kept in the
    # default run (the exhaustive flavor matrix runs under -m slow)
    model = LlamaForCausalLM(tiny_cfg())
    e1 = make_engine(model)
    l1 = run_steps(e1)
    e2 = make_engine(model, zero={"stage": 0, "offload_param": {
        "device": "cpu", "layers_per_group": 2}})
    l2 = run_steps(e2)
    # identical streamed math: losses match the dense engine step for step
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
    assert l2[-1] < l2[0]
    diff = max_param_diff(jax.device_get(e1.state.params), e2.get_params())
    assert diff < 5e-4, diff  # CPUAdam vs optax epsilon placement
    assert e2.state.params == ()  # no device-resident params


def test_param_offload_uneven_groups_and_gas1():
    model = LlamaForCausalLM(tiny_cfg())
    # 4 layers / 3-per-group -> groups of 3 and 1 (two jit variants)
    e = make_engine(model, gas=1, zero={"stage": 0, "offload_param": {
        "device": "cpu", "layers_per_group": 3}})
    losses = run_steps(e, steps=4, gas=1)
    assert losses[-1] < losses[0]
    assert [len(g) for g in e._param_offload._layer_groups] == [3, 1]


@pytest.mark.slow
def test_param_offload_nvme_trains_and_twin_flow(tmp_path):
    model = LlamaForCausalLM(tiny_cfg())
    e = make_engine(model, zero={"stage": 0, "offload_param": {
        "device": "nvme", "nvme_path": str(tmp_path),
        "layers_per_group": 1, "ratio": 0.5}})
    losses = run_steps(e, steps=4)
    assert losses[-1] < losses[0]
    # Twin-Flow ratio=0.5 over 4 groups: first 2 pinned in RAM, last 2 on nvme
    assert e._param_offload._nvme_groups == [False, False, True, True]
    files = glob.glob(str(tmp_path / "params_proc0" / "group*.bin"))
    assert sorted(os.path.basename(f) for f in files) == \
        ["group2.bin", "group3.bin"]
    # nvme matches the cpu-offload result exactly (same math, different tier)
    e2 = make_engine(model, zero={"stage": 0, "offload_param": {
        "device": "cpu", "layers_per_group": 1}})
    l2 = run_steps(e2, steps=4)
    np.testing.assert_allclose(losses, l2, rtol=1e-6)
    assert max_param_diff(e.get_params(), e2.get_params()) < 1e-6


@pytest.mark.slow
def test_param_offload_tied_embeddings_matches_dense():
    model = LlamaForCausalLM(tiny_cfg(tie_embeddings=True))
    e1 = make_engine(model)
    l1 = run_steps(e1)
    e2 = make_engine(model, zero={"stage": 0,
                                  "offload_param": {"device": "cpu"}})
    l2 = run_steps(e2)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
    assert max_param_diff(jax.device_get(e1.state.params),
                          e2.get_params()) < 5e-4


@pytest.mark.slow
def test_param_offload_grad_clip_matches_dense():
    model = LlamaForCausalLM(tiny_cfg())
    e1 = make_engine(model, gradient_clipping=0.01)
    l1 = run_steps(e1)
    e2 = make_engine(model, gradient_clipping=0.01,
                     zero={"stage": 0, "offload_param": {"device": "cpu"}})
    l2 = run_steps(e2)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
    assert max_param_diff(jax.device_get(e1.state.params),
                          e2.get_params()) < 5e-4


def test_param_offload_data_parallel_mesh(mesh_dp8):
    model = LlamaForCausalLM(tiny_cfg())
    e = make_engine(model, mesh=mesh_dp8, micro=8, gas=1,
                    zero={"stage": 0, "offload_param": {"device": "cpu"}})
    losses = run_steps(e, steps=3, gas=1)
    assert losses[-1] < losses[0]


def test_param_offload_bf16_loss_decreases():
    model = LlamaForCausalLM(tiny_cfg(dtype=jnp.bfloat16))
    e = make_engine(model, zero={"stage": 0,
                                 "offload_param": {"device": "cpu"}},
                    **{"bf16": {"enabled": True}})
    losses = run_steps(e, steps=5)
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_param_offload_checkpoint_roundtrip(tmp_path):
    model = LlamaForCausalLM(tiny_cfg())
    zero = {"stage": 0, "offload_param": {"device": "cpu"}}
    e1 = make_engine(model, zero=zero)
    run_steps(e1, steps=2)
    e1.save_checkpoint(str(tmp_path / "ckpt"))
    cont = run_steps(e1, steps=1)           # one more step on the original

    e2 = make_engine(model, zero=zero, seed=7)
    e2.load_checkpoint(str(tmp_path / "ckpt"))
    assert max_param_diff(e1.get_params(), e2.get_params()) > 0  # e1 stepped on
    resumed = run_steps(e2, steps=1)
    # resumed step == continued step (masters AND moments restored)
    np.testing.assert_allclose(cont, resumed, rtol=1e-5)
    assert max_param_diff(e1.get_params(), e2.get_params()) < 1e-6


def test_param_offload_unsupported_configs_raise():
    scan_model = LlamaForCausalLM(tiny_cfg(scan_layers=True))
    with pytest.raises(ValueError, match="scan_layers"):
        make_engine(scan_model, zero={"stage": 0,
                                      "offload_param": {"device": "cpu"}})
    model = LlamaForCausalLM(tiny_cfg())
    with pytest.raises(ValueError, match="fp16|bf16"):
        make_engine(model, zero={"stage": 0,
                                 "offload_param": {"device": "cpu"}},
                    **{"fp16": {"enabled": True}})
    with pytest.raises(ValueError, match="nvme_path"):
        make_engine(model, zero={"stage": 0,
                                 "offload_param": {"device": "nvme"}})
    with pytest.raises(ValueError, match="layered model"):
        from deepspeed_tpu.models.simple import SimpleModel, random_batch
        deepspeed_tpu.initialize(
            model=SimpleModel(hidden_dim=32),
            config={"train_batch_size": jax.device_count(),
                    "optimizer": ADAMW,
                    "zero_optimization": {
                        "stage": 0, "offload_param": {"device": "cpu"}}},
            example_batch=random_batch(4))
    with pytest.raises(ValueError, match="none|cpu|nvme"):
        make_engine(model, zero={"stage": 0,
                                 "offload_param": {"device": "disk"}})


def test_param_offload_compat_apis_raise():
    model = LlamaForCausalLM(tiny_cfg())
    e = make_engine(model, zero={"stage": 0,
                                 "offload_param": {"device": "cpu"}})
    with pytest.raises(NotImplementedError, match="train_batch"):
        e.forward(random_tokens(2, 32, vocab_size=VOCAB))
    with pytest.raises(NotImplementedError, match="train_batch"):
        e.step()


def test_param_offload_tp_sharded_streaming():
    """With tensor_rules, streamed leaves land on device sharded over the
    tensor axis (1/tp the H2D + HBM per chip) and training still matches
    the replicated stream numerically."""
    from deepspeed_tpu.comm.mesh import create_mesh, set_global_mesh
    from deepspeed_tpu.config.config import MeshConfig
    from deepspeed_tpu.models.llama import llama_tensor_rules

    mesh = create_mesh(MeshConfig(data=2, tensor=4))
    set_global_mesh(mesh)
    model = LlamaForCausalLM(tiny_cfg())
    config = {"train_batch_size": 4, "gradient_accumulation_steps": 1,
              "optimizer": ADAMW,
              "zero_optimization": {"stage": 0,
                                    "offload_param": {"device": "cpu"}}}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=config, mesh=mesh, seed=0,
        tensor_rules=llama_tensor_rules,
        example_batch=random_tokens(2, 32, vocab_size=VOCAB))
    po = engine._param_offload
    wq = [i for i, p in enumerate(po._paths) if p.endswith("wq/kernel")]
    assert wq and all("tensor" in jax.tree_util.tree_leaves(
        [po._leaf_sharding[i].spec]) or
        any("tensor" in str(e) for e in po._leaf_sharding[i].spec)
        for i in wq), [po._leaf_sharding[i].spec for i in wq]
    # train on ONE fixed batch: random-token batches carry no shared
    # signal, so a fresh batch per step leaves the loss hovering near
    # ln(VOCAB) and the convergence sign flips on short horizons;
    # memorizing a fixed batch drops decisively within 3 steps
    losses = [float(jax.device_get(engine.train_batch(
        batch=random_tokens(4, 32, vocab_size=VOCAB, seed=0, gas=1),
        stacked=True))) for i in range(3)]
    assert losses[-1] < losses[0], losses
    # numerically identical to the REPLICATED stream on the same mesh/batch
    e2, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=config, mesh=mesh, seed=0,
        example_batch=random_tokens(2, 32, vocab_size=VOCAB))
    assert all(s == e2._param_offload._replicated
               for s in e2._param_offload._leaf_sharding)
    l2 = [float(jax.device_get(e2.train_batch(
        batch=random_tokens(4, 32, vocab_size=VOCAB, seed=0, gas=1),
        stacked=True))) for i in range(3)]
    np.testing.assert_allclose(losses, l2, rtol=1e-4)


@pytest.mark.slow
def test_param_offload_mistral_style_sliding_window():
    """Param offload covers the whole LlamaConfig family — a mistral-style
    config (sliding window, GQA) streams and matches its dense engine."""
    model = LlamaForCausalLM(tiny_cfg(sliding_window=16, num_kv_heads=2))
    e1 = make_engine(model)
    l1 = run_steps(e1)
    e2 = make_engine(model, zero={"stage": 0,
                                  "offload_param": {"device": "cpu"}})
    l2 = run_steps(e2)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
    assert l2[-1] < l2[0]


def test_param_offload_from_hf_checkpoint():
    """The real >HBM workflow: HF checkpoint -> from_hf_checkpoint ->
    initialize(params=..., offload_param) trains without ever building
    device-resident params."""
    import dataclasses
    from deepspeed_tpu.models.families import export_hf_state_dict
    from deepspeed_tpu.models.hf import from_hf_checkpoint
    cfg = tiny_cfg()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        random_tokens(2, 32, vocab_size=VOCAB))["params"]
    hf_state = export_hf_state_dict(params, cfg)
    hf_cfg = {"model_type": "llama", "vocab_size": VOCAB, "hidden_size": 64,
              "intermediate_size": 128, "num_hidden_layers": 4,
              "num_attention_heads": 4, "num_key_value_heads": 2,
              "max_position_embeddings": 64, "rope_theta": cfg.rope_theta}
    model2, cfg2, params2 = from_hf_checkpoint(hf_cfg, hf_state)
    model2 = type(model2)(dataclasses.replace(
        cfg2, dtype=jnp.float32, attention_backend="xla"))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model2, model_parameters=params2,
        config={"train_batch_size": jax.device_count(), "optimizer": ADAMW,
                "zero_optimization": {"stage": 0, "offload_param": {
                    "device": "cpu", "layers_per_group": 2}}},
        example_batch=random_tokens(2, 32, vocab_size=VOCAB))
    losses = [float(jax.device_get(engine.train_batch(
        batch=random_tokens(jax.device_count(), 32, vocab_size=VOCAB,
                            seed=i, gas=1), stacked=True)))
        for i in range(3)]
    assert losses[-1] < losses[0], losses
    assert engine.state.params == ()


@pytest.mark.slow
def test_checkpoint_interchange_with_zero3(tmp_path, mesh8):
    """UCP across memory tiers: a param-offload checkpoint restores into a
    plain ZeRO-3 engine (device-sharded params) and vice versa — same orbax
    composite, reshape-on-load."""
    model = LlamaForCausalLM(tiny_cfg())
    po_zero = {"stage": 0, "offload_param": {"device": "cpu"}}

    e1 = make_engine(model, zero=po_zero)
    run_steps(e1, steps=2)
    e1.save_checkpoint(str(tmp_path / "po"))

    from deepspeed_tpu.models.llama import llama_tensor_rules
    e2, _, _, _ = deepspeed_tpu.initialize(
        model=model, mesh=mesh8, tensor_rules=llama_tensor_rules,
        config={"train_batch_size": 8, "optimizer": ADAMW,
                "zero_optimization": {"stage": 3}},
        example_batch=random_tokens(2, 32, vocab_size=VOCAB))
    e2.load_checkpoint(str(tmp_path / "po"), load_optimizer_states=False)
    assert max_param_diff(e1.get_params(),
                          jax.device_get(e2.state.params)) < 1e-6
    # trains on from the restored weights
    l = float(jax.device_get(e2.train_batch(
        batch=random_tokens(8, 32, vocab_size=VOCAB, seed=9))))
    assert np.isfinite(l)

    # reverse: zero-3 checkpoint into a param-offload engine
    e2.save_checkpoint(str(tmp_path / "z3"))
    e3 = make_engine(model, zero=po_zero, seed=4)
    e3.load_checkpoint(str(tmp_path / "z3"), load_optimizer_states=False)
    assert max_param_diff(jax.device_get(e2.state.params),
                          e3.get_params()) < 1e-6


@pytest.mark.slow
def test_param_offload_mixtral_moe_matches_dense():
    """MoE param offload (streaming experts is THE weights>HBM MoE case):
    MixtralBlocks stream layer-group by layer-group, each group's gating
    aux loss rides the fwd carry and its unit cotangent seeds the group's
    backward — exact parity with the dense mixtral engine."""
    import dataclasses
    from deepspeed_tpu.models.mixtral import TINY_MIXTRAL, MixtralForCausalLM
    cfg = dataclasses.replace(
        TINY_MIXTRAL,
        base=dataclasses.replace(TINY_MIXTRAL.base, dtype=jnp.float32),
        moe=dataclasses.replace(TINY_MIXTRAL.moe, dtype=jnp.float32))
    model = MixtralForCausalLM(cfg)
    conf = {"train_batch_size": 2 * jax.device_count(),
            "gradient_accumulation_steps": 2, "optimizer": ADAMW}

    def steps(extra):
        e, _, _, _ = deepspeed_tpu.initialize(
            model=model, config={**conf, **extra},
            example_batch=random_tokens(2, 16, vocab_size=512))
        return e, [float(jax.device_get(e.train_batch(
            batch=random_tokens(jax.device_count(), 16, vocab_size=512,
                                seed=i, gas=2), stacked=True)))
            for i in range(3)]
    _, l1 = steps({})
    e2, l2 = steps({"zero_optimization": {
        "stage": 0, "offload_param": {"device": "cpu",
                                      "layers_per_group": 1}}})
    np.testing.assert_allclose(l1, l2, rtol=1e-4)
    assert l2[-1] < l2[0]
    assert e2.state.params == ()


@pytest.mark.slow
def test_param_offload_gemma_flavor_matches_dense():
    """Gemma-family knobs compose: tied embeddings + embed scaling + rms
    scale-offset + logit softcap all stream correctly."""
    model = LlamaForCausalLM(tiny_cfg(
        tie_embeddings=True, scale_embeddings=True, rms_scale_offset=True,
        logits_soft_cap=30.0, hidden_act="gelu_tanh"))
    e1 = make_engine(model)
    l1 = run_steps(e1)
    e2 = make_engine(model, zero={"stage": 0,
                                  "offload_param": {"device": "cpu"}})
    l2 = run_steps(e2)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
    assert max_param_diff(jax.device_get(e1.state.params),
                          e2.get_params()) < 5e-4


def test_param_offload_reports_applied_lr():
    """The lr metric must be the schedule value at the step the offload
    optimizer ACTUALLY applied (pre-increment), not the next step's."""
    model = LlamaForCausalLM(tiny_cfg(num_layers=2))
    e = make_engine(
        model, zero={"stage": 0, "offload_param": {"device": "cpu"}},
        scheduler={"type": "WarmupLR",
                   "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 1e-2,
                              "warmup_num_steps": 10}})
    for applied_step in range(2):
        run_steps(e, steps=1)
        expected = float(jax.device_get(e.lr_schedule(applied_step)))
        assert float(e._last_metrics["lr"]) == pytest.approx(expected)
