"""FP6/FP12 packed minifloat formats + true-fp8 GEMM tests.

Reference analog: tests/unit/ops/fp_quantizer (FP_Quantize q_bits sweeps +
fp8_gemm matmul parity).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.fp_formats import (FP_FORMATS, FPQuantizer, _decode,
                                          _encode, dequantize_fp,
                                          quantize_fp,
                                          selective_dequantize_fp)


@pytest.mark.parametrize("fmt", ["fp6", "fp12"])
def test_every_code_roundtrips(fmt):
    """decode->encode is the identity on the full code space (the format is
    self-consistent, incl. subnormals and the saturating top exponent)."""
    e, m = FP_FORMATS[fmt]
    codes = jnp.arange(1 << (1 + e + m), dtype=jnp.uint32)
    back = _encode(_decode(codes, e, m), e, m)
    neg_zero = 1 << (e + m)                   # -0.0 re-encodes as +0.0
    ok = np.asarray(back == codes)
    assert all(int(codes[i]) == neg_zero for i in np.where(~ok)[0])


@pytest.mark.parametrize("fmt,bound,bytes_per_256", [
    ("fp6", 0.13, 192),     # 0.75 B/elem, mantissa step 2^-3
    ("fp12", 0.009, 384),   # 1.5 B/elem, mantissa step 2^-7
])
def test_group_quantize_roundtrip_and_packing(fmt, bound, bytes_per_256):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(32, 256)).astype(np.float32))
    p, s = quantize_fp(x, fmt=fmt)
    assert p.shape == (32, bytes_per_256) and p.dtype == jnp.uint8
    y = dequantize_fp(p, s, fmt, 256, dtype=jnp.float32)
    rel = np.abs(np.asarray(y) - np.asarray(x)) / np.abs(np.asarray(x)).max()
    assert 0 < rel.max() < bound, rel.max()
    # selective row gather matches full dequantize
    rows = jnp.asarray([3, 17, 3], jnp.int32)
    sel = selective_dequantize_fp(p, s, rows, fmt, 256, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(sel),
                                  np.asarray(y)[np.asarray(rows)])


@pytest.mark.slow
def test_fp_quantizer_dispatch_bits():
    """FP_Quantize-parity shim: q_bits 6/8/12 all roundtrip within their
    mantissa error bounds, tighter with more bits."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(16, 128)).astype(np.float32))
    errs = {}
    for qb, bound in [(6, 0.13), (8, 0.07), (12, 0.009)]:
        fq = FPQuantizer(q_bits=qb)
        q, s = fq.quantize(x)
        kw = {} if qb == 8 else {"d": 128}
        y = fq.dequantize(q, s, dtype=jnp.float32, **kw)
        errs[qb] = float(np.abs(np.asarray(y) - np.asarray(x)).max() /
                         np.abs(np.asarray(x)).max())
        assert errs[qb] < bound, (qb, errs[qb])
    assert errs[12] < errs[8] < errs[6]
    with pytest.raises(ValueError):
        FPQuantizer(q_bits=4)


def test_fp8_gemm_operands_stay_fp8():
    """fp8_gemm: parity with the fp32 matmul within fp8 rounding, and the
    dot_general's HLO operands are f8 (no dequantized copy materializes —
    reference fp8_gemm.py contract)."""
    from deepspeed_tpu.ops.pallas.fp_quant import fp8_gemm, fp8_gemm_quantize
    rng = np.random.default_rng(2)
    a = jnp.asarray(rng.normal(size=(64, 128)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(128, 96)).astype(np.float32))
    a_q, s_m, b_q, s_n = fp8_gemm_quantize(a, b)
    assert a_q.dtype == jnp.float8_e4m3fn and b_q.dtype == jnp.float8_e4m3fn
    y = fp8_gemm(a_q, s_m, b_q, s_n, out_dtype=jnp.float32)
    ref = np.asarray(a) @ np.asarray(b)
    rel = float(np.abs(np.asarray(y) - ref).max() / np.abs(ref).max())
    assert rel < 0.05, rel
    txt = jax.jit(fp8_gemm, static_argnames="out_dtype").lower(
        a_q, s_m, b_q, s_n, out_dtype=jnp.float32).as_text()
    assert "f8e4m3" in txt.lower(), "dot operands not fp8 in HLO"
