"""dsmem tests: analytic ledger goldens, counter-track round-trips, the
watermark ratchet CLI, the chaos OOM forensics drill, and the dslint
hot-path proof for the sampler.

Deterministic by construction: ledger values are closed-form arithmetic,
the CLI exit matrix runs on checked-in fixtures (tests/mem_fixtures/ +
repo-root mem_baseline.json — regenerate BOTH with
``python tests/mem_fixtures/make_fixtures.py``), the sampler tests inject
fake device stats, and the OOM drill is seed-free chaos (``oom_step`` is
an exact step match).
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

import deepspeed_tpu
from deepspeed_tpu.models.simple import SimpleModel, random_batch
from deepspeed_tpu.telemetry.memory import (MEM_BASELINE_NAME, MemoryLedger,
                                            MemorySampler, PHASES,
                                            check_mem_baseline,
                                            estimate_zero2_model_states_mem_needs,
                                            estimate_zero3_model_states_mem_needs,
                                            is_oom_error, is_oom_message,
                                            next_offload_tier, preflight,
                                            tie_out, write_mem_baseline)
from deepspeed_tpu.telemetry.tracer import Tracer, configure_tracing, get_tracer

pytestmark = pytest.mark.mem

REPO = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = pathlib.Path(__file__).resolve().parent / "mem_fixtures"
DSTPU = str(REPO / "bin" / "dstpu")


def _engine(extra=None, seed=1):
    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}}
    if extra:
        cfg.update(extra)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=32), config=cfg,
        example_batch=random_batch(4), seed=seed)
    return engine


# ---------------------------------------------------------------------------
# ledger goldens (closed-form: 1000 params, 4-way ZeRO world, bf16 compute)
# ---------------------------------------------------------------------------
def _micro(stage, **kw):
    return MemoryLedger(num_params=1000, zero_stage=stage, zero_world=4,
                        compute_dtype="bf16", **kw)


def test_ledger_golden_zero_stages():
    """Stage-by-stage HBM plan: exactly the reference sharding arithmetic
    (fp32 masters 4B/p, Adam 8B/p, fp32 grad accum 4B/p; sharded terms
    divide by the ZeRO world at their stage)."""
    # stage 0: everything replicated
    assert _micro(0).phase_bytes() == {
        "init": {"hbm_bytes": 12000, "host_bytes": 0},
        "first_step": {"hbm_bytes": 16000, "host_bytes": 0},
        "steady": {"hbm_bytes": 16000, "host_bytes": 0},
        "ckpt": {"hbm_bytes": 16000, "host_bytes": 0},
    }
    # stage 1: optimizer state / 4
    assert _micro(1).phase_bytes()["init"]["hbm_bytes"] == 6000
    assert _micro(1).phase_bytes()["steady"]["hbm_bytes"] == 10000
    # stage 2: + grads / 4
    assert _micro(2).phase_bytes()["steady"]["hbm_bytes"] == 7000
    # stage 3: + params / 4; ckpt adds the bf16 gather buffer (2B/p, full)
    s3 = _micro(3).phase_bytes()
    assert s3["init"]["hbm_bytes"] == 3000
    assert s3["steady"]["hbm_bytes"] == 4000
    assert s3["ckpt"]["hbm_bytes"] == 4000 + 2000


def test_ledger_offload_tiers():
    """Offload tiers move bytes to the host column, not into thin air."""
    opt = _micro(1, offload_optimizer="cpu").components()
    assert opt["opt_state"] == {"hbm_bytes": 0, "host_bytes": 2000}
    assert opt["grads"]["host_bytes"] == 4000   # host optimizer accumulates
    assert opt["grads"]["hbm_bytes"] == 0
    # Twin-Flow partial offload splits by ratio
    half = _micro(1, offload_optimizer="cpu",
                  offload_optimizer_ratio=0.5).components()
    assert half["opt_state"] == {"hbm_bytes": 1000, "host_bytes": 1000}
    # param offload: fp32 masters host-side, HBM holds one streamed group
    par = _micro(0, offload_param="cpu", num_layers=2,
                 layers_per_group=1).components()
    assert par["masters"] == {"hbm_bytes": 0, "host_bytes": 4000}
    assert par["params"] == {"hbm_bytes": 1000, "host_bytes": 0}


def test_ledger_activation_and_logits_terms():
    led = MemoryLedger(num_params=1000, micro_batch=2, seq_len=8,
                       hidden_size=4, num_layers=3, vocab_size=16,
                       compute_dtype="bf16",
                       remat_policy="dots_with_no_batch_dims_saveable")
    c = led.components()
    # 7 saved hidden-sized tensors per layer * 3 layers * 2B * (2*8*4)
    assert c["activations"]["hbm_bytes"] == 7 * 2 * 8 * 4 * 2 * 3
    # fp32 logits + exp temp: 2 * 4B * mb * seq * vocab
    assert c["logits"]["hbm_bytes"] == 2 * 4 * 2 * 8 * 16
    # chunked CE never materializes them
    led.loss_chunked = True
    assert led.components()["logits"]["hbm_bytes"] == 0


def test_estimate_zero_reference_apis():
    """The reference estimate_zero*_model_states_mem_needs shapes."""
    gpu, cpu = estimate_zero2_model_states_mem_needs(
        1000, num_gpus_per_node=4, cpu_offload=True)
    assert (gpu, cpu) == (2000, int(1000 * 16 * 1.5))
    gpu, cpu = estimate_zero2_model_states_mem_needs(
        1000, num_gpus_per_node=4, cpu_offload=False)
    assert gpu == 4 * 1000 + 16 * 1000 // 4
    gpu, _ = estimate_zero3_model_states_mem_needs(
        1000, largest_layer_params=100, num_gpus_per_node=4,
        cpu_offload=False)
    assert gpu == 4 * 100 + 18 * 1000 // 4
    gpu, _ = estimate_zero3_model_states_mem_needs(
        1000, largest_layer_params=100, num_gpus_per_node=4,
        cpu_offload=True, cpu_offload_params=True)
    assert gpu == 4 * 100


def test_ledger_from_config_reads_raw_keys():
    raw = {"zero_optimization": {"stage": 2,
                                 "offload_optimizer": {"device": "cpu"}},
           "bf16": {"enabled": True},
           "data_types": {"grad_accum_dtype": "bf16"},
           "optimizer": {"type": "sgd"},
           "train_micro_batch_size_per_gpu": 4,
           "activation_checkpointing": {"policy": "nothing_saveable"}}
    led = MemoryLedger.from_config(raw, num_params=1000,
                                   mesh_shape={"data": 2, "fsdp": 4})
    assert (led.zero_stage, led.zero_world) == (2, 4)
    assert led.compute_dtype == "bf16"
    assert led.optimizer_moments == 1          # sgd: one moment
    assert led.offload_optimizer == "cpu"
    assert led.grad_accum_dtype == "bf16"
    assert led.micro_batch == 4
    # grads: 2B/p sharded over 4 (stage 2), host-side (host optimizer)
    assert led.components()["grads"]["host_bytes"] == 500


def test_oom_classification():
    assert is_oom_message("RESOURCE_EXHAUSTED: out of memory allocating")
    assert is_oom_message("XlaRuntimeError: Out of memory while trying")
    assert not is_oom_message("deadline exceeded")
    assert is_oom_error(RuntimeError("RESOURCE_EXHAUSTED: 16.0G"))


# ---------------------------------------------------------------------------
# counter events: emit -> ring -> Chrome JSON -> aggregates
# ---------------------------------------------------------------------------
def test_counter_roundtrip_chrome_and_aggregates():
    tr = Tracer(capacity=128).configure(enabled=True)
    tr.counter("mem/hbm_bytes_in_use", TPU_0=100, TPU_1=150)
    tr.counter("mem/hbm_bytes_in_use", TPU_0=300, TPU_1=50)
    tr.counter("mem/host_rss_bytes", rss=7)
    dump = json.loads(json.dumps(tr.to_chrome(), default=str))
    cs = [e for e in dump["traceEvents"] if e.get("ph") == "C"]
    assert len(cs) == 3
    first = cs[0]
    assert first["name"] == "mem/hbm_bytes_in_use"
    # args are the raw series (no injected id — it would plot as a series)
    assert first["args"] == {"TPU_0": 100, "TPU_1": 150}
    # counters never pollute the span summary
    assert tr.summary() == {}
    agg = tr.counter_series()
    assert agg["mem/hbm_bytes_in_use"]["TPU_0"] == {
        "last": 300.0, "max": 300.0, "p95": 300.0, "p99": 300.0, "count": 2}
    assert agg["mem/hbm_bytes_in_use"]["TPU_1"] == {
        "last": 50.0, "max": 150.0, "p95": 150.0, "p99": 150.0, "count": 2}
    lines = tr.prometheus_lines(prefix="mem/")
    assert any('counter="mem/hbm_bytes_in_use",series="TPU_0",stat="max"'
               in ln and ln.endswith(" 300") for ln in lines)
    # disabled tracer: counter is a no-op
    tr.configure(enabled=False)
    tr.counter("mem/hbm_bytes_in_use", TPU_0=999)
    assert tr.counter_series()["mem/hbm_bytes_in_use"]["TPU_0"]["last"] == 300.0


def test_sampler_phases_watermarks_and_report():
    class FakeDev:
        def __init__(self, name, in_use, peak, limit):
            self._n, self._s = name, {"bytes_in_use": in_use,
                                      "peak_bytes_in_use": peak,
                                      "bytes_limit": limit}

        def __str__(self):
            return self._n

        def memory_stats(self):
            return self._s

    tr = Tracer(capacity=128).configure(enabled=True)
    stats = {"in_use": 100, "peak": 120}
    devices = lambda: [FakeDev("TPU_0", stats["in_use"], stats["peak"], 1000)]
    s = MemorySampler(tracer=tr, window=16, devices_fn=devices)
    s.sample(step=0, phase="init")
    stats.update(in_use=400, peak=450)
    s.sample(step=1, phase="first_step")
    stats.update(in_use=380, peak=460)
    s.sample(step=2, phase="steady")
    s.sample(step=3)                     # stays in steady
    wm = s.watermarks()
    assert wm["init"]["hbm_peak_bytes"] == 120
    assert wm["first_step"]["hbm_peak_bytes"] == 450
    assert wm["steady"] == {"hbm_bytes_in_use": 380, "hbm_peak_bytes": 460,
                            "host_rss_bytes": wm["steady"]["host_rss_bytes"],
                            "samples": 2}
    assert wm["steady"]["host_rss_bytes"] > 0     # /proc always available
    assert s.seen("steady") and not s.seen("ckpt")
    assert s.bytes_limit() == 1000
    rep = s.report(ledger=_micro(1), source="unit.json")
    assert rep["bytes_limit"] == 1000
    assert rep["observed"]["phases"]["steady"]["hbm_peak_bytes"] == 460
    assert rep["plan"]["phases"]["steady"]["hbm_bytes"] == 10000
    assert rep["devices"]["TPU_0"]["bytes_in_use"] == 380
    # counter tracks landed in the ring for every sample
    agg = tr.counter_series()
    assert agg["mem/hbm_bytes_in_use"]["TPU_0"]["count"] == 4
    assert agg["mem/hbm_bytes_limit"]["TPU_0"]["last"] == 1000.0
    # tie-out rows: observed vs plan, per phase, delta computed
    rows = {r["phase"]: r for r in tie_out(rep)}
    assert rows["steady"]["plan_hbm_bytes"] == 10000
    assert rows["steady"]["observed_hbm_bytes"] == 460
    assert rows["steady"]["delta_frac"] == round(460 / 10000 - 1, 4)


# ---------------------------------------------------------------------------
# the ratchet CLI (checked-in fixtures + repo-root mem_baseline.json)
# ---------------------------------------------------------------------------
def _run_mem(*args, cwd=REPO):
    return subprocess.run([sys.executable, DSTPU, "mem", *args],
                          cwd=cwd, capture_output=True, text=True)


def test_cli_exit_matrix():
    """0 clean / 1 seeded watermark regression / 2 unreadable — against the
    CHECKED-IN fixtures and baseline (workload-scoped discovery walks up
    from the artifact to the repo root)."""
    clean = _run_mem(str(FIXTURES / "mem_micro.json"))
    assert clean.returncode == 0, clean.stderr
    assert "REGRESSION" not in clean.stderr
    assert "steady" in clean.stdout          # tie-out table rendered
    # the regressed fixture is the same workload with steady peak * 3;
    # explicit --baseline compares regardless of its filename
    reg = _run_mem(str(FIXTURES / "mem_micro_regressed.json"),
                   "--baseline", str(REPO / MEM_BASELINE_NAME))
    assert reg.returncode == 1, reg.stderr
    assert "REGRESSION: steady hbm_peak_bytes" in reg.stderr
    bad = _run_mem("/etc/hostname")
    assert bad.returncode == 2


def test_cli_discovered_other_workload_skips(tmp_path):
    """A DISCOVERED baseline of another workload must not fabricate a
    verdict (plan-ledger contract)."""
    rep = json.load(open(FIXTURES / "mem_micro.json"))
    rep["source"] = "other_workload.json"
    art = tmp_path / "other_workload.json"
    art.write_text(json.dumps(rep))
    (tmp_path / MEM_BASELINE_NAME).write_text(
        (REPO / MEM_BASELINE_NAME).read_text())
    out = _run_mem(str(art))
    assert out.returncode == 0
    assert "comparison skipped" in out.stderr


def test_cli_write_baseline_ratchet(tmp_path):
    """Improvements are STALE entries expired only via --write-baseline;
    the rewrite keeps the stored tolerance (the ratchet contract)."""
    rep = json.load(open(FIXTURES / "mem_micro.json"))
    art = tmp_path / "mem_micro.json"
    art.write_text(json.dumps(rep))
    first = _run_mem(str(art), "--write-baseline", "--tolerance", "1.5")
    assert first.returncode == 0
    bl = json.load(open(tmp_path / MEM_BASELINE_NAME))
    assert bl["tolerance"] == 1.5 and bl["workload"] == "mem_micro.json"
    # improve steady by 10x -> stale note, still exit 0
    improved = json.loads(json.dumps(rep))
    for m in ("hbm_peak_bytes", "hbm_bytes_in_use"):
        improved["observed"]["phases"]["steady"][m] //= 10
    art.write_text(json.dumps(improved))
    out = _run_mem(str(art))
    assert out.returncode == 0
    assert "stale baseline entry" in out.stderr
    # expire via --write-baseline: tolerance 1.5 preserved, entry ratcheted
    _run_mem(str(art), "--write-baseline")
    bl2 = json.load(open(tmp_path / MEM_BASELINE_NAME))
    assert bl2["tolerance"] == 1.5
    assert bl2["entries"]["steady"]["hbm_peak_bytes"] == \
        improved["observed"]["phases"]["steady"]["hbm_peak_bytes"]
    # and the old (regressed-relative-to-new) numbers now fail
    art.write_text(json.dumps(rep))
    assert _run_mem(str(art)).returncode == 1


def test_check_mem_baseline_floor():
    """Sub-floor deltas are noise, not regressions."""
    rep = {"observed": {"phases": {"steady": {
        "hbm_peak_bytes": 3000, "host_rss_bytes": 0}}}}
    base = {"version": 1, "tolerance": 1.25, "min_abs_bytes": 1 << 20,
            "entries": {"steady": {"hbm_peak_bytes": 1000,
                                   "host_rss_bytes": 0}}}
    regs, stale = check_mem_baseline(rep, base)
    assert regs == [] and stale == []        # 3x but only 2000 bytes
    base["min_abs_bytes"] = 100
    regs, _ = check_mem_baseline(rep, base)
    assert len(regs) == 1 and regs[0]["ratio"] == 3.0


# ---------------------------------------------------------------------------
# preflight: analytic plan vs device limit + the offload ladder
# ---------------------------------------------------------------------------
def test_preflight_and_offload_ladder(tmp_path):
    led = _micro(0)                          # steady = 16000 bytes
    assert preflight(led, 20000)["fits"]
    verdict = preflight(led, 10000)
    assert not verdict["fits"]
    assert verdict["worst_phase"] in ("first_step", "steady", "ckpt")
    assert verdict["suggestion"]["overrides"] == {
        "zero_optimization": {"stage": 1}}   # shard first: free
    # ladder order once sharding is exhausted
    assert next_offload_tier(_micro(3))["overrides"] == {
        "zero_optimization": {"offload_optimizer": {"device": "cpu"}}}
    assert next_offload_tier(
        _micro(3, offload_optimizer="cpu"))["overrides"] == {
        "zero_optimization": {"offload_param": {"device": "cpu"}}}
    assert "nvme" in next_offload_tier(
        _micro(3, offload_optimizer="cpu",
               offload_param="cpu"))["suggestion"]
    # the CLI mode: exit 1 + suggestion when the plan cannot fit
    cfg = tmp_path / "ds_config.json"
    cfg.write_text(json.dumps({"zero_optimization": {"stage": 0},
                               "mesh": {"fsdp": 4}}))
    out = subprocess.run(
        [sys.executable, DSTPU, "mem", "--preflight", str(cfg),
         "--params", "1000000000", "--bytes-limit", "8000000000"],
        cwd=REPO, capture_output=True, text=True)
    assert out.returncode == 1
    assert "DOES NOT FIT" in out.stderr and "suggestion" in out.stderr
    fits = subprocess.run(
        [sys.executable, DSTPU, "mem", "--preflight", str(cfg),
         "--params", "1000", "--bytes-limit", "8000000000"],
        cwd=REPO, capture_output=True, text=True)
    assert fits.returncode == 0


def test_engine_preflight_refuse(monkeypatch):
    """memory.preflight: refuse raises at init when the plan cannot fit —
    the limit is monkeypatched in (CPU devices report no allocator
    stats)."""
    from deepspeed_tpu.accelerator.cpu_accelerator import CPUAccelerator
    from deepspeed_tpu.telemetry.memory import MemoryPreflightError
    monkeypatch.setattr(
        CPUAccelerator, "memory_stats",
        lambda self: {"TPU_0": {"bytes_in_use": 0, "peak_bytes_in_use": 0,
                                "bytes_limit": 10_000}})
    with pytest.raises(MemoryPreflightError) as exc_info:
        _engine(extra={"memory": {"enabled": True, "preflight": "refuse"}})
    assert "next tier" in str(exc_info.value)
    # warn (default) constructs fine under the same impossible limit
    eng = _engine(extra={"memory": {"enabled": True}})
    assert eng._mem_sampler is not None


# ---------------------------------------------------------------------------
# live engine: phases, report round-trip, traced counter tracks
# ---------------------------------------------------------------------------
def test_engine_phases_and_report_roundtrip(tmp_path):
    configure_tracing(enabled=True)
    try:
        eng = _engine(extra={"memory": {"enabled": True}})
        for step in range(2):
            eng.train_batch(batch=random_batch(8, seed=step))
        eng.save_checkpoint(str(tmp_path / "ckpt"))
        wm = eng._mem_sampler.watermarks()
        # every lifecycle bucket observed, even in a 2-step sync run
        assert {"init", "first_step", "steady", "ckpt"} <= set(wm)
        assert eng._param_count() > 0
        led = eng.memory_ledger()
        assert led.num_params == eng._param_count()
        art = tmp_path / "mem_report.json"
        rep = eng.dump_memory_report(str(art))
        assert rep["observed"]["phases"].keys() == wm.keys()
        # artifact round-trips through the CLI (no baseline in tmp: rc 0)
        out = _run_mem(str(art), cwd=tmp_path)
        assert out.returncode == 0, out.stderr
        assert "init" in out.stdout
    finally:
        configure_tracing(enabled=False)


def test_async_first_step_bucket_survives_drain_lag(tmp_path):
    """Async mode samples only at drains (up to sync_every steps after
    step 0): the first_step bucket must still get its observation instead
    of being overwritten to steady before any sample lands."""
    configure_tracing(enabled=True)
    try:
        eng = _engine(extra={"memory": {"enabled": True},
                             "async_pipeline": {"enabled": True,
                                                "sync_every": 4}})
        for s in range(10):
            eng.train_batch(batch=random_batch(8, seed=s))
        eng.flush_metrics()
        wm = eng._mem_sampler.watermarks()
        assert {"init", "first_step", "steady"} <= set(wm)
        assert wm["first_step"]["samples"] >= 1
    finally:
        configure_tracing(enabled=False)


def test_trace_env_dumps_counter_tracks(tmp_path):
    """Acceptance: a micro run under DSTPU_TRACE dumps Chrome-trace counter
    ("ph":"C") memory tracks alongside the existing spans."""
    trace = tmp_path / "trace.json"
    code = (
        "import deepspeed_tpu\n"
        "from deepspeed_tpu.models.simple import SimpleModel, random_batch\n"
        "engine, _, _, _ = deepspeed_tpu.initialize(\n"
        "    model=SimpleModel(hidden_dim=16),\n"
        "    config={'train_micro_batch_size_per_gpu': 1},\n"
        "    example_batch=random_batch(4))\n"
        "for s in range(2):\n"
        "    engine.train_batch(batch=random_batch(\n"
        "        engine.train_batch_size, seed=s))\n")
    env = dict(os.environ, DSTPU_TRACE=str(trace), JAX_PLATFORMS="cpu",
               PYTHONPATH=str(REPO))
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr
    dump = json.load(open(trace))
    phs = {e.get("ph") for e in dump["traceEvents"]}
    assert "C" in phs and "X" in phs
    counters = {e["name"] for e in dump["traceEvents"]
                if e.get("ph") == "C"}
    assert "mem/host_rss_bytes" in counters   # CPU: no HBM stats, RSS rides
    spans = {e["name"] for e in dump["traceEvents"] if e.get("ph") == "X"}
    assert "engine/dispatch" in spans


# ---------------------------------------------------------------------------
# OOM forensics: chaos drill + engine classification
# ---------------------------------------------------------------------------
def test_chaos_oom_bundle_drill(tmp_path):
    """RESOURCE_EXHAUSTED -> diagnostic bundle with ledger + samples +
    per-phase deltas + trace tail, then the error re-raises (an OOM is a
    config problem, not a restartable fault)."""
    from deepspeed_tpu.resilience.chaos import (ChaosConfig,
                                                ChaosInjectedOOMError,
                                                ChaosMonkey)
    from deepspeed_tpu.resilience.runner import FaultTolerantRunner
    configure_tracing(enabled=True)
    try:
        eng = _engine(extra={
            "memory": {"enabled": True},
            "resilience": {"diagnostics_dir": str(tmp_path / "diag")}})
        runner = FaultTolerantRunner(
            eng, save_dir=str(tmp_path / "ckpt"),
            chaos=ChaosMonkey(ChaosConfig(oom_step=2)))
        with pytest.raises(ChaosInjectedOOMError):
            runner.run(num_steps=5,
                       batch_fn=lambda s: random_batch(8, seed=s))
        runner.close()
        assert runner.chaos.injected["oom"] == 1
        bundle = tmp_path / "diag" / "oom_step2"
        assert bundle.is_dir()
        diag = json.load(open(bundle / "diag.json"))
        assert diag["reason"] == "oom"
        assert "RESOURCE_EXHAUSTED" in diag["error"]
        mem = diag["memory"]
        assert mem["ledger"]["inputs"]["num_params"] == eng._param_count()
        assert len(mem["samples"]) >= 1
        assert "plan_vs_observed_delta_frac" in mem
        assert set(mem["watermarks"]) >= {"init", "first_step"}
        # the trace tail rides in the bundle, Perfetto-loadable
        tail = json.load(open(bundle / "trace_tail.json"))
        names = {e.get("name") for e in tail["traceEvents"]}
        assert "chaos/oom" in names
    finally:
        configure_tracing(enabled=False)


def test_engine_note_oom_stashes_forensics():
    configure_tracing(enabled=True)
    try:
        eng = _engine(extra={"memory": {"enabled": True}})
        eng.train_batch(batch=random_batch(8, seed=0))
        eng._note_oom(RuntimeError("deadline exceeded"))
        assert eng.last_oom is None              # non-OOM: untouched
        eng._note_oom(RuntimeError(
            "RESOURCE_EXHAUSTED: out of memory allocating 16G"))
        assert eng.last_oom is not None
        assert eng.last_oom["ledger"]["inputs"]["zero_stage"] == 0
        assert get_tracer().instant_counts().get("mem/oom", 0) >= 1
    finally:
        configure_tracing(enabled=False)


# ---------------------------------------------------------------------------
# satellites: see_memory_usage, autotuner capture, serving reconciliation
# ---------------------------------------------------------------------------
def test_see_memory_usage_noop_is_jax_free(monkeypatch):
    """force=False must return before ANY jax call (the old version
    imported jax first); force=True routes through the timeline."""
    import jax

    from deepspeed_tpu.utils.memory import see_memory_usage

    def boom():
        raise AssertionError("no-op path touched jax")
    monkeypatch.setattr(jax, "process_index", boom)
    assert see_memory_usage("milestone") is None       # no raise: jax-free
    monkeypatch.undo()
    configure_tracing(enabled=True)
    try:
        stats = see_memory_usage("after fwd", force=True, step=7)
        assert stats is not None and "host" in stats
        counts = get_tracer().instant_counts(prefix="mem/")
        assert counts.get("mem/see_memory_usage", 0) >= 1
    finally:
        configure_tracing(enabled=False)


def test_autotuner_oom_experiment_capture():
    """An oom-classified experiment records live stats + the candidate's
    analytic ledger + the observed peak — not just the string match."""
    from deepspeed_tpu.autotuning.scheduler import ExperimentRunner
    from deepspeed_tpu.autotuning.tuner import Experiment

    def exploding_loss(*a, **kw):
        raise RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating "
                           "12.5G on TPU_0")

    runner = ExperimentRunner(
        SimpleModel(hidden_dim=16), lambda b: random_batch(b),
        {"optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
         "train_micro_batch_size_per_gpu": 2},
        loss_fn=exploding_loss, warmup_steps=1, measure_steps=1)
    exp = runner(Experiment("oom_candidate",
                            {"zero_optimization": {"stage": 2}}))
    assert exp.status == "oom"
    assert exp.memory is not None
    assert "stats" in exp.memory
    assert exp.memory["ledger"]["inputs"]["zero_stage"] == 2


def test_serving_kv_reconciliation():
    """Projected (admission model) vs observed (engine-reserved) KV bytes:
    gauges on /metrics, an edge-triggered drift instant, counter track."""
    from deepspeed_tpu.serving.request import Request
    from deepspeed_tpu.serving.server import InferenceServer, ServingConfig

    class FakeKV:
        class cfg:
            num_blocks = 8
        data = type("A", (), {"nbytes": 8 * 1024})()
        scales = None

        @staticmethod
        def blocks_needed(total):
            return 2

    class FakeEngine:
        kv = FakeKV()

        def kv_usable_blocks(self):
            return 7

        def kv_reserved_blocks(self):
            return 1

        def kv_block_bytes(self):
            return 1024

        def kv_occupancy(self):
            return 1 / 7

    configure_tracing(enabled=True)
    try:
        server = InferenceServer(FakeEngine(), ServingConfig())
        req = Request(uid=1, prompt_tokens=[1, 2], max_new_tokens=4)
        server._inflight[1] = req
        # projected 2 blocks * 1024 vs observed 1 * 1024 -> 50% drift
        server._reconcile_kv(projected_blocks=2)
        snap = server.metrics.snapshot()
        assert snap["kv_projected_bytes"] == 2048
        assert snap["kv_observed_bytes"] == 1024
        assert snap["kv_drift_events"] == 1
        # edge-triggered: still drifted, no second event
        server._reconcile_kv(projected_blocks=2)
        assert server.metrics.snapshot()["kv_drift_events"] == 1
        # convergence clears the edge; a new divergence fires again
        server._reconcile_kv(projected_blocks=1)
        server._reconcile_kv(projected_blocks=2)
        assert server.metrics.snapshot()["kv_drift_events"] == 2
        assert get_tracer().instant_counts().get("serve/kv_drift") == 2
        assert get_tracer().counter_series()["serve/kv_bytes"][
            "projected"]["last"] == 2048.0
        text = server.metrics.prometheus_text()
        assert "dstpu_serving_kv_projected_bytes 2048" in text
        assert "dstpu_serving_kv_observed_bytes 1024" in text
        assert "dstpu_serving_kv_drift_events 2" in text
        # serve/ + mem/ counter families share ONE metadata block: a second
        # '# TYPE dstpu_trace_counter' line fails the whole Prometheus scrape
        get_tracer().counter("mem/host_rss_bytes", rss=7)
        text = server.metrics.prometheus_text()
        assert text.count("# TYPE dstpu_trace_counter") == 1
        assert "mem/host_rss_bytes" in text
    finally:
        configure_tracing(enabled=False)


def test_plan_reads_memory_counters():
    """dstpu plan consumes the dsmem counter tracks: headroom lands in the
    report and the proposal table escalates the offload tier when the
    observed peak is within 5% of the limit."""
    from deepspeed_tpu.telemetry.attribution import (attribute,
                                                     events_from_chrome)
    # short dispatch spans with long gaps: a residual-dominant sync window
    # (the raise_micro_batch trigger) under the window-split threshold
    events = [
        {"name": "engine/dispatch", "ph": "X", "ts": i * 1000.0,
         "dur": 100.0, "tid": 1, "cat": "train", "args": {"step": i}}
        for i in range(4)
    ] + [
        {"name": "mem/hbm_bytes_in_use", "ph": "C", "ts": 500.0, "tid": 1,
         "args": {"TPU_0": 9_700}},
        {"name": "mem/hbm_bytes_limit", "ph": "C", "ts": 500.0, "tid": 1,
         "args": {"TPU_0": 10_000}},
    ]
    report = attribute(events_from_chrome(events), source="synthetic")
    memory = report["memory"]
    assert memory["devices"]["TPU_0"]["peak_bytes_in_use"] == 9700
    assert memory["min_headroom_frac"] == 0.03
    ids = [p["id"] for p in report["proposals"]]
    assert "raise_offload_tier" in ids
    assert "raise_micro_batch" not in ids    # <10% headroom: yields
    # with ample headroom the offload rule stays quiet and micro-batch
    # advice carries the observed number
    events[-2]["args"]["TPU_0"] = 4_000
    report = attribute(events_from_chrome(events), source="synthetic")
    ids = {p["id"]: p for p in report["proposals"]}
    assert "raise_offload_tier" not in ids
    assert "raise_micro_batch" in ids
    assert ids["raise_micro_batch"]["predicted"]["hbm_headroom_frac"] == 0.6


def test_env_report_memory_rows():
    from deepspeed_tpu.env_report import memory_report
    rows = dict(memory_report())
    assert "mem ledger" in rows
    assert rows["mem baseline"].startswith("4 phases ratcheted")


# ---------------------------------------------------------------------------
# the dslint proof: the sampler never host-syncs
# ---------------------------------------------------------------------------
def test_sampler_stays_inside_the_hot_taint(package_callgraph, hot_reached):
    g = package_callgraph
    for fn in ("on_drain", "sample", "_collect"):
        key = g.resolve("deepspeed_tpu/telemetry/memory.py",
                        f"MemorySampler.{fn}")
        assert key is not None, f"MemorySampler.{fn} gone"
        assert key in hot_reached, f"{fn} fell out of the hot taint"


def test_fixtures_regenerate_clean(tmp_path, monkeypatch):
    """Fixtures + baseline are ONE artifact set: the regeneration script's
    output matches what is checked in (drift here means someone changed
    the ledger math without re-running make_fixtures.py)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "mem_make_fixtures", FIXTURES / "make_fixtures.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mem = mod._load_memory()
    fresh = mod.build_clean_report(mem)
    checked_in = json.load(open(FIXTURES / "mem_micro.json"))
    assert fresh == checked_in
