"""Checkpoint round-trip tests.

Reference analog: tests/unit/checkpoint/ (13 files — incl. universal ckpt and
world-size-change resume). The reshape-on-load case below is the universal-checkpoint
capability: save on one mesh, resume on another.
"""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import create_mesh
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.models.simple import SimpleModel, random_batch


def _make(config, mesh, seed=0):
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=64), config=config,
        mesh=mesh, example_batch=random_batch(4), seed=seed)
    return engine


CFG = {
    "train_batch_size": 8,
    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    "fp16": {"enabled": True, "initial_scale_power": 6},
}


@pytest.mark.slow
def test_save_load_roundtrip(tmp_path, mesh_dp8):
    e1 = _make(dict(CFG), mesh_dp8, seed=1)
    for i in range(3):
        e1.train_batch(batch=random_batch(8, seed=i))
    e1.save_checkpoint(str(tmp_path), client_state={"epoch": 7})

    e2 = _make(dict(CFG), mesh_dp8, seed=99)  # different init
    path, client_state = e2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert client_state["epoch"] == 7
    assert e2.global_steps == 3
    assert int(jax.device_get(e2.state.step)) == 3

    p1 = jax.device_get(e1.state.params)
    p2 = jax.device_get(e2.state.params)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(a, b)

    # training continues bit-identically after resume
    l1 = float(e1.train_batch(batch=random_batch(8, seed=50)))
    l2 = float(e2.train_batch(batch=random_batch(8, seed=50)))
    assert abs(l1 - l2) < 1e-6


def test_reshape_on_load(tmp_path):
    """Save under ZeRO-3 on (data=2, fsdp=4); resume on (data=8) ZeRO-0 — the
    universal-checkpoint reshape capability (reference ds_to_universal.py), with no
    offline conversion step."""
    mesh_a = create_mesh(MeshConfig(data=2, fsdp=4))
    cfg_a = dict(CFG); cfg_a["zero_optimization"] = {"stage": 3}
    e1 = _make(cfg_a, mesh_a, seed=1)
    e1.train_batch(batch=random_batch(8, seed=0))
    e1.save_checkpoint(str(tmp_path))

    mesh_b = create_mesh(MeshConfig(data=8))
    cfg_b = dict(CFG)  # stage 0
    e2 = _make(cfg_b, mesh_b, seed=2)
    e2.load_checkpoint(str(tmp_path))

    p1 = jax.device_get(e1.state.params)
    p2 = jax.device_get(e2.state.params)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(a, b)


def test_latest_tag_protocol(tmp_path, mesh_dp8):
    e = _make(dict(CFG), mesh_dp8)
    e.train_batch(batch=random_batch(8))
    e.save_checkpoint(str(tmp_path), tag="step_a")
    e.train_batch(batch=random_batch(8))
    e.save_checkpoint(str(tmp_path), tag="step_b")
    assert (tmp_path / "latest").read_text() == "step_b"
    e2 = _make(dict(CFG), mesh_dp8, seed=3)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path.endswith("step_b")


@pytest.mark.slow
def test_async_save_commits_latest_after_wait(tmp_path):
    """async_save: save returns immediately; the latest tag is committed by
    the background finalizer; a fresh engine loads the result (reference:
    nebula async checkpoint engine)."""
    import deepspeed_tpu
    from deepspeed_tpu.checkpoint.engine import wait_pending_checkpoint
    from deepspeed_tpu.models.simple import SimpleModel, random_batch

    config = {"train_batch_size": 8,
              "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
              "checkpoint": {"async_save": True}}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=config,
        example_batch=random_batch(4))
    engine.train_batch(batch=random_batch(8, seed=0))
    engine.save_checkpoint(str(tmp_path))
    wait_pending_checkpoint(engine)
    assert (tmp_path / "latest").exists()

    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=config,
        example_batch=random_batch(4))
    engine2.load_checkpoint(str(tmp_path))
    a = jax.tree.leaves(engine.state.params)[0]
    b = jax.tree.leaves(engine2.state.params)[0]
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7)
