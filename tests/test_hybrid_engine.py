"""Hybrid engine (RLHF train↔generate) tests.

Reference analog: tests/hybrid_engine/ — generate correctness after training
steps, weight sharing between modes, LoRA fusing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.llama import (
    TINY_LLAMA, LlamaConfig, LlamaForCausalLM, random_tokens)
from deepspeed_tpu.runtime.hybrid_engine import (
    DeepSpeedTPUHybridEngine, fuse_lora_params)


def _hybrid_engine(**extra):
    cfg = LlamaConfig(**{**TINY_LLAMA.__dict__, "num_heads": 4, "num_kv_heads": 4,
                         "dtype": jnp.float32})
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "hybrid_engine": {"enabled": True, "max_out_tokens": 64, **extra},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=LlamaForCausalLM(cfg), config=config,
        example_batch=random_tokens(8, 16, vocab_size=cfg.vocab_size))
    return engine, cfg


def test_initialize_returns_hybrid_engine():
    engine, _ = _hybrid_engine()
    assert isinstance(engine, DeepSpeedTPUHybridEngine)


@pytest.mark.slow
def test_generate_matches_model_argmax():
    engine, cfg = _hybrid_engine()
    prompt = [3, 17, 29, 5]
    out = engine.generate(prompt, max_new_tokens=3)
    assert len(out) == 3
    # first generated token == argmax of the training model's own logits
    ids = jnp.asarray([prompt])
    logits = engine.model.apply({"params": engine.get_params()}, ids,
                                method=lambda m, x: m.model(x))
    expect = int(jnp.argmax(logits[0, -1]))
    assert out[0] == expect


@pytest.mark.slow
def test_generate_reflects_training_updates():
    engine, cfg = _hybrid_engine()
    prompt = [1, 2, 3, 4]
    before = engine.generate(prompt, max_new_tokens=4)
    v0 = engine._weights_version
    for i in range(3):
        engine.train_batch(batch=random_tokens(8, 16, vocab_size=cfg.vocab_size,
                                               seed=i))
    after = engine.generate(prompt, max_new_tokens=4)
    assert engine._weights_version == engine.global_steps != v0
    # training moved the weights; the inference view follows them (tokens may
    # or may not change on a tiny model — the version bump is the contract)
    assert engine.generate_latency > 0 and engine.training_latency > 0
    # flip (train->generate view refresh) is instrumented per phase: two
    # refreshes happened (initial + post-training), both timed
    rep = engine.latency_report()
    assert engine.flip_count == 2 and rep["flips"] == 2.0
    assert rep["flip_s"] > 0 and rep["flip_mean_s"] > 0
    assert rep["flip_s"] <= engine.generate_latency  # flips happen inside generate


@pytest.mark.slow
def test_batch_generate():
    engine, _ = _hybrid_engine()
    outs = engine.generate([[1, 2, 3], [4, 5, 6, 7]], max_new_tokens=2)
    assert len(outs) == 2 and all(len(o) == 2 for o in outs)


@pytest.mark.slow
def test_release_inference_cache():
    engine, cfg = _hybrid_engine(release_inference_cache=True)
    engine.generate([1, 2, 3], max_new_tokens=2)
    assert engine._infer_engine is not None
    engine.train_batch(batch=random_tokens(8, 16, vocab_size=cfg.vocab_size))
    assert engine._infer_engine is None  # KV HBM released for the train phase


def test_fuse_lora_params():
    a = jnp.full((4, 2), 0.5)
    b = jnp.full((2, 6), 0.25)
    kernel = jnp.ones((4, 6))
    tree = {"proj": {"kernel": kernel, "lora_a": a, "lora_b": b},
            "other": {"kernel": jnp.zeros((3, 3))}}
    fused = fuse_lora_params(tree, scaling=2.0)
    np.testing.assert_allclose(np.asarray(fused["proj"]["kernel"]),
                               np.asarray(kernel + (a @ b) * 2.0))
    assert "lora_a" not in fused["proj"]
    np.testing.assert_array_equal(np.asarray(fused["other"]["kernel"]),
                                  np.zeros((3, 3)))
