"""CLI tooling tests: nvme tune sweep, ssh fanout, comet monitor backend.

Reference analogs: ``bin/ds_nvme_tune`` (``deepspeed/nvme/perf_sweep``),
``bin/ds_ssh``, ``deepspeed/monitor/comet.py`` — pure-unit (no ssh, no
comet_ml service), mirroring ``tests/unit/launcher`` style.
"""

import json
import os
import subprocess

import numpy as np

from deepspeed_tpu.launcher.nvme_tune import main as nvme_main, sweep
from deepspeed_tpu.launcher.ssh_fanout import fanout, parse_args, run_on_host
from deepspeed_tpu.monitor.monitor import CometMonitor, MonitorMaster


def test_nvme_sweep_measures_and_picks_config(tmp_path, capsys):
    rc = nvme_main(["--nvme_dir", str(tmp_path), "--size_mb", "8",
                    "--threads", "1", "2", "--block_mb", "1", "4",
                    "--trials", "1", "--out", str(tmp_path / "aio.json")])
    assert rc == 0
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    rows = [l for l in lines if "threads" in l]
    assert len(rows) == 4 and all(r["read_gbps"] > 0 for r in rows)
    cfg = json.load(open(tmp_path / "aio.json"))
    assert cfg["aio"]["thread_count"] in (1, 2)
    assert cfg["aio"]["block_size"] % (1 << 20) == 0


def test_ssh_fanout_prefixes_and_aggregates_rc():
    class FakeProc:
        def __init__(self, rc, out):
            self.returncode, self.stdout, self.stderr = rc, out, ""

    def fake_runner(cmd, capture_output, text):
        host = cmd[-2]
        return FakeProc(1 if host == "bad" else 0, f"hello from {host}\n")

    res = fanout(["a", "bad", "c"], ["uptime"], runner=fake_runner)
    assert res["a"][0] == 0 and res["bad"][0] == 1
    host, rc, out, _ = run_on_host("a", ["echo", "x"], runner=fake_runner)
    assert host == "a" and rc == 0 and "hello" in out


def test_ssh_parse_args_remainder():
    a = parse_args(["-H", "/tmp/hosts", "nvidia-smi", "-L"])
    assert a.hostfile == "/tmp/hosts" and a.command == ["nvidia-smi", "-L"]


def test_comet_monitor_gated_and_master_includes_it():
    from deepspeed_tpu.config.config import DeepSpeedTPUConfig
    cfg = DeepSpeedTPUConfig({"train_batch_size": 8,
                              "comet": {"enabled": False}}, dp_world_size=1)
    mon = CometMonitor(cfg.comet)
    assert not mon.enabled  # disabled config -> no comet_ml import attempted
    master = MonitorMaster(cfg)
    assert any(isinstance(b, CometMonitor) for b in master.backends)
    # enabled but comet_ml not installed -> graceful degrade, not crash
    cfg2 = DeepSpeedTPUConfig({"train_batch_size": 8,
                               "comet": {"enabled": True}}, dp_world_size=1)
    assert not CometMonitor(cfg2.comet).enabled
