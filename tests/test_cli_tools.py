"""CLI tooling tests: nvme tune sweep, ssh fanout, comet monitor backend.

Reference analogs: ``bin/ds_nvme_tune`` (``deepspeed/nvme/perf_sweep``),
``bin/ds_ssh``, ``deepspeed/monitor/comet.py`` — pure-unit (no ssh, no
comet_ml service), mirroring ``tests/unit/launcher`` style.
"""

import json
import os
import subprocess

import numpy as np

from deepspeed_tpu.launcher.nvme_tune import main as nvme_main, sweep
from deepspeed_tpu.launcher.ssh_fanout import fanout, parse_args, run_on_host
from deepspeed_tpu.monitor.monitor import CometMonitor, MonitorMaster


def test_nvme_sweep_measures_and_picks_config(tmp_path, capsys):
    rc = nvme_main(["--nvme_dir", str(tmp_path), "--size_mb", "8",
                    "--threads", "1", "2", "--block_mb", "1", "4",
                    "--trials", "1", "--out", str(tmp_path / "aio.json")])
    assert rc == 0
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    rows = [l for l in lines if "threads" in l]
    assert len(rows) == 4 and all(r["read_gbps"] > 0 for r in rows)
    cfg = json.load(open(tmp_path / "aio.json"))
    assert cfg["aio"]["thread_count"] in (1, 2)
    assert cfg["aio"]["block_size"] % (1 << 20) == 0


def test_ssh_fanout_prefixes_and_aggregates_rc():
    class FakeProc:
        def __init__(self, rc, out):
            self.returncode, self.stdout, self.stderr = rc, out, ""

    def fake_runner(cmd, capture_output, text):
        host = cmd[-2]
        return FakeProc(1 if host == "bad" else 0, f"hello from {host}\n")

    res = fanout(["a", "bad", "c"], ["uptime"], runner=fake_runner)
    assert res["a"][0] == 0 and res["bad"][0] == 1
    host, rc, out, _ = run_on_host("a", ["echo", "x"], runner=fake_runner)
    assert host == "a" and rc == 0 and "hello" in out


def test_ssh_parse_args_remainder():
    a = parse_args(["-H", "/tmp/hosts", "nvidia-smi", "-L"])
    assert a.hostfile == "/tmp/hosts" and a.command == ["nvidia-smi", "-L"]


def test_comet_monitor_gated_and_master_includes_it():
    from deepspeed_tpu.config.config import DeepSpeedTPUConfig
    cfg = DeepSpeedTPUConfig({"train_batch_size": 8,
                              "comet": {"enabled": False}}, dp_world_size=1)
    mon = CometMonitor(cfg.comet)
    assert not mon.enabled  # disabled config -> no comet_ml import attempted
    master = MonitorMaster(cfg)
    assert any(isinstance(b, CometMonitor) for b in master.backends)
    # enabled but comet_ml not installed -> graceful degrade, not crash
    cfg2 = DeepSpeedTPUConfig({"train_batch_size": 8,
                               "comet": {"enabled": True}}, dp_world_size=1)
    assert not CometMonitor(cfg2.comet).enabled


def test_bench_watchdog_emits_stale_banked_headline(tmp_path):
    """Wedged-tunnel fallback: the driver bench must always print one
    parseable JSON line (BENCH_r02..r04 were empty rc=3 records)."""
    import json
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    logs = tmp_path / "bench_logs"
    logs.mkdir()
    (logs / "latest_headline.json").write_text(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip", "value": 30820.5,
        "unit": "tokens/sec/chip", "vs_baseline": 1.212,
        "measured_at": "2026-07-31T03:52:00+00:00"}) + "\n")
    env = dict(os.environ, DSTPU_BENCH_LOGS=str(logs))
    env.pop("DSTPU_STALE_REPLAY_RC0", None)
    # driver path: stale_metric set -> banked headline replayed with the
    # DISTINCT replay exit code (exit status alone must never conflate a
    # stale replay with a fresh rc-0 run)
    from bench_util import STALE_REPLAY_EXIT_CODE
    replay_src = (
        "import time\n"
        "from bench_util import guard_device_discovery\n"
        "guard_device_discovery('bench', timeout=0.2,"
        " stale_metric='llama_train_tokens_per_sec_per_chip')\n"
        "time.sleep(10)\n")
    out = subprocess.run([sys.executable, "-c", replay_src],
                         capture_output=True, text=True, cwd=repo, env=env)
    assert out.returncode == STALE_REPLAY_EXIT_CODE, out.stderr
    rec = json.loads(out.stdout.strip())
    assert rec["stale"] is True
    assert rec["metric"] == "llama_train_tokens_per_sec_per_chip"
    assert rec["source"] and rec["measured_at"] == "2026-07-31T03:52:00+00:00"
    # rc-0 replay is an explicit env opt-in for drivers that reject nonzero
    out_rc0 = subprocess.run(
        [sys.executable, "-c", replay_src], capture_output=True, text=True,
        cwd=repo, env=dict(env, DSTPU_STALE_REPLAY_RC0="1"))
    assert out_rc0.returncode == 0, out_rc0.stderr
    assert json.loads(out_rc0.stdout.strip())["stale"] is True
    # wrong metric is rejected, never substituted -> rc 3
    out2 = subprocess.run([sys.executable, "-c", (
        "import time\n"
        "from bench_util import guard_device_discovery\n"
        "guard_device_discovery('bench_decode', timeout=0.2,"
        " stale_metric='decode_tokens_per_sec')\n"
        "time.sleep(10)\n")], capture_output=True, text=True, cwd=repo, env=env)
    assert out2.returncode == 3 and not out2.stdout.strip()
    # non-driver path: no stale_metric -> rc 3, nothing on stdout
    out3 = subprocess.run([sys.executable, "-c", (
        "import time\n"
        "from bench_util import guard_device_discovery\n"
        "guard_device_discovery('bench_decode', timeout=0.2)\n"
        "time.sleep(10)\n")], capture_output=True, text=True, cwd=repo, env=env)
    assert out3.returncode == 3 and not out3.stdout.strip()


def test_env_report_checkpoint_status(tmp_path, capsys):
    """dstpu_report --ckpt: latest pointer + per-tag committed/verified/torn
    status for a run dir (the resume-or-not triage view)."""
    import json as _json
    import os as _os

    from deepspeed_tpu.checkpoint.engine import write_manifest, _commit_latest
    from deepspeed_tpu.env_report import checkpoint_report

    run = tmp_path / "run"
    # committed + verified tag
    good = run / "global_step2"
    good.mkdir(parents=True)
    (good / "ds_meta.json").write_text(_json.dumps({"global_steps": 2}))
    write_manifest(str(good))
    _commit_latest(str(run), "global_step2")
    # newer tag, committed but then corrupted (torn)
    torn = run / "global_step4"
    torn.mkdir()
    (torn / "ds_meta.json").write_text(_json.dumps({"global_steps": 4}))
    (torn / "data.bin").write_bytes(b"abcdef")
    write_manifest(str(torn))
    (torn / "data.bin").write_bytes(b"ABCDEF")
    _commit_latest(str(run), "global_step4")
    # uncommitted junk tag
    (run / "global_step9").mkdir()

    summary, tags = checkpoint_report(str(run))
    summary = dict(summary)
    assert summary["latest pointer"] == "global_step4"
    # resume skips the torn tag and falls back to the clean one
    assert summary["resume_from_latest would load"] == "global_step2"
    status = {t.split(" ")[0]: s for t, s in tags}
    assert "TORN" in status["global_step4"]
    assert "committed + verified" in status["global_step2"]
    assert "uncommitted" in status["global_step9"]


def test_env_report_dslint_rows():
    """dstpu_report carries the static-analysis surface: rule count,
    baseline debt, and the DS002 taint summary (roots resolved + closure
    size) so a glance at the report shows whether the lint layer is
    actually covering the hot path."""
    from deepspeed_tpu.env_report import dslint_report

    rows = dict(dslint_report())
    assert int(rows["dslint rules"]) >= 9
    assert rows["dslint baseline"].startswith("0 grandfathered")
    assert "functions" in rows["dslint callgraph"]
    # every declared root must resolve against the shipped tree
    taint = rows["dslint hot taint"]
    resolved, declared = taint.split(" ")[0].split("/")
    assert resolved == declared
    assert "under DS002" in taint
