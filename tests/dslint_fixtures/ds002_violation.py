"""DS002 fixture (linted with a spec naming FakeEngine's hot path):
float() in the hot function, a transfer in the async-guarded branch, and
device_get outside its confined functions — must fire for each."""

import jax


class FakeEngine:
    def train_batch(self, batch):
        loss = self._fn(batch)
        return float(loss)                       # sync in hot path -> DS002

    def record(self, out):
        if self._async_enabled:
            self.ring.append(jax.device_get(out))  # sync in async branch

    def helper(self, x):
        return jax.device_get(x)                 # outside confine allowlist

    def drain(self):
        return jax.device_get(self.ring)         # the designated drain: ok
