"""DS002 fixture (linted with a HotRoot naming FakeEngine.train_batch):
float() in the root itself, a transfer in the guarded hatch's async
branch, and a .item() two call hops from the root — must fire for each.
The designated drain (a sync_ok hatch) stays quiet."""

import jax


class FakeEngine:
    def train_batch(self, batch):
        loss = self._fn(batch)
        self.record(loss)
        self.note(loss)
        return float(loss)                       # sync in hot root -> DS002

    def record(self, out):                       # guarded hatch
        if self._async_enabled:
            self.ring.append(jax.device_get(out))  # sync in async branch
        else:
            self.last = float(out)               # sync fallback: allowed

    def note(self, x):
        self.history.append(x.item())            # two hops from the root

    def drain(self):
        return jax.device_get(self.ring)         # sync_ok hatch: quiet
