"""DS003 fixture: array reductions used bare as Python bools — must fire
in condition, `not`, and bool-shaped-return positions."""

import numpy as np


def admit(mask):
    if np.all(mask > 0):              # 0-d array as condition -> DS003
        return 1
    while not mask.any():             # .any() under `not` -> DS003
        mask = mask[1:]
    return 0


def is_healthy(x):
    return np.isfinite(x).all()       # bool-shaped return -> DS003
