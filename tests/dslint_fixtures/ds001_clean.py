"""DS001 clean twin: rebind-in-the-same-statement and snapshot-before —
the two blessed donation patterns. Must NOT fire."""

import jax


def ring_capture(state, batch, ring):
    step = jax.jit(lambda s, b: (s, 0.0), donate_argnums=(0,))
    scale = state.loss_scale          # snapshot BEFORE the donating call
    state, out = step(state, batch)   # rebound by the same statement
    ring.append(scale)
    return state, out


class Engine:
    def __init__(self, state):
        self.state = state
        self._fn = jax.jit(lambda s: s, donate_argnums=(0,))

    def capture_after_dispatch(self):
        params = self.state.params    # snapshot first
        self.state = self._fn(self.state)
        return params, self.state
