"""DS004 fixture: attributes crossing the thread boundary with unlocked
writes on either side — must fire for `_stop` (main writes, thread reads)
and `_latest` (thread writes, main reads)."""

import threading


class Worker:
    def __init__(self):
        self._stop = False
        self._latest = None
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        while not self._stop:          # thread-side read
            self._latest = object()    # unlocked thread-side write -> DS004

    def stop(self):
        self._stop = True              # unlocked main-side write -> DS004

    def latest(self):
        return self._latest            # main-side read
