"""DS005 clean twin: handlers only set flags / deliver signals — the
blessed shape (work happens later at a safe point)."""

import os
import signal
import threading

_STOP = threading.Event()


def _handler(signum, frame):
    _STOP.set()


class Server:
    def install(self):
        signal.signal(signal.SIGTERM, self._on_term)
        signal.signal(signal.SIGINT, lambda *_: _STOP.set())

    def _on_term(self, signum, frame):
        self._preempt_signal = signum
        os.kill(os.getpid(), 0)        # os-level probe: async-signal-safe


signal.signal(signal.SIGTERM, _handler)
