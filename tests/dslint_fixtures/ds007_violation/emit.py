"""DS007 fixture: an unregistered literal, a registered name emitted as
the wrong kind, a typo'd module-level constant, and an f-string whose
head is not a registered dynamic prefix — must fire for each."""

_DRAIN = "engine/dran"                           # typo: unregistered


class Engine:
    def step(self, tracer):
        with tracer.span("engine/step"):         # unregistered -> DS007
            pass
        tracer.complete("engine/train_step", 0.1)  # wrong kind -> DS007
        tracer.span(_DRAIN)                      # typo'd constant -> DS007

    def gauge(self, tracer, kind):
        tracer.counter(f"mem/{kind}_bytes", v=1)  # bad dynamic head -> DS007
