"""Fixture-local trace-name registry (found before the real one because
it ends in ``telemetry/names.py`` inside the linted subtree)."""

TRACE_NAMES = {
    "engine/train_step": ("span",),
    "engine/drain": ("span",),
}
DYNAMIC_PREFIXES = ("comm/",)
