"""Transitive hop: a helper that drags in the device runtime."""

import jax


def shape_of(x):
    return jax.numpy.shape(x)
