"""DS009 fixture, direction 2: a hot-root file imports the offline-only
module at module level, paying its import cost on the hot path."""

from ds009_violation import offline_tool


class Hot:
    def step(self, batch):
        return offline_tool.analyze(batch)
