"""DS009 fixture: declared OFFLINE_ONLY, but a module-level import chain
(offline_tool -> helper -> jax) reaches the device runtime."""

from ds009_violation import helper


def analyze(trace):
    return helper.shape_of(trace)
