"""Clean twin hot root: no module-level edge to the offline module."""


class Hot:
    def step(self, batch):
        return [t + 1 for t in batch]
