"""Clean twin helper: still imports the runtime (it is a device module)."""

import jax


def shape_of(x):
    return jax.numpy.shape(x)
