"""DS009 clean twin: the offline module defers the device-adjacent
helper to a lazy in-function import — the offline-purity idiom."""


def analyze(trace):
    from ds009_clean import helper               # lazy: not in the graph
    return helper.shape_of(trace)
