"""DS008 fixture: an unscoped TYPE f-string, the same concrete family
claimed at two sites, a concrete family shadowed by a loop-generated
prefix on a different line, and the same prefix claimed from two
functions — must fire for each."""


class Metrics:
    def render(self):
        lines = ["# TYPE dstpu_fleet_requests counter"]
        for key in self._gauges:
            # prefix claim dstpu_fleet_* shadows the concrete family above
            lines.append(f"# TYPE dstpu_fleet_{key} gauge")
        return lines

    def render_dup(self, name):
        return [
            f"# TYPE {name} counter",            # unscoped claim -> DS008
            "# TYPE dstpu_fleet_requests counter",   # duplicate family
        ]

    def render_other(self):
        out = []
        for key in self._counters:
            # same dstpu_fleet_* prefix from a second function -> overlap
            out.append(f"# TYPE dstpu_fleet_{key} counter")
        return out
