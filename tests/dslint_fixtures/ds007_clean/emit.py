"""DS007 clean twin: every literal registered with a matching kind, the
f-string head is a registered dynamic prefix, and a name the rule cannot
resolve statically (a parameter) is skipped, never guessed."""

_DRAIN = "engine/drain"


class Engine:
    def step(self, tracer):
        with tracer.span("engine/train_step"):
            pass
        tracer.complete("engine/train_step", 0.1)
        tracer.span(_DRAIN)

    def op(self, tracer, op_name):
        tracer.span(f"comm/{op_name}")           # registered dynamic head
        tracer.span(op_name)                     # unresolvable: skipped
