"""Fixture-local trace-name registry for the clean twin."""

TRACE_NAMES = {
    "engine/train_step": ("span", "complete"),
    "engine/drain": ("span",),
}
DYNAMIC_PREFIXES = ("comm/",)
