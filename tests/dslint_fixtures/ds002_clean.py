"""DS002 clean twin: same hot-path shape, readback only in the drain."""

import jax


class FakeEngine:
    def train_batch(self, batch):
        loss = self._fn(batch)
        self.ring.append(loss)                   # device array, no transfer
        return loss

    def record(self, out):
        if self._async_enabled:
            self.ring.append(out)                # queued verbatim

    def helper(self, x):
        return x

    def drain(self):
        return jax.device_get(self.ring)         # THE designated drain
