"""DS002 clean twin: same root/callee shape, no sync anywhere the taint
reaches — queued device arrays, the guarded hatch syncs only on its
fallback side, readback only in the sync_ok drain."""

import jax


class FakeEngine:
    def train_batch(self, batch):
        loss = self._fn(batch)
        self.record(loss)
        self.note(loss)
        return loss

    def record(self, out):                       # guarded hatch
        if self._async_enabled:
            self.ring.append(out)                # queued verbatim
        else:
            self.last = float(out)               # sync fallback: allowed

    def note(self, x):
        self.history.append(x)                   # device array, no transfer

    def drain(self):
        return jax.device_get(self.ring)         # THE designated drain
