"""DS005 fixture: signal handlers doing I/O / logging / lock work — must
fire for the named-function, method, and lambda registration shapes."""

import json
import signal
import threading

_LOCK = threading.Lock()


def _handler(signum, frame):
    with open("/tmp/preempt.json", "w") as f:   # open() in handler -> DS005
        json.dump({"sig": signum}, f)           # json.dump -> DS005


class Server:
    def install(self):
        signal.signal(signal.SIGTERM, self._on_term)
        signal.signal(signal.SIGINT, lambda *_: _LOCK.acquire())  # -> DS005

    def _on_term(self, signum, frame):
        self.log.warning("terminating")          # logging lock -> DS005


signal.signal(signal.SIGTERM, _handler)
