"""DS001 fixture: reads a pytree AFTER donating it — must fire twice."""

import jax


def ring_capture(state, batch, ring):
    step = jax.jit(lambda s, b: (s, 0.0), donate_argnums=(0,))
    new_state, out = step(state, batch)
    ring.append(state.loss_scale)     # read of donated `state` -> DS001
    return new_state, out


class Engine:
    def __init__(self, state):
        self.state = state
        self._fn = jax.jit(lambda s: s, donate_argnums=(0,))

    def capture_after_dispatch(self):
        out = self._fn(self.state)
        return self.state.params, out  # read of donated self.state -> DS001
