"""DS006 clean twin: all config reads go through constants."""

from .config.constants import ALPHA, BETA


class Config:
    def __init__(self, ds_config):
        self._raw = dict(ds_config)
        self.alpha = self._raw.get(ALPHA, 0)
        self.beta = self._raw.get(BETA, 0)
