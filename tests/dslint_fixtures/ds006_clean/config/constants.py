"""DS006 clean-twin constants: every constant referenced, every key
constant-mediated."""

ALPHA = "alpha"
BETA = "beta"
