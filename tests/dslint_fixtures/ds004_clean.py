"""DS004 clean twin: an Event for the flag, a Lock around the shared
value — must NOT fire."""

import threading


class Worker:
    def __init__(self):
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._latest = None
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        while not self._stop.is_set():
            with self._lock:
                self._latest = object()

    def stop(self):
        self._stop.set()

    def latest(self):
        with self._lock:
            return self._latest
