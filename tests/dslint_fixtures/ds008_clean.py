"""DS008 clean twin: one emission site per family, every f-string claim
scoped by an inlined namespace, and each prefix owned by exactly one
function (its keys keep the families disjoint)."""


class Metrics:
    def render(self):
        lines = ["# TYPE dstpu_fleet_requests counter"]
        for key in self._gauges:
            lines.append(f"# TYPE dstpu_fleet_gauge_{key} gauge")
        return lines

    def render_other(self):
        out = []
        for key in self._counters:
            out.append(f"# TYPE dstpu_serving_{key} counter")
        return out
