"""DS003 clean twin: converted to Python bool at the boundary."""

import numpy as np


def admit(mask):
    if bool(np.all(mask > 0)):
        return 1
    while not bool(mask.any()):
        mask = mask[1:]
    return 0


def is_healthy(x):
    return bool(np.isfinite(x).all())
