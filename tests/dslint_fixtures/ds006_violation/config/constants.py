"""DS006 fixture constants module: `ORPHANED` is referenced nowhere
(dead config surface -> DS006); `ALPHA` is healthy."""

ALPHA = "alpha"
ORPHANED = "orphaned_key"
