"""DS006 fixture reader: one constant-mediated read (fine) and one raw
string key with no constant — must fire for `"beta"`."""

from .config.constants import ALPHA


class Config:
    def __init__(self, ds_config):
        self._raw = dict(ds_config)
        self.alpha = self._raw.get(ALPHA, 0)
        self.beta = self._raw.get("beta", 0)     # raw key -> DS006
