"""MoE tests: gating semantics, capacity, EP sharding, Mixtral training.

Reference analog: tests/unit/moe/ (gating + layer tests vs config-driven models).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import create_mesh, set_global_mesh
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.moe.sharded_moe import (MOELayer, MoEConfig, _capacity,
                                            top_k_gating)
from deepspeed_tpu.models.mixtral import (
    TINY_MIXTRAL,
    MixtralForCausalLM,
    mixtral_tensor_rules,
)
from deepspeed_tpu.models.llama import random_tokens


def test_capacity_formula():
    assert _capacity(128, 8, 1.0, 4) == 16
    assert _capacity(128, 8, 1.25, 4) == 20
    assert _capacity(8, 8, 1.0, 4) == 4  # min_capacity floor


def test_gating_shapes_and_weights():
    cfg = MoEConfig(num_experts=4, top_k=2, aux_loss_weight=0.01)
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(32, 4)), jnp.float32)
    dispatch, combine, aux, z = top_k_gating(logits, cfg, capacity=32)
    assert dispatch.shape == (32, 4, 32)
    assert combine.shape == (32, 4, 32)
    # each token dispatched to exactly top_k slots (no drops at high capacity)
    assert int(jnp.sum(dispatch)) == 32 * 2
    # combine weights per token sum to 1 (normalized top-k)
    np.testing.assert_allclose(np.asarray(jnp.sum(combine, axis=(1, 2))),
                               np.ones(32), rtol=1e-5)
    assert float(aux) > 0 and float(z) > 0


def test_gating_capacity_drops():
    """With capacity 1, at most E slots filled per k."""
    cfg = MoEConfig(num_experts=2, top_k=1)
    logits = jnp.stack([jnp.zeros(16), jnp.full(16, -10.0)], axis=-1)  # all -> expert 0
    dispatch, combine, _, _ = top_k_gating(logits, cfg, capacity=4)
    # expert 0 receives exactly its capacity (4), remaining 12 tokens dropped
    assert int(jnp.sum(dispatch[:, 0])) == 4
    assert int(jnp.sum(dispatch[:, 1])) == 0


def test_aux_loss_balanced_vs_unbalanced():
    """Load-balance loss is minimal for uniform routing (reference l_aux)."""
    cfg = MoEConfig(num_experts=4, top_k=1, aux_loss_weight=1.0,
                    router_z_loss_weight=0.0)
    uniform = jnp.zeros((64, 4))
    skewed = jnp.stack([jnp.full(64, 10.0)] + [jnp.zeros(64)] * 3, axis=-1)
    _, _, aux_u, _ = top_k_gating(uniform, cfg, capacity=64)
    _, _, aux_s, _ = top_k_gating(skewed, cfg, capacity=64)
    assert float(aux_s) > float(aux_u)
    np.testing.assert_allclose(float(aux_u), 1.0, rtol=1e-2)  # E * (1/E * 1/E) * E = 1


@pytest.mark.slow
def test_mixtral_forward_and_logits():
    model = MixtralForCausalLM(TINY_MIXTRAL)
    batch = random_tokens(2, 16, vocab_size=512)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    loss = model.apply({"params": params}, batch)
    assert np.isfinite(float(loss))
    logits = model.apply({"params": params}, batch, method=MixtralForCausalLM.logits)
    assert logits.shape == (2, 16, 512)


def test_expert_params_sharded_over_expert_axis():
    mesh = create_mesh(MeshConfig(data=2, expert=4))
    set_global_mesh(mesh)
    model = MixtralForCausalLM(TINY_MIXTRAL)
    batch = random_tokens(2, 16, vocab_size=512)
    params = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), batch))["params"]
    from deepspeed_tpu.runtime.zero.partition import build_param_shardings
    shardings = build_param_shardings(params, mesh, stage=0,
                                      tensor_rules=mixtral_tensor_rules)
    flat = jax.tree_util.tree_flatten_with_path(shardings)[0]
    expert_specs = [str(s.spec) for p, s in flat
                    if "experts" in jax.tree_util.keystr(p)]
    assert expert_specs and all("expert" in s for s in expert_specs), expert_specs


@pytest.mark.slow
def test_train_mixtral_ep(tmp_path=None):
    """End-to-end: Mixtral trains with expert parallelism + ZeRO-1."""
    mesh = create_mesh(MeshConfig(data=2, expert=4))
    set_global_mesh(mesh)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=MixtralForCausalLM(TINY_MIXTRAL),
        config={"train_batch_size": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
                "zero_optimization": {"stage": 1},
                "bf16": {"enabled": True}},
        mesh=mesh, example_batch=random_tokens(2, 16, vocab_size=512),
        tensor_rules=mixtral_tensor_rules)
    batch = random_tokens(4, 16, vocab_size=512, seed=0)
    losses = [float(engine.train_batch(batch=batch)) for _ in range(8)]
    assert losses[-1] < losses[0]


def test_quantized_dispatch_parity_and_wire():
    """MoEConfig.quantized_dispatch routes dispatch/combine through int8-wire
    quantized_psum regions (reference _AllToAll, sharded_moe.py:533 +
    ZeRO++/EQuARX wire quantization): forward/grad parity with the dense
    einsum path within int8 error, and the lowered forward carries i8
    all_to_all collectives."""
    import dataclasses
    mesh = create_mesh(MeshConfig(data=2, expert=4))
    set_global_mesh(mesh)
    cfg_q = MoEConfig(num_experts=4, top_k=2, dtype=jnp.float32,
                      quantized_dispatch=True)
    cfg_d = dataclasses.replace(cfg_q, quantized_dispatch=False)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 16, 64)), jnp.float32)

    def run(cfg):
        layer = MOELayer(cfg, hidden_size=64, intermediate_size=128)
        params = layer.init(jax.random.PRNGKey(0), x, train=False)

        def loss_fn(p):
            out, aux = layer.apply(p, x, train=False)
            return jnp.sum(out ** 2) + aux
        out, _ = layer.apply(params, x, train=False)
        return out, jax.grad(loss_fn)(params)

    with mesh:
        out_q, g_q = jax.jit(lambda: run(cfg_q))()
        out_d, g_d = jax.jit(lambda: run(cfg_d))()
    rel = float(jnp.abs(out_q - out_d).max() / (jnp.abs(out_d).max() + 1e-9))
    assert 0 < rel < 0.05, rel          # int8 error, and path actually taken
    for a, b in zip(jax.tree.leaves(g_q), jax.tree.leaves(g_d)):
        r = float(jnp.abs(a - b).max() / (jnp.abs(b).max() + 1e-9))
        assert 0 < r < 0.15, (a.shape, r)   # straight-through grads flow

    def fwd_only():
        layer = MOELayer(cfg_q, hidden_size=64, intermediate_size=128)
        params = layer.init(jax.random.PRNGKey(0), x, train=False)
        return layer.apply(params, x, train=False)[0]

    with mesh:
        txt = jax.jit(fwd_only).lower().as_text()
    i8 = [ln for ln in txt.splitlines() if "all_to_all" in ln and "i8" in ln]
    assert i8, "quantized dispatch does not move int8 on the wire"


@pytest.mark.slow
def test_train_mixtral_ep_quantized_dispatch():
    """Mixtral EP training with int8-wire dispatch/combine converges."""
    import dataclasses
    mesh = create_mesh(MeshConfig(data=2, expert=4))
    set_global_mesh(mesh)
    cfg = dataclasses.replace(
        TINY_MIXTRAL, moe=dataclasses.replace(TINY_MIXTRAL.moe,
                                              quantized_dispatch=True))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=MixtralForCausalLM(cfg),
        config={"train_batch_size": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
                "zero_optimization": {"stage": 1},
                "bf16": {"enabled": True}},
        mesh=mesh, example_batch=random_tokens(2, 16, vocab_size=512),
        tensor_rules=mixtral_tensor_rules)
    batch = random_tokens(4, 16, vocab_size=512, seed=0)
    losses = [float(engine.train_batch(batch=batch)) for _ in range(8)]
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_quantized_dispatch_inside_qgz_region():
    """quantized_dispatch composes with the qgZ int8-wire gradient phase:
    inside the partial-manual region (data/fsdp manual) the dispatch falls
    back to the local dense einsum (_quantized_wire_axes filters manual
    axes) while the combine still opens the nested expert-axis region."""
    import dataclasses
    mesh = create_mesh(MeshConfig(data=2, expert=2, fsdp=2))
    set_global_mesh(mesh)
    cfg = dataclasses.replace(
        TINY_MIXTRAL, moe=dataclasses.replace(TINY_MIXTRAL.moe,
                                              quantized_dispatch=True))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=MixtralForCausalLM(cfg),
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
                "zero_optimization": {"stage": 1,
                                      "zero_quantized_gradients": True},
                "bf16": {"enabled": True}},
        mesh=mesh, example_batch=random_tokens(4, 16, vocab_size=512),
        tensor_rules=mixtral_tensor_rules)
    # stage 1: params replicated over data+fsdp -> both are replica axes
    assert engine._qgz_axes == ("data", "fsdp")
    batch = random_tokens(8, 16, vocab_size=512, seed=0)
    losses = [float(engine.train_batch(batch=batch)) for _ in range(6)]
    assert losses[-1] < losses[0] and np.isfinite(losses).all(), losses


@pytest.mark.slow
def test_hf_mixtral_torch_parity():
    """Convert a random torch-transformers Mixtral checkpoint and match its
    logits (high eval capacity so no token drops; HF renormalizes kept
    routing weights = our norm_topk_prob default)."""
    import dataclasses

    import torch
    from transformers import MixtralConfig as HFConfig
    from transformers import MixtralForCausalLM as HFModel

    from deepspeed_tpu.models.mixtral import (convert_hf_mixtral,
                                              mixtral_config_from_hf)

    hf_cfg = HFConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        router_jitter_noise=0.0, output_router_logits=False)
    torch.manual_seed(0)
    hf_model = HFModel(hf_cfg).eval()

    cfg = mixtral_config_from_hf(hf_cfg.to_dict())
    cfg = dataclasses.replace(
        cfg,
        base=dataclasses.replace(cfg.base, dtype=jnp.float32),
        moe=dataclasses.replace(cfg.moe, dtype=jnp.float32,
                                eval_capacity_factor=float(
                                    cfg.moe.num_experts)))
    params = convert_hf_mixtral(hf_model.state_dict(), cfg)

    ids = np.random.default_rng(0).integers(0, 256, size=(2, 16))
    with torch.no_grad():
        ref = hf_model(torch.tensor(ids)).logits.numpy()
    ours = MixtralForCausalLM(cfg).apply(
        {"params": jax.tree.map(jnp.asarray, params)},
        {"input_ids": jnp.asarray(ids.astype(np.int32))},
        method=MixtralForCausalLM.logits)
    np.testing.assert_allclose(np.asarray(ours), ref, atol=2e-4, rtol=2e-3)


def test_mixtral_config_from_hf_fields():
    from deepspeed_tpu.models.mixtral import mixtral_config_from_hf
    hf = {"model_type": "mixtral", "vocab_size": 32000, "hidden_size": 4096,
          "intermediate_size": 14336, "num_hidden_layers": 32,
          "num_attention_heads": 32, "num_key_value_heads": 8,
          "num_local_experts": 8, "num_experts_per_tok": 2,
          "rope_theta": 1e6, "router_aux_loss_coef": 0.02,
          "sliding_window": 4096}
    cfg = mixtral_config_from_hf(hf)
    assert cfg.moe.num_experts == 8 and cfg.moe.top_k == 2
    assert cfg.moe.norm_topk_prob is True        # HF Mixtral renormalizes
    assert cfg.moe.aux_loss_weight == 0.02
    assert cfg.base.num_kv_heads == 8 and cfg.base.rope_theta == 1e6
    assert cfg.base.sliding_window == 4096
    with pytest.raises(ValueError):
        mixtral_config_from_hf({**hf, "model_type": "mistral"})
    with pytest.raises(ValueError):
        mixtral_config_from_hf({k: v for k, v in hf.items()
                                if k != "num_local_experts"})
