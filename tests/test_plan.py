"""``dstpu plan`` — step-time attribution / planning tests.

Contracts pinned here:

  golden       : the checked-in micro fixtures attribute to a ledger whose
                 stages (incl. residual) sum EXACTLY to each step window
                 and whose over-attribution (tie_out_error) stays within
                 the 5% clock-skew tolerance; proposals are deterministic
  synthetic    : a hand-built trace with known durations exercises every
                 stage (incl. ckpt + comm rollups) and the priority sweep's
                 nesting rules, to exact microseconds
  ratchet      : plan_baseline.json regression/stale detection follows the
                 dslint idiom — the checked-in baseline is clean against
                 the checked-in fixture, a seeded drain growth exits 1,
                 improvements surface as stale entries
  CLI          : exit-code matrix 0 ok / 1 regression / 2 unreadable, via
                 both attribution.main and the bin/dstpu subcommand
  quantiles    : Tracer.summary / prometheus_lines p50/p95/p99 to exact
                 values (attribution consumes the same quantile rule)
  slicing      : dstpu_trace --step-range / --track produce plan-loadable
                 slices that keep the sliced steps' drain/h2d/comm spans
  offline-only : no registered hot-path file can import the attribution
                 module, and the module itself never touches jax
  loop         : Autotuner(plan=...) executes ONLY the plan's proposals
                 and verifies the readback-transfer prediction by exact
                 span counting (the telemetry->plan->config acceptance)
  live         : a real `bench.py micro` run under DSTPU_TRACE attributes
                 end to end
"""

import ast
import json
import math
import os
import subprocess
import sys

import pytest

from deepspeed_tpu.telemetry import attribution
from deepspeed_tpu.telemetry import report as trace_report
from deepspeed_tpu.telemetry.tracer import Tracer

pytestmark = pytest.mark.plan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "plan_fixtures")
SYNC_TRACE = os.path.join(FIXTURES, "micro_sync_trace.json")
ASYNC_TRACE = os.path.join(FIXTURES, "micro_async_trace.json")
BASELINE = os.path.join(REPO, attribution.PLAN_BASELINE_NAME)


def _stage_sum_us(window):
    return sum(window["stages_us"].values())


# ---------------------------------------------------------------------------
# golden attribution on the checked-in fixtures
# ---------------------------------------------------------------------------
def test_golden_sync_fixture_ledger_ties_out():
    rep = attribution.analyze_path(SYNC_TRACE)
    assert rep["mode"] == "sync"
    assert len(rep["windows"]) == 1
    w = rep["windows"][0]
    assert w["steps"] == 8
    # exclusive stages + residual sum EXACTLY to the window (residual is
    # the remainder by construction; rounding is 3 decimals of a us)
    assert _stage_sum_us(w) == pytest.approx(w["dur_us"], abs=0.01)
    # over-attribution stays within the acceptance tolerance
    assert w["tie_out_error"] <= attribution.TIE_OUT_TOLERANCE
    # per-step readback makes dispatch the dominant attributed stage
    agg = rep["aggregate"]
    assert agg["dispatch"]["share"] > agg["h2d"]["share"] > 0
    assert agg["drain"]["share"] == 0.0          # sync mode: no drain spans
    shares = sum(agg[s]["share"] for s in attribution.STAGES)
    assert shares == pytest.approx(1.0, abs=0.01)


def test_golden_sync_fixture_proposals_deterministic():
    rep1 = attribution.analyze_path(SYNC_TRACE)
    rep2 = attribution.analyze_path(SYNC_TRACE)
    assert rep1 == rep2                          # replay is a pure function
    props = rep1["proposals"]
    assert props[0]["id"] == "enable_async_pipeline"
    pred = props[0]["predicted"]
    assert pred["metric"] == "readback_transfers"
    assert pred["current"] == 8                  # per-step readback today
    assert pred["proposed"] == math.ceil(8 / pred["sync_every"])
    assert props[0]["overrides"]["async_pipeline"]["enabled"] is True
    # rule table orders by share, ties by id — stable across runs
    assert [p["id"] for p in props] == \
        sorted([p["id"] for p in props],
               key=lambda i: next(-p["share"] for p in props
                                  if p["id"] == i))


def test_golden_async_fixture_windows_and_config():
    rep = attribution.analyze_path(ASYNC_TRACE)
    assert rep["mode"] == "async"
    assert len(rep["windows"]) == 3              # 12 steps at sync_every=4
    for w in rep["windows"]:
        assert w["steps"] == 4
        assert _stage_sum_us(w) == pytest.approx(w["dur_us"], abs=0.01)
        assert w["tie_out_error"] <= attribution.TIE_OUT_TOLERANCE
        assert w["stages_us"]["drain"] > 0       # each window drains once
    cfg = rep["config_observed"]
    assert cfg["sync_every"] == 4                # read from the trace itself
    assert cfg["prefetch"] is False
    assert rep["steps_total"] == 12


def test_async_fixture_clean_against_checked_in_baseline():
    """fixtures + plan_baseline.json are ONE artifact set: the checked-in
    baseline must be exactly clean (no regressions, no stale entries)
    against the checked-in async fixture it was generated from."""
    rep = attribution.analyze_path(ASYNC_TRACE)
    baseline = attribution.load_plan_baseline(BASELINE)
    regressions, stale = attribution.check_baseline(rep, baseline)
    assert regressions == []
    assert stale == []
    assert set(baseline["entries"]) == set(attribution.STAGES)


# ---------------------------------------------------------------------------
# synthetic full-ledger golden (exact microseconds, every stage incl. ckpt)
# ---------------------------------------------------------------------------
def _ev(name, ts, dur, tid=1, cat="train", ph="X", **args):
    return {"name": name, "cat": cat, "ph": ph, "ts": ts, "dur": dur,
            "tid": tid, "args": args}


SYNTHETIC = {"traceEvents": [
    {"name": "thread_name", "ph": "M", "tid": 1,
     "args": {"name": "MainThread"}},
    {"name": "thread_name", "ph": "M", "tid": 2,
     "args": {"name": "prefetch"}},
    _ev("engine/steps_reconciled", 0, 10_000, steps=2, last_step=2),
    _ev("engine/dispatch", 0, 2_000, step=1),
    _ev("comm/h2d", 500, 500, cat="comm", bytes=4096),   # nested: h2d wins
    _ev("comm/all_reduce", 3_000, 400, cat="comm", bytes=1 << 20, world=8,
        algbw_gbps=2.0, busbw_gbps=3.5),
    _ev("comm/all_reduce", 3_500, 0, ph="i", cat="comm", bytes=1 << 20,
        world=8),                                        # in-jit analytic
    _ev("engine/dispatch", 5_000, 2_000, step=2),
    _ev("engine/drain", 7_000, 500, steps=2),
    _ev("ckpt/save", 7_600, 1_000, tag="t"),
    _ev("engine/drain", 8_000, 200),                     # nested: drain wins
    _ev("prefetch/next", 9_000, 100),                    # main-track stall
    _ev("prefetch/stage", 1_000, 1_000, tid=2),          # overlapped only
]}


def test_synthetic_exclusive_sweep_exact():
    rep = attribution.attribute(
        attribution.events_from_chrome(SYNTHETIC), source="synthetic")
    assert rep["mode"] == "async"
    (w,) = rep["windows"]
    st = w["stages_us"]
    assert st["h2d"] == 500                       # carved out of dispatch
    assert st["dispatch"] == 3_500                # 4000 - nested h2d
    assert st["comm"] == 400
    assert st["drain"] == 700                     # 500 + 200 inside ckpt
    assert st["ckpt"] == 800                      # 1000 - nested drain
    assert st["prefetch"] == 100                  # main-track stall only
    assert st["residual"] == 4_000
    assert _stage_sum_us(w) == w["dur_us"] == 10_000
    assert w["tie_out_error"] == 0.0
    # the worker's staging is informational overlap, never step cost
    assert w["overlapped_us"] == {"prefetch": 1_000.0}


def test_synthetic_comm_rollup_and_ckpt_proposal():
    rep = attribution.attribute(
        attribution.events_from_chrome(SYNTHETIC), source="synthetic")
    roll = rep["comm"]
    assert list(roll) == ["all_reduce@8"]
    r = roll["all_reduce@8"]
    assert r["count"] == 2                        # timed span + in-jit instant
    assert r["bytes"] == 2 << 20
    assert r["algbw_gbps_mean"] == pytest.approx(2.0)
    assert r["busbw_gbps_mean"] == pytest.approx(3.5)
    # ckpt is 8% — below its floor; grow it and the rule fires
    grown = json.loads(json.dumps(SYNTHETIC))
    for e in grown["traceEvents"]:
        if e["name"] == "ckpt/save":
            e["dur"] = 2_500
    rep2 = attribution.attribute(attribution.events_from_chrome(grown))
    assert any(p["id"] == "relax_ckpt_cadence" for p in rep2["proposals"])


def test_sync_pause_splits_windows():
    """A big inter-dispatch gap (eval phase, pause between loops) starts a
    NEW sync window — the idle time must never inflate any window's
    residual or the per-step quantiles the baseline ratchets."""
    ev = [_ev("engine/dispatch", t, 600, step=i + 1)
          for i, t in enumerate((0, 1_000, 2_000))]
    ev += [_ev("engine/dispatch", 500_000 + t, 600, step=i + 4)
           for i, t in enumerate((0, 1_000, 2_000))]
    rep = attribution.attribute(attribution.events_from_chrome(ev))
    assert len(rep["windows"]) == 2
    for w in rep["windows"]:
        assert w["steps"] == 3
        assert w["dur_us"] == 2_600                # pause excluded
        assert w["stages_us"]["residual"] == 800   # only the loop gaps
    assert rep["windows"][1]["last_step"] == 6


def test_sync_window_synthesis_without_reconciled_spans():
    """Sync traces have no reconciled spans: contiguous dispatch runs
    synthesize ONE window first-start -> last-end (inter-step host work
    still attributes)."""
    ev = [_ev("engine/dispatch", i * 1_000, 600, step=i + 1)
          for i in range(4)]
    rep = attribution.attribute(attribution.events_from_chrome(ev))
    (w,) = rep["windows"]
    assert rep["mode"] == "sync"
    assert w["steps"] == 4
    assert w["dur_us"] == 3_600
    assert w["stages_us"]["dispatch"] == 2_400
    assert w["stages_us"]["residual"] == 1_200    # the inter-dispatch gaps


def test_unreadable_traces_raise_plan_error():
    with pytest.raises(attribution.PlanError):
        attribution.events_from_chrome({"no": "traceEvents"})
    with pytest.raises(attribution.PlanError):
        attribution.events_from_chrome("not a trace")
    with pytest.raises(attribution.PlanError):
        attribution.attribute(attribution.events_from_chrome(
            {"traceEvents": [_ev("serve/engine_step", 0, 10)]}))


# ---------------------------------------------------------------------------
# regression ledger (ratchet idiom)
# ---------------------------------------------------------------------------
def _seed_drain_regression(factor=5):
    """Grow every drain span INTO its window (earlier start, same end, so
    clipping can't bound the growth away) — the deterministic 'drain time
    grew Nx' tripwire the baseline must flag."""
    with open(ASYNC_TRACE) as f:
        obj = json.load(f)
    for e in obj["traceEvents"]:
        if e.get("name") == "engine/drain":
            e["ts"] -= e["dur"] * (factor - 1)
            e["dur"] *= factor
    return obj


def test_seeded_drain_regression_detected(tmp_path):
    bad = tmp_path / "regressed.json"
    bad.write_text(json.dumps(_seed_drain_regression()))
    rep = attribution.analyze_path(str(bad))
    regressions, _ = attribution.check_baseline(
        rep, attribution.load_plan_baseline(BASELINE))
    assert any(r["stage"] == "drain" for r in regressions)
    ratio = next(r["ratio"] for r in regressions if r["stage"] == "drain")
    assert ratio > 2.0


def test_improvement_surfaces_as_stale_entry(tmp_path):
    """The other ratchet direction: a baseline recorded from a WORSE run
    goes stale once the stage improves — it must be expired explicitly
    (--write-baseline), never silently shield a future regression."""
    rep_bad = attribution.analyze_path(str(_write(tmp_path, "bad.json",
                                                  _seed_drain_regression())))
    bl_path = tmp_path / "baseline.json"
    attribution.write_plan_baseline(str(bl_path), rep_bad)
    rep_good = attribution.analyze_path(ASYNC_TRACE)
    regressions, stale = attribution.check_baseline(
        rep_good, attribution.load_plan_baseline(str(bl_path)))
    assert regressions == []
    assert any(r["stage"] == "drain" for r in stale)


def _write(tmp_path, name, obj):
    p = tmp_path / name
    p.write_text(json.dumps(obj))
    return p


# ---------------------------------------------------------------------------
# CLI exit-code matrix
# ---------------------------------------------------------------------------
def test_cli_exit_0_clean(capsys):
    rc = attribution.main([ASYNC_TRACE, "--baseline", BASELINE])
    assert rc == attribution.EXIT_OK
    out = capsys.readouterr().out
    assert "proposals" in out and "tie-out" in out


def test_cli_exit_1_regression(tmp_path, capsys):
    bad = _write(tmp_path, "regressed.json", _seed_drain_regression())
    rc = attribution.main([str(bad), "--baseline", BASELINE])
    assert rc == attribution.EXIT_REGRESSION
    err = capsys.readouterr().err
    assert "REGRESSION" in err and "drain" in err


def test_cli_exit_2_unreadable(tmp_path, capsys):
    garbage = tmp_path / "garbage.json"
    garbage.write_text("not json {")
    assert attribution.main([str(garbage)]) == attribution.EXIT_UNREADABLE
    nostep = _write(tmp_path, "nostep.json",
                    {"traceEvents": [_ev("serve/engine_step", 0, 10)]})
    assert attribution.main([str(nostep)]) == attribution.EXIT_UNREADABLE
    assert attribution.main([str(tmp_path / "absent.json")]) \
        == attribution.EXIT_UNREADABLE
    capsys.readouterr()


def test_cli_tolerance_overrides_baseline_factor(tmp_path, capsys):
    """--tolerance applies to the CHECK, not just baseline writing: the
    same seeded regression passes once the factor is raised past it."""
    bad = _write(tmp_path, "regressed.json", _seed_drain_regression())
    assert attribution.main([str(bad), "--baseline", BASELINE]) == 1
    assert attribution.main([str(bad), "--baseline", BASELINE,
                             "--tolerance", "50"]) == 0
    capsys.readouterr()


def test_cli_no_baseline_discovery_outside_trace_tree(tmp_path, capsys,
                                                      monkeypatch):
    """Discovery anchors at the TRACE path only: a trace outside the repo
    is a different workload — comparing it against the checked-in fixture
    baseline would flag meaningless regressions (cwd must not leak in)."""
    import shutil
    monkeypatch.chdir(REPO)                       # repo baseline in cwd
    loose = tmp_path / "loose_trace.json"
    shutil.copy(ASYNC_TRACE, loose)
    rc = attribution.main([str(loose), "--json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["baseline"]["path"] is None


def test_discovered_baseline_guarded_by_workload(tmp_path, capsys):
    """A DISCOVERED baseline only judges traces of its own workload: a
    real run's trace saved next to the fixture baseline must not be
    compared against micro-fixture quantiles (explicit --baseline always
    compares)."""
    import shutil
    shutil.copy(BASELINE, tmp_path / attribution.PLAN_BASELINE_NAME)
    other = tmp_path / "trace.json"           # same events, other workload
    other.write_text(json.dumps(_seed_drain_regression()))
    rc = attribution.main([str(other), "--json"])
    assert rc == 0                            # discovered: skipped, no lie
    assert json.loads(capsys.readouterr().out)["baseline"]["path"] is None
    same = tmp_path / "micro_async_trace.json"
    same.write_text(other.read_text())        # matching workload: compared
    assert attribution.main([str(same)]) == attribution.EXIT_REGRESSION
    capsys.readouterr()


def test_write_baseline_never_clobbers_other_workload(tmp_path, capsys):
    """--write-baseline on a DISCOVERED baseline of another workload
    starts a new baseline next to the trace (or refuses when that IS the
    conflicting location) — the checked-in fixture artifact set can't be
    silently overwritten by ratcheting an unrelated run."""
    import shutil
    nested = tmp_path / "runs"
    nested.mkdir()
    shutil.copy(BASELINE, tmp_path / attribution.PLAN_BASELINE_NAME)
    trace = nested / "mytrain.json"
    shutil.copy(ASYNC_TRACE, trace)
    assert attribution.main([str(trace), "--write-baseline"]) == 0
    err = capsys.readouterr().err
    assert "instead" in err                       # redirected, with a note
    redirected = nested / attribution.PLAN_BASELINE_NAME
    assert attribution.load_plan_baseline(
        str(redirected))["workload"] == "mytrain.json"
    # fixture baseline untouched
    assert attribution.load_plan_baseline(
        str(tmp_path / attribution.PLAN_BASELINE_NAME))["workload"] \
        == "micro_async_trace.json"
    # same-dir conflict: nowhere safe to redirect -> refuse, write nothing
    trace2 = tmp_path / "other.json"
    shutil.copy(ASYNC_TRACE, trace2)
    before = (tmp_path / attribution.PLAN_BASELINE_NAME).read_text()
    assert attribution.main([str(trace2), "--write-baseline"]) == 0
    assert "refusing" in capsys.readouterr().err
    assert (tmp_path / attribution.PLAN_BASELINE_NAME).read_text() == before


def test_prefetch_depth_proposal_is_self_sufficient():
    """Every async_pipeline override must carry enabled/prefetch: propose()
    never trusts the config file, so an Autotuner executing the proposal
    against a sync base config must still run the pipelined engine."""
    rep = attribution.analyze_path(ASYNC_TRACE)
    agg = {s: dict(rep["aggregate"][s]) for s in attribution.STAGES}
    agg["prefetch"]["share"] = 0.5                # dominant prefetch stall
    doctored = dict(rep, aggregate=agg)
    props = {p["id"]: p for p in attribution.propose(doctored)}
    ov = props["raise_prefetch_depth"]["overrides"]["async_pipeline"]
    assert ov["enabled"] is True and ov["prefetch"] is True


def test_write_baseline_preserves_stored_tolerance(tmp_path, capsys):
    bl = tmp_path / "bl.json"
    assert attribution.main([ASYNC_TRACE, "--baseline", str(bl),
                             "--write-baseline", "--tolerance", "3"]) == 0
    assert attribution.load_plan_baseline(str(bl))["tolerance"] == 3.0
    # ratchet rewrite without --tolerance keeps the factor the team chose
    assert attribution.main([ASYNC_TRACE, "--baseline", str(bl),
                             "--write-baseline"]) == 0
    assert attribution.load_plan_baseline(str(bl))["tolerance"] == 3.0
    capsys.readouterr()


def test_cli_artifact_json_and_write_baseline(tmp_path, capsys):
    out = tmp_path / "plan.json"
    bl = tmp_path / "bl.json"
    rc = attribution.main([ASYNC_TRACE, "--baseline", str(bl),
                           "--write-baseline", "--out", str(out), "--json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert json.loads(out.read_text()) == report
    assert report["baseline"]["path"] == str(bl)
    # the freshly written baseline is clean against its own report
    assert attribution.main([ASYNC_TRACE, "--baseline", str(bl)]) == 0
    capsys.readouterr()


def test_bin_dstpu_plan_subcommand():
    """The launcher CLI routes `plan` to the analyzer (and stays a
    checkout-runnable script)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "dstpu"), "plan",
         ASYNC_TRACE, "--baseline", BASELINE],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stderr
    assert "dstpu plan" in proc.stdout


# ---------------------------------------------------------------------------
# tracer quantiles (satellite: summary + prometheus_lines p50/p95/p99)
# ---------------------------------------------------------------------------
def test_summary_quantiles_exact_values():
    t = Tracer(capacity=256)
    t.configure(enabled=True)
    for ms in range(1, 21):                      # 1..20 ms, known spread
        t.complete("q/span", ms / 1000.0, end_ts=100.0 + ms)
    s = t.summary()["q/span"]
    # repo-wide rule: sorted[min(int(q*n), n-1)] over n=20 samples
    assert s["count"] == 20
    assert s["p50_s"] == pytest.approx(0.011)    # index 10
    assert s["p95_s"] == pytest.approx(0.020)    # index 19
    assert s["p99_s"] == pytest.approx(0.020)    # index 19
    assert s["max_s"] == pytest.approx(0.020)
    assert s["total_s"] == pytest.approx(sum(range(1, 21)) / 1000.0)


def test_prometheus_lines_carry_p95():
    t = Tracer(capacity=64)
    t.configure(enabled=True)
    for ms in (1, 2, 3, 4):
        t.complete("engine/drain", ms / 1000.0, end_ts=10.0 + ms)
    lines = t.prometheus_lines()
    for q, val in (("0.5", 0.003), ("0.95", 0.004), ("0.99", 0.004)):
        row = next(l for l in lines
                   if f'quantile="{q}"' in l and "engine/drain" in l)
        assert float(row.split()[-1]) == pytest.approx(val)


def test_attribution_quantile_rule_matches_tracer():
    from deepspeed_tpu.telemetry.tracer import _quantile
    vals = [float(v) for v in range(1, 21)]
    for q in (0.5, 0.95, 0.99):
        assert attribution.quantile(vals, q) == _quantile(vals, q)
    assert attribution.quantile([], 0.5) == 0.0


# ---------------------------------------------------------------------------
# dstpu_trace slicing (satellite: --step-range / --track)
# ---------------------------------------------------------------------------
def test_step_range_slice_keeps_window_spans(tmp_path, capsys):
    events = trace_report.load_events(ASYNC_TRACE)
    sliced = trace_report.filter_step_range(events, "6:9")
    steps = {int(e["args"]["step"]) for e in sliced
             if e.get("ph") == "X" and e.get("name") == "engine/dispatch"}
    assert steps >= {6, 7, 8, 9}                 # the requested steps...
    assert steps <= {5, 6, 7, 8, 9}              # ...plus at most the
    # window-anchor step the reconciled extension legitimately pulls in
    names = {e.get("name") for e in sliced}
    # the sliced steps' drain/h2d spans ride along even though they carry
    # no per-step arg — that is the point of wall-time slicing
    assert {"engine/drain", "comm/h2d", "engine/steps_reconciled"} <= names
    assert any(e.get("ph") == "M" for e in sliced)   # labels preserved
    # a slice is itself a plan-loadable trace
    out = tmp_path / "slice.json"
    rc = trace_report.main([ASYNC_TRACE, "--step-range", "6:9",
                            "--out", str(out), "--json"])
    assert rc == 0
    capsys.readouterr()
    rep = attribution.analyze_path(str(out))
    assert rep["steps_total"] == 8               # the two touched windows
    assert all(w["tie_out_error"] <= attribution.TIE_OUT_TOLERANCE
               for w in rep["windows"])


def test_track_filter_and_bad_specs(capsys):
    events = trace_report.load_events(ASYNC_TRACE)
    main_only = trace_report.filter_track(events, "MainThread")
    tids = {e.get("tid") for e in main_only if e.get("ph") != "M"}
    assert len(tids) == 1
    with pytest.raises(ValueError, match="MainThread"):
        trace_report.filter_track(events, "no-such-track")
    assert trace_report.main([ASYNC_TRACE, "--track", "nope"]) == 2
    assert trace_report.main([ASYNC_TRACE, "--step-range", "bogus"]) == 2
    assert trace_report.main([ASYNC_TRACE, "--step-range", "900:901"]) == 2
    assert trace_report.main([ASYNC_TRACE, "--track", "MainThread"]) == 0
    capsys.readouterr()


# ---------------------------------------------------------------------------
# env_report row (satellite)
# ---------------------------------------------------------------------------
def test_env_report_plan_rows(tmp_path, monkeypatch, capsys):
    from deepspeed_tpu.env_report import plan_report
    monkeypatch.chdir(tmp_path)
    monkeypatch.delenv(attribution.PLAN_ARTIFACT_ENV, raising=False)
    rows = dict(plan_report())
    assert "no artifact" in rows["dstpu plan"]
    assert "ratcheted" in rows["plan baseline"]   # repo baseline discovered
    # produce an artifact, point the env var at it
    out = tmp_path / "plan.json"
    assert attribution.main([ASYNC_TRACE, "--baseline", BASELINE,
                             "--out", str(out)]) == 0
    capsys.readouterr()
    monkeypatch.setenv(attribution.PLAN_ARTIFACT_ENV, str(out))
    rows = dict(plan_report())
    assert str(out) in rows["dstpu plan"]
    assert "% of step time" in rows["dstpu plan"]
    n_stages = len(attribution.load_plan_baseline(BASELINE)["entries"])
    assert f"{n_stages} stages ratcheted" in rows["plan baseline"]


# ---------------------------------------------------------------------------
# offline-only contract (satellite: hotpath registry)
# ---------------------------------------------------------------------------
def _imports_of(path):
    tree = ast.parse(open(path).read())
    mods = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            mods.update(a.name for a in node.names)
        elif isinstance(node, ast.ImportFrom) and node.module:
            mods.add(node.module)
    return mods


def test_plan_subcommand_never_imports_the_package():
    """`dstpu plan` file-loads the stdlib-only analyzer: the deepspeed_tpu
    package (and its jax import chain) must stay out of the process, so
    replaying a dump works on jax-less hosts and costs no framework
    import."""
    proc = subprocess.run(
        [sys.executable, "-X", "importtime",
         os.path.join(REPO, "bin", "dstpu"), "plan", ASYNC_TRACE, "--json"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    imported = [l for l in proc.stderr.splitlines() if "import time:" in l]
    assert imported                                # importtime was active
    assert not any("deepspeed_tpu" in l for l in imported)


def test_telemetry_package_lazy_attribution_reexport():
    """The package __init__ re-exports the replay API lazily (PEP 562):
    hot-path files importing telemetry for get_tracer must not load the
    offline analyzer transitively."""
    code = (
        "import sys\n"
        "import deepspeed_tpu.telemetry as T\n"
        "assert 'deepspeed_tpu.telemetry.attribution' not in sys.modules\n"
        "T.analyze_path\n"
        "assert 'deepspeed_tpu.telemetry.attribution' in sys.modules\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_attribution_is_offline_only():
    """Both directions of the purity contract are now the DS009 lint
    rule (transitive module-level import graph, not just direct imports)
    — this test pins the declaration AND runs the real rule over the
    package. One subprocess keep-alive remains above
    (``test_plan_subcommand_never_imports_the_package``); the other
    scattered ``-X importtime`` checks collapsed into this rule."""
    from deepspeed_tpu.tools.dslint import lint_paths
    from deepspeed_tpu.tools.dslint.hotpath import OFFLINE_ONLY_MODULES
    from deepspeed_tpu.tools.dslint.rules.ds009_offline_purity import \
        OfflinePurityRule
    assert "deepspeed_tpu/telemetry/attribution.py" in OFFLINE_ONLY_MODULES
    res = lint_paths([os.path.join(REPO, "deepspeed_tpu")], root=REPO,
                     rules=[OfflinePurityRule()])
    assert not res.findings, "\n".join(f.render() for f in res.findings)


# ---------------------------------------------------------------------------
# the closed loop: plan -> Autotuner executes + verifies (acceptance)
# ---------------------------------------------------------------------------
def test_autotuner_executes_and_verifies_plan(tmp_path):
    """The acceptance drill: the sync fixture's plan proposes the async
    pipeline; Autotuner(plan=...) runs ONLY that candidate set and proves
    the predicted transfer reduction by exact drain-span counting
    (8 steps at sync_every=8 -> exactly 1 readback transfer)."""
    from deepspeed_tpu.autotuning.autotuner import Autotuner
    from deepspeed_tpu.models.simple import SimpleModel, random_batch
    rep = attribution.analyze_path(SYNC_TRACE)
    base = {"train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "autotuning": {"results_dir": str(tmp_path)}}
    tuner = Autotuner(model=SimpleModel(hidden_dim=32), base_config=base,
                      example_batch=random_batch(8),
                      batch_fn=lambda bs: random_batch(int(bs)),
                      measure_steps=8, plan=rep)
    cfg, metrics = tuner.tune()
    by_id = {v["proposal"]: v for v in tuner.plan_verifications}
    v = by_id["enable_async_pipeline"]
    assert v["verdict"] == "verified", v
    assert v["observed"]["steps"] == 8
    assert v["observed"]["transfers"] == 1       # ceil(8/8), counted
    assert v["observed"]["transfers_without_plan"] == 8
    # only the plan's executable proposals ran — no blind grid search
    assert {e.name for e in tuner.records} == \
        {f"plan_{p['id']}" for p in rep["proposals"] if p["overrides"]}
    assert cfg is not None and "async_pipeline" in cfg
    # verifications persist next to the tuning results
    results = json.load(open(tmp_path / "autotuning_results.json"))
    assert results["plan"]["verifications"]
    # and the tracer is back off for everyone else
    from deepspeed_tpu.telemetry import get_tracer
    assert not get_tracer().enabled


def test_verify_counterfactual_uses_baseline_cadence():
    """transfers_without_plan is the counterfactual at the cadence the
    PLAN observed — ceil(steps/1) for sync mode, ceil(steps/cur) for
    raise_sync_every — over THIS experiment's step count."""
    from deepspeed_tpu.autotuning.autotuner import Autotuner, Experiment
    proposal = {"id": "raise_sync_every",
                "predicted": {"metric": "readback_transfers",
                              "sync_every": 16, "baseline_sync_every": 8}}
    exp = Experiment("plan_raise_sync_every", {})
    exp.status = "done"
    exp.metrics = {"trace_dispatch_spans": 3.0, "trace_drain_spans": 1.0}
    v = Autotuner._verify_proposal(None, proposal, exp)
    assert v["verdict"] == "verified"            # ceil(3/16) == 1
    assert v["observed"]["transfers_without_plan"] == 1   # ceil(3/8), NOT 3


def test_autotuner_load_plan_accepts_trace_and_artifact(tmp_path):
    from deepspeed_tpu.autotuning.autotuner import Autotuner
    rep = Autotuner._load_plan(SYNC_TRACE)       # raw dump: attributed here
    assert rep["proposals"]
    art = tmp_path / "plan.json"
    art.write_text(json.dumps(attribution.analyze_path(SYNC_TRACE)))
    rep2 = Autotuner._load_plan(str(art))        # plan artifact: as-is
    assert rep2["proposals"] == rep["proposals"]
    with pytest.raises(ValueError, match="proposals"):
        Autotuner._load_plan({"not": "a plan"})


# ---------------------------------------------------------------------------
# live round-trip: bench.py micro under DSTPU_TRACE (acceptance)
# ---------------------------------------------------------------------------
def test_bench_micro_trace_roundtrip(tmp_path):
    trace = tmp_path / "bench_trace.json"
    env = dict(os.environ, DSTPU_BENCH_MODEL="micro", DSTPU_TRACE=str(trace),
               JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "bench.py"], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rep = attribution.analyze_path(str(trace))
    assert rep["mode"] == "sync"                  # bench default: no pipeline
    assert rep["steps_total"] >= 10               # the timed loop
    for w in rep["windows"]:
        assert _stage_sum_us(w) == pytest.approx(w["dur_us"], abs=0.01)
        assert w["tie_out_error"] <= attribution.TIE_OUT_TOLERANCE
    # the plan knows what to do about a per-step-readback bench
    assert any(p["id"] == "enable_async_pipeline" for p in rep["proposals"])
