"""Test harness configuration.

Reference analog: ``tests/unit/common.py`` — the reference spawns world_size real
processes per test (DistributedTest) so CI needs no GPUs. Here the same effect is a
virtual 8-device CPU platform (``xla_force_host_platform_device_count=8``): every
test sees 8 JAX devices and exercises real mesh shardings + collectives in one
process. Set BEFORE importing jax anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = os.environ.get("DSTPU_TEST_PLATFORM", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("DSTPU_ACCELERATOR", "cpu")

import jax  # noqa: E402

# jax may have been pre-imported at interpreter startup (platform plugins), making
# the env vars above too late; config updates still apply pre-backend-init.
if os.environ.get("DSTPU_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # older jax: no such option — XLA_FLAGS above already forces the
        # 8-device host platform when jax wasn't pre-imported
        pass
jax.config.update("jax_default_matmul_precision", "highest")

import pytest  # noqa: E402


@pytest.fixture
def mesh8():
    """data=2, fsdp=4 mesh over the 8 virtual devices."""
    from deepspeed_tpu.comm.mesh import create_mesh
    from deepspeed_tpu.config.config import MeshConfig
    return create_mesh(MeshConfig(data=2, fsdp=4))


@pytest.fixture
def mesh_dp8():
    from deepspeed_tpu.comm.mesh import create_mesh
    from deepspeed_tpu.config.config import MeshConfig
    return create_mesh(MeshConfig(data=8))
