"""Test harness configuration.

Reference analog: ``tests/unit/common.py`` — the reference spawns world_size real
processes per test (DistributedTest) so CI needs no GPUs. Here the same effect is a
virtual 8-device CPU platform (``xla_force_host_platform_device_count=8``): every
test sees 8 JAX devices and exercises real mesh shardings + collectives in one
process. Set BEFORE importing jax anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = os.environ.get("DSTPU_TEST_PLATFORM", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("DSTPU_ACCELERATOR", "cpu")

import jax  # noqa: E402

# jax may have been pre-imported at interpreter startup (platform plugins), making
# the env vars above too late; config updates still apply pre-backend-init.
if os.environ.get("DSTPU_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # older jax: no such option — XLA_FLAGS above already forces the
        # 8-device host platform when jax wasn't pre-imported
        pass
jax.config.update("jax_default_matmul_precision", "highest")

import pytest  # noqa: E402

# Persistent XLA compilation cache, OPT-IN per module. The heavy training
# modules compile near-identical tiny graphs over and over (XLA's in-process
# cache is per-jit-instance, so the same HLO recompiles test after test);
# the content-addressed disk cache roughly halves their wall clock even when
# cold. It is NOT safe globally: executables that embed host callbacks
# (pallas interpret mode, io_callback — e.g. the comm/compress error-feedback
# graphs) segfault when reloaded from the cache on this jaxlib, so only
# pure-XLA modules that have been verified green with the cache are listed.
_XLA_CACHE_MODULES = {
    "test_param_offload", "test_offload", "test_t5", "test_pipeline",
    "test_llama", "test_gpt_neox", "test_gpt2", "test_gemma2",
    "test_aux_runtime", "test_onebit", "test_fast_convergence",
    "test_sched",
}


@pytest.fixture(autouse=True)
def _scoped_xla_cache(request):
    mod = request.node.module.__name__.rpartition(".")[2] \
        if request.node.module else ""
    if mod not in _XLA_CACHE_MODULES:
        yield
        return
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("DSTPU_TEST_XLA_CACHE",
                                         "/tmp/dstpu-test-xla-cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # older jax: no cache knobs — run uncached
        yield
        return
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", None)


@pytest.fixture
def mesh8():
    """data=2, fsdp=4 mesh over the 8 virtual devices."""
    from deepspeed_tpu.comm.mesh import create_mesh
    from deepspeed_tpu.config.config import MeshConfig
    return create_mesh(MeshConfig(data=2, fsdp=4))


@pytest.fixture
def mesh_dp8():
    from deepspeed_tpu.comm.mesh import create_mesh
    from deepspeed_tpu.config.config import MeshConfig
    return create_mesh(MeshConfig(data=8))


@pytest.fixture(scope="session")
def package_callgraph():
    """The dslint call graph over ``deepspeed_tpu/``, built ONCE per test
    session — the lint-layer tests (hot-path coverage proofs, offline
    purity, reachability assertions) all read from this instead of
    re-parsing ~200 files each."""
    import pathlib as _pathlib

    from deepspeed_tpu.tools.dslint.callgraph import build_graph_from_sources
    from deepspeed_tpu.tools.dslint.engine import iter_python_files

    repo = _pathlib.Path(__file__).resolve().parent.parent
    files = []
    for p in iter_python_files([str(repo / "deepspeed_tpu")]):
        rel = str(_pathlib.Path(p).relative_to(repo)).replace(os.sep, "/")
        files.append((rel, _pathlib.Path(p).read_text(encoding="utf-8")))
    # routes through the dslint snapshot cache: whichever of the engine
    # rules / env_report / this fixture runs first pays for the one build
    return build_graph_from_sources(files)


@pytest.fixture(scope="session")
def hot_reached(package_callgraph):
    """Keys reachable from the declared DS002 hot roots (prune hatches
    applied) — the taint closure the layer tests assert membership in."""
    from deepspeed_tpu.tools.dslint.hotpath import ESCAPE_HATCHES, HOT_ROOTS
    g = package_callgraph
    roots = sorted(filter(None, (g.resolve(r.path, r.qualname)
                                 for r in HOT_ROOTS)))
    prune = {k for k in (g.resolve(h.path, h.qualname)
                         for h in ESCAPE_HATCHES if h.mode == "prune") if k}
    return set(g.reachable_from(roots, prune=prune))
