"""Fast-suite convergence teeth (round-4 verdict weak #5 / task #6).

Every major parallelism / feature mode asserts an ACTUAL 3-step loss
decrease in the DEFAULT suite — the deeper step-for-step parity and
long-convergence runs stay behind @pytest.mark.slow, but the fast suite
alone must prove each mode trains, not merely that one step is finite.
Modes already fast-covered elsewhere (hpZ in test_mics_zeropp, offload in
test_offload, param offload in test_param_offload, dense in test_engine,
paged decode correctness in test_inference_v2) are not repeated here.

Reference analog: tests/unit/runtime/zero (17 files of per-mode training
assertions run in default CI).
"""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import create_mesh, set_global_mesh
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.models.simple import SimpleModel, random_batch


def _losses(engine, batch, steps=3):
    return [float(jax.device_get(engine.train_batch(batch=batch)))
            for _ in range(steps)]


def test_qgz_int8_wire_gradients_train():
    """qgZ (zero_quantized_gradients) over a replica axis: int8-wire grad
    reduction still decreases the loss (slow suite has the 40-step parity)."""
    mesh = create_mesh(MeshConfig(data=2, fsdp=4))
    set_global_mesh(mesh)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=64),
        config={"train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {"stage": 3,
                                      "zero_quantized_gradients": True}},
        mesh=mesh, example_batch=random_batch(4), seed=0)
    assert engine._qgz_axes, "expected a replica axis for the int8 wire"
    losses = _losses(engine, random_batch(8, seed=0))
    assert losses[-1] < losses[0], losses


def test_pipeline_engine_1f1b_trains():
    """PipelineEngine 1F1B on a pipe=4 mesh decreases the loss (slow suite
    has the 8-step single-stage parity)."""
    from tests.test_pipeline import _toy_setup
    from deepspeed_tpu.runtime.pipe.engine import PipeModule, PipelineEngine

    stacked, tied, toks, block_fn, first_fn, last_fn = _toy_setup()
    tokens = np.asarray(toks.reshape(-1, toks.shape[-1]))
    mesh = create_mesh(MeshConfig(pipe=4, data=2))
    set_global_mesh(mesh)
    mod = PipeModule(block_fn, first_fn, last_fn,
                     jax.tree.map(jnp.copy, stacked),
                     jax.tree.map(jnp.copy, tied))
    eng = PipelineEngine(mod, {"gradient_accumulation_steps": 8,
                               "optimizer": {"type": "AdamW",
                                             "params": {"lr": 5e-3}},
                               "gradient_clipping": 1.0}, mesh=mesh)
    losses = [float(eng.train_batch(tokens)) for _ in range(3)]
    assert losses[-1] < losses[0], losses


def test_moe_expert_parallel_trains():
    """Mixtral EP over the expert axis decreases the loss (slow suite has
    the 8-step run + quantized-dispatch parity)."""
    from deepspeed_tpu.models.mixtral import (TINY_MIXTRAL,
                                              MixtralForCausalLM,
                                              mixtral_tensor_rules)
    from deepspeed_tpu.models.llama import random_tokens

    mesh = create_mesh(MeshConfig(data=2, expert=4))
    set_global_mesh(mesh)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=MixtralForCausalLM(TINY_MIXTRAL),
        config={"train_batch_size": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
                "zero_optimization": {"stage": 1}},
        mesh=mesh, example_batch=random_tokens(2, 16, vocab_size=512),
        tensor_rules=mixtral_tensor_rules)
    losses = _losses(engine, random_tokens(4, 16, vocab_size=512, seed=0))
    assert losses[-1] < losses[0], losses


def _llama_sp_losses(backend):
    from deepspeed_tpu.models.llama import (TINY_LLAMA, LlamaConfig,
                                            LlamaForCausalLM, random_tokens)
    mesh = create_mesh(MeshConfig(data=2, sequence=4))
    set_global_mesh(mesh)
    cfg = LlamaConfig(**{**TINY_LLAMA.__dict__, "attention_backend": backend,
                         "dtype": jnp.float32})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=LlamaForCausalLM(cfg),
        config={"train_batch_size": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}}},
        mesh=mesh, example_batch=random_tokens(2, 32))
    return _losses(engine, random_tokens(4, 32, seed=0))


def test_ring_attention_sp_trains():
    """Ring-attention context parallelism (the TPU long-context must-add)
    decreases the loss on a sequence=4 mesh."""
    losses = _llama_sp_losses("ring")
    assert losses[-1] < losses[0], losses


def test_ulysses_sp_trains():
    """Ulysses head-scatter all-to-all SP decreases the loss on a
    sequence=4 mesh (reference sequence/layer.py:271)."""
    losses = _llama_sp_losses("ulysses")
    assert losses[-1] < losses[0], losses
