"""Regenerate the checked-in cross-rank fixtures for tests/test_crossrank.py.

Run from the repo root (pure stdlib — the fixtures are synthetic
exact-microsecond dumps, deterministic by construction on any host):

    python tests/crossrank_fixtures/make_fixtures.py

The artifact set (fixtures + baseline move TOGETHER; the regeneration pin
test fails if they drift):

  rank0_trace.json    rank 0's dstrace dump: 12 guarded comm spans with
                      op_seq 1..12, 12 dispatch spans, an in-jit comm
                      instant, plus the synthetic comm-overlap (tid
                      900000) and request-7 (tid 1000007) tracks that
                      exist IDENTICALLY on both ranks — the tid-collision
                      case the merge must namespace apart
  rank1_trace.json    rank 1's dump: same program, but ops 7..12 COMPLETE
                      2000us late (duration stretched — the chaos
                      comm_delay shape: the delay rides inside the span,
                      so rank 1 is the straggler on the back half) and
                      dispatch runs 2ms slower
  merged_micro.json   `merge_traces([rank0, rank1])` output — wall-anchor
                      aligned, per-rank pids, namespaced tids/event-ids
  ../../crossrank_baseline.json
                      the repo-root ratchet written from the merged
                      fixture's skew ledger (workload-scoped to
                      merged_micro.json), checked in exactly clean

Golden numbers the tests assert (derive, don't measure):
  12 matched collectives; ops 1..6 tie at arrival (wait 0), ops 7..12
  rank0 waits 2000us each -> rank0 waited 12000us total, rank1 caused
  12000us, wait_share rank1 = 1.0, dominant straggler = rank 1; one
  window (20000us spacing << the 200000us split cut), tie-out 0.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
HERE = os.path.dirname(os.path.abspath(__file__))


def _load_crossrank():
    """File-load the stdlib-only analyzer (no package import: regeneration
    works on jax-less hosts, same contract as bin/dstpu)."""
    import importlib.util
    path = os.path.join(REPO, "deepspeed_tpu", "telemetry", "crossrank.py")
    spec = importlib.util.spec_from_file_location("crossrank_fixgen", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


#: per-op spacing (us) and the rank-1 lateness on the back half
OP_SPACING_US = 20_000.0
DELAY_US = 2_000.0
N_OPS = 12

#: synthetic tracks present on BOTH ranks (tracer.COMM_OVERLAP_TID and a
#: request uid 7 track) — the collision case
OVERLAP_TID = 900_000
REQUEST_TID = 1_000_007
MAIN_TID = 7_777


def rank_dump(rank: int) -> dict:
    late = lambda k: DELAY_US if (rank == 1 and k >= 6) else 0.0  # noqa: E731
    evs = [
        {"name": "process_name", "ph": "M", "pid": 4000 + rank,
         "args": {"name": f"deepspeed_tpu rank{rank}/2"}},
        {"name": "thread_name", "ph": "M", "pid": 4000 + rank,
         "tid": MAIN_TID, "args": {"name": "MainThread"}},
        {"name": "thread_name", "ph": "M", "pid": 4000 + rank,
         "tid": OVERLAP_TID, "args": {"name": "comm-overlap"}},
        {"name": "thread_name", "ph": "M", "pid": 4000 + rank,
         "tid": REQUEST_TID, "args": {"name": "request-7"}},
    ]
    eid = 1
    for k in range(N_OPS):
        base = k * OP_SPACING_US
        # the chaos comm_delay shape: the delay rides INSIDE the span, so
        # rank 1's op STARTS on time but COMPLETES (arrives) 2000us late
        dur = 500.0 + late(k)
        evs.append({"name": "comm/guarded/drill_allreduce", "cat": "comm",
                    "ph": "X", "ts": base, "dur": dur, "tid": MAIN_TID,
                    "args": {"op_seq": k + 1, "call": k, "id": eid}})
        eid += 1
        # the training step that produced it (attribution's cross_rank
        # per-rank ledgers read these)
        evs.append({"name": "engine/dispatch", "cat": "train", "ph": "X",
                    "ts": base + 4_000.0,
                    "dur": 15_000.0 + (2_000.0 if rank == 1 else 0.0),
                    "tid": MAIN_TID,
                    "args": {"step": k, "mode": "sync", "id": eid}})
        eid += 1
    # in-jit analytic comm instant (zero-duration: must NOT join the skew
    # ledger, which reads complete spans only)
    evs.append({"name": "comm/all_reduce", "cat": "comm", "ph": "i",
                "ts": 1_000.0, "tid": MAIN_TID, "s": "t",
                "args": {"bytes": 4096, "wire_bytes": 4096, "world": 2,
                         "kind": "all_reduce", "op_seq": 100 + rank,
                         "id": eid}})
    eid += 1
    # synthetic-track spans with IDENTICAL tids/event-ids on both ranks —
    # the collision the merge namespaces apart
    evs.append({"name": "comm/overlap", "cat": "comm", "ph": "X",
                "ts": 5_000.0, "dur": 800.0, "tid": OVERLAP_TID,
                "args": {"bucket": 0, "bytes": 2048, "id": 999}})
    evs.append({"name": "serve/decode", "cat": "serve", "ph": "X",
                "ts": 6_000.0, "dur": 700.0, "tid": REQUEST_TID,
                "args": {"uid": 7, "tokens": 3, "id": 1000}})
    return {
        "traceEvents": evs,
        "displayTimeUnit": "ms",
        "otherData": {
            "clock": "monotonic",
            "events": len(evs),
            # wall anchors: both ranks' epochs sit at the same wall time
            # (single-host drill shape) -> wall-anchor offsets are 0 and
            # every arrival delta in the ledger is REAL skew
            "process": {"rank": rank, "world": 2, "hostname": "fixture",
                        "pid": 4000 + rank, "wall_s": 1_000.0,
                        "monotonic_s": 500.0 + 100.0 * rank,
                        "epoch_monotonic_s": 400.0 + 100.0 * rank},
        },
    }


def main():
    cr = _load_crossrank()
    paths = []
    for rank in (0, 1):
        path = os.path.join(HERE, f"rank{rank}_trace.json")
        with open(path, "w") as f:
            json.dump(rank_dump(rank), f, indent=1)
            f.write("\n")
        paths.append(path)
        print(f"wrote {path}")
    merged = cr.merge_traces(paths)
    merged_path = os.path.join(HERE, "merged_micro.json")
    with open(merged_path, "w") as f:
        json.dump(merged, f, indent=1)
        f.write("\n")
    print(f"wrote {merged_path}")
    report = cr.attribute_crossrank(merged, source=merged_path)
    bl_path = os.path.join(REPO, cr.CROSSRANK_BASELINE_NAME)
    cr.write_crossrank_baseline(bl_path, report)
    print(f"wrote {bl_path} (workload merged_micro.json, "
          f"dominant straggler rank {report['dominant_straggler']})")


if __name__ == "__main__":
    sys.exit(main())
