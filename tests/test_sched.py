"""The async serve core (PR 18): the extracted host-orchestration
scheduler (``runtime/sched.py``), decode-first chunked prefill, and the
prefill/decode role split.

Proof obligations, all deterministic counters (no wall-clock judgments):

  * DispatchRing/StagedPrefetcher/TickLedger units — drain semantics,
    anchor windows, bounded-queue overflow accounting, identity-keyed
    prefetch lifecycle, ceil-div decode-gap arithmetic
  * chunked-prefill bit-parity: a prompt prefilled in k capped chunks
    generates EXACTLY the single-shot tokens, composed with prefix-cache
    hits and speculative decoding (``speculative_k > 0``)
  * `serving.scheduler` off => bit-identical pre-PR planning (the config
    group defaults pin) and chunk shapes add ZERO compiles after warmup
    (chunk buckets stay inside the compile-ledger ladder)
  * disaggregation: the block-granular KV handoff round-trips pages
    bit-identical (full-width codec) / tolerance-pinned (int8), and a
    handed-off sequence continues decode to the same tokens as a
    single-engine run
  * the seeded ``long_prompt`` A/B: every chunked tick's prefill tokens
    <= cap, the worst decode gap strictly smaller than unchunked over the
    SAME seeded arrivals (common gap-unit normalizer), and the
    ``prefill_chunk_tokens`` plan rule verifies end-to-end
    (plan -> verify -> VERIFIED persisted under plan.serve_verifications)
"""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  V2EngineConfig)
from deepspeed_tpu.inference.v2.kv_offload import (quantize_error_bound,
                                                   quantize_pages)
from deepspeed_tpu.inference.v2.ragged_manager import StateManager
from deepspeed_tpu.inference.v2.scheduler import SchedulerConfig, plan_step
from deepspeed_tpu.models.llama import (TINY_LLAMA, LlamaConfig,
                                        LlamaForCausalLM)
from deepspeed_tpu.runtime.sched import (DispatchRing, StagedPrefetcher,
                                         TickLedger)
from deepspeed_tpu.telemetry.compiles import compiles_total
from deepspeed_tpu.telemetry.tracer import get_tracer

pytestmark = pytest.mark.sched


# ---------------------------------------------------------------------------
# the extracted core: DispatchRing / StagedPrefetcher / TickLedger units
# ---------------------------------------------------------------------------
def test_dispatch_ring_cadence_and_drain():
    ring = DispatchRing(sync_every=3)
    assert ring.drain() is None                      # nothing pending
    assert not ring.push({"x": jnp.float32(0.0)})
    assert not ring.push({"x": jnp.float32(1.0)})
    assert ring.push({"x": jnp.float32(2.0)})        # cadence reached
    assert len(ring) == 3
    res = ring.drain(extra=jnp.float32(7.0))
    assert len(ring) == 0
    assert [float(p["x"]) for p in res.payloads] == [0.0, 1.0, 2.0]
    assert float(res.extra) == 7.0
    assert not res.anchored and res.window_s == 0.0  # never armed


def test_dispatch_ring_anchor_window():
    ring = DispatchRing()
    ring.rearm_if_idle()                 # empty -> anchors
    assert ring.anchor is not None
    anchor = ring.anchor
    ring.push({"x": jnp.float32(0.0)})
    ring.rearm_if_idle()                 # pending -> must NOT re-anchor
    assert ring.anchor == anchor
    res = ring.drain()
    assert res.anchored and res.window_s >= 0.0
    # drain does NOT consume the anchor (the producer re-arms at the next
    # idle dispatch); reset_anchor un-arms explicitly
    assert ring.anchor == anchor
    ring.reset_anchor()
    ring.push({"x": jnp.float32(1.0)})
    assert not ring.drain().anchored


def test_dispatch_ring_store_take_requeue_overflow():
    ring = DispatchRing(capacity=4)
    assert ring.store([{"i": i} for i in range(3)]) == 0
    # 3 queued + 3 more > maxlen 4: the deque evicts the 2 OLDEST entries
    # (warned — the return value is the accounting the warning reports)
    assert ring.store([{"i": i} for i in range(3, 6)]) == 2
    taken = ring.take()
    assert [e["i"] for e in taken] == [2, 3, 4, 5]
    assert ring.take() == []
    # requeue restores original order at the front...
    ring.store([{"i": 9}])
    ring.requeue(taken[:2])
    assert [e["i"] for e in ring.take()] == [2, 3, 9]
    # ...and refuses to evict NEWER entries: with 3 slots free only the
    # first 3 requeued entries land, the tail is dropped (warned)
    ring.store([{"i": 0}])
    ring.requeue([{"i": i} for i in range(10, 14)])
    assert [e["i"] for e in ring.take()] == [10, 11, 12, 0]


class _FakeLoader:
    def __init__(self):
        self.closed = False

    def close(self):
        self.closed = True


def test_staged_prefetcher_identity_keyed():
    staged = StagedPrefetcher(depth=2)
    src_a, src_b = object(), object()
    a = staged.ensure(src_a, _FakeLoader)
    assert staged.ensure(src_a, _FakeLoader) is a    # stable identity
    assert staged.switches == 0
    b = staged.ensure(src_b, _FakeLoader)            # churn: close + rebuild
    assert b is not a and a.closed and not b.closed
    assert staged.switches == 1
    staged.close()
    assert b.closed and staged.loader is None
    staged.close()                                   # idempotent


def test_tick_ledger_counters_and_gap():
    led = TickLedger()
    led.observe_tick(64, 1, 0, cap=0)           # pure prefill tick
    led.observe_tick(63, 1, 1, cap=0)           # decode stalled behind 63
    led.observe_tick(0, 0, 4, cap=0)            # pure decode tick
    assert (led.ticks, led.prefill_ticks, led.decode_ticks) == (3, 2, 2)
    snap = led.snapshot(gap_unit_tokens=16)
    assert snap["max_prefill_tokens_per_tick"] == 64
    assert snap["max_decode_stall_tokens"] == 63    # the 64 ran no decode
    assert snap["max_decode_gap_ticks"] == 4        # ceil(63 / 16)
    assert snap["chunk_tokens_total"] == 127
    # the window resets maxima, not totals
    led.reset_window()
    led.observe_tick(32, 1, 2, cap=32)
    snap = led.snapshot(cap=32)
    assert snap["max_prefill_tokens_per_tick"] == 32
    assert snap["decode_gap_unit_tokens"] == 32     # cap is the unit
    assert snap["max_decode_gap_ticks"] == 1
    assert snap["chunk_tokens_total"] == 159        # cumulative survived
    assert snap["capped_chunk_ticks"] == 1
    assert snap["prefill_cap_utilization"] == 1.0
    # merge: the disagg pair folds both role ledgers into one proof set
    other = TickLedger()
    other.observe_tick(48, 2, 1, cap=0)
    led.merge_from(other)
    assert led.chunk_tokens_total == 207
    assert led.max_decode_stall_tokens == 48


# ---------------------------------------------------------------------------
# the tick planner: chunk cap + block snapping; cap off == pre-PR planning
# ---------------------------------------------------------------------------
def _planner_state():
    sm = StateManager()
    sm.create(1, np.arange(90) % 100)            # long prompt mid-prefill
    dec = sm.create(2, [1, 2, 3])
    dec.seen_tokens = 3
    dec.generated.append(7)
    return sm


def test_plan_step_chunk_cap_and_block_snap():
    sm = _planner_state()
    cfg = SchedulerConfig(max_tokens_per_step=64, prefill_buckets=(16, 32, 64),
                          prefill_chunk_tokens=24)
    plan = plan_step(sm.decoding(), sm.prefilling(), cfg, block_tokens=16)
    assert [s.uid for s in plan.decode_seqs] == [2]  # decode-first
    chunk = plan.prefill_chunks[0]
    # 24-token cap snapped DOWN to the 16-token KV block boundary: a
    # mid-prompt chunk may never end inside a block (the next chunk would
    # re-open a partially-filled page)
    assert chunk.length == 16 and chunk.length % 16 == 0
    assert chunk.bucket == 16
    # the FINAL chunk of a prompt may end mid-block (normal tail)
    seq = sm.get(1)
    seq.seen_tokens = 80
    plan = plan_step(sm.decoding(), sm.prefilling(), cfg, block_tokens=16)
    assert plan.prefill_chunks[0].length == 10


def test_plan_step_cap_off_bit_identical():
    """`serving.scheduler` off (cap=0) => the planner output is EXACTLY the
    pre-PR plan, block_tokens or not — the config group defaults to
    today's semantics."""
    def plans(cfg, block_tokens):
        sm = _planner_state()
        p = plan_step(sm.decoding(), sm.prefilling(), cfg,
                      block_tokens=block_tokens)
        return ([s.uid for s in p.decode_seqs],
                [(c.seq.uid, c.start, c.length, c.bucket)
                 for c in p.prefill_chunks])

    legacy = SchedulerConfig(max_tokens_per_step=64,
                             prefill_buckets=(16, 32, 64))
    assert legacy.prefill_chunk_tokens == 0          # the default IS off
    assert plans(legacy, 0) == plans(legacy, 16) == plans(
        dataclasses.replace(legacy, prefill_chunk_tokens=0), 16)


# ---------------------------------------------------------------------------
# live-engine parity: chunked == single-shot, composed with prefix + spec
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def model_and_params():
    cfg = LlamaConfig(**{**TINY_LLAMA.__dict__, "dtype": jnp.float32,
                         "max_seq_len": 512})
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": np.zeros((1, 8), np.int32)})["params"]
    return cfg, model, params


def _make_engine(params, cfg, spec_k=0):
    return InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=64,
        scheduler=SchedulerConfig(max_tokens_per_step=64,
                                  prefill_buckets=(16, 32, 64)),
        speculative_k=spec_k))


def test_chunked_prefill_bit_parity(model_and_params):
    cfg, _model, params = model_and_params
    prompt = list(np.random.default_rng(3).integers(0, cfg.vocab_size, 90))
    plain = _make_engine(params, cfg).generate(prompt, max_new_tokens=6)

    eng = _make_engine(params, cfg)
    eng.configure_chunked_prefill(32)
    chunked = eng.generate(prompt, max_new_tokens=6)
    assert chunked == plain
    # the ledger proves it WAS chunked, every chunk under the cap, and
    # chunk conservation: exactly the prompt's tokens went through chunks
    snap = eng.sched_stats()
    assert snap["chunks_total"] >= 3
    assert snap["max_prefill_tokens_per_tick"] <= 32
    assert snap["chunk_tokens_total"] == len(prompt)


def test_chunked_prefill_validation(model_and_params):
    cfg, _model, params = model_and_params
    eng = _make_engine(params, cfg)
    with pytest.raises(ValueError, match="block"):
        eng.configure_chunked_prefill(8)     # 0 < cap < kv block size
    eng.configure_chunked_prefill(16)
    eng.configure_chunked_prefill(0)         # 0 = disable, always legal
    assert eng.config.scheduler.prefill_chunk_tokens == 0


def test_chunked_prefill_with_prefix_cache(model_and_params):
    """Chunking composes with prefix-cache hits: the chunk planner sees
    only the post-hit remainder and the tokens stay bit-identical."""
    cfg, _model, params = model_and_params
    rng = np.random.default_rng(4)
    shared = list(rng.integers(0, cfg.vocab_size, 48))
    tail_a = list(rng.integers(0, cfg.vocab_size, 20))
    tail_b = list(rng.integers(0, cfg.vocab_size, 24))

    def run(chunk_cap):
        eng = _make_engine(params, cfg)
        eng.enable_prefix_cache(32)
        if chunk_cap:
            eng.configure_chunked_prefill(chunk_cap)
        out = [eng.generate(shared + tail_a, max_new_tokens=4, uid=1),
               eng.generate(shared + tail_b, max_new_tokens=4, uid=2)]
        return out, eng.prefix_stats(), eng.sched_stats()

    plain, _stats0, _snap0 = run(0)
    chunked, stats, snap = run(32)
    assert chunked == plain
    assert stats["prefix_hit_tokens"] >= 48          # the hit happened
    assert snap["max_prefill_tokens_per_tick"] <= 32
    # conservation THROUGH the cache: chunks carried exactly the computed
    # (post-hit) tokens, not the full prompts
    assert snap["chunk_tokens_total"] == stats["prefill_tokens_computed"]
    assert snap["chunk_tokens_total"] < len(shared) * 2 + len(tail_a) + \
        len(tail_b)


def test_chunked_prefill_with_speculative(model_and_params):
    cfg, _model, params = model_and_params
    prompt = list(np.random.default_rng(5).integers(0, cfg.vocab_size, 70))
    plain = _make_engine(params, cfg).generate(prompt, max_new_tokens=12)

    eng = _make_engine(params, cfg, spec_k=4)
    eng.configure_chunked_prefill(32)
    spec = eng.generate(prompt, max_new_tokens=12)
    assert spec[:len(plain)] == plain
    assert eng.sched_stats()["max_prefill_tokens_per_tick"] <= 32


def test_chunked_shapes_zero_compiles_after_warmup(model_and_params):
    """The compile-ledger gate: chunk boundaries snap to the bucket ladder
    and KV blocks, so turning the cap ON adds ZERO XLA compiles once the
    unchunked shapes are warm — no mid-siege compiles."""
    cfg, _model, params = model_and_params
    prompt = list(np.random.default_rng(6).integers(0, cfg.vocab_size, 90))
    warm = _make_engine(params, cfg)
    warm_tokens = warm.generate(prompt, max_new_tokens=6)   # pays compiles

    mark = compiles_total()
    eng = _make_engine(params, cfg)
    eng.configure_chunked_prefill(32)
    assert eng.generate(prompt, max_new_tokens=6) == warm_tokens
    assert compiles_total() - mark == 0


# ---------------------------------------------------------------------------
# the serving.scheduler config group
# ---------------------------------------------------------------------------
def test_serving_scheduler_group_validation():
    from deepspeed_tpu.serving.server import SCHEDULER_DEFAULTS, ServingConfig
    assert ServingConfig().scheduler == SCHEDULER_DEFAULTS
    # partial dicts merge over the defaults (config-file ergonomics)
    cfg = ServingConfig(scheduler={"prefill_chunk_tokens": 32})
    assert cfg.scheduler["prefill_chunk_tokens"] == 32
    assert cfg.scheduler["role_split"] is False
    with pytest.raises(ValueError, match="unknown"):
        ServingConfig(scheduler={"chunk_cap": 32})
    with pytest.raises(ValueError, match="prefill_chunk_tokens"):
        ServingConfig(scheduler={"prefill_chunk_tokens": -1})
    with pytest.raises(ValueError, match="handoff_quantize"):
        ServingConfig(scheduler={"handoff_quantize": "zstd"})


def test_scheduler_defaults_pinned_across_modules():
    """serve_attribution carries a literal copy of the scheduler defaults
    (it must load standalone on jax-less hosts) — pin the copies equal so
    drift between the planner's fallback and the server is impossible."""
    from deepspeed_tpu.serving.server import SCHEDULER_DEFAULTS
    from deepspeed_tpu.telemetry.serve_attribution import SERVING_DEFAULTS
    assert SERVING_DEFAULTS["scheduler"] == SCHEDULER_DEFAULTS


# ---------------------------------------------------------------------------
# disaggregation: the role split + block-granular KV handoff
# ---------------------------------------------------------------------------
def _disagg_pair(params, cfg, handoff_quantize="none"):
    from deepspeed_tpu.serving.disagg import DisaggregatedEngine
    return DisaggregatedEngine(_make_engine(params, cfg),
                               _make_engine(params, cfg),
                               handoff_quantize=handoff_quantize)


def test_disagg_handoff_roundtrip_bit_identical(model_and_params):
    cfg, _model, params = model_and_params
    prompt = list(np.random.default_rng(7).integers(0, cfg.vocab_size, 50))
    pair = _disagg_pair(params, cfg)
    pair.prefill.put([7], [prompt])
    while pair.prefill.state.get(7).in_prefill:
        pair.prefill.step()
    donor = pair.prefill.state.get(7)
    ref_data, ref_scales = pair.prefill.kv.gather_blocks(donor.blocks)
    first_token = list(donor.generated)

    pair._handoff()
    assert pair.handoff_stats["handoffs"] == 1
    assert 7 not in pair.prefill.state and pair.prefill.host_kv.get(7) is None
    adopted = pair.decode.state.get(7)
    assert adopted is not None and list(adopted.generated) == first_token
    got_data, got_scales = pair.decode.kv.gather_blocks(adopted.blocks)
    # full-width codec: the pages land on the decode engine BIT-identical
    assert np.array_equal(np.asarray(ref_data), np.asarray(got_data))
    if ref_scales is not None:
        assert np.array_equal(np.asarray(ref_scales), np.asarray(got_scales))
    # donor residue fully released
    assert pair.prefill.kv.free_blocks == \
        pair.prefill.kv.allocator.total_blocks


def test_disagg_handoff_quantized_tolerance_pinned(model_and_params):
    cfg, _model, params = model_and_params
    prompt = list(np.random.default_rng(8).integers(0, cfg.vocab_size, 40))
    pair = _disagg_pair(params, cfg, handoff_quantize="int8")
    pair.prefill.put([9], [prompt])
    while pair.prefill.state.get(9).in_prefill:
        pair.prefill.step()
    ref_data, _ = pair.prefill.kv.gather_blocks(
        pair.prefill.state.get(9).blocks)
    ref = np.asarray(ref_data, np.float32)
    _q, qscales = quantize_pages(ref, "int8")
    bound = quantize_error_bound(qscales, "int8")

    pair._handoff()
    assert pair.handoff_stats["handoffs"] == 1
    # int8 travels at ~1/4 width; the wire accounting proves it
    assert pair.handoff_stats["handoff_bytes"] < \
        pair.handoff_stats["handoff_raw_bytes"]
    got, _ = pair.decode.kv.gather_blocks(pair.decode.state.get(9).blocks)
    err = float(np.max(np.abs(np.asarray(got, np.float32) - ref)))
    assert err <= bound, (err, bound)


@pytest.mark.parametrize("handoff_quantize", ["none", "int8"])
def test_disagg_continues_to_single_engine_tokens(model_and_params,
                                                  handoff_quantize):
    """The acceptance round-trip: sequences handed across the role
    boundary continue decode to the SAME tokens as a single-engine run
    (greedy argmax; the int8 path holds on this fp32 tiny model because
    the perturbation sits below every argmax margin on these seeds)."""
    cfg, _model, params = model_and_params
    rng = np.random.default_rng(9)
    prompts = [list(rng.integers(0, cfg.vocab_size, int(n)))
               for n in rng.integers(20, 60, 4)]

    solo = _make_engine(params, cfg)
    solo.put(list(range(4)), prompts)
    for _ in range(40):
        solo.step()
        if all(len(solo.state.get(u).generated) >= 8 for u in range(4)):
            break
    want = {u: solo.flush(u)[:8] for u in range(4)}

    pair = _disagg_pair(params, cfg, handoff_quantize=handoff_quantize)
    pair.prefill.put(list(range(4)), prompts)
    for _ in range(60):
        pair.step()
        if all((s := pair.state.get(u)) and len(s.generated) >= 8
               for u in range(4)):
            break
    got = {u: pair.flush(u)[:8] for u in range(4)}
    assert got == want
    assert pair.handoff_stats["handoffs"] == 4      # every uid crossed
    # the handoff store drains: no KV bytes stranded on the boundary
    assert pair.host_kv_bytes() == 0


# ---------------------------------------------------------------------------
# the seeded long_prompt A/B + the plan->verify acceptance drill
# ---------------------------------------------------------------------------
def _long_prompt(num_requests=12):
    from deepspeed_tpu.serving import bench_serve
    return dataclasses.replace(bench_serve.SCENARIOS["long_prompt"],
                               num_requests=num_requests)


def _run_long_prompt(serving_overrides):
    from deepspeed_tpu.serving import bench_serve
    server = bench_serve.build_tiny_server(
        serving_overrides=serving_overrides).start()
    try:
        return bench_serve.run_scenario(server, _long_prompt())
    finally:
        server.stop(drain_timeout=30.0)


def test_long_prompt_decode_gap_ab_proof():
    """The tentpole's acceptance inequalities over the SAME seeded
    arrivals: chunked ticks never exceed the cap, the worst decode gap is
    STRICTLY smaller than unchunked (common 32-token normalizer), chunk
    conservation holds in both modes, and — the run being second in the
    process — chunking adds zero mid-measurement compiles."""
    cap = 32
    base = _run_long_prompt(None)
    chunk = _run_long_prompt({"scheduler": {"prefill_chunk_tokens": cap}})
    b, c = base["scheduler"], chunk["scheduler"]

    assert b["prefill_chunk_tokens"] == 0 and c["prefill_chunk_tokens"] == cap
    # every chunked tick bounded by the cap; unchunked proves the workload
    # genuinely produced over-cap ticks to cut
    assert c["max_prefill_tokens_per_tick"] <= cap
    assert b["max_prefill_tokens_per_tick"] > cap
    # the decode-gap A/B in COMMON units (ceil of stall tokens / cap)
    base_gap = -(-b["max_decode_stall_tokens"] // cap)
    assert c["max_decode_gap_ticks"] < base_gap, (c, b)
    assert c["decode_gap_unit_tokens"] == cap
    # conservation: chunking moved exactly the tokens prefill computed
    assert b["chunk_conservation_ok"] and c["chunk_conservation_ok"]
    assert c["chunk_tokens_total"] == b["chunk_tokens_total"]
    assert c["prefill_cap_utilization"] > 0.5       # the cap binds
    # the chunked run rides shapes the unchunked run already compiled
    assert chunk["counters"]["compiles_during_measurement"] == 0
    # the counter the plan rule predicates on is mirrored into counters
    assert chunk["counters"]["max_prefill_tokens_per_tick"] == \
        c["max_prefill_tokens_per_tick"]
    states = chunk["requests"]["states"]
    assert states.get("finished", 0) == 12, states


def test_long_prompt_chunk_proposal_verify_loop(tmp_path):
    """Acceptance drill: the seeded long_prompt preset trips the
    `prefill_chunk_tokens` rule (dominant prefill share with decodes in
    flight), `--verify-plan` re-runs the SAME preset with the proposed
    cap, and the `max_prefill_tokens_per_tick <= cap` prediction holds
    EXACTLY — VERIFIED, persisted under plan.serve_verifications."""
    from deepspeed_tpu.autotuning.serve_verify import verify_serve_plan
    from deepspeed_tpu.serving import bench_serve
    from deepspeed_tpu.telemetry import serve_attribution as sa

    builder = {"kv_num_blocks": 64, "kv_block_size": 16}
    # decisively prefill-dominant variant of the preset: near-max prompts,
    # short decodes — the prefill share clears the rule's 0.35 threshold
    # whatever this host's compile-cache state is (the preset's balanced
    # mix is the A/B gap proof's job, not this drill's)
    scenario = dataclasses.replace(_long_prompt(), prompt_len=(80, 96),
                                   max_new_tokens=(4, 6))
    warm = bench_serve.build_tiny_server(**builder).start()
    try:
        bench_serve.run_scenario(
            warm, dataclasses.replace(scenario, num_requests=4))
    finally:
        warm.stop(drain_timeout=30.0)
    tracer = get_tracer()
    tracer.clear()
    tracer.configure(enabled=True)
    server = bench_serve.build_tiny_server(**builder).start()
    try:
        report = bench_serve.run_scenario(server, scenario, provenance={
            "builder": builder, "trace_path": "long_prompt_trace.json"})
    finally:
        server.stop(drain_timeout=30.0)
    tracer.export_chrome(str(tmp_path / "long_prompt_trace.json"))
    tracer.configure(enabled=False)
    report_path = tmp_path / "long_prompt_report.json"
    report_path.write_text(json.dumps(report, default=str))

    plan = sa.analyze_serve_path(str(report_path))
    chunk_props = [p for p in plan["proposals"]
                   if p["id"] == "prefill_chunk_tokens"]
    assert chunk_props, [p["id"] for p in plan["proposals"]]
    prop = chunk_props[0]
    assert prop["knob"] == "scheduler.prefill_chunk_tokens"
    new_cap = prop["overrides"]["serving"]["scheduler"][
        "prefill_chunk_tokens"]
    assert new_cap >= 16 and new_cap % 16 == 0       # block-aligned
    assert prop["predicted"]["counter"] == "max_prefill_tokens_per_tick"
    assert prop["predicted"]["value"] == new_cap
    assert prop["predicted"]["baseline"] > new_cap

    # verify ONLY the chunk proposal (the drill under test)
    plan["proposals"] = chunk_props
    art = tmp_path / "serve_plan.json"
    art.write_text(json.dumps(plan, default=str))
    verdicts = verify_serve_plan(str(art), results_dir=str(tmp_path),
                                 max_proposals=1)
    get_tracer().configure(enabled=False)
    assert len(verdicts) == 1
    assert verdicts[0]["proposal"] == "prefill_chunk_tokens"
    assert verdicts[0]["verdict"] == "verified", verdicts[0]
    observed = verdicts[0]["observed"]["max_prefill_tokens_per_tick"]
    assert observed <= new_cap
    results = json.load(open(tmp_path / "autotuning_results.json"))
    assert results["plan"]["serve_verifications"] == verdicts


def test_role_split_server_token_parity(model_and_params):
    """`serving.scheduler.role_split` through the real server: the pair
    serves the same seeded prompts to the same tokens as a single-engine
    server, with every sequence crossing the handoff boundary."""
    del model_and_params    # ordering only: reuse the compiled tiny shapes
    from deepspeed_tpu.serving import bench_serve

    def serve(serving_overrides):
        rng = np.random.default_rng(10)
        prompts = [list(map(int, rng.integers(0, 128, int(n))))
                   for n in rng.integers(20, 70, 6)]
        server = bench_serve.build_tiny_server(
            serving_overrides=serving_overrides).start()
        try:
            reqs = [server.submit(p, max_new_tokens=6, timeout_s=120.0)
                    for p in prompts]
            for r in reqs:
                r.wait(timeout=120.0)
            return [list(r.tokens) for r in reqs], server.engine
        finally:
            server.stop(drain_timeout=30.0)

    solo, _ = serve(None)
    split, engine = serve({"scheduler": {"role_split": True,
                                         "prefill_chunk_tokens": 32}})
    assert split == solo
    assert engine.handoff_stats["handoffs"] == 6
    assert engine.host_kv_bytes() == 0               # boundary drained
    assert engine.sched_stats()["max_prefill_tokens_per_tick"] <= 32
