"""Pallas evoformer attention kernels (ops/pallas/evoformer.py) vs the jnp
oracle — forward and full gradient set (q, k, v, bias1, bias2), interpret
mode (reference analog: tests for csrc/deepspeed4science/evoformer_attn)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.evoformer_attn import evoformer_attention_reference
from deepspeed_tpu.ops.pallas.evoformer import pallas_evoformer_attention

B, N, L, H, D = 2, 3, 20, 2, 16     # L=20 vs 16-blocks exercises key padding
BLK = dict(block_q=16, block_k=16, interpret=True)


def _inputs(seed=0, lead=(B, N)):
    rng = np.random.default_rng(seed)
    f = lambda *s: jnp.asarray(rng.normal(size=s).astype(np.float32))
    q, k, v = f(*lead, L, H, D), f(*lead, L, H, D), f(*lead, L, H, D)
    bias1 = f(B, N, 1, 1, L) if lead == (B, N) else None
    bias2 = f(B, 1, H, L, L) if lead == (B, N) else None
    return q, k, v, bias1, bias2


@pytest.mark.parametrize("use_b1,use_b2", [(False, False), (True, False),
                                           (False, True), (True, True)])
def test_evoformer_fwd_matches_reference(use_b1, use_b2):
    q, k, v, b1, b2 = _inputs()
    biases = tuple(b for b, u in ((b1, use_b1), (b2, use_b2)) if u)
    out = pallas_evoformer_attention(q, k, v, biases, **BLK)
    ref = evoformer_attention_reference(q, k, v, biases)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_evoformer_grads_match_reference():
    q, k, v, b1, b2 = _inputs(seed=1)
    w = jnp.asarray(np.random.default_rng(9).normal(
        size=(B, N, L, H, D)).astype(np.float32))

    def loss_pallas(q, k, v, b1, b2):
        return jnp.sum(pallas_evoformer_attention(q, k, v, (b1, b2),
                                                  **BLK) * w)

    def loss_ref(q, k, v, b1, b2):
        return jnp.sum(evoformer_attention_reference(q, k, v, (b1, b2)) * w)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2, 3, 4))(q, k, v, b1, b2)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(q, k, v, b1, b2)
    for name, a, b in zip("q k v bias1 bias2".split(), gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-5, err_msg=name)


def test_evoformer_bias_broadcast_grad_sums():
    """A bias broadcast over B must get its cotangent summed back (the
    canonicalization is plain jnp broadcasting, so autodiff transposes it)."""
    q, k, v, _, _ = _inputs(seed=2)
    rng = np.random.default_rng(3)
    b2_shared = jnp.asarray(rng.normal(size=(1, 1, H, L, L)).astype(np.float32))

    def loss_pallas(b):
        return jnp.sum(pallas_evoformer_attention(q, k, v, (b,), **BLK) ** 2)

    def loss_ref(b):
        return jnp.sum(evoformer_attention_reference(q, k, v, (b,)) ** 2)

    ga = jax.grad(loss_pallas)(b2_shared)
    gb = jax.grad(loss_ref)(b2_shared)
    assert ga.shape == b2_shared.shape
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                               atol=5e-5, rtol=5e-5)


def test_evoformer_single_lead_dim():
    q, k, v, _, _ = _inputs(seed=4, lead=(B,))
    out = pallas_evoformer_attention(q, k, v, (), **BLK)
    ref = evoformer_attention_reference(q, k, v, ())
    assert out.shape == (B, L, H, D)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_evoformer_row_varying_pair_bias_rejected():
    q, k, v, _, _ = _inputs(seed=5)
    bad = jnp.zeros((B, N, H, L, L), jnp.float32)
    with pytest.raises(ValueError, match="row"):
        pallas_evoformer_attention(q, k, v, (bad,), **BLK)


def test_unsupported_layout_raises_typed_and_dispatch_falls_back():
    """Only UnsupportedBiasLayout may trigger the jnp fallback — internal
    kernel ValueErrors must propagate (round-5 review finding)."""
    from deepspeed_tpu.ops.evoformer_attn import DS4Sci_EvoformerAttention
    from deepspeed_tpu.ops.pallas.evoformer import UnsupportedBiasLayout
    q, k, v, _, _ = _inputs(seed=6)
    one_d = jnp.zeros((L,), jnp.float32)       # broadcastable, 1-d
    with pytest.raises(UnsupportedBiasLayout):
        # wrong key length is a layout error, not a crash
        pallas_evoformer_attention(q, k, v, (jnp.zeros((L + 3,)),), **BLK)
    # 1-d per-key bias is within contract (mask-like)
    out = pallas_evoformer_attention(q, k, v, (one_d,), **BLK)
    ref = evoformer_attention_reference(q, k, v, (one_d,))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # the public entry keeps accepting any broadcastable bias regardless
    out2 = DS4Sci_EvoformerAttention(q, k, v, [one_d])
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
