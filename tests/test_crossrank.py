"""dstrace-mp tests — cross-rank trace merge, collective-skew ledger,
compile-event ledger (ISSUE 15).

Fast tier-1 half: checked-in synthetic fixtures (tests/crossrank_fixtures/
make_fixtures.py regenerates fixtures + the repo-root crossrank_baseline.json
as ONE artifact set) drive merge/namespacing/ledger goldens, the CLI exit
matrix, clock-alignment contracts, the ``--rank`` slice, env_report rows,
and the compile ledger. The 2-proc gloo MULTICHIP drill (chaos comm_delay
on rank 1 -> rank 1 dominant in BOTH the ledger and StragglerDetector) is
marked slow like every harness drill.
"""

import copy
import json
import os
import subprocess
import sys

import pytest

from deepspeed_tpu.telemetry import crossrank

pytestmark = pytest.mark.crossrank

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "crossrank_fixtures")
R0 = os.path.join(FIXTURES, "rank0_trace.json")
R1 = os.path.join(FIXTURES, "rank1_trace.json")
MERGED = os.path.join(FIXTURES, "merged_micro.json")
BASELINE = os.path.join(REPO, "crossrank_baseline.json")
DSTPU = os.path.join(REPO, "bin", "dstpu")
DSTPU_TRACE = os.path.join(REPO, "bin", "dstpu_trace")

RANK_SHIFT = crossrank.RANK_SHIFT


@pytest.fixture
def tracing():
    from deepspeed_tpu.telemetry.tracer import get_tracer
    t = get_tracer()
    was = t.enabled
    t.configure(enabled=True)
    t.clear()
    yield t
    t.clear()
    t.configure(enabled=was)


# ---------------------------------------------------------------------------
# fixtures are one artifact set
# ---------------------------------------------------------------------------
def test_fixture_regeneration_pin():
    """merged_micro.json and crossrank_baseline.json are exactly what
    make_fixtures.py produces from the rank dumps — fixtures and baseline
    move together or not at all."""
    merged = crossrank.merge_traces([R0, R1])
    with open(MERGED) as f:
        assert merged == json.load(f)
    report = crossrank.attribute_crossrank(merged, source=MERGED)
    import tempfile
    with tempfile.NamedTemporaryFile("r", suffix=".json") as tmp:
        crossrank.write_crossrank_baseline(tmp.name, report)
        regenerated = json.load(open(tmp.name))
    assert regenerated == json.load(open(BASELINE))


def test_baseline_exactly_clean():
    report = crossrank.analyze_crossrank_path(MERGED)
    baseline = crossrank.load_crossrank_baseline(BASELINE)
    assert baseline["workload"] == "merged_micro.json"
    regressions, stale = crossrank.check_crossrank_baseline(report, baseline)
    assert regressions == [] and stale == []


# ---------------------------------------------------------------------------
# merge: identity, alignment, namespacing
# ---------------------------------------------------------------------------
def test_merge_identity_and_wall_alignment():
    merged = crossrank.merge_traces([R0, R1])
    cr = merged["otherData"]["crossrank"]
    assert cr["ranks"] == [0, 1]
    assert cr["reference_rank"] == 0
    assert cr["alignment"] == "wall_anchor"
    # both fixture epochs sit at the same wall time -> zero offsets, and
    # the 2000us residual is REAL systematic skew (the back-half delay)
    assert cr["offsets_us"] == {"0": 0.0, "1": 0.0}
    assert cr["residual_skew_us"]["1"] == 2000.0
    assert cr["max_residual_skew_us"] == 2000.0
    assert cr["matched_collectives"] == {"0": 12, "1": 12}
    assert cr["sources"]["1"]["hostname"] == "fixture"


def test_merge_namespaces_synthetic_tids_no_collision():
    """The satellite fix: COMM_OVERLAP_TID (900000) and the request-7
    track exist with IDENTICAL raw tids on both ranks — the merge must
    namespace them apart as rank<<40 | tid."""
    merged = crossrank.merge_traces([R0, R1])
    overlap_tids = {e["tid"] for e in merged["traceEvents"]
                    if e.get("name") == "comm/overlap"}
    assert overlap_tids == {900_000, (1 << RANK_SHIFT) | 900_000}
    req_tids = {e["tid"] for e in merged["traceEvents"]
                if e.get("name") == "serve/decode"}
    assert req_tids == {1_000_007, (1 << RANK_SHIFT) | 1_000_007}
    labels = {(e.get("args") or {}).get("name")
              for e in merged["traceEvents"]
              if e.get("ph") == "M" and e.get("name") == "thread_name"}
    assert {"r0/comm-overlap", "r1/comm-overlap",
            "r0/request-7", "r1/request-7"} <= labels


def test_merge_namespaces_event_ids_unique():
    """Event ids are only process-unique; merged args ids must never
    collide across ranks (rank<<40 | id), including the deliberately
    identical ids 999/1000 planted on both rank fixtures."""
    merged = crossrank.merge_traces([R0, R1])
    ids = [e["args"]["id"] for e in merged["traceEvents"]
           if e.get("ph") != "M" and isinstance(e.get("args"), dict)
           and "id" in e["args"]]
    assert len(ids) == len(set(ids))
    # per-rank track groups: pid == rank
    pids = {e.get("pid") for e in merged["traceEvents"]}
    assert pids == {0, 1}


def test_merge_positional_rank_fallback_for_headerless_dumps(tmp_path):
    """Pre-header dumps (no otherData.process) merge by argument
    position, never silently as N copies of rank 0."""
    for i, src in enumerate((R0, R1)):
        obj = json.load(open(src))
        del obj["otherData"]["process"]
        json.dump(obj, open(tmp_path / f"d{i}.json", "w"))
    merged = crossrank.merge_traces([str(tmp_path / "d0.json"),
                                     str(tmp_path / "d1.json")])
    cr = merged["otherData"]["crossrank"]
    assert cr["ranks"] == [0, 1]
    assert cr["alignment"] == "matched_collectives"

    # two dumps CLAIMING the same rank (header duplicates) also fall back
    # to position, with a note — never two track groups labeled rank 0
    obj = json.load(open(R1))
    obj["otherData"]["process"]["rank"] = 0
    json.dump(obj, open(tmp_path / "dup.json", "w"))
    merged = crossrank.merge_traces([R0, str(tmp_path / "dup.json")])
    cr = merged["otherData"]["crossrank"]
    assert cr["ranks"] == [0, 1] and "note" in cr


# ---------------------------------------------------------------------------
# skew ledger goldens
# ---------------------------------------------------------------------------
def test_skew_ledger_golden():
    rep = crossrank.analyze_crossrank_path(MERGED)
    assert rep["matched"] == 12
    assert rep["alignment"] == "wall_anchor"
    assert rep["dominant_straggler"] == 1
    assert rep["wait_total_us"] == 12_000.0
    r0, r1 = rep["per_rank"]["0"], rep["per_rank"]["1"]
    # ops 7..12: rank 1 completes 2000us late -> rank 0 waits 2000us each
    assert r0["waited_us"] == 12_000.0 and r0["caused_us"] == 0.0
    assert r1["caused_us"] == 12_000.0 and r1["wait_share"] == 1.0
    assert r1["straggled"] == 6
    assert r0["wait_p99_us"] == 2000.0 and r1["wait_p99_us"] == 0.0
    # one window (20ms spacing << the 200ms split cut), clean tie-out
    assert len(rep["windows"]) == 1
    w = rep["windows"][0]
    assert w["dominant_straggler"] == 1 and w["tie_out_error"] == 0.0
    assert w["collectives"] == 12
    # per-collective waits sum consistently with the matched spans
    assert sum(c["wait_total_us"] for c in rep["collectives"]) \
        == rep["wait_total_us"]


def test_matched_collectives_excludes_injit_instants():
    """In-jit comm instants (ph 'i') carry op_seq too but have no runtime
    duration — they must never join the skew ledger."""
    matched = crossrank.matched_collectives(json.load(open(MERGED)))
    assert set(matched) == set(range(1, 13))      # spans only, not 100/101
    assert all(rec["op"] == "comm/guarded/drill_allreduce"
               for rec in matched.values())


def test_window_split_on_large_gaps():
    """Collectives separated by a phase-sized pause land in separate
    windows with their own dominant straggler."""
    merged = copy.deepcopy(json.load(open(MERGED)))
    for e in merged["traceEvents"]:
        if e.get("ph") == "M" or "op_seq" not in (e.get("args") or {}):
            continue
        if e["args"]["op_seq"] > 6:
            e["ts"] += 10_000_000.0       # 10s pause before the back half
    rep = crossrank.attribute_crossrank(merged)
    assert len(rep["windows"]) == 2
    assert rep["windows"][0]["dominant_straggler"] == 0   # all ties
    assert rep["windows"][1]["dominant_straggler"] == 1


def test_straggler_detector_ties_out_with_ledger():
    """The detector's duration-outlier verdict and the ledger's
    waiter-causer verdict must name the SAME rank on the fixture."""
    from deepspeed_tpu.resilience.membership import StragglerDetector
    matched = crossrank.matched_collectives(json.load(open(MERGED)))
    det = StragglerDetector(factor=3.0)
    flagged = []
    for seq, rec in sorted(matched.items()):
        flagged.extend(det.observe(
            f"{rec['op']}@{seq}",
            {r: v["dur_us"] / 1e6 for r, v in rec["ranks"].items()}))
    assert flagged and set(flagged) == {1}
    assert crossrank.analyze_crossrank_path(MERGED)["dominant_straggler"] \
        == 1


def test_straggler_detector_flags_two_rank_outlier():
    """The lower-median fix: with exactly 2 ranks the detector compares
    against the FASTER rank (the upper median — the slower rank itself —
    made 2-process stragglers mathematically unflaggable)."""
    from deepspeed_tpu.resilience.membership import StragglerDetector
    det = StragglerDetector(factor=3.0)
    assert det.observe("drill", {0: 0.002, 1: 0.050}) == [1]
    assert det.observe("drill", {0: 0.002, 1: 0.004}) == []   # under factor


def test_matched_collective_alignment_recovers_clock_shift(tmp_path):
    """An anchor-less dump with a constant clock shift: the median
    matched-collective delta recovers the offset, and the systematic
    back-half delay is partially absorbed — the documented failure mode
    (the ledger under-reports a persistently-late rank without anchors)."""
    obj = json.load(open(R1))
    del obj["otherData"]["process"]      # no anchors on rank 1
    for e in obj["traceEvents"]:
        if e.get("ph") != "M":
            e["ts"] += 500_000.0         # +0.5s clock shift
    shifted = tmp_path / "r1_shifted.json"
    json.dump(obj, open(shifted, "w"))
    merged = crossrank.merge_traces([R0, str(shifted)])
    cr = merged["otherData"]["crossrank"]
    assert cr["alignment"] == "matched_collectives"
    # median end-delta over the join: sorted [500000]*6 + [502000]*6 ->
    # 502000 (the estimator absorbed the 2000us delay into the offset)
    assert cr["offsets_us"]["1"] == -502_000.0
    rep = crossrank.attribute_crossrank(merged)
    # under-attribution, exactly as documented: rank 0 now looks late on
    # the TIED ops; the ledger still ties out, but the verdict flipped —
    # the reason wall anchors win when present
    assert rep["alignment"] == "matched_collectives"
    assert all(w["tie_out_error"] <= crossrank.TIE_OUT_TOLERANCE
               for w in rep["windows"])


def test_quantile_parity_with_tracer():
    from deepspeed_tpu.telemetry.tracer import _quantile
    samples = sorted([0.3, 1.0, 2.5, 2.5, 7.0, 9.9, 11.0])
    for q in (0.0, 0.5, 0.95, 0.99, 1.0):
        assert crossrank.quantile(samples, q) == _quantile(samples, q)


# ---------------------------------------------------------------------------
# process-identity header + op_seq stamping
# ---------------------------------------------------------------------------
def test_tracer_dump_carries_identity_header(tracing):
    tracing.set_process_identity(3, 8)
    try:
        with tracing.span("x/y"):
            pass
        dump = tracing.to_chrome()
        proc = dump["otherData"]["process"]
        assert proc["rank"] == 3 and proc["world"] == 8
        assert proc["pid"] == os.getpid()
        assert isinstance(proc["hostname"], str) and proc["hostname"]
        # a monotonic<->wall anchor PAIR stamped at dump time
        for key in ("monotonic_s", "wall_s", "epoch_monotonic_s"):
            assert isinstance(proc[key], float)
        labels = [e["args"]["name"] for e in dump["traceEvents"]
                  if e.get("ph") == "M" and e["name"] == "process_name"]
        assert labels == ["deepspeed_tpu rank3/8"]
    finally:
        tracing.set_process_identity(0, 1)


def test_guarded_ops_carry_monotonic_op_seq(tracing):
    from deepspeed_tpu.comm.guard import CommGuard, CommGuardConfig
    guard = CommGuard(CommGuardConfig(enabled=True))
    guard.run("drill", lambda: 1)
    guard.run("drill", lambda: 2)
    seqs = [e[7]["op_seq"] for e in tracing.events_snapshot()
            if e[1] == "comm/guarded/drill"]
    assert len(seqs) == 2 and seqs[1] > seqs[0]


def test_comm_instant_carries_op_seq(tracing):
    from deepspeed_tpu.comm.comms_logging import emit_comm_instant
    emit_comm_instant("all_reduce", 4096, 2, op_seq=41)
    ev = [e for e in tracing.events_snapshot()
          if e[1] == "comm/all_reduce"][-1]
    assert ev[7]["op_seq"] == 41 and ev[7]["bytes"] == 4096


# ---------------------------------------------------------------------------
# CLI: exit matrix, merge subcommand, jax-less load, --rank slice
# ---------------------------------------------------------------------------
def _run(args, **kw):
    return subprocess.run([sys.executable] + args, capture_output=True,
                          text=True, cwd=REPO, **kw)


def test_cli_clean_exit_zero():
    proc = _run([DSTPU, "plan", "--cross-rank", MERGED])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dominant straggler: rank 1" in proc.stdout
    assert "REGRESSION" not in proc.stderr


def test_cli_regression_exit_one(tmp_path):
    """Growing rank 0's waits (rank 0 becomes the late one on the front
    half) regresses its caused-wait share past tolerance+floor."""
    merged = copy.deepcopy(json.load(open(MERGED)))
    for e in merged["traceEvents"]:
        args = e.get("args") or {}
        if e.get("ph") == "M" or "op_seq" not in args:
            continue
        if args.get("rank") == 0 and args["op_seq"] <= 6:
            e["dur"] = e.get("dur", 0.0) + 5_000.0    # rank 0 ends late
    bad = tmp_path / "merged_micro.json"
    json.dump(merged, open(bad, "w"))
    proc = _run([DSTPU, "plan", "--cross-rank", str(bad),
                 "--baseline", BASELINE])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "REGRESSION" in proc.stderr
    assert "rank 0 wait_share" in proc.stderr


def test_cli_unreadable_exit_two(tmp_path):
    junk = tmp_path / "junk.json"
    junk.write_text("not json")
    proc = _run([DSTPU, "plan", "--cross-rank", str(junk)])
    assert proc.returncode == 2


def test_cli_discovered_baseline_skips_other_workload(tmp_path):
    """A differently-named merged dump must not be judged against the
    repo's merged_micro baseline via discovery (workload scoping)."""
    other = tmp_path / "other_workload.json"
    other.write_text(open(MERGED).read())
    (tmp_path / crossrank.CROSSRANK_BASELINE_NAME).write_text(
        open(BASELINE).read())
    proc = _run([DSTPU, "plan", "--cross-rank", str(other)])
    assert proc.returncode == 0
    assert "comparison skipped" in proc.stderr


def test_cli_write_baseline_and_stale_expiry(tmp_path):
    """The ratchet: write a fresh baseline, improve the workload, and the
    improvement surfaces as a STALE entry (exit 0) until re-written."""
    merged_path = tmp_path / "drill.json"
    merged_path.write_text(open(MERGED).read())
    bl = tmp_path / "bl.json"
    proc = _run([DSTPU, "plan", "--cross-rank", str(merged_path),
                 "--write-baseline", "--baseline", str(bl)])
    assert proc.returncode == 0 and bl.exists()
    improved = copy.deepcopy(json.load(open(MERGED)))
    for e in improved["traceEvents"]:
        args = e.get("args") or {}
        if e.get("ph") != "M" and "op_seq" in args:
            e["dur"] = 500.0                      # nobody is late anymore
    json.dump(improved, open(merged_path, "w"))
    proc = _run([DSTPU, "plan", "--cross-rank", str(merged_path),
                 "--baseline", str(bl)])
    assert proc.returncode == 0
    assert "stale baseline entry" in proc.stderr


def test_trace_merge_cli_roundtrip(tmp_path):
    out = tmp_path / "merged.json"
    proc = _run([DSTPU, "trace", "merge", R0, R1, "--out", str(out)])
    assert proc.returncode == 0, proc.stderr[-2000:]
    rep = crossrank.attribute_crossrank(json.load(open(out)))
    assert rep["dominant_straggler"] == 1


def test_crossrank_cli_stays_jaxless():
    """`dstpu plan --cross-rank` and `dstpu trace merge` file-load the
    stdlib-only analyzer — the jax-less contract itself is the DS009
    offline-purity rule now (crossrank.py is declared OFFLINE_ONLY; one
    subprocess keep-alive lives in test_plan.py). Here: the declaration
    plus a plain functional run of both subcommands."""
    from deepspeed_tpu.tools.dslint.hotpath import OFFLINE_ONLY_MODULES
    assert "deepspeed_tpu/telemetry/crossrank.py" in OFFLINE_ONLY_MODULES
    for args in (["plan", "--cross-rank", MERGED, "--json"],
                 ["trace", "merge", R0, R1, "--out", os.devnull]):
        proc = _run([DSTPU] + args)
        assert proc.returncode == 0, proc.stderr[-2000:]


def test_rank_filter_slices_one_rank_plus_matched_spans(tmp_path):
    from deepspeed_tpu.telemetry import report as trace_report
    events = trace_report.load_events(MERGED)
    sliced = trace_report.filter_rank(events, 1)
    pids = {e.get("pid") for e in sliced if e.get("ph") != "M"}
    assert 1 in pids and 0 in pids
    # rank 0 contributes ONLY its matched collective spans to the slice
    rank0 = [e for e in sliced if e.get("pid") == 0 and e.get("ph") != "M"]
    assert rank0 and all("op_seq" in (e.get("args") or {}) for e in rank0)
    assert not any(e.get("name") == "engine/dispatch" for e in rank0)
    # the slice stays plan-loadable and the ledger still matches
    out = tmp_path / "r1_slice.json"
    trace_report.write_slice(str(out), sliced)
    proc = _run([DSTPU, "plan", "--cross-rank", str(out), "--json"])
    assert proc.returncode == 0
    assert json.loads(proc.stdout)["dominant_straggler"] == 1
    with pytest.raises(ValueError, match="merged ranks"):
        trace_report.filter_rank(events, 9)


def test_dstpu_trace_rank_flag(tmp_path):
    out = tmp_path / "slice.json"
    proc = _run([DSTPU_TRACE, MERGED, "--rank", "0", "--out", str(out)])
    assert proc.returncode == 0, proc.stderr[-2000:]
    sliced = json.load(open(out))["traceEvents"]
    assert any(e.get("pid") == 0 for e in sliced)


# ---------------------------------------------------------------------------
# plan integration + env_report + registries
# ---------------------------------------------------------------------------
def test_merged_dump_gets_cross_rank_attribution():
    """`dstpu plan` (plain) on a merged dump: reference-rank ledger plus
    per-rank stage ledgers + the cross-rank variance section."""
    from deepspeed_tpu.telemetry import attribution
    rep = attribution.analyze_path(MERGED)
    cr = rep["cross_rank"]
    assert cr["ranks"] == [0, 1] and cr["reference_rank"] == 0
    assert cr["per_rank"]["0"]["steps_total"] == 12
    # rank 1's dispatch runs 2ms slower by construction (the exclusive
    # sweep carves the tail each dispatch span shares with the NEXT op's
    # higher-priority comm span, so the per-step p50 sits just under the
    # raw 15/17ms durations — the spread is what the section is for)
    var = cr["variance"]["dispatch"]
    assert var["slowest_rank"] == 1
    assert 1.0 < var["spread_ms"] <= 2.0
    assert cr["per_rank"]["0"]["stages"]["dispatch"]["p50_step_ms"] == 15.0
    assert cr["per_rank"]["1"]["stages"]["dispatch"]["p50_step_ms"] \
        == pytest.approx(16.29, abs=0.01)


def test_env_report_rows(tmp_path, monkeypatch):
    from deepspeed_tpu.env_report import crossrank_report
    artifact = tmp_path / "crossrank.json"
    proc = _run([DSTPU, "plan", "--cross-rank", MERGED,
                 "--out", str(artifact)])
    assert proc.returncode == 0
    monkeypatch.setenv(crossrank.CROSSRANK_ARTIFACT_ENV, str(artifact))
    rows = dict(crossrank_report())
    assert str(artifact) in rows["cross-rank"]
    assert "ranks [0, 1]" in rows["cross-rank"]
    assert "max residual skew 2000us" in rows["cross-rank"]
    assert "dominant straggler rank 1" in rows["cross-rank"]
    assert "2 ranks ratcheted" in rows["cross-rank baseline"]


def test_taint_covers_crossrank_substrate(package_callgraph, hot_reached):
    from deepspeed_tpu.tools.dslint.hotpath import OFFLINE_ONLY_MODULES
    assert "deepspeed_tpu/telemetry/crossrank.py" in OFFLINE_ONLY_MODULES
    g = package_callgraph
    for path, qn in (("deepspeed_tpu/telemetry/compiles.py",
                      "CompileWatched.__call__"),
                     ("deepspeed_tpu/comm/guard.py", "next_op_seq")):
        key = g.resolve(path, qn)
        assert key is not None, f"{qn} gone from {path}"
        assert key in hot_reached, f"{qn} fell out of the hot taint"


def test_telemetry_lazy_crossrank_reexport():
    code = (
        "import sys\n"
        "import deepspeed_tpu.telemetry as T\n"
        "assert 'deepspeed_tpu.telemetry.crossrank' not in sys.modules\n"
        "T.merge_traces\n"
        "assert 'deepspeed_tpu.telemetry.crossrank' in sys.modules\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-2000:]


# ---------------------------------------------------------------------------
# compile-event ledger
# ---------------------------------------------------------------------------
def test_watch_jit_emits_compile_instants(tracing):
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.telemetry import compiles
    fn = compiles.watch_jit(jax.jit(lambda x: x * 2), "test.double")
    before = compiles.compiles_total()
    fn(jnp.ones((3,)))                  # compile 1
    fn(jnp.ones((3,)))                  # cached
    fn(jnp.ones((2, 4)))                # compile 2 (new shape)
    assert compiles.compiles_total() - before == 2
    instants = [e[7] for e in tracing.events_snapshot()
                if e[1] == compiles.COMPILE_INSTANT]
    assert len(instants) == 2
    assert instants[0]["fn"] == "test.double"
    assert instants[0]["signature"] == "float32[3]"
    assert instants[1]["signature"] == "float32[2,4]"
    assert instants[0]["wall_ms"] > 0


def test_engine_step_zero_compiles_after_warmup(tracing):
    """The acceptance invariant bench.py asserts, proven at engine level:
    after the warm step compiled the exact shapes, further same-shape
    steps never compile."""
    import deepspeed_tpu
    from deepspeed_tpu.models.simple import SimpleModel, random_batch
    from deepspeed_tpu.telemetry import compiles
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16),
        config={"train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}},
        example_batch=random_batch(4))
    engine.train_batch(batch=random_batch(8, seed=0))      # warm/compile
    assert compiles.compiles_total() > 0
    warm_instants = len([e for e in tracing.events_snapshot()
                         if e[1] == compiles.COMPILE_INSTANT])
    assert warm_instants >= 1
    mark = compiles.compiles_total()
    for i in range(1, 3):
        engine.train_batch(batch=random_batch(8, seed=i))
    assert compiles.compiles_total() - mark == 0


@pytest.mark.serve_load
def test_bench_serve_warm_reports_zero_compiles(tracing):
    """bench_serve's proof set: a warmed run reports
    compiles_during_measurement == 0 — the 'warm the exact shapes first'
    discipline as a machine-checked counter."""
    from deepspeed_tpu.serving.bench_serve import (SCENARIOS,
                                                   build_tiny_server,
                                                   run_scenario)
    import dataclasses
    scenario = dataclasses.replace(SCENARIOS["micro"], num_requests=12,
                                   concurrency=4)
    server = build_tiny_server().start()
    try:
        report = run_scenario(server, scenario, warmup=True)
    finally:
        server.stop(drain_timeout=30.0)
    assert report["warmed"]["enabled"] and report["warmed"]["requests"] > 0
    assert report["counters"]["compiles_during_measurement"] == 0
    # conservation identities survive the warm wave (cumulative counters)
    assert report["prefix"] == {} or report["prefix"]["conservation_ok"]


# ---------------------------------------------------------------------------
# MULTICHIP drill (slow: real 2-proc gloo processes)
# ---------------------------------------------------------------------------
def _crossrank_drill_body():
    """Per-rank drill: 10 guarded 'collectives' (2ms of work) with a REAL
    cross-process reduction as the inter-op barrier; chaos comm_delay
    (50ms, every call) on rank 1 only. Rank 1's guarded spans complete
    late -> it is the straggler in every layer's verdict."""
    import os
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deepspeed_tpu.comm.guard import CommGuard, CommGuardConfig
    from deepspeed_tpu.resilience.chaos import ChaosConfig, ChaosMonkey
    from deepspeed_tpu.telemetry.tracer import get_tracer

    rank = jax.process_index()
    tracer = get_tracer()
    tracer.configure(enabled=True)
    # identity was stamped by init_distributed in the harness bootstrap
    assert tracer.process_identity()["rank"] == rank
    assert tracer.process_identity()["world"] == 2

    chaos = ChaosMonkey(ChaosConfig(comm_delay_s=0.05,
                                    comm_delay_prob=1.0)) if rank == 1 \
        else None
    guard = CommGuard(CommGuardConfig(enabled=True, op_deadline_s=60.0),
                      chaos=chaos)
    devs = jax.devices()
    mesh = Mesh(np.array(devs).reshape(len(devs)), ("data",))
    x = jax.device_put(jnp.ones((len(devs),)),
                       NamedSharding(mesh, P("data")))
    total = jax.jit(lambda v: v.sum(),
                    out_shardings=NamedSharding(mesh, P()))

    for _ in range(10):
        guard.run("drill_allreduce", lambda: time.sleep(0.002))
        # REAL cross-process barrier between ops: fetching the global sum
        # blocks until every rank dispatched — per-op lateness shows as a
        # late span END, and never accumulates past the window (tie-out)
        assert float(total(x)) == float(len(devs))
    out = os.path.join(os.environ["DSTPU_CROSSRANK_DIR"],
                       f"r{rank}.json")
    tracer.export_chrome(out)
    print(f"rank {rank} dumped ok")


@pytest.mark.slow
def test_multichip_crossrank_drill(tmp_path):
    """Acceptance (ISSUE 15): 2-proc gloo drill with chaos comm_delay on
    rank 1 — per-rank DSTPU-style dumps merge into ONE timeline (rc=0),
    `dstpu plan --cross-rank` runs rc=0, waits tie out, and rank 1 is the
    dominant straggler in BOTH the skew ledger and StragglerDetector."""
    from deepspeed_tpu.resilience.membership import StragglerDetector
    from deepspeed_tpu.testing import run_distributed

    outs = run_distributed(_crossrank_drill_body, world_size=2,
                           devices_per_process=1,
                           env={"DSTPU_CROSSRANK_DIR": str(tmp_path)})
    assert all("dumped ok" in o for o in outs)
    r0, r1 = str(tmp_path / "r0.json"), str(tmp_path / "r1.json")
    merged_path = tmp_path / "merged.json"
    proc = _run([DSTPU, "trace", "merge", r0, r1,
                 "--out", str(merged_path)])
    assert proc.returncode == 0, proc.stderr[-2000:]
    artifact = tmp_path / "crossrank.json"
    proc = _run([DSTPU, "plan", "--cross-rank", str(merged_path),
                 "--out", str(artifact), "--json"])
    assert proc.returncode == 0, proc.stderr[-2000:]

    merged = json.load(open(merged_path))
    cr = merged["otherData"]["crossrank"]
    assert cr["ranks"] == [0, 1]
    assert cr["alignment"] == "wall_anchor"       # headers on both dumps

    rep = json.load(open(artifact))
    assert rep["matched"] == 10
    assert rep["dominant_straggler"] == 1
    # rank 0 pays ~50ms per op waiting on the delayed rank
    assert rep["per_rank"]["0"]["waited_us"] > 10 * 30_000
    assert rep["per_rank"]["1"]["wait_share"] > 0.9
    # the ledger's waits sum consistently with the matched spans, and no
    # rank waits longer than its window (tie-out <= 5%)
    assert sum(c["wait_total_us"] for c in rep["collectives"]) == \
        pytest.approx(rep["wait_total_us"])
    assert rep["tie_out_violations"] == []
    # StragglerDetector verdict == ledger verdict (per-op durations from
    # the SAME matched spans; lower-median rule makes 2 ranks judgeable)
    matched = crossrank.matched_collectives(merged)
    det = StragglerDetector(factor=3.0, min_s=0.01)
    flagged = []
    for seq, rec in sorted(matched.items()):
        flagged.extend(det.observe(
            f"drill@{seq}",
            {r: v["dur_us"] / 1e6 for r, v in rec["ranks"].items()}))
    assert flagged and set(flagged) == {1}
