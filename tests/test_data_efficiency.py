"""Data-efficiency tests: curriculum scheduler/sampler, analyzer, indexed dataset,
random-LTD.

Reference analog: tests/unit/runtime/test_data_efficiency.py +
data_pipeline behavior (curriculum_scheduler.py, data_sampler.py,
data_routing/).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.data_pipeline import (
    CurriculumDataSampler, CurriculumScheduler, DataAnalyzer, MMapIndexedDataset,
    MMapIndexedDatasetBuilder, RandomLTDScheduler, gather_tokens,
    random_ltd_layer, sample_token_indices, scatter_tokens)
from deepspeed_tpu.models.simple import SimpleModel, random_batch


# ---------------------------------------------------------------- curriculum
def test_fixed_linear_schedule():
    s = CurriculumScheduler({
        "schedule_type": "fixed_linear", "min_difficulty": 8, "max_difficulty": 64,
        "schedule_config": {"total_curriculum_step": 100, "difficulty_step": 8}})
    assert s.update_difficulty(0) == 8
    mid = s.update_difficulty(50)
    assert 8 < mid < 64 and mid % 8 == 0
    assert s.update_difficulty(100) == 64
    assert s.update_difficulty(1000) == 64  # clamped past total


def test_fixed_root_schedule_monotone():
    s = CurriculumScheduler({
        "schedule_type": "fixed_root", "min_difficulty": 8, "max_difficulty": 128,
        "schedule_config": {"total_curriculum_step": 200, "difficulty_step": 8,
                            "root_degree": 2}})
    vals = [s.update_difficulty(t) for t in range(0, 201, 10)]
    assert vals == sorted(vals)
    assert vals[0] == 8 and vals[-1] == 128
    # sqrt schedule reaches half-way difficulty well before half the steps
    assert s.get_difficulty(50) > 8 + (128 - 8) * 50 / 200


def test_fixed_discrete_schedule():
    s = CurriculumScheduler({
        "schedule_type": "fixed_discrete", "min_difficulty": 1, "max_difficulty": 3,
        "schedule_config": {"difficulty": [1, 2, 3], "max_step": [5, 10]}})
    assert s.get_difficulty(0) == 1
    assert s.get_difficulty(5) == 2
    assert s.get_difficulty(9) == 2
    assert s.get_difficulty(10) == 3
    assert s.get_difficulty(99) == 3


def test_custom_schedule_and_state_roundtrip():
    s = CurriculumScheduler({"schedule_type": "custom", "min_difficulty": 1,
                             "max_difficulty": 10})
    s.set_custom_get_difficulty(lambda step: min(10, 1 + step))
    assert s.update_difficulty(3) == 4
    state = s.state_dict()
    s2 = CurriculumScheduler({"schedule_type": "custom", "min_difficulty": 1,
                              "max_difficulty": 10})
    s2.load_state_dict(state)
    assert s2.get_current_difficulty() == 4


# ---------------------------------------------------------------- sampler
def _sampler(n=256, gbs=16, difficulty_type="value"):
    seqlens = np.arange(n) % 64 + 1  # difficulty 1..64
    cfg = {"seqlen": {
        "schedule_type": "fixed_linear", "min_difficulty": 8, "max_difficulty": 64,
        "difficulty_type": difficulty_type,
        "schedule_config": {"total_curriculum_step": 20, "difficulty_step": 8}}}
    return seqlens, CurriculumDataSampler(
        metric_values={"seqlen": seqlens}, metric_configs=cfg,
        total_samples=n, global_batch_size=gbs, seed=7)


def test_sampler_honors_difficulty():
    seqlens, sampler = _sampler()
    first = sampler.get_next_global_batch()
    assert len(first) == 16
    assert (seqlens[first] <= 8).all()  # step 0: only easy samples
    for _ in range(30):
        batch = sampler.get_next_global_batch()
    assert (seqlens[batch] <= 64).all()
    # after the schedule completes, hard samples do appear
    assert (seqlens[batch] > 8).any()


def test_sampler_percentile_mode():
    n = 100
    vals = np.linspace(0, 1000, n)
    sampler = CurriculumDataSampler(
        metric_values={"m": vals},
        metric_configs={"m": {
            "schedule_type": "fixed_discrete", "difficulty_type": "percentile",
            "min_difficulty": 10, "max_difficulty": 100,
            "schedule_config": {"difficulty": [10, 100], "max_step": [5]}}},
        total_samples=n, global_batch_size=5, seed=0)
    batch = sampler.get_next_global_batch()
    # 10th percentile → only the 10 smallest values are admitted
    assert (vals[batch] <= vals[9]).all()


def test_sampler_deterministic_and_resumable():
    _, a = _sampler()
    _, b = _sampler()
    for _ in range(3):
        assert (a.get_next_global_batch() == b.get_next_global_batch()).all()
    state = a.state_dict()
    next_a = a.get_next_global_batch()
    b.load_state_dict(state)
    assert (next_a == b.get_next_global_batch()).all()


def test_sampler_epoch_reset_covers_pool():
    n, gbs = 32, 16
    vals = np.ones(n)
    sampler = CurriculumDataSampler(
        metric_values={"m": vals},
        metric_configs={"m": {"schedule_type": "fixed_discrete",
                              "min_difficulty": 1, "max_difficulty": 1,
                              "schedule_config": {"difficulty": [1], "max_step": []}}},
        total_samples=n, global_batch_size=gbs, seed=3)
    seen = np.concatenate([sampler.get_next_global_batch() for _ in range(2)])
    assert len(np.unique(seen)) == n  # one full epoch, no repeats


# ---------------------------------------------------------------- analyzer
def test_data_analyzer_map_reduce(tmp_path):
    data = [np.arange(i % 7 + 1) for i in range(50)]
    for w in range(2):
        DataAnalyzer(data, {"seqlen": len}, str(tmp_path), worker_id=w,
                     num_workers=2, batch_size=8).run_map()
    DataAnalyzer(data, {"seqlen": len}, str(tmp_path), num_workers=2).run_reduce()
    vals = DataAnalyzer.load_metric(str(tmp_path), "seqlen")
    assert vals.shape == (50,)
    assert (vals == np.array([len(d) for d in data])).all()


# ---------------------------------------------------------------- indexed dataset
def test_indexed_dataset_roundtrip(tmp_path):
    prefix = str(tmp_path / "tokens")
    builder = MMapIndexedDatasetBuilder(prefix, dtype=np.int32)
    seqs = [np.arange(n, dtype=np.int32) * 3 for n in (5, 1, 9, 4)]
    for s in seqs:
        builder.add_item(s)
    builder.finalize()

    assert MMapIndexedDataset.exists(prefix)
    ds = MMapIndexedDataset(prefix)
    assert len(ds) == 4
    for got, want in zip(list(ds[:4]), seqs):
        assert (np.asarray(got) == want).all()
    assert (ds.get(2, offset=2, length=3) == np.array([6, 9, 12])).all()


def test_indexed_dataset_merge(tmp_path):
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    for prefix, vals in ((a, [1, 2]), (b, [3],)):
        builder = MMapIndexedDatasetBuilder(prefix, dtype=np.int64)
        for v in vals:
            builder.add_item(np.full(v, v, dtype=np.int64))
        builder.finalize()
    merged = MMapIndexedDatasetBuilder(str(tmp_path / "m"), dtype=np.int64)
    merged.merge_file(a)
    merged.merge_file(b)
    merged.finalize()
    ds = MMapIndexedDataset(str(tmp_path / "m"))
    assert len(ds) == 3 and (np.asarray(ds[2]) == 3).all()


# ---------------------------------------------------------------- random-LTD
def test_random_ltd_scheduler_annealing():
    sched = RandomLTDScheduler({
        "total_layer_num": 12, "random_ltd_layer_num": 10, "global_batch_size": 4,
        "random_ltd_schedule": {
            "min_value": 128, "max_value": 512, "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 100, "difficulty_step": 16}}})
    assert sched.get_current_seq() == 128
    sched.update_seq(50)
    assert 128 < sched.get_current_seq() < 512
    sched.update_seq(100)
    assert sched.get_current_seq() == 512
    # token accounting grows monotonically and counts non-LTD layers at full seq
    total = sched.get_total_layer_tokens(10)
    assert total > 0
    state = sched.state_dict()
    sched2 = RandomLTDScheduler({
        "total_layer_num": 12, "random_ltd_layer_num": 10,
        "random_ltd_schedule": {"min_value": 128, "max_value": 512,
                                "schedule_type": "fixed_linear",
                                "schedule_config": {"total_curriculum_step": 100}}})
    sched2.load_state_dict(state)
    assert sched2.get_current_seq() == sched.get_current_seq()


def test_gather_scatter_inverse():
    rng = jax.random.PRNGKey(0)
    h = jax.random.normal(rng, (2, 16, 8))
    idx = sample_token_indices(rng, 2, 16, 6, decoder=True)
    assert idx.shape == (2, 6)
    # decoder indices sorted → causal order preserved
    assert (jnp.diff(idx, axis=-1) > 0).all()
    part = gather_tokens(h, idx)
    assert part.shape == (2, 6, 8)
    back = scatter_tokens(h, part, idx)
    np.testing.assert_allclose(back, h, rtol=1e-6)


def test_random_ltd_layer_identity_outside_subset():
    rng = jax.random.PRNGKey(1)
    h = jax.random.normal(rng, (2, 12, 4))
    out = random_ltd_layer(lambda x: x + 100.0, h, rng, reserved=5)
    changed = np.abs(np.asarray(out - h)).sum(axis=-1) > 1.0
    assert changed.sum() == 2 * 5  # exactly `reserved` tokens per example touched
    # reserved >= seq → layer applied to everything
    out_full = random_ltd_layer(lambda x: x + 100.0, h, rng, reserved=12)
    np.testing.assert_allclose(out_full, h + 100.0)


def test_random_ltd_layer_jit_and_grad():
    rng = jax.random.PRNGKey(2)
    h = jax.random.normal(rng, (2, 8, 4))
    w = jnp.ones((4, 4)) * 0.5

    @jax.jit
    def loss(w, h):
        out = random_ltd_layer(lambda x: x @ w, h, rng, reserved=3)
        return (out ** 2).sum()

    g = jax.grad(loss)(w, h)
    assert jnp.isfinite(g).all() and (jnp.abs(g) > 0).any()


# ---------------------------------------------------------------- engine wiring
def test_engine_curriculum_integration():
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "curriculum_learning": {
            "enabled": True, "curriculum_type": "seqlen",
            "min_difficulty": 2, "max_difficulty": 8,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 4, "difficulty_step": 2}},
        "data_efficiency": {
            "enabled": True,
            "data_routing": {"enabled": True, "random_ltd": {
                "enabled": True, "total_layer_num": 2, "random_ltd_layer_num": 1,
                "random_ltd_schedule": {
                    "min_value": 4, "max_value": 16, "schedule_type": "fixed_linear",
                    "schedule_config": {"total_curriculum_step": 4,
                                        "difficulty_step": 4}}}}},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16), config=config,
        example_batch=random_batch(4))
    assert engine.curriculum_seqlen() == 2
    assert engine.random_ltd_reserved_length() == 4
    for i in range(5):
        engine.train_batch(batch=random_batch(8, seed=i))
    assert engine.curriculum_seqlen() == 8
    assert engine.random_ltd_reserved_length() == 16


@pytest.mark.slow
def test_curriculum_state_resyncs_on_checkpoint_load(tmp_path):
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "curriculum_learning": {
            "enabled": True, "min_difficulty": 2, "max_difficulty": 8,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 4, "difficulty_step": 2}},
        "data_efficiency": {
            "enabled": True,
            "data_routing": {"enabled": True, "random_ltd": {
                "enabled": True, "total_layer_num": 2, "random_ltd_layer_num": 1,
                "random_ltd_schedule": {
                    "min_value": 4, "max_value": 16, "schedule_type": "fixed_linear",
                    "schedule_config": {"total_curriculum_step": 4,
                                        "difficulty_step": 4}}}}},
    }

    def build():
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=SimpleModel(hidden_dim=16), config=config,
            example_batch=random_batch(4))
        return engine

    engine = build()
    for i in range(5):
        engine.train_batch(batch=random_batch(8, seed=i))
    engine.save_checkpoint(str(tmp_path))
    consumed = engine.random_ltd_scheduler.consumed_layer_tokens

    fresh = build()
    assert fresh.curriculum_seqlen() == 2  # pre-load: schedules at min
    fresh.load_checkpoint(str(tmp_path))
    assert fresh.global_steps == 5
    assert fresh.curriculum_seqlen() == 8
    assert fresh.random_ltd_reserved_length() == 16
    assert fresh.random_ltd_scheduler.consumed_layer_tokens == consumed


def test_data_analyzer_mmap_merge_and_value_map(tmp_path):
    """Reduce streams shards into an mmap-backed sample_values (no in-RAM
    concat) and builds the CSR metric->sample map (reference
    metric_to_sample_dict, data_analyzer.py)."""
    rng = np.random.default_rng(0)
    lens = rng.integers(3, 8, size=101)
    data = [list(range(n)) for n in lens]
    for w in range(3):
        DataAnalyzer(data, {"seqlen": len}, str(tmp_path), worker_id=w,
                     num_workers=3, batch_size=7).run_map()
    DataAnalyzer(data, {"seqlen": len}, str(tmp_path), num_workers=3,
                 batch_size=7).run_reduce()
    vals = DataAnalyzer.load_metric(str(tmp_path), "seqlen", mmap=True)
    assert isinstance(vals, np.memmap)
    np.testing.assert_array_equal(np.asarray(vals), lens.astype(np.float64))
    order = np.load(tmp_path / "seqlen" / "index_to_sample.npy")
    assert np.all(np.diff(np.asarray(vals)[order]) >= 0)
    for v in (3, 5, 7):
        ids = DataAnalyzer.samples_with_value(str(tmp_path), "seqlen", v)
        np.testing.assert_array_equal(np.sort(ids), np.flatnonzero(lens == v))
    assert DataAnalyzer.samples_with_value(
        str(tmp_path), "seqlen", 99).size == 0


def test_data_analyzer_accumulate_metric(tmp_path):
    """accumulate_value_over_samples: workers write partial vectors, reduce
    sums them (reference metric_type, e.g. vocabulary counts)."""
    data = [[t] * (i % 4 + 1) for i, t in
            enumerate([1, 0, 2, 1, 1, 0, 2, 2, 2, 0])]

    def vocab_counts(sample):
        c = np.zeros(3)
        for t in sample:
            c[t] += 1
        return c

    kw = dict(metric_functions={"counts": vocab_counts},
              metric_types={"counts": "accumulate_value_over_samples"},
              save_path=str(tmp_path))
    for w in range(2):
        DataAnalyzer(data, worker_id=w, num_workers=2, **kw).run_map()
    DataAnalyzer(data, num_workers=2, **kw).run_reduce()
    got = DataAnalyzer.load_metric(str(tmp_path), "counts")
    want = np.zeros(3)
    for s in data:
        want += vocab_counts(s)
    np.testing.assert_array_equal(got, want)


def _analyzer_distributed_body():
    """2-process run_map_reduce with the cross-host barrier (reference:
    distributed map/reduce over torch.distributed)."""
    import os

    import numpy as np

    from deepspeed_tpu.data_pipeline import DataAnalyzer

    data = [list(range(n)) for n in (np.arange(40) % 6 + 2)]
    an = DataAnalyzer(data, {"seqlen": len},
                      os.environ["DSTPU_TEST_ANALYZER_DIR"], batch_size=7)
    an.run_map_reduce()
    vals = DataAnalyzer.load_metric(os.environ["DSTPU_TEST_ANALYZER_DIR"],
                                    "seqlen")
    np.testing.assert_array_equal(vals, (np.arange(40) % 6 + 2).astype(float))
    print("analyzer distributed ok")


@pytest.mark.slow
def test_data_analyzer_distributed_map_reduce(tmp_path):
    from deepspeed_tpu.testing import run_distributed
    outs = run_distributed(_analyzer_distributed_body, world_size=2,
                           devices_per_process=1,
                           env={"DSTPU_TEST_ANALYZER_DIR": str(tmp_path)})
    assert all("analyzer distributed ok" in o for o in outs)


def test_data_analyzer_empty_trailing_worker(tmp_path):
    """num_workers whose ceil-division overshoots the dataset: trailing
    workers have empty ranges and must produce valid (empty) shards for
    both metric types — reduce still merges correctly."""
    data = [[0] * n for n in (3, 4, 5, 6, 7)]   # n=5, 4 workers -> per=2

    def counts(sample):
        c = np.zeros(2)
        c[len(sample) % 2] += 1
        return c

    kw = dict(metric_functions={"seqlen": len, "counts": counts},
              metric_types={"counts": "accumulate_value_over_samples"},
              save_path=str(tmp_path))
    for w in range(4):
        DataAnalyzer(data, worker_id=w, num_workers=4, **kw).run_map()
    DataAnalyzer(data, num_workers=4, **kw).run_reduce()
    np.testing.assert_array_equal(
        DataAnalyzer.load_metric(str(tmp_path), "seqlen"),
        [3.0, 4.0, 5.0, 6.0, 7.0])
    np.testing.assert_array_equal(
        DataAnalyzer.load_metric(str(tmp_path), "counts"), [2.0, 3.0])
