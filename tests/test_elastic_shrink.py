"""Elastic shrink-to-survive: mesh-portable checkpoint resume +
world-size-aware relaunch.

Covers the PR-12 tentpole end to end:

  - resharding round-trip parity: save@N -> load@M -> save@M -> load@N is
    bit-identical for params AND optimizer state (the parameter-atomic
    store is the reshard substrate)
  - ds_meta.json provenance: recorded on save, rendered by
    ``dstpu_ckpt inspect``, checked on load — a different model or a
    broken sampler contract is a CLASSIFIED error, never a shape crash
  - optimizer state survives offload-ladder tier changes in both
    directions (optax -> host moments on escalation; host npz -> optax
    graft on de-escalation)
  - the rng stream resumes exactly (recorded key, world-independent)
  - agent shrink accounting: membership-verdict shrink at world-1, budget
    untouched, min_world floor refusal, regrow when capacity returns,
    ledger-preflight ladder escalation exported to workers
  - chaos: the permanent peer-dead variant survives DSTPU_RESUME
  - the acceptance drill (real subprocesses): permanent kill -> membership
    lost -> autosave/exit 75 -> shrink relaunch at world-1 -> losses
    bit-identical to a from-checkpoint baseline at the smaller world, the
    whole episode reconstructable from elastic/ trace instants
"""

import json
import os
import shutil
import sys
import time

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.checkpoint.engine import CheckpointProvenanceError
from deepspeed_tpu.checkpoint.universal import compat_check, inspect_checkpoint
from deepspeed_tpu.comm.mesh import create_mesh
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.elasticity import ElasticAgent, WorkerSpec
from deepspeed_tpu.models.simple import SimpleModel, random_batch
from deepspeed_tpu.resilience import ChaosConfig, ChaosMonkey
from deepspeed_tpu.telemetry import get_tracer

pytestmark = pytest.mark.chaos

CFG = {"train_batch_size": 8,
       "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}}


@pytest.fixture
def tracing():
    t = get_tracer()
    t.clear()
    t.detach_sink()
    t.configure(enabled=True)
    try:
        yield t
    finally:
        t.configure(enabled=False)
        t.detach_sink()
        t.clear()


def _engine(config=None, mesh=None, seed=1, hidden=64):
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=hidden), config=dict(config or CFG),
        mesh=mesh, example_batch=random_batch(4), seed=seed)
    return engine


def _host_tree(tree):
    return [np.asarray(jax.device_get(x))
            for x in jax.tree_util.tree_leaves(tree)]


# ---------------------------------------------------------------------------
# mesh-portable resume
# ---------------------------------------------------------------------------
def test_reshard_roundtrip_parity(tmp_path):
    """save@8 (zero-3, data=2 x fsdp=4) -> load@4 (zero-1, data=2 x fsdp=2)
    -> save@4 -> load@8: params AND optimizer state bit-identical after the
    full round trip."""
    cfg_a = dict(CFG); cfg_a["zero_optimization"] = {"stage": 3}
    e1 = _engine(cfg_a, create_mesh(MeshConfig(data=2, fsdp=4)), seed=1)
    for i in range(3):
        e1.train_batch(batch=random_batch(8, seed=i))
    d1 = str(tmp_path / "w8")
    e1.save_checkpoint(d1)
    want_params = _host_tree(e1.state.params)
    want_opt = _host_tree(e1.state.opt_state)

    cfg_b = dict(CFG); cfg_b["zero_optimization"] = {"stage": 1}
    mesh4 = create_mesh(MeshConfig(data=2, fsdp=2), devices=jax.devices()[:4])
    e2 = _engine(cfg_b, mesh4, seed=77)
    e2.load_checkpoint(d1)
    for a, b in zip(want_params, _host_tree(e2.state.params)):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(want_opt, _host_tree(e2.state.opt_state)):
        np.testing.assert_array_equal(a, b)
    assert e2.global_steps == 3

    d2 = str(tmp_path / "w4")
    e2.save_checkpoint(d2)
    e3 = _engine(cfg_a, create_mesh(MeshConfig(data=2, fsdp=4)), seed=99)
    e3.load_checkpoint(d2)
    for a, b in zip(want_params, _host_tree(e3.state.params)):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(want_opt, _host_tree(e3.state.opt_state)):
        np.testing.assert_array_equal(a, b)

    # training continues bit-identically at the original world
    l1 = float(e1.train_batch(batch=random_batch(8, seed=50)))
    l3 = float(e3.train_batch(batch=random_batch(8, seed=50)))
    assert abs(l1 - l3) < 1e-6


def test_rng_stream_restored_on_resume(tmp_path):
    e1 = _engine(seed=1)
    e1.train_batch(batch=random_batch(8, seed=0))
    e1.save_checkpoint(str(tmp_path))
    want = np.asarray(jax.device_get(e1._rng))
    e2 = _engine(seed=12345)   # different init seed -> different live key
    assert not np.array_equal(want, np.asarray(jax.device_get(e2._rng)))
    e2.load_checkpoint(str(tmp_path))
    np.testing.assert_array_equal(want, np.asarray(jax.device_get(e2._rng)))


def test_provenance_recorded_and_inspected(tmp_path):
    mesh = create_mesh(MeshConfig(data=2, fsdp=4))
    cfg = dict(CFG); cfg["zero_optimization"] = {"stage": 3}
    e = _engine(cfg, mesh, seed=1)
    e.train_batch(batch=random_batch(8, seed=0))
    e.save_checkpoint(str(tmp_path))

    with open(tmp_path / "global_step1" / "ds_meta.json") as f:
        prov = json.load(f)["provenance"]
    assert prov["version"] == 1
    assert prov["world"]["device_count"] == 8
    assert prov["mesh"]["fsdp"] == 4
    assert prov["zero"]["stage"] == 3 and prov["zero"]["zero_world"] == 4
    assert prov["batch"]["train_batch_size"] == 8
    assert prov["sampler"]["consumed_samples"] == 8
    assert "train_batch_size invariant" in prov["sampler"]["contract"]
    assert prov["params"]["count"] == e._param_count()
    assert prov["rng"]["shape"] and prov["rng"]["data"]
    assert prov["config"]["zero_optimization"] == {"stage": 3}

    info = inspect_checkpoint(str(tmp_path))
    summary = info["provenance"]
    assert summary["saved_world"]["device_count"] == 8
    assert summary["mesh_axes"] == {"data": 2, "fsdp": 4}
    assert summary["zero"]["stage"] == 3
    assert summary["step"] == 1
    assert summary["sampler"]["consumed_samples"] == 8
    assert summary["rng_key"]["shape"] == prov["rng"]["shape"]


def test_compat_check_reports_feasibility(tmp_path):
    e = _engine(seed=1)
    e.train_batch(batch=random_batch(8, seed=0))
    e.save_checkpoint(str(tmp_path))
    ok = compat_check(str(tmp_path), world=4)
    assert ok["feasible"] and ok["checks"]["batch"]["ok"]
    assert ok["checks"]["ledger"]["ok"]
    bad = compat_check(str(tmp_path), world=3)   # 8 % 3 != 0
    assert not bad["feasible"] and not bad["checks"]["batch"]["ok"]
    # the CLI form: exit 0 feasible / 1 infeasible, with --compat in JSON
    from deepspeed_tpu.checkpoint.universal import main as ckpt_main
    assert ckpt_main(["inspect", str(tmp_path), "--compat", "4"]) == 0
    assert ckpt_main(["inspect", str(tmp_path), "--compat", "3"]) == 1


def test_provenance_mismatch_is_classified_error(tmp_path):
    e = _engine(seed=1, hidden=64)
    e.train_batch(batch=random_batch(8, seed=0))
    e.save_checkpoint(str(tmp_path))
    # different model -> classified, names the differing leaves, never an
    # orbax shape crash
    other = _engine(seed=2, hidden=32)
    with pytest.raises(CheckpointProvenanceError, match="different model"):
        other.load_checkpoint(str(tmp_path))
    # changed global batch breaks the sampler contract...
    cfg = dict(CFG); cfg["train_batch_size"] = 16
    bigger = _engine(cfg, seed=3)
    with pytest.raises(CheckpointProvenanceError, match="sampler contract"):
        bigger.load_checkpoint(str(tmp_path))
    # ...unless deliberately overridden
    path, _ = bigger.load_checkpoint(str(tmp_path), strict_provenance=False)
    assert path is not None and bigger.global_steps == 1


def test_offload_escalation_preserves_optimizer_state(tmp_path):
    """The ladder escalates on shrink (optax -> host-offload): moments are
    adopted bit-identically; de-escalation (offload ckpt -> optax engine)
    grafts them back."""
    e1 = _engine(seed=1, mesh=create_mesh(MeshConfig(data=8)))
    for i in range(3):
        e1.train_batch(batch=random_batch(8, seed=i))
    d1 = str(tmp_path / "optax")
    e1.save_checkpoint(d1)
    mu = _host_tree(e1.state.opt_state[0].mu)
    nu = _host_tree(e1.state.opt_state[0].nu)

    cfg = dict(CFG)
    cfg["zero_optimization"] = {"stage": 1,
                                "offload_optimizer": {"device": "cpu"}}
    mesh4 = create_mesh(MeshConfig(data=4), devices=jax.devices()[:4])
    e2 = _engine(cfg, mesh4, seed=9)
    e2.load_checkpoint(d1)
    got = [e2._offload._materialized_states(l) for l in e2._offload.leaves]
    for (m, n), wm, wn in zip(got, mu, nu):
        np.testing.assert_array_equal(m, wm)
        np.testing.assert_array_equal(n, wn)
    assert e2._offload.kernel.step_count == 3
    e2.train_batch(batch=random_batch(8, seed=50))   # trains at the new tier

    d2 = str(tmp_path / "offload")
    e2.save_checkpoint(d2)
    e3 = _engine(seed=4, mesh=create_mesh(MeshConfig(data=8)))
    e3.load_checkpoint(d2)
    got_mu = _host_tree(e3.state.opt_state[0].mu)
    want_mu = [e2._offload._materialized_states(l)[0]
               for l in e2._offload.leaves]
    for a, b in zip(got_mu, want_mu):
        np.testing.assert_array_equal(a, b)
    assert int(jax.device_get(e3.state.opt_state[0].count)) == 4
    e3.train_batch(batch=random_batch(8, seed=60))


# ---------------------------------------------------------------------------
# chaos: permanent peer death
# ---------------------------------------------------------------------------
def test_chaos_peer_dead_permanent_survives_resume(monkeypatch):
    cfg = ChaosConfig.from_env({"DSTPU_CHAOS_PEER_DEAD_RANKS": "1",
                                "DSTPU_CHAOS_PEER_DEAD_PERMANENT_RANKS": "2"})
    assert cfg.active
    monkey = ChaosMonkey(cfg)
    monkeypatch.delenv("DSTPU_RESUME", raising=False)
    assert monkey.peer_dead(1) and monkey.peer_dead(2)
    assert not monkey.peer_dead(0)
    # a DSTPU_RESUME relaunch spares the once-set (transient loss drill)
    # but the permanent set stays dead — the shrink drill's determinism
    monkeypatch.setenv("DSTPU_RESUME", "latest")
    assert not monkey.peer_dead(1)
    assert monkey.peer_dead(2)


def test_chaos_permanent_silence_keeps_membership_stale(tmp_path,
                                                        monkeypatch):
    from deepspeed_tpu.resilience import Heartbeat, MembershipView
    monkeypatch.setenv("DSTPU_RESUME", "latest")    # relaunched worker
    monkey = ChaosMonkey(ChaosConfig(peer_dead_permanent_ranks=frozenset({3})))
    hb = Heartbeat(3, str(tmp_path), interval_s=0.02, chaos=monkey,
                   listen_comm_ops=False).start()
    time.sleep(0.1)
    hb.stop()
    view = MembershipView(str(tmp_path), lost_after_s=0.2,
                          expected_ranks=[3])
    time.sleep(0.25)
    assert view.lost_peers() == [3]      # never published, even on resume


# ---------------------------------------------------------------------------
# agent shrink accounting (scripted processes, real membership files)
# ---------------------------------------------------------------------------
class _Proc:
    def __init__(self, codes):
        self.codes = list(codes)
        self.last = None

    def poll(self):
        if self.codes:
            self.last = self.codes.pop(0)
        return self.last

    def terminate(self):
        pass

    def wait(self, timeout=None):
        return self.last

    def kill(self):
        pass


def _write_peer(members, rank, age=0.0):
    p = os.path.join(members, f"rank_{rank}.json")
    with open(p, "w") as f:
        json.dump({"rank": rank, "pid": 1, "ts": time.time() - age,
                   "beat": 3}, f)
    if age:
        t = time.time() - age
        os.utime(p, (t, t))


def _shrink_cfg(**over):
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 64,
                          "micro_batch_sizes": [2, 4], "min_gpus": 1,
                          "max_gpus": 8, "version": 0.1,
                          "shrink_on_peer_loss": True, "min_world_size": 1,
                          "rejoin_grace_s": 0.2}}
    cfg["elasticity"].update(over.pop("elasticity", {}))
    cfg.update(over)
    return cfg


def _spec(tmp_path, members, **kw):
    kw.setdefault("max_restarts", 0)
    kw.setdefault("monitor_interval_s", 0.01)
    kw.setdefault("term_grace_s", 0.05)
    kw.setdefault("restart_backoff_s", 0.0)
    kw.setdefault("membership_dir", str(members))
    kw.setdefault("lost_after_s", 5.0)
    kw.setdefault("status_path", str(tmp_path / "elastic_status.json"))
    return WorkerSpec(cmd=["x"], **kw)


def test_agent_shrinks_on_permanent_peer_loss(tmp_path, tracing):
    members = tmp_path / "members"
    members.mkdir()
    launches = []

    def popen(cmd, env=None):
        launches.append(env)
        if int(env["DSTPU_ELASTIC_RESTART"]) == 0:
            # rank 0 survives (exits 75, classified); rank 1 is the dead
            # chip (SIGKILL-shaped exit + stale heartbeat)
            _write_peer(str(members), 0, age=0.0)
            _write_peer(str(members), 1, age=60.0)
            return _Proc([None, 75]) if env["DSTPU_PROCESS_ID"] == "0" \
                else _Proc([None, -9])
        return _Proc([0])

    agent = ElasticAgent(_spec(tmp_path, members), _shrink_cfg(),
                         host_provider=lambda: ["h0", "h1"], popen=popen)
    assert agent.run() == 0
    # shrunk generation: world 1, resume env set, budget untouched
    assert launches[-1]["DSTPU_NUM_PROCESSES"] == "1"
    assert launches[-1]["DSTPU_RESUME"] == "latest"
    assert agent.crash_restarts == 0
    assert [(e["type"], e["from_world"], e["to_world"])
            for e in agent.shrink_events] == [("shrink", 2, 1)]
    # corpse heartbeat cleaned so the shrunk generation can't wedge on it
    assert not (members / "rank_1.json").exists()
    # status artifact carries the episode
    with open(tmp_path / "elastic_status.json") as f:
        st = json.load(f)
    assert st["current_world"] == 1 and st["target_world"] == 2
    assert st["last_event"]["type"] == "shrink"
    # timeline: peer_lost then shrink_planned, in order
    names = [e[1] for e in tracing.events_snapshot()]
    assert "elastic/peer_lost" in names and "elastic/shrink_planned" in names
    assert names.index("elastic/peer_lost") < \
        names.index("elastic/shrink_planned")


def test_agent_refuses_shrink_below_min_world(tmp_path):
    members = tmp_path / "members"
    members.mkdir()

    def popen(cmd, env=None):
        _write_peer(str(members), 0, age=0.0)
        _write_peer(str(members), 1, age=60.0)
        return _Proc([None, 75]) if env["DSTPU_PROCESS_ID"] == "0" \
            else _Proc([None, -9])

    agent = ElasticAgent(
        _spec(tmp_path, members),
        _shrink_cfg(elasticity={"min_world_size": 2}),
        host_provider=lambda: ["h0", "h1"], popen=popen)
    rc = agent.run()
    assert rc == 75                      # classified, not a success
    assert agent.crash_restarts == 0     # still not charged as a crash
    assert agent.shrink_events[-1]["type"] == "shrink_refused"


def test_agent_regrows_when_capacity_returns(tmp_path):
    members = tmp_path / "members"
    members.mkdir()
    launches = []

    def popen(cmd, env=None):
        launches.append(env)
        gen = int(env["DSTPU_ELASTIC_RESTART"])
        if gen == 0:
            _write_peer(str(members), 0, age=0.0)
            _write_peer(str(members), 1, age=60.0)
            return _Proc([None, 75]) if env["DSTPU_PROCESS_ID"] == "0" \
                else _Proc([None, -9])
        if gen == 1:
            # shrunk world-1 generation runs healthy; meanwhile the lost
            # rank's heartbeat comes back (node rebooted into the pool)
            _write_peer(str(members), 0, age=0.0)
            _write_peer(str(members), 1, age=0.0)
            return _Proc([None] * 400)
        return _Proc([0])

    agent = ElasticAgent(_spec(tmp_path, members), _shrink_cfg(),
                         host_provider=lambda: ["h0", "h1"], popen=popen)
    assert agent.run() == 0
    worlds = [env["DSTPU_NUM_PROCESSES"] for env in launches]
    # gen0: 2 workers; gen1: 1 (shrunk); gen2: 2 again (regrown)
    assert worlds == ["2", "2", "1", "2", "2"]
    types = [e["type"] for e in agent.shrink_events]
    assert types == ["shrink", "regrow"]
    assert agent.crash_restarts == 0


def test_agent_preflight_escalates_ladder_and_exports_overrides(tmp_path):
    members = tmp_path / "members"
    members.mkdir()
    ck = tmp_path / "ckpt"
    (ck / "tag7").mkdir(parents=True)
    (ck / "latest").write_text("tag7")
    # 7B fp32 adam at 16GB chips: world 4 needs the full ladder
    raw = {"train_batch_size": 64,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}}
    with open(ck / "tag7" / "ds_meta.json", "w") as f:
        json.dump({"provenance": {
            "params": {"count": 7_000_000_000},
            "ledger": {"bytes_limit": 16 << 30},
            "world": {"process_count": 8, "device_count": 8},
            "config": raw}}, f)
    launches = []

    def popen(cmd, env=None):
        launches.append(env)
        if int(env["DSTPU_ELASTIC_RESTART"]) == 0:
            for r in range(5):
                _write_peer(str(members), r, age=0.0)
            for r in range(5, 8):
                _write_peer(str(members), r, age=60.0)
            # ranks 0-4 survive and classify (75); 5-7 are the lost chips
            return _Proc([None, 75]) if int(env["DSTPU_PROCESS_ID"]) < 5 \
                else _Proc([None, -9])
        return _Proc([0])

    agent = ElasticAgent(
        _spec(tmp_path, members, ckpt_dir=str(ck)),
        _shrink_cfg(), host_provider=lambda: ["h"] * 8, popen=popen)
    assert agent.run() == 0
    # 5 chips survive but the elastic batch only factors at 4 — the agent
    # shrinks to the largest COMPATIBLE world
    assert launches[-1]["DSTPU_NUM_PROCESSES"] == "4"
    # preflight recorded the ladder and exported the escalated overrides
    assert agent.last_preflight["world"] == 4
    assert agent.last_preflight["escalations"]
    overrides = json.loads(launches[-1]["DSTPU_ELASTIC_CONFIG_OVERRIDES"])
    assert overrides["zero_optimization"]
    with open(tmp_path / "elastic_status.json") as f:
        assert json.load(f)["preflight"]["escalations"]


def test_elastic_overrides_env_merges_into_config(monkeypatch):
    from deepspeed_tpu.config.config import DeepSpeedTPUConfig
    monkeypatch.setenv(
        "DSTPU_ELASTIC_CONFIG_OVERRIDES",
        json.dumps({"zero_optimization": {
            "stage": 3, "offload_optimizer": {"device": "cpu"}}}))
    # the training entry point (initialize) opts in ...
    cfg = DeepSpeedTPUConfig({"train_batch_size": 8,
                              "zero_optimization": {"stage": 1}},
                             dp_world_size=1, apply_elastic_overrides=True)
    assert cfg.zero_config.stage == 3
    assert cfg.zero_config.offload_optimizer.device == "cpu"
    # ... but any OTHER config parsed in the worker process (autotuning
    # candidates, serving groups) sees exactly what it was given
    plain = DeepSpeedTPUConfig({"train_batch_size": 8,
                                "zero_optimization": {"stage": 1}},
                               dp_world_size=1)
    assert plain.zero_config.stage == 1
    assert plain.zero_config.offload_optimizer.device == "none"


def test_env_report_elastic_rows(tmp_path, monkeypatch):
    status = tmp_path / "st.json"
    with open(status, "w") as f:
        json.dump({"target_world": 8, "current_world": 7,
                   "checkpoint_world": 8, "crash_restarts": 1,
                   "max_restarts": 100, "total_restarts": 3,
                   "max_total_restarts": 1000,
                   "last_exit": {"classification": "capacity_loss",
                                 "codes": [75, -9], "lost_ranks": [5]},
                   "last_event": {"type": "shrink", "from_world": 8,
                                  "to_world": 7, "generation": 3,
                                  "at": time.time()},
                   "preflight": {"world": 7, "fits": True,
                                 "escalations": []}}, f)
    monkeypatch.setenv("DSTPU_ELASTIC_STATUS", str(status))
    from deepspeed_tpu.env_report import elastic_report
    rows = dict(elastic_report())
    assert rows["elastic world"] == "current 7 / target 8 / checkpoint 8"
    assert "crashes 1/100" in rows["elastic budget"]
    assert "capacity_loss" in rows["elastic last exit"]
    assert "lost ranks [5]" in rows["elastic last exit"]
    assert "shrink world 8 -> 7" in rows["elastic last event"]
    assert "fits" in rows["elastic preflight"]


def test_plan_world_config_ladder_escalation():
    """Shrink preflight unit: fewer chips escalates the ladder rung by
    rung; the merged overrides are exactly what workers receive."""
    from deepspeed_tpu.telemetry.memory import plan_world_config
    raw = {"train_batch_size": 64,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}}
    at8 = plan_world_config(raw, num_params=1_000_000_000, world_chips=8,
                            bytes_limit=16 << 30)
    at2 = plan_world_config(raw, num_params=7_000_000_000, world_chips=2,
                            bytes_limit=16 << 30)
    assert len(at2["escalations"]) > len(at8["escalations"])
    assert at2["verdict"]["fits"]
    zo = at2["overrides"]["zero_optimization"]
    assert zo.get("offload_optimizer", {}).get("device") == "cpu" or \
        zo.get("stage") == 3
    # no limit recorded -> plan only, never escalates
    free = plan_world_config(raw, num_params=7_000_000_000, world_chips=1,
                             bytes_limit=0)
    assert free["escalations"] == [] and free["verdict"]["fits"]


# ---------------------------------------------------------------------------
# the acceptance drill: real subprocesses, end to end
# ---------------------------------------------------------------------------
def test_shrink_drill_end_to_end(tmp_path, tracing):
    """Chaos kills rank 1 permanently right after step KILL's autosave
    commits -> membership classifies it lost -> the agent relaunches at
    world 1 (free, preflight recorded) -> the shrunk run's per-step losses
    are bit-identical to a from-checkpoint baseline started directly at
    world 1 -> the episode reconstructs from elastic/ instants."""
    import subprocess
    from deepspeed_tpu.testing import free_port

    workdir = str(tmp_path)
    members = os.path.join(workdir, "members")
    total, kill_step = 14, 3
    spec = WorkerSpec(
        cmd=[sys.executable,
             os.path.join(os.path.dirname(__file__), "shrink_worker.py")],
        max_restarts=0,                      # ANY budgeted crash fails it
        monitor_interval_s=0.3, term_grace_s=5.0,
        coordinator_port=free_port(),
        membership_dir=members, lost_after_s=1.0,
        ckpt_dir=os.path.join(workdir, "ckpt"),
        status_path=os.path.join(workdir, "elastic_status.json"),
        env={"DSTPU_SW_DIR": workdir,
             "DSTPU_SW_TOTAL_STEPS": str(total),
             "DSTPU_SW_LOST_AFTER_S": "1.0",
             "DSTPU_SW_KILL_RANK": "1",
             "DSTPU_SW_KILL_STEP": str(kill_step)})
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 8,
                          "micro_batch_sizes": [1, 2, 4], "min_gpus": 1,
                          "max_gpus": 4, "version": 0.1,
                          "shrink_on_peer_loss": True, "min_world_size": 1,
                          "rejoin_grace_s": 0.2},
           "comm_guard": {"lost_after_s": 1.0}}
    agent = ElasticAgent(spec, cfg,
                         host_provider=lambda: ["localhost", "localhost"])
    assert agent.run() == 0
    assert agent.crash_restarts == 0                 # the loss was free
    assert [(e["type"], e["from_world"], e["to_world"])
            for e in agent.shrink_events] == [("shrink", 2, 1)]
    assert agent.last_preflight is not None          # verdict recorded
    with open(os.path.join(workdir, "elastic_status.json")) as f:
        st = json.load(f)
    assert st["current_world"] == 1 and st["target_world"] == 2

    def read(label, rank=0, root=None):
        path = os.path.join(root or workdir,
                            f"losses_{label}_rank{rank}.jsonl")
        with open(path) as f:
            return {r["step"]: (r["loss"], r["world"])
                    for r in map(json.loads, f)}

    g0, g1 = read("gen0"), read("gen1")
    assert all(w == 2 for _, w in g0.values())
    assert all(w == 1 for _, w in g1.values())
    resume_step = min(g1)
    assert kill_step <= resume_step <= min(g0) + len(g0)  # resumed, not 0
    assert max(g1) == total - 1                           # finished

    # baseline: fresh world-1 run resumed DIRECTLY from the same tag the
    # shrunk generation restored (copy the ckpt dir, pin `latest` there)
    basedir = os.path.join(workdir, "baseline")
    os.makedirs(os.path.join(basedir, "members"))
    shutil.copytree(os.path.join(workdir, "ckpt"),
                    os.path.join(basedir, "ckpt"))
    with open(os.path.join(basedir, "ckpt", "latest"), "w") as f:
        f.write(f"global_step{resume_step}")
    env = dict(os.environ)
    env.update(spec.env)
    env.update({"DSTPU_SW_DIR": basedir, "DSTPU_SW_BASELINE": "1",
                "DSTPU_RESUME": "latest", "DSTPU_NUM_PROCESSES": "1",
                "DSTPU_PROCESS_ID": "0", "DSTPU_ELASTIC_BATCH": "8"})
    subprocess.run(spec.cmd, env=env, check=True, timeout=300)
    base = read("base", root=basedir)
    assert min(base) == resume_step
    # bit-identical per-step losses: shrunk resume == direct small-world run
    for step in sorted(g1):
        assert base[step][0] == g1[step][0], (step, base[step], g1[step])

    # the episode reconstructs from the elastic/ timeline: the agent's
    # instants in THIS process, the worker-side reshard in gen1's trace
    names = [e[1] for e in tracing.events_snapshot()]
    assert "elastic/peer_lost" in names and "elastic/shrink_planned" in names
    with open(os.path.join(workdir, "trace_gen1_rank0.json")) as f:
        worker_events = [ev.get("name") for ev in
                         json.load(f)["traceEvents"]]
    assert "elastic/reshard" in worker_events
