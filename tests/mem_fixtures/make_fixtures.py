"""Regenerate the checked-in dsmem fixtures AND the repo-root
``mem_baseline.json`` — fixtures and baseline are ONE artifact set, pinned
clean against each other (the plan-fixtures contract):

  mem_micro.json            the clean tie-out report: micro ledger + a
                            deterministic synthetic observation set
                            (plan * fixed per-phase factors), exit 0 vs
                            the baseline
  mem_micro_regressed.json  the same workload with the steady-phase
                            watermark grown 3x — the seeded regression the
                            CLI exit-matrix test drives (exit 1)
  ../../mem_baseline.json   written from the clean report via
                            write_mem_baseline (the ratchet's anchor)

Run from anywhere: ``python tests/mem_fixtures/make_fixtures.py``. The
memory module is file-loaded (stdlib-only contract), so this script works
on jax-less hosts too.
"""

import copy
import importlib.util
import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(os.path.dirname(HERE))


def _load_memory():
    spec = importlib.util.spec_from_file_location(
        "dsmem_fixtures_memory",
        os.path.join(REPO, "deepspeed_tpu", "telemetry", "memory.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


#: the micro workload: 1M params, zero-1 over a 4-way fsdp world, bf16
#: compute, full shape hints so every ledger component is exercised
MICRO_LEDGER_KW = dict(
    num_params=1_000_000, zero_stage=1, zero_world=4,
    compute_dtype="bf16", grad_accum_dtype="fp32",
    micro_batch=4, seq_len=128, hidden_size=256, num_layers=2,
    vocab_size=1000, remat_policy="dots_with_no_batch_dims_saveable")

#: synthetic observation = plan * factor, per phase — deterministic stand-in
#: for real allocator stats (the CPU backend has none). first_step runs
#: hotter than plan (compile workspace, which the ledger deliberately does
#: not model); the others track the plan closely.
OBS_FACTOR = {"init": 0.97, "first_step": 1.08, "steady": 1.02,
              "ckpt": 1.03}
HOST_RSS = {"init": 400_000_000, "first_step": 430_000_000,
            "steady": 435_000_000, "ckpt": 450_000_000}
BYTES_LIMIT = 16_000_000_000
SAMPLES_PER_PHASE = 4


def build_clean_report(mem) -> dict:
    ledger = mem.MemoryLedger(**MICRO_LEDGER_KW)
    plan_phases = ledger.phase_bytes()
    observed = {}
    for phase in mem.PHASES:
        hbm = int(plan_phases[phase]["hbm_bytes"] * OBS_FACTOR[phase])
        observed[phase] = {
            "hbm_bytes_in_use": int(hbm * 0.95),
            "hbm_peak_bytes": hbm,
            "host_rss_bytes": HOST_RSS[phase],
            "samples": SAMPLES_PER_PHASE,
        }
    return {
        "version": mem.MEM_REPORT_VERSION,
        "source": "mem_micro.json",
        "bytes_limit": BYTES_LIMIT,
        "plan": ledger.to_dict(),
        "observed": {"phases": observed,
                     "num_samples": SAMPLES_PER_PHASE * len(mem.PHASES)},
        "devices": {"TPU_0": {
            "bytes_in_use": observed["steady"]["hbm_bytes_in_use"],
            "peak_bytes_in_use": observed["steady"]["hbm_peak_bytes"],
            "bytes_limit": BYTES_LIMIT}},
    }


def build_regressed_report(clean: dict) -> dict:
    # the seeded watermark regression: steady-phase device peak grows 3x —
    # far past the 1.25x tolerance AND the 1MB absolute floor
    reg = copy.deepcopy(clean)
    steady = reg["observed"]["phases"]["steady"]
    steady["hbm_peak_bytes"] *= 3
    steady["hbm_bytes_in_use"] *= 3
    return reg


def _write(path: str, obj: dict) -> None:
    with open(path, "w") as f:
        json.dump(obj, f, indent=2, sort_keys=True)
        f.write("\n")


def main():
    mem = _load_memory()
    clean = build_clean_report(mem)
    _write(os.path.join(HERE, "mem_micro.json"), clean)
    _write(os.path.join(HERE, "mem_micro_regressed.json"),
           build_regressed_report(clean))
    baseline = os.path.join(REPO, mem.MEM_BASELINE_NAME)
    mem.write_mem_baseline(baseline, clean)
    print(f"wrote mem_micro.json, mem_micro_regressed.json, {baseline}")


if __name__ == "__main__":
    main()
