"""Utils breadth tests: nvtx/instrument, init_on_device, tensor_fragment,
z3 leaf modules.

Reference analogs: ``deepspeed/utils/{nvtx,init_on_device,tensor_fragment,
z3_leaf_module}.py``; tests mirror ``tests/unit/runtime/zero/test_zero_leaf_
module.py`` and the tensor-fragment debug API cases.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import create_mesh
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.models.llama import TINY_LLAMA, LlamaForCausalLM, random_tokens
from deepspeed_tpu.utils.init_on_device import OnDevice, abstract_init, sharded_init
from deepspeed_tpu.utils.nvtx import annotate, instrument, instrument_w_nvtx
from deepspeed_tpu.utils.tensor_fragment import (
    safe_get_full_fp32_param, safe_get_full_grad,
    safe_get_full_optimizer_state, safe_set_full_fp32_param)
from deepspeed_tpu.utils.z3_leaf_module import (
    is_z3_leaf_path, set_z3_leaf_modules, unset_z3_leaf_modules)


def _engine(mesh=None):
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3},
    }
    return deepspeed_tpu.initialize(
        model=LlamaForCausalLM(TINY_LLAMA), config=cfg, mesh=mesh,
        example_batch=random_tokens(2, 16, vocab_size=TINY_LLAMA.vocab_size))[0]


def test_instrument_and_annotate():
    @instrument
    def f(x):
        return x + 1

    @instrument_w_nvtx(name="scaled")
    def g(x):
        return x * 2

    with annotate("outer"):
        assert int(f(jnp.asarray(1))) == 2
        assert int(jax.jit(g)(jnp.asarray(3))) == 6


def test_abstract_init_allocates_nothing_and_matches_real():
    model = LlamaForCausalLM(TINY_LLAMA)
    batch = random_tokens(2, 16, vocab_size=TINY_LLAMA.vocab_size)
    shapes = abstract_init(model, jax.random.PRNGKey(0), batch)
    real = model.init(jax.random.PRNGKey(0), batch)
    assert jax.tree.structure(shapes) == jax.tree.structure(real)
    jax.tree.map(lambda s, r: (s.shape, s.dtype) == (r.shape, r.dtype) or
                 (_ for _ in ()).throw(AssertionError((s, r.shape))),
                 shapes, real)
    assert isinstance(OnDevice(dtype=jnp.bfloat16).__enter__(), OnDevice)


def test_sharded_init_births_params_sharded():
    mesh = create_mesh(MeshConfig(data=2, fsdp=4))
    model = LlamaForCausalLM(TINY_LLAMA)
    batch = random_tokens(2, 16, vocab_size=TINY_LLAMA.vocab_size)
    variables, shardings = sharded_init(model, jax.random.PRNGKey(0), batch,
                                        mesh=mesh, stage=3)
    kernel = variables["params"]["model"]["lm_head"]["kernel"]
    assert "fsdp" in str(kernel.sharding.spec)


def test_tensor_fragment_get_set_roundtrip():
    eng = _engine()
    w = safe_get_full_fp32_param(eng, "lm_head/kernel")
    assert w.dtype == np.float32 and w.ndim == 2
    mu = safe_get_full_optimizer_state(eng, "lm_head/kernel", "mu")
    assert mu.shape == w.shape
    assert safe_get_full_grad(eng, "lm_head/kernel") is None
    new = np.zeros_like(w)
    safe_set_full_fp32_param(eng, "lm_head/kernel", new)
    np.testing.assert_allclose(
        safe_get_full_fp32_param(eng, "lm_head/kernel"), new)


def test_z3_leaf_modules_opt_out_of_fsdp():
    from deepspeed_tpu.runtime.zero.partition import build_param_shardings
    mesh = create_mesh(MeshConfig(data=2, fsdp=4))
    params = {"experts": {"w": np.zeros((64, 64), np.float32)},
              "dense": {"w": np.zeros((64, 64), np.float32)}}
    set_z3_leaf_modules(["experts"])
    try:
        assert is_z3_leaf_path("moe/experts/w")
        sh = build_param_shardings(params, mesh, stage=3, min_shard_size=1)
        assert "fsdp" not in str(sh["experts"]["w"].spec)
        assert "fsdp" in str(sh["dense"]["w"].spec)
    finally:
        unset_z3_leaf_modules(["experts"])


@pytest.mark.slow
def test_tensor_fragment_routes_through_offload_masters():
    """Under host offload, get/set must hit the fp32 masters, not the
    compute-dtype device shadows (reference tensor_fragment fragment map)."""
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2,
                              "offload_optimizer": {"device": "cpu"}},
    }
    eng = deepspeed_tpu.initialize(
        model=LlamaForCausalLM(TINY_LLAMA), config=cfg,
        example_batch=random_tokens(2, 16, vocab_size=TINY_LLAMA.vocab_size))[0]
    assert eng._offload is not None
    w = safe_get_full_fp32_param(eng, "lm_head/kernel")
    assert w.dtype == np.float32
    new = np.full_like(w, 0.125)
    safe_set_full_fp32_param(eng, "lm_head/kernel", new)
    np.testing.assert_allclose(
        safe_get_full_fp32_param(eng, "lm_head/kernel"), new)
    # master survives on the host tier (not just the shadow)
    idx_master = safe_get_full_optimizer_state(eng, "lm_head/kernel", "mu")
    assert idx_master.shape == w.shape


# ---------------------------------------------------------------------------
# timers: never-started hardening + async-pipeline reconciliation hooks
# ---------------------------------------------------------------------------
def test_timer_never_started_returns_zero_with_warning(monkeypatch):
    from deepspeed_tpu.utils import timer as timer_mod
    from deepspeed_tpu.utils.timer import Timer
    warned = []
    monkeypatch.setattr(timer_mod.logger, "warning",
                        lambda msg, *a: warned.append(msg % a if a else msg))
    t = Timer("idle", synchronize=False)
    assert t.elapsed() == 0.0
    assert t.mean() == 0.0
    assert len(warned) == 2          # one per accessor, no raise
    assert all("idle" in m for m in warned)

    t.start()
    t.stop()
    assert t.mean() >= 0.0           # started once: no warning path
    assert t.elapsed(reset=True) >= 0.0
    assert t.elapsed() == 0.0        # post-reset: still no raise/warning spam


def test_timer_record_external_reconciles_async_windows():
    from deepspeed_tpu.utils.timer import Timer
    t = Timer("train_batch", synchronize=False)
    t.record_external(0.8, count=4)  # one drained window, 4 steps
    assert t.mean() == pytest.approx(0.2)
    assert t.elapsed(reset=False) == pytest.approx(0.8)
    t.record_external(0.2, count=2)
    assert t.mean() == pytest.approx(1.0 / 6)


def test_throughput_timer_mark_edge_closes_windows_without_sync():
    import time as _time
    from deepspeed_tpu.utils.timer import ThroughputTimer
    msgs = []
    t = ThroughputTimer(batch_size=4, steps_per_output=2, synchronize=False,
                        logging_fn=msgs.append)
    for _ in range(4):
        t.start()
        t.stop(global_step=True)     # no window close without an edge
        _time.sleep(0.01)
    assert t.total_elapsed_time == 0.0
    t.mark_edge()                    # the engine's post-drain hook
    assert t.total_elapsed_time > 0.0
    assert t.avg_samples_per_sec() > 0.0
    assert len(msgs) == 1            # reported once past steps_per_output
