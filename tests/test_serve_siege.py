"""Serving-under-siege tests: the host-RAM KV offload tier, the
degradation ladder, request-level fault isolation (poison quarantine),
the serve chaos knobs, and the bench_serve overload harness.

Engines share the KV/bucket shapes of tests/test_serving.py so jit
compilations are shared across the module (XLA static shapes — one
compile per shape per process). Unit pieces (planners, ladder, chaos
parsing) run without an engine; fault-isolation and drift tests drive
``_serve_once`` manually on fake engines for exact tick control; the
acceptance drills run the real serve loop on the tiny fp32 llama.
"""

import time
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                  V2EngineConfig)
from deepspeed_tpu.inference.v2.scheduler import SchedulerConfig
from deepspeed_tpu.models.llama import (TINY_LLAMA, LlamaConfig,
                                        LlamaForCausalLM)
from deepspeed_tpu.resilience.chaos import (ChaosConfig, ChaosMonkey,
                                            ChaosInjectedPoisonError)
from deepspeed_tpu.serving import (BackpressureError, DegradationLadder,
                                   InferenceServer, LadderConfig,
                                   RequestState, ServeLevel, ServingConfig)
from deepspeed_tpu.serving.kv_tier import (effective_usable_blocks,
                                           plan_demotions, plan_promotions,
                                           tier_pressure)
from deepspeed_tpu.serving.server import _EngineStepError
from deepspeed_tpu.telemetry.tracer import get_tracer

pytestmark = pytest.mark.serve_load


def _tiny_fp32():
    return LlamaConfig(**{**TINY_LLAMA.__dict__, "dtype": jnp.float32,
                          "max_seq_len": 512})


@pytest.fixture(scope="module")
def model_and_params():
    cfg = _tiny_fp32()
    model = LlamaForCausalLM(cfg)
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    return cfg, params


KV_BLOCKS = 64  # shared with tests/test_serving.py: kv shape is a compile shape


def _engine(cfg, params, kv_blocks=KV_BLOCKS):
    return InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=kv_blocks,
        scheduler=SchedulerConfig(max_tokens_per_step=64,
                                  prefill_buckets=(16, 32, 64))))


def _tick(server):
    """One manual serve tick with the loop's fault-handling semantics —
    exact tick control for the fake-engine tests."""
    try:
        return server._serve_once()
    except _EngineStepError as e:
        server._on_step_fault(e)
        return False


# ---------------------------------------------------------------------------
# tier planners (pure arithmetic)
# ---------------------------------------------------------------------------
def test_tier_planners():
    assert effective_usable_blocks(63, 0.0) == 63
    assert effective_usable_blocks(63, 0.85) == 9
    assert effective_usable_blocks(63, 0.999) == 1

    # demote LIFO until both lines hold, never below min_active
    assert plan_demotions([2, 2, 2, 2], [2, 2, 2, 2], reserved_blocks=8,
                          capacity_blocks=100, demote_line_blocks=3,
                          min_active=1) == [3, 2, 1]
    assert plan_demotions([2, 2, 2, 2], [2, 2, 2, 2], reserved_blocks=8,
                          capacity_blocks=100, demote_line_blocks=3,
                          min_active=3) == [3]
    # capacity-line violation (chaos shrank effective usable) demotes too
    assert plan_demotions([4, 4], [1, 1], reserved_blocks=2,
                          capacity_blocks=5, demote_line_blocks=100,
                          min_active=1) == [1]
    assert plan_demotions([2, 2], [2, 2], reserved_blocks=4,
                          capacity_blocks=100, demote_line_blocks=10,
                          min_active=1) == []
    # a zero-held victim frees nothing against the demote line: skipped
    # (kept active) instead of paused for no benefit
    assert plan_demotions([2, 2, 2], [2, 0, 2], reserved_blocks=6,
                          capacity_blocks=100, demote_line_blocks=3,
                          min_active=1) == [2, 0]

    # promotion respects capacity, free blocks AND the demote line (no
    # same-tick demote->promote ping-pong)
    assert plan_promotions([2, 2], [2, 2], active_worst_sum=2,
                           capacity_blocks=10, free_blocks=10,
                           reserved_blocks=2, demote_line_blocks=8) == 2
    assert plan_promotions([2, 2], [2, 2], active_worst_sum=2,
                           capacity_blocks=10, free_blocks=10,
                           reserved_blocks=2, demote_line_blocks=3) == 0
    assert plan_promotions([2], [8], active_worst_sum=2,
                           capacity_blocks=10, free_blocks=4,
                           reserved_blocks=2, demote_line_blocks=100) == 0
    # progress guard: nothing active -> FIFO head promotes past the lines
    assert plan_promotions([20], [4], active_worst_sum=0,
                           capacity_blocks=10, free_blocks=4,
                           reserved_blocks=0, demote_line_blocks=1) == 1

    p, reason = tier_pressure(9, 10, 0, 8, 0, 0)
    assert p == pytest.approx(0.9) and reason == "device_kv"
    p, reason = tier_pressure(1, 10, 8, 8, 0, 0)
    assert p == pytest.approx(1.0) and reason == "queue"
    p, reason = tier_pressure(0, 10, 0, 8, 900, 1000)
    assert p == pytest.approx(0.9) and reason == "host_kv"


# ---------------------------------------------------------------------------
# degradation ladder (hysteresis, edges, sticky degraded)
# ---------------------------------------------------------------------------
def test_ladder_transitions_hysteresis_and_sticky():
    ladder = DegradationLadder(LadderConfig(
        brownout_pressure=0.5, shed_pressure=0.9, hysteresis=0.1,
        cooldown_ticks=3))
    assert ladder.level is ServeLevel.HEALTHY
    assert ladder.observe(0.4) is None
    # upward edges are immediate, and may jump rungs
    assert ladder.observe(0.6) == (ServeLevel.HEALTHY, ServeLevel.BROWNOUT)
    assert ladder.observe(0.95) == (ServeLevel.BROWNOUT, ServeLevel.SHED)
    # descending needs cooldown_ticks BELOW threshold - hysteresis (0.8)
    assert ladder.observe(0.85) is None          # calm zone not reached
    assert ladder.observe(0.7) is None
    assert ladder.observe(0.85) is None          # resets the calm count
    assert ladder.observe(0.7) is None
    assert ladder.observe(0.7) is None
    assert ladder.observe(0.7) == (ServeLevel.SHED, ServeLevel.BROWNOUT)
    # one rung at a time
    assert ladder.level is ServeLevel.BROWNOUT
    for _ in range(2):
        assert ladder.observe(0.1) is None
    assert ladder.observe(0.1) == (ServeLevel.BROWNOUT, ServeLevel.HEALTHY)
    assert ladder.entries["brownout"] == 2 and ladder.entries["shed"] == 1

    # degraded is sticky: pressure can neither cause nor clear it
    assert ladder.latch_degraded("engine fault") == (
        ServeLevel.HEALTHY, ServeLevel.DEGRADED)
    assert ladder.observe(0.0) is None
    assert ladder.level is ServeLevel.DEGRADED
    assert ladder.latch_degraded("again") is None

    with pytest.raises(ValueError):
        LadderConfig(brownout_pressure=0.9, shed_pressure=0.5).validate()


# ---------------------------------------------------------------------------
# chaos knobs: parsing + determinism contract
# ---------------------------------------------------------------------------
def test_chaos_serve_knobs():
    env = {"DSTPU_CHAOS_SERVE_SLOW_TICK": "4:0.01",
           "DSTPU_CHAOS_SERVE_KV_PRESSURE": "0.8:5:9",
           "DSTPU_CHAOS_SERVE_POISON_UID": "3"}
    cfg = ChaosConfig.from_env(env)
    assert cfg.active
    assert cfg.serve_slow_tick_every == 4 and cfg.serve_slow_tick_s == 0.01
    assert cfg.serve_kv_pressure_frac == 0.8
    assert (cfg.serve_kv_pressure_from, cfg.serve_kv_pressure_until) == (5, 9)
    assert cfg.serve_poison_uid == 3
    # probability spelling parses through the sha-roll path
    pcfg = ChaosConfig.from_env({"DSTPU_CHAOS_SERVE_SLOW_TICK": "p0.25:0.5"})
    assert pcfg.serve_slow_tick_prob == 0.25 and pcfg.serve_slow_tick_every == 0

    monkey = ChaosMonkey(cfg)
    # pressure window [5, 9): off, on, off again — with edge instants
    assert monkey.serve_kv_pressure(4) == 0.0
    assert monkey.serve_kv_pressure(5) == 0.8
    assert monkey.serve_kv_pressure(8) == 0.8
    assert monkey.serve_kv_pressure(9) == 0.0
    assert monkey.injected["serve_kv_pressure"] == 1   # one ON edge

    # slow tick: every 4th, injected count exact
    stalled = [monkey.serve_slow_tick(t) for t in range(1, 9)]
    assert [s > 0 for s in stalled] == [False, False, False, True,
                                        False, False, False, True]
    assert monkey.injected["serve_slow_tick"] == 2

    # poison raises only when the uid is resident; classifies TRANSIENT
    from deepspeed_tpu.comm.guard import CommOutcome, classify_exception
    monkey.maybe_poison_serve([1, 2])     # not resident: no raise
    with pytest.raises(ChaosInjectedPoisonError) as ei:
        monkey.maybe_poison_serve([2, 3])
    assert classify_exception(ei.value) is CommOutcome.TRANSIENT

    # sha-roll determinism: same (seed, kind, tick) -> same decision
    m1 = ChaosMonkey(ChaosConfig(seed=7, serve_slow_tick_prob=0.5,
                                 serve_slow_tick_s=0.0))
    m2 = ChaosMonkey(ChaosConfig(seed=7, serve_slow_tick_prob=0.5,
                                 serve_slow_tick_s=0.0))
    rolls1 = [m1._roll("serve_slow", t) for t in range(20)]
    rolls2 = [m2._roll("serve_slow", t) for t in range(20)]
    assert rolls1 == rolls2


# ---------------------------------------------------------------------------
# serving config group (DS006-clean constants)
# ---------------------------------------------------------------------------
def test_serving_config_from_ds_config():
    cfg = ServingConfig.from_ds_config({
        "train_batch_size": 8,
        "serving": {"max_queue_depth": 4, "kv_offload_enabled": True,
                    "brownout_pressure": 0.5}})
    assert cfg.max_queue_depth == 4
    assert cfg.kv_offload_enabled and cfg.brownout_pressure == 0.5
    assert ServingConfig.from_ds_config({}).max_queue_depth == 64
    with pytest.raises(ValueError, match="unknown 'serving' config keys"):
        ServingConfig.from_ds_config({"serving": {"max_que_depth": 4}})


# ---------------------------------------------------------------------------
# engine-level KV offload: demote/promote round-trip is bit-identical
# ---------------------------------------------------------------------------
def test_kv_offload_demote_promote_parity(model_and_params):
    cfg, params = model_and_params
    prompts = [list(range(1, 20)), list(range(3, 15))]
    ref = _engine(cfg, params)
    ref.put([1, 2], prompts)
    for _ in range(9):
        ref.step()
    ref_gen = {u: list(ref.state.get(u).generated) for u in (1, 2)}

    e = _engine(cfg, params)
    e.put([1, 2], prompts)
    for _ in range(3):
        e.step()
    free_before = e.kv.free_blocks
    nbytes = e.demote_kv(1)
    assert nbytes > 0 and e.kv.free_blocks > free_before
    assert e.state.get(1).paused and e.state.get(1).blocks == []
    assert e.demoted_uids() == [1] and e.host_kv_bytes() == nbytes
    assert e.demote_kv(1) == 0            # idempotent: already demoted
    for _ in range(3):
        e.step()                          # seq 2 decodes alone
    assert e.promote_kv(1) == nbytes
    assert not e.state.get(1).paused and e.host_kv_bytes() == 0
    while any(len(e.state.get(u).generated) < len(ref_gen[u])
              for u in (1, 2)):
        e.step()
    for u in (1, 2):
        assert e.state.get(u).generated[:len(ref_gen[u])] == ref_gen[u], \
            f"uid {u} diverged after demote/promote round-trip"
    # flush clears both tiers; ledger returns to zero
    e.demote_kv(2)
    e.flush(1), e.flush(2)
    ledger = e.kv_ledger()
    assert ledger["device_blocks_reserved"] == 0
    assert ledger["host_entries"] == 0 and ledger["host_bytes"] == 0
    assert ledger["demotions"] == 2 and ledger["promotions"] == 1


# ---------------------------------------------------------------------------
# fake engines for exact-tick fault isolation / drift tests
# ---------------------------------------------------------------------------
class _FakeSeq:
    def __init__(self):
        self.done = False


class _FakeEngine:
    """Functional minimal engine: one token per resident sequence per
    step; scriptable step failures by 1-based step-call index."""

    def __init__(self, fail_calls=(), fail_exc=None):
        self._seqs = {}
        self.step_calls = 0
        self.fail_calls = set(fail_calls)
        self.fail_exc = fail_exc or RuntimeError("connection reset by peer")
        self.state = types.SimpleNamespace(
            max_context_length=512,
            get=lambda uid: self._seqs.get(uid))
        self.kv = types.SimpleNamespace(
            blocks_needed=lambda total: (total + 15) // 16, free_blocks=63)

    def kv_usable_blocks(self):
        return 64

    def kv_occupancy(self):
        return len(self._seqs) / 64.0

    def can_schedule(self, uids, needs):
        return True

    def admit(self, uid, tokens):
        self._seqs[uid] = _FakeSeq()

    def has_work(self):
        return any(not s.done for s in self._seqs.values())

    def step(self):
        self.step_calls += 1
        if self.step_calls in self.fail_calls:
            raise self.fail_exc
        return {uid: 7 for uid, s in self._seqs.items() if not s.done}

    def finish(self, uid):
        if uid in self._seqs:
            self._seqs[uid].done = True

    def reap_finished(self):
        done = [u for u, s in self._seqs.items() if s.done]
        for u in done:
            self._seqs.pop(u)
        return {u: [] for u in done}


def test_transient_step_fault_recovers_without_restart():
    """Satellite regression: a transient engine-step failure must NOT
    latch the sticky degraded 503 — the suspect is evicted, retried, and
    the server keeps answering 200s without a restart."""
    engine = _FakeEngine(fail_calls={1})
    server = InferenceServer(engine, ServingConfig(
        recover_clean_steps=3, poison_retry_budget=1, idle_poll_s=0.001))
    req = server.submit([1, 2, 3], max_new_tokens=4)
    for _ in range(20):
        _tick(server)
        if req.state.terminal:
            break
    assert req.state == RequestState.FINISHED
    assert req.fault_count == 1            # evicted once, retried, finished
    assert server._degraded is None
    assert server.ladder.level is not ServeLevel.DEGRADED
    snap = server.metrics.snapshot()
    assert snap["engine_step_faults"] == 1
    assert snap["degraded_latches"] == 0
    assert snap["recomputed_tokens"] >= 3  # the re-prefilled prompt
    # the server still takes and completes NEW work (the "200s resume")
    req2 = server.submit([4, 5], max_new_tokens=2)
    for _ in range(20):
        _tick(server)
        if req2.state.terminal:
            break
    assert req2.state == RequestState.FINISHED
    # and health auto-recovered after recover_clean_steps clean steps
    assert server.health()["fault_episode"] is False
    assert server.metrics.snapshot()["fault_recoveries"] == 1


def test_fatal_step_fault_still_latches_degraded():
    """The sticky path survives the overreach fix: fatal classifications
    (no transient marker) latch exactly as before."""
    engine = _FakeEngine(fail_calls={1, 2, 3, 4},
                         fail_exc=RuntimeError("kaboom: device went away"))
    server = InferenceServer(engine, ServingConfig(idle_poll_s=0.001))
    req = server.submit([1, 2, 3], max_new_tokens=4)
    for _ in range(5):
        _tick(server)
        if req.state.terminal:
            break
    assert req.state == RequestState.FAILED
    assert server._degraded is not None
    assert server.ladder.level is ServeLevel.DEGRADED
    assert server.metrics.snapshot()["degraded_latches"] == 1


def test_repeated_unattributed_faults_latch_degraded():
    """A step that faults every time (transient-shaped) with eviction
    never isolating it must eventually latch — the engine itself is sick.
    The latch fires through the 4x backstop (suspects keep existing, but
    the fault streak never sees a clean step)."""
    engine = _FakeEngine(fail_calls=set(range(1, 100)))
    server = InferenceServer(engine, ServingConfig(
        poison_retry_budget=0, max_consecutive_step_faults=1,
        idle_poll_s=0.001))
    reqs = [server.submit([i + 1], max_new_tokens=2) for i in range(6)]
    for _ in range(30):
        _tick(server)
        if server._degraded is not None:
            break
    assert server._degraded is not None
    assert server.ladder.level is ServeLevel.DEGRADED
    assert all(r.state == RequestState.FAILED for r in reqs)
    # isolation was attempted before giving up (quarantines precede latch)
    assert server.metrics.snapshot()["requests_quarantined"] >= 3


class _DriftEngine(_FakeEngine):
    """Fake engine whose observed KV reservation is test-controlled — the
    projected-vs-observed drift recalibration surface."""

    def __init__(self):
        super().__init__()
        self.reserved = 0

    def kv_block_bytes(self):
        return 1024

    def kv_reserved_blocks(self):
        return self.reserved


def test_kv_drift_recalibrates_projected_watermark():
    engine = _DriftEngine()
    server = InferenceServer(engine, ServingConfig(idle_poll_s=0.001))
    tracer = get_tracer()
    tracer.configure(enabled=True)
    before = tracer.instant_counts(prefix="serve/kv_recalibrate").get(
        "serve/kv_recalibrate", 0)
    # observed >> projected (0): the unsafe direction -> watermark scales
    # down (edge-triggered, once)
    engine.reserved = 10
    _tick(server)
    assert server._kv_watermark_scale == 0.5
    snap = server.metrics.snapshot()
    assert snap["kv_drift_events"] == 1
    assert snap["kv_recalibrations"] == 1
    _tick(server)                      # still drifted: NO second event
    assert server.metrics.snapshot()["kv_drift_events"] == 1
    # drift clears -> scale restored, second recalibration logged
    engine.reserved = 0
    _tick(server)
    assert server._kv_watermark_scale == 1.0
    snap = server.metrics.snapshot()
    assert snap["kv_recalibrations"] == 2
    counts = tracer.instant_counts(prefix="serve/kv_recalibrate")
    assert counts.get("serve/kv_recalibrate", 0) - before == 2


# ---------------------------------------------------------------------------
# brownout semantics: low-priority admits pause, budgets cap
# ---------------------------------------------------------------------------
def test_brownout_pauses_low_priority_and_caps_budget():
    engine = _FakeEngine()
    server = InferenceServer(engine, ServingConfig(
        brownout_max_new_tokens=3, idle_poll_s=0.001))
    low = server.submit([1, 2], max_new_tokens=5, priority=-1)
    server.ladder.observe(0.9)             # force BROWNOUT
    assert server.ladder.level is ServeLevel.BROWNOUT
    # budget capped at the door while browned out
    capped = server.submit([3, 4], max_new_tokens=50)
    assert capped.max_new_tokens == 3
    server._admit_from_queue()
    # the low-priority request waits in the queue; normal work admitted
    assert low.state == RequestState.QUEUED
    assert capped.state == RequestState.PREFILL
    # back to healthy: the low-priority admit resumes
    for _ in range(100):
        if server.ladder.observe(0.0) is not None:
            break
    assert server.ladder.level is ServeLevel.HEALTHY
    server._admit_from_queue()
    assert low.state == RequestState.PREFILL
    # stringly-typed priority is a client error at the door
    with pytest.raises(ValueError, match="priority"):
        server.submit([1], max_new_tokens=2, priority="high")


# ---------------------------------------------------------------------------
# ACCEPTANCE: chaos KV-pressure drill — brownout before the first 429,
# shed with Retry-After, recovery to healthy, episode on the trace
# ---------------------------------------------------------------------------
def test_chaos_kv_pressure_ladder_drill(model_and_params, monkeypatch):
    cfg, params = model_and_params
    monkeypatch.setenv("DSTPU_CHAOS_SERVE_KV_PRESSURE", "0.85:0:1200")
    tracer = get_tracer()
    tracer.configure(enabled=True)
    tracer.clear()
    # host budget ~20 blocks: the tier absorbs the first wave, then fills
    # — pressure must SURFACE through the ladder instead of silently
    # swallowing the whole siege into host RAM. The wide queue (32) makes
    # the FIRST 429 come from the ladder/projection, which are
    # structurally downstream of brownout
    server = InferenceServer(_engine(cfg, params), ServingConfig(
        max_queue_depth=32, kv_offload_enabled=True,
        host_kv_budget_bytes=20 * 16384,
        brownout_pressure=0.5, shed_pressure=0.9, ladder_hysteresis=0.1,
        ladder_cooldown_ticks=6, kv_demote_watermark=0.8,
        kv_demote_watermark_brownout=0.4, idle_poll_s=0.001,
        retry_after_s=0.05)).start()
    try:
        # warm the compile cache with a wave shaped exactly like the siege
        # (prefill bucket + decode batch buckets 1/2/4): a mid-siege XLA
        # compile would stall the serve tick for seconds and let the queue
        # fill before the ladder can even observe once
        warm = [server.submit(list(np.random.default_rng(100 + i)
                                   .integers(1, 99, 16)),
                              max_new_tokens=8) for i in range(4)]
        for w in warm:
            w.result(timeout=300)
        # siege: arrivals outpace the pressure-throttled service rate
        accepted, rejections = [], 0
        first_reject_eid = None
        for i in range(60):
            try:
                accepted.append(server.submit(
                    list(np.random.default_rng(i).integers(1, 99, 16)),
                    max_new_tokens=8))
            except BackpressureError as e:
                rejections += 1
                assert e.retry_after_s > 0          # Retry-After semantics
                if first_reject_eid is None:
                    evs = [ev for ev in tracer.events_snapshot()
                           if ev[1] == "serve/backpressure"]
                    first_reject_eid = evs[0][0] if evs else None
            time.sleep(0.005)
        assert rejections > 0, "pressure never pushed back"
        # everything accepted reaches a terminal state (slower, not dead)
        for r in accepted:
            r.result(timeout=300)
        assert all(r.state == RequestState.FINISHED for r in accepted)
        # ladder climbed: brownout BEFORE the first 429 (event-id order)
        snap = server.metrics.snapshot()
        assert snap["brownout_entries"] >= 1
        assert snap["shed_entries"] >= 1, snap
        assert snap["kv_demotions"] > 0
        assert snap["degraded_latches"] == 0        # sticky-503 count == 0
        brownout_evs = [ev for ev in tracer.events_snapshot()
                        if ev[1] == "serve/ladder"
                        and ev[7] and ev[7].get("to") == "brownout"]
        assert brownout_evs, "no brownout edge on the trace"
        assert first_reject_eid is not None
        assert brownout_evs[0][0] < first_reject_eid, \
            "server rejected before visiting brownout"
        # pressure lifts at tick 1200: the ladder climbs back down
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if server.ladder.level is ServeLevel.HEALTHY:
                break
            time.sleep(0.05)
        assert server.ladder.level is ServeLevel.HEALTHY
        assert server.health()["status"] == "serving"
        # the whole episode is reconstructible from the trace; the chaos
        # OFF edge lands when the (still-ticking idle) loop passes the
        # window end, so poll for it bounded
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if tracer.instant_counts().get("chaos/serve_kv_pressure",
                                           0) >= 2:
                break
            time.sleep(0.1)
        counts = tracer.instant_counts()
        assert counts.get("chaos/serve_kv_pressure", 0) >= 2   # on + off
        assert counts.get("serve/kv_demote", 0) == snap["kv_demotions"]
        assert counts.get("serve/ladder", 0) == snap["ladder_transitions"]
        # and the KV ledger is clean (both tiers)
        ledger = server.engine.kv_ledger()
        assert ledger["device_blocks_reserved"] == 0
        assert ledger["host_entries"] == 0 and ledger["host_bytes"] == 0
    finally:
        server.stop(drain_timeout=30.0)


# ---------------------------------------------------------------------------
# ACCEPTANCE: poison-request drill — quarantined after its retry budget
# while concurrent well-formed requests complete and health recovers
# ---------------------------------------------------------------------------
def test_poison_request_quarantine_drill(model_and_params):
    cfg, params = model_and_params
    chaos = ChaosMonkey(ChaosConfig(serve_poison_uid=2))
    tracer = get_tracer()
    tracer.configure(enabled=True)
    server = InferenceServer(_engine(cfg, params), ServingConfig(
        poison_retry_budget=1, recover_clean_steps=3,
        max_consecutive_step_faults=8, idle_poll_s=0.001),
        chaos=chaos).start()
    try:
        good_a = server.submit([5, 5, 5, 5], max_new_tokens=6)
        poison = server.submit([6, 6, 6, 6], max_new_tokens=6)   # uid 2
        good_b = server.submit([7, 7, 7, 7], max_new_tokens=6)
        assert poison.uid == 2
        for r in (good_a, poison, good_b):
            r.wait(timeout=300)
        # the poison is quarantined after its retry budget...
        assert poison.state == RequestState.FAILED
        assert poison.finish_reason == "quarantined"
        assert poison.fault_count == 2       # initial + 1 retry
        # ...while concurrent well-formed requests complete normally
        assert good_a.state == RequestState.FINISHED
        assert good_b.state == RequestState.FINISHED
        assert len(good_a.tokens) == 6 and len(good_b.tokens) == 6
        snap = server.metrics.snapshot()
        assert snap["requests_quarantined"] == 1
        assert snap["degraded_latches"] == 0
        assert snap["engine_step_faults"] >= 2
        assert chaos.injected["serve_poison"] >= 2
        # health returns to ok after the clean-step window
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            h = server.health()
            if h["ok"] and not h["fault_episode"]:
                break
            # keep clean steps flowing
            server.submit([8, 8], max_new_tokens=2).wait(timeout=60)
        h = server.health()
        assert h["ok"] and h["fault_episode"] is False
        assert server.metrics.snapshot()["fault_recoveries"] >= 1
        assert tracer.instant_counts().get("serve/quarantine", 0) >= 1
    finally:
        server.stop(drain_timeout=30.0)


# ---------------------------------------------------------------------------
# graceful drain under load: every request terminal, streams closed,
# the KV ledger returns to zero in BOTH tiers
# ---------------------------------------------------------------------------
def test_graceful_drain_under_load_ledger_zero(model_and_params):
    cfg, params = model_and_params
    server = InferenceServer(_engine(cfg, params, kv_blocks=16),
                             ServingConfig(
        kv_offload_enabled=True, kv_demote_watermark=0.35,
        kv_demote_watermark_brownout=0.25, idle_poll_s=0.001)).start()
    try:
        rng = np.random.default_rng(3)
        reqs = [server.submit(list(rng.integers(1, 99, 16)),
                              max_new_tokens=6) for _ in range(8)]
        # drain mid-decode: wait until tokens are actually flowing
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if any(r.tokens for r in reqs):
                break
            time.sleep(0.005)
        assert server.drain(timeout=300), "drain timed out under load"
        # every request reached a terminal state with its full budget
        assert all(r.state == RequestState.FINISHED for r in reqs)
        assert all(len(r.tokens) == 6 for r in reqs)
        # streams are closed: iterating an unconsumed stream yields the
        # full token list then terminates (END sentinel) instead of
        # blocking on a next token that will never come
        for r in reqs:
            assert list(r.stream(timeout=1.0)) == r.tokens
        # the tier actually exercised during the run...
        assert server.metrics.snapshot()["kv_demotions"] > 0
        # ...and the ledger is zero in both tiers
        ledger = server.engine.kv_ledger()
        assert ledger["device_blocks_reserved"] == 0
        assert ledger["host_entries"] == 0 and ledger["host_bytes"] == 0
        assert server.engine.kv_occupancy() == 0.0
    finally:
        server.stop(drain_timeout=10.0)


# ---------------------------------------------------------------------------
# bench_serve micro scenario (the tier-1 serve_load gate): deterministic
# counter invariants on a ~100-request closed-loop run
# ---------------------------------------------------------------------------
def test_bench_serve_micro_counter_invariants(model_and_params):
    import dataclasses as dc

    from deepspeed_tpu.serving.bench_serve import SCENARIOS, run_scenario

    cfg, params = model_and_params
    scenario = dc.replace(SCENARIOS["micro"], num_requests=100,
                          prompt_len=(8, 24), max_new_tokens=(2, 5))
    # scope the span-derived latency section to THIS run's request uids
    get_tracer().configure(enabled=True)
    get_tracer().clear()
    server = InferenceServer(_engine(cfg, params, kv_blocks=16),
                             ServingConfig(
        max_queue_depth=32, kv_offload_enabled=True,
        kv_demote_watermark=0.35, kv_demote_watermark_brownout=0.25,
        brownout_pressure=0.6, shed_pressure=0.95,
        ladder_cooldown_ticks=5, idle_poll_s=0.001,
        retry_after_s=0.01)).start()
    try:
        report = run_scenario(server, scenario)
    finally:
        server.stop(drain_timeout=30.0)
    m = report["metrics"]
    c = report["counters"]
    # conservation: every submitted request reached exactly one terminal
    assert m["requests_submitted"] == 100
    assert (m["requests_completed"] + m["requests_failed"]
            + m["requests_cancelled"] + m["requests_timed_out"]) == 100
    assert m["requests_failed"] == 0
    assert report["requests"]["states"] == {"finished": 100}
    # token conservation: engine-side count == client-side count
    assert m["tokens_generated"] == report["requests"]["client_tokens"]
    assert m["tokens_generated"] >= 2 * 100
    # the tier was exercised AND balanced back to zero
    assert c["demotions"] > 0
    assert c["demotions"] == c["promotions"]
    assert c["demoted_bytes"] == c["promoted_bytes"]
    assert report["kv_ledger"]["device_blocks_reserved"] == 0
    assert report["kv_ledger"]["host_entries"] == 0
    assert report["kv_ledger"]["host_bytes"] == 0
    # availability: the siege never latched the sticky 503
    assert c["sticky_503"] == 0
    assert c["quarantined"] == 0 and c["step_faults"] == 0
    assert report["drained"] is True
    assert report["ladder"]["level"] == "healthy"
    # span-derived latencies cover the full population
    ttft = report["latency_from_trace"]["ttft_s"]
    assert ttft["count"] == 100 and ttft["p50_s"] > 0
    tpot = report["latency_from_trace"]["tpot_s"]
    assert tpot["count"] > 0 and tpot["p50_s"] > 0
    # and the report is JSON-serializable (the CLI contract)
    import json
    json.dumps(report, default=str)
