"""BERT encoder tests: bidirectional attention, MLM training, padding mask,
TP rules.

Reference analog: the vendored regression BERT (``tests/unit/modeling.py``)
and BERT container cases; the compression suite's standard target.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.bert import (
    MLM_IGNORE_INDEX, TINY_BERT, BertForMaskedLM, bert_tensor_rules,
    mlm_mask_batch)


def _batch(bs=8, s=16, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(4, TINY_BERT.vocab_size, size=(bs, s)).astype(np.int32)
    b = mlm_mask_batch(ids, rng, mask_token_id=3,
                       vocab_size=TINY_BERT.vocab_size)
    return {k: np.asarray(v, np.int32) for k, v in b.items()}


def test_attention_is_bidirectional():
    """Flipping a future token must change an earlier position's logits."""
    model = BertForMaskedLM(TINY_BERT)
    b = _batch(2, 12)
    params = model.init(jax.random.PRNGKey(0), b)["params"]
    logits = model.apply({"params": params}, b, method=BertForMaskedLM.logits)
    b2 = {**b, "input_ids": np.array(b["input_ids"], copy=True)}
    b2["input_ids"][:, -1] = (b2["input_ids"][:, -1] + 1) % TINY_BERT.vocab_size
    logits2 = model.apply({"params": params}, b2, method=BertForMaskedLM.logits)
    assert not np.allclose(np.asarray(logits)[:, 0], np.asarray(logits2)[:, 0])


def test_padding_mask_isolates_pad_tokens():
    model = BertForMaskedLM(TINY_BERT)
    b = _batch(2, 12)
    mask = np.ones((2, 12), np.int32)
    mask[:, -4:] = 0
    b["attention_mask"] = mask
    params = model.init(jax.random.PRNGKey(0), b)["params"]
    base = np.asarray(model.apply({"params": params}, b,
                                  method=BertForMaskedLM.logits))
    b2 = {**b, "input_ids": np.array(b["input_ids"], copy=True)}
    b2["input_ids"][:, -1] = (b2["input_ids"][:, -1] + 7) % TINY_BERT.vocab_size
    got = np.asarray(model.apply({"params": params}, b2,
                                 method=BertForMaskedLM.logits))
    np.testing.assert_allclose(got[:, :8], base[:, :8], rtol=1e-5, atol=1e-6)


def test_mlm_loss_ignores_unmasked_positions():
    model = BertForMaskedLM(TINY_BERT)
    b = _batch(4, 16)
    params = model.init(jax.random.PRNGKey(1), b)["params"]
    loss = float(model.apply({"params": params}, b))
    assert np.isfinite(loss) and loss > 0
    # all-ignored labels -> zero loss (denominator guard)
    b0 = {**b, "labels": np.full_like(b["labels"], MLM_IGNORE_INDEX)}
    assert float(model.apply({"params": params}, b0)) == 0.0


@pytest.mark.slow
def test_bert_trains_with_engine_tp():
    model = BertForMaskedLM(TINY_BERT)
    config = {"train_batch_size": 8,
              "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
              "zero_optimization": {"stage": 2},
              "mesh": {"data": 2, "fsdp": 2, "tensor": 2}}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=config, example_batch=_batch(4, 16),
        tensor_rules=bert_tensor_rules)
    fixed = _batch(8, 16, seed=1)
    losses = [float(engine.train_batch(batch=fixed)) for _ in range(5)]
    assert losses[-1] < losses[0] and all(np.isfinite(losses))


def test_mlm_masking_statistics():
    rng = np.random.default_rng(0)
    ids = rng.integers(4, 500, size=(64, 64)).astype(np.int32)
    b = mlm_mask_batch(ids, rng, mask_token_id=3, vocab_size=500)
    sel = b["labels"] != MLM_IGNORE_INDEX
    frac = sel.mean()
    assert 0.10 < frac < 0.20
    masked = (b["input_ids"] == 3) & sel
    assert 0.6 < masked.sum() / sel.sum() < 0.95
    np.testing.assert_array_equal(b["input_ids"][~sel], ids[~sel])
