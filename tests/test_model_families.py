"""Model-family tests: mistral/qwen2/phi3 llama variants (sliding window, qkv
bias, fused-weight conversion), falcon, opt, HF mappers, paged decode.

Reference analog: tests/unit/inference/v2/model_implementations/ — per-arch
forward correctness + weight mapping.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.llama import (
    TINY_LLAMA, LlamaConfig, LlamaForCausalLM, random_tokens)
from deepspeed_tpu.models.families import (
    MISTRAL_7B, PHI3_MINI, QWEN2_7B, config_from_hf, convert_hf_state_dict,
    export_hf_state_dict)
from deepspeed_tpu.models.falcon import (
    TINY_FALCON, FalconForCausalLM, convert_hf_falcon, falcon_tensor_rules)
from deepspeed_tpu.models.opt import (
    TINY_OPT, OPTForCausalLM, convert_hf_opt, opt_tensor_rules)


def _tiny_llama_variant(**kw):
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_layers=2, num_heads=4, num_kv_heads=2,
                max_seq_len=128, dtype=jnp.float32)
    base.update(kw)
    return LlamaConfig(**base)


# ------------------------------------------------------------- llama variants
def test_presets_have_arch_knobs():
    assert MISTRAL_7B.sliding_window == 4096
    assert QWEN2_7B.attention_bias
    assert PHI3_MINI.num_kv_heads == PHI3_MINI.num_heads


def test_qwen2_style_bias_params_exist_and_train():
    cfg = _tiny_llama_variant(attention_bias=True)
    model = LlamaForCausalLM(cfg)
    batch = random_tokens(2, 16, vocab_size=cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    assert "bias" in params["model"]["layer_0"]["attn"]["wq"]
    loss = model.apply({"params": params}, batch)
    assert jnp.isfinite(loss)


def test_sliding_window_restricts_context():
    # with window=4, token t must be independent of tokens < t-3
    cfg = _tiny_llama_variant(sliding_window=4, num_kv_heads=4)
    model = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(0, 256, size=(1, 16)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]

    def logits_of(ids_arr):
        return model.apply({"params": params}, jnp.asarray(ids_arr),
                           method=lambda m, x: m.model(x))

    base = logits_of(ids)
    mutated = ids.copy()
    mutated[0, 0] = (mutated[0, 0] + 7) % 256  # outside the window of t=15
    alt = logits_of(mutated)
    np.testing.assert_allclose(np.asarray(base[0, -1]), np.asarray(alt[0, -1]),
                               atol=1e-5)
    mutated2 = ids.copy()
    mutated2[0, 14] = (mutated2[0, 14] + 7) % 256  # inside the window of t=15
    alt2 = logits_of(mutated2)
    assert np.abs(np.asarray(base[0, -1]) - np.asarray(alt2[0, -1])).max() > 1e-4


def test_config_from_hf_variants():
    mistral = config_from_hf({"model_type": "mistral", "vocab_size": 32000,
                              "hidden_size": 128, "intermediate_size": 256,
                              "num_hidden_layers": 2, "num_attention_heads": 4,
                              "num_key_value_heads": 2, "sliding_window": 1024})
    assert mistral.sliding_window == 1024 and not mistral.attention_bias
    qwen = config_from_hf({"model_type": "qwen2", "vocab_size": 1000,
                           "hidden_size": 128, "intermediate_size": 256,
                           "num_hidden_layers": 2, "num_attention_heads": 4})
    assert qwen.attention_bias and qwen.sliding_window is None
    with pytest.raises(ValueError):
        config_from_hf({"model_type": "falcon", "vocab_size": 10,
                        "hidden_size": 8, "intermediate_size": 16,
                        "num_hidden_layers": 1, "num_attention_heads": 2})


# ------------------------------------------------------------- HF conversion
def test_hf_roundtrip_matches_forward():
    cfg = _tiny_llama_variant(attention_bias=True)
    model = LlamaForCausalLM(cfg)
    batch = random_tokens(2, 12, vocab_size=cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(1), batch)["params"]
    hf = export_hf_state_dict(params, cfg)
    # add qwen2-style biases to the exported dict for the reimport
    for i in range(cfg.num_layers):
        lp = params["model"][f"layer_{i}"]["attn"]
        for nm, key in (("q", "wq"), ("k", "wk"), ("v", "wv")):
            hf[f"model.layers.{i}.self_attn.{nm}_proj.bias"] = \
                np.asarray(lp[key]["bias"]).reshape(-1)
    back = convert_hf_state_dict(hf, cfg)
    l1 = model.apply({"params": params}, batch)
    l2 = model.apply({"params": jax.tree.map(jnp.asarray, back)}, batch)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)


def test_phi3_fused_weights_split():
    cfg = _tiny_llama_variant(num_kv_heads=4)
    h, dh, d = cfg.num_heads, cfg.head_dim_, cfg.hidden_size
    rng = np.random.default_rng(0)
    hf = {"model.embed_tokens.weight": rng.normal(size=(cfg.vocab_size, d)),
          "model.norm.weight": np.ones(d),
          "lm_head.weight": rng.normal(size=(cfg.vocab_size, d))}
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        hf[p + "input_layernorm.weight"] = np.ones(d)
        hf[p + "post_attention_layernorm.weight"] = np.ones(d)
        hf[p + "self_attn.qkv_proj.weight"] = rng.normal(size=(3 * h * dh, d))
        hf[p + "self_attn.o_proj.weight"] = rng.normal(size=(d, h * dh))
        hf[p + "mlp.gate_up_proj.weight"] = rng.normal(
            size=(2 * cfg.intermediate_size, d))
        hf[p + "mlp.down_proj.weight"] = rng.normal(
            size=(d, cfg.intermediate_size))
    tree = convert_hf_state_dict(hf, cfg, model_type="phi3")
    lp = tree["model"]["layer_0"]
    assert lp["attn"]["wq"]["kernel"].shape == (d, h, dh)
    assert lp["mlp"]["w_gate"]["kernel"].shape == (d, cfg.intermediate_size)
    # split correctness: wq == first h*dh rows of the fused tensor (transposed)
    fused = hf["model.layers.0.self_attn.qkv_proj.weight"]
    np.testing.assert_allclose(
        lp["attn"]["wq"]["kernel"].reshape(d, h * dh), fused[:h * dh].T)
    fused_gu = hf["model.layers.0.mlp.gate_up_proj.weight"]
    np.testing.assert_allclose(lp["mlp"]["w_up"]["kernel"],
                               fused_gu[cfg.intermediate_size:].T)


# ------------------------------------------------------------- paged decode
@pytest.mark.slow
def test_mistral_style_paged_decode_matches_full():
    cfg = _tiny_llama_variant(sliding_window=8, num_kv_heads=4,
                              attention_bias=True)
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2, V2EngineConfig
    model = LlamaForCausalLM(cfg)
    batch = random_tokens(1, 16, vocab_size=cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    prompt = list(range(2, 14))
    out = InferenceEngineV2(params, cfg, V2EngineConfig(kv_block_size=8,
                                                        kv_num_blocks=32)) \
        .generate(prompt, max_new_tokens=3)
    # reference: greedy decode with the full (windowed) model forward
    ids = list(prompt)
    expect = []
    for _ in range(3):
        logits = model.apply({"params": params}, jnp.asarray([ids]),
                             method=lambda m, x: m.model(x))
        nxt = int(jnp.argmax(logits[0, -1]))
        expect.append(nxt)
        ids.append(nxt)
    assert out == expect, (out, expect)


# ------------------------------------------------------------- falcon / opt
@pytest.mark.slow
def test_falcon_trains_and_tp_rules():
    model = FalconForCausalLM(TINY_FALCON)
    config = {"train_batch_size": 8,
              "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
              "zero_optimization": {"stage": 3},
              "mesh": {"data": 4, "fsdp": 2}}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=config,
        example_batch=random_tokens(8, 16, vocab_size=TINY_FALCON.vocab_size),
        tensor_rules=falcon_tensor_rules)
    fixed = random_tokens(8, 16, vocab_size=TINY_FALCON.vocab_size, seed=0)
    losses = [float(engine.train_batch(batch=fixed)) for _ in range(5)]
    assert losses[-1] < losses[0] and all(np.isfinite(losses))


@pytest.mark.slow
def test_opt_trains():
    model = OPTForCausalLM(TINY_OPT)
    config = {"train_batch_size": 8,
              "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=config,
        example_batch=random_tokens(8, 16, vocab_size=TINY_OPT.vocab_size),
        tensor_rules=opt_tensor_rules)
    fixed = random_tokens(8, 16, vocab_size=TINY_OPT.vocab_size, seed=0)
    losses = [float(engine.train_batch(batch=fixed)) for _ in range(5)]
    assert losses[-1] < losses[0] and all(np.isfinite(losses))


def _fake_hf_falcon(cfg):
    rng = np.random.default_rng(1)
    d, h, hkv, dh = cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_
    hf = {"transformer.word_embeddings.weight":
          rng.normal(size=(cfg.vocab_size, d)).astype(np.float32),
          "transformer.ln_f.weight": np.ones(d, np.float32),
          "transformer.ln_f.bias": np.zeros(d, np.float32)}
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}."
        hf[p + "input_layernorm.weight"] = np.ones(d, np.float32)
        hf[p + "input_layernorm.bias"] = np.zeros(d, np.float32)
        hf[p + "self_attention.query_key_value.weight"] = \
            rng.normal(size=((h + 2 * hkv) * dh, d)).astype(np.float32) * 0.02
        hf[p + "self_attention.dense.weight"] = \
            rng.normal(size=(d, h * dh)).astype(np.float32) * 0.02
        hf[p + "mlp.dense_h_to_4h.weight"] = \
            rng.normal(size=(4 * d, d)).astype(np.float32) * 0.02
        hf[p + "mlp.dense_4h_to_h.weight"] = \
            rng.normal(size=(d, 4 * d)).astype(np.float32) * 0.02
    return hf


def test_falcon_hf_conversion_shapes_and_forward():
    cfg = TINY_FALCON
    tree = convert_hf_falcon(_fake_hf_falcon(cfg), cfg)
    model = FalconForCausalLM(cfg)
    batch = random_tokens(2, 12, vocab_size=cfg.vocab_size)
    loss = model.apply({"params": jax.tree.map(jnp.asarray, tree)}, batch)
    assert jnp.isfinite(loss)


def test_falcon_qkv_split_new_decoder_architecture():
    """40B/180B layout: qkv rows interleaved per KV group. Build a fused matrix
    from known per-head rows and check the grouped split recovers them."""
    import dataclasses
    from deepspeed_tpu.models.falcon import _split_falcon_qkv
    cfg = dataclasses.replace(TINY_FALCON, num_heads=4, num_kv_heads=2,
                              new_decoder_architecture=True)
    h, hkv, dh, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_, cfg.hidden_size
    g = h // hkv
    rng = np.random.default_rng(7)
    q_heads = rng.normal(size=(h, dh, d)).astype(np.float32)
    k_heads = rng.normal(size=(hkv, dh, d)).astype(np.float32)
    v_heads = rng.normal(size=(hkv, dh, d)).astype(np.float32)
    rows = []
    for grp in range(hkv):                     # interleaved: g q's, then k, v
        rows.extend(q_heads[grp * g:(grp + 1) * g])
        rows.append(k_heads[grp])
        rows.append(v_heads[grp])
    fused = np.concatenate(rows, axis=0)
    wq, wk, wv = _split_falcon_qkv(fused, cfg)
    np.testing.assert_array_equal(wq, q_heads.reshape(h * dh, d))
    np.testing.assert_array_equal(wk, k_heads.reshape(hkv * dh, d))
    np.testing.assert_array_equal(wv, v_heads.reshape(hkv * dh, d))


def test_falcon_new_decoder_architecture_conversion_and_forward():
    """40B-style checkpoint (dual ln_attn/ln_mlp + grouped qkv) converts and
    runs: param tree matches the model's init tree, loss finite."""
    import dataclasses
    cfg = dataclasses.replace(TINY_FALCON, num_heads=4, num_kv_heads=2,
                              new_decoder_architecture=True)
    d, h, hkv, dh = cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_
    rng = np.random.default_rng(3)
    hf = {"transformer.word_embeddings.weight":
          rng.normal(size=(cfg.vocab_size, d)).astype(np.float32),
          "transformer.ln_f.weight": np.ones(d, np.float32),
          "transformer.ln_f.bias": np.zeros(d, np.float32)}
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}."
        for ln in ("ln_attn", "ln_mlp"):
            hf[p + ln + ".weight"] = np.ones(d, np.float32)
            hf[p + ln + ".bias"] = np.zeros(d, np.float32)
        hf[p + "self_attention.query_key_value.weight"] = \
            rng.normal(size=((h + 2 * hkv) * dh, d)).astype(np.float32) * 0.02
        hf[p + "self_attention.dense.weight"] = \
            rng.normal(size=(d, h * dh)).astype(np.float32) * 0.02
        hf[p + "mlp.dense_h_to_4h.weight"] = \
            rng.normal(size=(4 * d, d)).astype(np.float32) * 0.02
        hf[p + "mlp.dense_4h_to_h.weight"] = \
            rng.normal(size=(d, 4 * d)).astype(np.float32) * 0.02
    tree = convert_hf_falcon(hf, cfg)
    model = FalconForCausalLM(cfg)
    batch = random_tokens(2, 12, vocab_size=cfg.vocab_size)
    init_tree = model.init(jax.random.PRNGKey(0), batch)["params"]
    assert jax.tree_util.tree_structure(jax.tree.map(lambda x: 0, tree)) == \
        jax.tree_util.tree_structure(jax.tree.map(lambda x: 0, init_tree))
    loss = model.apply({"params": jax.tree.map(jnp.asarray, tree)}, batch)
    assert jnp.isfinite(loss)


def test_falcon_qkv_split_rejects_grouped_without_flag():
    import dataclasses
    from deepspeed_tpu.models.falcon import _split_falcon_qkv
    cfg = dataclasses.replace(TINY_FALCON, num_heads=4, num_kv_heads=2)
    fused = np.zeros(((4 + 2 * 2) * cfg.head_dim_, cfg.hidden_size), np.float32)
    with pytest.raises(ValueError, match="new_decoder_architecture"):
        _split_falcon_qkv(fused, cfg)


def test_falcon_qkv_split_mha_interleaved():
    """Old MHA falcon (falcon-rw, hkv==h) packs rows per-head [q_i, k_i, v_i]
    (transformers FalconAttention._split_heads), not sequential q|k|v."""
    import dataclasses
    from deepspeed_tpu.models.falcon import _split_falcon_qkv
    cfg = dataclasses.replace(TINY_FALCON, num_heads=4, num_kv_heads=4)
    h, dh, d = 4, cfg.head_dim_, cfg.hidden_size
    rng = np.random.default_rng(0)
    qh = rng.normal(size=(h, dh, d)).astype(np.float32)
    kh = rng.normal(size=(h, dh, d)).astype(np.float32)
    vh = rng.normal(size=(h, dh, d)).astype(np.float32)
    fused = np.concatenate(
        [blk for i in range(h) for blk in (qh[i], kh[i], vh[i])], axis=0)
    wq, wk, wv = _split_falcon_qkv(fused, cfg)
    np.testing.assert_array_equal(wq, qh.reshape(h * dh, d))
    np.testing.assert_array_equal(wk, kh.reshape(h * dh, d))
    np.testing.assert_array_equal(wv, vh.reshape(h * dh, d))


def test_opt_hf_conversion_shapes_and_forward():
    cfg = TINY_OPT
    rng = np.random.default_rng(2)
    d, h, dh = cfg.hidden_size, cfg.num_heads, cfg.head_dim_
    hf = {"model.decoder.embed_tokens.weight":
          rng.normal(size=(cfg.vocab_size, d)).astype(np.float32),
          "model.decoder.embed_positions.weight":
          rng.normal(size=(cfg.max_seq_len + 2, d)).astype(np.float32),
          "model.decoder.final_layer_norm.weight": np.ones(d, np.float32),
          "model.decoder.final_layer_norm.bias": np.zeros(d, np.float32)}
    for i in range(cfg.num_layers):
        p = f"model.decoder.layers.{i}."
        for ln in ("self_attn_layer_norm", "final_layer_norm"):
            hf[p + ln + ".weight"] = np.ones(d, np.float32)
            hf[p + ln + ".bias"] = np.zeros(d, np.float32)
        for proj in ("q_proj", "k_proj", "v_proj", "out_proj"):
            hf[p + f"self_attn.{proj}.weight"] = \
                rng.normal(size=(d, d)).astype(np.float32) * 0.02
            hf[p + f"self_attn.{proj}.bias"] = np.zeros(d, np.float32)
        hf[p + "fc1.weight"] = rng.normal(size=(cfg.ffn_dim, d)).astype(np.float32) * 0.02
        hf[p + "fc1.bias"] = np.zeros(cfg.ffn_dim, np.float32)
        hf[p + "fc2.weight"] = rng.normal(size=(d, cfg.ffn_dim)).astype(np.float32) * 0.02
        hf[p + "fc2.bias"] = np.zeros(d, np.float32)
    tree = convert_hf_opt(hf, cfg)
    model = OPTForCausalLM(cfg)
    batch = random_tokens(2, 12, vocab_size=cfg.vocab_size)
    loss = model.apply({"params": jax.tree.map(jnp.asarray, tree)}, batch)
    assert jnp.isfinite(loss)


@pytest.mark.slow
def test_gemma_knobs_train_and_serve_parity():
    """Gemma = llama variant (gelu_tanh gated MLP, (1+scale) norms, sqrt(d)
    embedding normalizer, tied head): trains and paged-serves with the same
    policy (reference gemma container alias)."""
    import dataclasses

    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, V2EngineConfig)

    cfg = dataclasses.replace(
        TINY_LLAMA, dtype=jnp.float32, tie_embeddings=True,
        hidden_act="gelu_tanh", rms_scale_offset=True, scale_embeddings=True,
        logits_soft_cap=30.0, num_kv_heads=4)
    model = LlamaForCausalLM(cfg)
    batch = random_tokens(4, 16, vocab_size=cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    # offset convention: norm scales init at ZERO (1+0 == ones init applied)
    assert np.allclose(np.asarray(
        params["model"]["final_norm"]["scale"]), 0.0)
    assert np.isfinite(float(model.apply({"params": params}, batch)))

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}}},
        example_batch=batch)
    fixed = random_tokens(8, 16, vocab_size=cfg.vocab_size, seed=2)
    losses = [float(engine.train_batch(batch=fixed)) for _ in range(5)]
    assert losses[-1] < losses[0]

    # paged serve parity on the trained weights
    trained = jax.device_get(engine.state.params)
    serve = InferenceEngineV2(trained, cfg, V2EngineConfig(kv_block_size=16,
                                                           kv_num_blocks=64))
    prompt = [int(x) for x in fixed["input_ids"][0][:9]]
    got = serve.generate(list(prompt), max_new_tokens=4)
    ids = list(prompt)
    for _ in range(4):
        logits = model.apply({"params": trained},
                             {"input_ids": np.asarray([ids], np.int32)},
                             method=LlamaForCausalLM.logits)
        ids.append(int(np.argmax(np.asarray(logits)[0, -1])))
    assert got == ids[len(prompt):], (got, ids[len(prompt):])


def test_gemma_config_from_hf():
    cfg = config_from_hf({
        "model_type": "gemma", "vocab_size": 256000, "hidden_size": 2048,
        "intermediate_size": 16384, "num_hidden_layers": 18,
        "num_attention_heads": 8, "num_key_value_heads": 1, "head_dim": 256,
        "tie_word_embeddings": True})
    assert cfg.hidden_act == "gelu_tanh" and cfg.rms_scale_offset
    assert cfg.scale_embeddings and cfg.head_dim_ == 256
    from deepspeed_tpu.models.families import GEMMA_2B
    assert GEMMA_2B.rms_norm_eps == 1e-6
    import pytest
    with pytest.raises(ValueError, match="gemma2|llama-family"):
        config_from_hf({"model_type": "gemma2", "vocab_size": 4,
                        "hidden_size": 4, "intermediate_size": 4,
                        "num_hidden_layers": 1, "num_attention_heads": 1})
