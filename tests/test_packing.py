"""Sequence packing (data_pipeline/packing.py): packed batches train
identically to the same documents padded one-per-row (the segment mask +
per-document positions + target-gated loss make packing transparent)."""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.data_pipeline import pack_sequences, packing_efficiency
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def docs(rng, n, lo=5, hi=20, vocab=128):
    return [rng.integers(1, vocab, size=rng.integers(lo, hi)).astype(np.int32)
            for _ in range(n)]


def test_pack_shapes_masks_positions():
    rng = np.random.default_rng(0)
    batches = pack_sequences(docs(rng, 12), batch_size=2, seq_len=32)
    assert all(b["input_ids"].shape == (2, 32) for b in batches)
    b0 = batches[0]
    # positions restart at each segment start; padding has segment -1
    for r in range(2):
        seg_row, pos_row = b0["segment_ids"][r], b0["positions"][r]
        for s in np.unique(seg_row[seg_row >= 0]):
            sel = pos_row[seg_row == s]
            assert sel[0] == 0 and np.array_equal(sel, np.arange(len(sel)))
            # first token of every doc is not a loss target
            first = np.argmax(seg_row == s)
            assert b0["loss_mask"][r, first] == 0.0
    assert (b0["loss_mask"][b0["segment_ids"] < 0] == 0).all()
    # long docs split across rows
    long = pack_sequences([np.arange(70)], batch_size=1, seq_len=32)
    assert sum((b["segment_ids"] >= 0).sum() for b in long) == 70
    assert 0 < packing_efficiency(batches) <= 1


def test_packed_loss_equals_unpacked():
    """Mean CE over a packed batch == over the same docs one-per-row: the
    kernel segment mask + position restart + target gating are exactly
    per-document training."""
    rng = np.random.default_rng(1)
    ds = docs(rng, 6, lo=6, hi=14)
    cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                      num_layers=2, num_heads=2, num_kv_heads=2,
                      max_seq_len=64, dtype=jnp.float32,
                      attention_backend="xla")
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": np.zeros((1, 8), np.int32)})["params"]

    packed = pack_sequences(ds, batch_size=2, seq_len=32)

    def loss(batch):
        return float(model.apply(
            {"params": params},
            {k: jnp.asarray(v) for k, v in batch.items()}))
    # token-weighted mean over packed batches
    pl, pw = 0.0, 0.0
    for b in packed:
        w = float(b["loss_mask"].sum())
        pl += loss(b) * w
        pw += w
    packed_loss = pl / pw

    # one doc per row, padded (segment ids still confine the pad row-tail)
    ul, uw = 0.0, 0.0
    for d in ds:
        b = pack_sequences([d], batch_size=1, seq_len=32)[0]
        w = float(b["loss_mask"].sum())
        ul += loss(b) * w
        uw += w
    np.testing.assert_allclose(packed_loss, ul / uw, rtol=1e-5)


def test_packed_training_with_flash_kernel_engine():
    """End-to-end: engine.train_batch on packed batches with the flash
    backend (in-kernel segment masking) decreases the loss."""
    rng = np.random.default_rng(2)
    cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                      num_layers=2, num_heads=2, num_kv_heads=2,
                      max_seq_len=64, dtype=jnp.float32,
                      attention_backend="flash")
    n_dev = jax.device_count()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=LlamaForCausalLM(cfg),
        config={"train_batch_size": n_dev,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}}},
        example_batch={"input_ids": np.zeros((2, 32), np.int32)})
    batches = pack_sequences(docs(rng, 8 * n_dev, vocab=128),
                             batch_size=n_dev, seq_len=32)
    fixed = batches[0]
    losses = [float(jax.device_get(engine.train_batch(batch=fixed)))
              for _ in range(4)]
    assert losses[-1] < losses[0], losses
