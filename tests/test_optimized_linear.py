"""OptimizedLinear / LoRA / quantized linear tests.

Reference analog: tests/unit/linear/ (test_quant_param, test_linear behavior
vs dense baselines).
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deepspeed_tpu.linear import (
    LoRAConfig, LoRAOptimizedLinear, OptimizedLinear, QuantizationConfig,
    QuantizedLinear, lora_trainable_mask, make_lora_optimizer)


def test_factory_dispatch():
    assert isinstance(OptimizedLinear(8, 16), nn.Dense)
    assert isinstance(OptimizedLinear(8, 16, lora_config=LoRAConfig(lora_r=4)),
                      LoRAOptimizedLinear)
    assert isinstance(OptimizedLinear(8, 16,
                                      quantization_config=QuantizationConfig()),
                      QuantizedLinear)


def test_quantized_linear_close_to_fp():
    layer = QuantizedLinear(input_dim=64, output_dim=32,
                            quantization_config=QuantizationConfig(q_bits=8,
                                                                   group_size=64),
                            dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 64))
    variables = layer.init(jax.random.PRNGKey(0), x)
    assert "frozen_params" in variables and "params" not in variables.get("params", {})
    codes, scale = variables["frozen_params"]["weight_q"]
    assert codes.dtype == jnp.int8
    y = layer.apply(variables, x)
    # reconstruct the dense weight and compare
    w = (codes.astype(jnp.float32) * scale).ravel()[:64 * 32].reshape(64, 32)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x @ w), rtol=1e-5,
                               atol=1e-5)


def test_quantization_error_scales_with_bits():
    # actual reconstruction error of the grouped quantizer at each bit width
    from deepspeed_tpu.linear.optimized_linear import (
        _dequantize_grouped, _quantize_grouped)
    w_true = jax.random.normal(jax.random.PRNGKey(0), (128, 64)) * 0.1
    errs = {}
    for bits in (4, 8):
        codes, scale = _quantize_grouped(w_true, bits, group_size=128)
        w = _dequantize_grouped(codes, scale, (128, 64), dtype=jnp.float32)
        errs[bits] = float(jnp.abs(w - w_true).mean())
    assert errs[8] < errs[4] < 0.02  # finer resolution at 8 bits, both sane
    assert errs[8] < 0.002


def test_lora_linear_starts_as_base_and_trains_only_adapters():
    lc = LoRAConfig(lora_r=4, lora_alpha=8)
    layer = LoRAOptimizedLinear(input_dim=16, output_dim=8, lora_config=lc,
                                dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 16))
    variables = layer.init(jax.random.PRNGKey(0), x)
    # B starts at zero → output equals frozen base matmul
    base = variables["frozen_params"]["weight"]
    y0 = layer.apply(variables, x)
    np.testing.assert_allclose(np.asarray(y0), np.asarray(x @ base), rtol=1e-5,
                               atol=1e-5)
    # only lora_a / lora_b are trainable params
    assert set(variables["params"].keys()) == {"lora_a", "lora_b"}

    target = jnp.ones((2, 8))

    def loss_fn(params):
        y = layer.apply({"params": params,
                         "frozen_params": variables["frozen_params"]}, x)
        return jnp.mean((y - target) ** 2)

    tx = optax.adam(1e-2)
    params = variables["params"]
    state = tx.init(params)
    l0 = float(loss_fn(params))
    for _ in range(50):
        g = jax.grad(loss_fn)(params)
        upd, state = tx.update(g, state, params)
        params = optax.apply_updates(params, upd)
    assert float(loss_fn(params)) < 0.1 * l0
    # frozen base untouched by construction (separate collection)
    np.testing.assert_array_equal(np.asarray(variables["frozen_params"]["weight"]),
                                  np.asarray(base))


def test_lora_with_quantized_base():
    lc = LoRAConfig(lora_r=4)
    layer = LoRAOptimizedLinear(input_dim=32, output_dim=16, lora_config=lc,
                                quantization_config=QuantizationConfig(
                                    q_bits=8, group_size=32),
                                dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 32))
    variables = layer.init(jax.random.PRNGKey(0), x)
    codes, scale = variables["frozen_params"]["weight_q"]
    assert codes.dtype == jnp.int8
    y = layer.apply(variables, x)
    assert y.shape == (2, 16) and jnp.isfinite(y).all()


def test_lora_mask_and_masked_optimizer():
    params = {"layer": {"lora_a": jnp.ones((4, 2)), "lora_b": jnp.zeros((2, 4)),
                        "kernel": jnp.ones((4, 4))}}
    mask = lora_trainable_mask(params)
    assert mask["layer"]["lora_a"] and mask["layer"]["lora_b"]
    assert not mask["layer"]["kernel"]

    tx = make_lora_optimizer(optax.sgd(0.1), params)
    state = tx.init(params)
    grads = jax.tree.map(jnp.ones_like, params)
    upd, _ = tx.update(grads, state, params)
    assert float(jnp.abs(upd["layer"]["kernel"]).sum()) == 0.0  # frozen
    assert float(jnp.abs(upd["layer"]["lora_a"]).sum()) > 0.0
