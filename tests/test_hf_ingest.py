"""Unified HF-checkpoint ingestion tests (engine_factory analog).

Reference analog: inference/v2/engine_factory.py building per-arch engines
from an HF checkpoint; per-family numeric parity lives in the family tests —
here the dispatch, config mapping, and an end-to-end forward per arch class.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.hf import from_hf_checkpoint, supported_model_types

MINIMAL = {
    "llama": {"model_type": "llama", "vocab_size": 128, "hidden_size": 32,
              "intermediate_size": 64, "num_hidden_layers": 2,
              "num_attention_heads": 4},
    "mixtral": {"model_type": "mixtral", "vocab_size": 128,
                "hidden_size": 32, "intermediate_size": 64,
                "num_hidden_layers": 2, "num_attention_heads": 4,
                "num_local_experts": 4},
    "qwen2_moe": {"model_type": "qwen2_moe", "vocab_size": 128,
                  "hidden_size": 32, "num_hidden_layers": 2,
                  "num_attention_heads": 4, "num_experts": 4,
                  "moe_intermediate_size": 16,
                  "shared_expert_intermediate_size": 32},
    "falcon": {"model_type": "falcon", "vocab_size": 128, "hidden_size": 32,
               "num_hidden_layers": 2, "num_attention_heads": 4,
               "multi_query": True},
    "opt": {"model_type": "opt", "vocab_size": 128, "hidden_size": 32,
            "ffn_dim": 64, "num_hidden_layers": 2,
            "num_attention_heads": 4},
    "bloom": {"model_type": "bloom", "vocab_size": 128, "hidden_size": 32,
              "n_layer": 2, "n_head": 4},
    "gpt2": {"model_type": "gpt2", "vocab_size": 128, "n_embd": 32,
             "n_layer": 2, "n_head": 4},
    "gpt_neox": {"model_type": "gpt_neox", "vocab_size": 128,
                 "hidden_size": 32, "intermediate_size": 64,
                 "num_hidden_layers": 2, "num_attention_heads": 4},
    "t5": {"model_type": "t5", "vocab_size": 128, "d_model": 32,
           "d_ff": 64, "num_layers": 2, "num_heads": 4, "d_kv": 8},
    "gemma2": {"model_type": "gemma2", "vocab_size": 128, "hidden_size": 32,
               "intermediate_size": 64, "num_hidden_layers": 2,
               "num_attention_heads": 4, "head_dim": 8,
               "query_pre_attn_scalar": 8},
}


def test_all_supported_types_dispatch_config_only():
    """Every advertised model_type builds its (model, cfg) from a minimal HF
    config dict; unknown types raise with the supported list."""
    assert set(MINIMAL) <= set(supported_model_types())
    for mt, hf in MINIMAL.items():
        model, cfg, params = from_hf_checkpoint(hf)
        assert params is None
        assert model is not None and cfg is not None, mt
    with pytest.raises(ValueError, match="supported"):
        from_hf_checkpoint({"model_type": "mamba"})


def test_llama_roundtrip_through_unified_ingest():
    """export -> from_hf_checkpoint == original forward (the dispatch wires
    the right converter, not just the right config)."""
    from deepspeed_tpu.models.families import export_hf_state_dict
    from deepspeed_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                            random_tokens)
    cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                      num_layers=2, num_heads=4, num_kv_heads=2,
                      max_seq_len=64, dtype=jnp.float32)
    model = LlamaForCausalLM(cfg)
    batch = random_tokens(2, 12, vocab_size=128)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    hf_state = export_hf_state_dict(params, cfg)
    hf_cfg = {"model_type": "llama", "vocab_size": 128, "hidden_size": 32,
              "intermediate_size": 64, "num_hidden_layers": 2,
              "num_attention_heads": 4, "num_key_value_heads": 2,
              "max_position_embeddings": 64,
              "rope_theta": cfg.rope_theta}
    model2, cfg2, params2 = from_hf_checkpoint(hf_cfg, hf_state)
    import dataclasses
    model2 = type(model2)(dataclasses.replace(cfg2, dtype=jnp.float32))
    l1 = float(model.apply({"params": params}, batch))
    l2 = float(model2.apply({"params": jax.tree.map(jnp.asarray, params2)},
                            batch))
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_unsupported_variants_raise_clearly():
    with pytest.raises(ValueError, match="falcon-rw"):
        from_hf_checkpoint({**MINIMAL["falcon"], "alibi": True})
    with pytest.raises(ValueError, match="opt-350m"):
        from_hf_checkpoint({**MINIMAL["opt"], "word_embed_proj_dim": 16})
    with pytest.raises(ValueError, match="post-LN"):
        from_hf_checkpoint({**MINIMAL["opt"],
                            "do_layer_norm_before": False})
    with pytest.raises(ValueError, match="num_kv_heads"):
        from_hf_checkpoint({**MINIMAL["falcon"],
                            "new_decoder_architecture": True,
                            "multi_query": False})
