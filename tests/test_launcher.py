"""Launcher unit tests (reference shape: tests/unit/launcher/ — arg/hostfile
parsing and runner command construction, no ssh)."""

import base64
import json
import os
import subprocess
import sys

import pytest

from deepspeed_tpu.launcher import launch as launch_mod
from deepspeed_tpu.launcher import multinode_runner as mnr
from deepspeed_tpu.launcher import runner
from deepspeed_tpu.launcher.constants import (ENV_COORDINATOR,
                                              ENV_NUM_PROCESSES,
                                              ENV_PROCESS_ID)


def test_fetch_hostfile(tmp_path):
    hostfile = tmp_path / "hostfile"
    hostfile.write_text(
        "# comment\n"
        "worker-0 slots=4\n"
        "worker-1 slots=8\n"
        "\n")
    pool = runner.fetch_hostfile(str(hostfile))
    assert pool == {"worker-0": 4, "worker-1": 8}


def test_fetch_hostfile_missing_returns_empty():
    assert runner.fetch_hostfile("/nonexistent/hostfile") == {}


def test_fetch_hostfile_bad_format(tmp_path):
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("worker-0 4\n")
    with pytest.raises(ValueError):
        runner.fetch_hostfile(str(hostfile))


def test_fetch_hostfile_duplicate(tmp_path):
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("w slots=2\nw slots=2\n")
    with pytest.raises(ValueError):
        runner.fetch_hostfile(str(hostfile))


def test_resource_filter_include():
    hosts = {"a": [0, 1, 2, 3], "b": [0, 1, 2, 3]}
    out = runner.parse_resource_filter(hosts, include_str="a@0,2")
    assert out == {"a": [0, 2]}
    out = runner.parse_resource_filter(hosts, include_str="a;b@1")
    assert out == {"a": [0, 1, 2, 3], "b": [1]}


def test_resource_filter_exclude():
    hosts = {"a": [0, 1], "b": [0, 1]}
    out = runner.parse_resource_filter(hosts, exclude_str="b")
    assert out == {"a": [0, 1]}
    out = runner.parse_resource_filter(hosts, exclude_str="b@0")
    assert out == {"a": [0, 1], "b": [1]}


def test_resource_filter_mutually_exclusive():
    with pytest.raises(ValueError):
        runner.parse_resource_filter({"a": [0]}, include_str="a", exclude_str="a")


def test_resource_filter_unknown_host():
    with pytest.raises(ValueError):
        runner.parse_resource_filter({"a": [0]}, include_str="zzz")


def test_world_info_roundtrip():
    info = {"a": [0, 1], "b": [0]}
    encoded = runner.encode_world_info(info)
    assert launch_mod.decode_world_info(encoded) == info


def test_build_rank_env_global_ids():
    world = {"a": [0, 1], "b": [0, 1, 2]}
    env = launch_mod.build_rank_env(world, node_rank=1, local_rank=2,
                                    coordinator_addr="a", coordinator_port=1234)
    assert env[ENV_PROCESS_ID] == "4"  # 2 procs on node a + local_rank 2
    assert env[ENV_NUM_PROCESSES] == "5"
    assert env[ENV_COORDINATOR] == "a:1234"


class _Args:
    def __init__(self, **kw):
        self.user_script = kw.pop("user_script", "train.py")
        self.user_args = kw.pop("user_args", ["--foo", "1"])
        self.coordinator_addr = kw.pop("coordinator_addr", "worker-0")
        self.coordinator_port = kw.pop("coordinator_port", 8476)
        self.nproc_per_node = kw.pop("nproc_per_node", None)
        self.tpu_name = kw.pop("tpu_name", None)
        self.tpu_zone = kw.pop("tpu_zone", None)
        for k, v in kw.items():
            setattr(self, k, v)


def test_pdsh_runner_cmd():
    args = _Args()
    world = runner.encode_world_info({"worker-0": [0], "worker-1": [0]})
    r = mnr.PDSHRunner(args, world)
    cmd = r.get_cmd({"PATH": "/usr/bin"}, {"worker-0": [0], "worker-1": [0]})
    assert cmd[0] == "pdsh"
    assert "-w" in cmd and "worker-0,worker-1" in cmd
    payload = cmd[-1]
    assert "deepspeed_tpu.launcher.launch" in payload
    assert f"--world_info={world}" in payload
    assert "train.py" in payload and "--foo" in payload


def test_ssh_runner_node_cmd():
    args = _Args()
    world = runner.encode_world_info({"h0": [0], "h1": [0]})
    r = mnr.SSHRunner(args, world)
    cmd = r.get_node_cmd("h1", 1, {"XLA_FLAGS": "--foo"})
    assert cmd[0] == "ssh" and "h1" in cmd
    remote = cmd[-1]
    assert "--node_rank=1" in remote
    assert "export XLA_FLAGS=" in remote


def test_gcloud_runner_cmd():
    args = _Args(tpu_name="my-pod", tpu_zone="us-central2-b")
    r = mnr.GcloudTPURunner(args, runner.encode_world_info({}))
    cmd = r.get_cmd({}, {})
    assert cmd[:5] == ["gcloud", "compute", "tpus", "tpu-vm", "ssh"]
    assert "my-pod" in cmd and "--worker=all" in cmd
    assert any(c.startswith("--zone=") for c in cmd)
    assert any(c.startswith("--command=") for c in cmd)


def test_slurm_runner_cmd():
    args = _Args(slurm_comment="")
    world = runner.encode_world_info({"n0": [0], "n1": [0]})
    r = mnr.SlurmRunner(args, world)
    cmd = r.get_cmd({}, {"n0": [0], "n1": [0]})
    assert cmd[0] == "srun" and "-N" in cmd and "2" in cmd


def test_xpk_runner_cmd():
    """GKE multislice dispatch via xpk workload create (the TPU-pod analog
    of the reference SLURM runner; pure command construction)."""
    args = _Args(xpk_cluster="my-cluster", xpk_workload="job1",
                 xpk_docker_image="gcr.io/p/img:latest",
                 tpu_type="v5litepod-256", num_slices=2)
    r = mnr.XpkRunner(args, runner.encode_world_info({}))
    cmd = r.get_cmd({"XLA_FLAGS": "--bar"}, {})
    assert cmd[:3] == ["xpk", "workload", "create"]
    assert "--cluster=my-cluster" in cmd
    assert "--workload=job1" in cmd
    assert "--tpu-type=v5litepod-256" in cmd
    assert "--num-slices=2" in cmd
    assert "--docker-image=gcr.io/p/img:latest" in cmd
    command = [c for c in cmd if c.startswith("--command=")][0]
    assert "train.py" in command and "export XLA_FLAGS=" in command


def test_xpk_cluster_arg_selects_and_validates():
    a = runner.parse_args(["--xpk_cluster", "c1", "--tpu_type",
                           "v5litepod-16", "train.py"])
    assert a.xpk_cluster == "c1" and a.num_slices == 1
    import pytest
    with pytest.raises(ValueError, match="tpu_type"):
        runner.main(["--xpk_cluster", "c1", "train.py"])


def test_mpi_runner_cmd():
    args = _Args()
    world = runner.encode_world_info({"n0": [0], "n1": [0]})
    r = mnr.MPIRunner(args, world)
    cmd = r.get_cmd({"JAX_PLATFORMS": "cpu"}, {"n0": [0], "n1": [0]})
    assert cmd[0] == "mpirun"
    assert "-host" in cmd and "n0,n1" in cmd
    assert "-x" in cmd  # env export


def test_launch_spawns_and_propagates_failure(tmp_path):
    """launch.py kills the group when one child fails (reference launch.py
    signal/monitor loop)."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys, time\n"
        "rank = int(os.environ['DSTPU_PROCESS_ID'])\n"
        "if rank == 1:\n"
        "    sys.exit(3)\n"
        "time.sleep(30)\n")
    world = runner.encode_world_info({"localhost": [0, 1]})
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
         f"--world_info={world}", "--node_rank=0",
         "--coordinator_addr=127.0.0.1", "--coordinator_port=9999",
         str(script)],
        cwd="/root/repo", capture_output=True, text=True, timeout=60)
    assert proc.returncode == 3


@pytest.mark.slow
def test_launch_success(tmp_path):
    script = tmp_path / "ok.py"
    script.write_text("print('hello from', __import__('os').environ['DSTPU_PROCESS_ID'])\n")
    world = runner.encode_world_info({"localhost": [0, 1]})
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
         f"--world_info={world}", "--node_rank=0",
         "--coordinator_addr=127.0.0.1", "--coordinator_port=9999",
         str(script)],
        cwd="/root/repo", capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0


def test_core_binding_prefix_slices_cores():
    from deepspeed_tpu.launcher.launch import core_binding_prefix
    import os
    n = os.cpu_count() or 1
    cores = sorted(os.sched_getaffinity(0))
    if len(cores) >= 2:
        p0 = core_binding_prefix(0, 2)
        p1 = core_binding_prefix(1, 2)
        assert p0[:2] == ["taskset", "-c"]
        assert p0[2].split(",")[0] == str(cores[0])
        assert p1[2].split(",")[-1] == str(cores[-1])
        # slices are disjoint and only use allowed cores
        s0 = {int(c) for c in p0[2].split(",")}
        s1 = {int(c) for c in p1[2].split(",")}
        assert not (s0 & s1) and (s0 | s1) <= set(cores)
    assert core_binding_prefix(0, len(cores) + 1) == []


def test_discover_cluster_env_chains(monkeypatch):
    from deepspeed_tpu.comm.mesh import discover_cluster_env
    for var in ("DSTPU_NUM_PROCESSES", "DSTPU_PROCESS_ID",
                "DSTPU_COORDINATOR_ADDRESS", "DSTPU_AUTO_MPI_DISCOVERY",
                "WORLD_SIZE", "RANK", "MASTER_ADDR", "MASTER_PORT",
                "OMPI_COMM_WORLD_SIZE", "OMPI_COMM_WORLD_RANK",
                "SLURM_NTASKS", "SLURM_PROCID", "SLURM_NODELIST",
                "SLURM_STEP_NODELIST"):
        monkeypatch.delenv(var, raising=False)
    assert discover_cluster_env() == {}
    monkeypatch.setenv("WORLD_SIZE", "4")
    monkeypatch.setenv("RANK", "2")
    monkeypatch.setenv("MASTER_ADDR", "10.0.0.1")
    d = discover_cluster_env()
    assert d == {"num_processes": 4, "process_id": 2,
                 "coordinator_address": "10.0.0.1:29500"}
    # DSTPU_* takes precedence over torch-style
    monkeypatch.setenv("DSTPU_NUM_PROCESSES", "8")
    monkeypatch.setenv("DSTPU_PROCESS_ID", "5")
    d = discover_cluster_env()
    assert d["num_processes"] == 8 and d["process_id"] == 5
    # SLURM fallback
    for var in ("DSTPU_NUM_PROCESSES", "DSTPU_PROCESS_ID", "WORLD_SIZE", "RANK"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.delenv("MASTER_ADDR", raising=False)
    monkeypatch.setenv("SLURM_NTASKS", "16")
    monkeypatch.setenv("SLURM_PROCID", "3")
    monkeypatch.setenv("SLURM_NODELIST", "tpu-pod-node[1-4],tpu-pod-node7")
    # stray SLURM env without opt-in must NOT trigger discovery (a bare
    # python under sbatch would otherwise hang waiting for peers)
    assert discover_cluster_env() == {}
    monkeypatch.setenv("DSTPU_AUTO_MPI_DISCOVERY", "1")
    d = discover_cluster_env()
    assert d["num_processes"] == 16 and d["process_id"] == 3
    assert d["coordinator_address"].startswith("tpu-pod-node1:")
    monkeypatch.delenv("DSTPU_AUTO_MPI_DISCOVERY")


@pytest.mark.slow
def test_bench_decode_smoke_reports_mixed_load(tmp_path):
    """bench_decode.py end-to-end on the tiny CPU config: one JSON line with
    the decode + mixed-load (TTFT) fields — guards the round-end bench
    artifact against silent breakage."""
    import json
    import subprocess
    import sys

    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        "import bench_decode; bench_decode.main()")
    env = dict(os.environ, DSTPU_DECODE_TINY="1", DSTPU_DECODE_BATCH="2",
               DSTPU_DECODE_PROMPT="32", DSTPU_DECODE_STEPS="4",
               DSTPU_DECODE_MIXED_STEPS="16")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=600,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))))
    assert r.returncode == 0, r.stderr[-2000:]
    row = json.loads(r.stdout.strip().splitlines()[-1])
    assert row["metric"] == "llama_decode_tokens_per_sec"
    for key in ("mixed_tokens_per_sec", "ttft_p50_ms", "ttft_p95_ms"):
        assert key in row["extra"], row["extra"]
