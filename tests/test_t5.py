"""T5 family tests: bucketing, masking, training (v1.0 + v1.1), HF
conversion, greedy decode, TP parity.

Reference analog: t5 injection-policy cases under ``tests/unit/inference``.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.t5 import (
    TINY_T5, TINY_T5_V11, T5ForConditionalGeneration, convert_hf_t5,
    relative_position_bucket, t5_tensor_rules)


def _batch(bs=4, s=12, t=8, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "input_ids": rng.integers(2, TINY_T5.vocab_size,
                                  size=(bs, s)).astype(np.int32),
        "labels": rng.integers(2, TINY_T5.vocab_size,
                               size=(bs, t)).astype(np.int32),
    }


def test_relative_position_buckets():
    rel = jnp.arange(-20, 21)[None, :]
    bi = np.asarray(relative_position_bucket(rel, True, 32, 128))[0]
    assert bi.min() >= 0 and bi.max() < 32
    # bidirectional: sign splits halves; exact buckets near zero
    assert bi[20] == 0                       # rel 0
    assert bi[19] != bi[21]                  # -1 vs +1 in different halves
    causal = np.asarray(relative_position_bucket(rel, False, 32, 128))[0]
    assert (causal[21:] == 0).all()          # future positions clamp to 0
    assert causal.max() < 32


@pytest.mark.parametrize("cfg", [
    TINY_T5,
    pytest.param(TINY_T5_V11, marks=pytest.mark.slow),
], ids=["v1.0-tied-relu", "v1.1-untied-geglu"])
def test_t5_trains(cfg):
    model = T5ForConditionalGeneration(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
                "zero_optimization": {"stage": 2},
                "mesh": {"data": 2, "fsdp": 2, "tensor": 2}},
        example_batch=_batch(4), tensor_rules=t5_tensor_rules)
    fixed = _batch(8, seed=1)
    losses = [float(engine.train_batch(batch=fixed)) for _ in range(6)]
    assert losses[-1] < losses[0] and all(np.isfinite(losses))


@pytest.mark.slow
def test_encoder_mask_isolates_padding():
    model = T5ForConditionalGeneration(TINY_T5)
    b = _batch(2)
    mask = np.ones_like(b["input_ids"])
    mask[:, -4:] = 0
    b["attention_mask"] = mask
    params = model.init(jax.random.PRNGKey(0), b)["params"]
    base = np.asarray(model.apply({"params": params}, b,
                                  method=T5ForConditionalGeneration.logits))
    b2 = {**b, "input_ids": np.array(b["input_ids"], copy=True)}
    b2["input_ids"][:, -1] = (b2["input_ids"][:, -1] + 3) % TINY_T5.vocab_size
    got = np.asarray(model.apply({"params": params}, b2,
                                 method=T5ForConditionalGeneration.logits))
    np.testing.assert_allclose(got, base, rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_ignore_index_and_decoder_shift():
    model = T5ForConditionalGeneration(TINY_T5)
    b = _batch(2)
    params = model.init(jax.random.PRNGKey(1), b)["params"]
    loss = float(model.apply({"params": params}, b))
    assert np.isfinite(loss) and loss > 0
    b0 = {**b, "labels": np.full_like(b["labels"], -100)}
    assert float(model.apply({"params": params}, b0)) == 0.0


@pytest.mark.slow
def test_greedy_generate_shapes():
    model = T5ForConditionalGeneration(TINY_T5)
    b = _batch(2)
    params = model.init(jax.random.PRNGKey(2), b)["params"]
    out = model.generate_greedy(params, jnp.asarray(b["input_ids"]),
                                max_new_tokens=5)
    assert out.shape == (2, 5)
    assert np.asarray(out).max() < TINY_T5.vocab_size


def test_hf_conversion_structure():
    cfg = TINY_T5_V11
    rng = np.random.default_rng(4)
    d, h, dk, ff = cfg.d_model, cfg.num_heads, cfg.d_kv, cfg.d_ff

    def lin(o, i):
        return rng.normal(size=(o, i)).astype(np.float32) * 0.05

    hf = {"shared.weight": lin(cfg.vocab_size, d),
          "lm_head.weight": lin(cfg.vocab_size, d),
          "encoder.final_layer_norm.weight": np.ones(d, np.float32),
          "decoder.final_layer_norm.weight": np.ones(d, np.float32)}
    for stack, n, dec in (("encoder", cfg.num_layers, False),
                          ("decoder", cfg.n_dec_, True)):
        for i in range(n):
            p = f"{stack}.block.{i}.layer."
            hf[p + "0.layer_norm.weight"] = np.ones(d, np.float32)
            for m, shape in (("q", (h * dk, d)), ("k", (h * dk, d)),
                             ("v", (h * dk, d)), ("o", (d, h * dk))):
                hf[p + f"0.SelfAttention.{m}.weight"] = lin(*shape)
            if i == 0:
                hf[p + "0.SelfAttention.relative_attention_bias.weight"] = \
                    lin(cfg.relative_attention_num_buckets, h)
            ff_idx = 2 if dec else 1
            if dec:
                hf[p + "1.layer_norm.weight"] = np.ones(d, np.float32)
                for m, shape in (("q", (h * dk, d)), ("k", (h * dk, d)),
                                 ("v", (h * dk, d)), ("o", (d, h * dk))):
                    hf[p + f"1.EncDecAttention.{m}.weight"] = lin(*shape)
            hf[p + f"{ff_idx}.layer_norm.weight"] = np.ones(d, np.float32)
            hf[p + f"{ff_idx}.DenseReluDense.wi_0.weight"] = lin(ff, d)
            hf[p + f"{ff_idx}.DenseReluDense.wi_1.weight"] = lin(ff, d)
            hf[p + f"{ff_idx}.DenseReluDense.wo.weight"] = lin(d, ff)

    params = jax.tree.map(jnp.asarray, convert_hf_t5(hf, cfg))
    model = T5ForConditionalGeneration(cfg)
    b = _batch(2)
    ref = model.init(jax.random.PRNGKey(0), b)["params"]
    assert jax.tree.structure(ref) == jax.tree.structure(params)
    assert np.isfinite(float(model.apply({"params": params}, b)))
