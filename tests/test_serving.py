"""Serving-layer tests: continuous batching over the v2 ragged engine with
request lifecycle, streaming, admission control, drain, and the HTTP front
door — all hermetic on CPU with the tiny fp32 llama.

Every engine here uses the SAME kv/bucket shapes so jit compilations are
shared across tests (XLA static shapes — one compile per shape per process).
"""

import http.client
import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2, V2EngineConfig
from deepspeed_tpu.inference.v2.scheduler import SchedulerConfig
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM, TINY_LLAMA
from deepspeed_tpu.serving import (BackpressureError, InferenceServer,
                                   RequestState, ServerClosedError,
                                   ServingConfig, ServingFrontend)


def _tiny_fp32():
    return LlamaConfig(**{**TINY_LLAMA.__dict__, "dtype": jnp.float32,
                          "max_seq_len": 512})


@pytest.fixture(scope="module")
def model_and_params():
    cfg = _tiny_fp32()
    model = LlamaForCausalLM(cfg)
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    return cfg, params


KV_BLOCKS = 64  # shared across all engines: kv shape is a compile shape


def _engine(cfg, params):
    return InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=16, kv_num_blocks=KV_BLOCKS,
        scheduler=SchedulerConfig(max_tokens_per_step=64,
                                  prefill_buckets=(16, 32, 64))))


def _server(cfg, params, **kw):
    kw.setdefault("max_queue_depth", 32)
    return InferenceServer(_engine(cfg, params), ServingConfig(**kw))


def _prompts(rng, lengths, vocab):
    return [list(rng.integers(0, vocab, n)) for n in lengths]


# ---------------------------------------------------------------------------
# the acceptance workload: ≥8 concurrent mixed-length requests
# ---------------------------------------------------------------------------
def test_concurrent_workload_interleaving_parity_backpressure(model_and_params):
    cfg, params = model_and_params
    rng = np.random.default_rng(0)
    # request 0 is long (prompt 48, 24 new); 1..7 are short and finish first
    lengths = [48, 8, 12, 16, 8, 20, 8, 12]
    max_new = [24, 4, 6, 4, 8, 4, 6, 4]
    prompts = _prompts(rng, lengths, cfg.vocab_size)
    # worst-case blocks (16-token blocks): 5 + 1+2+2+1+2+1+1 = 15; watermark
    # 0.25 of 64 = 16 blocks, so the 8-request workload fits and a burst of
    # 1-block extras must start rejecting by the second extra
    server = _server(cfg, params, kv_high_watermark=0.25).start()
    try:
        reqs = [server.submit(p, max_new_tokens=m)
                for p, m in zip(prompts, max_new)]
        # stream one short request concurrently to prove live fan-out
        streamed = []
        t = threading.Thread(
            target=lambda: streamed.extend(reqs[1].stream(timeout=120)))
        t.start()

        # (c) backpressure: burst of extras while the 8 are in flight
        rejected, extras = 0, []
        for _ in range(15):
            try:
                extras.append(server.submit(_prompts(rng, [8], cfg.vocab_size)[0],
                                            max_new_tokens=4))
            except BackpressureError as e:
                rejected += 1
                assert e.retry_after_s > 0
        assert rejected > 0, "KV watermark never pushed back"

        for r in reqs + extras:
            r.result(timeout=300)
        t.join(timeout=10)
        assert all(r.state == RequestState.FINISHED for r in reqs + extras)
        assert all(r.finish_reason == "length" for r in reqs)

        # (a) interleaving: a later-submitted short finished before request 0
        assert any(r.finish_ts < reqs[0].finish_ts for r in reqs[1:]), \
            "no short request finished before the long one"

        # (b) parity: streamed tokens == direct single-request engine run
        assert streamed == reqs[1].tokens
        for p, m, r in zip(prompts, max_new, reqs):
            solo = _engine(cfg, params).generate(p, max_new_tokens=m)
            assert r.tokens == solo, f"uid {r.uid} diverged from solo run"

        # request-level metrics populated
        assert all(r.queue_wait_s > 0 and r.ttft_s > 0 for r in reqs)
        snap = server.metrics.snapshot()
        assert snap["requests_completed"] == len(reqs) + len(extras)
        assert snap["requests_rejected"] == rejected
        assert snap["ttft_mean_s"] > 0 and snap["tpot_mean_s"] > 0
        assert snap["queue_wait_mean_s"] > 0
        assert snap["kv_occupancy_peak"] > 0
        assert snap["tokens_generated"] == sum(len(r.tokens)
                                               for r in reqs + extras)
    finally:
        server.stop(drain_timeout=5.0)


def test_queue_depth_backpressure(model_and_params):
    """Queue-bound rejection, deterministic: the loop is not started, so
    submissions sit in the admission queue."""
    cfg, params = model_and_params
    server = _server(cfg, params, max_queue_depth=3)
    for _ in range(3):
        server.submit([1, 2, 3], max_new_tokens=2)
    with pytest.raises(BackpressureError) as ei:
        server.submit([1, 2, 3], max_new_tokens=2)
    assert ei.value.retry_after_s > 0
    assert server.metrics.snapshot()["requests_rejected"] == 1


def test_timeout_and_cancel(model_and_params):
    cfg, params = model_and_params
    server = _server(cfg, params).start()
    try:
        # deadline far shorter than a 500-token decode on this host
        timed = server.submit([3, 1, 4, 1, 5], max_new_tokens=500,
                              timeout_s=0.15)
        timed.wait(timeout=60)
        assert timed.state == RequestState.TIMED_OUT
        assert timed.finish_reason == "timeout"
        assert len(timed.tokens) < 500

        cancelled = server.submit([2, 7, 1, 8], max_new_tokens=500)
        it = cancelled.stream(timeout=60)
        first = next(it)                      # wait for decode to start
        cancelled.cancel()
        rest = list(it)                       # stream must terminate
        cancelled.wait(timeout=60)
        assert cancelled.state == RequestState.CANCELLED
        assert cancelled.finish_reason == "cancelled"
        assert [first] + rest == cancelled.tokens

        # engine state fully reaped afterwards: KV occupancy returns to 0
        deadline = time.monotonic() + 30
        while server.engine.kv_occupancy() > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert server.engine.kv_occupancy() == 0.0
        snap = server.metrics.snapshot()
        assert snap["requests_timed_out"] == 1
        assert snap["requests_cancelled"] == 1
    finally:
        server.stop(drain_timeout=5.0)


def test_graceful_drain(model_and_params):
    cfg, params = model_and_params
    server = _server(cfg, params).start()
    reqs = [server.submit([7, 7, 7, i + 1], max_new_tokens=6)
            for i in range(3)]
    assert server.drain(timeout=120), "drain timed out with work in flight"
    with pytest.raises(ServerClosedError):
        server.submit([1, 2, 3])
    # in-flight requests completed with their full budget
    for r in reqs:
        assert r.state == RequestState.FINISHED
        assert len(r.tokens) == 6
    server.stop(drain_timeout=5.0)
    assert not server.running


def test_oversized_request_fails_alone(model_and_params):
    """A request the engine can never hold fails itself, not the server."""
    cfg, params = model_and_params
    server = _server(cfg, params).start()
    try:
        with pytest.raises(ValueError):
            server.submit(list(range(600)), max_new_tokens=4)  # > max_seq_len
        ok = server.submit([5, 5, 5], max_new_tokens=3)
        assert ok.result(timeout=120) == ok.tokens and len(ok.tokens) == 3
    finally:
        server.stop(drain_timeout=5.0)


# ---------------------------------------------------------------------------
# HTTP front door on a real localhost socket
# ---------------------------------------------------------------------------
def _http(method, host, port, path, body=None):
    conn = http.client.HTTPConnection(host, port, timeout=300)
    try:
        conn.request(method, path,
                     body=None if body is None else json.dumps(body),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def test_frontend_generate_metrics_healthz(model_and_params):
    cfg, params = model_and_params
    server = _server(cfg, params).start()
    fe = ServingFrontend(server, port=0).start()
    host, port = fe.host, fe.port
    try:
        status, _, body = _http("GET", host, port, "/healthz")
        assert status == 200 and json.loads(body)["status"] == "serving"

        status, _, body = _http("POST", host, port, "/generate",
                                {"prompt_tokens": [9, 8, 7, 6],
                                 "max_new_tokens": 5})
        out = json.loads(body)
        assert status == 200 and len(out["tokens"]) == 5
        assert out["finish_reason"] == "length"
        solo = _engine(cfg, params).generate([9, 8, 7, 6], max_new_tokens=5)
        assert out["tokens"] == solo

        # streaming endpoint: http.client de-chunks transparently
        status, headers, body = _http("POST", host, port, "/generate",
                                      {"prompt_tokens": [9, 8, 7, 6],
                                       "max_new_tokens": 5, "stream": True})
        assert status == 200
        lines = [json.loads(l) for l in body.decode().splitlines() if l]
        assert [l["token"] for l in lines[:-1]] == solo
        assert lines[-1]["done"] is True

        status, _, err = _http("POST", host, port, "/generate", {"nope": 1})
        assert status == 400

        status, headers, body = _http("GET", host, port, "/metrics")
        assert status == 200
        text = body.decode()
        metrics = {}
        for line in text.splitlines():
            if line.startswith("#") or not line.strip():
                continue
            key, val = line.rsplit(" ", 1)
            metrics[key] = float(val)
        assert metrics["dstpu_serving_ttft_seconds_count"] > 0
        assert metrics["dstpu_serving_ttft_seconds_sum"] > 0
        assert metrics["dstpu_serving_tpot_seconds_sum"] > 0
        assert metrics["dstpu_serving_queue_wait_seconds_sum"] > 0
        assert metrics["dstpu_serving_kv_occupancy_peak"] > 0
        assert metrics["dstpu_serving_tokens_generated"] == 10
        assert metrics["dstpu_serving_requests_completed"] == 2

        # backpressure surfaces as 429 + Retry-After
        tiny = InferenceServer(_engine(cfg, params),
                               ServingConfig(max_queue_depth=0))
        fe2 = ServingFrontend(tiny, port=0).start()
        try:
            status, headers, body = _http("POST", fe2.host, fe2.port,
                                          "/generate",
                                          {"prompt_tokens": [1, 2]})
            assert status == 429 and "Retry-After" in headers
        finally:
            fe2.stop()

        # drain: healthz flips to 503, new work refused with 503
        server.drain(timeout=60)
        status, _, body = _http("GET", host, port, "/healthz")
        assert status == 503 and json.loads(body)["status"] == "draining"
        status, _, body = _http("POST", host, port, "/generate",
                                {"prompt_tokens": [1, 2, 3]})
        assert status == 503
    finally:
        fe.stop()
        server.stop(drain_timeout=5.0)


def test_monitor_export(model_and_params, tmp_path):
    """Serving metrics fan out through the deepspeed_tpu.monitor backends."""
    cfg, params = model_and_params
    from deepspeed_tpu.config.config import CSVConfig
    from deepspeed_tpu.monitor import CSVMonitor
    mon = CSVMonitor(CSVConfig(enabled=True, output_path=str(tmp_path),
                               job_name="serve"))
    server = _server(cfg, params).start()
    try:
        server.submit([4, 4, 4], max_new_tokens=3).result(timeout=120)
        server.metrics.export(mon, step=1)
    finally:
        server.stop(drain_timeout=5.0)
    written = list((tmp_path / "serve").glob("*.csv"))
    names = {p.stem for p in written}
    assert "serving_tokens_generated" in names
    assert "serving_ttft_mean_s" in names


# ---------------------------------------------------------------------------
# engine failure -> degraded health (load balancers must stop routing)
# ---------------------------------------------------------------------------
class _ExplodingEngine:
    """Minimal engine double whose step() always raises — the serve loop
    must fail the in-flight requests AND flip health to unhealthy."""

    def __init__(self):
        import types
        self.state = types.SimpleNamespace(max_context_length=512,
                                           get=lambda uid: None)
        self.kv = types.SimpleNamespace(blocks_needed=lambda total: 1)
        self._resident = set()

    def kv_usable_blocks(self):
        return 64

    def kv_occupancy(self):
        return 0.0

    def can_schedule(self, uids, needs):
        return True

    def admit(self, uid, tokens):
        self._resident.add(uid)

    def has_work(self):
        return bool(self._resident)

    def step(self):
        raise RuntimeError("kaboom: device went away")

    def finish(self, uid):
        self._resident.discard(uid)

    def reap_finished(self):
        return []


def test_health_degraded_after_engine_step_failure():
    server = InferenceServer(_ExplodingEngine(),
                             ServingConfig(idle_poll_s=0.001)).start()
    frontend = ServingFrontend(server).start()
    try:
        req = server.submit([1, 2, 3], max_new_tokens=4)
        assert req.wait(timeout=10.0)
        assert req.state == RequestState.FAILED

        h = server.health()
        assert h["status"] == "degraded"
        assert h["ok"] is False
        assert "engine step failed" in h["degraded_reason"]
        # /healthz mirrors it with a 503 so LBs eject this replica
        status, _, body = _http("GET", frontend.host, frontend.port,
                                "/healthz")
        assert status == 503
        assert json.loads(body)["status"] == "degraded"
        # a suspect engine refuses new work at the door (503, not a slow 500)
        with pytest.raises(ServerClosedError):
            server.submit([1, 2, 3], max_new_tokens=4)
    finally:
        frontend.stop()
        server.stop(drain_timeout=2.0)
