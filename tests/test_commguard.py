"""commguard tests: timeout-bounded collectives, distributed health, and
the coordinated comm-fault recovery drill.

Every fault is deterministic (chaos comm knobs key off guarded-call
indices; heartbeat staleness is driven by explicit clocks), so this suite
runs in tier-1 by default (``chaos`` marker) and asserts exact behavior:

  - bounded ops   -> a wedged guarded op raises ``CommWedgeError`` inside
                     the deadline with the dstrace comm-span tail attached;
                     TRANSIENT init failures retry with backoff; FATAL and
                     auth failures never retry
  - membership    -> per-rank heartbeat files classify peers alive/lost;
                     chaos-silenced ranks go stale exactly like dead ones
  - stragglers    -> rank-relative duration outliers emit ``comm/straggler``
                     instants and bump the proof counter
  - recovery      -> the acceptance drill: injected wedge -> classified
                     error -> autosave -> relaunch resumes bit-identical to
                     an uninterrupted baseline; exit code 75 so the elastic
                     agent accounts the relaunch like a preemption (free)
"""

import json
import os
import subprocess
import sys
import time

import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.guard import (COMM_FAULT_EXIT_CODE, CommGuard,
                                      CommGuardConfig, CommInitError,
                                      CommOutcome, CommPeerLostError,
                                      CommWedgeError, bounded_init,
                                      classify_exception)
from deepspeed_tpu.models.simple import SimpleModel, random_batch
from deepspeed_tpu.resilience import (ChaosConfig, ChaosMonkey,
                                      FaultTolerantRunner, Heartbeat,
                                      MembershipView, ResilienceConfig,
                                      StragglerDetector,
                                      find_latest_committed)
from deepspeed_tpu.telemetry import get_tracer

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = {
    "train_batch_size": 8,
    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
}


@pytest.fixture
def tracing():
    """Enable the process tracer for one test, fully restored afterwards."""
    t = get_tracer()
    t.clear()
    t.detach_sink()
    t.configure(enabled=True)
    try:
        yield t
    finally:
        t.configure(enabled=False)
        t.detach_sink()
        t.clear()


def _engine(seed=1, extra=None):
    cfg = dict(CFG)
    if extra:
        cfg.update(extra)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=32), config=cfg,
        example_batch=random_batch(4), seed=seed)
    return engine


def _guard_cfg(tmp_path, **kw):
    kw.setdefault("op_deadline_s", 0.3)
    kw.setdefault("heartbeat_interval_s", 0.05)
    kw.setdefault("lost_after_s", 0.5)
    kw.setdefault("membership_dir", str(tmp_path / "members"))
    return kw


def _runner(engine, tmp_path, chaos=None):
    rc = ResilienceConfig(diagnostics_dir=str(tmp_path / "diag"),
                          autosave={"io_backoff_s": 0.01})
    return FaultTolerantRunner(engine, save_dir=str(tmp_path / "ckpt"),
                               config=rc, chaos=chaos)


def _batch_fn(step):
    return random_batch(8, seed=step)


def _write_peer(path, rank, age_s=0.0, beat=1):
    """Publish a rank file aged ``age_s`` — staleness is judged by the
    file's mtime (the store's single clock), so simulating a dead peer
    means backdating the file itself, not the embedded wall-clock ts."""
    path.write_text(json.dumps(
        {"rank": rank, "pid": 9, "ts": time.time() - age_s, "beat": beat}))
    if age_s:
        t = time.time() - age_s
        os.utime(path, (t, t))


# ---------------------------------------------------------------------------
# outcome classification
# ---------------------------------------------------------------------------
def test_classify_exception_taxonomy():
    assert classify_exception(ConnectionRefusedError("refused")) \
        is CommOutcome.TRANSIENT
    assert classify_exception(RuntimeError("UNAVAILABLE: channel down")) \
        is CommOutcome.TRANSIENT
    assert classify_exception(TimeoutError("rendezvous timed out")) \
        is CommOutcome.TRANSIENT
    # auth is NEVER transient — retrying a revoked credential burns the
    # deadline for nothing (even when the transport also says "refused")
    assert classify_exception(
        RuntimeError("PERMISSION_DENIED: connection refused for principal")) \
        is CommOutcome.FATAL
    assert classify_exception(ValueError("bad mesh shape")) \
        is CommOutcome.FATAL


# ---------------------------------------------------------------------------
# bounded_init: deadline + backoff retry
# ---------------------------------------------------------------------------
def test_bounded_init_transient_retried_then_ok():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionRefusedError("coordinator not up yet")
        return "connected"

    assert bounded_init(flaky, name="t", deadline_s=5.0, retries=3,
                        backoff_s=0.01) == "connected"
    assert len(calls) == 3


def test_bounded_init_transient_budget_exhausted():
    def always_down():
        raise ConnectionResetError("reset by peer")

    with pytest.raises(CommInitError) as ei:
        bounded_init(always_down, name="t", deadline_s=5.0, retries=2,
                     backoff_s=0.01)
    assert ei.value.outcome is CommOutcome.TRANSIENT
    assert ei.value.attempts == 3          # 1 try + 2 retries
    assert isinstance(ei.value.__cause__, ConnectionResetError)


def test_bounded_init_fatal_never_retried():
    calls = []

    def fatal():
        calls.append(1)
        raise RuntimeError("permission denied: bad TPU credential")

    with pytest.raises(CommInitError) as ei:
        bounded_init(fatal, name="t", deadline_s=5.0, retries=5,
                     backoff_s=0.01)
    assert ei.value.outcome is CommOutcome.FATAL
    assert len(calls) == 1


def test_bounded_init_wedge_detected_within_deadline():
    t0 = time.monotonic()
    with pytest.raises(CommWedgeError) as ei:
        bounded_init(lambda: time.sleep(60), name="pjrt", deadline_s=0.2,
                     retries=3, backoff_s=0.01)
    assert time.monotonic() - t0 < 5.0     # detected, not sat out
    assert ei.value.outcome is CommOutcome.TIMEOUT
    assert ei.value.op == "pjrt"


def test_bounded_init_zero_deadline_runs_inline():
    assert bounded_init(lambda: 42, name="t", deadline_s=0) == 42


def test_init_distributed_wedge_proof(monkeypatch):
    """The BENCH r02–r05 wedge, mechanized: a hung rendezvous becomes a
    classified error inside the deadline; a transient one is retried."""
    import jax

    from deepspeed_tpu.comm.mesh import init_distributed

    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: time.sleep(60))
    t0 = time.monotonic()
    with pytest.raises(CommWedgeError):
        init_distributed(coordinator_address="127.0.0.1:1",
                         num_processes=2, process_id=0, deadline_s=0.2)
    assert time.monotonic() - t0 < 5.0

    calls = []

    def flaky(**kw):
        calls.append(kw)
        if len(calls) < 2:
            raise ConnectionRefusedError("coordinator not up yet")

    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    init_distributed(coordinator_address="127.0.0.1:1", num_processes=2,
                     process_id=0, deadline_s=5.0, backoff_s=0.01)
    assert len(calls) == 2


# ---------------------------------------------------------------------------
# CommGuard: bounded eager ops + chaos faults
# ---------------------------------------------------------------------------
def test_guard_ok_op_counted_and_noted(tmp_path):
    guard = CommGuard(CommGuardConfig(enabled=True))
    noted = []
    from deepspeed_tpu.comm.guard import set_comm_op_listener
    set_comm_op_listener(noted.append)
    try:
        assert guard.run("scatter", lambda: "v") == "v"
    finally:
        set_comm_op_listener(None)
    assert guard.counters["ok"] == 1
    assert noted == ["scatter"]


def test_guard_chaos_wedge_raises_with_comm_tail(tracing):
    chaos = ChaosMonkey(ChaosConfig(seed=3, comm_wedge_call=1))
    guard = CommGuard(CommGuardConfig(enabled=True, op_deadline_s=0.2),
                      chaos=chaos)
    assert guard.run("allgather", lambda: 1) == 1      # call 0 unharmed
    t0 = time.monotonic()
    with pytest.raises(CommWedgeError) as ei:
        guard.run("allgather", lambda: 1)              # call 1 wedges
    assert time.monotonic() - t0 < 5.0
    assert guard.counters["timeout"] == 1
    assert chaos.injected["comm_wedge"] == 1
    # the error carries the dstrace comm tail: the completed call-0 span
    # and the wedge instant are both in it
    names = [e["name"] for e in ei.value.comm_tail]
    assert "comm/guarded/allgather" in names
    assert "comm/wedge" in names
    # a second wedge-eligible call is NOT re-wedged once DSTPU_RESUME is
    # set (comm_wedge_once spares the relaunched worker)
    os.environ["DSTPU_RESUME"] = "latest"
    try:
        chaos2 = ChaosMonkey(ChaosConfig(seed=3, comm_wedge_call=0))
        guard2 = CommGuard(CommGuardConfig(enabled=True, op_deadline_s=0.2),
                           chaos=chaos2)
        assert guard2.run("allgather", lambda: 1) == 1
        assert chaos2.injected["comm_wedge"] == 0
    finally:
        del os.environ["DSTPU_RESUME"]


def test_guard_chaos_delay_is_slow_but_ok():
    chaos = ChaosMonkey(ChaosConfig(seed=3, comm_delay_calls=frozenset({0}),
                                    comm_delay_s=0.05))
    guard = CommGuard(CommGuardConfig(enabled=True, op_deadline_s=5.0),
                      chaos=chaos)
    t0 = time.monotonic()
    assert guard.run("reduce", lambda: "r") == "r"
    assert time.monotonic() - t0 >= 0.05
    assert guard.counters["ok"] == 1
    assert chaos.injected["comm_delay"] == 1


def test_guard_failure_classified_and_reraised():
    guard = CommGuard(CommGuardConfig(enabled=True))
    with pytest.raises(ValueError):
        guard.run("scatter", lambda: (_ for _ in ()).throw(
            ValueError("shape mismatch")))
    assert guard.counters["fatal"] == 1


# ---------------------------------------------------------------------------
# membership: heartbeats + peer classification
# ---------------------------------------------------------------------------
def test_heartbeat_publishes_and_membership_sees_alive(tmp_path):
    d = str(tmp_path / "members")
    view = MembershipView(d, lost_after_s=5.0)
    with Heartbeat(0, d, interval_s=0.05, listen_comm_ops=False) as hb:
        hb.note_op("all_reduce")
        time.sleep(0.15)
        snap = view.snapshot()
    assert 0 in snap and snap[0].alive
    assert snap[0].beat >= 1
    # the published record carries the last-completed comm op
    final = view.snapshot()[0]
    assert final.last_op == "all_reduce"
    assert final.op_seq == 1
    assert view.healthy()


def test_membership_stale_peer_classified_lost(tmp_path):
    d = tmp_path / "members"
    d.mkdir()
    _write_peer(d / "rank_0.json", 0, beat=5)
    _write_peer(d / "rank_1.json", 1, age_s=60.0, beat=3)
    view = MembershipView(str(d), lost_after_s=5.0)
    assert view.lost_peers() == [1]
    assert not view.healthy()
    summary = view.summary()
    assert summary["lost"] == [1]
    assert summary["ranks"]["0"]["alive"] is True
    assert summary["ranks"]["1"]["alive"] is False


def test_membership_age_is_mtime_not_writer_clock(tmp_path):
    """A freshly-published heartbeat from a host whose wall clock is 60s
    behind must NOT read as lost — age comes from the rank file's mtime
    (the store's single clock), never the writer's embedded timestamp."""
    d = tmp_path / "members"
    d.mkdir()
    (d / "rank_0.json").write_text(json.dumps(
        {"rank": 0, "pid": 1, "ts": time.time() - 60.0, "beat": 7}))
    view = MembershipView(str(d), lost_after_s=5.0)
    snap = view.snapshot()
    assert snap[0].alive and snap[0].age_s < 5.0
    assert view.lost_peers() == []


def test_membership_expected_rank_missing_after_grace(tmp_path):
    d = tmp_path / "members"
    d.mkdir()
    (d / "rank_0.json").write_text(json.dumps(
        {"rank": 0, "pid": 1, "ts": time.time(), "beat": 1}))
    view = MembershipView(str(d), lost_after_s=0.1, expected_ranks=(0, 1))
    # inside the startup grace a never-published peer is NOT lost yet
    assert view.lost_peers() == []
    time.sleep(0.15)
    # keep rank 0 fresh — only the never-published rank 1 should be lost
    (d / "rank_0.json").write_text(json.dumps(
        {"rank": 0, "pid": 1, "ts": time.time(), "beat": 2}))
    assert view.lost_peers() == [1]


def test_chaos_silenced_heartbeat_goes_stale(tmp_path):
    d = str(tmp_path / "members")
    chaos = ChaosMonkey(ChaosConfig(seed=1, peer_dead_ranks=frozenset({1})))
    hb0 = Heartbeat(0, d, interval_s=0.05, chaos=chaos,
                    listen_comm_ops=False).start()
    hb1 = Heartbeat(1, d, interval_s=0.05, chaos=chaos,
                    listen_comm_ops=False).start()
    try:
        time.sleep(0.2)
        view = MembershipView(d, lost_after_s=5.0)
        snap = view.snapshot()
        assert 0 in snap                     # rank 0 publishes normally
        assert 1 not in snap                 # rank 1 silenced — never lands
        view2 = MembershipView(d, lost_after_s=0.0001, expected_ranks=(0, 1))
        time.sleep(0.01)
        assert 1 in view2.lost_peers()
    finally:
        hb0.stop()
        hb1.stop()


def test_heartbeat_overlap_keeps_newer_listener(tmp_path):
    """Stopping an OLD heartbeat must not sever a newer one's comm-op feed
    (rolling runner replacement / training + serving in one process)."""
    from deepspeed_tpu.comm.guard import note_comm_op
    d = str(tmp_path / "members")
    old = Heartbeat(0, d, interval_s=0.05).start()
    new = Heartbeat(0, d, interval_s=0.05).start()   # takes the listener
    try:
        old.stop()                                   # must NOT clear it
        note_comm_op("all_reduce")
        with new._lock:
            assert new._last_op == "all_reduce"
            assert new._op_seq == 1
    finally:
        new.stop()
    # the newest heartbeat's own stop DOES clear its listener
    note_comm_op("all_gather")
    with new._lock:
        assert new._op_seq == 1


# ---------------------------------------------------------------------------
# straggler detection
# ---------------------------------------------------------------------------
def test_straggler_outlier_flagged_with_instant(tracing):
    det = StragglerDetector(factor=3.0)
    out = det.observe("all_reduce", {0: 0.010, 1: 0.011, 2: 0.012, 3: 0.500})
    assert out == [3]
    assert det.count == 1
    assert det.flagged[0][0] == "all_reduce" and det.flagged[0][1] == 3
    assert tracing.instant_counts().get("comm/straggler") == 1


def test_straggler_uniform_ranks_not_flagged():
    det = StragglerDetector(factor=3.0)
    assert det.observe("all_reduce", {0: 0.01, 1: 0.012, 2: 0.011}) == []
    assert det.count == 0


def test_straggler_min_s_filters_clock_noise():
    det = StragglerDetector(factor=3.0, min_s=1.0)
    # 5x the median but only 40ms over it — below the absolute floor
    assert det.observe("barrier", {0: 0.01, 1: 0.01, 2: 0.05}) == []
    assert det.count == 0


def test_straggler_ingest_synthetic_spans(tracing):
    """The satellite proof: straggler instants from synthetic span timings
    shaped like ``Tracer.events_snapshot`` rows."""
    #              (eid, name, cat, ph, ts, dur, tid, args)
    events = [
        (1, "comm/all_gather", "comm", "X", 0.0, 0.010, 0, {"rank": 0}),
        (2, "comm/all_gather", "comm", "X", 0.0, 0.012, 0, {"rank": 1}),
        (3, "comm/all_gather", "comm", "X", 0.0, 0.011, 0, {"rank": 2}),
        (4, "comm/all_gather", "comm", "X", 0.0, 0.900, 0, {"rank": 3}),
        # non-span / non-comm / rank-less rows must be ignored
        (5, "comm/all_gather", "comm", "i", 0.0, 0.0, 0, {"rank": 0}),
        (6, "engine/dispatch", "host", "X", 0.0, 9.9, 0, {"rank": 0}),
        (7, "comm/all_gather", "comm", "X", 0.0, 9.9, 0, {}),
    ]
    det = StragglerDetector(factor=3.0)
    assert det.ingest_spans(events) == [3]
    assert det.count == 1
    assert tracing.instant_counts().get("comm/straggler") == 1


def test_runner_feeds_stragglers_from_config(tmp_path, tracing):
    """The ``straggler_*`` config keys are live: the runner constructs the
    detector from the group and judges fresh rank-tagged comm spans at the
    membership-poll cadence — a 2.5x outlier is flagged at factor 2.0 (it
    would NOT be at the default 3.0), and already-judged event ids are
    never double-counted."""
    engine = _engine(seed=1, extra={"comm_guard": _guard_cfg(
        tmp_path, straggler_factor=2.0)})
    runner = _runner(engine, tmp_path)
    try:
        assert runner.straggler is not None
        assert runner.straggler.factor == 2.0
        for rank, dur in ((0, 0.10), (1, 0.11), (2, 0.12), (3, 0.27)):
            tracing.complete("comm/all_gather", dur, cat="comm", rank=rank)
        runner._check_peers()
        assert runner.straggler.count == 1
        assert runner.straggler.flagged[0][1] == 3
        # second poll over the SAME spans: no double count
        runner.membership._next_poll = 0.0
        runner._check_peers()
        assert runner.straggler.count == 1
    finally:
        runner.close()


# ---------------------------------------------------------------------------
# the acceptance drill: wedge -> classified error -> autosave -> resume
# ---------------------------------------------------------------------------
def _trajectory(engine, start, stop):
    out = []
    for step in range(start, stop):
        loss = float(engine.train_batch(batch=_batch_fn(step)))
        out.append((loss, engine.get_lr()[0]))
    return out


def test_comm_wedge_drill_autosave_then_resume_matches_baseline(
        tmp_path, tracing):
    """The acceptance scenario: an injected comm wedge is detected within
    the configured deadline (no hang), produces a classified error with the
    dstrace comm-span tail attached, autosaves, and a relaunched run
    resumes bit-identical to an uninterrupted baseline."""
    total = 6
    base = _engine(seed=1)
    base_traj = _trajectory(base, 0, total)

    victim = _engine(seed=1, extra={"comm_guard": _guard_cfg(tmp_path)})
    chaos = ChaosMonkey(ChaosConfig(seed=7, comm_wedge_call=3))
    runner = _runner(victim, tmp_path, chaos=chaos)
    assert runner.comm_guard is not None and runner.heartbeat is not None
    # the runner installed its guard process-wide: the comm facade's eager
    # ops (device_broadcast, ckpt scatter) route through it with NO caller
    # change — the drill below never references runner.comm_guard
    from deepspeed_tpu.comm.guard import get_active_guard, guarded
    assert get_active_guard() is runner.comm_guard

    def guarded_batches(step):
        # the eager guarded op an UNMODIFIED training script would run
        # (ckpt scatter, debug broadcast, ... — routed via the active
        # guard exactly like comm.device_broadcast) — call #3 wedges,
        # i.e. during step 3
        guarded("ckpt_scatter", lambda: None)
        return _batch_fn(step)

    t0 = time.monotonic()
    result = runner.run(num_steps=total, batch_fn=guarded_batches)
    detect_s = time.monotonic() - t0
    runner.close()
    # detected within the deadline (0.3s) + slack, never a hang
    assert result.stop_reason == "comm_fault"
    assert result.steps_completed == 3
    assert result.preempted                      # relaunch-with-resume class
    assert result.exit_code == COMM_FAULT_EXIT_CODE
    assert chaos.injected["comm_wedge"] == 1
    assert runner.comm_guard.counters["timeout"] == 1
    assert detect_s < 60.0                       # vs the 0.3s deadline

    # autosave committed at the fault boundary
    assert find_latest_committed(str(tmp_path / "ckpt")) == "global_step3"
    # diagnostic bundle carries the classified fault + comm-span tail
    bundle = tmp_path / "diag" / "comm_fault_step3"
    with open(bundle / "diag.json") as f:
        diag = json.load(f)
    assert diag["reason"] == "comm_fault"
    assert diag["comm_fault"]["op"] == "ckpt_scatter"
    assert diag["comm_fault"]["outcome"] == "timeout"
    tail_names = [e["name"] for e in diag["comm_fault"]["comm_tail"]]
    assert "comm/wedge" in tail_names

    # --- relaunch: fresh process state, different init seed -------------
    resumed = _engine(seed=42, extra={"comm_guard": _guard_cfg(tmp_path)})
    runner2 = _runner(resumed, tmp_path)
    assert runner2.resume_from_latest() == "global_step3"
    assert resumed.global_steps == 3
    resumed_traj = _trajectory(resumed, 3, total)
    runner2.close()
    for (bl, blr), (rl, rlr) in zip(base_traj[3:], resumed_traj):
        assert abs(bl - rl) < 1e-6
        assert rlr == pytest.approx(blr, rel=1e-7)
    assert resumed.global_steps == total


def test_peer_loss_stops_run_with_comm_fault(tmp_path):
    """A peer whose heartbeat goes stale becomes CommPeerLostError at the
    step boundary — coordinated stop + autosave, never a wedged collective."""
    members = tmp_path / "members"
    members.mkdir()
    # a peer that published once, 60s ago, then died
    _write_peer(members / "rank_1.json", 1, age_s=60.0, beat=2)
    engine = _engine(seed=1, extra={"comm_guard": _guard_cfg(
        tmp_path, lost_after_s=0.5)})
    runner = _runner(engine, tmp_path)
    result = runner.run(num_steps=4, batch_fn=_batch_fn)
    runner.close()
    assert result.stop_reason == "comm_fault"
    assert result.steps_completed == 0           # detected before stepping
    assert result.exit_code == COMM_FAULT_EXIT_CODE
    assert find_latest_committed(str(tmp_path / "ckpt")) is not None
    with open(tmp_path / "diag" / "comm_fault_step0" / "diag.json") as f:
        diag = json.load(f)
    assert diag["comm_fault"]["op"] == "membership"


def test_runner_heartbeat_stops_on_close(tmp_path):
    from deepspeed_tpu.comm.guard import get_active_guard
    engine = _engine(seed=1, extra={"comm_guard": _guard_cfg(tmp_path)})
    runner = _runner(engine, tmp_path)
    hb_thread = runner.heartbeat._thread
    assert hb_thread.is_alive()
    assert get_active_guard() is runner.comm_guard
    runner.close()
    assert runner.heartbeat._thread is None
    assert not hb_thread.is_alive()
    assert get_active_guard() is None      # facade back to inline ops


def test_run_result_exit_code_classification():
    """The worker idiom ``sys.exit(result.exit_code)``: every stop reason
    maps into the elastic agent's accounting classes."""
    import signal as _signal
    from deepspeed_tpu.resilience.runner import RunResult
    assert RunResult(stop_reason="completed").exit_code == 0
    assert RunResult(stop_reason="comm_fault").exit_code == \
        COMM_FAULT_EXIT_CODE
    # preemption carries the 128+signal shell convention the agent's
    # preemption_exit_codes (143, 130) already recognizes
    assert RunResult(stop_reason="preempted",
                     preempt_signal=_signal.SIGTERM).exit_code == 143
    assert RunResult(stop_reason="preempted",
                     preempt_signal=_signal.SIGINT).exit_code == 130
    # watchdog/unknown-signal stops default to the SIGTERM form
    assert RunResult(stop_reason="watchdog").exit_code == 143
    from deepspeed_tpu.elasticity import WorkerSpec
    spec = WorkerSpec(cmd=["x"])
    assert 143 in spec.preemption_exit_codes
    assert 130 in spec.preemption_exit_codes
    assert COMM_FAULT_EXIT_CODE in spec.comm_fault_exit_codes


# ---------------------------------------------------------------------------
# elastic-agent accounting: comm faults are free, like preemptions
# ---------------------------------------------------------------------------
def test_agent_comm_fault_exit_is_free_not_budgeted():
    from deepspeed_tpu.elasticity import ElasticAgent, WorkerSpec
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 64,
                          "micro_batch_sizes": [2], "min_gpus": 1,
                          "max_gpus": 8, "version": 0.1}}
    agent = ElasticAgent(WorkerSpec(cmd=["x"]), cfg,
                         popen=lambda *a, **k: None)
    agent._last_codes = [COMM_FAULT_EXIT_CODE]
    assert agent._is_comm_fault(COMM_FAULT_EXIT_CODE)
    assert not agent._is_preemption(COMM_FAULT_EXIT_CODE)
    # comm fault in one worker + clean preemption in another: still free
    agent._last_codes = [COMM_FAULT_EXIT_CODE, -15]
    assert agent._is_comm_fault(COMM_FAULT_EXIT_CODE)
    # comm fault alongside a real crash: the generation is a crash
    agent._last_codes = [COMM_FAULT_EXIT_CODE, 1]
    assert not agent._is_comm_fault(1)
    # pure preemption vector is not a comm fault (no 75 present)
    agent._last_codes = [-15, 143]
    assert not agent._is_comm_fault(143)


def test_agent_relaunches_comm_fault_without_consuming_budget():
    from deepspeed_tpu.elasticity import ElasticAgent, WorkerSpec
    codes = iter([COMM_FAULT_EXIT_CODE, 0])

    class _Proc:
        def __init__(self):
            self.code = next(codes)

        def poll(self):
            return self.code

        def terminate(self):
            pass

        def wait(self, timeout=None):
            return 0

        def kill(self):
            pass

    launches = []

    def popen(cmd, env=None):
        launches.append(env)
        return _Proc()

    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 64,
                          "micro_batch_sizes": [2], "min_gpus": 1,
                          "max_gpus": 8, "version": 0.1}}
    spec = WorkerSpec(cmd=["x"], max_restarts=0, monitor_interval_s=0.01,
                      restart_backoff_s=0.0)
    agent = ElasticAgent(spec, cfg, popen=popen)
    assert agent.run() == 0
    assert agent.crash_restarts == 0             # budget untouched
    assert len(launches) == 2
    assert launches[-1]["DSTPU_RESUME"] == "latest"


def test_agent_exports_init_budget_env_from_config():
    """The ``comm_guard.init_*`` keys are live end to end: the agent
    exports them as DSTPU_COMM_INIT_* so every (re)launched worker's
    ``init_distributed`` rendezvous honors the configured budget."""
    from deepspeed_tpu.comm.guard import (INIT_BACKOFF_ENV,
                                          INIT_DEADLINE_ENV,
                                          INIT_RETRIES_ENV)
    from deepspeed_tpu.elasticity import ElasticAgent, WorkerSpec
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 64,
                          "micro_batch_sizes": [2], "min_gpus": 1,
                          "max_gpus": 8, "version": 0.1},
           "comm_guard": {"init_deadline_s": 30.0, "init_retries": 1,
                          "init_backoff_s": 0.5}}
    launches = []

    def popen(cmd, env=None):
        launches.append(env)

        class _Done:
            def poll(self):
                return 0
        return _Done()

    agent = ElasticAgent(
        WorkerSpec(cmd=["x"], monitor_interval_s=0.01,
                   env={INIT_RETRIES_ENV: "9"}),    # operator env wins
        cfg, popen=popen)
    assert agent.run() == 0
    env = launches[0]
    assert env[INIT_DEADLINE_ENV] == "30.0"
    assert env[INIT_BACKOFF_ENV] == "0.5"
    assert env[INIT_RETRIES_ENV] == "9"


# ---------------------------------------------------------------------------
# bench bounded discovery: classified rc + one-line diagnosis
# ---------------------------------------------------------------------------
def _run_discovery(tmp_path, body, extra_env=None):
    env = dict(os.environ)
    env.pop("DSTPU_STALE_REPLAY_RC0", None)
    env.update(DSTPU_BENCH_LOGS=str(tmp_path / "bench_logs"),
               **(extra_env or {}))
    return subprocess.run(
        [sys.executable, "-c",
         "from bench_util import bounded_device_discovery\n" + body],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)


def test_discovery_wedge_stale_replay_rc_unchanged(tmp_path):
    """A wedged discovery with a banked headline still replays it stale at
    rc 7 (rc 0 under DSTPU_STALE_REPLAY_RC0) — behavior unchanged."""
    from bench_util import STALE_REPLAY_EXIT_CODE
    logs = tmp_path / "bench_logs"
    logs.mkdir()
    (logs / "latest_headline.json").write_text(json.dumps(
        {"metric": "llama_train_tokens_per_sec_per_chip", "value": 5000.0,
         "unit": "tokens/s/chip"}) + "\n")
    body = ("bounded_device_discovery('bench', timeout=0.2, retries=0,\n"
            "    stale_metric='llama_train_tokens_per_sec_per_chip',\n"
            "    devices_fn=lambda: __import__('time').sleep(60))\n")
    out = _run_discovery(tmp_path, body)
    assert out.returncode == STALE_REPLAY_EXIT_CODE, out.stderr
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["stale"] is True and rec["value"] == 5000.0
    assert "tunnel wedge" in out.stderr

    out0 = _run_discovery(tmp_path, body,
                          extra_env={"DSTPU_STALE_REPLAY_RC0": "1"})
    assert out0.returncode == 0, out0.stderr


def test_discovery_wedge_nothing_banked_rc3(tmp_path):
    body = ("bounded_device_discovery('bench', timeout=0.2, retries=0,\n"
            "    stale_metric='llama_train_tokens_per_sec_per_chip',\n"
            "    devices_fn=lambda: __import__('time').sleep(60))\n")
    out = _run_discovery(tmp_path, body)
    assert out.returncode == 3, out.stderr
    assert "tunnel wedge" in out.stderr


def test_discovery_auth_distinct_rc_never_replayed(tmp_path):
    """Auth failures get their own rc and are never papered over with a
    stale replay — the banked headline would hide a revoked credential."""
    from bench_util import DISCOVERY_AUTH_EXIT_CODE
    logs = tmp_path / "bench_logs"
    logs.mkdir()
    (logs / "latest_headline.json").write_text(json.dumps(
        {"metric": "llama_train_tokens_per_sec_per_chip", "value": 5000.0,
         "unit": "tokens/s/chip"}) + "\n")
    body = ("def f():\n"
            "    raise RuntimeError('PERMISSION_DENIED: bad credential')\n"
            "bounded_device_discovery('bench', timeout=5, retries=3,\n"
            "    stale_metric='llama_train_tokens_per_sec_per_chip',\n"
            "    devices_fn=f)\n")
    out = _run_discovery(tmp_path, body)
    assert out.returncode == DISCOVERY_AUTH_EXIT_CODE, out.stderr
    assert "auth" in out.stderr
    assert not out.stdout.strip()                # no stale replay line


def test_discovery_no_devices_distinct_rc(tmp_path):
    from bench_util import DISCOVERY_NO_DEVICES_EXIT_CODE
    body = ("bounded_device_discovery('bench', timeout=5, retries=0,\n"
            "    devices_fn=lambda: [])\n")
    out = _run_discovery(tmp_path, body)
    assert out.returncode == DISCOVERY_NO_DEVICES_EXIT_CODE, out.stderr
    assert "no devices" in out.stderr


def test_discovery_transient_retried_then_succeeds(tmp_path):
    body = ("import tempfile, os\n"
            "marker = os.path.join(os.environ['DSTPU_BENCH_LOGS'], 'tries')\n"
            "def f():\n"
            "    n = int(open(marker).read()) if os.path.exists(marker) else 0\n"
            "    os.makedirs(os.path.dirname(marker), exist_ok=True)\n"
            "    open(marker, 'w').write(str(n + 1))\n"
            "    if n < 2:\n"
            "        raise ConnectionRefusedError('tunnel not up')\n"
            "    return ['cpu:0']\n"
            "devs = bounded_device_discovery('bench', timeout=5, retries=3,\n"
            "    backoff_s=0.01, devices_fn=f)\n"
            "print('DEVICES', devs)\n")
    out = _run_discovery(tmp_path, body)
    assert out.returncode == 0, out.stderr
    assert "DEVICES ['cpu:0']" in out.stdout


# ---------------------------------------------------------------------------
# serving: membership view flips health to degraded
# ---------------------------------------------------------------------------
class _IdleEngine:
    """Minimal engine double that never has work — the membership poll on
    the serve tick is the thing under test."""

    def __init__(self):
        import types
        self.state = types.SimpleNamespace(max_context_length=512,
                                           get=lambda uid: None)
        self.kv = types.SimpleNamespace(blocks_needed=lambda total: 1)

    def kv_usable_blocks(self):
        return 64

    def kv_occupancy(self):
        return 0.0

    def can_schedule(self, uids, needs):
        return True

    def admit(self, uid, tokens):
        pass

    def has_work(self):
        return False

    def step(self):
        pass

    def reap_finished(self):
        return []


def test_serving_degrades_on_lost_peer(tmp_path):
    from deepspeed_tpu.serving import ServingConfig
    from deepspeed_tpu.serving.server import InferenceServer

    members = tmp_path / "members"
    members.mkdir()
    _write_peer(members / "rank_1.json", 1, age_s=60.0, beat=2)
    view = MembershipView(str(members), lost_after_s=0.5)
    server = InferenceServer(_IdleEngine(), ServingConfig(idle_poll_s=0.001),
                             membership=view).start()
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            h = server.health()
            if h["status"] == "degraded":
                break
            time.sleep(0.01)
        h = server.health()
        assert h["status"] == "degraded", h
        assert "peer" in h["degraded_reason"]
        assert h["membership"]["lost"] == [1]
        assert h["membership"]["ranks"]["1"]["alive"] is False
    finally:
        server.stop(drain_timeout=2.0)


def test_serving_healthy_membership_reported_not_degraded(tmp_path):
    from deepspeed_tpu.serving import ServingConfig
    from deepspeed_tpu.serving.server import InferenceServer

    members = tmp_path / "members"
    members.mkdir()
    (members / "rank_0.json").write_text(json.dumps(
        {"rank": 0, "pid": 1, "ts": time.time(), "beat": 1}))

    view = MembershipView(str(members), lost_after_s=3600.0)
    server = InferenceServer(_IdleEngine(), ServingConfig(idle_poll_s=0.001),
                             membership=view).start()
    try:
        time.sleep(0.1)
        h = server.health()
        assert h["status"] == "serving", h
        assert h["membership"]["lost"] == []
    finally:
        server.stop(drain_timeout=2.0)
