"""Pipeline parallelism tests: schedule streams, SPMD executor vs sequential,
gradient flow through the pipeline, partition balancing.

Reference analog: tests/unit/runtime/pipe + pipe schedule unit tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.comm.mesh import create_mesh, set_global_mesh
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.runtime.pipe.module import (
    partition_balanced,
    partition_uniform,
)
from deepspeed_tpu.runtime.pipe.schedule import (
    BackwardPass,
    ForwardPass,
    InferenceSchedule,
    LoadMicroBatch,
    OptimizerStep,
    TrainSchedule,
    bubble_fraction,
)
from deepspeed_tpu.runtime.pipe.spmd import pipeline_apply, stack_to_stages


def test_inference_schedule_order():
    sched = InferenceSchedule(micro_batches=3, stages=2, stage_id=0)
    steps = list(sched)
    fwd_mbs = [c.micro_batch_id for step in steps for c in step
               if isinstance(c, ForwardPass)]
    assert fwd_mbs == [0, 1, 2]
    loads = [c.micro_batch_id for step in steps for c in step
             if isinstance(c, LoadMicroBatch)]
    assert loads == [0, 1, 2]


def test_train_schedule_1f1b_properties():
    m, s = 4, 2
    for stage in range(s):
        sched = TrainSchedule(micro_batches=m, stages=s, stage_id=stage)
        steps = list(sched)
        fwds = [c.micro_batch_id for st in steps for c in st if isinstance(c, ForwardPass)]
        bwds = [c.micro_batch_id for st in steps for c in st if isinstance(c, BackwardPass)]
        assert fwds == list(range(m))
        assert bwds == list(range(m))
        # every forward precedes its backward
        flat = [c for st in steps for c in st]
        for mb in range(m):
            fi = next(i for i, c in enumerate(flat)
                      if isinstance(c, ForwardPass) and c.micro_batch_id == mb)
            bi = next(i for i, c in enumerate(flat)
                      if isinstance(c, BackwardPass) and c.micro_batch_id == mb)
            assert fi < bi
        assert isinstance(flat[-1], OptimizerStep)


def test_bubble_fraction():
    assert bubble_fraction(1, 4) == pytest.approx(3 / 4)
    assert bubble_fraction(16, 4) == pytest.approx(3 / 19)


def test_partition_uniform():
    assert partition_uniform(8, 4) == [0, 2, 4, 6, 8]
    assert partition_uniform(7, 2) == [0, 4, 7]


def test_partition_balanced():
    bounds = partition_balanced([1, 1, 1, 10, 1, 1], 2)
    assert bounds[0] == 0 and bounds[-1] == 6
    # heavy layer isolated enough that max stage weight is near 10+
    w = [1, 1, 1, 10, 1, 1]
    stage_weights = [sum(w[bounds[i]:bounds[i + 1]]) for i in range(2)]
    assert max(stage_weights) <= 13


def _make_blocks(num_layers, d, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(rng.normal(size=(num_layers, d, d)) * 0.1, jnp.float32),
        "b": jnp.asarray(rng.normal(size=(num_layers, d)) * 0.1, jnp.float32),
    }


def _block_fn(lp, x):
    return jnp.tanh(x @ lp["w"] + lp["b"])


def _sequential(params, x_mb):
    def one(x):
        def step(carry, lp):
            return _block_fn(lp, carry), None
        y, _ = jax.lax.scan(step, x, params)
        return y
    return jax.vmap(one)(x_mb)


def test_stack_to_stages():
    params = _make_blocks(8, 4)
    staged = stack_to_stages(params, 4)
    assert staged["w"].shape == (4, 2, 4, 4)


def test_pipeline_matches_sequential():
    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    set_global_mesh(mesh)
    params = _make_blocks(8, 16)
    x_mb = jnp.asarray(np.random.default_rng(1).normal(size=(6, 2, 16)), jnp.float32)
    out_pipe = jax.jit(lambda p, x: pipeline_apply(_block_fn, p, x, mesh=mesh))(
        params, x_mb)
    out_seq = _sequential(params, x_mb)
    np.testing.assert_allclose(np.asarray(out_pipe), np.asarray(out_seq),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_gradients_match_sequential():
    """jax.grad through the pipeline == grads of the sequential model (the SPMD
    executor's backward pipeline is derived by autodiff)."""
    mesh = create_mesh(MeshConfig(pipe=4, data=2))
    set_global_mesh(mesh)
    params = _make_blocks(4, 8)
    x_mb = jnp.asarray(np.random.default_rng(2).normal(size=(4, 2, 8)), jnp.float32)

    def loss_pipe(p):
        return jnp.sum(pipeline_apply(_block_fn, p, x_mb, mesh=mesh) ** 2)

    def loss_seq(p):
        return jnp.sum(_sequential(p, x_mb) ** 2)

    g1 = jax.jit(jax.grad(loss_pipe))(params)
    g2 = jax.grad(loss_seq)(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_pipeline_single_stage_passthrough():
    mesh = create_mesh(MeshConfig(data=8))
    set_global_mesh(mesh)
    params = _make_blocks(4, 8)
    x_mb = jnp.asarray(np.random.default_rng(3).normal(size=(3, 2, 8)), jnp.float32)
    out = pipeline_apply(_block_fn, params, x_mb, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_sequential(params, x_mb)),
                               atol=1e-6)


# ---------------------------------------------------------------- 1F1B
def _toy_setup(l=8, d=32, vocab=64):
    rng = np.random.default_rng(0)
    stacked = {
        "w1": jnp.asarray(rng.normal(size=(l, d, 2 * d)) * 0.1, jnp.float32),
        "w2": jnp.asarray(rng.normal(size=(l, 2 * d, d)) * 0.1, jnp.float32),
    }
    tied = {"embed": jnp.asarray(rng.normal(size=(vocab, d)) * 0.1, jnp.float32)}

    def block_fn(lp, x):
        return x + jax.nn.relu(x @ lp["w1"]) @ lp["w2"]

    def first_fn(tp, toks):
        return tp["embed"][toks]

    def last_fn(tp, y, toks):
        logits = y @ tp["embed"].T            # tied unembed
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), -1)
        ll = jnp.take_along_axis(logp, toks[:, 1:, None], axis=-1)[..., 0]
        return -jnp.mean(ll)

    toks = jnp.asarray(rng.integers(0, vocab, size=(8, 2, 16)), jnp.int32)
    return stacked, tied, toks, block_fn, first_fn, last_fn


def test_1f1b_matches_no_pipe():
    """1F1B executor: loss AND grads (incl. tied embedding grads from both
    pipeline ends) match the unpipelined computation."""
    from deepspeed_tpu.runtime.pipe.one_f_one_b import (
        _no_pipe, pipeline_train_step_1f1b)
    stacked, tied, toks, block_fn, first_fn, last_fn = _toy_setup()
    mesh = create_mesh(MeshConfig(pipe=4, data=2))
    set_global_mesh(mesh)

    loss_p, gp_p, gt_p = pipeline_train_step_1f1b(
        block_fn, stacked, tied, toks, first_fn, last_fn, mesh=mesh)
    loss_r, gp_r, gt_r = _no_pipe(block_fn, stacked, tied, toks, first_fn,
                                  last_fn)
    np.testing.assert_allclose(float(loss_p), float(loss_r), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(gp_p), jax.tree.leaves(gp_r)):
        np.testing.assert_allclose(
            np.asarray(a).reshape(np.asarray(b).shape), np.asarray(b),
            atol=1e-5, rtol=1e-4)
    for a, b in zip(jax.tree.leaves(gt_p), jax.tree.leaves(gt_r)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-4)


def test_1f1b_bounded_activation_memory():
    """The 1F1B property: the ring buffer holds min(M, 2S-1) stage inputs —
    independent of the microbatch count (GPipe would hold M)."""
    from deepspeed_tpu.runtime.pipe import one_f_one_b as mod
    stacked, tied, toks, block_fn, first_fn, last_fn = _toy_setup()
    m, s = toks.shape[0], 4
    assert min(m, 2 * s - 1) == 7 < m + s - 1     # tighter than GPipe's M

    # 32 microbatches: buffer stays at 2S-1 = 7
    toks32 = jnp.tile(toks, (4, 1, 1))
    mesh = create_mesh(MeshConfig(pipe=4, data=2))
    set_global_mesh(mesh)
    loss, _, _ = mod.pipeline_train_step_1f1b(
        block_fn, stacked, tied, toks32, first_fn, last_fn, mesh=mesh)
    assert np.isfinite(float(loss))


def test_trainschedule_inflight_matches_pipe_buffers():
    """The 1F1B instruction stream never holds more in-flight microbatches
    than num_pipe_buffers (reference: schedule.py:268)."""
    from deepspeed_tpu.runtime.pipe.schedule import (
        BackwardPass, ForwardPass, TrainSchedule)
    for stages in (2, 4):
        for m in (1, 4, 8):
            for p in range(stages):
                sched = TrainSchedule(m, stages, p)
                inflight = 0
                peak = 0
                for cmds in sched.steps():
                    for c in cmds:
                        if isinstance(c, ForwardPass):
                            inflight += 1
                        elif isinstance(c, BackwardPass):
                            inflight -= 1
                    peak = max(peak, inflight)
                assert peak <= sched.num_pipe_buffers(), \
                    (stages, m, p, peak, sched.num_pipe_buffers())


def test_bubble_fraction_model():
    """Executor macro-step count obeys the (S-1)/(M+S-1) bubble model: total
    steps = fwd-critical-path + drain = (M + S - 1) + (S - 1)."""
    from deepspeed_tpu.runtime.pipe.schedule import bubble_fraction
    m, s = 8, 4
    total = 2 * (s - 1) + m                      # executor's scan length
    fwd_steps = m + s - 1
    assert total == fwd_steps + (s - 1)
    assert bubble_fraction(m, s) == (s - 1) / (m + s - 1)


@pytest.mark.slow
def test_pipeline_engine_trains():
    """PipelineEngine.train_batch analog: 1F1B + optimizer converges on a
    pipe=4 mesh, and matches single-stage training step-for-step."""
    from deepspeed_tpu.runtime.pipe.engine import PipeModule, PipelineEngine
    stacked, tied, toks, block_fn, first_fn, last_fn = _toy_setup()
    tokens = np.asarray(toks.reshape(-1, toks.shape[-1]))   # [16, S]

    def make(mesh_cfg):
        mesh = create_mesh(mesh_cfg)
        set_global_mesh(mesh)
        mod = PipeModule(block_fn, first_fn, last_fn,
                         jax.tree.map(jnp.copy, stacked),
                         jax.tree.map(jnp.copy, tied))
        return PipelineEngine(mod, {"gradient_accumulation_steps": 8,
                                    "optimizer": {"type": "AdamW",
                                                  "params": {"lr": 5e-3}},
                                    "gradient_clipping": 1.0}, mesh=mesh)

    eng_pipe = make(MeshConfig(pipe=4, data=2))
    losses_p = [eng_pipe.train_batch(tokens) for _ in range(8)]
    eng_one = make(MeshConfig(data=8))
    losses_1 = [eng_one.train_batch(tokens) for _ in range(8)]
    assert losses_p[-1] < losses_p[0]
    np.testing.assert_allclose(losses_p, losses_1, rtol=2e-3, atol=2e-4)


def test_lockstep_masks_match_schedule():
    """The executor's in-scan fwd/bwd occupancy (f = t - p, b = t - (2(S-1)-p))
    equals the LockstepSPMDSchedule instruction stream — the schedule module
    is the executor's source of truth (drives total_steps + ring depth)."""
    from deepspeed_tpu.runtime.pipe.schedule import (
        BackwardPass, ForwardPass, LockstepSPMDSchedule, num_macro_steps)
    for m, s in [(1, 2), (4, 2), (2, 4), (8, 3), (3, 5)]:
        total = num_macro_steps(m, s)
        assert total == 2 * (s - 1) + m
        for p in range(s):
            steps = list(LockstepSPMDSchedule(m, s, p).steps())
            assert len(steps) == total + 1          # + reduce/step tail
            for t, cmds in enumerate(steps[:-1]):
                fwd = [c.micro_batch_id for c in cmds
                       if isinstance(c, ForwardPass)]
                bwd = [c.micro_batch_id for c in cmds
                       if isinstance(c, BackwardPass)]
                f = t - p
                b = t - (2 * (s - 1) - p)
                assert fwd == ([f] if 0 <= f < m else [])
                assert bwd == ([b] if 0 <= b < m else [])


@pytest.mark.parametrize("flavor", [
    "llama", pytest.param("gemma", marks=pytest.mark.slow)])
def test_llama_pipe_module_via_initialize(flavor, tmp_path):
    """initialize(model=PipeModule) returns a PipelineEngine (reference:
    deepspeed.initialize dispatching on PipelineModule, __init__.py:69); the
    llama adapter's pipelined loss matches the full model bit-for-bit-ish
    and training decreases it. The gemma flavor covers the tied-embedding,
    embed-scaling, soft-cap, and rms-offset branches of the adapter."""
    import deepspeed_tpu
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
    from deepspeed_tpu.runtime.pipe.module import llama_pipe_module

    extra = {} if flavor == "llama" else dict(
        tie_embeddings=True, scale_embeddings=True, logits_soft_cap=30.0,
        rms_scale_offset=True, remat=True)
    cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                      num_layers=4, num_heads=2, num_kv_heads=2,
                      max_seq_len=32, scan_layers=True, dtype=jnp.float32,
                      **extra)
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 128, size=(8, 16)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": jnp.asarray(tokens)})

    mesh = create_mesh(MeshConfig(pipe=4, data=2))
    set_global_mesh(mesh)
    engine, tx, _, _ = deepspeed_tpu.initialize(
        model=llama_pipe_module(cfg, params), mesh=mesh,
        config={"gradient_accumulation_steps": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}}})
    assert isinstance(engine, PipelineEngine)

    ref_loss = float(model.apply(params, {"input_ids": jnp.asarray(tokens)}))
    if flavor == "llama":
        # eval executor numerics: InferenceSchedule fill-drain == full model
        assert abs(engine.eval_batch(tokens) - ref_loss) < 5e-3
    l0 = engine.train_batch(tokens)
    assert abs(l0 - ref_loss) < 5e-3, (l0, ref_loss)
    l1 = engine.train_batch(tokens)
    l2 = engine.train_batch(tokens)
    assert l2 < l0, (l0, l1, l2)
    if flavor != "llama":
        return
    # checkpoint roundtrip on the same engine/compile (reference
    # PipelineEngine save/load through the latest-tag protocol)
    ev = engine.eval_batch(tokens)
    assert np.isfinite(ev) and ev < ref_loss    # trained -> lower loss
    d = str(tmp_path)
    engine.save_checkpoint(d)
    # pipeline checkpoints carry the same committed-checkpoint contract as
    # the main engine (ds_meta.json + integrity manifest + atomic latest),
    # so the resilience tooling recognizes them
    from deepspeed_tpu.checkpoint.engine import is_committed
    from deepspeed_tpu.resilience import find_latest_committed
    assert find_latest_committed(d) is not None
    assert is_committed(d, find_latest_committed(d))
    engine.train_batch(tokens)              # diverge past the checkpoint
    engine.load_checkpoint(d)
    e_after = engine.eval_batch(tokens)
    assert abs(e_after - ev) < 1e-5         # restore == pre-divergence state
    fresh, _, _, _ = deepspeed_tpu.initialize(
        model=llama_pipe_module(cfg, params), mesh=mesh,
        config={"gradient_accumulation_steps": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}}})
    fresh.load_checkpoint(d)
    assert abs(e_after - fresh.eval_batch(tokens)) < 1e-5
    assert fresh.global_steps == 3


@pytest.mark.slow
def test_pipe_to_dense_cross_topology_restore():
    """A PP run's weights consolidate back into the dense model tree and
    load into a ZeRO-3 engine with matching loss (the universal-checkpoint
    pp-rank consolidation story: reference checkpoint/universal covering
    pipeline-parallel topologies)."""
    import deepspeed_tpu
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from deepspeed_tpu.runtime.pipe.module import (llama_params_from_pipe,
                                                   llama_pipe_module)

    cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                      num_layers=4, num_heads=2, num_kv_heads=2,
                      max_seq_len=32, scan_layers=True, dtype=jnp.float32)
    model = LlamaForCausalLM(cfg)
    tokens = np.random.default_rng(0).integers(
        0, 128, size=(8, 16)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": jnp.asarray(tokens)})
    mesh = create_mesh(MeshConfig(pipe=4, data=2))
    set_global_mesh(mesh)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=llama_pipe_module(cfg, params), mesh=mesh,
        config={"gradient_accumulation_steps": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}}})
    for _ in range(3):
        eng.train_batch(tokens)
    pipe_eval = eng.eval_batch(tokens)

    stacked, tied = eng.consolidated_module_params()
    dense = llama_params_from_pipe(cfg, stacked, tied)
    z3_mesh = create_mesh(MeshConfig(data=2, fsdp=4))
    set_global_mesh(z3_mesh)
    e3, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        model_parameters=jax.tree.map(jnp.asarray, dense["params"]),
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3}},
        mesh=z3_mesh, example_batch={"input_ids": tokens[:4]})
    assert abs(float(e3.eval_batch({"input_ids": tokens})) - pipe_eval) < 5e-3


@pytest.mark.slow
def test_1f1b_masked_mode_matches_predicated():
    """predicate=False (the dstpu_pipe_bench A/B baseline: compute-both-and-
    mask) is numerically identical to the predicated executor — the bench's
    speedup comparison is apples-to-apples. (Slow: compiles a second
    executor variant; the predicated executor's correctness is covered fast
    by test_1f1b_matches_no_pipe.)"""
    from deepspeed_tpu.runtime.pipe.one_f_one_b import pipeline_train_step_1f1b
    stacked, tied, toks, block_fn, first_fn, last_fn = _toy_setup()
    mesh = create_mesh(MeshConfig(pipe=4, data=2))
    set_global_mesh(mesh)

    loss_p, gp_p, gt_p = pipeline_train_step_1f1b(
        block_fn, stacked, tied, toks, first_fn, last_fn, mesh=mesh)
    loss_m, gp_m, gt_m = pipeline_train_step_1f1b(
        block_fn, stacked, tied, toks, first_fn, last_fn, mesh=mesh,
        predicate=False)
    np.testing.assert_allclose(float(loss_p), float(loss_m), rtol=1e-6)
    for a, b in zip(jax.tree.leaves((gp_p, gt_p)),
                    jax.tree.leaves((gp_m, gt_m))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)
