"""Async step pipeline tests (runtime.async_pipeline config group).

The pipeline defers step-output readback onto a device-side ring drained
every ``sync_every`` steps and stages batches one step ahead on a background
thread. These tests pin the contracts that make that safe:

  numerics    : sync_every=1 vs 8 (± prefetch) produce bit-identical params
                and identical per-step losses on a seed-pinned run
  determinism : prefetch preserves batch order and the engine RNG stream
  readback    : host transfers scale as steps/sync_every (counted, not
                timed — wall-clock wins depend on host slack CI lacks)
  guard lag   : the resilience StepGuard observes steps with bounded lag
                (≤ sync_every) and every save/stop boundary flushes first,
                so checkpoints and RunResults never reflect un-guarded steps
"""

import numpy as np
import jax
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.simple import SimpleModel, random_batch
from deepspeed_tpu.runtime.dataloader import PrefetchLoader

CFG = {
    "train_batch_size": 8,
    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
}


def _engine(seed=1, extra=None):
    cfg = dict(CFG)
    if extra:
        cfg.update(extra)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=32), config=cfg,
        example_batch=random_batch(4), seed=seed)
    return engine


def _params(engine):
    return [np.asarray(x) for x in
            jax.tree.leaves(jax.device_get(engine.state.params))]


def _async_cfg(sync_every, prefetch=False):
    return {"async_pipeline": {"enabled": True, "sync_every": sync_every,
                               "prefetch": prefetch}}


# ---------------------------------------------------------------------------
# PrefetchLoader unit behavior
# ---------------------------------------------------------------------------
def test_prefetch_loader_preserves_order_and_ends():
    src = [{"x": np.full((2,), i)} for i in range(17)]
    out = list(PrefetchLoader(iter(src), depth=2))
    assert len(out) == 17
    for i, item in enumerate(out):
        assert item["x"][0] == i          # exact source order


def test_prefetch_loader_exhaustion_is_sticky():
    """A drained (or closed) loader keeps raising StopIteration — it must
    never block a caller that retries after the end of the stream."""
    pl = PrefetchLoader(iter(range(2)), depth=2)
    assert list(pl) == [0, 1]
    for _ in range(3):
        with pytest.raises(StopIteration):
            next(pl)
    pl2 = PrefetchLoader(iter(range(100)), depth=2)
    next(pl2)
    pl2.close()
    with pytest.raises(StopIteration):
        next(pl2)


def test_prefetch_loader_applies_stage_fn_and_propagates_errors():
    def bad_stage(item):
        if item == 3:
            raise ValueError("boom")
        return item * 10

    pl = PrefetchLoader(iter(range(5)), stage_fn=bad_stage, depth=2)
    assert next(pl) == 0
    assert next(pl) == 10
    assert next(pl) == 20
    with pytest.raises(ValueError, match="boom"):
        # the staged error surfaces at the consuming __next__
        next(pl)
    pl.close()


# ---------------------------------------------------------------------------
# numerics: the acceptance parity gate
# ---------------------------------------------------------------------------
def test_bit_identical_params_and_losses_sync1_vs_sync8_vs_prefetch():
    """sync_every=8 (+ prefetch) must be a pure scheduling change: identical
    per-step losses and bit-identical final params vs the synchronous path,
    with the engine RNG stream consumed identically."""
    steps = 8
    batches = [random_batch(8, seed=i) for i in range(steps)]

    sync = _engine(seed=1)
    sync_losses = [float(sync.train_batch(batch=b)) for b in batches]

    lagged = _engine(seed=1, extra=_async_cfg(8))
    lagged_losses = [lagged.train_batch(batch=b) for b in batches]
    lagged.flush_metrics()
    lagged_losses = [float(x) for x in lagged_losses]

    pre = _engine(seed=1, extra=_async_cfg(8, prefetch=True))
    it = iter(batches)
    pre_losses = []
    for _ in range(steps):
        pre_losses.append(pre.train_batch(data_iter=it))
    pre.flush_metrics()
    pre_losses = [float(x) for x in pre_losses]

    assert sync_losses == lagged_losses == pre_losses
    for a, b, c in zip(_params(sync), _params(lagged), _params(pre)):
        assert np.array_equal(a, b)
        assert np.array_equal(a, c)       # bit-identical, not approx
    # same RNG stream consumed (prefetch must not touch engine RNG)
    assert np.array_equal(np.asarray(jax.device_get(sync._rng)),
                          np.asarray(jax.device_get(pre._rng)))


# ---------------------------------------------------------------------------
# readback: transfers scale as steps / sync_every
# ---------------------------------------------------------------------------
def test_deferred_readback_transfer_count(monkeypatch):
    """The mechanical claim of the optimization, asserted deterministically:
    N steps cost ceil(N / sync_every) drain transfers, not N."""
    counts = {}

    real_device_get = jax.device_get

    def run(sync_every, steps=8):
        engine = _engine(seed=1, extra=_async_cfg(sync_every))
        batches = [random_batch(8, seed=i) for i in range(steps)]
        calls = [0]

        def counting_device_get(x):
            calls[0] += 1
            return real_device_get(x)

        monkeypatch.setattr(jax, "device_get", counting_device_get)
        try:
            for b in batches:
                engine.train_batch(batch=b)
        finally:
            monkeypatch.setattr(jax, "device_get", real_device_get)
        counts[sync_every] = calls[0]

    run(1)
    run(8)
    assert counts[1] == 8                 # one drain per step
    assert counts[8] == 1                 # one drain per 8 steps


def test_drained_entries_ordered_and_complete():
    engine = _engine(seed=1, extra=_async_cfg(3))
    for i in range(7):
        engine.train_batch(batch=random_batch(8, seed=i))
    assert len(engine._metric_ring) == 1            # 7 = 2 drains * 3 + 1
    flushed = engine.flush_metrics()
    assert len(flushed) == 1
    entries = engine.take_drained_metrics()
    assert [e["step"] for e in entries] == list(range(1, 8))
    for e in entries:
        assert {"step", "samples", "loss", "grad_norm", "lr", "overflow",
                "loss_scale"} <= set(e)
        assert isinstance(e["loss"], float)
    # consumed: the queue is drained
    assert engine.take_drained_metrics() == []
    # _last_metrics reflects the newest step, as host scalars
    assert isinstance(engine._last_metrics["loss"], float)


def test_monitor_events_land_at_drain(tmp_path):
    """steps_per_print-boundary events survive the deferred readback (at most
    sync_every late), plus the drain's steps_per_sec gauge."""
    engine = _engine(seed=1, extra={
        **_async_cfg(4),
        "steps_per_print": 2,
        "csv_monitor": {"enabled": True, "output_path": str(tmp_path),
                        "job_name": "async"}})
    for i in range(8):
        engine.train_batch(batch=random_batch(8, seed=i))
    engine.flush_metrics()
    d = tmp_path / "async"
    loss_rows = (d / "Train_Samples_train_loss.csv").read_text().strip()
    assert len(loss_rows.splitlines()) == 1 + 4     # header + steps 2,4,6,8
    assert (d / "Train_Samples_steps_per_sec.csv").exists()


def test_configure_async_pipeline_runtime_toggle():
    engine = _engine(seed=1)
    assert not engine._async_enabled
    engine.configure_async_pipeline(enabled=True, sync_every=4)
    for i in range(3):
        engine.train_batch(batch=random_batch(8, seed=i))
    assert len(engine._metric_ring) == 3
    engine.configure_async_pipeline(enabled=False)  # flushes first
    assert engine._metric_ring == []
    engine.train_batch(batch=random_batch(8, seed=9))
    assert engine._metric_ring == []                # back to per-step path


def test_async_disabled_on_host_offload_engines():
    """The fused host-optimizer step is synchronous by construction: an
    async ring would never fill and async-mode consumers would go blind —
    the engine refuses instead of silently degrading."""
    engine = _engine(seed=1, extra={
        **_async_cfg(8),
        "zero_optimization": {"offload_optimizer": {"device": "cpu"}}})
    assert not engine._async_enabled                # forced off at init
    loss = engine.train_batch(batch=random_batch(8, seed=0))
    assert np.isfinite(float(loss))
    assert engine._metric_ring == []
    with pytest.raises(ValueError, match="host-offload"):
        engine.configure_async_pipeline(enabled=True)


def test_save_checkpoint_flushes_ring(tmp_path):
    engine = _engine(seed=1, extra=_async_cfg(8))
    for i in range(3):
        engine.train_batch(batch=random_batch(8, seed=i))
    assert len(engine._metric_ring) == 3
    engine.save_checkpoint(str(tmp_path / "ckpt"))
    assert engine._metric_ring == []                # ckpt boundary = drain


# ---------------------------------------------------------------------------
# resilience integration: bounded guard lag + drain-on-signal ordering
# ---------------------------------------------------------------------------
def _runner(engine, tmp_path, chaos=None, **rc_kw):
    from deepspeed_tpu.resilience import FaultTolerantRunner, ResilienceConfig
    rc_kw.setdefault("diagnostics_dir", str(tmp_path / "diag"))
    rc_kw.setdefault("autosave", {})
    rc_kw["autosave"].setdefault("io_backoff_s", 0.01)
    return FaultTolerantRunner(engine, save_dir=str(tmp_path / "ckpt"),
                               config=ResilienceConfig(**rc_kw), chaos=chaos)


def test_guard_detection_lag_bounded_by_sync_every(tmp_path):
    """A NaN step is skipped on-device immediately, but the host guard only
    learns about it at the next drain — and no later."""
    from deepspeed_tpu.resilience import ChaosConfig, ChaosMonkey
    engine = _engine(seed=1, extra=_async_cfg(4))
    chaos = ChaosMonkey(ChaosConfig(seed=7, nan_steps=frozenset({1})))
    runner = _runner(engine, tmp_path, chaos=chaos,
                     step_guard={"backoff_after": 0, "quarantine_after": 0})
    for step in range(3):
        runner.step(batch=random_batch(8, seed=step))
    # device-side skip already happened; host guard hasn't drained yet
    assert engine.skipped_steps == 1
    assert runner.guard.total_bad == 0
    runner.step(batch=random_batch(8, seed=3))      # step 4 -> drain boundary
    assert runner.guard.total_bad == 1              # lag <= sync_every
    assert len(runner.history) == 4
    runner.close()


def test_quarantine_still_fires_with_lag_and_params_stay_clean(tmp_path):
    from deepspeed_tpu.resilience import (ChaosConfig, ChaosMonkey,
                                          QuarantineError)
    engine = _engine(seed=1, extra=_async_cfg(4))
    chaos = ChaosMonkey(ChaosConfig(seed=1, nan_prob=1.0))
    runner = _runner(engine, tmp_path, chaos=chaos,
                     step_guard={"backoff_after": 0, "quarantine_after": 3})
    with pytest.raises(QuarantineError):
        runner.run(num_steps=10, batch_fn=lambda s: random_batch(8, seed=s))
    runner.close()
    # every bad step was still dropped on-device at the step it happened
    assert engine.skipped_steps >= 3
    assert all(bool(np.isfinite(p).all()) for p in _params(engine))
    # quarantine fired at the 3rd bad entry; close()'s final drain judged
    # the requeued 4th (no step escapes the guard), hence >= not ==
    assert runner.guard.consecutive_bad >= 3


def test_runner_hands_iterator_through_to_prefetch(tmp_path):
    """FaultTolerantRunner(data_iter=...) must not defeat prefetch by
    materializing batches itself — without a chaos monkey the iterator goes
    straight through to the engine's background staging. With chaos, batch
    corruption needs host materialization, so prefetch stays off."""
    from deepspeed_tpu.resilience import ChaosConfig, ChaosMonkey
    engine = _engine(seed=1, extra=_async_cfg(4, prefetch=True))
    runner = _runner(engine, tmp_path, chaos=None)
    it = iter([random_batch(8, seed=i) for i in range(6)])
    result = runner.run(num_steps=4, data_iter=it)
    runner.close()
    assert result.steps_completed == 4
    assert engine._prefetcher is not None          # staging engaged
    assert np.isfinite(result.last_loss)

    chaotic = _engine(seed=1, extra=_async_cfg(4, prefetch=True))
    runner2 = _runner(chaotic, tmp_path,
                      chaos=ChaosMonkey(ChaosConfig(seed=5)))
    it2 = iter([random_batch(8, seed=i) for i in range(3)])
    runner2.run(num_steps=2, data_iter=it2)
    runner2.close()
    assert chaotic._prefetcher is None             # inline path kept


def test_guard_raise_mid_replay_requeues_unjudged_tail(tmp_path):
    """When quarantine fires on entry k of a drained batch, entries k+1..n
    go back to the engine's queue — a later flush still judges them, so no
    step ever escapes the guard because an earlier one blew up."""
    from deepspeed_tpu.resilience import (ChaosConfig, ChaosMonkey,
                                          QuarantineError)
    engine = _engine(seed=1, extra=_async_cfg(4))
    chaos = ChaosMonkey(ChaosConfig(seed=1, nan_prob=1.0))
    runner = _runner(engine, tmp_path, chaos=chaos,
                     step_guard={"backoff_after": 0, "quarantine_after": 2})
    with pytest.raises(QuarantineError):
        runner.run(num_steps=8, batch_fn=lambda s: random_batch(8, seed=s))
    # 4 entries drained at the step-4 boundary; quarantine raised on the
    # 2nd -> the other 2 are back in the queue, not silently dropped
    assert len(engine._drained_metrics) == 2
    assert [e["step"] for e in engine._drained_metrics] == [3, 4]
    runner.close()                                  # final drain judges them
    assert len(engine._drained_metrics) == 0
    assert runner.guard.total_bad == 4
    runner.close()


def test_sigterm_autosave_flushes_ring_before_snapshot(tmp_path):
    """Drain-on-signal ordering: the preemption save replays the pending
    ring through the guard FIRST, so the committed checkpoint's guard state
    already counts a NaN hiding in the un-drained window."""
    import os
    import signal
    from deepspeed_tpu.checkpoint.engine import is_committed
    from deepspeed_tpu.resilience import (ChaosConfig, ChaosMonkey,
                                          find_latest_committed)
    engine = _engine(seed=1, extra=_async_cfg(8))
    chaos = ChaosMonkey(ChaosConfig(seed=7, nan_steps=frozenset({0})))
    runner = _runner(engine, tmp_path, chaos=chaos,
                     step_guard={"backoff_after": 0, "quarantine_after": 0})
    fired = []

    def batches(step):
        if step == 2 and not fired:
            fired.append(step)
            os.kill(os.getpid(), signal.SIGTERM)
        return random_batch(8, seed=step)

    result = runner.run(num_steps=6, batch_fn=batches)
    runner.close()
    assert result.stop_reason == "preempted"
    assert result.steps_completed == 3
    assert engine._metric_ring == []                # flushed before snapshot
    assert runner.guard.total_bad == 1              # NaN seen despite lag
    ckpt_dir = str(tmp_path / "ckpt")
    tag = find_latest_committed(ckpt_dir)
    assert tag == "global_step3"
    assert is_committed(ckpt_dir, tag)

    # the committed client_state carries the flushed guard verdicts
    fresh = _engine(seed=9, extra=_async_cfg(8))
    runner2 = _runner(fresh, tmp_path)
    assert runner2.resume_from_latest() == "global_step3"
    assert runner2.guard.total_bad == 1
    runner2.close()


@pytest.mark.slow
def test_resume_parity_with_async_pipeline(tmp_path):
    """save -> SIGTERM -> resume under the async pipeline matches an
    uninterrupted async baseline step for step (the PR-2 chaos contract
    survives deferred readback). Marked slow: tier-1 keeps the cheaper
    drain-on-signal ordering test above; full CI (`pytest -m ""`) runs
    this three-engine parity flavor."""
    import os
    import signal
    total = 6
    base = _engine(seed=1, extra=_async_cfg(4))
    base_losses = [float(base.train_batch(batch=random_batch(8, seed=s)))
                   for s in range(total)]

    victim = _engine(seed=1, extra=_async_cfg(4))
    runner = _runner(victim, tmp_path)
    fired = []

    def batches(step):
        if step == 3 and not fired:
            fired.append(step)
            os.kill(os.getpid(), signal.SIGTERM)
        return random_batch(8, seed=step)

    result = runner.run(num_steps=total, batch_fn=batches)
    runner.close()
    assert result.stop_reason == "preempted"

    resumed = _engine(seed=42, extra=_async_cfg(4))
    runner2 = _runner(resumed, tmp_path)
    assert runner2.resume_from_latest() == "global_step4"
    post = [float(resumed.train_batch(batch=random_batch(8, seed=s)))
            for s in range(4, total)]
    resumed.flush_metrics()
    runner2.close()
    for expect, got in zip(base_losses[4:], post):
        assert abs(expect - got) < 1e-6
