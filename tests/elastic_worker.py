"""Elastic end-to-end worker: train, checkpoint every step, resume on relaunch.

Spawned by ``ElasticAgent`` in the kill-and-resume test
(test_elasticity.py::test_elastic_kill_and_resume_end_to_end). Env contract:
the agent's rendezvous vars (``DSTPU_COORDINATOR_ADDRESS`` / ``_NUM_PROCESSES``
/ ``_PROCESS_ID``), ``DSTPU_ELASTIC_BATCH`` (the compatible global batch the
agent computed for this generation — same across scales, the elastic
invariant), ``DSTPU_ELASTIC_RESTART`` (generation), plus test knobs:
``DSTPU_EW_DIR`` (checkpoint + loss-log dir), ``DSTPU_EW_TOTAL_STEPS``,
``DSTPU_EW_KILL_RANK``/``DSTPU_EW_KILL_STEP`` (generation-0 fault injection:
SIGKILL that rank right after that step's checkpoint commits — the
uncatchable-death case a supervisor exists for).
"""

import json
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices",
                  int(os.environ.get("DSTPU_EW_LOCAL_DEVICES", "2")))

nproc = int(os.environ["DSTPU_NUM_PROCESSES"])
rank = int(os.environ["DSTPU_PROCESS_ID"])
if nproc > 1:
    # rendezvous itself happens inside deepspeed_tpu.initialize() via the
    # agent's DSTPU_* env (comm/mesh.py discover_cluster_env) — exactly the
    # production worker flow; only the CPU collective impl needs configuring
    jax.config.update("jax_cpu_collectives_implementation", "gloo")


def main():
    import deepspeed_tpu
    from deepspeed_tpu.models.simple import SimpleModel, random_batch

    workdir = os.environ["DSTPU_EW_DIR"]
    total_steps = int(os.environ["DSTPU_EW_TOTAL_STEPS"])
    gen = int(os.environ["DSTPU_ELASTIC_RESTART"])
    batch = int(os.environ["DSTPU_ELASTIC_BATCH"])
    kill_rank = int(os.environ.get("DSTPU_EW_KILL_RANK", "-1"))
    kill_step = int(os.environ.get("DSTPU_EW_KILL_STEP", "-1"))

    # no mesh arg and no jax calls before initialize(): the rendezvous
    # (jax.distributed) must run before anything touches the XLA backend;
    # initialize() then builds the default data-parallel mesh over the
    # global device set
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=64),
        config={"train_batch_size": batch,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}},
        example_batch=random_batch(2))

    ckpt_dir = os.path.join(workdir, "ckpt")
    engine.load_checkpoint(ckpt_dir)   # no-op when no 'latest' yet (gen 0)
    start = engine.global_steps

    log = os.path.join(workdir, f"losses_gen{gen}_rank{rank}.jsonl")
    local = batch // nproc
    for step in range(start, total_steps):
        # deterministic per-step GLOBAL batch, sliced to this process's
        # distinct shard (engine._shard_batch assembles the global array from
        # per-process locals) — the loss trajectory is comparable across
        # generations/world sizes because the assembled batch is identical
        full = random_batch(batch, seed=step)
        shard = {k: v[rank * local:(rank + 1) * local] for k, v in full.items()}
        loss = float(engine.train_batch(batch=shard))
        engine.save_checkpoint(ckpt_dir)
        with open(log, "a") as f:
            f.write(json.dumps({"step": step, "loss": loss,
                                "world": nproc}) + "\n")
        if gen == 0 and rank == kill_rank and step + 1 >= kill_step:
            os.kill(os.getpid(), signal.SIGKILL)   # simulated node loss


if __name__ == "__main__":
    main()
