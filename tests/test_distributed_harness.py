"""Multi-process harness tests.

Reference analog: ``tests/unit/comm/test_dist.py`` (the harness self-test) —
spawn real processes, rendezvous, run collectives, propagate failures.
Marked slow: each case pays multi-process jax startup + compiles.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.testing import DistributedTest, run_distributed

pytestmark = pytest.mark.slow


def _psum_body():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    assert len(devs) == 4, devs  # 2 procs x 2 local devices -> global view
    mesh = Mesh(np.array(devs).reshape(4), ("data",))
    x = jax.device_put(jnp.ones((8, 2)), NamedSharding(mesh, P("data")))
    total = jax.jit(lambda v: v.sum(), out_shardings=NamedSharding(mesh, P()))(x)
    assert float(total) == 16.0
    print(f"rank {jax.process_index()} ok")


def _engine_body():
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.comm.mesh import create_mesh
    from deepspeed_tpu.config.config import MeshConfig
    from deepspeed_tpu.models.simple import SimpleModel, random_batch

    mesh = create_mesh(MeshConfig(data=2, fsdp=2))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=64),
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3}},
        mesh=mesh, example_batch=random_batch(4))
    loss = engine.train_batch(batch=random_batch(8 // engine.dp_world_size
                                                 * engine.dp_world_size))
    assert np.isfinite(float(loss))
    print(f"rank {jax.process_index()} loss {float(loss):.3f}")


def _failing_body():
    raise AssertionError("rank failure must propagate")


def test_psum_across_processes():
    outs = run_distributed(_psum_body, world_size=2, devices_per_process=2)
    assert all("ok" in o for o in outs)


def test_engine_trains_across_processes():
    outs = run_distributed(_engine_body, world_size=2, devices_per_process=2)
    assert all("loss" in o for o in outs)


def test_failure_propagates():
    with pytest.raises(RuntimeError, match="rank .* exited"):
        run_distributed(_failing_body, world_size=2, devices_per_process=1,
                        timeout=120)


def test_class_style_harness():
    class TwoRank(DistributedTest):
        world_size = 2
        devices_per_process = 2
        run = staticmethod(_psum_body)

    TwoRank().launch()


def test_rejects_local_functions():
    def local():
        pass

    with pytest.raises(ValueError, match="importable"):
        run_distributed(local, world_size=2)


def _save_ckpt_body():
    """DistributedFixture setup half: train 2 steps across 2 processes on a
    (data=2, fsdp=2) global mesh and save one logical checkpoint."""
    import os

    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.comm.mesh import create_mesh
    from deepspeed_tpu.config.config import MeshConfig
    from deepspeed_tpu.models.simple import SimpleModel, random_batch

    mesh = create_mesh(MeshConfig(data=2, fsdp=2))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=64),
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3}},
        mesh=mesh, example_batch=random_batch(4))
    for _ in range(2):
        loss = engine.train_batch(batch=random_batch(8, seed=0))
    engine.save_checkpoint(os.environ["DSTPU_TEST_CKPT_DIR"])
    print(f"saved at loss {float(loss):.4f}")


def test_checkpoint_saved_multiprocess_loads_single_process(tmp_path):
    """The reference's DistributedFixture canonical example (common.py:360):
    produce a checkpoint at one world size, consume it at another. Here: save
    from 2 real processes (4 global devices), load in THIS process on the
    8-device mesh — reshape-on-load across process topologies."""
    ckpt = str(tmp_path / "ckpt")
    outs = run_distributed(_save_ckpt_body, world_size=2,
                           devices_per_process=2,
                           env={"DSTPU_TEST_CKPT_DIR": ckpt})
    assert any("saved at loss" in o for o in outs)

    import deepspeed_tpu
    from deepspeed_tpu.comm.mesh import create_mesh, set_global_mesh
    from deepspeed_tpu.config.config import MeshConfig
    from deepspeed_tpu.models.simple import SimpleModel, random_batch

    mesh = create_mesh(MeshConfig(data=4, fsdp=2))   # different topology
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=64),
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3}},
        mesh=mesh, example_batch=random_batch(4))
    engine.load_checkpoint(ckpt)
    assert engine.global_steps == 2
    loss = float(engine.train_batch(batch=random_batch(8, seed=0)))
    assert np.isfinite(loss)
    set_global_mesh(None)


def _commguard_body():
    """MULTICHIP-with-guards body: the bounded rendezvous already ran in the
    bootstrap (testing.py routes through comm.init_distributed); here each
    rank trains under a FaultTolerantRunner with the comm_guard group active
    — heartbeat publishing, membership polling at every step boundary — and
    asserts the whole cluster stays healthy."""
    import os
    import time

    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.comm.mesh import create_mesh
    from deepspeed_tpu.config.config import MeshConfig
    from deepspeed_tpu.models.simple import SimpleModel, random_batch
    from deepspeed_tpu.resilience import FaultTolerantRunner

    members = os.environ["DSTPU_TEST_MEMBERS_DIR"]
    mesh = create_mesh(MeshConfig(data=2, fsdp=2))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=64),
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "comm_guard": {"heartbeat_interval_s": 0.2,
                               "lost_after_s": 30.0,
                               "membership_dir": members}},
        mesh=mesh, example_batch=random_batch(4))
    rank = jax.process_index()
    runner = FaultTolerantRunner(
        engine, save_dir=os.path.join(members, f"ckpt_r{rank}"))
    assert runner.comm_guard is not None
    assert runner.heartbeat is not None and runner.membership is not None
    result = runner.run(num_steps=2,
                        batch_fn=lambda step: random_batch(8, seed=step))
    assert result.stop_reason == "completed", result.stop_reason
    assert bool(np.isfinite(result.last_loss))
    # both ranks' heartbeats are on disk and fresh: membership healthy
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        snap = runner.membership.snapshot()
        if len(snap) == 2 and all(h.alive for h in snap.values()):
            break
        time.sleep(0.1)
    snap = runner.membership.snapshot()
    assert sorted(snap) == [0, 1] and all(h.alive for h in snap.values()), snap
    runner.close()
    print(f"rank {rank} guarded ok (peers {sorted(snap)})")


def test_multichip_with_commguard_active(tmp_path):
    """Acceptance: the MULTICHIP harness stays rc=0 with guards active —
    bounded rendezvous + heartbeats + membership across real processes."""
    outs = run_distributed(
        _commguard_body, world_size=2, devices_per_process=2,
        env={"DSTPU_TEST_MEMBERS_DIR": str(tmp_path / "members")})
    assert all("guarded ok" in o for o in outs)


def _comm_compress_body():
    """MULTICHIP-with-compression body: every gradient reduction over the
    replica axis moves int8 codes + per-chunk scales across REAL process
    boundaries, error-feedback state threaded through the optimizer state,
    wire-byte counters recorded on every rank."""
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.comm.comms_logging import get_comms_logger
    from deepspeed_tpu.comm.mesh import create_mesh
    from deepspeed_tpu.config.config import MeshConfig
    from deepspeed_tpu.models.simple import SimpleModel, random_batch

    cl = get_comms_logger()
    cl.configure(enabled=True)
    mesh = create_mesh(MeshConfig(data=2, fsdp=2))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=64),
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3},
                "comm_compression": {"enabled": True}},
        mesh=mesh, example_batch=random_batch(4))
    assert engine._comm_compress is not None
    for _ in range(2):
        loss = engine.train_batch(batch=random_batch(8, seed=0))
    assert np.isfinite(float(loss))
    totals = cl.per_op_totals()["quantized_all_reduce"]
    assert totals["bytes"] / totals["wire_bytes"] >= 3.5, totals
    # EF state is sharded over the replica axis (rows span processes):
    # inspect this rank's addressable shards
    ef_leaves = jax.tree_util.tree_leaves(
        engine.state.opt_state.error_feedback)
    assert any(np.abs(np.asarray(s.data)).max() > 0
               for leaf in ef_leaves for s in leaf.addressable_shards)
    print(f"rank {jax.process_index()} compressed ok "
          f"({totals['bytes'] / totals['wire_bytes']:.2f}x)")


def test_multichip_with_comm_compression_enabled():
    """Acceptance (ISSUE 14): the MULTICHIP harness exits rc=0 with
    ``comm_compression`` enabled — quantized error-feedback collectives
    over a real multi-process replica axis, counters proving the wire
    reduction on every rank."""
    outs = run_distributed(_comm_compress_body, world_size=2,
                           devices_per_process=2)
    assert all("compressed ok" in o for o in outs)
