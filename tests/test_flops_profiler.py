"""Flops profiler tests.

Reference analog: ``tests/unit/profiling/flops_profiler/test_flops_profiler.py`` —
checks counted flops/params on small known models (within tolerance) and that the
engine auto-profiles at ``profile_step``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.simple import SimpleModel, random_batch
from deepspeed_tpu.profiling.flops_profiler import (
    FlopsProfiler,
    count_flops,
    flops_to_string,
    get_model_profile,
    params_to_string,
)


def test_count_matmul_exact():
    a = jnp.zeros((8, 64))
    b = jnp.zeros((64, 32))
    flops, macs, per_mod = count_flops(lambda x, y: x @ y, a, b)
    assert macs == 8 * 64 * 32
    assert flops == 2 * 8 * 64 * 32


def test_elementwise_and_reduction():
    x = jnp.zeros((128,))
    flops, _, _ = count_flops(lambda v: jnp.sum(v * v), x)
    assert flops == 128 + 128  # mul + reduce_sum


def test_scan_multiplies_body_cost():
    x = jnp.zeros((16, 16))

    def step(c, _):
        return c @ x, None

    def fn(v):
        out, _ = jax.lax.scan(step, v, None, length=5)
        return out

    flops, macs, _ = count_flops(fn, jnp.zeros((16, 16)))
    assert macs == 5 * 16 * 16 * 16


def test_dense_model_attribution():
    model = SimpleModel(hidden_dim=32)
    batch = random_batch(4)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]

    def fwd(p, b):
        return model.apply({"params": p}, b)

    flops, macs, per_mod = count_flops(fwd, params, batch)
    assert macs > 0
    # flax named_scope attribution: at least one scope mentions a Dense layer
    assert any(per_mod.values())


def test_profiler_api_and_strings():
    model = SimpleModel(hidden_dim=16)
    batch = random_batch(2)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]

    def fwd(p, b):
        return model.apply({"params": p}, b)

    prof = FlopsProfiler(fwd, params=params)
    prof.start_profile()
    fwd(params, batch)
    prof.stop_profile(params, batch)
    assert prof.get_total_flops() > 0
    assert prof.get_total_params() == sum(
        int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    text = prof.print_model_profile(top_modules=3)
    assert "Flops Profiler" in text
    assert "FLOPS" in prof.get_total_flops(as_string=True)
    prof.end_profile()
    assert prof.get_total_flops() == 0
    assert flops_to_string(2.5e12) == "2.5 TFLOPS"
    assert params_to_string(125e6) == "125.0 M"


def test_xla_cost_analysis_close_to_analytic():
    # pure matmul: analytic == XLA (no fusion to shrink it)
    a = jnp.zeros((32, 128), jnp.float32)
    b = jnp.zeros((128, 64), jnp.float32)

    def fn(x, y):
        return x @ y

    prof = FlopsProfiler(fn)
    prof.start_profile()
    prof.stop_profile(a, b)
    xla = prof.get_xla_flops()
    if xla:  # cost analysis availability is backend-dependent
        assert xla == pytest.approx(prof.get_total_flops(), rel=0.01)


def test_get_model_profile_oneshot():
    model = SimpleModel(hidden_dim=16)
    batch = random_batch(2)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]

    def fwd(p, b):
        return model.apply({"params": p}, b)

    flops, macs, n_params = get_model_profile(
        fwd, args=(params, batch), params=params, print_profile=False)
    assert flops > 0 and macs > 0 and n_params > 0


def test_engine_profiles_at_step():
    model = SimpleModel(hidden_dim=16)
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "flops_profiler": {"enabled": True, "profile_step": 1, "top_modules": 3},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=config, example_batch=random_batch(8))
    engine.train_batch(batch=random_batch(8))  # step 0 -> 1
    engine.train_batch(batch=random_batch(8))  # profiles at step 1
    assert hasattr(engine, "flops_profiler")
    assert engine.flops_profiler.get_total_flops() > 0


@pytest.mark.slow
def test_engine_profile_trace(tmp_path):
    import deepspeed_tpu
    from deepspeed_tpu.models.simple import SimpleModel, random_batch
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16),
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}},
        example_batch=random_batch(4))
    engine.start_profile_trace(str(tmp_path))
    engine.train_batch(batch=random_batch(8, seed=0))
    engine.stop_profile_trace()
    import os
    found = [f for _, _, fs in os.walk(tmp_path) for f in fs]
    assert found, "no trace files written"
