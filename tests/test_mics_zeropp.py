"""MiCS + ZeRO++ (hpZ / qwZ) hierarchical sharding tests.

Reference analog: tests/unit/runtime/zero/test_zeropp.py + mics tests —
hierarchical partitioning correctness and parity with plain ZeRO-3 training.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import create_mesh, get_data_parallel_world_size
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.models.simple import SimpleModel, random_batch
from deepspeed_tpu.runtime.zero.partition import (
    build_param_shardings, param_partition_spec, secondary_partition_spec)


def _leaf_specs(shardings):
    return [s.spec for s in jax.tree.leaves(shardings)]


# ---------------------------------------------------------------- spec logic
def test_stage3_spec_covers_full_hierarchical_world():
    spec = param_partition_spec((256, 256), stage=3, fsdp_size=4,
                                fsdp_axes=("fsdp_out", "fsdp"))
    assert ("fsdp_out", "fsdp") in tuple(spec)


def test_mics_spec_inner_axis_only():
    spec = param_partition_spec((256, 256), stage=3, fsdp_size=2,
                                fsdp_axes=("fsdp",))
    assert "fsdp" in tuple(spec) and not any(
        isinstance(e, tuple) and "fsdp_out" in e for e in spec)


def test_secondary_partition_spec_rewrites():
    sec = secondary_partition_spec(PartitionSpec(("fsdp_out", "fsdp"), None))
    assert tuple(sec) == ("fsdp", None)
    sec2 = secondary_partition_spec(PartitionSpec(("tensor", "fsdp_out", "fsdp")))
    assert tuple(sec2) == (("tensor", "fsdp"),)
    # untouched specs pass through
    assert tuple(secondary_partition_spec(PartitionSpec(None, "tensor"))) == \
        (None, "tensor")


# ---------------------------------------------------------------- MiCS engine
def _engine(zero_cfg, mesh_cfg=None, hidden=64, seed=0):
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": zero_cfg,
    }
    if mesh_cfg:
        config["mesh"] = mesh_cfg
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=hidden), config=config,
        example_batch=random_batch(4), seed=seed)
    return engine


def test_mics_splits_mesh_and_shards_inner_only():
    engine = _engine({"stage": 3, "mics_shard_size": 2,
                      "zero_quantized_gradients": True},
                     mesh_cfg={"data": 2, "fsdp": 4})
    assert engine.mesh.shape["fsdp"] == 2 and engine.mesh.shape["fsdp_out"] == 2
    assert get_data_parallel_world_size(engine.mesh) == 8
    for spec in _leaf_specs(engine.param_shardings):
        for entry in spec:
            assert entry != ("fsdp_out", "fsdp")  # never the full world
    # at least one big leaf sharded over the inner axis
    assert any("fsdp" in tuple(s) for s in _leaf_specs(engine.param_shardings))
    # engine-level MiCS+qgZ wiring: the replicated fsdp_out hop joins the
    # replica axes, giving the reference's hierarchical intra->inter reduce
    assert engine._qgz_axes == ("data", "fsdp_out")


@pytest.mark.slow
def test_mics_matches_plain_zero3_training():
    fixed = random_batch(8, seed=0)
    e_mics = _engine({"stage": 3, "mics_shard_size": 2},
                     mesh_cfg={"data": 2, "fsdp": 4})
    e_z3 = _engine({"stage": 3}, mesh_cfg={"data": 2, "fsdp": 4})
    losses_m = [float(e_mics.train_batch(batch=fixed)) for _ in range(5)]
    losses_3 = [float(e_z3.train_batch(batch=fixed)) for _ in range(5)]
    np.testing.assert_allclose(losses_m, losses_3, rtol=2e-4)


# ---------------------------------------------------------------- hpZ engine
def test_hpz_secondary_shardings_built_and_trains():
    """Fast hpZ engine check: shardings + a 3-step loss decrease on a fixed
    batch (full 5-step z3-parity lives in the slow tests)."""
    engine = _engine({"stage": 3, "zero_hpz_partition_size": 2},
                     mesh_cfg={"data": 2, "fsdp": 4})
    assert engine.mesh.shape["fsdp_out"] == 2 and engine.mesh.shape["fsdp"] == 2
    assert engine._secondary_shardings is not None
    # primary params keep the full hierarchical shard (memory), secondary
    # rewrites to inner-only
    prim = _leaf_specs(engine.param_shardings)
    sec = _leaf_specs(engine._secondary_shardings)
    assert any(("fsdp_out", "fsdp") in tuple(p) for p in prim)
    assert not any(("fsdp_out", "fsdp") in tuple(s) for s in sec)
    fixed = random_batch(8, seed=0)
    losses = [float(engine.train_batch(batch=fixed)) for _ in range(3)]
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses


@pytest.mark.slow
def test_hpz_matches_plain_zero3_losses():
    fixed = random_batch(8, seed=0)
    e_hpz = _engine({"stage": 3, "zero_hpz_partition_size": 2},
                    mesh_cfg={"data": 2, "fsdp": 4})
    e_z3 = _engine({"stage": 3}, mesh_cfg={"data": 2, "fsdp": 4})
    losses_h = [float(e_hpz.train_batch(batch=fixed)) for _ in range(5)]
    losses_3 = [float(e_z3.train_batch(batch=fixed)) for _ in range(5)]
    np.testing.assert_allclose(losses_h, losses_3, rtol=2e-4)


@pytest.mark.slow
def test_qwz_quantized_gather_close_to_exact():
    fixed = random_batch(8, seed=0)
    e_q = _engine({"stage": 3, "zero_hpz_partition_size": 2,
                   "zero_quantized_weights": True},
                  mesh_cfg={"data": 2, "fsdp": 4})
    assert e_q._quantized_weights
    e_z3 = _engine({"stage": 3}, mesh_cfg={"data": 2, "fsdp": 4})
    losses_q = [float(e_q.train_batch(batch=fixed)) for _ in range(40)]
    losses_3 = [float(e_z3.train_batch(batch=fixed)) for _ in range(40)]
    # int8 weight gather adds noise (coarse on a 64-wide toy model) but training
    # still converges and the first-step loss matches the exact path closely
    assert losses_q[-1] < 0.5 * losses_q[0], (losses_q[0], losses_q[-1])
    np.testing.assert_allclose(losses_q[0], losses_3[0], rtol=0.05)


def test_qwz_without_hpz_is_ignored():
    engine = _engine({"stage": 3, "zero_quantized_weights": True},
                     mesh_cfg={"data": 2, "fsdp": 4})
    assert not engine._quantized_weights


@pytest.mark.slow
def test_mics_checkpoint_reshape_to_plain_zero3(tmp_path):
    fixed = random_batch(8, seed=0)
    e_mics = _engine({"stage": 3, "mics_shard_size": 2},
                     mesh_cfg={"data": 2, "fsdp": 4})
    for _ in range(3):
        e_mics.train_batch(batch=fixed)
    e_mics.save_checkpoint(str(tmp_path))
    loss_m = float(e_mics.eval_batch(fixed))

    e_z3 = _engine({"stage": 3}, mesh_cfg={"data": 4, "fsdp": 2}, seed=99)
    e_z3.load_checkpoint(str(tmp_path))
    loss_3 = float(e_z3.eval_batch(fixed))
    np.testing.assert_allclose(loss_3, loss_m, rtol=1e-4)


def test_invalid_mics_split_raises():
    with pytest.raises(ValueError):
        _engine({"stage": 3, "mics_shard_size": 3}, mesh_cfg={"data": 2, "fsdp": 4})


@pytest.mark.slow
def test_qgz_stage3_converges_to_parity():
    """zero_quantized_gradients: stage-3 training with int8 gradient
    quantization at the reduction boundary converges like fp gradients
    (reference: all_to_all_quant_reduce, coalesced_collectives.py:31)."""
    import deepspeed_tpu
    from deepspeed_tpu.models.simple import SimpleModel, random_batch

    def train(qgz):
        config = {
            "train_batch_size": 16,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 3,
                                  "zero_quantized_gradients": qgz},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=SimpleModel(hidden_dim=64), config=config,
            example_batch=random_batch(4))
        assert engine._quantized_gradients == qgz
        fixed = random_batch(16, seed=0)
        return [float(engine.train_batch(batch=fixed)) for _ in range(15)]

    fp = train(False)
    qg = train(True)
    assert qg[-1] < 0.2 * qg[0], qg          # converges
    assert abs(qg[-1] - fp[-1]) < 0.1 + 0.5 * fp[-1], (qg[-1], fp[-1])


def test_qgz_pure_fsdp_fallback_warns():
    """zero_quantized_gradients on a mesh with no replica batch axis saves no
    wire bytes — the engine must say so LOUDLY (UserWarning + logger.warning),
    not fall back silently (VERDICT r3 weak #5)."""
    with pytest.warns(UserWarning, match="no bytes are saved on the wire|NO "
                                         "bytes"):
        engine = _engine({"stage": 3, "zero_quantized_gradients": True},
                         mesh_cfg={"fsdp": 8})
    assert engine._quantized_gradients and not engine._qgz_axes


def test_qgz_replica_axes_detection():
    """qgZ engages the int8-wire path exactly on the replica batch axes
    (batch-sharded, parameter-free, size>1) — runtime/zero/qgz.py. Pure
    function-level check (the engine wiring is asserted by the wire test);
    a NamedSharding leaf tree stands in for param_shardings."""
    from jax.sharding import NamedSharding
    from deepspeed_tpu.runtime.zero.qgz import replica_grad_axes

    def axes(mesh_cfg, param_spec):
        mesh = create_mesh(MeshConfig(**mesh_cfg))
        shardings = {"w": NamedSharding(mesh, param_spec)}
        return replica_grad_axes(
            mesh, PartitionSpec(("data", "fsdp_out", "fsdp")), shardings)

    # data is a replica axis; fsdp shards params under stage 3
    assert axes({"data": 2, "fsdp": 4},
                PartitionSpec("fsdp", None)) == ("data",)
    # MiCS: params shard over inner fsdp only -> fsdp_out is replica too
    # (the reference's hierarchical intra->inter structure)
    assert axes({"data": 2, "fsdp_outer": 2, "fsdp": 2},
                PartitionSpec("fsdp", None)) == ("data", "fsdp_out")
    # pure-fsdp mesh: no replica axis -> numerics-simulation fallback
    assert axes({"fsdp": 8},
                PartitionSpec(("fsdp_out", "fsdp"), None)) == ()


def test_qgz_wire_is_int8_and_converges_to_parity():
    """The qgZ gradient reduction moves REAL int8 bytes: the lowered train
    step contains all_to_all + all_gather collectives with i8 operands
    (reference: all_to_all_quant_reduce, coalesced_collectives.py:31 — int8
    on the wire, not a numerics round-trip), and training matches fp
    gradients."""
    e_qg = _engine({"stage": 3, "zero_quantized_gradients": True},
                   mesh_cfg={"data": 2, "fsdp": 4})
    e_fp = _engine({"stage": 3}, mesh_cfg={"data": 2, "fsdp": 4})

    e_qg._build_train_batch_fn()
    stacked = jax.tree.map(lambda x: np.asarray(x)[None],
                           random_batch(8, seed=0))
    device_batch = e_qg._shard_batch(stacked, stacked=True)
    txt = e_qg._train_batch_fn.lower(
        e_qg.state, device_batch, jax.random.PRNGKey(0)).as_text()
    a2a_i8 = [ln for ln in txt.splitlines()
              if "all_to_all" in ln and "i8" in ln]
    ag_i8 = [ln for ln in txt.splitlines()
             if "all_gather" in ln and "i8" in ln]
    assert a2a_i8, "gradient reduce-scatter does not carry int8 on the wire"
    assert ag_i8, "gradient regather does not carry int8 on the wire"

    fixed = random_batch(8, seed=0)
    qg = [float(e_qg.train_batch(batch=fixed)) for _ in range(10)]
    fp = [float(e_fp.train_batch(batch=fixed)) for _ in range(10)]
    assert qg[-1] < 0.2 * qg[0], qg
    assert abs(qg[-1] - fp[-1]) < 0.1 + 0.5 * fp[-1], (qg[-1], fp[-1])


def test_qgz_grad_sync_matches_pmean():
    """quantized_grad_sync == pmean within int8 quantization error, on a
    2-axis (hierarchical) manual mesh."""
    from jax.sharding import NamedSharding
    from deepspeed_tpu.runtime.zero.qgz import quantized_grad_sync

    mesh = create_mesh(MeshConfig(data=2, fsdp_outer=2, fsdp=2))
    rng = np.random.default_rng(7)
    # one large leaf (quantized wire) + one tiny leaf (fp pmean)
    big = jnp.asarray(rng.normal(size=(8, 64, 64)), jnp.float32)
    tiny = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)

    def body(b, t):
        out = quantized_grad_sync(
            {"big": b[0], "tiny": t[0]}, ("data", "fsdp_out"))
        return out["big"], out["tiny"]

    f = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(PartitionSpec(("data", "fsdp_out")),) * 2,
        out_specs=(PartitionSpec(), PartitionSpec()),
        axis_names=frozenset({"data", "fsdp_out"}), check_vma=False))
    # 4 manual groups (data x fsdp_out), one partial per group on dim 0
    big4, tiny4 = big[:4], tiny[:4]
    ob, ot = f(big4, tiny4)
    exact_b = np.asarray(big4).mean(0)
    exact_t = np.asarray(tiny4).mean(0)
    rel = np.abs(np.asarray(ob) - exact_b).max() / np.abs(exact_b).max()
    assert rel < 0.03, rel                      # int8 wire error bound
    np.testing.assert_allclose(np.asarray(ot), exact_t, rtol=1e-5, atol=1e-6)
