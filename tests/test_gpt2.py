"""GPT-2 family tests: training on a TP mesh, HF Conv1D conversion (numeric
split check), paged serving parity.

Reference analog: HFGPT2LayerPolicy / megatron-gpt container cases.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.llama import random_tokens


@pytest.mark.slow
def test_gpt2_trains_and_serves():
    """GPT-2: train on a TP mesh, HF Conv1D conversion, paged serving parity."""
    from deepspeed_tpu.inference.v2.engine_v2 import (
        InferenceEngineV2, V2EngineConfig)
    from deepspeed_tpu.inference.v2.modules import GPT2Policy, policy_for
    from deepspeed_tpu.models.gpt2 import (
        TINY_GPT2, GPT2ForCausalLM, convert_hf_gpt2, gpt2_tensor_rules)

    cfg = TINY_GPT2
    assert policy_for(cfg) is GPT2Policy
    model = GPT2ForCausalLM(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3},
                "mesh": {"data": 2, "fsdp": 2, "tensor": 2}},
        example_batch=random_tokens(8, 16, vocab_size=cfg.vocab_size),
        tensor_rules=gpt2_tensor_rules)
    fixed = random_tokens(8, 16, vocab_size=cfg.vocab_size, seed=0)
    losses = [float(engine.train_batch(batch=fixed)) for _ in range(5)]
    assert losses[-1] < losses[0] and all(np.isfinite(losses))

    # HF Conv1D conversion: [in, out] with column-fused qkv, no transpose
    rng = np.random.default_rng(7)
    d, h, dh = cfg.hidden_size, cfg.num_heads, cfg.head_dim_
    hf = {"wte.weight": rng.normal(size=(cfg.vocab_size, d)).astype(np.float32) * 0.02,
          "wpe.weight": rng.normal(size=(cfg.max_seq_len, d)).astype(np.float32) * 0.02,
          "ln_f.weight": np.ones(d, np.float32), "ln_f.bias": np.zeros(d, np.float32)}
    for i in range(cfg.num_layers):
        p = f"h.{i}."
        hf[p + "attn.c_attn.weight"] = rng.normal(size=(d, 3 * d)).astype(np.float32) * 0.02
        hf[p + "attn.c_attn.bias"] = np.zeros(3 * d, np.float32)
        hf[p + "attn.c_proj.weight"] = rng.normal(size=(d, d)).astype(np.float32) * 0.02
        hf[p + "attn.c_proj.bias"] = np.zeros(d, np.float32)
        hf[p + "ln_1.weight"] = np.ones(d, np.float32)
        hf[p + "ln_1.bias"] = np.zeros(d, np.float32)
        hf[p + "ln_2.weight"] = np.ones(d, np.float32)
        hf[p + "ln_2.bias"] = np.zeros(d, np.float32)
        hf[p + "mlp.c_fc.weight"] = rng.normal(size=(d, 4 * d)).astype(np.float32) * 0.02
        hf[p + "mlp.c_fc.bias"] = np.zeros(4 * d, np.float32)
        hf[p + "mlp.c_proj.weight"] = rng.normal(size=(4 * d, d)).astype(np.float32) * 0.02
        hf[p + "mlp.c_proj.bias"] = np.zeros(d, np.float32)
    params = jax.tree.map(jnp.asarray, convert_hf_gpt2(hf, cfg))
    ref = model.init(jax.random.PRNGKey(0),
                     random_tokens(1, 8, vocab_size=cfg.vocab_size))["params"]
    assert jax.tree.structure(ref) == jax.tree.structure(params)
    # numeric split check: sequential q|k|v columns of c_attn, no transpose
    np.testing.assert_allclose(
        np.asarray(params["model"]["layer_0"]["wq"]["kernel"]),
        hf["h.0.attn.c_attn.weight"][:, :d].reshape(d, h, dh))
    np.testing.assert_allclose(
        np.asarray(params["model"]["layer_0"]["wv"]["kernel"]),
        hf["h.0.attn.c_attn.weight"][:, 2 * d:].reshape(d, h, dh))

    # paged serving parity on the converted weights
    prompt = list(np.random.default_rng(8).integers(0, cfg.vocab_size, 9))
    serve = InferenceEngineV2(params, cfg, V2EngineConfig(kv_block_size=16,
                                                          kv_num_blocks=64))
    got = serve.generate(list(prompt), max_new_tokens=4)
    ids = list(prompt)
    for _ in range(4):
        logits = model.apply({"params": params}, jnp.asarray([ids], jnp.int32),
                             method=lambda m, x: m.model(x))
        ids.append(int(np.argmax(np.asarray(logits)[0, -1])))
    assert got == ids[len(prompt):], (got, ids[len(prompt):])



def test_gpt2_forward_and_policy_lookup():
    """Fast default-suite coverage: registry routing + finite forward loss
    (the full train/convert/serve integration runs under -m slow)."""
    from deepspeed_tpu.inference.v2.modules import GPT2Policy, policy_for
    from deepspeed_tpu.models.gpt2 import TINY_GPT2, GPT2ForCausalLM

    assert policy_for(TINY_GPT2) is GPT2Policy
    model = GPT2ForCausalLM(TINY_GPT2)
    batch = random_tokens(2, 16, vocab_size=TINY_GPT2.vocab_size)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    assert np.isfinite(float(model.apply({"params": params}, batch)))
