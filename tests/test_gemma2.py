"""Gemma-2 family tests: sandwich norms, softcaps, alternating windows.

Reference analog: gemma-2 was an explicitly-flagged coverage gap (the
reference v2 engine covers gemma-1 only); parity is held against
torch-transformers directly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import create_mesh, set_global_mesh
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.models.gemma2 import (TINY_GEMMA2, Gemma2ForCausalLM,
                                         gemma2_tensor_rules)
from deepspeed_tpu.models.llama import random_tokens


@pytest.mark.slow
def test_gemma2_trains():
    mesh = create_mesh(MeshConfig(data=2, fsdp=4))
    set_global_mesh(mesh)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=Gemma2ForCausalLM(TINY_GEMMA2),
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2}},
        mesh=mesh, example_batch=random_tokens(4, 32, vocab_size=512),
        tensor_rules=gemma2_tensor_rules)
    batch = random_tokens(8, 32, vocab_size=512, seed=0)
    losses = [float(engine.train_batch(batch=batch)) for _ in range(5)]
    assert losses[-1] < losses[0] and all(np.isfinite(losses)), losses


def test_gemma2_sliding_layers_restrict_context():
    """Even layers use the sliding window: with every layer sliding-w=8 the
    receptive field per layer is bounded, so token t in a 4-layer model
    (2 sliding + 2 full) still differs from full attention on long context;
    here we check the per-layer masks directly via config."""
    assert TINY_GEMMA2.is_sliding(0) and not TINY_GEMMA2.is_sliding(1)
    assert TINY_GEMMA2.is_sliding(2) and not TINY_GEMMA2.is_sliding(3)


def test_gemma2_sliding_window_masks_old_context():
    """Behavioral window check (fast): with identical weights, logits at
    positions inside the window match a full-attention run, positions past
    it diverge — the masking path is live, not just the config flag."""
    import dataclasses
    cfg = dataclasses.replace(TINY_GEMMA2, dtype=jnp.float32, num_layers=1)
    assert cfg.is_sliding(0)               # layer 0 slides (window 8)
    full = dataclasses.replace(cfg, sliding_window=128)
    model_w, model_f = Gemma2ForCausalLM(cfg), Gemma2ForCausalLM(full)
    batch = random_tokens(1, 32, vocab_size=512, seed=3)
    params = model_w.init(jax.random.PRNGKey(0), batch)
    lw = np.asarray(model_w.apply(params, batch,
                                  method=Gemma2ForCausalLM.logits))
    lf = np.asarray(model_f.apply(params, batch,
                                  method=Gemma2ForCausalLM.logits))
    np.testing.assert_allclose(lw[:, :8], lf[:, :8], atol=1e-5, rtol=1e-5)
    assert np.abs(lw[:, 16:] - lf[:, 16:]).max() > 1e-3


@pytest.mark.slow
def test_hf_gemma2_torch_parity():
    import torch
    from transformers import Gemma2Config as HFConfig
    from transformers import Gemma2ForCausalLM as HFModel

    from test_hf_torch_parity import _ids, _parity

    hf_cfg = HFConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, query_pre_attn_scalar=32,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        sliding_window=8, max_position_embeddings=128,
        rms_norm_eps=1e-6, rope_theta=10000.0)
    torch.manual_seed(0)
    hf_model = HFModel(hf_cfg).eval()
    _parity(hf_model, hf_cfg.to_dict(), _ids(256, s=32))


@pytest.mark.slow
def test_serve_gemma2():
    """Paged serving parity for gemma2 (sandwich norms, folded attention
    scale, per-layer windows + logit softcap through the generic loop)."""
    import dataclasses

    from test_v2_multiarch import _serve_and_reference

    cfg = dataclasses.replace(TINY_GEMMA2, dtype=jnp.float32)
    model = Gemma2ForCausalLM(cfg)
    prompt = list(np.random.default_rng(4).integers(0, cfg.vocab_size, 12))
    params = model.init(jax.random.PRNGKey(0),
                        random_tokens(1, 8, vocab_size=cfg.vocab_size)
                        )["params"]
    _serve_and_reference(
        model, params, cfg,
        lambda b: model.apply({"params": params},
                              {"input_ids": jnp.asarray(b["input_ids"])},
                              method=Gemma2ForCausalLM.logits),
        prompt)
