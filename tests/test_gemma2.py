"""Gemma-2 family tests: sandwich norms, softcaps, alternating windows.

Reference analog: gemma-2 was an explicitly-flagged coverage gap (the
reference v2 engine covers gemma-1 only); parity is held against
torch-transformers directly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import create_mesh, set_global_mesh
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.models.gemma2 import (TINY_GEMMA2, Gemma2ForCausalLM,
                                         gemma2_tensor_rules)
from deepspeed_tpu.models.llama import random_tokens


def test_gemma2_trains():
    mesh = create_mesh(MeshConfig(data=2, fsdp=4))
    set_global_mesh(mesh)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=Gemma2ForCausalLM(TINY_GEMMA2),
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2}},
        mesh=mesh, example_batch=random_tokens(4, 32, vocab_size=512),
        tensor_rules=gemma2_tensor_rules)
    batch = random_tokens(8, 32, vocab_size=512, seed=0)
    losses = [float(engine.train_batch(batch=batch)) for _ in range(5)]
    assert losses[-1] < losses[0] and all(np.isfinite(losses)), losses


def test_gemma2_sliding_layers_restrict_context():
    """Even layers use the sliding window: with every layer sliding-w=8 the
    receptive field per layer is bounded, so token t in a 4-layer model
    (2 sliding + 2 full) still differs from full attention on long context;
    here we check the per-layer masks directly via config."""
    assert TINY_GEMMA2.is_sliding(0) and not TINY_GEMMA2.is_sliding(1)
    assert TINY_GEMMA2.is_sliding(2) and not TINY_GEMMA2.is_sliding(3)


@pytest.mark.slow
def test_hf_gemma2_torch_parity():
    import torch
    from transformers import Gemma2Config as HFConfig
    from transformers import Gemma2ForCausalLM as HFModel

    from deepspeed_tpu.models.gemma2 import (convert_hf_gemma2,
                                             gemma2_config_from_hf)

    hf_cfg = HFConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, query_pre_attn_scalar=16,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        sliding_window=8, max_position_embeddings=128,
        rms_norm_eps=1e-6, rope_theta=10000.0)
    torch.manual_seed(0)
    hf_model = HFModel(hf_cfg).eval()

    import dataclasses
    cfg = gemma2_config_from_hf(hf_cfg.to_dict())
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    params = convert_hf_gemma2(hf_model.state_dict(), cfg)

    ids = np.random.default_rng(0).integers(0, 256, size=(2, 32))
    with torch.no_grad():
        ref = hf_model(torch.tensor(ids)).logits.numpy()
    ours = Gemma2ForCausalLM(cfg).apply(
        {"params": jax.tree.map(jnp.asarray, params)},
        {"input_ids": jnp.asarray(ids.astype(np.int32))},
        method=Gemma2ForCausalLM.logits)
    np.testing.assert_allclose(np.asarray(ours), ref, atol=3e-4, rtol=3e-3)
