"""Eigenvalue / progressive layer drop / sparse gradients tests.

Reference analog: the engine hooks at runtime/engine.py:346,1871 (PLD),
runtime/eigenvalue.py (power iteration), runtime/sparse_tensor.py + engine
sparse allreduce (:2518-2588).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.simple import SimpleModel, random_batch


# ------------------------------------------------------------- eigenvalue
def test_power_iteration_matches_dense_hessian():
    """On a quadratic loss the Hessian is known exactly; power iteration must
    find its top eigenvalue."""
    from deepspeed_tpu.runtime.eigenvalue import Eigenvalue, EigenvalueConfig
    rng = np.random.default_rng(0)
    a = rng.normal(size=(6, 6))
    h = a @ a.T + 6 * np.eye(6)          # SPD with known spectrum
    hj = jnp.asarray(h, jnp.float32)
    params = {"blocks": {"b0": jnp.asarray(rng.normal(size=(6,)), jnp.float32)}}

    def loss(p):
        x = p["blocks"]["b0"]
        return 0.5 * x @ hj @ x

    ev = Eigenvalue(EigenvalueConfig(enabled=True, layer_name="blocks",
                                     max_iter=200, tol=1e-5))
    out = ev.compute_eigenvalue(loss, params, jax.random.PRNGKey(0))
    expected = float(np.linalg.eigvalsh(h).max())
    assert abs(out["b0"] - expected) / expected < 0.05, (out, expected)


@pytest.mark.slow
def test_eigenvalue_orders_model_blocks():
    """Per-layer eigenvalues over a real model's loss come out positive and
    finite (ordering input for the compression scheduler)."""
    from deepspeed_tpu.runtime.eigenvalue import Eigenvalue, EigenvalueConfig
    from deepspeed_tpu.models.llama import TINY_LLAMA, LlamaConfig, LlamaForCausalLM, random_tokens
    cfg = LlamaConfig(**{**TINY_LLAMA.__dict__, "dtype": jnp.float32})
    model = LlamaForCausalLM(cfg)
    batch = random_tokens(2, 16, vocab_size=cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]

    def loss(p):
        return model.apply({"params": p}, batch)

    ev = Eigenvalue(EigenvalueConfig(enabled=True, layer_name="model",
                                     layer_num=2, max_iter=8, tol=1e-2))
    out = ev.compute_eigenvalue(loss, params, jax.random.PRNGKey(1))
    assert len(out) == 2
    assert all(np.isfinite(v) and v > 0 for v in out.values()), out


# ------------------------------------------------------------- PLD
def test_pld_schedule_matches_reference_formula():
    from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop
    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.001)
    assert pld.get_theta() == 1.0
    for step in (1, 10, 1000, 100000):
        pld.update_state(step)
        expected = (1 - 0.5) * np.exp(-0.001 * step) + 0.5
        assert abs(pld.get_theta() - expected) < 1e-9
    assert pld.get_state()["progressive_layer_drop"] is True
    assert 0.5 <= pld.get_theta() < 1.0


def test_pld_survival_probs_and_drop_helper():
    from deepspeed_tpu.runtime.progressive_layer_drop import (
        layer_survival_probs, maybe_drop_layer)
    probs = layer_survival_probs(0.5, 8)
    assert probs[0] == 1.0 and abs(probs[-1] - 0.5) < 1e-6
    assert (np.diff(probs) < 0).all()                  # deeper -> more dropped
    # expectation preservation of the inverted-dropout skip
    x = jnp.ones((4, 8))
    y = jnp.full((4, 8), 3.0)
    outs = [maybe_drop_layer(jax.random.PRNGKey(i), x, y, 0.5)
            for i in range(400)]
    mean = np.mean([np.asarray(o).mean() for o in outs])
    assert abs(mean - 3.0) < 0.35                      # E[out] == y under 1/p scaling


def test_engine_updates_pld_theta():
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16),
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "progressive_layer_drop": {"enabled": True, "theta": 0.6,
                                           "gamma": 0.01}},
        example_batch=random_batch(4))
    assert engine.progressive_layer_drop is not None
    t0 = engine.progressive_layer_drop.get_theta()
    engine.train_batch(batch=random_batch(8))
    engine.train_batch(batch=random_batch(8))
    t2 = engine.progressive_layer_drop.get_theta()
    assert t2 < t0 == 1.0


# ------------------------------------------------------------- sparse grads
def test_sparse_tensor_roundtrip_and_add():
    from deepspeed_tpu.runtime.sparse_tensor import SparseTensor
    rng = np.random.default_rng(1)
    dense = np.zeros((32, 8), np.float32)
    rows = [3, 7, 19]
    for r in rows:
        dense[r] = rng.normal(size=8)
    st = SparseTensor.from_dense(jnp.asarray(dense), k=3)
    assert sorted(np.asarray(st.indices).tolist()) == rows
    np.testing.assert_allclose(np.asarray(st.to_dense()), dense, atol=1e-6)
    both = st.add(st)
    np.testing.assert_allclose(np.asarray(both.to_dense()), 2 * dense,
                               atol=1e-6)
    nnz, total = st.sparse_size()
    assert nnz == 3 + 3 * 8 and total == 32 * 8


def test_sparse_all_gather_matches_dense_psum(mesh_dp8):
    """Embedding-gradient pattern: each rank contributes a few rows; the
    gathered sparse tensor densifies to the exact global sum."""
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.runtime.sparse_tensor import SparseTensor, sparse_all_gather
    rng = np.random.default_rng(2)
    dense = np.zeros((8, 32, 16), np.float32)      # per-rank dense grads
    for r in range(8):
        for row in rng.choice(32, size=4, replace=False):
            dense[r, row] = rng.normal(size=16)
    parts = jnp.asarray(dense)

    def body(x_l):
        st = SparseTensor.from_dense(x_l[0], k=4)
        return sparse_all_gather(st, "data").to_dense()

    out = jax.jit(lambda v: jax.shard_map(
        body, mesh=mesh_dp8, in_specs=P("data"), out_specs=P(),
        check_vma=False)(v))(parts)
    np.testing.assert_allclose(np.asarray(out), dense.sum(0), atol=1e-5)


def test_engine_parses_sparse_gradients_flag():
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=16),
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "sparse_gradients": True},
        example_batch=random_batch(4))
    assert engine.sparse_gradients_enabled


# ------------------------------------------------- vocab-parallel CE / tiling
def test_vocab_parallel_cross_entropy_matches_dense():
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.comm.mesh import create_mesh, set_global_mesh
    from deepspeed_tpu.config.config import MeshConfig
    from deepspeed_tpu.sequence.cross_entropy import vocab_parallel_cross_entropy
    mesh = create_mesh(MeshConfig(tensor=8))
    set_global_mesh(mesh)
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(2, 12, 64)) * 3, jnp.float32)
    labels = jnp.asarray(rng.integers(0, 64, size=(2, 12)), jnp.int32)
    logits_sharded = jax.device_put(
        logits, NamedSharding(mesh, P(None, None, "tensor")))
    loss = vocab_parallel_cross_entropy(logits_sharded, labels, mesh=mesh)
    ref = -np.take_along_axis(
        np.asarray(jax.nn.log_softmax(logits, -1)),
        np.asarray(labels)[..., None], axis=-1)[..., 0]
    np.testing.assert_allclose(np.asarray(loss), ref, atol=1e-5, rtol=1e-5)


def test_tiled_linear_matches_dense():
    from deepspeed_tpu.runtime.zero.tiling import TiledLinear, split_tiled_weight
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 64)), jnp.float32)
    full = jnp.asarray(rng.normal(size=(64, 96)) * 0.1, jnp.float32)
    layer = TiledLinear(features=96, in_splits=4, out_splits=3,
                        use_bias=False, dtype=jnp.float32)
    params = layer.init(jax.random.PRNGKey(0), x)["params"]
    params = {"kernel": split_tiled_weight(full, 4, 3)}
    out = layer.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ full),
                               atol=1e-4, rtol=1e-4)


def test_tiled_linear_trains():
    from deepspeed_tpu.runtime.zero.tiling import TiledLinear
    layer = TiledLinear(features=32, in_splits=2, out_splits=2,
                        dtype=jnp.float32)
    x = jnp.ones((8, 16))
    params = layer.init(jax.random.PRNGKey(0), x)["params"]
    g = jax.grad(lambda p: jnp.sum(layer.apply({"params": p}, x) ** 2))(params)
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))


def test_see_memory_usage():
    from deepspeed_tpu.utils.memory import get_memory_stats, see_memory_usage
    stats = see_memory_usage("test", force=True)
    assert stats is not None and "host" in stats
    assert get_memory_stats()["host"]["rss_gb"] > 0


def _sparse_grad_setup():
    import deepspeed_tpu
    from deepspeed_tpu.comm.mesh import create_mesh, set_global_mesh
    from deepspeed_tpu.config.config import MeshConfig
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=2048, hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=2,
                      max_seq_len=64, dtype=jnp.float32)
    rng = np.random.default_rng(0)

    def batch(bs):
        return {"input_ids":
                rng.integers(0, 2048, size=(bs, 32)).astype(np.int32)}

    def make(sparse, model_cfg=cfg):
        mesh = create_mesh(MeshConfig(data=2, fsdp=4))
        set_global_mesh(mesh)
        e, _, _, _ = deepspeed_tpu.initialize(
            model=LlamaForCausalLM(model_cfg),
            config={"train_batch_size": 16,
                    "train_micro_batch_size_per_gpu": 2,
                    "sparse_gradients": sparse,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 2}},
            mesh=mesh, example_batch=batch(8))
        return e

    return cfg, batch, make


def test_sparse_gradients_engine_path_active():
    """sparse_gradients routes embedding grads through the sparse wire
    (reference sparse_allreduce_bucket, engine.py:2518) inside the
    partial-manual gradient phase: the lowered step carries the scatter-add
    densify only the sparse path emits, and tied-embedding models (dense
    head grads) are excluded. Exact dense-parity runs under -m slow."""
    import dataclasses
    cfg, batch, make = _sparse_grad_setup()
    es = make(True)
    assert es._sparse_grad_paths == ("model/embed/embedding",)
    assert es._sparse_grad_axes == ("data", "fsdp")

    es._build_train_batch_fn()
    stacked = jax.tree.map(lambda x: np.asarray(x).reshape(1, *x.shape),
                           batch(16))
    db = es._shard_batch(stacked, stacked=True)
    txt = es._train_batch_fn.lower(es.state, db,
                                   jax.random.PRNGKey(0)).as_text()
    assert "scatter" in txt, "sparse densify scatter-add missing from HLO"
    assert np.isfinite(float(es.train_batch(batch=batch(16))))

    # tied embeddings get dense head grads: the tie flag disables the path
    et = make(True, dataclasses.replace(cfg, tie_embeddings=True))
    assert et._sparse_grad_paths == ()


@pytest.mark.slow
def test_sparse_gradients_exact_dense_parity():
    """EXACT loss parity with dense reduction: k >= tokens-per-device keeps
    every touched embedding row."""
    _, batch, make = _sparse_grad_setup()
    es, ed = make(True), make(False)
    fixed = batch(16)
    ls = [float(es.train_batch(batch=fixed)) for _ in range(5)]
    ld = [float(ed.train_batch(batch=fixed)) for _ in range(5)]
    np.testing.assert_allclose(ls, ld, rtol=2e-5)
