"""Unit tests for the dslint call-graph builder (stdlib-ast only).

The graph is the substrate of DS002's taint and DS009's purity check, so
its resolution rules are pinned directly: method calls through ``self``,
constructor-typed locals and attributes, attr-bound callables handed to
workers, cycles, and — crucially — that dynamic calls it cannot resolve
degrade to *statistics* (``unresolved``), never to edges or findings.
"""

import ast

import pytest

from deepspeed_tpu.tools.dslint.callgraph import build_graph

pytestmark = pytest.mark.lint


def _graph(**files):
    return build_graph(
        [(name.replace("__", "/") + ".py", ast.parse(src))
         for name, src in files.items()])


def _key(g, qualname, path_suffix=None):
    for key, info in g.functions.items():
        if info.qualname == qualname and (
                path_suffix is None or info.relpath.endswith(path_suffix)):
            return key
    raise AssertionError(f"{qualname} not indexed: {sorted(g.functions)}")


def test_self_method_calls_and_self_recursion():
    g = _graph(mod=(
        "class A:\n"
        "    def outer(self):\n"
        "        self.inner()\n"
        "        self.outer()\n"
        "    def inner(self):\n"
        "        pass\n"))
    outer, inner = _key(g, "A.outer"), _key(g, "A.inner")
    assert inner in g.callees(outer)
    assert outer in g.callees(outer)        # self-recursion is an edge


def test_constructor_typed_attribute_resolves_cross_class():
    g = _graph(mod=(
        "class Helper:\n"
        "    def peek(self):\n"
        "        pass\n"
        "class Engine:\n"
        "    def __init__(self):\n"
        "        self.h = Helper()\n"
        "    def step(self):\n"
        "        self.h.peek()\n"))
    assert _key(g, "Helper.peek") in g.callees(_key(g, "Engine.step"))


def test_attr_bound_callable_reference_is_an_edge():
    """Passing a bound method as a value (thread target, listener
    registration) keeps the callee in the graph — the taint must not
    lose workers that are only ever *referenced*."""
    g = _graph(mod=(
        "import threading\n"
        "class W:\n"
        "    def start(self):\n"
        "        t = threading.Thread(target=self._worker)\n"
        "        t.start()\n"
        "    def _worker(self):\n"
        "        pass\n"))
    assert _key(g, "W._worker") in g.callees(_key(g, "W.start"))


def test_cycles_terminate_and_reach_everything():
    g = _graph(mod=(
        "def a():\n    b()\n"
        "def b():\n    c()\n"
        "def c():\n    a()\n"))
    ka = _key(g, "a")
    pred = g.reachable_from([ka])
    assert {_key(g, "a"), _key(g, "b"), _key(g, "c")} <= set(pred)
    # path_to never loops on the cycle
    assert g.path_to(pred, _key(g, "c"))[0] == ka


def test_dynamic_calls_degrade_to_statistics_never_edges():
    g = _graph(mod=(
        "def go(cb, fns):\n"
        "    cb()\n"                       # injected callable: dynamic
        "    fns[0]()\n"))                 # subscript call: no edge
    key = _key(g, "go")
    assert not g.callees(key)
    assert g.unresolved.get(key), "dynamic calls must be counted"
    assert g.stats()["unresolved_calls"] >= 1


def test_reachable_from_prune_reaches_but_does_not_expand():
    g = _graph(mod=(
        "def root():\n    mid()\n"
        "def mid():\n    leaf()\n"
        "def leaf():\n    pass\n"))
    pred = g.reachable_from([_key(g, "root")], prune=[_key(g, "mid")])
    assert _key(g, "mid") in pred
    assert _key(g, "leaf") not in pred


def test_module_level_imports_vs_lazy_imports():
    """DS009's substrate: module-level imports land in the import graph
    (internal edges + external names); in-function imports register an
    alias for call resolution but stay OUT of the import graph — the
    lazy import IS the offline-purity idiom."""
    g = _graph(
        pkg__hot=("from pkg import offline\n"
                  "def f():\n    offline.go()\n"),
        pkg__offline=("def go():\n"
                      "    import jax\n"
                      "    return jax\n"))
    hot = g.modules["pkg/hot.py"]
    off = g.modules["pkg/offline.py"]
    assert "pkg/offline.py" in hot.internal_imports
    assert hot.import_lines["pkg/offline.py"] == 1
    assert "jax" not in off.external_imports       # lazy: not in the graph
    # ...but the alias still resolves the cross-module call edge
    assert _key(g, "go") in g.callees(_key(g, "f"))


def test_resolve_matches_path_suffix_only_at_boundaries():
    g = _graph(a__engine=("def f():\n    pass\n"),
               b__engine=("def f():\n    pass\n"))
    assert g.resolve("a/engine.py", "f").startswith("a/")
    assert g.resolve("b/engine.py", "f").startswith("b/")
    assert g.resolve("gine.py", "f") is None       # no substring matches
