"""Elasticity tests (reference shape: tests/unit/elasticity/test_elastic.py)."""

import os
import subprocess

import pytest

from deepspeed_tpu.testing import free_port

from deepspeed_tpu.elasticity import (ElasticAgent, ElasticityConfigError,
                                      ElasticityIncompatibleWorldSize,
                                      WorkerSpec, compute_elastic_config,
                                      get_candidate_batch_sizes,
                                      get_valid_devices)

BASE_CONFIG = {
    "elasticity": {
        "enabled": True,
        "max_train_batch_size": 10000,
        "micro_batch_sizes": [8, 12, 16, 17],
        "min_gpus": 32,
        "max_gpus": 1500,
        "min_time": 20,
        "version": 0.1,
    }
}


def test_candidate_batch_sizes():
    # each base → base × largest highly-composite number fitting under max
    # base 2: HCNs ≤ 6 are [1,2,4,6] → 12; base 3: HCNs ≤ 4 are [1,2,4] → 12
    assert get_candidate_batch_sizes([2, 3], 12) == [12]


def test_valid_devices():
    devices = get_valid_devices(batch_size=24, micro_batches=[4, 6],
                                min_valid_devices=1, max_valid_devices=24)
    # micro=4 → dp in divisors of 6; micro=6 → dp in divisors of 4
    assert set(devices) == {1, 2, 3, 4, 6}


def test_compute_elastic_config_basic():
    batch, valid = compute_elastic_config(BASE_CONFIG)
    assert batch <= 10000
    assert all(32 <= w <= 1500 for w in valid)
    assert len(valid) > 10  # highly-composite batch ⇒ many valid world sizes


def test_world_size_validation():
    _, valid = compute_elastic_config(BASE_CONFIG)
    w = valid[0]
    batch, valid2 = compute_elastic_config(BASE_CONFIG, world_size=w)
    assert w in valid2
    # a world size outside [min,max] or non-divisible should raise
    with pytest.raises(ElasticityIncompatibleWorldSize):
        compute_elastic_config(BASE_CONFIG, world_size=1531)


def test_micro_batch_resolution():
    _, valid = compute_elastic_config(BASE_CONFIG)
    w = valid[-1]
    batch, _, micro = compute_elastic_config(
        BASE_CONFIG, world_size=w, return_microbatch=True)
    per_rank = batch // w
    assert per_rank % micro == 0
    assert micro in BASE_CONFIG["elasticity"]["micro_batch_sizes"]


def test_same_global_batch_across_scales():
    """The elastic invariant: global batch identical at different world sizes."""
    _, valid = compute_elastic_config(BASE_CONFIG)
    w_a, w_b = valid[0], valid[len(valid) // 2]
    assert w_a != w_b
    b_a, _ = compute_elastic_config(BASE_CONFIG, world_size=w_a)
    b_b, _ = compute_elastic_config(BASE_CONFIG, world_size=w_b)
    assert b_a == b_b


def test_disabled_raises():
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config({"elasticity": {"enabled": False}})


def test_model_parallel_v2():
    cfg = {
        "elasticity": {
            "enabled": True,
            "max_train_batch_size": 4096,
            "micro_batch_sizes": [2, 4],
            "min_gpus": 1, "max_gpus": 512,
            "version": 0.2,
            "model_parallel_size": 4,
            "num_gpus_per_node": 8,
        }
    }
    batch, valid = compute_elastic_config(cfg, world_size=32)
    assert batch <= 4096
    # dp world = 32/4 = 8 must be in the valid dp set
    assert 8 in valid


class _FakeProc:
    """Deterministic fake Popen: exits with a scripted code after n polls."""

    def __init__(self, codes):
        self.codes = list(codes)
        self.terminated = False

    def poll(self):
        return self.codes.pop(0) if self.codes else 0

    def terminate(self):
        self.terminated = True

    def wait(self, timeout=None):
        return 0

    def kill(self):
        pass


def test_elastic_agent_restarts_on_failure():
    launches = []

    def fake_popen(cmd, env=None):
        launches.append(env)
        # first group: rank0 fails once; second group: both succeed
        if len(launches) <= 2:
            return _FakeProc([None, 1])
        return _FakeProc([0])

    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 64,
                          "micro_batch_sizes": [2, 4], "min_gpus": 1,
                          "max_gpus": 8, "version": 0.1}}
    spec = WorkerSpec(cmd=["python", "train.py"], max_restarts=3,
                      monitor_interval_s=0.01, restart_backoff_s=0.0)
    agent = ElasticAgent(spec, cfg,
                         host_provider=lambda: ["h0", "h1"], popen=fake_popen)
    assert agent.run() == 0
    assert agent.restart_count == 1
    assert len(launches) == 4  # 2 hosts × 2 generations
    # rendezvous env regenerated each generation
    assert launches[-1]["DSTPU_ELASTIC_RESTART"] == "1"
    assert launches[-1]["DSTPU_NUM_PROCESSES"] == "2"


def test_elastic_agent_budget_exhausted():
    def always_fail(cmd, env=None):
        return _FakeProc([2])

    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 64,
                          "micro_batch_sizes": [2], "min_gpus": 1,
                          "max_gpus": 8, "version": 0.1}}
    spec = WorkerSpec(cmd=["x"], max_restarts=2, monitor_interval_s=0.01,
                      restart_backoff_s=0.0)
    agent = ElasticAgent(spec, cfg, popen=always_fail)
    assert agent.run() == 2
    assert agent.restart_count == 3  # budget (2) + the final attempt
    assert agent.crash_restarts == 3


class _ScriptedProc:
    """Fake Popen whose poll() walks a code script then repeats the final
    value (unlike _FakeProc, safe to poll any number of times). terminate()
    is a no-op unless ``term_exits``; kill() always lands."""

    def __init__(self, codes, term_exits=False):
        self.codes = list(codes)
        self.last = None
        self.terminated = False
        self.killed = False
        self.term_exits = term_exits

    def poll(self):
        if self.codes:
            self.last = self.codes.pop(0)
        return self.last

    def terminate(self):
        self.terminated = True
        if self.term_exits and self.last is None:
            self.last = -15
            self.codes = []

    def wait(self, timeout=None):
        if self.poll() is None:
            raise subprocess.TimeoutExpired(cmd="x", timeout=timeout or 0)
        return self.last

    def kill(self):
        self.killed = True
        self.last = -9
        self.codes = []


def _agent_cfg():
    return {"elasticity": {"enabled": True, "max_train_batch_size": 64,
                           "micro_batch_sizes": [2, 4], "min_gpus": 1,
                           "max_gpus": 8, "version": 0.1}}


def test_terminate_all_escalates_sigterm_to_sigkill():
    """One hung worker (ignores SIGTERM) must not block group teardown: the
    agent SIGKILLs it after the grace window."""
    spec = WorkerSpec(cmd=["x"], term_grace_s=0.05)
    agent = ElasticAgent(spec, _agent_cfg(), popen=lambda *a, **k: None)
    polite = _ScriptedProc([None], term_exits=True)
    hung = _ScriptedProc([None], term_exits=False)
    agent.procs = [polite, hung]
    agent._terminate_all()
    assert polite.terminated and not polite.killed
    assert hung.terminated and hung.killed


def test_preemption_exits_do_not_consume_restart_budget():
    """SIGTERM deaths are platform churn, not crashes: with a crash budget
    of ZERO the agent still relaunches through two preemptions, and the
    relaunch env carries DSTPU_RESUME=latest."""
    launches = []

    def popen(cmd, env=None):
        launches.append(env)
        gen = int(env["DSTPU_ELASTIC_RESTART"])
        return _ScriptedProc([None, -15] if gen < 2 else [0])

    spec = WorkerSpec(cmd=["x"], max_restarts=0, monitor_interval_s=0.01,
                      term_grace_s=0.05, restart_backoff_s=0.0)
    agent = ElasticAgent(spec, _agent_cfg(), popen=popen)
    assert agent.run() == 0
    assert agent.restart_count == 2      # two relaunches happened...
    assert agent.crash_restarts == 0     # ...none charged to the budget
    assert "DSTPU_RESUME" not in launches[0]
    assert launches[1]["DSTPU_RESUME"] == "latest"
    assert launches[2]["DSTPU_RESUME"] == "latest"


def test_mixed_exit_vector_counts_as_crash():
    """A generation where ANY worker crashed is a crash, even if another
    worker died by SIGTERM."""
    spec = WorkerSpec(cmd=["x"])
    agent = ElasticAgent(spec, _agent_cfg(), popen=lambda *a, **k: None)
    agent._last_codes = [-15, 1]
    assert not agent._is_preemption(1)
    agent._last_codes = [-15, None]      # other worker still running
    assert agent._is_preemption(-15)
    agent._last_codes = [130, 143]       # shell-convention SIGINT/SIGTERM
    assert agent._is_preemption(143)
    agent._last_codes = [-9]             # SIGKILL (OOM killer) = crash
    assert not agent._is_preemption(-9)


def test_crash_backoff_exponential_and_capped():
    spec = WorkerSpec(cmd=["x"], restart_backoff_s=1.0,
                      restart_backoff_max_s=5.0)
    agent = ElasticAgent(spec, _agent_cfg())
    for streak, expected in [(0, 0.0), (1, 1.0), (2, 2.0), (3, 4.0),
                             (4, 5.0), (10, 5.0)]:
        agent.consecutive_crashes = streak
        assert agent._crash_backoff_s() == expected


@pytest.mark.slow
def test_elastic_kill_and_resume_end_to_end(tmp_path):
    """The full supervisor loop on real processes (reference:
    elastic_agent.py:32,127): a 2-process run loses a worker to SIGKILL after
    step 2's checkpoint commits; the host set shrinks to one process; the
    agent recomputes a compatible batch (same GLOBAL batch — the elastic
    invariant), relaunches, and the worker resumes from the checkpoint and
    finishes training with the loss continuing to decrease."""
    import json
    import sys

    workdir = str(tmp_path)
    total_steps = 6
    spec = WorkerSpec(
        cmd=[sys.executable,
             os.path.join(os.path.dirname(__file__), "elastic_worker.py")],
        max_restarts=3, monitor_interval_s=0.5, coordinator_port=free_port(),
        env={"DSTPU_EW_DIR": workdir,
             "DSTPU_EW_TOTAL_STEPS": str(total_steps),
             "DSTPU_EW_LOCAL_DEVICES": "2",
             "DSTPU_EW_KILL_RANK": "1", "DSTPU_EW_KILL_STEP": "3"})
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 8,
                          "micro_batch_sizes": [1, 2, 4], "min_gpus": 1,
                          "max_gpus": 4, "version": 0.1}}

    # real resolvable hosts (the coordinator address is hosts[0]:port). The
    # provider mirrors a membership service: once a worker process has died
    # (or after the first restart), the failed "node" is gone — sampled by
    # the agent in the same poll iteration that detects the failure, so the
    # relaunch happens at the smaller world size
    agent = ElasticAgent(spec, cfg)

    def membership():
        lost = agent.restart_count > 0 or any(
            p.poll() not in (None, 0) for p in agent.procs)
        return ["localhost"] if lost else ["localhost", "localhost"]

    agent.host_provider = membership

    assert agent.run() == 0
    assert agent.restart_count == 1

    def read(gen, rank):
        path = os.path.join(workdir, f"losses_gen{gen}_rank{rank}.jsonl")
        with open(path) as f:
            return [json.loads(l) for l in f]

    g0 = read(0, 0)
    g1 = read(1, 0)
    # gen 0 stopped at the kill step; gen 1 resumed FROM the checkpoint (no
    # step re-run from 0) and finished the budget at the smaller world size
    assert g0[-1]["step"] >= 2 and g0[0]["world"] == 2
    assert g1[0]["step"] == g0[-1]["step"] + 1, (g0, g1)
    assert g1[-1]["step"] == total_steps - 1 and g1[0]["world"] == 1
    # same global batch across scales -> the loss keeps decreasing through
    # the restart boundary within tolerance
    assert g1[0]["loss"] < g0[0]["loss"] * 1.05
    assert g1[-1]["loss"] < g0[0]["loss"]


def test_total_restart_backstop_bounds_preemption_loops():
    """Preemptions don't consume the crash budget, but max_total_restarts
    still bounds a worker that always dies preemption-shaped — the agent
    must not spin forever."""
    def popen(cmd, env=None):
        return _ScriptedProc([-15])

    spec = WorkerSpec(cmd=["x"], max_restarts=0, max_total_restarts=3,
                      monitor_interval_s=0.01, term_grace_s=0.05,
                      restart_backoff_s=0.0)
    agent = ElasticAgent(spec, _agent_cfg(), popen=popen)
    assert agent.run() == -15
    assert agent.restart_count == 4      # 3 allowed relaunches + the breaker
    assert agent.crash_restarts == 0     # still not charged as crashes
